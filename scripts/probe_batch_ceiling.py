"""Batch ceiling probe: is there decode amortization left past B=16?

PERF.md finding 24 landed B=16 via chunked prefill (decode 1.36x per-row
vs B=8 — 3.2 GB of weight reads amortize over twice the rows). The weight
term keeps shrinking with B until the int8 KV cache (477 MB/row at
C=8320) hits the 16 GB HBM wall: B=20 needs ~12.7 GB resident, B=24
~14.6 GB. This probe measures ONE dispatch at each candidate B (chunked
prefill keeps transients at a chunk's worth) and compares PER-ROW wall —
prefill should stay flat per row, decode should keep dropping until OOM.

OOM is a recorded outcome, not an error. Writes
artifacts/batch_ceiling.json.
"""
from __future__ import annotations

import argparse
import gc
import json
import sys
import tempfile
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


def run_arm(label: str, tok_spec, prompts, batch: int, chunk: int,
            gen_cfg) -> dict:
    import bench
    from vnsum_tpu.backend.engine import EngineStats, TpuBackend

    kw = bench.e2e_engine_kwargs(tok_spec, None)
    kw.update(batch_size=batch, prefill_chunk_tokens=chunk)
    try:
        be = TpuBackend(**kw, instrument=True)
        t0 = time.time()
        be.generate(prompts[:batch], config=gen_cfg)
        compile_s = time.time() - t0
        be.stats = EngineStats()
        t1 = time.time()
        be.generate(prompts[:batch], config=gen_cfg)
        wall = time.time() - t1
        st = be.stats
        steps = sum(d["steps"] for d in st.dispatches)
        row = {
            "label": label, "B": batch, "chunk": chunk,
            "compile_and_warm_s": round(compile_s, 1),
            "wall_s": round(wall, 2),
            "wall_s_per_row": round(wall / batch, 4),
            "prefill_s": round(st.phase_seconds.get("prefill", 0.0), 2),
            "prefill_s_per_row": round(
                st.phase_seconds.get("prefill", 0.0) / batch, 4),
            "decode_s": round(st.phase_seconds.get("decode", 0.0), 3),
            "decode_ms_per_step": round(
                1e3 * st.phase_seconds.get("decode", 0.0) / max(steps, 1), 2),
            "decode_ms_per_step_row": round(
                1e3 * st.phase_seconds.get("decode", 0.0)
                / max(steps, 1) / batch, 3),
            "decode_steps": steps,
            "dispatches": st.dispatches,
        }
        try:
            # NOTE peak_bytes_in_use is the PROCESS-lifetime allocator peak,
            # so later arms inherit earlier arms' peak — fit/no-fit (OOM) is
            # the per-arm memory signal; bytes_in_use is current-resident
            import jax

            ms = jax.local_devices()[0].memory_stats() or {}
            for k in ("bytes_in_use", "peak_bytes_in_use"):
                if k in ms:
                    row[k] = int(ms[k])
        except Exception:
            pass
        del be
        gc.collect()
        print(f"{label}: {json.dumps(row)[:360]}", file=sys.stderr)
        return row
    except Exception as e:
        gc.collect()
        row = {"label": label, "B": batch, "chunk": chunk,
               "status": "failed", "error": str(e)[:300]}
        print(f"{label} FAILED: {str(e)[:200]}", file=sys.stderr)
        return row


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="artifacts/batch_ceiling.json")
    ap.add_argument("--max-new", type=int, default=128)
    ap.add_argument("--batches", default="16,20,24")
    ap.add_argument("--chunk", type=int, default=0,
                    help="override prefill chunk for every arm (0 = auto)")
    args = ap.parse_args()

    from vnsum_tpu.core.config import GenerationConfig
    from vnsum_tpu.core.jax_cache import enable_compilation_cache
    from vnsum_tpu.data.synthesize import synthesize_corpus
    from vnsum_tpu.models.fixtures import train_bpe_tokenizer

    enable_compilation_cache()
    root = tempfile.mkdtemp(prefix="vnsum_bceil_")
    synthesize_corpus(
        f"{root}/corpus", n_docs=4, tokens_per_doc=9_000,
        summary_tokens=200, seed=7, ragged=0.0,
    )
    doc_paths = sorted(Path(f"{root}/corpus/doc").glob("*.txt"))
    hf_tok = train_bpe_tokenizer(
        (p.read_text(encoding="utf-8") for p in doc_paths), vocab_size=4096
    )
    hf_tok.save_pretrained(f"{root}/tok")
    tok_spec = f"hf:{root}/tok"

    words = " ".join(p.read_text(encoding="utf-8") for p in doc_paths).split()
    batches = [int(b) for b in args.batches.split(",")]
    n_prompts = max(batches)
    prompts = []
    for i in range(n_prompts):
        seg = " ".join(words[(i * 1500) % 20000 : (i * 1500) % 20000 + 7400])
        prompts.append(f"Tóm tắt văn bản số {i}: " + seg)

    gen_cfg = GenerationConfig(
        max_new_tokens=args.max_new, temperature=1.0, seed=11
    )
    rows = []
    for b in batches:
        # chunk 2048 is the production default; drop to 1024 at B>=24 to
        # keep prefill transients inside the shrinking headroom
        chunk = args.chunk or (2048 if b < 24 else 1024)
        rows.append(run_arm(f"b{b}_chunk{chunk}", tok_spec, prompts, b,
                            chunk, gen_cfg))
        if rows[-1].get("status") == "failed":
            break  # bigger B only gets worse

    ok = [r for r in rows if r.get("status") != "failed"]
    if ok:
        base = ok[0]["wall_s_per_row"]
        for r in ok:
            r["per_row_speedup_vs_first"] = round(base / r["wall_s_per_row"], 3)
    rec = {
        "what": "single-dispatch per-row wall at growing B (e2e config, "
                "chunked prefill); OOM marks the HBM ceiling",
        "arms": rows,
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%S"),
    }
    out = Path(args.out)
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_text(json.dumps(rec, indent=2))
    print(json.dumps({"ok": True, "arms": {
        r["label"]: r.get("per_row_speedup_vs_first") or r.get("status")
        for r in rows
    }}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
