"""A/B: continuous scheduling (segmented decode + tail compaction) vs the
one-shot program, on the real chip (VERDICT r2 #6).

Two workloads, sampled decode with a ragged EOS byte tuned so rows
terminate around step ~budget/3 (the termination shape a real checkpoint
produces): the e2e pipeline shape (B=8, S=8192) and the map-bench shape
(B=64, S=1024), where the batch's cache traffic rivals the weight traffic
and compaction has something worth shedding.

Per-row counter-based RNG keeps each surviving row's DRAWS identical across
compaction; across the batch-shape change the logits themselves can differ
in the last bits (different matmul tilings), and with random-init weights
the near-uniform distributions flip draws on any such difference — so arms
are compared on work-normalized wall-clock (seconds per 1k generated
tokens), not bit equality (which CPU/interpret tests do pin, same-shape).

Writes artifacts/compaction_ab.json; PERF.md cites it.
"""
from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def build_backend(
    params, continuous, segment_tokens, min_batch,
    batch_size=8, max_seq_len=8448,
):
    from vnsum_tpu.backend.engine import TpuBackend
    from vnsum_tpu.models import llama32_3b

    return TpuBackend(
        model_config=llama32_3b(max_seq_len=max_seq_len),
        tokenizer="byte",
        params=params,
        batch_size=batch_size,
        max_new_tokens=128,
        quantize=True,
        continuous=continuous,
        segment_tokens=segment_tokens,
        min_batch=min_batch,
    )


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rounds", type=int, default=3)
    ap.add_argument("--segment-tokens", type=int, default=32)
    ap.add_argument("--min-batch", type=int, default=2)
    ap.add_argument("--out", default="artifacts/compaction_ab.json")
    args = ap.parse_args()

    import jax

    from bench import _pick_ragged_eos
    from vnsum_tpu.core.config import GenerationConfig
    from vnsum_tpu.data.synthesize import synthesize_corpus
    import tempfile

    root = tempfile.mkdtemp(prefix="vnsum_ab_")
    synthesize_corpus(
        f"{root}/c", n_docs=2, tokens_per_doc=37_000, summary_tokens=100,
        seed=5,
    )
    raw = open(f"{root}/c/doc/doc_000.txt", "rb").read()

    import gc

    def run(be, label, prompts, gen):
        # warmup (compile; the persistent cache usually makes this fast)
        be.generate(prompts, config=gen)
        base_tok = be.stats.generated_tokens
        t0 = time.time()
        for r in range(args.rounds):
            be.generate([p + f" vòng {r}" for p in prompts], config=gen)
        dt = time.time() - t0
        gen_tok = be.stats.generated_tokens - base_tok
        rec = {
            "seconds": round(dt, 2),
            "batches": args.rounds,
            "rows": len(prompts) * args.rounds,
            "generated_tokens": int(gen_tok),
            "sec_per_1k_tokens": round(1000 * dt / max(gen_tok, 1), 3),
            "compactions": be.stats.compactions,
            "compacted_batch_sizes": be.stats.compacted_batch_sizes,
        }
        print(f"{label}: {rec}", file=sys.stderr)
        return rec

    def ab(name, batch_size, prompt_bytes, max_seq_len, params):
        prompts = [
            "Tóm tắt: "
            + raw[i * prompt_bytes : (i + 1) * prompt_bytes].decode(
                "utf-8", "ignore"
            )
            for i in range(batch_size)
        ]
        one = build_backend(
            params, False, args.segment_tokens, args.min_batch,
            batch_size=batch_size, max_seq_len=max_seq_len,
        )
        probe = one.generate(
            prompts, config=GenerationConfig(temperature=1.0, seed=11)
        )
        eos = _pick_ragged_eos(probe, one.tok)
        gen = GenerationConfig(
            max_new_tokens=128, temperature=1.0, seed=11, eos_ids=eos
        )
        print(f"[{name}] ragged eos byte: {eos}", file=sys.stderr)
        a_rec = run(one, f"{name} one-shot", prompts, gen)
        params = one.params
        del one
        gc.collect()
        cont = build_backend(
            params, True, args.segment_tokens, args.min_batch,
            batch_size=batch_size, max_seq_len=max_seq_len,
        )
        b_rec = run(cont, f"{name} continuous", prompts, gen)
        del cont
        gc.collect()
        return {
            "workload": {
                "batch": batch_size, "max_seq_len": max_seq_len,
                "prompt_bytes": prompt_bytes, "max_new": 128,
                "temperature": 1.0, "eos_byte": list(eos),
                "segment_tokens": args.segment_tokens,
                "min_batch": args.min_batch,
            },
            "one_shot": a_rec,
            "continuous": b_rec,
            "speedup_tokens_normalized": round(
                a_rec["sec_per_1k_tokens"] / b_rec["sec_per_1k_tokens"], 3
            ) if b_rec["sec_per_1k_tokens"] else 0,
        }, params

    e2e_shape, params = ab("e2e-shape", 8, 7000, 8448, None)
    # params are seq-len-independent — reuse the quantized tree.
    # B=96 (the map bench's one-shot sweet spot) OOMs on the continuous
    # arm: the segmented path keeps cache/cur/done/out live ACROSS
    # dispatches (host-visible carry) instead of inside one program, and
    # compaction's un-donated gather briefly doubles the cache — so the
    # segmented path tops out at a smaller batch than one-shot. B=64 is
    # the largest shape both arms fit.
    map_shape, _ = ab("map-shape", 64, 900, 4096, params)

    result = {
        "e2e_shape_B8_S8192": e2e_shape,
        "map_shape_B64_S1024": map_shape,
        "note": (
            "arms compared on sec/1k generated tokens; sampled draws can "
            "differ across the compaction batch-shape change on real "
            "hardware (near-uniform random-init logits + tiling-order "
            "float differences), so totals differ slightly between arms"
        ),
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%S"),
    }
    out = Path(args.out)
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_text(json.dumps(result, indent=2))
    print(json.dumps({
        "ok": True,
        "e2e_shape_speedup": e2e_shape["speedup_tokens_normalized"],
        "map_shape_speedup": map_shape["speedup_tokens_normalized"],
    }))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
