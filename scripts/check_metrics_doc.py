#!/usr/bin/env python
"""CI lint shim: metrics registry <-> README table consistency.

The check now lives in the analysis framework as the `metrics-doc` rule
(vnsum_tpu/analysis/rules/metrics_doc.py), which also extended it to be
BIDIRECTIONAL: every registered metric must appear in the README, and every
`vnsum_serve_*` name the README mentions must be a registered metric. This
script stays as a thin entry point so CI step history remains comparable
(and old muscle memory keeps working):

    python scripts/check_metrics_doc.py

Equivalent to:

    python -m vnsum_tpu.analysis --rule metrics-doc --root . vnsum_tpu/serve

Like its predecessor it never imports the serving code — the rule parses
source, so it runs before dependencies are installed.
"""
from __future__ import annotations

import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))

from vnsum_tpu.analysis.core import render_findings, run_paths  # noqa: E402


def main() -> int:
    findings = run_paths([], root=ROOT, rules=["metrics-doc"])
    if findings:
        print(render_findings(findings))
        return 1
    print("ok: metrics registry and README observability table agree "
          "(bidirectional)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
