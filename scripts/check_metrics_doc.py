#!/usr/bin/env python
"""CI lint: every metric registered in serve/metrics.py must appear in the
README's observability metrics table.

The registry keeps metric names as literal strings in `_reg("...")` calls
exactly so this check can PARSE the source instead of importing it — the
lint runs before dependencies are installed and can never be skewed by
import-time failures. Fails (exit 1) listing any registered metric whose
full `vnsum_serve_*` name is missing from README.md.

    python scripts/check_metrics_doc.py
"""
from __future__ import annotations

import re
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
METRICS_PY = ROOT / "vnsum_tpu" / "serve" / "metrics.py"
README = ROOT / "README.md"

_REG = re.compile(r'_reg\(\s*"([a-z0-9_]+)"', re.MULTILINE)


def registered_names() -> list[str]:
    src = METRICS_PY.read_text(encoding="utf-8")
    names = _REG.findall(src)
    if not names:
        raise SystemExit(
            f"no _reg(\"...\") registrations found in {METRICS_PY} — "
            "registry moved? update scripts/check_metrics_doc.py"
        )
    return [f"vnsum_serve_{n}" for n in names]


def main() -> int:
    readme = README.read_text(encoding="utf-8")
    missing = [n for n in registered_names() if n not in readme]
    if missing:
        print("metrics registered in serve/metrics.py but missing from the "
              "README observability table:")
        for n in missing:
            print(f"  - {n}")
        return 1
    print(f"ok: all {len(registered_names())} registered metrics documented "
          "in README.md")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
