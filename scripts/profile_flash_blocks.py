"""Standalone flash-prefill kernel timing vs block geometry (DMA probe).

artifacts/prefill_gap.json: attention costs 2.7 s of the 7.0 s e2e prefill
dispatch (~39% of device time for ~18% of FLOPs), and switching the MXU
dots to bf16 moved NOTHING — so the kernel is not compute-rate-bound.
Prime suspect: K/V DMA redundancy. The grid (B, H, I, J) streams each K/V
block once per QUERY head (3x redundant under GQA 24:8) and once per
q-block (S/BQ re-streams of the prefix). If that's the bottleneck,
raising block_q (halving K/V re-streams) must cut time near-linearly
while block_k moves little (same bytes, different DMA granularity).

Times the kernel alone at the REAL e2e chunk shape (B=16, S=2048 chunk,
off=6144 — the worst chunk of the chunked prefill; C=8320, int8 cache),
28-layer-equivalent via repeated chained calls. Writes
artifacts/flash_block_geometry.json.
"""
from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="artifacts/flash_block_geometry.json")
    ap.add_argument("--iters", type=int, default=28)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from vnsum_tpu.core.jax_cache import enable_compilation_cache
    from vnsum_tpu.ops.flash_attention import flash_prefill_attention

    enable_compilation_cache()
    B, S, H, KV, hd, C = 16, 2048, 24, 8, 128, 8320
    off = 6144
    key = jax.random.key(0)
    kq, kk, kv, ks, vs = jax.random.split(key, 5)
    q = jax.random.normal(kq, (B, S, H, hd), jnp.bfloat16)
    cache = {
        "k": jax.random.randint(kk, (1, B, KV, C, hd), -127, 128, jnp.int8),
        "v": jax.random.randint(kv, (1, B, KV, C, hd), -127, 128, jnp.int8),
        "ks": jax.random.uniform(ks, (1, B, KV, C), jnp.float32, 0.01, 0.02),
        "vs": jax.random.uniform(vs, (1, B, KV, C), jnp.float32, 0.01, 0.02),
    }
    pad = jnp.zeros((B,), jnp.int32)

    def timed(bq: int, bk: int) -> dict:
        @jax.jit
        def run(q, cache):
            # cache enters as an ARGUMENT (a closure constant would ship
            # its 270 MB inside the remote-compile request body — HTTP 413).
            # Chain iters kernel calls through a data dependency so the
            # tunnel can't lie about completion (PERF.md hygiene)
            def body(i, acc):
                o = flash_prefill_attention(
                    acc, cache, 0, pad, H // KV,
                    q_offset=jnp.int32(off), block_q=bq, block_k=bk,
                )
                return o.astype(acc.dtype)

            out = jax.lax.fori_loop(0, args.iters, body, q)
            # reduce to a SCALAR on device: fetching the full [B,S,H,hd]
            # output (201 MB) through the tunnel dominates wall otherwise
            return jnp.sum(out.astype(jnp.float32))

        try:
            t0 = time.time()
            np.asarray(run(q, cache))
            compile_s = time.time() - t0
            t1 = time.time()
            np.asarray(run(q, cache))
            wall = time.time() - t1
            row = {"block_q": bq, "block_k": bk,
                   "compile_s": round(compile_s, 1),
                   "seconds_28layer": round(wall, 3),
                   "ms_per_layer": round(1e3 * wall / args.iters, 2)}
        except Exception as e:
            row = {"block_q": bq, "block_k": bk, "status": "failed",
                   "error": str(e)[:200]}
        print(json.dumps(row), file=sys.stderr)
        return row

    rows = [
        timed(512, 512),    # production default
        timed(1024, 512),   # half the K/V re-streams
        timed(2048, 512),   # quarter the re-streams (whole chunk = 1 block)
        timed(512, 1024),   # same bytes, coarser DMA granularity
        timed(1024, 1024),
        timed(2048, 1024),
    ]
    rec = {
        "what": ("flash_prefill_attention alone at the e2e chunk shape "
                 "(B=16, S=2048@off=6144, C=8320, int8 cache, bf16 q), "
                 f"{args.iters} chained calls"),
        "rows": rows,
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%S"),
    }
    out = Path(args.out)
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_text(json.dumps(rec, indent=2))
    print(json.dumps({"ok": True, "rows": [
        {k: r.get(k) for k in ("block_q", "block_k", "ms_per_layer", "status")}
        for r in rows
    ]}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
