"""Engine-as-judge with TRAINED weights: content-dependent G-Eval scores.

Closes the round-5 caveat on VERDICT r4 missing #4: the constrained-choice
device judge parses a score on every case, but on an untrained fixture the
digit is input-independent (degenerate 5/5, artifacts/geval_e2e.json).
Here the judge fixture is TRAINED on the judging curriculum
(vnsum_tpu/eval/judge_fixture.py — corruption-graded summaries under the
production judge template), then run as a real ``TpuBackend`` +
``LLMJudge(constrained=True)``:

1. held-out grading — fresh cases at five corruption levels through
   ``LLMJudge.evaluate`` (the pipeline's exact seam): per-level mean
   scores must DECREASE with corruption, and the distribution must span
   multiple digits (the "sane distributions" VERDICT asked for).
2. full-pipeline pass — ``PipelineRunner`` with ``include_llm_eval``, the
   trained judge as the device judge, and planted generated summaries at
   per-doc corruption levels: the results file's
   ``summary_statistics.llm_scores`` (the block the reference's schema
   carries, evaluate/evaluate_summaries_semantic.py:203-433) shows
   non-degenerate spread, llm_failed_cases == 0.

Writes artifacts/geval_trained_judge.json.
"""
from __future__ import annotations

import argparse
import json
import random
import sys
import tempfile
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="artifacts/geval_trained_judge.json")
    ap.add_argument("--steps", type=int, default=800)
    ap.add_argument("--n-per-level", type=int, default=24)
    ap.add_argument("--judge-dir", default="",
                    help="reuse an already-trained judge checkpoint "
                         "(skips the ~55-min CPU training phase)")
    args = ap.parse_args()

    from vnsum_tpu.backend.engine import TpuBackend
    from vnsum_tpu.backend.fake import FakeBackend
    from vnsum_tpu.core.config import EvalConfig, PipelineConfig
    from vnsum_tpu.core.jax_cache import enable_compilation_cache
    from vnsum_tpu.eval import LLMJudge
    from vnsum_tpu.eval.judge_fixture import (
        LEVELS,
        corrupt,
        make_summary,
        train_judge_fixture,
    )
    from vnsum_tpu.models.convert import load_hf_checkpoint
    from vnsum_tpu.pipeline.runner import PipelineRunner, model_name_safe

    enable_compilation_cache()
    root = tempfile.mkdtemp(prefix="vnsum_judge_")

    # training provenance travels WITH the checkpoint (train_meta.json
    # sidecar) so the --judge-dir fast path reproduces the same artifact
    # fields instead of recording a ~0s no-op as the training time
    if args.judge_dir:
        judge_dir = args.judge_dir
        if args.steps != 800 or args.n_per_level != 24:
            print("WARNING: --steps/--n-per-level are ignored with "
                  "--judge-dir (the checkpoint is already trained)",
                  file=sys.stderr)
        meta_p = Path(judge_dir) / "train_meta.json"
        train_meta = (json.loads(meta_p.read_text()) if meta_p.exists()
                      else {"note": "reused checkpoint without sidecar; "
                                    "training provenance unknown"})
    else:
        judge_dir = f"{root}/judge"
        t0 = time.time()
        train_judge_fixture(
            judge_dir, steps=args.steps, n_per_level=args.n_per_level,
            progress=lambda s, l: print(f"  step {s}: loss {l:.3f}",
                                        file=sys.stderr),
        )
        train_meta = {
            "train_seconds": round(time.time() - t0, 1),
            "steps": args.steps,
            "n_per_level": args.n_per_level,
            "lr": "2e-3 cosine (train_judge_fixture default)",
            "seed": 0,
        }
        (Path(judge_dir) / "train_meta.json").write_text(
            json.dumps(train_meta, indent=2)
        )

    cfg, params = load_hf_checkpoint(judge_dir)
    judge_engine = TpuBackend(
        model_config=cfg, params=params, tokenizer=f"hf:{judge_dir}",
        batch_size=8, max_new_tokens=8,
    )
    judge = LLMJudge(backend=judge_engine, constrained=True)

    # --- arm 1: held-out grading, per corruption level -------------------
    rng = random.Random(999)  # disjoint from the training seed
    per_level = {}
    n_eval = 8
    for p in LEVELS:
        gen, ref = {}, {}
        for i in range(n_eval):
            r = make_summary(rng)
            g = corrupt(rng, make_summary(rng) if p > 0 else r, p)
            gen[f"case{i}.txt"], ref[f"case{i}.txt"] = g, r
        stats = judge.evaluate(gen, ref)
        per_level[str(p)] = {
            "correctness_mean_1to5":
                round(1 + 4 * stats["llm_correctness_mean"], 3),
            "coherence_mean_1to5":
                round(1 + 4 * stats["llm_coherence_mean"], 3),
            "failed": stats["llm_failed_cases"],
        }
        print(f"level {p}: {per_level[str(p)]}", file=sys.stderr)

    means = [per_level[str(p)]["correctness_mean_1to5"] for p in LEVELS]
    monotone_pairs = sum(
        1 for a, b in zip(means, means[1:]) if a >= b
    )
    spread = max(means) - min(means)

    # --- arm 2: full pipeline with planted per-doc quality ----------------
    # truncated approach = one LLM call per doc, so FakeBackend responses
    # map 1:1 onto docs in sorted-filename order; each doc gets a corruption
    # level and the device judge grades through the FULL runner/evaluator
    doc_dir = Path(f"{root}/c/doc"); doc_dir.mkdir(parents=True)
    sum_dir = Path(f"{root}/c/summary"); sum_dir.mkdir(parents=True)
    rng2 = random.Random(1234)
    doc_levels = [0.0, 0.0, 0.5, 0.5, 1.0, 1.0]
    planted = []
    for i, p in enumerate(doc_levels):
        ref = make_summary(rng2, sentences=3)
        body = " ".join(make_summary(rng2, sentences=4) for _ in range(3))
        (doc_dir / f"doc{i}.txt").write_text(ref + " " + body,
                                             encoding="utf-8")
        (sum_dir / f"doc{i}.txt").write_text(ref, encoding="utf-8")
        planted.append(corrupt(rng2, ref, p))
    pcfg = PipelineConfig(
        approach="truncated",
        models=["llama3.2-3b"],
        backend="fake",
        docs_dir=str(doc_dir),
        summary_dir=str(sum_dir),
        generated_summaries_dir=f"{root}/gen",
        results_dir=f"{root}/results",
        logs_dir=f"{root}/logs",
        chunk_size=1200,
        chunk_overlap=50,
        token_max=1000,
        max_new_tokens=64,
        evaluation=EvalConfig(include_llm_eval=True),
    )
    planted_backend = FakeBackend(responses=list(planted))
    runner = PipelineRunner(
        pcfg, backend_factory=lambda model: planted_backend, llm_judge=judge
    )
    results = runner.run()
    pipe_scores = results.evaluation["llama3.2-3b"]["llm_scores"]
    on_disk = json.loads(
        (Path(pcfg.results_dir)
         / f"{model_name_safe('llama3.2-3b')}_results.json").read_text()
    )
    assert on_disk["summary_statistics"]["llm_scores"] == pipe_scores

    rec = {
        "what": ("TRAINED tiny judge on the engine: constrained-choice "
                 "G-Eval with content-dependent scores"),
        "judge_training": train_meta,
        "judge_checkpoint_reused": bool(args.judge_dir),
        "held_out_by_corruption_level": per_level,
        "held_out_checks": {
            "correctness_means_1to5_by_level": means,
            "monotone_nonincreasing_pairs": f"{monotone_pairs}/4",
            "spread_1to5": round(spread, 3),
        },
        "pipeline_llm_scores": pipe_scores,
        "pipeline_doc_corruption_levels": doc_levels,
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%S"),
    }
    out = Path(args.out)
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_text(json.dumps(rec, indent=2))
    ok = (spread >= 1.0 and monotone_pairs >= 3
          and pipe_scores["llm_failed_cases"] == 0
          and pipe_scores["llm_correctness_std"] > 0)
    print(json.dumps({"ok": ok, "spread": spread,
                      "monotone_pairs": monotone_pairs,
                      "pipeline_failed": pipe_scores["llm_failed_cases"],
                      "out": str(out)}))
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
