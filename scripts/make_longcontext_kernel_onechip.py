"""One-chip validation of the kernelized long-context decode (VERDICT r3 #5,
hardened per VERDICT r4 #4).

Runs the REAL llama3.2-3b shapes through the long-context path on a
degenerate seq=1 mesh (one chip), dense einsum shard partial vs the
stacked-cache Pallas kernel partial, at the e2e-relevant shape
(B=8, ~7.9k-token prompts, 64 sampled new tokens). At seq=1 the shard IS
the whole cache, so the A/B isolates exactly what the kernel removes: the
per-step per-layer `dynamic_index_in_dim` extraction copy (~3.8 GB/step of
int8 K/V at this shape) plus the dense lowering's layout copies.

The r4 attempt lost every copy-dominated shape to transient HTTP 500s from
the remote-compile service and proved only the expected tie at B=2/4k
(weight-dominated). This version: (1) retries transient compile-service
failures with backoff (deterministic OOMs fail fast — the boundary is
data); (2) brackets with intermediate shapes (B=8/6k, B=4/6k); (3) runs
the weight-dominated control first, then measures copy-dominated shapes
until one pair lands, keeping the exhaustive attempt log either way.

Writes artifacts/longcontext_kernel_onechip.json.
"""
from __future__ import annotations

import gc
import json
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

_FILLER = "Quốc hội thông qua nghị quyết phát triển kinh tế xã hội. "


def run_arm(decode_kernel: bool, params, cfg, mesh, B: int, tokens: int):
    from vnsum_tpu.backend.long_context import LongContextBackend
    from vnsum_tpu.core.config import GenerationConfig

    # sampled decode: greedy random-init hits the (sampleable) EOS within a
    # couple of tokens; T=1.0 rows run most of the 64-token budget
    gen = GenerationConfig(temperature=1.0, seed=3)
    be = LongContextBackend(
        model_config=cfg, mesh=mesh, params=params, batch_size=B,
        max_new_tokens=64, max_total_tokens=8192,
        quantize=True, quantize_kv=True, decode_kernel=decode_kernel,
    )
    body = _FILLER * (tokens // len(_FILLER.encode()) + 1)
    prompts = [f"tài liệu {i}: {body}"[:tokens] for i in range(B)]
    t0 = time.time()
    be.generate(prompts, config=gen)  # compile + first run
    compile_and_run = time.time() - t0
    t1 = time.time()
    outs = be.generate([p + " tiếp" for p in prompts], config=gen)
    warm = time.time() - t1
    return {
        "decode_kernel": decode_kernel,
        "B": B, "prompt_tokens": tokens,
        "compile_and_first_run_s": round(compile_and_run, 1),
        "warm_run_s": round(warm, 2),
        "outputs_nonempty": sum(bool(o) for o in outs),
    }


def narrow_mechanism_config():
    """A dim-1024/16-layer Llama variant where K/V extraction DOMINATES
    weights at shapes the remote-compile service accepts.

    The copy-vs-weights ratio is (B*C*KV*hd*2) / per-layer-weight-bytes —
    independent of layer count — so at the 3B's 99 MB/layer the ratio
    needs B*C >= ~48k, and every such long-context program deterministically
    kills the compile helper (attempt_log). This config has 17 MB/layer:
    at B=4/C~4160 extraction is ~3.6x weights, same kernels, same code
    path, at the B=2/4k-class program size the service compiles."""
    from vnsum_tpu.models.llama import LlamaConfig

    return LlamaConfig(
        vocab_size=32_768, dim=1024, n_layers=16, n_heads=8, n_kv_heads=8,
        head_dim=128, intermediate=4096, max_seq_len=8192,
        use_llama3_rope_scaling=False, rope_theta=500_000.0,
    )


def main() -> int:
    import argparse

    from vnsum_tpu.core.jax_cache import enable_compilation_cache
    from vnsum_tpu.models import jitted_init, llama32_3b
    from vnsum_tpu.models.llama import init_params
    from vnsum_tpu.parallel.mesh import make_mesh

    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--phase", default="all", choices=("all", "ladder", "narrow"),
        help="'narrow' appends the mechanism rows to an existing artifact "
             "without re-running the 3B ladder",
    )
    args = ap.parse_args()

    enable_compilation_cache()
    cfg = llama32_3b(max_seq_len=8192)
    mesh = make_mesh({"data": 1, "model": 1, "seq": 1})
    params = None
    if args.phase in ("all", "ladder"):
        params = jitted_init(init_params, cfg, 0)

    rec: dict = {
        "config": "llama3.2-3b int8 weights + int8 prefill cache, 64 new "
                  "tokens sampled T=1.0, mesh seq=1 (one chip)",
        "attempt_log": [],
        "shapes": [],
    }
    out = REPO / "artifacts" / "longcontext_kernel_onechip.json"
    if args.phase == "narrow" and out.exists():
        rec = json.loads(out.read_text())  # keep the measured 3B rows

    _TRANSIENT = ("500", "502", "503", "UNAVAILABLE", "DEADLINE",
                  "INTERNAL", "connection", "Connection", "timed out")

    def attempt_with_retries(kernel: bool, B: int, tokens: int, tries=3,
                             cfg_=None, params_=None):
        name = "kernel" if kernel else "dense"
        for t in range(tries):
            try:
                row = run_arm(
                    kernel, params_ if params_ is not None else params,
                    cfg_ if cfg_ is not None else cfg, mesh, B, tokens,
                )
                rec["attempt_log"].append(
                    {"arm": name, "B": B, "prompt_tokens": tokens,
                     "try": t + 1, "ok": True}
                )
                print(row, file=sys.stderr)
                return row
            except Exception as e:
                msg = str(e)
                rec["attempt_log"].append(
                    {"arm": name, "B": B, "prompt_tokens": tokens,
                     "try": t + 1, "ok": False, "error": msg[:300]}
                )
                print(f"{name} B={B}/{tokens} try {t + 1} failed: "
                      f"{msg[:160]}", file=sys.stderr)
                gc.collect()
                # only the remote-compile service's transient failures are
                # worth a retry (on success the program lands in the
                # persistent cache, so a retry never re-pays what already
                # compiled); an OOM at these shapes is deterministic — the
                # capacity boundary is data, retrying it is pure waste
                if "RESOURCE_EXHAUSTED" in msg or not any(
                    s in msg for s in _TRANSIENT
                ):
                    return None
                if t + 1 < tries:
                    time.sleep(20 * (t + 1))
        return None

    # SMALL shape first: the weight-dominated tie is the control row the
    # claim needs (r4 measured 1.01x there; re-measuring keeps the artifact
    # self-contained after this rewrite) — then copy-dominated big-to-small
    # (B=8/7.9k: ~3.8 GB of K/V extraction per step vs 3.2 GB of weights),
    # with 6k brackets between the r4 failures and the known-good shape
    ladder = (
        ((2, 4000), (8, 7900), (8, 6000), (4, 7900), (4, 6000))
        if args.phase in ("all", "ladder") else ()
    )
    for B, tokens in ladder:
        arms = {}
        for kernel in (False, True):
            row = attempt_with_retries(kernel, B, tokens)
            if row is not None:
                arms["kernel" if kernel else "dense"] = row
            gc.collect()
        shape_rec: dict = {"B": B, "prompt_tokens": tokens, **arms}
        if "dense" in arms and "kernel" in arms:
            shape_rec["warm_speedup_kernel_vs_dense"] = round(
                arms["dense"]["warm_run_s"]
                / max(arms["kernel"]["warm_run_s"], 1e-9), 2
            )
        elif "kernel" in arms:
            shape_rec["note"] = (
                "dense arm failed at this shape; kernel ran — the "
                "extraction-copy savings ARE the capacity margin"
            )
        if arms:
            rec["shapes"].append(shape_rec)
        # checkpoint after every shape: a later OOM/crash must not lose
        # measured rows
        out.write_text(json.dumps(rec, indent=2))
        # stop once BOTH rows the claim needs are measured — the small-shape
        # weight-dominated control AND a copy-dominated pair; further
        # brackets are compile-budget without information
        done_pairs = [
            s for s in rec["shapes"] if "warm_speedup_kernel_vs_dense" in s
        ]
        have_control = any(
            s["B"] * s["prompt_tokens"] <= 2 * 4000 for s in done_pairs
        )
        have_big = any(
            s["B"] * s["prompt_tokens"] >= 8 * 6000 for s in done_pairs
        )
        if have_control and have_big:
            break

    rec["headline"] = next(
        (
            {
                "B": s["B"], "prompt_tokens": s["prompt_tokens"],
                "warm_speedup_kernel_vs_dense":
                    s["warm_speedup_kernel_vs_dense"],
            }
            for s in rec["shapes"]
            if "warm_speedup_kernel_vs_dense" in s
            and s["B"] * s["prompt_tokens"] >= 8 * 6000
        ),
        None,
    )

    if args.phase in ("all", "narrow") and rec["headline"] is None:
        # mechanism demonstration (VERDICT r4 #4 fallback, beyond the
        # attempt log): every 3B shape past B=2/4k deterministically kills
        # the compile helper, so demonstrate the extraction-copy claim at a
        # config whose PER-LAYER weights are small enough that B=4/4k is
        # already ~3.6x copy-dominated — same kernels, same code path,
        # program-size class the service compiles
        del params
        gc.collect()
        ncfg = narrow_mechanism_config()
        nparams = jitted_init(init_params, ncfg, 1)
        narrow_rows = []
        for B, tokens in ((4, 4000), (2, 4000)):
            arms = {}
            for kernel in (False, True):
                row = attempt_with_retries(
                    kernel, B, tokens, cfg_=ncfg, params_=nparams
                )
                if row is not None:
                    arms["kernel" if kernel else "dense"] = row
                gc.collect()
            nrow: dict = {"B": B, "prompt_tokens": tokens, **arms}
            if "dense" in arms and "kernel" in arms:
                nrow["warm_speedup_kernel_vs_dense"] = round(
                    arms["dense"]["warm_run_s"]
                    / max(arms["kernel"]["warm_run_s"], 1e-9), 2
                )
            if arms:
                narrow_rows.append(nrow)
            rec["narrow_mechanism"] = {
                "config": (
                    "dim-1024/16L/8kv/hd128, int8+int8KV: 17 MB/layer "
                    "weights -> extraction/weights ~3.6x at B=4/C~4160 "
                    "(vs 0.34x at the 3B control shape)"
                ),
                "shapes": narrow_rows,
            }
            out.write_text(json.dumps(rec, indent=2))
            if narrow_rows and "warm_speedup_kernel_vs_dense" in narrow_rows[0]:
                break  # the copy-dominated row landed; the control is optional

    out.write_text(json.dumps(rec, indent=2))
    print(json.dumps({"ok": True, "headline": rec["headline"],
                      "attempts": len(rec["attempt_log"])}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
