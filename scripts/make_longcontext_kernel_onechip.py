"""One-chip validation of the kernelized long-context decode (VERDICT r3 #5).

Runs the REAL llama3.2-3b shapes through the long-context path on a
degenerate seq=1 mesh (one chip), dense einsum shard partial vs the
stacked-cache Pallas kernel partial, at the e2e-relevant shape
(B=8, ~7.9k-token prompts, 64 sampled new tokens). At seq=1 the shard IS
the whole cache, so the A/B isolates exactly what the kernel removes: the
per-step per-layer `dynamic_index_in_dim` extraction copy (~3.8 GB/step of
int8 K/V at this shape) plus the dense lowering's layout copies. If an arm
does not fit the chip at a shape, that is recorded and the ladder steps
down — "kernel runs where dense cannot" is itself the finding.

Writes artifacts/longcontext_kernel_onechip.json.
"""
from __future__ import annotations

import gc
import json
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

_FILLER = "Quốc hội thông qua nghị quyết phát triển kinh tế xã hội. "


def run_arm(decode_kernel: bool, params, cfg, mesh, B: int, tokens: int):
    from vnsum_tpu.backend.long_context import LongContextBackend
    from vnsum_tpu.core.config import GenerationConfig

    # sampled decode: greedy random-init hits the (sampleable) EOS within a
    # couple of tokens; T=1.0 rows run most of the 64-token budget
    gen = GenerationConfig(temperature=1.0, seed=3)
    be = LongContextBackend(
        model_config=cfg, mesh=mesh, params=params, batch_size=B,
        max_new_tokens=64, max_total_tokens=8192,
        quantize=True, quantize_kv=True, decode_kernel=decode_kernel,
    )
    body = _FILLER * (tokens // len(_FILLER.encode()) + 1)
    prompts = [f"tài liệu {i}: {body}"[:tokens] for i in range(B)]
    t0 = time.time()
    be.generate(prompts, config=gen)  # compile + first run
    compile_and_run = time.time() - t0
    t1 = time.time()
    outs = be.generate([p + " tiếp" for p in prompts], config=gen)
    warm = time.time() - t1
    return {
        "decode_kernel": decode_kernel,
        "B": B, "prompt_tokens": tokens,
        "compile_and_first_run_s": round(compile_and_run, 1),
        "warm_run_s": round(warm, 2),
        "outputs_nonempty": sum(bool(o) for o in outs),
    }


def main() -> int:
    from vnsum_tpu.core.jax_cache import enable_compilation_cache
    from vnsum_tpu.models import jitted_init, llama32_3b
    from vnsum_tpu.models.llama import init_params
    from vnsum_tpu.parallel.mesh import make_mesh

    enable_compilation_cache()
    cfg = llama32_3b(max_seq_len=8192)
    mesh = make_mesh({"data": 1, "model": 1, "seq": 1})
    params = jitted_init(init_params, cfg, 0)

    rec: dict = {
        "config": "llama3.2-3b int8 weights + int8 prefill cache, 64 new "
                  "tokens sampled T=1.0, mesh seq=1 (one chip)",
        "failures": [],
    }
    for B, tokens in ((8, 7900), (4, 7900), (2, 4000)):
        arms = {}
        for kernel in (False, True):
            name = "kernel" if kernel else "dense"
            try:
                arms[name] = run_arm(kernel, params, cfg, mesh, B, tokens)
                print(arms[name], file=sys.stderr)
            except Exception as e:
                rec["failures"].append(
                    {"arm": name, "B": B, "prompt_tokens": tokens,
                     "error": str(e)[:300]}
                )
                print(f"{name} B={B} failed: {str(e)[:160]}", file=sys.stderr)
            gc.collect()
        if "dense" in arms and "kernel" in arms:
            rec["dense"], rec["kernel"] = arms["dense"], arms["kernel"]
            rec["warm_speedup_kernel_vs_dense"] = round(
                arms["dense"]["warm_run_s"]
                / max(arms["kernel"]["warm_run_s"], 1e-9), 2
            )
            break
        if "kernel" in arms and "dense" not in arms:
            rec["kernel"] = arms["kernel"]
            rec["note"] = (
                "dense partial did not fit at this shape; the kernel arm "
                "ran — the extraction-copy savings ARE the capacity margin"
            )
            break

    out = REPO / "artifacts" / "longcontext_kernel_onechip.json"
    out.write_text(json.dumps(rec, indent=2))
    print(json.dumps({"ok": True,
                      "speedup": rec.get("warm_speedup_kernel_vs_dense"),
                      "failures": len(rec["failures"])}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
