#!/usr/bin/env bash
# Tier-1 verify — static analysis gate, then the ROADMAP.md command verbatim.
# CI and local runs use this wrapper so "what the driver checks" and "what
# you ran" cannot drift.

# named step: domain lint (guarded-by, host-sync-in-hot-path,
# donation-safety, jit-recompile-hazard, metrics-doc). Exit 1 here means a
# machine-checked invariant broke — fix it or lint-allow it with a reason.
echo "== analysis: python -m vnsum_tpu.analysis vnsum_tpu/ scripts/ =="
python -m vnsum_tpu.analysis vnsum_tpu/ scripts/ || exit 1

# named step: the tier-1 fast suite (ROADMAP command, verbatim)
set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=${PIPESTATUS[0]}; echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c); exit $rc
