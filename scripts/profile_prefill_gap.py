"""Decompose prefill device time at the e2e shape: attention vs projections.

PERF finding 14/18: prefill runs at 0.66-0.67 MFU (bf16-peak basis) with
W8A8 — the largest single term in the e2e wall (67% of device time). This
script attributes the remaining gap: of the ~7 s B=16/S=8192 chunked
dispatch, how much is the bf16 flash-attention kernel (the only major
MXU consumer W8A8 does NOT accelerate) and how much is the s8xs8
projection path already at its measured ceiling?

Ablation arms (instrument=True, one B=16 dispatch, chunk 2048, warm):

  A  baseline      — e2e_engine_kwargs exact (W8A8, flash kernels)
  B  window-256    — sliding_window=256 on EVERY layer: the prefill
                     kernel clamps FLOPs and DMAs to a 256-token band
                     (finding 15), removing ~97% of attention work at
                     S=8192. Attention cost ~= A - B.
  C  no-W8A8       — quantize_act=False: the projection matmuls fall
                     back to mixed int8xbf16 (bf16 MXU rate). W8A8's
                     projection gain ~= C - A (cross-check of finding 18).

Analytic table: FLOPs per dispatch (projections 2*tokens*params, causal
attention 2*B*H*S^2*hd per layer for QK^T+PV), the s8 microbench ceiling
(132.7 TFLOP/s) and bf16 peak (197) — so the measured arms can be read
against an optimistic bound. Writes artifacts/prefill_gap.json.
"""
from __future__ import annotations

import argparse
import gc
import json
import sys
import tempfile
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

BF16_PEAK = 197e12
S8_MEASURED_CEILING = 132.7e12  # chained-matmul microbench, PERF finding 18


def run_arm(label: str, tok_spec, prompts, gen_cfg, model_kw: dict,
            engine_overrides: dict) -> dict:
    import bench
    from vnsum_tpu.backend.engine import EngineStats, TpuBackend
    from vnsum_tpu.models import llama32_3b

    kw = bench.e2e_engine_kwargs(tok_spec, None)
    if model_kw:
        kw["model_config"] = llama32_3b(max_seq_len=8448, **model_kw)
    kw.update(engine_overrides)
    try:
        be = TpuBackend(**kw, instrument=True)
        t0 = time.time()
        be.generate(prompts, config=gen_cfg)
        compile_s = time.time() - t0
        be.stats = EngineStats()
        t1 = time.time()
        be.generate(prompts, config=gen_cfg)
        wall = time.time() - t1
        st = be.stats
        row = {
            "label": label,
            "compile_and_warm_s": round(compile_s, 1),
            "wall_s": round(wall, 2),
            "prefill_s": round(st.phase_seconds.get("prefill", 0.0), 3),
            "decode_s": round(st.phase_seconds.get("decode", 0.0), 3),
            "dispatches": st.dispatches,
        }
        del be
        gc.collect()
        print(f"{label}: {json.dumps(row)[:300]}", file=sys.stderr)
        return row
    except Exception as e:
        gc.collect()
        row = {"label": label, "status": "failed", "error": str(e)[:300]}
        print(f"{label} FAILED: {str(e)[:200]}", file=sys.stderr)
        return row


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="artifacts/prefill_gap.json")
    ap.add_argument("--max-new", type=int, default=32)
    args = ap.parse_args()

    from vnsum_tpu.core.config import GenerationConfig
    from vnsum_tpu.core.jax_cache import enable_compilation_cache
    from vnsum_tpu.data.synthesize import synthesize_corpus
    from vnsum_tpu.models import llama32_3b
    from vnsum_tpu.models.fixtures import train_bpe_tokenizer

    enable_compilation_cache()
    root = tempfile.mkdtemp(prefix="vnsum_pfgap_")
    synthesize_corpus(
        f"{root}/corpus", n_docs=4, tokens_per_doc=9_000,
        summary_tokens=200, seed=7, ragged=0.0,
    )
    doc_paths = sorted(Path(f"{root}/corpus/doc").glob("*.txt"))
    hf_tok = train_bpe_tokenizer(
        (p.read_text(encoding="utf-8") for p in doc_paths), vocab_size=4096
    )
    hf_tok.save_pretrained(f"{root}/tok")
    tok_spec = f"hf:{root}/tok"
    words = " ".join(p.read_text(encoding="utf-8") for p in doc_paths).split()
    prompts = []
    for i in range(16):
        seg = " ".join(words[(i * 1500) % 20000 : (i * 1500) % 20000 + 7400])
        prompts.append(f"Tóm tắt văn bản số {i}: " + seg)
    gen_cfg = GenerationConfig(max_new_tokens=args.max_new, temperature=1.0,
                               seed=11)

    rows = [
        run_arm("A_baseline", tok_spec, prompts, gen_cfg, {}, {}),
        run_arm("B_window256", tok_spec, prompts, gen_cfg,
                {"sliding_window": 256}, {}),
        run_arm("C_no_w8a8", tok_spec, prompts, gen_cfg, {},
                {"quantize_act": False}),
    ]

    # analytic FLOPs at the dispatch shape
    cfg = llama32_3b(max_seq_len=8448)
    B, S = 16, 8192
    params = (
        cfg.vocab_size * cfg.dim
        + cfg.n_layers * (
            cfg.dim * cfg.n_heads * cfg.head_dim          # q
            + 2 * cfg.dim * cfg.n_kv_heads * cfg.head_dim  # k, v
            + cfg.n_heads * cfg.head_dim * cfg.dim         # o
            + 3 * cfg.dim * cfg.intermediate               # SwiGLU
        )
    )
    proj_flops = 2 * B * S * params
    # QK^T and PV are 2*B*H*S*S*hd FLOPs EACH (mult+add); causal halves
    # the S^2 → per-layer total 2*B*H*S^2*hd
    attn_flops = cfg.n_layers * 2 * B * cfg.n_heads * S * S * cfg.head_dim
    analytic = {
        "B": B, "S": S,
        "proj_flops": proj_flops,
        "attn_flops_causal": attn_flops,
        "attn_share_of_flops": round(
            attn_flops / (attn_flops + proj_flops), 3),
        "optimistic_bound_s": round(
            proj_flops / S8_MEASURED_CEILING + attn_flops / BF16_PEAK, 2),
        "s8_ceiling_tflops": S8_MEASURED_CEILING / 1e12,
        "bf16_peak_tflops": BF16_PEAK / 1e12,
        "note": (
            "optimistic_bound_s is a SANITY SCALE, not a bound: the "
            "chained-matmul s8 microbench (132.7 TFLOP/s) underestimates "
            "what the fused decoder achieves at this shape (~173 TFLOP/s "
            "on the projection share — MFU 0.88 of bf16 peak per the "
            "instrumented device budget), so measured dispatches can land "
            "below it"
        ),
    }

    ok = {r["label"]: r for r in rows if r.get("status") != "failed"}
    derived = {}
    if "A_baseline" in ok and "B_window256" in ok:
        derived["attention_cost_s"] = round(
            ok["A_baseline"]["prefill_s"] - ok["B_window256"]["prefill_s"], 3)
    if "A_baseline" in ok and "C_no_w8a8" in ok:
        derived["w8a8_projection_gain_s"] = round(
            ok["C_no_w8a8"]["prefill_s"] - ok["A_baseline"]["prefill_s"], 3)

    rec = {
        "what": ("prefill device-time decomposition at the e2e dispatch "
                 "(B=16, S=8192, chunk 2048)"),
        "arms": rows,
        "derived": derived,
        "analytic": analytic,
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%S"),
    }
    out = Path(args.out)
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_text(json.dumps(rec, indent=2))
    print(json.dumps({"ok": True, "derived": derived,
                      "analytic_attn_share": analytic["attn_share_of_flops"]}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
