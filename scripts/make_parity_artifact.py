"""Produce the end-to-end quality-parity artifact.

Exercises the COMPLETE real-weight chain the quality gate needs
(VERDICT r1 'what's missing' #1): HF checkpoint on disk (config.json +
model.safetensors + trained BPE tokenizer) → models.convert →
TpuBackend(HF tokenizer) → mapreduce strategy → ROUGE/BERTScore/semsim →
structured results JSON. With no pretrained weights on an air-gapped host,
the checkpoint is a tiny real-format transformers Llama LM-trained on a
synthetic VN corpus (models.fixtures), so greedy decoding emits sane
Vietnamese and ROUGE is meaningful.

For the reference's actual gate (mapreduce + Llama-3.2-3B on VN-LongSum,
ROUGE-L ≈ 0.3053 — evaluation_results/first_dataset/mapreduce/
llama3_2_3b_results.json), run the same command with the real checkout:

    vnsum-pipeline --approach mapreduce --backend tpu \
        --weights-dir /path/to/Llama-3.2-3B \
        --docs-dir data_1/doc --summary-dir data_1/summary

Usage: python scripts/make_parity_artifact.py [--out artifacts/parity_e2e_tiny.json]
"""
from __future__ import annotations

import argparse
import json
import sys
import tempfile
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=str(REPO / "artifacts/parity_e2e_tiny.json"))
    ap.add_argument("--workdir", default=None)
    ap.add_argument("--docs", type=int, default=6)
    ap.add_argument("--tokens-per-doc", type=int, default=1500)
    ap.add_argument("--train-steps", type=int, default=300)
    args = ap.parse_args()

    from vnsum_tpu.core.config import PipelineConfig
    from vnsum_tpu.data.synthesize import synthesize_corpus
    from vnsum_tpu.models.fixtures import (
        make_tiny_hf_checkpoint,
        make_tiny_hf_encoder_checkpoint,
    )
    from vnsum_tpu.pipeline.runner import PipelineRunner

    work = Path(args.workdir or tempfile.mkdtemp(prefix="parity_"))
    corpus_dir = work / "corpus"
    ckpt_dir = work / "ckpt"
    enc_dir = work / "encoder"

    t0 = time.time()
    corpus_stats = synthesize_corpus(
        corpus_dir, n_docs=args.docs, tokens_per_doc=args.tokens_per_doc,
        summary_tokens=100, seed=0,
    )
    docs = [
        p.read_text(encoding="utf-8")
        for p in sorted((corpus_dir / "doc").glob("*.txt"))
    ]
    ckpt_info = make_tiny_hf_checkpoint(
        ckpt_dir, docs, vocab_size=1024, train_steps=args.train_steps,
    )
    # BERT-family encoder checkpoint for the embedding metrics: the same
    # convert chain a real all-MiniLM-L6-v2 / mBERT checkout would take
    enc_info = make_tiny_hf_encoder_checkpoint(enc_dir, docs, vocab_size=1024)

    cfg = PipelineConfig(
        approach="mapreduce",
        models=["tiny-vn-parity"],
        backend="tpu",
        weights_dir=str(ckpt_dir),
        docs_dir=str(corpus_dir / "doc"),
        summary_dir=str(corpus_dir / "summary"),
        generated_summaries_dir=str(work / "gen"),
        results_dir=str(work / "results"),
        logs_dir=str(work / "logs"),
        chunk_size=400,
        chunk_overlap=40,
        token_max=300,
        max_new_tokens=96,
        batch_size=8,
    )
    cfg.evaluation.embedding_dir = str(enc_dir)
    runner = PipelineRunner(cfg)
    results = runner.run()

    model = cfg.models[0]
    evaluation = results.evaluation.get(model, {})
    summarization = results.summarization.get(model, {})
    samples = sorted(runner._output_dir(model).glob("*.txt"))
    if not samples:
        raise RuntimeError(
            f"no summaries generated; summarization record: {summarization}"
        )

    artifact = {
        "what": (
            "end-to-end real-weight parity chain: HF safetensors checkpoint "
            "-> models.convert -> TpuBackend(HF BPE tokenizer) -> mapreduce "
            "-> ROUGE; tiny real-format transformers Llama LM-trained on a "
            "synthetic VN corpus (no pretrained weights on this host)"
        ),
        "reference_gate": {
            "note": (
                "reference quality gate is mapreduce + Llama-3.2-3B on "
                "VN-LongSum, ROUGE-L ~= 0.3053; run the runbook_command "
                "with that checkpoint to reproduce it on this framework"
            ),
            "runbook_command": (
                "vnsum-pipeline --approach mapreduce --backend tpu "
                "--weights-dir /path/to/Llama-3.2-3B "
                "--embedding-dir /path/to/all-MiniLM-L6-v2 "
                "--docs-dir data_1/doc --summary-dir data_1/summary"
            ),
        },
        "backend": "tpu",
        "jax_devices": _devices(),
        "corpus": {
            "docs": corpus_stats["documents"]["total_files"],
            "avg_doc_tokens": corpus_stats["documents"]["avg_tokens_per_file"],
            "avg_summary_tokens": corpus_stats["summaries"]["avg_tokens_per_file"],
        },
        "checkpoint": ckpt_info,
        "encoder_checkpoint": enc_info,
        "summarization": {
            k: summarization.get(k)
            for k in ("successful", "failed", "total_chunks", "total_time")
        },
        "evaluation": evaluation,
        "sample_generated_summary": samples[0].read_text(encoding="utf-8")[:500],
        "wall_seconds": round(time.time() - t0, 1),
        "embedding_metrics_note": (
            "bert/semsim computed with the on-device encoder loaded from a "
            "real-format HF BERT checkpoint via models.convert_encoder "
            "(--embedding-dir) — the same chain a pretrained "
            "all-MiniLM-L6-v2 / mBERT checkout takes; parity vs "
            "transformers tested in tests/test_model_convert_encoder.py"
        ),
    }
    out = Path(args.out)
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_text(
        json.dumps(artifact, indent=1, ensure_ascii=False), encoding="utf-8"
    )
    print(json.dumps({
        "rougeL": evaluation.get("rouge_scores", {}).get("rougeL_f1"),
        "out": str(out),
        "wall_seconds": artifact["wall_seconds"],
    }))


def _devices() -> list[str]:
    import jax

    return [str(d) for d in jax.devices()]


if __name__ == "__main__":
    main()
