"""3B real-weights runbook artifact (VERDICT r2 #3).

Proves the one command the quality gate depends on — HF safetensors →
models/convert.load_hf_checkpoint → TpuBackend — at REAL 3B scale on the
attached chip, without network access to the real weights:

1. random-init Llama-3.2-3B params on the TPU (the exact shapes/dtypes of
   meta-llama/Llama-3.2-3B, models/llama.py LlamaConfig defaults);
2. export them to a sharded HF-format checkpoint on disk
   (models/convert.save_hf_checkpoint — config.json + bf16 safetensors
   shards + model.safetensors.index.json, the layout `save_pretrained`
   produces and the reference consumes at runners/run_summarization.py:54-62);
3. load it back through the production converter, timing the load;
4. assert bit-exact logit parity between the original params and the
   converted checkpoint on a prefill forward;
5. run the int8-quantized engine on the converted weights and record
   decode throughput + HBM in use.

Artifact: artifacts/runbook_3b.json. With the real checkpoint downloaded,
the identical path is:  vnsum-pipeline --backend tpu --weights-dir
/path/to/Llama-3.2-3B --approach mapreduce ...
"""
from __future__ import annotations

import argparse
import gc
import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def hbm_stats() -> dict:
    import jax

    dev = jax.devices()[0]
    stats = dev.memory_stats() or {}
    return {
        "bytes_in_use": stats.get("bytes_in_use"),
        "peak_bytes_in_use": stats.get("peak_bytes_in_use"),
        "bytes_limit": stats.get("bytes_limit"),
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--work", default="/tmp/vnsum_3b_runbook")
    ap.add_argument("--out", default="artifacts/runbook_3b.json")
    ap.add_argument("--batch-size", type=int, default=8)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from vnsum_tpu.core.jax_cache import enable_compilation_cache
    from vnsum_tpu.models import init_params, llama32_3b
    from vnsum_tpu.models.convert import load_hf_checkpoint, save_hf_checkpoint
    from vnsum_tpu.models.llama import (
        forward,
        init_kv_cache,
        prefill_attention_mask,
        prefill_positions,
    )

    enable_compilation_cache()
    rec: dict = {"config": {}, "steps": {}}
    cfg = llama32_3b(max_seq_len=4096)
    rec["config"] = {
        "model": "llama3.2-3b (random init, real shapes)",
        "vocab_size": cfg.vocab_size, "dim": cfg.dim,
        "n_layers": cfg.n_layers, "n_heads": cfg.n_heads,
        "n_kv_heads": cfg.n_kv_heads, "head_dim": cfg.head_dim,
        "intermediate": cfg.intermediate, "dtype": "bfloat16",
    }

    t0 = time.time()
    params0 = jax.jit(lambda k: init_params(k, cfg))(jax.random.key(0))
    jax.block_until_ready(params0)
    n_params = sum(int(np.prod(x.shape)) for x in jax.tree.leaves(params0))
    rec["config"]["n_params"] = n_params
    rec["steps"]["init_seconds"] = round(time.time() - t0, 1)
    print(f"init {n_params/1e9:.2f}B params: {rec['steps']['init_seconds']}s",
          file=sys.stderr)

    # reference logits BEFORE the round trip (B=2 prefill, last position)
    S = 256
    rng = np.random.default_rng(0)
    toks = rng.integers(0, cfg.vocab_size, (2, S), dtype=np.int32)
    pad = np.asarray([0, 40], np.int32)
    toks[1, :40] = 0

    def last_logits(p):
        cache = init_kv_cache(cfg, 2, S)
        out, _ = forward(
            p, cfg, jnp.asarray(toks),
            prefill_positions(jnp.asarray(pad), S), cache, 0,
            prefill_attention_mask(jnp.asarray(pad), S, S), last_only=True,
        )
        return np.asarray(out, np.float32)

    logits0 = last_logits(params0)

    # export to sharded HF format
    export_dir = os.path.join(args.work, "export")
    t0 = time.time()
    index = save_hf_checkpoint(params0, cfg, export_dir, shard_layers=4)
    rec["steps"]["export_seconds"] = round(time.time() - t0, 1)
    rec["steps"]["export_bytes"] = index["metadata"]["total_size"]
    rec["steps"]["export_shards"] = len(set(index["weight_map"].values()))
    print(f"export: {rec['steps']['export_bytes']/1e9:.2f} GB in "
          f"{rec['steps']['export_shards']} shards, "
          f"{rec['steps']['export_seconds']}s", file=sys.stderr)

    # free the original before loading the converted copy (both on one chip
    # would be ~13 GB of bf16 next to compile workspace)
    del params0
    gc.collect()

    t0 = time.time()
    cfg_loaded, params1 = load_hf_checkpoint(export_dir, dtype=jnp.bfloat16)
    jax.block_until_ready(params1)
    rec["steps"]["load_seconds"] = round(time.time() - t0, 1)
    if cfg_loaded.dim != cfg.dim or cfg_loaded.n_layers != cfg.n_layers:
        raise RuntimeError("loaded config mismatch")
    rec["steps"]["hbm_after_load"] = hbm_stats()
    print(f"load_hf_checkpoint: {rec['steps']['load_seconds']}s; "
          f"HBM {rec['steps']['hbm_after_load']}", file=sys.stderr)

    logits1 = last_logits(params1)
    max_abs = float(np.max(np.abs(logits0 - logits1)))
    rec["steps"]["logit_max_abs_diff"] = max_abs
    print(f"logit parity converted vs direct: max|Δ|={max_abs}", file=sys.stderr)
    if max_abs != 0.0:
        raise RuntimeError(f"3B convert round trip not bit-exact: {max_abs}")

    # int8 engine on the converted weights: decode throughput
    from vnsum_tpu.backend.engine import TpuBackend

    be = TpuBackend(
        model_config=cfg_loaded, tokenizer="byte", params=params1,
        batch_size=args.batch_size, max_new_tokens=128, quantize=True,
    )
    del params1
    gc.collect()
    prompt = "Tóm tắt văn bản sau bằng tiếng Việt: " + (
        "Quốc hội thông qua nghị quyết về phát triển kinh tế. " * 18
    )
    be.generate([prompt] * args.batch_size)  # compile + warmup
    t0 = time.time()
    outs = be.generate(
        [prompt + f" ({i})" for i in range(args.batch_size)]
    )
    dt = time.time() - t0
    stats = be.stats
    rec["steps"]["engine"] = {
        "batch_size": args.batch_size,
        "quantize": "int8 weight-only",
        "generate_seconds": round(dt, 2),
        "tokens_per_second_overall": round(stats.tokens_per_second, 1),
        "hbm_after_engine": hbm_stats(),
        "outputs_nonempty": sum(bool(o) for o in outs),
    }
    print(f"engine: {dt:.1f}s for B={args.batch_size}, "
          f"{stats.tokens_per_second:.0f} tok/s overall", file=sys.stderr)

    rec["runbook"] = [
        "download meta-llama/Llama-3.2-3B (config.json + *.safetensors + tokenizer)",
        "vnsum-pipeline --backend tpu --weights-dir /path/to/Llama-3.2-3B "
        "--approach mapreduce --quantize --docs-dir data_1/doc "
        "--summary-dir data_1/summary",
        "quality gate: ROUGE-L ~= 0.3053 "
        "(reference evaluation_results/first_dataset/mapreduce/"
        "llama3_2_3b_results.json)",
    ]
    rec["timestamp"] = time.strftime("%Y-%m-%dT%H:%M:%S")
    out = Path(args.out)
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_text(json.dumps(rec, indent=2))
    print(json.dumps({"ok": True, "artifact": str(out),
                      "logit_max_abs_diff": max_abs,
                      "load_seconds": rec["steps"]["load_seconds"]}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
