"""3B real-weights runbook artifact (VERDICT r2 #3).

Proves the one command the quality gate depends on — HF safetensors →
models/convert.load_hf_checkpoint → TpuBackend — at REAL 3B scale on the
attached chip, without network access to the real weights:

1. write a random-weight Llama-3.2-3B-shaped checkpoint to disk in the real
   HF layout (config.json + sharded bf16 safetensors + index), generated
   host-side shard by shard — exactly the on-disk shape `save_pretrained`
   produces and the reference consumes (runners/run_summarization.py:54-62);
2. load it through the production converter onto the TPU, timing the load
   and recording HBM in use;
3. logit-parity against HF transformers' LlamaForCausalLM running the SAME
   checkpoint on CPU in float32 — and OUR side in float32 too, so the
   comparison is falsifiable (VERDICT r3 weak #1: bf16 vs f32 on random
   weights is the regime where argmax disagreement is maximal and least
   informative). 128+64 positions at two sequence lengths, argmax agreement
   + top-5 overlap, gated at >= 0.99 f32 agreement; the production bf16
   load is then re-measured for context;
4. run the int8-quantized engine on the converted weights and record decode
   throughput.

Artifact: artifacts/runbook_3b.json. With the real checkpoint downloaded,
the identical path is:  vnsum-pipeline --backend tpu --weights-dir
/path/to/Llama-3.2-3B --approach mapreduce ...
"""
from __future__ import annotations

import argparse
import gc
import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def hbm_stats() -> dict:
    import jax

    dev = jax.devices()[0]
    stats = dev.memory_stats() or {}
    return {
        "bytes_in_use": stats.get("bytes_in_use"),
        "peak_bytes_in_use": stats.get("peak_bytes_in_use"),
        "bytes_limit": stats.get("bytes_limit"),
    }


def write_random_hf_checkpoint(out_dir: str, cfg, seed: int = 0) -> dict:
    """Random Llama-shaped HF checkpoint, generated and written shard by
    shard on the host (no device round trip — the device→host path through
    the tunnel moves ~7 MB/s, hours for 6.4 GB)."""
    import ml_dtypes
    import numpy as np
    from safetensors.numpy import save_file

    os.makedirs(out_dir, exist_ok=True)
    D, H, KV, hd, I, V = (
        cfg.dim, cfg.n_heads, cfg.n_kv_heads, cfg.head_dim,
        cfg.intermediate, cfg.vocab_size,
    )
    rng = np.random.default_rng(seed)
    bf16 = ml_dtypes.bfloat16

    def t(shape, scale=0.02):
        return (rng.standard_normal(shape, dtype=np.float32) * scale).astype(bf16)

    hf_cfg = {
        "architectures": ["LlamaForCausalLM"],
        "model_type": "llama",
        "vocab_size": V,
        "hidden_size": D,
        "num_hidden_layers": cfg.n_layers,
        "num_attention_heads": H,
        "num_key_value_heads": KV,
        "head_dim": hd,
        "intermediate_size": I,
        "rope_theta": cfg.rope_theta,
        "rms_norm_eps": cfg.norm_eps,
        "max_position_embeddings": cfg.max_seq_len,
        "tie_word_embeddings": cfg.tie_embeddings,
        "torch_dtype": "bfloat16",
        "rope_scaling": {
            "rope_type": "llama3",
            "factor": cfg.rope_scale_factor,
            "low_freq_factor": cfg.rope_low_freq_factor,
            "high_freq_factor": cfg.rope_high_freq_factor,
            "original_max_position_embeddings": cfg.rope_original_max_len,
        },
    }
    with open(os.path.join(out_dir, "config.json"), "w") as f:
        json.dump(hf_cfg, f, indent=2)

    weight_map: dict[str, str] = {}
    total = 0
    shard_layers = 4
    n_shards = (cfg.n_layers + shard_layers - 1) // shard_layers + 1
    shard_id = 0

    def write(tensors):
        nonlocal shard_id, total
        name = f"model-{shard_id + 1:05d}-of-{n_shards:05d}.safetensors"
        save_file(tensors, os.path.join(out_dir, name))
        for k, v in tensors.items():
            weight_map[k] = name
            total += v.nbytes
        shard_id += 1

    for start in range(0, cfg.n_layers, shard_layers):
        tensors = {}
        for li in range(start, min(start + shard_layers, cfg.n_layers)):
            p = f"model.layers.{li}."
            tensors[p + "self_attn.q_proj.weight"] = t((H * hd, D))
            tensors[p + "self_attn.k_proj.weight"] = t((KV * hd, D))
            tensors[p + "self_attn.v_proj.weight"] = t((KV * hd, D))
            tensors[p + "self_attn.o_proj.weight"] = t((D, H * hd))
            tensors[p + "mlp.gate_proj.weight"] = t((I, D))
            tensors[p + "mlp.up_proj.weight"] = t((I, D))
            tensors[p + "mlp.down_proj.weight"] = t((D, I))
            tensors[p + "input_layernorm.weight"] = np.ones(D, dtype=bf16)
            tensors[p + "post_attention_layernorm.weight"] = np.ones(
                D, dtype=bf16
            )
        write(tensors)
        print(f"  shard {shard_id}/{n_shards} written", file=sys.stderr)

    head = {
        "model.embed_tokens.weight": t((V, D)),
        "model.norm.weight": np.ones(D, dtype=bf16),
    }
    if not cfg.tie_embeddings:
        head["lm_head.weight"] = t((V, D))
    write(head)

    with open(os.path.join(out_dir, "model.safetensors.index.json"), "w") as f:
        json.dump({"metadata": {"total_size": total}, "weight_map": weight_map}, f)
    return {"bytes": total, "shards": shard_id}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--work", default="/tmp/vnsum_3b_runbook")
    ap.add_argument("--out", default="artifacts/runbook_3b.json")
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--oracle-positions", type=int, default=128)
    args = ap.parse_args()

    import numpy as np

    from vnsum_tpu.core.jax_cache import enable_compilation_cache
    from vnsum_tpu.models import llama32_3b

    enable_compilation_cache()
    cfg0 = llama32_3b(max_seq_len=4096)
    rec: dict = {
        "config": {
            "model": "llama3.2-3b shapes (random init)",
            "vocab_size": cfg0.vocab_size, "dim": cfg0.dim,
            "n_layers": cfg0.n_layers, "n_heads": cfg0.n_heads,
            "n_kv_heads": cfg0.n_kv_heads, "head_dim": cfg0.head_dim,
            "intermediate": cfg0.intermediate, "dtype": "bfloat16",
        },
        "steps": {},
    }

    export_dir = os.path.join(args.work, "export")
    t0 = time.time()
    if os.path.exists(os.path.join(export_dir, "model.safetensors.index.json")):
        # resumable: the 6.4 GB checkpoint survives across invocations
        with open(os.path.join(export_dir, "model.safetensors.index.json")) as f:
            idx = json.load(f)
        info = {"bytes": idx["metadata"]["total_size"],
                "shards": len(set(idx["weight_map"].values()))}
        print("checkpoint already on disk; skipping write", file=sys.stderr)
    else:
        info = write_random_hf_checkpoint(export_dir, cfg0)
    rec["steps"]["write_checkpoint_seconds"] = round(time.time() - t0, 1)
    rec["steps"]["checkpoint_bytes"] = info["bytes"]
    rec["steps"]["checkpoint_shards"] = info["shards"]
    print(f"checkpoint: {info['bytes']/1e9:.2f} GB in {info['shards']} shards, "
          f"{rec['steps']['write_checkpoint_seconds']}s", file=sys.stderr)

    # ---- CPU oracle FIRST (needs host RAM, not HBM) ----
    import torch
    import transformers

    S_FULL = args.oracle_positions          # 128 default
    S_SHORT = max(S_FULL // 2, 1)           # second sequence length (64)
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg0.vocab_size, (1, S_FULL), dtype=np.int64)
    # cached INSIDE the checkpoint dir so deleting/regenerating the
    # checkpoint also invalidates the oracle computed from it. A causal
    # decoder's logits at positions < S_SHORT are identical in the S_FULL
    # forward, so ONE oracle forward serves both lengths; our side runs
    # separate S=64 and S=128 programs (different padding/bucket shapes).
    oracle_path = os.path.join(export_dir, f"oracle_logits_{S_FULL}.npy")
    t0 = time.time()
    if os.path.exists(oracle_path):
        oracle = np.load(oracle_path)
        print("oracle logits cached; skipping CPU forward", file=sys.stderr)
    else:
        hf_model = transformers.AutoModelForCausalLM.from_pretrained(
            export_dir, torch_dtype=torch.float32
        ).eval()
        with torch.no_grad():
            oracle = hf_model(torch.from_numpy(tokens)).logits.float().numpy()
        del hf_model
        gc.collect()
        np.save(oracle_path, oracle)
    rec["steps"]["oracle_seconds"] = round(time.time() - t0, 1)
    print(f"HF CPU oracle forward: {rec['steps']['oracle_seconds']}s",
          file=sys.stderr)

    # ---- production converter -> TPU ----
    import jax
    import jax.numpy as jnp

    from vnsum_tpu.models.convert import load_hf_checkpoint
    from vnsum_tpu.models.llama import (
        forward,
        init_kv_cache,
        prefill_attention_mask,
        prefill_positions,
    )

    def our_logits(cfg, params, S):
        toks32 = tokens[:, :S].astype(np.int32)
        pad = np.zeros((1,), np.int32)

        @jax.jit
        def prefill_logits(p, toks):
            cache = init_kv_cache(cfg, 1, S)
            out, _ = forward(
                p, cfg, toks,
                prefill_positions(jnp.asarray(pad), S), cache, 0,
                prefill_attention_mask(jnp.asarray(pad), S, S),
            )
            return out

        return np.asarray(prefill_logits(params, jnp.asarray(toks32)),
                          np.float32)

    def parity_metrics(ours, S):
        ref = oracle[:, :S]
        argmax_agree = float((ours.argmax(-1) == ref.argmax(-1)).mean())
        k = 5
        top_ours = np.argsort(-ours, axis=-1)[..., :k]
        top_ref = np.argsort(-ref, axis=-1)[..., :k]
        overlap = np.mean([
            len(set(top_ours[0, p]) & set(top_ref[0, p])) / k
            for p in range(S)
        ])
        return {
            "positions": S,
            "argmax_agreement": argmax_agree,
            "top5_overlap": float(overlap),
            "logit_max_abs_diff": float(np.max(np.abs(ours - ref))),
        }

    # float32 pass FIRST: same numerics as the oracle, so disagreement is a
    # converter bug, not dtype noise — this is the gated check. It runs on
    # the HOST CPU device: 12.86 GB of f32 weights leave a 16 GB chip no
    # temp headroom (measured OOM), and converter correctness is
    # device-independent — the bf16 pass below covers the chip itself.
    t0 = time.time()
    cpu = jax.devices("cpu")[0]
    with jax.default_device(cpu):
        cfg, params32 = load_hf_checkpoint(export_dir, dtype=jnp.float32)
        jax.block_until_ready(params32)
        rec["steps"]["load_seconds_f32_cpu"] = round(time.time() - t0, 1)
        f32_parities = [
            parity_metrics(our_logits(cfg, params32, S), S)
            for S in (S_SHORT, S_FULL)
        ]
    del params32
    gc.collect()
    rec["steps"]["parity_f32"] = {
        "oracle": "transformers.LlamaForCausalLM (CPU, float32)",
        "engine_dtype": "float32",
        "engine_device": "cpu (f32 3B + temps exceed one 16 GB chip)",
        "per_length": f32_parities,
    }
    worst = min(p["argmax_agreement"] for p in f32_parities)
    print(f"f32 parity: {f32_parities}", file=sys.stderr)
    if worst < 0.99:
        raise RuntimeError(
            f"3B converter f32 parity failed: {rec['steps']['parity_f32']}"
        )

    # production bf16 load: context numbers (argmax flips here are dtype
    # noise quantified against the gated f32 baseline above)
    t0 = time.time()
    cfg, params = load_hf_checkpoint(export_dir, dtype=jnp.bfloat16)
    jax.block_until_ready(params)
    rec["steps"]["load_seconds"] = round(time.time() - t0, 1)
    rec["steps"]["hbm_after_load"] = hbm_stats()
    print(f"load_hf_checkpoint: {rec['steps']['load_seconds']}s; "
          f"HBM {rec['steps']['hbm_after_load']}", file=sys.stderr)
    rec["steps"]["parity_bf16_context"] = {
        "engine_dtype": "bfloat16",
        "per_length": [
            parity_metrics(our_logits(cfg, params, S), S)
            for S in (S_SHORT, S_FULL)
        ],
    }
    print(f"bf16 context: {rec['steps']['parity_bf16_context']}",
          file=sys.stderr)

    # ---- int8 engine throughput on the converted weights ----
    from vnsum_tpu.backend.engine import TpuBackend

    from vnsum_tpu.core.config import GenerationConfig

    be = TpuBackend(
        model_config=cfg, tokenizer="byte", params=params,
        batch_size=args.batch_size, max_new_tokens=128, quantize=True,
    )
    del params
    gc.collect()
    prompt = "Tóm tắt văn bản sau bằng tiếng Việt: " + (
        "Quốc hội thông qua nghị quyết về phát triển kinh tế. " * 18
    )
    # SAMPLED decode: greedy on random weights now stops at the (correctly
    # sampleable) native EOS within a token or two, which would measure
    # prefill only; temperature-1.0 rows run most of the budget with
    # scattered EOS stops — the real decode workload shape
    gen = GenerationConfig(temperature=1.0, seed=7)
    be.generate([prompt] * args.batch_size, config=gen)  # compile + warmup
    g0 = be.stats.generated_tokens
    t0 = time.time()
    outs = be.generate(
        [prompt + f" ({i})" for i in range(args.batch_size)], config=gen
    )
    dt = time.time() - t0
    rec["steps"]["engine"] = {
        "batch_size": args.batch_size,
        "quantize": "int8 weight-only",
        "decode": "sampled T=1.0 (see comment: greedy random-init stops "
                  "at EOS instantly)",
        "generate_seconds": round(dt, 2),
        "generated_tokens": be.stats.generated_tokens - g0,
        "tokens_per_second_overall": round(be.stats.tokens_per_second, 1),
        "hbm_after_engine": hbm_stats(),
        "outputs_nonempty": sum(bool(o) for o in outs),
    }
    print(f"engine: {dt:.1f}s for B={args.batch_size}, "
          f"{be.stats.tokens_per_second:.0f} tok/s overall", file=sys.stderr)

    rec["runbook"] = [
        "download meta-llama/Llama-3.2-3B (config.json + *.safetensors + tokenizer)",
        "vnsum-pipeline --backend tpu --weights-dir /path/to/Llama-3.2-3B "
        "--approach mapreduce --quantize --docs-dir data_1/doc "
        "--summary-dir data_1/summary",
        "quality gate: ROUGE-L ~= 0.3053 "
        "(reference evaluation_results/first_dataset/mapreduce/"
        "llama3_2_3b_results.json)",
    ]
    rec["timestamp"] = time.strftime("%Y-%m-%dT%H:%M:%S")
    out = Path(args.out)
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_text(json.dumps(rec, indent=2))
    print(json.dumps({"ok": True, "artifact": str(out),
                      "f32_argmax_agreement_min": worst,
                      "load_seconds": rec["steps"]["load_seconds"]}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
