#!/usr/bin/env python
"""Fold a fleet incident bundle into one causally-ordered timeline.

An incident bundle (serve/federation.py IncidentManager) is a directory of
per-process evidence: ``manifest.json``, ``router.json`` (the router's
routing-decision flight-recorder ring), and ``worker_<name>.json`` files
(each worker's ring + thread stacks). Every ring event carries ``t_rel``
seconds since ITS process started plus that ring's ``started_wall``
anchor — so each event maps onto wall time using only its own process's
anchors, and the merged timeline is monotone by construction.

Usage::

    python scripts/incident_report.py <bundle_dir>            # human text
    python scripts/incident_report.py <bundle_dir> --json     # machine
    python scripts/incident_report.py <bundle_dir> --limit 50

The heavy lifting (loading + folding) lives in
``vnsum_tpu.serve.federation.fold_incident_bundle`` so the chaos soak's
bundle validator and the tests consume the same code path.
"""
from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from vnsum_tpu.serve.federation import fold_incident_bundle  # noqa: E402


def render_text(report: dict, limit: int | None = None) -> str:
    """The human rendering: header, per-source counts, then one line per
    event — absolute wall stamp, +offset from the first event, source,
    kind, and whatever typed fields the event carried."""
    lines = [
        f"incident  : {report['incident']}",
        f"reason    : {report['reason']}"
        + (f" ({report['detail']})" if report.get("detail") else ""),
        f"captured  : {time.strftime('%Y-%m-%dT%H:%M:%SZ', time.gmtime(report['wall']))}"
        if report.get("wall") else "captured  : ?",
        "sources   : " + ", ".join(
            f"{name}={info.get('events', 0)}ev"
            for name, info in sorted(report["sources"].items())
        ),
        "",
    ]
    events = report["events"]
    shown = events if limit is None else events[-limit:]
    if shown is not events:
        lines.append(f"... {len(events) - len(shown)} earlier event(s) "
                     "elided (--limit)")
    t0 = shown[0]["wall"] if shown else 0.0
    for e in shown:
        extras = " ".join(
            f"{k}={v}" for k, v in e.items()
            if k not in ("wall", "source", "kind", "seq")
        )
        lines.append(
            f"{e['wall']:.6f} +{e['wall'] - t0:8.3f}s "
            f"[{e['source']:>10}] {e['kind']:<16} {extras}".rstrip()
        )
    if not shown:
        lines.append("(no events in any ring)")
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="incident_report")
    p.add_argument("bundle", help="incident bundle directory "
                                  "(<incident-dir>/<incident-id>)")
    p.add_argument("--json", action="store_true",
                   help="emit the folded report as JSON instead of text")
    p.add_argument("--limit", type=int, default=None,
                   help="show only the last N events (text mode)")
    args = p.parse_args(argv)

    bundle = Path(args.bundle)
    if not (bundle / "manifest.json").exists():
        print(f"error: {bundle} has no manifest.json — not an incident "
              "bundle", file=sys.stderr)
        return 2
    report = fold_incident_bundle(bundle)
    try:
        if args.json:
            print(json.dumps(report, ensure_ascii=False, indent=2))
        else:
            print(render_text(report, limit=args.limit))
    except BrokenPipeError:
        # downstream pager/head closed the pipe; not an error
        sys.stderr.close()
        return 0
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
