"""Standalone on-chip run of bench.py's instrumented device-budget phase.

Answers VERDICT r3 weak #2 directly: where do the e2e mapreduce seconds go
at device level (prefill vs decode vs host phases), with MFU and HBM-roofline
context — without paying for the full 4-phase bench. Writes
artifacts/device_budget_r4.json.

Usage:  python scripts/measure_device_budget.py [--docs 4] [--out PATH]
"""
from __future__ import annotations

import argparse
import json
import pathlib
import sys
import tempfile

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--docs", type=int, default=4)
    ap.add_argument(
        "--out", default=str(REPO / "artifacts" / "device_budget_r4.json")
    )
    args = ap.parse_args()

    import bench
    from vnsum_tpu.core.jax_cache import enable_compilation_cache
    from vnsum_tpu.data.synthesize import synthesize_corpus
    from vnsum_tpu.models.fixtures import train_bpe_tokenizer

    enable_compilation_cache()
    root = tempfile.mkdtemp(prefix="vnsum_budget_")
    synthesize_corpus(
        f"{root}/corpus", n_docs=args.docs,
        tokens_per_doc=bench.E2E_WORDS_PER_DOC, summary_tokens=714,
        seed=7, ragged=0.5,
    )
    doc_paths = sorted(pathlib.Path(f"{root}/corpus/doc").glob("*.txt"))
    hf_tok = train_bpe_tokenizer(
        (p.read_text(encoding="utf-8") for p in doc_paths), vocab_size=4096
    )
    hf_tok.save_pretrained(f"{root}/tok")

    out = bench.run_device_budget(None, root, f"hf:{root}/tok", None)
    pathlib.Path(args.out).write_text(json.dumps(out, indent=2))
    print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
