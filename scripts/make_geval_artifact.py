"""G-Eval end-to-end artifact with LOCAL judges (VERDICT r3 #8).

The reference's llm_scores column (evaluate/evaluate_summaries_semantic.py:
203-433: DeepEval correctness/coherence via OpenRouter) was the one eval
column never exercised end-to-end here — this host has no API egress. This
artifact runs the FULL pipeline with include_llm_eval through the Backend-
protocol judge seam (eval/geval.py LLMJudge(backend=...)), twice:

1. scripted-judge pass — a deterministic Backend whose completions are
   realistic judge JSONs: proves correctness/coherence statistics flow
   through SemanticEvaluator into summary_statistics.llm_scores exactly like
   the reference's results files.
2. device-judge pass (constrained) — a real TpuBackend as the judge with
   LLMJudge(constrained=True): the verdict template is forced and the
   engine picks the score digit by next-token logits
   (TpuBackend.score_choices), so every case parses and the engine path
   produces REAL llm_scores (VERDICT r4 missing #4: this arm previously
   succeeded on 0 cases).
3. device-judge pass (free decode) — the same engine free-decoding the
   JSON: an untrained model rarely emits parseable scores; failures must
   be contained per file, never void the run (ref :318-376 semantics).
   Kept as the deliberate-failure containment demonstration.

Writes artifacts/geval_e2e.json.
"""
from __future__ import annotations

import json
import sys
import tempfile
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


def run_pass(root: str, tag: str, judge, n_docs: int) -> dict:
    from vnsum_tpu.core.config import EvalConfig, PipelineConfig
    from vnsum_tpu.pipeline.runner import PipelineRunner, model_name_safe

    cfg = PipelineConfig(
        approach="mapreduce",
        models=["llama3.2-3b"],
        backend="fake",
        docs_dir=f"{root}/c/doc",
        summary_dir=f"{root}/c/summary",
        generated_summaries_dir=f"{root}/gen_{tag}",
        results_dir=f"{root}/results_{tag}",
        logs_dir=f"{root}/logs",
        chunk_size=1200,
        chunk_overlap=50,
        token_max=1000,
        max_new_tokens=128,
        evaluation=EvalConfig(include_llm_eval=True),
    )
    runner = PipelineRunner(cfg, llm_judge=judge)
    results = runner.run()
    stats = results.evaluation["llama3.2-3b"]
    # the on-disk results file must carry the same block (that file is what
    # the reference's schema diff reads)
    on_disk = json.loads(
        (Path(cfg.results_dir) / f"{model_name_safe('llama3.2-3b')}_results.json")
        .read_text()
    )
    assert on_disk["summary_statistics"]["llm_scores"] == stats["llm_scores"]
    return stats["llm_scores"]


def main() -> int:
    from vnsum_tpu.backend.engine import TpuBackend
    from vnsum_tpu.backend.fake import FakeBackend
    from vnsum_tpu.data.synthesize import synthesize_corpus
    from vnsum_tpu.eval import LLMJudge
    from vnsum_tpu.models import tiny_llama

    n_docs = 4
    root = tempfile.mkdtemp(prefix="vnsum_geval_")
    synthesize_corpus(
        f"{root}/c", n_docs=n_docs, tokens_per_doc=400, summary_tokens=60,
        seed=11,
    )

    # pass 1: scripted judge — 2 calls per doc (correctness, coherence)
    scores = ["4", "5", "3", "4", "2", "4", "5", "3"]
    scripted = FakeBackend(
        responses=[
            f'{{"score": {s}, "reason": "đánh giá tự động"}}' for s in scores
        ]
    )
    scripted_scores = run_pass(
        root, "scripted", LLMJudge(backend=scripted), n_docs
    )
    assert scripted_scores["llm_successful_cases"] == n_docs, scripted_scores
    assert scripted_scores["llm_failed_cases"] == 0

    # pass 2: the judge IS the TPU engine, constrained — the device picks
    # the score digit by logits, the host assembles the JSON. Every case
    # must parse: the engine path now PRODUCES scores instead of only
    # containing failures
    judge_engine = TpuBackend(
        model_config=tiny_llama(max_seq_len=2048), tokenizer="byte",
        batch_size=2, max_new_tokens=32,
    )
    constrained_judge = LLMJudge(
        backend=judge_engine, max_new_tokens=32, constrained=True
    )
    constrained_scores = run_pass(
        root, "device_constrained", constrained_judge, n_docs
    )
    assert constrained_scores["llm_successful_cases"] == n_docs, (
        constrained_scores
    )
    assert constrained_scores["llm_failed_cases"] == 0

    # pass 3: same engine, free decode — an untrained model rarely emits
    # parseable JSON; parse failures must be contained per case (the
    # deliberate-failure arm the containment semantics are judged by)
    device_judge = LLMJudge(backend=judge_engine, max_new_tokens=32)
    device_scores = run_pass(root, "device", device_judge, n_docs)
    assert device_scores["llm_total_cases_processed"] == n_docs
    assert (
        device_scores["llm_successful_cases"]
        + device_scores["llm_failed_cases"]
        == n_docs
    )

    rec = {
        "scripted_judge": {
            "what": "deterministic Backend completions -> llm_scores stats",
            "llm_scores": scripted_scores,
        },
        "device_judge": {
            "what": (
                "TpuBackend as judge, constrained choice scoring "
                "(score_choices): the engine path parses REAL scores on "
                "every case"
            ),
            "llm_scores": constrained_scores,
        },
        "device_judge_free_decode": {
            "what": (
                "TpuBackend (tiny random model) free-decoding the verdict: "
                "unparseable scores contained per case — deliberate-failure "
                "containment arm"
            ),
            "llm_scores": device_scores,
        },
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%S"),
    }
    out = REPO / "artifacts" / "geval_e2e.json"
    out.write_text(json.dumps(rec, indent=2))
    print(json.dumps({"ok": True, "out": str(out),
                      "scripted_success": scripted_scores["llm_successful_cases"],
                      "device_constrained_success":
                          constrained_scores["llm_successful_cases"],
                      "device_processed": device_scores["llm_total_cases_processed"]}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
