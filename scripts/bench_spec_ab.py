#!/usr/bin/env python
"""Hermetic A/B bench for reference-guided speculative decoding.

    JAX_PLATFORMS=cpu python scripts/bench_spec_ab.py \
        --out BENCH_spec_r01.json

What it proves (the ISSUE 2 acceptance criteria):

1. **Lossless**: greedy outputs with ``spec_k>0`` are byte-identical to
   plain decode (``spec_k=0``) on every workload, including one whose
   references are garbage;
2. **Profitable on extractive workloads**: on a memorized-corpus
   continuation task — the hermetic stand-in for summarization's
   copy-heavy regime — mean ACCEPTED tokens per verify step > 1.0, i.e.
   each batched verify forward retires strictly more than the one token a
   plain decode step can.

Hermetic setup: a tiny random-init Llama is trained on-device (JAX
trainer, CPU-friendly shapes, ~15 s) to memorize a repetitive Vietnamese
news corpus. Prompted with a corpus prefix it greedily re-emits the
memorized continuation; handing the corpus text to the drafter as the
reference makes that continuation draftable — exactly the overlap
structure map/collapse/refine calls have with their source chunks. The
control arm feeds unrelated references: acceptance collapses to ~0 and
outputs stay identical, demonstrating graceful degradation.
"""
from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np  # noqa: E402

CORPUS_SENTENCES = [
    "Quốc hội đã thông qua nghị quyết về phát triển kinh tế xã hội "
    "trong năm nay với nhiều giải pháp trọng tâm.",
    "Tòa án nhân dân xét xử vụ án theo đúng quy định của pháp luật "
    "và bản án được tuyên sau khi hội đồng nghị án.",
    "Nhà trường tổ chức kỳ thi tốt nghiệp cho học sinh khối mười hai "
    "và kết quả sẽ được công bố trong tuần tới.",
    "Chính phủ sẽ triển khai các giải pháp trọng tâm về an sinh xã hội "
    "cho người dân ở các vùng khó khăn.",
]


def train_fixture(cfg, steps: int, lr: float, seq: int):
    """Memorize the corpus with the JAX trainer; returns (params, losses)."""
    from vnsum_tpu.parallel import make_mesh
    from vnsum_tpu.text.tokenizer import get_tokenizer
    from vnsum_tpu.train import TrainConfig, Trainer

    tok = get_tokenizer("byte")
    ids: list[int] = []
    for s in CORPUS_SENTENCES * 4:
        ids.extend(tok.encode(s + " ", add_bos=False))
    rows = [ids[i : i + seq] for i in range(0, len(ids) - seq, seq // 2)]
    data = np.asarray(rows[:16], np.int32)

    mesh = make_mesh({"data": 1, "model": 1}, platform="cpu")
    tr = Trainer(cfg, mesh, TrainConfig(learning_rate=lr, remat=False))
    first = last = None
    for _ in range(steps):
        loss = float(tr.step(data))
        first = first if first is not None else loss
        last = loss
    return tr.params, {"loss_first": first, "loss_last": last}


def run_arm(backend, prompts, refs, spec_k: int, max_new: int):
    from vnsum_tpu.core.config import GenerationConfig

    st = backend.stats
    base = (st.spec_verify_steps, st.spec_draft_tokens, st.spec_accepted_tokens)
    t0 = time.time()
    outs = backend.generate(
        prompts,
        config=GenerationConfig(spec_k=spec_k),
        references=refs if spec_k else None,
    )
    wall = time.time() - t0
    report = backend.take_spec_report()
    steps = st.spec_verify_steps - base[0]
    drafted = st.spec_draft_tokens - base[1]
    accepted = st.spec_accepted_tokens - base[2]
    emitted = sum(
        len(backend.tok.encode(o, add_bos=False)) for o in outs
    )
    # per-prompt accepted-per-step distribution through the SAME fixed
    # buckets /metrics exports (vnsum_serve_spec_accepted_per_step), so the
    # bench reports bucket-derived p50/p95/p99 instead of a bare mean
    from vnsum_tpu.obs.histogram import ACCEPT_BUCKETS, Histogram

    hist = Histogram(ACCEPT_BUCKETS)
    for r in report:
        if r.verify_steps:
            hist.observe(r.accepted_tokens / r.verify_steps)
    return {
        "spec_k": spec_k,
        "wall_s": round(wall, 3),
        "outputs_preview": [o[:48] for o in outs],
        "emitted_tokens": emitted,
        "verify_steps": steps,
        "draft_tokens": drafted,
        "accepted_tokens": accepted,
        "acceptance_rate": round(accepted / drafted, 4) if drafted else 0.0,
        "accepted_per_step": round(accepted / steps, 4) if steps else 0.0,
        "accepted_per_step_hist": hist.to_dict(),
        "per_prompt": [r.to_dict() for r in report],
    }, outs


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--out", default="BENCH_spec_r01.json")
    p.add_argument("--train-steps", type=int, default=220)
    p.add_argument("--lr", type=float, default=3e-3)
    p.add_argument("--spec-k", type=int, default=8)
    p.add_argument("--max-new", type=int, default=48)
    args = p.parse_args()

    from vnsum_tpu.backend.engine import TpuBackend
    from vnsum_tpu.models import tiny_llama

    cfg = tiny_llama(max_seq_len=512)
    t0 = time.time()
    params, losses = train_fixture(cfg, args.train_steps, args.lr, seq=64)
    train_s = time.time() - t0
    print(f"trained fixture to loss {losses['loss_last']:.3f} in {train_s:.1f}s")

    backend = TpuBackend(
        model_config=cfg, params=params, batch_size=8,
        max_new_tokens=args.max_new, seed=0,
    )

    # extractive workload: continue a memorized sentence from a prefix; the
    # full sentence is the reference (the summarization-overlap stand-in)
    prompts, refs = [], []
    for s in CORPUS_SENTENCES:
        n_chars = len(s) // 3
        prompts.append(s[:n_chars])
        refs.append(s)
    # control references: unrelated text — acceptance should collapse
    ctrl_refs = [CORPUS_SENTENCES[(i + 2) % len(CORPUS_SENTENCES)][::-1]
                 for i in range(len(prompts))]

    plain, outs_plain = run_arm(backend, prompts, refs, 0, args.max_new)
    spec, outs_spec = run_arm(backend, prompts, refs, args.spec_k, args.max_new)
    ctrl, outs_ctrl = run_arm(backend, prompts, ctrl_refs, args.spec_k, args.max_new)

    identical = outs_plain == outs_spec
    identical_ctrl = outs_plain == outs_ctrl
    gate = spec["accepted_per_step"] > 1.0

    result = {
        "bench": "spec_ab",
        "round": 1,
        "setup": {
            "model": "tiny_llama(max_seq_len=512) trained to memorize a "
                     "4-sentence Vietnamese corpus (JAX trainer, CPU)",
            "train": {**losses, "steps": args.train_steps,
                      "seconds": round(train_s, 1)},
            "workload": "continue a memorized sentence from its first third; "
                        "reference = the full sentence (extractive regime)",
            "prompts": len(prompts),
            "max_new_tokens": args.max_new,
            "platform": "cpu-hermetic (step-count evidence, not wall-clock)",
        },
        "arms": {"plain": plain, "spec": spec, "spec_control_bad_refs": ctrl},
        "checks": {
            "greedy_outputs_identical_spec": identical,
            "greedy_outputs_identical_bad_refs": identical_ctrl,
            "accepted_per_step_gt_1": gate,
            "verify_steps_reduced": spec["verify_steps"] < plain["emitted_tokens"],
        },
    }
    Path(args.out).write_text(
        json.dumps(result, indent=2, ensure_ascii=False) + "\n",
        encoding="utf-8",
    )
    print(json.dumps(result["checks"], indent=2))
    print(
        f"spec arm: {spec['accepted_per_step']} accepted/step over "
        f"{spec['verify_steps']} steps (plain: {plain['emitted_tokens']} "
        f"tokens = that many steps); control acceptance "
        f"{ctrl['acceptance_rate']}"
    )
    ok = identical and identical_ctrl and gate
    print("PASS" if ok else "FAIL")
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
