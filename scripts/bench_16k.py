"""One-chip 14-16k-context truncated-path bench (VERDICT r2 #7).

The reference's truncated strategy runs 16,384-token contexts
(run_full_evaluation_pipeline.py:1004-1007: max_context 16384, input cut to
16384-2048); every previously committed on-chip number was S<=8192. This
measures the Pallas flash prefill + int8-KV decode at the S=16384 bucket —
B chosen to fit: 16512-slot int8 KV cache is ~460 MB/row next to ~3.2 GB of
int8 weights.

Writes artifacts/bench_16k.json; PERF.md cites it.
"""
from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=4)
    ap.add_argument("--prompt-tokens", type=int, default=14_300)
    ap.add_argument("--max-new", type=int, default=128)
    ap.add_argument("--rounds", type=int, default=3)
    ap.add_argument("--out", default="artifacts/bench_16k.json")
    args = ap.parse_args()

    from vnsum_tpu.backend.engine import TpuBackend
    from vnsum_tpu.models import llama32_3b

    be = TpuBackend(
        model_config=llama32_3b(max_seq_len=16_512),
        tokenizer="byte",
        batch_size=args.batch_size,
        max_new_tokens=args.max_new,
        quantize=True,
    )
    filler = "Quốc hội đã thông qua nghị quyết về phát triển kinh tế xã hội. "
    base = "Tóm tắt văn bản sau bằng tiếng Việt: "
    reps = (args.prompt_tokens - len(base.encode())) // len(filler.encode())
    prompt = base + filler * reps
    prompts = [
        prompt + f" (tài liệu {i})" for i in range(args.batch_size)
    ]
    n_tok = len(prompt.encode())
    print(f"prompt ~{n_tok} byte tokens, B={args.batch_size}", file=sys.stderr)

    t0 = time.time()
    be.generate(prompts)  # compile + warmup
    warm = time.time() - t0
    print(f"warmup (incl. compile): {warm:.1f}s", file=sys.stderr)

    t0 = time.time()
    rows = 0
    for r in range(args.rounds):
        outs = be.generate([p + f" vòng {r}" for p in prompts])
        rows += len(outs)
    dt = time.time() - t0
    sec_per_row = dt / rows
    rec = {
        "bucket_S": 16_384,
        "prompt_byte_tokens": n_tok,
        "batch_size": args.batch_size,
        "max_new": args.max_new,
        "quantize": "int8 weights + int8 KV",
        "warmup_seconds": round(warm, 1),
        "rounds": args.rounds,
        "rows": rows,
        "seconds": round(dt, 2),
        "seconds_per_doc": round(sec_per_row, 2),
        "docs_per_min": round(60 / sec_per_row, 2),
        # reference truncated path: Law dataset 3.5 s/doc but those docs are
        # ~3.9k tokens; at 14k+ tokens the serial Ollama path has no
        # recorded number — this row fills the gap from our side
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%S"),
    }
    print(json.dumps(rec), file=sys.stderr)
    out = Path(args.out)
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_text(json.dumps(rec, indent=2))
    print(json.dumps({"ok": True, "seconds_per_doc": rec["seconds_per_doc"]}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
