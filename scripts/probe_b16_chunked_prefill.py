"""B=16 decode via chunked prefill — the memory-ceiling experiment.

PERF.md finding 20: at B=8 decode pays 3.2 GB of weight reads per step
regardless of rows; doubling B nearly halves the per-row weight cost, but
B=16 at S=8192 has never fit one v5e chip because WHOLE-PROMPT prefill
transients (q/k/v + MLP intermediates at 16x8192 tokens) blow the budget
next to the 7.8 GB int8 KV cache. prefill_chunk_tokens caps transients at
a chunk's worth (the Pallas prefill kernel's q_offset places each chunk's
queries at their cache slots), so the experiment becomes runnable.

Arms (16 identical ~7.4k-token prompts, e2e engine config, W8A8):
  baseline_b8      — two B=8 whole-prompt dispatches (today's production)
  b16_chunk2048    — one B=16 dispatch, prefill in 4 chunks of 2048
  b16_chunk4096    — one B=16 dispatch, prefill in 2 chunks of 4096 (if
                     2048 fits, try the cheaper chunk count)
  b8_chunk4096     — control: chunking at B=8 (isolates chunk overhead
                     from the batch-size change)

Each arm: compile+warm, then a measured instrumented pass. OOM is a
recorded outcome, not an error. Writes artifacts/b16_chunked_prefill.json.
"""
from __future__ import annotations

import argparse
import gc
import json
import sys
import tempfile
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


def run_arm(label: str, tok_spec, prompts, batch: int, chunk: int,
            gen_cfg) -> dict:
    import jax
    import numpy as np

    import bench
    from vnsum_tpu.backend.engine import EngineStats, TpuBackend

    kw = bench.e2e_engine_kwargs(tok_spec, None)
    kw.update(
        batch_size=batch, prefill_chunk_tokens=chunk,
        max_new_tokens=gen_cfg.max_new_tokens or kw["max_new_tokens"],
    )
    try:
        be = TpuBackend(**kw, instrument=True)
        t0 = time.time()
        be.generate(prompts, config=gen_cfg)
        compile_s = time.time() - t0
        be.stats = EngineStats()
        t1 = time.time()
        be.generate(prompts, config=gen_cfg)
        wall = time.time() - t1
        st = be.stats
        row = {
            "label": label, "B": batch, "chunk": chunk,
            "compile_and_warm_s": round(compile_s, 1),
            "wall_s": round(wall, 2),
            "prefill_s": round(st.phase_seconds.get("prefill", 0.0), 2),
            "decode_s": round(st.phase_seconds.get("decode", 0.0), 3),
            "decode_steps": sum(d["steps"] for d in st.dispatches),
            "dispatches": st.dispatches,
        }
        try:
            # best-effort; NOTE peak_bytes_in_use is the PROCESS-lifetime
            # allocator peak, so later arms inherit earlier arms' peak —
            # fit/no-fit (no OOM) is the per-arm memory signal here
            ms = jax.local_devices()[0].memory_stats() or {}
            for k in ("bytes_in_use", "peak_bytes_in_use"):
                if k in ms:
                    row[k] = int(ms[k])
        except Exception:
            pass
        del be
        gc.collect()
        print(f"{label}: {json.dumps(row)[:360]}", file=sys.stderr)
        return row
    except Exception as e:
        gc.collect()
        row = {"label": label, "B": batch, "chunk": chunk,
               "status": "failed", "error": str(e)[:300]}
        print(f"{label} FAILED: {str(e)[:160]}", file=sys.stderr)
        return row


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="artifacts/b16_chunked_prefill.json")
    ap.add_argument("--max-new", type=int, default=128)
    args = ap.parse_args()

    from vnsum_tpu.core.config import GenerationConfig
    from vnsum_tpu.core.jax_cache import enable_compilation_cache
    from vnsum_tpu.data.synthesize import synthesize_corpus
    from vnsum_tpu.models.fixtures import train_bpe_tokenizer

    enable_compilation_cache()
    root = tempfile.mkdtemp(prefix="vnsum_b16_")
    synthesize_corpus(
        f"{root}/corpus", n_docs=4, tokens_per_doc=9_000,
        summary_tokens=200, seed=7, ragged=0.0,
    )
    doc_paths = sorted(Path(f"{root}/corpus/doc").glob("*.txt"))
    hf_tok = train_bpe_tokenizer(
        (p.read_text(encoding="utf-8") for p in doc_paths), vocab_size=4096
    )
    hf_tok.save_pretrained(f"{root}/tok")
    tok_spec = f"hf:{root}/tok"

    words = " ".join(p.read_text(encoding="utf-8") for p in doc_paths).split()
    prompts = []
    for i in range(16):
        seg = " ".join(words[(i * 2000) % 20000 : (i * 2000) % 20000 + 7400])
        prompts.append(f"Tóm tắt văn bản số {i}: " + seg)

    gen_cfg = GenerationConfig(
        max_new_tokens=args.max_new, temperature=1.0, seed=11
    )
    rows = [
        run_arm("baseline_b8", tok_spec, prompts, 8, 0, gen_cfg),
        run_arm("b8_chunk4096", tok_spec, prompts, 8, 4096, gen_cfg),
        run_arm("b16_chunk2048", tok_spec, prompts, 16, 2048, gen_cfg),
    ]
    if rows[-1].get("status") != "failed":
        rows.append(run_arm("b16_chunk4096", tok_spec, prompts, 16, 4096,
                            gen_cfg))

    rec = {
        "what": "B=16 decode via chunked prefill (16 prompts, e2e config)",
        "arms": rows,
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%S"),
    }
    ok = {r["label"]: r for r in rows if r.get("status") != "failed"}
    if "baseline_b8" in ok:
        base = ok["baseline_b8"]["wall_s"]
        for name, r in ok.items():
            r["speedup_vs_b8"] = round(base / r["wall_s"], 3)
    out = Path(args.out)
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_text(json.dumps(rec, indent=2))
    print(json.dumps({"ok": True, "arms": {
        r["label"]: r.get("speedup_vs_b8") or r.get("status")
        for r in rows
    }}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
