"""Attribute the gemma sweep anomaly (VERDICT r4 weak #4 / next #7).

artifacts/multimodel_sweep.json recorded sweep-gemma3-8l at 50.1 s vs
sweep-llama-8l at 26.9 s on the same 4 docs / 48 chunks — an unexplained
1.9x on the family whose windowed kernels were round 4's centerpiece.

This script reruns the two sweep configs STANDALONE through TpuBackend
with instrument=True at the exact sweep shape (B=4, S-bucket 4096,
max_new=64, byte tokenizer, bf16 weights — what the PipelineRunner built),
and splits wall clock into compile, prefill device time, decode device
time, and host residue, per dispatch. Whatever phase carries the 2x is
the answer; the artifact records it either way.

Writes artifacts/sweep_anomaly_profile.json.
"""
from __future__ import annotations

import argparse
import dataclasses
import gc
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

_FILLER = (
    "Quốc hội đã thông qua nghị quyết về phát triển kinh tế xã hội "
    "trong giai đoạn tới với nhiều nội dung quan trọng. "
)


def profile_model(label: str, cfg, n_prompts: int, prompt_bytes: int,
                  batch_size: int, max_new: int) -> dict:
    from vnsum_tpu.backend.engine import TpuBackend

    be = TpuBackend(
        model_config=cfg, tokenizer="byte", batch_size=batch_size,
        max_new_tokens=max_new, instrument=True,
    )
    body = (_FILLER * (prompt_bytes // len(_FILLER.encode()) + 1)).encode()
    prompts = [
        (f"tài liệu {i}: ".encode() + body)[:prompt_bytes].decode(
            "utf-8", "ignore"
        )
        for i in range(n_prompts)
    ]
    # engaged-path facts the artifact must carry: which attention path each
    # phase actually compiled with at the bucket the prompts actually land in
    from vnsum_tpu.backend.engine import _bucket_len

    n_tok = len(be.tok.encode(prompts[0], add_bos=True))
    S_bucket = _bucket_len(n_tok, cfg.max_seq_len - max_new)
    C = S_bucket + max_new
    use_flash, use_flash_decode = be._decode_settings(S_bucket, C)

    t0 = time.time()
    be.generate(prompts[:batch_size], max_new_tokens=max_new)  # compile+warm
    compile_s = time.time() - t0
    from vnsum_tpu.backend.engine import EngineStats

    be.stats = EngineStats()
    t1 = time.time()
    be.generate(prompts, max_new_tokens=max_new)
    wall = time.time() - t1
    st = be.stats
    pre = st.phase_seconds.get("prefill", 0.0)
    dec = st.phase_seconds.get("decode", 0.0)
    row = {
        "label": label,
        "use_flash": bool(use_flash),
        "use_flash_decode": bool(use_flash_decode),
        "quantize_kv": bool(be.quantize_kv),
        "vocab": cfg.vocab_size,
        "layers": cfg.n_layers,
        "dim": cfg.dim,
        "head_dim": cfg.head_dim,
        "sliding_window": cfg.sliding_window,
        "compile_and_warm_s": round(compile_s, 2),
        "wall_s": round(wall, 2),
        "prefill_s": round(pre, 2),
        "decode_s": round(dec, 2),
        "host_s": round(wall - pre - dec, 2),
        "decode_steps": sum(d["steps"] for d in st.dispatches),
        "dispatches": st.dispatches,
    }
    print(f"{label}: {json.dumps(row)[:400]}", file=sys.stderr)
    del be
    gc.collect()
    return row


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="artifacts/sweep_anomaly_profile.json")
    ap.add_argument("--prompts", type=int, default=48)
    ap.add_argument("--prompt-bytes", type=int, default=3600)
    ap.add_argument("--max-new", type=int, default=64)
    args = ap.parse_args()

    from vnsum_tpu.core.jax_cache import enable_compilation_cache
    from vnsum_tpu.models.llama import gemma3_4b, llama32_3b

    enable_compilation_cache()

    llama_cfg = dataclasses.replace(llama32_3b(max_seq_len=4352), n_layers=8)
    gemma_cfg = dataclasses.replace(
        gemma3_4b(max_seq_len=4352),
        n_layers=8,
        layer_is_global=tuple((i + 1) % 6 == 0 for i in range(8)),
    )
    # a gemma variant with the LLAMA vocab size: if the anomaly follows the
    # 262k vocab (embed/lm_head bytes + argmax width), this arm lands near
    # llama; if it follows the windowed-attention path, it stays near gemma
    gemma_small_vocab = dataclasses.replace(gemma_cfg, vocab_size=128_256)

    rec = {
        "shape": {
            "prompts": args.prompts, "prompt_bytes": args.prompt_bytes,
            "batch_size": 4, "max_new": args.max_new,
        },
        "rows": [
            profile_model("sweep-llama-8l", llama_cfg, args.prompts,
                          args.prompt_bytes, 4, args.max_new),
            profile_model("sweep-gemma3-8l", gemma_cfg, args.prompts,
                          args.prompt_bytes, 4, args.max_new),
            profile_model("gemma3-8l-vocab128k", gemma_small_vocab,
                          args.prompts, args.prompt_bytes, 4, args.max_new),
        ],
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%S"),
    }
    out = Path(args.out)
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_text(json.dumps(rec, indent=2))
    print(json.dumps({"ok": True, "rows": [
        {k: r[k] for k in ("label", "wall_s", "prefill_s", "decode_s")}
        for r in rec["rows"]
    ]}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
