"""Chaos soak: SIGKILL a live serving process at seeded points and prove
the durable-serving ledger invariant.

The write-ahead journal (vnsum_tpu/serve/journal.py) claims at-least-once
acceptance semantics across process death. This harness is the acceptance
test for that claim, end to end and out of process:

1. start ``python -m vnsum_tpu.serve.server --backend fake --journal-dir D``
   as a subprocess (the fake backend carries a device-shaped latency model
   so kills land mid-prefill/mid-decode, not between instantaneous calls);
2. drive mixed closed-loop load (unique deterministic prompts, explicit
   ``request_id``\\ s, a mix of default and seeded-sampling configs);
3. at seeded points (``--seed``), SIGKILL it — ``mid_load`` kills catch
   requests mid-prefill or mid-decode; ``mid_drain`` kills send SIGTERM
   first and SIGKILL a beat into the drain, so the journal dies UNSEALED
   with work in every state;
4. restart on the same journal dir — startup replay re-enqueues every
   unfinished ACCEPT through the supervised path;
5. after the schedule: wait for the ledger to quiesce
   (``GET /metrics`` -> ``vnsum_serve_journal_pending 0``), spot-check the
   reconnect surface (``GET /v1/requests/<id>``), SIGTERM for a graceful
   drain+seal, and assert exit code 0;
6. audit the journal OFFLINE (read-only) and assert:

   - **ledger invariant**: every journaled ACCEPT ended COMPLETE or typed
     FAILED — never lost;
   - **byte-identity**: every COMPLETE's text equals the deterministic
     reference output computed from the same payload in-process (greedy
     replays are byte-identical by the engine's determinism guarantees).

Exit 0 only when every assertion holds. ``--out`` records the run as a
JSON artifact (written atomically, of course).

    python scripts/chaos_soak.py --seed 7 --kills 3 --out CHAOS_soak_r01.json
"""
from __future__ import annotations

import argparse
import json
import os
import re
import shutil
import signal
import sys
import tempfile
import threading
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from vnsum_tpu.backend.fake import FakeBackend  # noqa: E402
from vnsum_tpu.core.artifacts import atomic_write_json  # noqa: E402
from vnsum_tpu.serve.journal import RequestJournal, aggregate_status  # noqa: E402
from vnsum_tpu.testing.chaos import (  # noqa: E402
    KillSchedule,
    RouterProcess,
    ServerProcess,
    free_port,
    http_delete,
    http_json,
    sse_stream,
)

# the load: unique deterministic Vietnamese-shaped prompts; half the
# requests carry a seeded sampling config so replay determinism is proven
# for the journaled-seed path too, not just default greedy
_WORDS = ("văn bản tiếng Việt cần tóm tắt nội dung chính sách kinh tế "
          "xã hội giáo dục y tế môi trường").split()


def make_prompt(cid: int, i: int) -> str:
    body = " ".join(_WORDS[(cid + i + k) % len(_WORDS)] for k in range(60))
    return f"Tài liệu {cid}-{i}: {body}"


def make_payload(cid: int, i: int) -> dict:
    payload = {
        "prompt": make_prompt(cid, i),
        "request_id": f"soak-{cid}-{i}",
    }
    if (cid + i) % 2:
        # journaled-seed arm: temperature 0 keeps the fake backend
        # deterministic while exercising config round-trip through the WAL
        payload.update({"temperature": 0.0, "seed": cid * 1000 + i})
    return payload


def reference_output(payload: dict) -> str:
    """What an uninterrupted run returns for this journaled payload — the
    fake backend is deterministic per payload, so one in-process call is
    the oracle the replayed COMPLETEs must byte-match. The journaled
    GenerationConfig rides along: a WAL round-trip that dropped or mangled
    the config/seed must FAIL this check, not coincide with it."""
    from vnsum_tpu.core.config import GenerationConfig

    cfg = None
    if payload.get("config") is not None:
        c = dict(payload["config"])
        c["eos_ids"] = tuple(c.get("eos_ids") or ())
        cfg = GenerationConfig(**c)
    return FakeBackend().generate(
        [payload.get("prompt", "")],
        max_new_tokens=payload.get("max_new_tokens"),
        config=cfg,
    )[0]


class LoadDriver:
    """Closed-loop clients firing the deterministic payload stream; robust
    to the server dying mid-request (that is the point). With ``qos=True``
    odd clients ride the preemptible batch tenant and even ones the
    interactive tenant (X-Tenant header) — the mix that makes the server
    actually preempt."""

    def __init__(self, port: int, clients: int, per_client: int,
                 qos: bool = False) -> None:
        self.port = port
        self.clients = clients
        self.per_client = per_client
        self.qos = qos
        self.attempted: dict[str, str] = {}  # rid -> prompt
        self.completed: dict[str, str] = {}  # rid -> text (HTTP 200 seen)
        self._lock = threading.Lock()
        self._cursor = [0] * clients
        self._threads: list[threading.Thread] = []
        self._stop = threading.Event()

    def _client(self, cid: int) -> None:
        while not self._stop.is_set():
            i = self._cursor[cid]
            if i >= self.per_client:
                return
            payload = make_payload(cid, i)
            rid = payload["request_id"]
            with self._lock:
                self.attempted[rid] = payload["prompt"]
            headers = None
            if self.qos:
                headers = {
                    "X-Tenant": "batch" if cid % 2 else "interactive"
                }
            try:
                status, body = http_json(
                    "POST", "127.0.0.1", self.port, "/v1/generate",
                    payload, timeout=20.0, headers=headers,
                )
                if status == 200 and body and body.get("completions"):
                    with self._lock:
                        self.completed[rid] = body["completions"][0]["text"]
                    self._cursor[cid] = i + 1
                elif status in (400, 404):
                    self._cursor[cid] = i + 1  # don't spin on a client bug
                else:
                    time.sleep(0.05)  # shed/error: back off, retry same i
            except OSError:
                time.sleep(0.1)  # server is down/being killed: wait it out

    def start(self) -> None:
        self._threads = [
            threading.Thread(target=self._client, args=(cid,), daemon=True)
            for cid in range(self.clients)
        ]
        for t in self._threads:
            t.start()

    @property
    def done(self) -> bool:
        return all(c >= self.per_client for c in self._cursor)

    def stop(self, timeout_s: float = 30.0) -> None:
        self._stop.set()
        t_end = time.monotonic() + timeout_s
        for t in self._threads:
            t.join(timeout=max(t_end - time.monotonic(), 0.1))


def scrape_metric(port: int, name: str) -> int | None:
    """One /metrics scrape -> the integer value of ``name`` (labels
    allowed verbatim, e.g. ``..._total{stage="queued"}``), or None."""
    import http.client

    try:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=5)
        conn.request("GET", "/metrics")
        text = conn.getresponse().read().decode()
        conn.close()
    except OSError:
        return None
    m = re.search(rf"^{re.escape(name)} (\d+)", text, re.M)
    return int(m.group(1)) if m else None


# -- client-churn soak (--churn): cancels/disconnects, no process kills ------


class ChurnDriver:
    """Seeded client churn against a live in-flight server: every request
    draws one behavior — complete normally (plain or streamed), DELETE
    itself mid-flight (instantly = mid-queue-biased, or after a delay =
    mid-slot-biased), or open a stream and drop the socket mid-decode.
    Odd clients ride the preemptible batch tenant, even ones interactive,
    so tier preemption runs underneath the churn the whole time."""

    MODES = ("plain", "stream_full", "cancel_fast", "cancel_slow",
             "stream_abandon")
    WEIGHTS = (0.30, 0.20, 0.15, 0.20, 0.15)

    def __init__(self, port: int, clients: int, per_client: int,
                 seed: int) -> None:
        self.port = port
        self.clients = clients
        self.per_client = per_client
        self.seed = seed
        self._lock = threading.Lock()
        self.attempted: dict[str, str] = {}     # rid -> prompt
        self.completed: dict[str, str] = {}     # rid -> text (client saw it)
        self.churned: set[str] = set()          # rid -> cancelled/abandoned
        self.mode_counts: dict[str, int] = {}
        self.identity_failures: list[str] = []  # streamed deltas != done
        self._threads: list[threading.Thread] = []
        self._stop = threading.Event()

    def _headers(self, cid: int) -> dict:
        return {"X-Tenant": "batch" if cid % 2 else "interactive"}

    def _rid(self, cid: int, i: int) -> str:
        return f"churn-{cid}-{i}"

    def _client(self, cid: int) -> None:
        import random

        rng = random.Random(self.seed * 1000 + cid)
        for i in range(self.per_client):
            if self._stop.is_set():
                return
            mode = rng.choices(self.MODES, weights=self.WEIGHTS)[0]
            rid = self._rid(cid, i)
            payload = {"prompt": make_prompt(cid, i), "request_id": rid}
            if (cid + i) % 2:
                payload.update({"temperature": 0.0,
                                "seed": cid * 1000 + i})
            with self._lock:
                self.attempted[rid] = payload["prompt"]
                self.mode_counts[mode] = self.mode_counts.get(mode, 0) + 1
            try:
                self._one(cid, i, rng, mode, rid, payload)
            except OSError:
                time.sleep(0.1)  # server hiccup: this request is forfeit

    def _one(self, cid, i, rng, mode, rid, payload) -> None:
        headers = self._headers(cid)
        if mode == "plain":
            status, body = http_json(
                "POST", "127.0.0.1", self.port, "/v1/generate",
                payload, timeout=30.0, headers=headers,
            )
            if status == 200 and body and body.get("completions"):
                with self._lock:
                    self.completed[rid] = body["completions"][0]["text"]
        elif mode == "stream_full":
            status, events = sse_stream(
                "127.0.0.1", self.port, "/v1/generate",
                {**payload, "stream": True}, headers=headers,
            )
            if status != 200 or not events or events[-1][0] != "done":
                return
            done = events[-1][1]
            text = done["completions"][0]["text"]
            deltas = "".join(p["text"] for n, p in events if n == "delta")
            if deltas != text:
                with self._lock:
                    self.identity_failures.append(rid)
            with self._lock:
                self.completed[rid] = text
        elif mode in ("cancel_fast", "cancel_slow"):
            # DELETE from a side thread while the POST blocks: fast draws
            # bias mid-queue/mid-prefill, slow draws mid-slot/mid-decode
            delay = (rng.uniform(0.0, 0.02) if mode == "cancel_fast"
                     else rng.uniform(0.06, 0.25))
            with self._lock:
                self.churned.add(rid)

            def cancel_later():
                time.sleep(delay)
                try:
                    http_delete("127.0.0.1", self.port,
                                f"/v1/requests/{rid}")
                except OSError:
                    pass  # lint-allow[swallowed-exception]: the POST side still resolves the request; a lost DELETE just means this draw degraded to a plain request

            t = threading.Thread(target=cancel_later, daemon=True)
            t.start()
            status, body = http_json(
                "POST", "127.0.0.1", self.port, "/v1/generate",
                payload, timeout=30.0, headers=headers,
            )
            t.join(timeout=10)
            if status == 200 and body and body.get("completions"):
                # the cancel lost the completion race — legal; the ledger
                # must then say COMPLETE and byte-match like any survivor
                with self._lock:
                    self.completed[rid] = body["completions"][0]["text"]
        else:  # stream_abandon
            with self._lock:
                self.churned.add(rid)
            sse_stream(
                "127.0.0.1", self.port, "/v1/generate",
                {**payload, "stream": True},
                abandon_after=rng.randint(1, 3), headers=headers,
            )

    def start(self) -> None:
        self._threads = [
            threading.Thread(target=self._client, args=(cid,), daemon=True)
            for cid in range(self.clients)
        ]
        for t in self._threads:
            t.start()

    def join(self, timeout_s: float) -> bool:
        t_end = time.monotonic() + timeout_s
        for t in self._threads:
            t.join(timeout=max(t_end - time.monotonic(), 0.1))
        return not any(t.is_alive() for t in self._threads)

    def stop(self) -> None:
        self._stop.set()


def _churn_stage_probes(port: int) -> dict:
    """Deterministic stage coverage on top of the random churn: pin each
    lifecycle stage with a dedicated scenario so the acceptance assertions
    never depend on a lucky draw. Returns the probe bookkeeping (rids per
    scenario) for the offline audit."""
    long_prompt = " ".join(f"tai lieu dai {k}" for k in range(120))
    probes = {"resident": [], "queued": [], "preempt_cancel": []}

    def submit_bg(rid: str, tenant: str):
        def run():
            try:
                http_json("POST", "127.0.0.1", port, "/v1/generate",
                          {"prompt": long_prompt, "request_id": rid},
                          timeout=30.0, headers={"X-Tenant": tenant})
            except OSError:
                pass  # lint-allow[swallowed-exception]: the server resolves the request either way; the probe audits the LEDGER, not this socket

        t = threading.Thread(target=run, daemon=True)
        t.start()
        return t

    # (a) saturate all 4 slots with batch-tier work, cancel one RESIDENT
    fillers = [submit_bg(f"probe-res-{k}", "batch") for k in range(4)]
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if scrape_metric(port, "vnsum_serve_slots_busy") == 4:
            break
        time.sleep(0.02)
    probes["resident"].append("probe-res-0")
    http_delete("127.0.0.1", port, "/v1/requests/probe-res-0")
    # (b) with slots still saturated, a 5th request must QUEUE — cancel it
    queued_t = submit_bg("probe-q-0", "interactive")
    time.sleep(0.03)
    probes["queued"].append("probe-q-0")
    http_delete("127.0.0.1", port, "/v1/requests/probe-q-0")
    # (c) mid-preemption: an interactive burst evicts the remaining batch
    # residents (the widened eviction->journal gap keeps the window open).
    # Wait for the preemption counter to actually move — a DELETE fired
    # before the eviction would cancel the victim as a plain resident and
    # prove nothing about the preempt->cancel window — then cancel the
    # victims while they sit preempted/requeued
    preempts_before = scrape_metric(
        port, "vnsum_serve_qos_preemptions_total") or 0
    burst = [submit_bg(f"probe-burst-{k}", "interactive") for k in range(6)]
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        n = scrape_metric(port, "vnsum_serve_qos_preemptions_total")
        if n is not None and n > preempts_before:
            break
        time.sleep(0.01)
    for k in range(1, 4):
        rid = f"probe-res-{k}"
        probes["preempt_cancel"].append(rid)
        http_delete("127.0.0.1", port, f"/v1/requests/{rid}")
    for t in fillers + [queued_t] + burst:
        t.join(timeout=30)
    return probes


def churn_soak(args) -> int:
    """Client-churn soak: no process ever dies — the CLIENTS do. Seeded
    cancels and disconnects land mid-queue, mid-stream, mid-slot, and
    mid-preemption against an in-flight, two-tier, journaled server; the
    audit then proves the server reclaimed everything: zero busy slots,
    prefix-cache pins back to baseline, every journaled ACCEPT terminal
    (CANCELLED included), and every COMPLETE byte-identical to the
    deterministic reference."""
    journal_dir = args.journal_dir or tempfile.mkdtemp(prefix="vnsum-churn-")
    own_dir = args.journal_dir is None
    server_args = [
        "--max-batch", "4",
        "--max-wait-ms", "20",
        "--drain-timeout-s", "20",
        "--trace-sample", "0",
        "--inflight", "--slots", "4",
        # fused multi-step decode: cancels, disconnects, and preemptions
        # must land at the COARSER fused-dispatch cadence without leaking
        # a slot or a pin — the audit's reclamation invariants run against
        # the fused loop, not the N=1 special case
        "--fused-segments", "4",
        "--tenants", "interactive:4:0,batch:1:0:batch",
        "--fake-batch-overhead-ms", str(args.fake_batch_overhead_ms),
        "--fake-per-prompt-ms", str(args.fake_per_prompt_ms),
        "--fake-segment-overhead-ms", "30",
        # 2 words/segment -> a 40-word summary spans ~20 segments (~600ms):
        # abandoned streams are still decoding when the idle window fires
        "--fake-segment-words", "2",
        "--stream-heartbeat-s", "0.1",
        "--stream-idle-timeout-s", str(args.stream_idle_timeout_s),
    ]
    server_env = {
        # widen the eviction->PREEMPTED-journal gap so mid-preemption
        # cancels have a real window to land in
        "VNSUM_CHAOS_PREEMPT_GAP_MS": str(args.preempt_gap_ms),
    }
    port = free_port()
    srv = ServerProcess(port, journal_dir=journal_dir,
                        extra_args=server_args, env=server_env)
    srv.start()
    srv.wait_healthy()
    driver = ChurnDriver(port, args.clients, args.per_client, args.seed)
    print(f"churn soak: {args.clients} clients x {args.per_client} "
          f"requests, seed={args.seed}", flush=True)
    counters: dict = {}
    try:
        driver.start()
        if not driver.join(timeout_s=120):
            driver.stop()
            print("FAIL: churn driver never finished")
            return 1
        probes = _churn_stage_probes(port)

        # quiesce: every accepted request terminal, nothing resident
        t_end = time.monotonic() + args.quiesce_timeout_s
        while time.monotonic() < t_end:
            pending = scrape_metric(port, "vnsum_serve_journal_pending")
            busy = scrape_metric(port, "vnsum_serve_slots_busy")
            depth = scrape_metric(port, "vnsum_serve_queue_depth")
            if pending == 0 and busy == 0 and depth == 0:
                break
            time.sleep(0.2)
        for name in (
            "vnsum_serve_journal_pending",
            "vnsum_serve_slots_busy",
            "vnsum_serve_queue_depth",
            "vnsum_serve_cache_pinned_blocks",
            'vnsum_serve_cancel_requests_total{stage="queued"}',
            'vnsum_serve_cancel_requests_total{stage="dispatched"}',
            'vnsum_serve_cancel_requests_total{stage="resident"}',
            "vnsum_serve_cancel_disconnects_total",
            "vnsum_serve_qos_preemptions_total",
            "vnsum_serve_stream_backpressure_coalesced_total",
            "vnsum_serve_stream_heartbeats_total",
            "vnsum_serve_inflight_fused_dispatches_total",
            "vnsum_serve_inflight_segments_total",
        ):
            counters[name] = scrape_metric(port, name)

        srv.sigterm()
        rc = srv.wait_exit(timeout_s=30)
        if rc != 0:
            print(f"FAIL: graceful SIGTERM shutdown exited {rc}, not 0")
            return 1
        srv = None
    finally:
        driver.stop()
        if srv is not None and srv.alive:
            srv.sigkill()

    # -- offline ledger audit (read-only) ---------------------------------
    entries, sealed, torn = RequestJournal.read_state(journal_dir)
    lost = [e.rid for e in entries.values() if not e.terminal]
    completed = [e for e in entries.values() if e.status == "complete"]
    cancelled = [e for e in entries.values() if e.status == "cancelled"]
    mismatches = [
        e.rid for e in completed if e.text != reference_output(e.payload)
    ]
    by_rid = {e.rid: e for e in entries.values()}
    client_vs_ledger = [
        rid for rid, text in driver.completed.items()
        if (e := by_rid.get(rid)) is not None
        and e.status == "complete" and e.text != text
    ]
    # every churned rid must be terminal as cancelled OR complete (losing
    # the completion race is legal; limbo is not)
    churn_unresolved = [
        rid for rid in driver.churned
        if (e := by_rid.get(rid)) is not None
        and e.status not in ("cancelled", "complete")
    ]
    # mid-preemption coverage: at least one cancelled rid whose raw event
    # stream also carries a PREEMPTED record
    raw = b"".join(
        p.read_bytes() for p in sorted(Path(journal_dir).glob("*.jsonl"))
    )
    preempted_rids = {
        m.group(1).decode()
        for m in re.finditer(
            rb'"e":"preempted","rid":"([^"]+)"', raw
        )
    }
    preempt_cancel_overlap = sorted(
        preempted_rids & {e.rid for e in cancelled}
    )

    record = {
        "bench": "chaos_soak_client_churn",
        "seed": args.seed,
        "clients": args.clients,
        "per_client": args.per_client,
        "mode_counts": driver.mode_counts,
        "stage_probes": probes,
        "counters": counters,
        "sealed": sealed,
        "torn_records_dropped": torn,
        "journaled_accepts": len(entries),
        "completed": len(completed),
        "cancelled": len(cancelled),
        "typed_failed": sum(
            1 for e in entries.values() if e.status == "failed"
        ),
        "lost": lost,
        "replay_byte_mismatches": mismatches,
        "client_vs_ledger_mismatches": client_vs_ledger,
        "stream_identity_failures": driver.identity_failures,
        "churned_unresolved": churn_unresolved,
        "preempt_cancel_overlap": preempt_cancel_overlap,
        "client_attempted": len(driver.attempted),
        "client_saw_200": len(driver.completed),
        "client_churned": len(driver.churned),
    }
    print(json.dumps(record, indent=2, ensure_ascii=False))
    if args.out:
        atomic_write_json(args.out, record)
        print(f"wrote {args.out}")
    if own_dir:
        shutil.rmtree(journal_dir, ignore_errors=True)

    ok = (
        not lost
        and not mismatches
        and not client_vs_ledger
        and not driver.identity_failures
        and not churn_unresolved
        and sealed
        and len(entries) > 0
        and len(cancelled) > 0
        # reclamation: nothing resident, no pin leaks at quiesce
        and counters.get("vnsum_serve_slots_busy") == 0
        and counters.get("vnsum_serve_queue_depth") == 0
        and counters.get("vnsum_serve_cache_pinned_blocks") == 0
        # all four lifecycle stages actually exercised
        and (counters.get(
            'vnsum_serve_cancel_requests_total{stage="queued"}') or 0) > 0
        and (counters.get(
            'vnsum_serve_cancel_requests_total{stage="resident"}') or 0) > 0
        and (counters.get("vnsum_serve_cancel_disconnects_total") or 0) > 0
        and (counters.get("vnsum_serve_qos_preemptions_total") or 0) > 0
        and len(preempt_cancel_overlap) > 0
        # the whole soak ran on the FUSED loop: dispatches happened and
        # each host round trip really covered >1 on-device segment
        and (counters.get(
            "vnsum_serve_inflight_fused_dispatches_total") or 0) > 0
        and (counters.get("vnsum_serve_inflight_segments_total") or 0)
        > (counters.get("vnsum_serve_inflight_fused_dispatches_total") or 0)
    )
    print("churn ledger invariant:", "OK" if ok else "VIOLATED")
    return 0 if ok else 1


# -- hang-injection soak (--hang): wedged threads, no exceptions -------------


def hang_soak(args) -> int:
    """Hang-injection soak (ISSUE 15): the process never crashes and no
    exception ever fires — threads simply STOP RETURNING, at seeded points,
    and the watchdog must keep the service live end to end:

    - epoch 1 (``mid_dispatch``): a forever-hang inside a one-shot engine
      dispatch. The watchdog declares it HUNG past its budget, resolves the
      riders typed (clients retry), replaces the scheduler thread, and the
      server keeps serving — graceful SIGTERM must still exit 0.
    - epoch 2 (``mid_slot_loop``): a forever-hang inside an in-flight decode
      segment. Recovery tears the loop down and REQUEUES every resident
      through the journal's replayable ACCEPT — clients see nothing but
      latency; byte-identity holds on the rebuilt loop.
    - epoch 3 (``mid_fused_loop``): the same slot-loop hang, but under
      fused multi-step decode (``--fused-segments 4``). The watchdog's
      budget is N-scaled (``segment_budget(4)``), so the epoch proves two
      things at once: slow-but-legitimate fused dispatches never read as
      HUNG (exactly ONE dispatch stall — the injected hang — and zero
      false positives), and a genuinely wedged fused dispatch still trips
      and recovers with the residents requeued byte-identically.
    - epoch 4 (``mid_fsync``): a forever-hang inside the journal's
      group-commit fsync — the scheduler wedges INSIDE the journal lock,
      where a replacement thread would deadlock too. The watchdog
      classifies it as a lock stall and escalates: supervised
      seal-and-exit with WATCHDOG_EXIT_CODE, the harness restarts (the
      process-manager role), and journal replay restores state.
    - final epoch: no faults; the ledger quiesces and seals.

    Offline audit: every journaled ACCEPT terminal (0 lost), COMPLETEs
    byte-identical to the deterministic reference, watchdog stack dumps on
    disk for BOTH the dispatch and the lock stalls (with the wedged frame —
    the fault plan's hang site — visible in a stack), a flight-recorder
    dump carrying the typed ``stall`` event, and every stall detected
    within its configured bound + ``--detect-slack-s``."""
    from vnsum_tpu.serve.watchdog import WATCHDOG_EXIT_CODE

    journal_dir = args.journal_dir or tempfile.mkdtemp(prefix="vnsum-hang-")
    own_dir = args.journal_dir is None
    flight_dir = str(Path(journal_dir) / "flight")
    common = [
        "--max-batch", "4",
        "--max-wait-ms", "20",
        "--drain-timeout-s", "20",
        "--trace-sample", "0",
        "--fake-batch-overhead-ms", "40",
        "--fake-per-prompt-ms", "2",
        "--flight-dir", flight_dir,
        # tight liveness bounds so the soak runs in seconds: dispatches get
        # a 1s budget (per-token term off for determinism), loop heartbeats
        # a 1s deadline, the monitor ticks at 10Hz
        "--watchdog-interval-s", "0.1",
        "--watchdog-stall-s", "1.0",
        "--watchdog-dispatch-budget-s", "1.0",
        "--watchdog-dispatch-per-token-ms", "0",
    ]
    inflight = [
        "--inflight", "--slots", "4",
        "--fake-segment-overhead-ms", "20",
        "--fake-segment-words", "2",
    ]
    s = args.seed
    epochs = [
        # (name, extra server args, VNSUM_FAULTS, expected stall kind, end)
        ("mid_dispatch", [],
         f"seed={s};fake.dispatch:hang@on_call=4,delay_s=0",
         "dispatch", "sigterm"),
        ("mid_slot_loop", inflight,
         f"seed={s};fake.slot_step:hang@on_call=6,delay_s=0",
         "dispatch", "sigterm"),
        ("mid_fused_loop", inflight + ["--fused-segments", "4"],
         f"seed={s};fake.slot_step:hang@on_call=6,delay_s=0",
         "dispatch", "sigterm"),
        ("mid_fsync", ["--journal-fsync-ms", "0"],
         f"seed={s};journal.fsync:hang@on_call=3,delay_s=0",
         "lock", "escalate"),
    ]
    port = free_port()
    driver = LoadDriver(port, args.clients, args.per_client * 10)
    epoch_counters: list[dict] = []
    escalate_rc: int | None = None
    srv = None

    def scrape_stalls(kind: str):
        return scrape_metric(
            port, f'vnsum_serve_watchdog_stalls_total{{kind="{kind}"}}'
        )

    try:
        driver_started = False
        for name, extra, faults, expect_kind, end in epochs:
            print(f"[epoch {name}] faults={faults}", flush=True)
            srv = ServerProcess(
                port, journal_dir=journal_dir, extra_args=common + extra,
                env={"VNSUM_FAULTS": faults},
            )
            srv.start()
            srv.wait_healthy()
            if not driver_started:
                driver.start()
                driver_started = True
            if end == "sigterm":
                # in-process recovery epoch: wait for the stall verdict AND
                # a completed recovery, settle, then prove the server is
                # still a working server (graceful drain, exit 0)
                deadline = time.monotonic() + 60
                while time.monotonic() < deadline:
                    stalls = scrape_stalls(expect_kind)
                    recoveries = scrape_metric(
                        port, "vnsum_serve_watchdog_recoveries_total"
                    )
                    if (stalls or 0) > 0 and (recoveries or 0) > 0:
                        break
                    time.sleep(0.1)
                else:
                    print(f"FAIL: epoch {name}: no {expect_kind} stall/"
                          "recovery observed")
                    return 1
                time.sleep(1.0)  # let retried/requeued work flow
                epoch_counters.append({
                    "epoch": name,
                    "stalls_dispatch": scrape_stalls("dispatch"),
                    "stalls_lock": scrape_stalls("lock"),
                    "recoveries": scrape_metric(
                        port, "vnsum_serve_watchdog_recoveries_total"),
                    "hung_dispatches": scrape_metric(
                        port, "vnsum_serve_watchdog_hung_dispatches_total"),
                    "fused_dispatches": scrape_metric(
                        port,
                        "vnsum_serve_inflight_fused_dispatches_total"),
                    "segments": scrape_metric(
                        port, "vnsum_serve_inflight_segments_total"),
                })
                srv.sigterm()
                rc = srv.wait_exit(timeout_s=30)
                if rc != 0:
                    print(f"FAIL: epoch {name}: graceful SIGTERM exited "
                          f"{rc}, not 0")
                    return 1
                srv = None
            else:
                # escalation epoch: the wedge is inside the journal lock —
                # the only liveness-preserving exit is seal-and-exit with
                # the watchdog code; the harness is the process manager
                rc = srv.wait_exit(timeout_s=60)
                escalate_rc = rc
                if rc != WATCHDOG_EXIT_CODE:
                    print(f"FAIL: epoch {name}: expected watchdog exit "
                          f"{WATCHDOG_EXIT_CODE}, got {rc}")
                    return 1
                epoch_counters.append({"epoch": name, "exit_code": rc})
                srv = None

        # final epoch: no faults — replay the escalation epoch's unfinished
        # work, quiesce, and seal
        print("[epoch final] no faults: replay + quiesce + seal", flush=True)
        srv = ServerProcess(port, journal_dir=journal_dir,
                            extra_args=common, env={"VNSUM_FAULTS": ""})
        srv.start()
        srv.wait_healthy()
        # the manual twin: SIGUSR1 must write an on-demand stack dump to
        # --flight-dir (audited below alongside the automatic ones)
        import os as _os
        import signal as _signal

        _os.kill(srv.proc.pid, _signal.SIGUSR1)
        driver.stop(timeout_s=30)
        t_end = time.monotonic() + args.quiesce_timeout_s
        while time.monotonic() < t_end:
            if scrape_metric(port, "vnsum_serve_journal_pending") == 0:
                break
            time.sleep(0.2)
        pending = scrape_metric(port, "vnsum_serve_journal_pending")
        if pending != 0:
            print(f"FAIL: journal never quiesced (pending={pending})")
            return 1
        srv.sigterm()
        rc = srv.wait_exit(timeout_s=30)
        if rc != 0:
            print(f"FAIL: final graceful SIGTERM exited {rc}, not 0")
            return 1
        srv = None
    finally:
        driver.stop(timeout_s=5)
        if srv is not None and srv.alive:
            srv.sigkill()

    # -- offline audit (read-only) ----------------------------------------
    entries, sealed, torn = RequestJournal.read_state(journal_dir)
    lost = [e.rid for e in entries.values() if not e.terminal]
    completed = [e for e in entries.values() if e.status == "complete"]
    hung_failed = [e for e in entries.values()
                   if e.status == "failed" and e.reason == "hung"]
    mismatches = [
        e.rid for e in completed if e.text != reference_output(e.payload)
    ]

    # watchdog stack dumps: both classifications on disk, the wedged frame
    # (the fault plan's hang site) visible in a stack, detection latency
    # inside the configured bound
    wd_dumps = sorted(
        p for p in Path(flight_dir).glob("watchdog_*.json")
        if not p.name.startswith("watchdog_sigusr1_")  # audited separately
    )
    dump_kinds: dict[str, int] = {}
    detect_latencies: list[float] = []
    stacks_show_wedge = False
    dumps_well_formed = bool(wd_dumps)
    for p in wd_dumps:
        try:
            d = json.loads(p.read_text())
            stall = d["stall"]
            dump_kinds[stall["kind"]] = dump_kinds.get(stall["kind"], 0) + 1
            detect_latencies.append(
                round(stall["stalled_for_s"] - stall["limit_s"], 3)
            )
            if not d["stacks"]:
                raise ValueError("dump carries no thread stacks")
            if any("faults.py" in ln or "_hang_release" in ln
                   for t in d["stacks"] for ln in t["stack"]):
                stacks_show_wedge = True
        except (KeyError, ValueError):
            dumps_well_formed = False
    # SIGUSR1's manual stack dump (written by the final, healthy epoch)
    sigusr1_dumps = sorted(Path(flight_dir).glob("watchdog_sigusr1_*.json"))
    sigusr1_ok = False
    for p in sigusr1_dumps:
        try:
            d = json.loads(p.read_text())
            sigusr1_ok = bool(d["stacks"])
        except (KeyError, ValueError):
            pass
    # flight-recorder ring dumps carrying the typed stall event
    stall_events = 0
    for p in sorted(Path(flight_dir).glob("flight_*.json")):
        try:
            d = json.loads(p.read_text())
            stall_events += sum(
                1 for e in d.get("events", []) if e.get("kind") == "stall"
            )
        except ValueError:
            dumps_well_formed = False

    fused_epoch = next(
        (c for c in epoch_counters if c.get("epoch") == "mid_fused_loop"),
        None,
    )

    record = {
        "bench": "chaos_soak_hang_injection",
        "seed": args.seed,
        "epochs": epoch_counters,
        "fused_segments": 4,
        "fused_false_hung": (
            (fused_epoch["stalls_dispatch"] or 0) - 1
            if fused_epoch else None
        ),
        "escalation_exit_code": escalate_rc,
        "sealed": sealed,
        "torn_records_dropped": torn,
        "journaled_accepts": len(entries),
        "completed": len(completed),
        "typed_failed_hung": len(hung_failed),
        "typed_failed": sum(
            1 for e in entries.values() if e.status == "failed"
        ),
        "lost": lost,
        "replay_byte_mismatches": mismatches,
        "watchdog_dumps": {
            "files": len(wd_dumps),
            "by_kind": dump_kinds,
            "detect_latencies_s": detect_latencies,
            "stacks_show_wedged_frame": stacks_show_wedge,
            "well_formed": dumps_well_formed,
        },
        "flight_stall_events": stall_events,
        "sigusr1_dump_ok": sigusr1_ok,
        "detect_slack_s": args.detect_slack_s,
        "client_attempted": len(driver.attempted),
        "client_saw_200": len(driver.completed),
    }
    print(json.dumps(record, indent=2, ensure_ascii=False))
    if args.out:
        atomic_write_json(args.out, record)
        print(f"wrote {args.out}")
    if own_dir:
        shutil.rmtree(journal_dir, ignore_errors=True)

    ok = (
        not lost
        and not mismatches
        and sealed
        and len(entries) > 0
        and dumps_well_formed
        # both stall classes actually exercised, stacks on the tape, and
        # the typed stall event in a flight dump
        and dump_kinds.get("dispatch", 0) >= 3  # one per in-process epoch
        and dump_kinds.get("lock", 0) >= 1
        and stacks_show_wedge
        and stall_events > 0
        and sigusr1_ok
        # the escalation epoch exited with the supervised watchdog code
        and escalate_rc == WATCHDOG_EXIT_CODE
        # detection bound: each stall declared within (limit + slack) —
        # the monitor interval is 0.1s, so the slack is host-scheduling
        # headroom, not a loophole
        and all(lat <= args.detect_slack_s for lat in detect_latencies)
        # fused epoch: dispatches actually fused (segments > dispatches),
        # and the ONLY dispatch stall was the injected hang — a fused
        # dispatch that is merely N segments slow must never read as HUNG
        and fused_epoch is not None
        and (fused_epoch["fused_dispatches"] or 0) > 0
        and (fused_epoch["segments"] or 0)
        > (fused_epoch["fused_dispatches"] or 0)
        and fused_epoch["stalls_dispatch"] == 1
        and (fused_epoch["recoveries"] or 0) >= 1
    )
    print("hang-soak liveness invariant:", "OK" if ok else "VIOLATED")
    return 0 if ok else 1


# -- replica-fleet soak (--fleet): worker kills behind the router ------------


def fleet_soak(args) -> int:
    """Kill engine workers behind a live router and prove the FLEET ledger
    invariant: the router journals every admitted request before dispatch,
    so a SIGKILLed worker's unfinished ACCEPTs replay onto survivors —
    0 requests lost, replays byte-identical, and the client never has to
    know. The seeded schedule reuses the single-process kill shapes:
    ``mid_load`` points SIGKILL the busiest worker; the first ``mid_drain``
    point becomes a rolling drain-one-restart-one wave (the deploy path,
    under the same load). Ends with a graceful SIGTERM of the ROUTER
    (exit 0: drain, worker drains, journal seal) and an offline audit of
    the router's journal against the deterministic reference outputs."""
    fleet_dir = args.journal_dir or tempfile.mkdtemp(prefix="vnsum-fleet-")
    own_dir = args.journal_dir is None
    schedule = KillSchedule(args.seed, kills=args.kills,
                            load_window_s=args.load_window_s)
    print(f"fleet kill schedule (seed={args.seed}): "
          f"{json.dumps(schedule.describe())}", flush=True)
    worker_args = (
        "--max-batch 4 --max-wait-ms 20 --drain-timeout-s 20 "
        "--trace-sample 0 "
        f"--fake-batch-overhead-ms {args.fake_batch_overhead_ms} "
        f"--fake-per-prompt-ms {args.fake_per_prompt_ms}"
    )
    port = free_port()
    router = RouterProcess(
        port, fleet_dir=fleet_dir, spawn_workers=args.fleet_workers,
        extra_args=["--probe-interval-ms", "100",
                    "--worker-args", worker_args],
    )
    driver = LoadDriver(port, args.clients, args.per_client)
    kills: list[str] = []
    rolling_waves = 0
    polled = 0
    health: dict = {}

    def fleet_health() -> dict:
        _, payload = http_json("GET", "127.0.0.1", port, "/healthz",
                               timeout=10)
        return payload or {}

    try:
        router.start()
        router.wait_ready(timeout_s=90)
        driver.start()

        for n, point in enumerate(schedule.points, start=1):
            t_point = time.monotonic() + point.delay_s
            while time.monotonic() < t_point:
                time.sleep(0.05)
            if point.kind == "mid_drain":
                # the deploy path under load: drain-one-restart-one
                print(f"[wave {n}] rolling restart under load", flush=True)
                http_json("POST", "127.0.0.1", port,
                          "/admin/rolling-restart", {}, timeout=10)
                rolling_waves += 1
                continue
            live = [w for w in fleet_health().get("workers", [])
                    if w.get("pid") and w.get("up")]
            if not live:
                time.sleep(0.2)
                live = [w for w in fleet_health().get("workers", [])
                        if w.get("pid") and w.get("up")]
            if not live:
                print(f"[kill {n}] skipped: no live worker", flush=True)
                continue
            victim = max(live, key=lambda w: w["inflight"])
            print(f"[kill {n}] SIGKILL {victim['name']} "
                  f"(pid {victim['pid']}, inflight {victim['inflight']}) "
                  "mid-load", flush=True)
            router.kill_worker(victim["name"])
            kills.append(victim["name"])

        # quiesce: load done, rolling wave finished, router ledger drained
        t_end = time.monotonic() + args.quiesce_timeout_s
        while time.monotonic() < t_end:
            pending = scrape_metric(port, "vnsum_serve_journal_pending")
            health = fleet_health()
            if driver.done and pending == 0 and not health.get("rolling"):
                break
            time.sleep(0.2)
        driver.stop()
        health = fleet_health()
        pending = scrape_metric(port, "vnsum_serve_journal_pending")
        if pending != 0:
            print(f"FAIL: router ledger never quiesced (pending={pending})")
            return 1

        # the reconnect surface survives worker deaths: ids a client saw
        # complete poll back terminal off the ROUTER's global ledger
        for rid in list(driver.completed)[:10]:
            status, body = http_json(
                "GET", "127.0.0.1", port, f"/v1/requests/{rid}", timeout=10,
            )
            if status != 200 or body["status"] != "completed":
                print(f"FAIL: poll {rid}: {status} {body}")
                return 1
            polled += 1

        # operator incident: SIGUSR1 to the quiesced router fans out
        # POST /debug/dump to every (respawned) worker — the deterministic
        # bundle the offline validator audits below, on top of whatever
        # failover/markdown incidents the kills themselves minted
        if hasattr(signal, "SIGUSR1"):
            os.kill(router.proc.pid, signal.SIGUSR1)
            t_inc = time.monotonic() + 15.0
            incidents_root = Path(fleet_dir) / "incidents"
            while time.monotonic() < t_inc:
                manifests = list(incidents_root.glob("inc_*/manifest.json"))
                if any(json.loads(m.read_text()).get("reason") == "operator"
                       for m in manifests):
                    break
                time.sleep(0.2)

        # graceful exit: SIGTERM drains the front door, drains every
        # worker (exit 0 each), seals the router journal, exits 0
        router.sigterm()
        rc = router.wait_exit(timeout_s=60)
        if rc != 0:
            print(f"FAIL: graceful router SIGTERM exited {rc}, not 0")
            return 1
    finally:
        if router.alive:
            router.sigkill()
        driver.stop(timeout_s=5)

    # -- offline audit of the ROUTER journal (read-only) -------------------
    entries, sealed, torn = RequestJournal.read_state(
        Path(fleet_dir) / "router"
    )
    lost = [e.rid for e in entries.values() if not e.terminal]
    completed = [e for e in entries.values() if e.status == "complete"]
    failed = [e for e in entries.values() if e.status == "failed"]
    mismatches = [e.rid for e in completed
                  if e.text != reference_output(e.payload)]
    # retry-aware grouping (a shed-then-retried id journals rid, rid#1...):
    # every id a client saw 200 for must aggregate completed AND carry the
    # exact text the client received
    groups: dict[str, list] = {}
    for e in entries.values():
        groups.setdefault(e.rid.split("#")[0], []).append(e)
    client_vs_ledger = []
    for rid, text in driver.completed.items():
        group = groups.get(rid)
        if group is None:
            client_vs_ledger.append(rid)
            continue
        if aggregate_status(group) != "completed" or not any(
            e.status == "complete" and e.text == text for e in group
        ):
            client_vs_ledger.append(rid)

    # -- offline audit of the INCIDENT bundles (read-only) -----------------
    # the correlated-capture invariant: at least one bundle is well-formed
    # (manifest + router ring + >= 2 worker contributions under ONE
    # incident id) and folds into a monotone timeline — the exact artifact
    # an operator would open first after this soak's kills
    from vnsum_tpu.serve.federation import fold_incident_bundle
    from incident_report import render_text

    incident_best: dict | None = None
    incident_bundles = 0
    for manifest_path in sorted(
        (Path(fleet_dir) / "incidents").glob("inc_*/manifest.json")
    ):
        incident_bundles += 1
        bundle = manifest_path.parent
        try:
            report = fold_incident_bundle(bundle)
        except (OSError, ValueError, KeyError) as e:
            print(f"incident bundle {bundle.name}: unreadable ({e})")
            continue
        walls = [e["wall"] for e in report["events"]]
        worker_sources = [s for s in report["sources"] if s != "router"]
        well_formed = (
            report["incident"] == bundle.name
            and report["reason"] in ("slo_fast_burn", "markdown",
                                     "failover", "operator")
            and "router" in report["sources"]
            and len(worker_sources) >= 2
            and report["sources"]["router"]["events"] > 0
            and walls == sorted(walls)
            and bool(walls)
        )
        if well_formed and (
            incident_best is None
            or len(report["events"]) > incident_best["events"]
        ):
            incident_best = {
                "id": report["incident"],
                "reason": report["reason"],
                "sources": {s: i["events"]
                            for s, i in report["sources"].items()},
                "events": len(report["events"]),
                "timeline_monotone": True,
            }
            # the report CLI consumes the same fold — smoke its rendering
            render_text(report, limit=5)

    workers_tbl = health.get("workers", [])
    failovers = sum(w.get("failovers", 0) for w in workers_tbl)
    restarts = sum(w.get("restarts", 0) for w in workers_tbl)
    record = {
        "bench": "chaos_soak_fleet_worker_kill",
        "seed": args.seed,
        "workers": args.fleet_workers,
        "schedule": schedule.describe(),
        "worker_kills": kills,
        "rolling_waves": rolling_waves,
        "worker_failovers": failovers,
        "worker_restarts": restarts,
        "sealed": sealed,
        "torn_records_dropped": torn,
        "journaled_accepts": len(entries),
        "completed": len(completed),
        "typed_failed": len(failed),
        "lost": lost,
        "replay_byte_mismatches": mismatches,
        "client_vs_ledger_mismatches": client_vs_ledger,
        "client_attempted": len(driver.attempted),
        "client_saw_200": len(driver.completed),
        "polled_after_kills": polled,
        "router_sheds": health.get("sheds", {}),
        "incident_bundles": incident_bundles,
        "incident_validated": incident_best,
        "router_incident_counts": health.get("incidents", {}),
    }
    print(json.dumps(record, indent=2, ensure_ascii=False))
    if args.out:
        atomic_write_json(args.out, record)
        print(f"wrote {args.out}")
    if own_dir:
        shutil.rmtree(fleet_dir, ignore_errors=True)

    ok = (
        not lost
        and not mismatches
        and not client_vs_ledger
        and sealed
        and len(entries) > 0
        # the soak must actually exercise the failover machinery: at least
        # one kill landed and at least one journaled request replayed (or
        # retried inline) onto a survivor
        and bool(kills)
        and failovers + restarts > 0
        # correlated incident capture: at least one well-formed bundle —
        # router ring + >= 2 worker contributions under one incident id,
        # folded into a monotone timeline
        and incident_best is not None
    )
    print("fleet ledger invariant:", "OK" if ok else "VIOLATED")
    print(f"kills={len(kills)} rolling_waves={rolling_waves} "
          f"failovers={failovers} restarts={restarts} "
          f"incident_bundles={incident_bundles} "
          f"incident_validated={incident_best['id'] if incident_best else None}")
    return 0 if ok else 1


# -- structured-jobs soak (--gang): SIGKILL mid-map-fan-out ------------------


def make_doc(cid: int, i: int) -> str:
    """A deterministic multi-chunk document: long enough that the mapreduce
    splitter (chunk_size 12000 whitespace tokens) fans it out into several
    map children plus a reduce — the gang shape the kills must land inside
    of. Sizes vary per (cid, i) so fan-out widths differ across the run."""
    nwords = 12600 + 700 * ((cid + i) % 3)
    body = " ".join(_WORDS[(cid + i + k) % len(_WORDS)] for k in range(nwords))
    return f"Tài liệu dài {cid}-{i}.\n\n{body}"


def reference_summary(doc: str) -> str:
    """The offline-barrier oracle for a whole structured job: the BLOCKING
    MapReduceStrategy over a latency-free fake backend, with the server's
    exact approach defaults. The serving path streams the same rounds
    through the gang machinery — across kills and replays the final
    summary a client sees must byte-match this."""
    from vnsum_tpu.core.config import PipelineConfig, approach_defaults
    from vnsum_tpu.strategies import get_strategy

    cfg = PipelineConfig(approach="mapreduce",
                         **approach_defaults("mapreduce"))
    strat = get_strategy("mapreduce", FakeBackend(), cfg)
    return strat.summarize_batch([doc])[0].summary


class GangLoadDriver:
    """Closed-loop summarize clients: each POST fans out server-side into a
    gang of map children plus a reduce, all journaled under one trace id.
    Robust to the server dying mid-fan-out — a client that never saw the
    200 re-POSTs the same document under the same request_id, which rejoins
    the (replay-restored) gang rather than forking a new one."""

    def __init__(self, port: int, clients: int, per_client: int) -> None:
        self.port = port
        self.clients = clients
        self.per_client = per_client
        # docs are big (~13k words); build the deterministic stream once
        self.docs = {
            f"gang-{cid}-{i}": make_doc(cid, i)
            for cid in range(clients) for i in range(per_client)
        }
        self.attempted: dict[str, str] = {}  # rid -> doc
        self.completed: dict[str, str] = {}  # rid -> summary (HTTP 200 seen)
        self.partials: set[str] = set()
        self._lock = threading.Lock()
        self._cursor = [0] * clients
        self._threads: list[threading.Thread] = []
        self._stop = threading.Event()

    def _client(self, cid: int) -> None:
        while not self._stop.is_set():
            i = self._cursor[cid]
            if i >= self.per_client:
                return
            rid = f"gang-{cid}-{i}"
            doc = self.docs[rid]
            with self._lock:
                self.attempted[rid] = doc
            try:
                status, body = http_json(
                    "POST", "127.0.0.1", self.port, "/v1/summarize",
                    {"text": doc, "approach": "mapreduce",
                     "request_id": rid},
                    timeout=60.0,
                )
                if status == 200 and body and body.get("summary"):
                    with self._lock:
                        self.completed[rid] = body["summary"]
                        if body.get("partial"):
                            self.partials.add(rid)
                    self._cursor[cid] = i + 1
                elif status in (400, 404):
                    self._cursor[cid] = i + 1  # don't spin on a client bug
                else:
                    time.sleep(0.05)  # shed/error: back off, retry same i
            except OSError:
                time.sleep(0.1)  # server is down/being killed: wait it out

    def start(self) -> None:
        self._threads = [
            threading.Thread(target=self._client, args=(cid,), daemon=True)
            for cid in range(self.clients)
        ]
        for t in self._threads:
            t.start()

    @property
    def done(self) -> bool:
        return all(c >= self.per_client for c in self._cursor)

    def stop(self, timeout_s: float = 30.0) -> None:
        self._stop.set()
        t_end = time.monotonic() + timeout_s
        for t in self._threads:
            t.join(timeout=max(t_end - time.monotonic(), 0.1))


def gang_soak(args) -> int:
    """Structured-jobs chaos epoch: SIGKILL the server while gangs of
    fanned-out map/reduce children are mid-flight, restart on the same
    journal, and audit that every admitted gang folds to a TERMINAL parent
    aggregate with byte-identical replays and no stranded cache pins.

    Beyond the base ledger invariant this asserts, per gang:

    - a typed GANG record exists and every recorded member is journaled
      and terminal (membership never outlives the ledger);
    - the parent aggregate (``rid`` plus its ``#N`` children folded by
      ``aggregate_status``) is terminal for EVERY admitted gang — completed,
      partial, failed, or cancelled, never stuck mid-lifecycle;
    - every summary a client saw (HTTP 200) byte-matches the OFFLINE
      blocking MapReduceStrategy over the same document — the streaming
      reduce plus kills plus replay changed nothing observable;
    - after quiesce ``vnsum_serve_cache_pinned_blocks`` reads 0: dead
      gangs released every prefix-cache pin their fan-out took."""
    journal_dir = args.journal_dir or tempfile.mkdtemp(prefix="vnsum-gangs-")
    own_dir = args.journal_dir is None
    schedule = KillSchedule(args.seed, kills=args.kills,
                            load_window_s=args.load_window_s, qos=False)
    print(f"gang kill schedule (seed={args.seed}): "
          f"{json.dumps(schedule.describe())}", flush=True)

    server_args = [
        "--max-batch", "4",
        "--max-wait-ms", "20",
        "--drain-timeout-s", "20",
        "--trace-sample", "0",
        "--fake-batch-overhead-ms", str(args.fake_batch_overhead_ms),
        "--fake-per-prompt-ms", str(args.fake_per_prompt_ms),
    ]
    port = free_port()
    driver = GangLoadDriver(port, args.clients, args.per_client)
    restarts = 0
    srv = None
    pinned = None
    gang_admitted_final = None
    try:
        srv = ServerProcess(port, journal_dir=journal_dir,
                            extra_args=server_args)
        srv.start()
        srv.wait_healthy()
        driver.start()

        for n, point in enumerate(schedule.points, start=1):
            t_kill = time.monotonic() + point.delay_s
            while time.monotonic() < t_kill:
                time.sleep(0.05)
            if point.kind == "mid_drain":
                print(f"[kill {n}] SIGTERM, then SIGKILL "
                      f"{point.drain_gap_s}s into the drain", flush=True)
                srv.sigterm()
                time.sleep(point.drain_gap_s)
                srv.sigkill()
            else:
                print(f"[kill {n}] {point.kind}: SIGKILL after "
                      f"{point.delay_s}s of load", flush=True)
                srv.sigkill()
            restarts += 1
            srv = ServerProcess(port, journal_dir=journal_dir,
                                extra_args=server_args)
            srv.start()
            srv.wait_healthy()

        # let the surviving load finish, then wait for the ledger to
        # quiesce — replayed gang children resolve through the same path
        t_end = time.monotonic() + args.quiesce_timeout_s
        while time.monotonic() < t_end:
            pending = scrape_metric(port, "vnsum_serve_journal_pending")
            if driver.done and pending == 0:
                break
            time.sleep(0.2)
        driver.stop()
        pending = scrape_metric(port, "vnsum_serve_journal_pending")
        if pending != 0:
            print(f"FAIL: journal never quiesced (pending={pending})")
            return 1
        # stranded-pin probe: with everything terminal, the prefix cache
        # must hold zero pinned blocks — a gang that died mid-fan-out and
        # left its template-header pins behind shows up RIGHT HERE
        pinned = scrape_metric(port, "vnsum_serve_cache_pinned_blocks")
        gang_admitted_final = scrape_metric(
            port, "vnsum_serve_gang_admitted_total"
        )

        # reconnect surface: completed parents must poll back terminal
        # WITH their per-phase gang progress attached
        polled = 0
        for rid in list(driver.completed)[:6]:
            status, body = http_json(
                "GET", "127.0.0.1", port, f"/v1/requests/{rid}", timeout=10,
            )
            assert status == 200 and body["status"] in (
                "completed", "partial"
            ), f"poll {rid}: {status} {body}"
            gang = body.get("gang")
            assert gang and "map" in gang.get("phases", {}), \
                f"poll {rid}: no gang phase progress in {body}"
            polled += 1

        srv.sigterm()
        rc = srv.wait_exit(timeout_s=30)
        if rc != 0:
            print(f"FAIL: graceful SIGTERM shutdown exited {rc}, not 0")
            return 1
        srv = None
    finally:
        if srv is not None and srv.alive:
            srv.sigkill()
        driver.stop(timeout_s=5)

    # -- offline ledger + gang audit (read-only) ---------------------------
    entries, sealed, torn = RequestJournal.read_state(journal_dir)
    lost = [e.rid for e in entries.values() if not e.terminal]
    completed = [e for e in entries.values() if e.status == "complete"]
    failed = [e for e in entries.values() if e.status == "failed"]
    mismatches = [e.rid for e in completed
                  if e.text != reference_output(e.payload)]

    # parent aggregates: fold each trace's children; every admitted gang
    # must land on a terminal fold, whatever the kills did to it
    groups: dict[str, list] = {}
    for e in entries.values():
        groups.setdefault(e.rid.split("#")[0], []).append(e)
    terminal = {"completed", "partial", "failed", "cancelled"}
    parent_status = {base: aggregate_status(g) for base, g in groups.items()}
    stuck_parents = sorted(
        b for b, s in parent_status.items() if s not in terminal
    )

    # gang membership: every member a GANG record names must be journaled
    # and terminal, and every parent trace must carry a GANG record
    gangs = RequestJournal.read_gangs(journal_dir)
    member_gaps = sorted(
        rid
        for g in gangs.values()
        for rid in g["members"]
        if rid not in entries or not entries[rid].terminal
    )
    unrecorded_parents = sorted(b for b in groups if b not in gangs)

    # end-to-end byte identity: streaming + kills + replay vs the offline
    # blocking strategy, per document a client actually saw complete
    summary_mismatches = [
        rid for rid, text in driver.completed.items()
        if text != reference_summary(driver.docs[rid])
    ]

    record = {
        "bench": "chaos_soak_gang_kill",
        "seed": args.seed,
        "schedule": schedule.describe(),
        "restarts": restarts,
        "sealed": sealed,
        "torn_records_dropped": torn,
        "journaled_accepts": len(entries),
        "completed": len(completed),
        "typed_failed": len(failed),
        "lost": lost,
        "replay_byte_mismatches": mismatches,
        "gangs_recorded": len(gangs),
        "gang_members_recorded": sum(len(g["members"])
                                     for g in gangs.values()),
        "gang_admitted_final_epoch": gang_admitted_final,
        "parent_aggregates": {
            s: sum(1 for v in parent_status.values() if v == s)
            for s in sorted(set(parent_status.values()))
        },
        "stuck_parents": stuck_parents,
        "gang_member_gaps": member_gaps,
        "unrecorded_parents": unrecorded_parents,
        "summary_byte_mismatches": summary_mismatches,
        "client_partials": sorted(driver.partials),
        "cache_pinned_blocks_after_quiesce": pinned,
        "client_attempted": len(driver.attempted),
        "client_saw_200": len(driver.completed),
        "polled_after_restart": polled,
    }
    print(json.dumps(record, indent=2, ensure_ascii=False))
    if args.out:
        atomic_write_json(args.out, record)
        print(f"wrote {args.out}")
    if own_dir:
        shutil.rmtree(journal_dir, ignore_errors=True)

    ok = (
        not lost
        and not mismatches
        and not summary_mismatches
        and not stuck_parents
        and not member_gaps
        and not unrecorded_parents
        and sealed
        and len(entries) > 0
        and len(gangs) > 0
        and pinned == 0
    )
    print("gang ledger invariant:", "OK" if ok else "VIOLATED")
    print(f"gangs={len(gangs)} parents={len(groups)} "
          f"children={len(entries)} pinned_after={pinned}")
    return 0 if ok else 1


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--seed", type=int, default=7)
    p.add_argument("--kills", type=int, default=3)
    p.add_argument("--clients", type=int, default=6)
    p.add_argument("--per-client", type=int, default=8)
    p.add_argument("--journal-dir", default=None,
                   help="journal directory (default: fresh temp dir)")
    p.add_argument("--load-window-s", type=float, default=1.5,
                   help="how long load runs before each seeded kill")
    p.add_argument("--quiesce-timeout-s", type=float, default=60.0)
    p.add_argument("--fake-batch-overhead-ms", type=float, default=80.0)
    p.add_argument("--fake-per-prompt-ms", type=float, default=4.0)
    p.add_argument("--qos", action="store_true",
                   help="multi-tenant QoS soak: in-flight serving with an "
                        "interactive + preemptible-batch tenant mix, a "
                        "widened eviction->PREEMPTED-journal gap "
                        "(VNSUM_CHAOS_PREEMPT_GAP_MS), and a mid_preempt "
                        "kill point — the ledger audit then also proves "
                        "preempted requests reach exactly one terminal "
                        "state after restart replay")
    p.add_argument("--preempt-gap-ms", type=float, default=120.0,
                   help="qos mode: how long the server sleeps between slot "
                        "eviction and the PREEMPTED journal append (the "
                        "window kills must be able to land in)")
    p.add_argument("--churn", action="store_true",
                   help="client-churn soak: no process kills — seeded "
                        "client cancels (DELETE) and stream disconnects "
                        "land mid-queue, mid-stream, mid-slot, and "
                        "mid-preemption against an in-flight two-tier "
                        "server; the audit asserts zero leaked slots, pin "
                        "counts back to baseline, every ACCEPT terminal "
                        "(CANCELLED included), and survivor outputs "
                        "byte-identical")
    p.add_argument("--stream-idle-timeout-s", type=float, default=0.4,
                   help="churn mode: the server's bounded resume window "
                        "(abandoned streams cancel after this)")
    p.add_argument("--hang", action="store_true",
                   help="hang-injection soak (serve/watchdog.py): seeded "
                        "forever-hangs mid-dispatch (one-shot), "
                        "mid-slot-loop (in-flight), and mid-fsync (inside "
                        "the journal lock). Proves liveness end to end: "
                        "hung riders fail typed / residents requeue, the "
                        "lock wedge escalates to a supervised "
                        "seal-and-exit + restart replay, every ACCEPT "
                        "reaches a terminal state, each stall is detected "
                        "within its bound, and stack dumps land on disk")
    p.add_argument("--detect-slack-s", type=float, default=3.0,
                   help="hang mode: allowed detection latency beyond the "
                        "configured budget/deadline (monitor runs at 10Hz; "
                        "this is host-scheduling headroom)")
    p.add_argument("--fleet", action="store_true",
                   help="replica-fleet mode: run a front-door router over "
                        "N spawned engine workers, SIGKILL workers at the "
                        "seeded points (plus one rolling-restart wave), "
                        "and audit the ROUTER's global journal")
    p.add_argument("--fleet-workers", type=int, default=3,
                   help="engine workers behind the router in --fleet mode")
    p.add_argument("--gang", action="store_true",
                   help="structured-jobs mode: drive /v1/summarize fan-outs "
                        "(gangs of map children plus a streaming reduce), "
                        "SIGKILL mid-fan-out, and audit that every admitted "
                        "gang folds to a terminal parent aggregate with "
                        "byte-identical replays and zero stranded cache pins")
    p.add_argument("--out", default=None,
                   help="optional JSON artifact for the run record")
    args = p.parse_args(argv)

    if args.churn:
        return churn_soak(args)
    if args.hang:
        return hang_soak(args)
    if args.fleet:
        return fleet_soak(args)
    if args.gang:
        return gang_soak(args)

    journal_dir = args.journal_dir or tempfile.mkdtemp(prefix="vnsum-chaos-")
    own_dir = args.journal_dir is None
    schedule = KillSchedule(args.seed, kills=args.kills,
                            load_window_s=args.load_window_s, qos=args.qos)
    print(f"kill schedule (seed={args.seed}): "
          f"{json.dumps(schedule.describe())}", flush=True)

    server_args = [
        "--max-batch", "4",
        "--max-wait-ms", "20",
        "--drain-timeout-s", "20",
        "--trace-sample", "0",
        "--fake-batch-overhead-ms", str(args.fake_batch_overhead_ms),
        "--fake-per-prompt-ms", str(args.fake_per_prompt_ms),
    ]
    server_env = None
    flight_dir = None
    if args.qos:
        # in-flight + two tiers + real per-segment latency, so kills and
        # preemptions land mid-decode rather than between instant segments
        server_args += [
            "--inflight", "--slots", "4",
            "--tenants", "interactive:4:0,batch:1:0:batch",
            "--fake-segment-overhead-ms", "30",
        ]
        # flight recorder: every process epoch dumps its typed-event ring
        # on graceful drain (SIGKILLed epochs leave nothing — that is the
        # point of the ring being in-memory); the final SIGTERM's drain
        # dump is the one the audit below holds to account
        flight_dir = str(Path(journal_dir) / "flight")
        server_args += ["--flight-dir", flight_dir]
        server_env = {
            "VNSUM_CHAOS_PREEMPT_GAP_MS": str(args.preempt_gap_ms),
        }
    port = free_port()
    driver = LoadDriver(port, args.clients, args.per_client, qos=args.qos)
    restarts = 0
    # preemption evidence: the counter resets per process, so sample its
    # high-water mark within each process epoch and sum across restarts
    preempts_observed = 0
    epoch_high = 0
    final_epoch_preempts = 0

    def sample_preempts() -> None:
        nonlocal epoch_high
        n = scrape_metric(port, "vnsum_serve_qos_preemptions_total")
        if n is not None:
            epoch_high = max(epoch_high, n)

    srv = None
    try:
        srv = ServerProcess(port, journal_dir=journal_dir,
                            extra_args=server_args, env=server_env)
        srv.start()
        srv.wait_healthy()
        driver.start()

        for n, point in enumerate(schedule.points, start=1):
            t_kill = time.monotonic() + point.delay_s
            while time.monotonic() < t_kill:
                time.sleep(0.05)
                if args.qos:
                    sample_preempts()
            if point.kind == "mid_drain":
                print(f"[kill {n}] SIGTERM, then SIGKILL "
                      f"{point.drain_gap_s}s into the drain", flush=True)
                srv.sigterm()
                time.sleep(point.drain_gap_s)
                srv.sigkill()
            else:
                # mid_load and mid_preempt are both SIGKILL-under-load; in
                # qos mode every preemption holds the widened gap open, so
                # a mid_preempt draw has a real window to land in
                print(f"[kill {n}] {point.kind}: SIGKILL after "
                      f"{point.delay_s}s of load", flush=True)
                srv.sigkill()
            restarts += 1
            preempts_observed += epoch_high
            epoch_high = 0
            srv = ServerProcess(port, journal_dir=journal_dir,
                                extra_args=server_args, env=server_env)
            srv.start()
            srv.wait_healthy()

        # let the remaining load finish, then wait for the ledger to
        # quiesce: pending == 0 means every replayed ACCEPT resolved
        t_end = time.monotonic() + args.quiesce_timeout_s
        while time.monotonic() < t_end:
            pending = scrape_metric(port, "vnsum_serve_journal_pending")
            if args.qos:
                sample_preempts()
            if driver.done and pending == 0:
                break
            time.sleep(0.2)
        driver.stop()
        final_epoch_preempts = epoch_high
        preempts_observed += epoch_high
        pending = scrape_metric(port, "vnsum_serve_journal_pending")
        if pending != 0:
            print(f"FAIL: journal never quiesced (pending={pending})")
            return 1
        # how much crash recovery this run actually exercised (final
        # process only — each restart's replays are its own counter)
        last_replayed = scrape_metric(
            port, "vnsum_serve_journal_replayed_total"
        )

        # reconnect surface: every id a client SAW complete must poll back
        # terminal (spot-check a handful to keep the smoke fast)
        polled = 0
        for rid in list(driver.completed)[:10]:
            status, body = http_json(
                "GET", "127.0.0.1", port, f"/v1/requests/{rid}", timeout=10,
            )
            # the client SAW a 200 for this id, so the poll surface must
            # say completed — even when a replayed duplicate of the same
            # payload failed typed (the retry-aware aggregation)
            assert status == 200 and body["status"] == "completed", \
                f"poll {rid}: {status} {body}"
            polled += 1

        # graceful exit: SIGTERM drains, seals, exits 0 (the satellite)
        srv.sigterm()
        rc = srv.wait_exit(timeout_s=30)
        if rc != 0:
            print(f"FAIL: graceful SIGTERM shutdown exited {rc}, not 0")
            return 1
        srv = None
    finally:
        if srv is not None and srv.alive:
            srv.sigkill()
        driver.stop(timeout_s=5)

    # -- offline ledger audit (read-only: no compaction, no appends) -------
    entries, sealed, torn = RequestJournal.read_state(journal_dir)
    lost = [e.rid for e in entries.values() if not e.terminal]
    completed = [e for e in entries.values() if e.status == "complete"]
    failed = [e for e in entries.values() if e.status == "failed"]
    mismatches = []
    for e in completed:
        if e.text != reference_output(e.payload):
            mismatches.append(e.rid)
    # every text a CLIENT saw (HTTP 200) must match the ledger's COMPLETE
    client_vs_ledger = []
    by_rid = {e.rid: e for e in entries.values()}
    for rid, text in driver.completed.items():
        e = by_rid.get(rid)
        if e is not None and e.status == "complete" and e.text != text:
            client_vs_ledger.append(rid)

    # flight-recorder audit (qos mode): the final graceful SIGTERM dumped
    # the drain ring — assert a WELL-FORMED dump exists (reason + typed
    # events with monotone seqs and the serving lifecycle in them), and
    # that the preemption lifecycle is on the tape whenever the final
    # process epoch actually preempted (earlier epochs die by SIGKILL —
    # their in-memory rings are exactly what a black box cannot keep)
    flight_ok = True
    flight_summary: dict = {}
    if args.qos:
        dump_paths = sorted(Path(flight_dir).glob("flight_*.json"))
        events: list[dict] = []
        well_formed = bool(dump_paths)
        for p in dump_paths:
            try:
                d = json.loads(p.read_text())
                # explicit raises, not asserts: the audit must survive -O
                if not (d["reason"] and isinstance(d["events"], list)):
                    raise ValueError("missing reason / events list")
                seqs = [e["seq"] for e in d["events"]]
                if seqs != sorted(seqs):
                    raise ValueError("event seqs not monotone")
                if not all("kind" in e and "t_rel" in e
                           for e in d["events"]):
                    raise ValueError("untyped event on the tape")
                events.extend(d["events"])
            # lint-allow[swallowed-exception]: a malformed dump fails the audit via flight_ok below — recording the verdict IS the handling
            except (KeyError, ValueError):
                well_formed = False
        kinds = {e["kind"] for e in events}
        preempt_events = sum(1 for e in events if e["kind"] == "preempt")
        flight_ok = (
            well_formed
            and {"admit", "dispatch"} <= kinds
            and (final_epoch_preempts == 0 or preempt_events > 0)
        )
        flight_summary = {
            "dumps": len(dump_paths),
            "events": len(events),
            "event_kinds": sorted(kinds),
            "preempt_events": preempt_events,
            "final_epoch_preemptions": final_epoch_preempts,
            "well_formed": well_formed,
        }

    record = {
        "bench": "chaos_soak_process_kill",
        "seed": args.seed,
        "qos": args.qos,
        "flight_recorder": flight_summary,
        "preemptions_observed": preempts_observed,
        "schedule": schedule.describe(),
        "restarts": restarts,
        "last_restart_replayed": last_replayed,
        "sealed": sealed,
        "torn_records_dropped": torn,
        "journaled_accepts": len(entries),
        "completed": len(completed),
        "typed_failed": len(failed),
        "lost": lost,
        "replay_byte_mismatches": mismatches,
        "client_vs_ledger_mismatches": client_vs_ledger,
        "client_attempted": len(driver.attempted),
        "client_saw_200": len(driver.completed),
        "polled_after_restart": polled,
    }
    print(json.dumps(record, indent=2, ensure_ascii=False))
    if args.out:
        atomic_write_json(args.out, record)
        print(f"wrote {args.out}")
    if own_dir:
        shutil.rmtree(journal_dir, ignore_errors=True)

    ok = (
        not lost
        and not mismatches
        and not client_vs_ledger
        and sealed
        and len(entries) > 0
        # qos mode must actually exercise the preemption path: a soak
        # that never preempted proved nothing about the mid-preempt
        # kill window
        and (not args.qos or preempts_observed > 0)
        # ...and must leave a well-formed flight-recorder dump behind
        and flight_ok
    )
    print("ledger invariant:", "OK" if ok else "VIOLATED")
    if args.qos:
        print(f"preemptions observed across processes: {preempts_observed}")
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
