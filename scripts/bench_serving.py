"""Serving benchmark: micro-batching server vs the serial demo-server shape.

Hermetic by construction — both servers run the FakeBackend with the same
latency model (a fixed per-dispatch cost plus a small per-prompt marginal
cost, the economics of a real device batch), so the measured difference is
pure scheduling: one-request-at-a-time behind a lock (how demo/server.py
worked before the serve rebase, and how the reference's Ollama loop behaves)
vs coalesced engine batches through vnsum_tpu.serve.

Two load shapes:
- closed loop: N concurrent clients with persistent connections, each
  issuing back-to-back requests — the "16 concurrent users" acceptance
  shape. Reports p50/p95/p99 latency and GOODPUT (requests completed within
  their deadline per second).
- overload: a worker pool several times the engine's concurrency sends
  back-to-back requests with a TIGHT deadline against a bounded queue —
  admission control and deadline shedding answer with typed 429s instead of
  letting latency grow without bound, and the shed counters land in
  /metrics.

    python scripts/bench_serving.py --out BENCH_serving_r01.json

The latency model (40 ms/dispatch + 3 ms/prompt) is the measured shape of
the one-chip engine at summary lengths scaled down ~10x so the bench runs
in seconds; the RATIO between serial and batched serving is what the number
means, not the absolute latencies.
"""
from __future__ import annotations

import argparse
import http.client
import json
import logging
import sys
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from vnsum_tpu.backend.fake import FakeBackend  # noqa: E402
from vnsum_tpu.core.artifacts import atomic_write_json  # noqa: E402
from vnsum_tpu.serve.server import ServeState, make_server  # noqa: E402

PROMPT = "Tóm tắt văn bản sau: nội dung tiếng Việt có dấu thanh. " * 8


# -- the pre-serve baseline: one request at a time behind a lock -------------


def make_serial_server(backend: FakeBackend) -> ThreadingHTTPServer:
    """The demo server's pre-rebase shape (and the reference's serial Ollama
    loop): every request takes a global lock around backend.generate, so
    concurrent clients queue behind each other, one dispatch per request."""
    lock = threading.Lock()

    class Server(ThreadingHTTPServer):
        request_queue_size = 128  # match the serve server's listen backlog
        daemon_threads = True

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"  # same keep-alive as the serve server

        def do_POST(self):  # noqa: N802
            length = int(self.headers.get("Content-Length", "0"))
            req = json.loads(self.rfile.read(length) or b"{}")
            with lock:
                outs = backend.generate([req["prompt"]])
            body = json.dumps({"completions": [{"text": outs[0]}]}).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    return Server(("127.0.0.1", 0), Handler)


# -- persistent-connection client -------------------------------------------


class Client:
    """One keep-alive connection; reconnects transparently. A fresh TCP
    handshake per request would make the load generator the bottleneck and
    measure socket churn instead of scheduling."""

    def __init__(self, base: str) -> None:
        u = urllib.parse.urlparse(base)
        self.host, self.port = u.hostname, u.port
        self.conn: http.client.HTTPConnection | None = None

    def connect(self) -> None:
        """Establish the connection eagerly (before a start barrier), so the
        measured window contains requests, not a TCP connect herd."""
        if self.conn is None:
            self.conn = http.client.HTTPConnection(
                self.host, self.port, timeout=60
            )
            self.conn.connect()

    def post(self, path: str, payload: dict,
             headers: dict | None = None) -> tuple[int, bytes]:
        """Returns (status, raw body). The body is NOT parsed here: the load
        shapes only branch on status, and json.loads on every response is
        measurable GIL work that competes with the server under test on a
        small host (the QoS phase parses selectively, off the hot loop).
        ``headers`` adds request headers (the QoS phase's X-Tenant)."""
        body = json.dumps(payload)
        for attempt in (0, 1):
            if self.conn is None:
                self.conn = http.client.HTTPConnection(
                    self.host, self.port, timeout=60
                )
            try:
                self.conn.request(
                    "POST", path, body=body,
                    headers={"Content-Type": "application/json",
                             **(headers or {})},
                )
                resp = self.conn.getresponse()
                data = resp.read()  # must drain for keep-alive reuse
                return resp.status, data
            except (http.client.HTTPException, OSError):
                self.conn.close()
                self.conn = None
                if attempt:
                    raise
        raise RuntimeError("unreachable")

    def close(self) -> None:
        if self.conn is not None:
            self.conn.close()


# -- load shapes -------------------------------------------------------------


def _percentiles(latencies: list[float]) -> dict:
    latencies = sorted(latencies)

    def pct(p):
        if not latencies:
            return 0.0
        return latencies[min(int(len(latencies) * p), len(latencies) - 1)]

    return {
        "p50_s": round(pct(0.50), 4),
        "p95_s": round(pct(0.95), 4),
        "p99_s": round(pct(0.99), 4),
    }


def closed_loop(base: str, clients: int, per_client: int,
                deadline_s: float, payload_fn=None) -> dict:
    """N clients, each firing back-to-back requests; a request is GOOD when
    it completes (HTTP 200) within deadline_s of its submission.
    ``payload_fn(client_id, i)`` overrides the request body (the
    shared-prefix arm varies prompts per request and rides a cache_hint)."""
    latencies: list[float] = []
    good = bad = shed = errors = 0
    lock = threading.Lock()
    barrier = threading.Barrier(clients + 1)
    if payload_fn is None:
        def payload_fn(cid, i):
            return {"prompt": PROMPT, "deadline_ms": deadline_s * 1000}

    def client_fn(cid):
        nonlocal good, bad, shed, errors
        c = Client(base)
        c.connect()
        barrier.wait()
        for i in range(per_client):
            t0 = time.monotonic()
            try:
                status, _ = c.post("/v1/generate", payload_fn(cid, i))
                dt = time.monotonic() - t0
                with lock:
                    if status == 200:
                        latencies.append(dt)
                        if dt <= deadline_s:
                            good += 1
                        else:
                            bad += 1
                    elif status == 429:
                        shed += 1
                    else:
                        errors += 1
            except Exception:
                with lock:
                    errors += 1
        c.close()

    threads = [
        threading.Thread(target=client_fn, args=(cid,))
        for cid in range(clients)
    ]
    for t in threads:
        t.start()
    barrier.wait()
    t0 = time.monotonic()
    for t in threads:
        t.join()
    wall = time.monotonic() - t0
    total = clients * per_client
    return {
        "clients": clients,
        "requests": total,
        "wall_s": round(wall, 3),
        "throughput_rps": round((good + bad) / wall, 2) if wall else 0.0,
        "goodput_rps": round(good / wall, 2) if wall else 0.0,
        "good": good,
        "deadline_missed": bad,
        "shed": shed,
        "errors": errors,
        **_percentiles(latencies),
    }


def overload_loop(base: str, workers: int, duration_s: float,
                  deadline_s: float) -> dict:
    """Open-style overload: a worker pool far above engine concurrency fires
    back-to-back with a deadline tighter than the queueing it would take to
    serve everyone — the bounded queue and deadline shedding must convert
    the excess into typed 429s rather than unbounded latency."""
    latencies: list[float] = []
    counts = {"good": 0, "late": 0, "shed": 0, "errors": 0}
    lock = threading.Lock()
    barrier = threading.Barrier(workers + 1)
    t_end = [0.0]

    def worker():
        c = Client(base)
        c.connect()
        barrier.wait()
        while time.monotonic() < t_end[0]:
            t0 = time.monotonic()
            try:
                status, _ = c.post(
                    "/v1/generate",
                    {"prompt": PROMPT, "deadline_ms": deadline_s * 1000},
                )
                dt = time.monotonic() - t0
                with lock:
                    if status == 200:
                        latencies.append(dt)
                        counts["good" if dt <= deadline_s else "late"] += 1
                    elif status == 429:
                        counts["shed"] += 1
                    else:
                        counts["errors"] += 1
            except Exception:
                with lock:
                    counts["errors"] += 1
        c.close()

    threads = [threading.Thread(target=worker) for _ in range(workers)]
    for t in threads:
        t.start()
    t_end[0] = time.monotonic() + duration_s
    barrier.wait()
    for t in threads:
        t.join()
    submitted = sum(counts.values())
    return {
        "workers": workers,
        "duration_s": duration_s,
        "deadline_s": deadline_s,
        "submitted": submitted,
        **counts,
        **_percentiles(latencies),
    }


def shared_prefix_phase(args) -> dict:
    """Prefix-cache A/B under live serving traffic (vnsum_tpu.cache):
    identical load against two servers whose FakeBackend charges
    ``per_token_s`` per UNCACHED prompt token — the hermetic stand-in for
    prefill compute. Every request shares one long Vietnamese preamble
    (sent as its cache_hint) with a unique tail; with the synthetic radix
    cache on, only the tail bills, so anchored TTFT and goodput improve by
    exactly the mechanism the real engine's resume-prefill exploits.
    Tracing is ON in both arms (TTFT needs the prefill anchor)."""
    shared = ("Bạn là một chuyên gia tóm tắt nội dung các văn bản tiếng "
              "Việt dài và phức tạp. " * 24)
    deadline_s = args.deadline_s

    def payload(cid, i):
        return {
            "prompt": shared + f"Tài liệu {cid}-{i}: " + "nội dung riêng " * 8,
            "cache_hint": shared,
            "deadline_ms": deadline_s * 1000,
        }

    arms = {}
    for name, blocks in (("cache_off", 0), ("cache_on", 4096)):
        backend = FakeBackend(
            batch_overhead_s=0.02,
            per_prompt_s=0.002,
            per_token_s=args.per_token_s,
            prefix_cache_blocks=blocks,
            cache_block_tokens=16,
        )
        state = ServeState(
            backend,
            max_batch=args.max_batch,
            max_wait_s=args.max_wait_ms / 1000.0,
            max_queue_depth=64,
            trace_sample=1.0,
            trace_ring=64,
        )
        server = make_server(state, "127.0.0.1", 0)
        threading.Thread(target=server.serve_forever, daemon=True).start()
        base = f"http://127.0.0.1:{server.server_address[1]}"
        loop = closed_loop(
            base, 8, max(args.per_client // 2, 5), deadline_s, payload
        )
        server.shutdown()
        server.server_close()
        hists = state.scheduler.metrics.histograms_snapshot()
        snap = state.scheduler.metrics.snapshot()
        state.close()
        arms[name] = {
            **loop,
            "ttft_p50_s": hists["ttft_seconds"]["p50"],
            "ttft_p95_s": hists["ttft_seconds"]["p95"],
            "cache_hit_tokens": snap.cache_hit_tokens,
            "cache_hit_rate": round(snap.cache_hit_rate, 4),
            "cache_stats": backend.prefix_cache_stats(),
        }
    on, off = arms["cache_on"], arms["cache_off"]
    return {
        "workload": "8 clients, shared 24-rep preamble + unique tails, "
                    "cache_hint = the preamble; per_token_s charges "
                    "uncached prompt tokens only",
        "per_token_s": args.per_token_s,
        **arms,
        "ttft_p50_improvement_pct": (
            round((off["ttft_p50_s"] - on["ttft_p50_s"])
                  / off["ttft_p50_s"] * 100.0, 1)
            if off["ttft_p50_s"] else 0.0
        ),
        "goodput_ratio": (
            round(on["goodput_rps"] / off["goodput_rps"], 2)
            if off["goodput_rps"] else float("inf")
        ),
    }


def inflight_phase(args) -> dict:
    """In-flight batching A/B (ISSUE 8 tentpole): identical closed-loop load
    against the PR 1 batch-dispatch scheduler and the slot-feeding in-flight
    scheduler, tracing ON in both (the batch arm needs the prefill anchor
    for TTFT; the in-flight arm anchors at each joiner's own prefill
    regardless).

    Latency model — SYMMETRIC per-step decode: both arms charge
    ``per_step_s`` per decode step, but a one-shot batch decodes until its
    LONGEST row finishes (every rider pays the convoy) while the slot loop
    pays only for the steps its segments actually run and refills freed
    slots mid-flight. Overheads are calibrated so the in-flight arm is
    slightly HEAVIER per unit of useful work at full occupancy (each admit
    group bills its own prefill dispatch; each segment bills a dispatch),
    so the measured gains are pure scheduling — the refill mechanism — not
    a cheaper cost model.

    Workload: 1:1 mix of short (8-word) and long (40-word) summaries, the
    ragged regime PERF.md finding 13 showed segmented decode LOSES offline
    — refill is what flips it online."""
    deadline_s = args.deadline_s
    arms = {}
    # batch arm: prefill 0.05 + 2 ms/row dispatch overheads, then the
    # convoy: per_step_s x the longest row's output
    # in-flight arm: 10 ms admit prefill per JOIN GROUP (paid much more
    # often than the batch arm's per-batch prefill), 2 ms dispatch per
    # segment, the same per_step_s for the steps a segment runs
    specs = {
        "batch_dispatch": dict(
            backend=dict(batch_overhead_s=0.05, per_prompt_s=0.002,
                         per_step_s=args.per_step_s),
            state=dict(),
        ),
        "inflight": dict(
            backend=dict(
                batch_overhead_s=args.inflight_prefill_s,
                per_step_s=args.per_step_s,
                segment_words=args.segment_words,
                segment_overhead_s=args.segment_overhead_s,
                per_slot_segment_s=args.per_slot_segment_s,
            ),
            state=dict(inflight=True, slots=args.max_batch),
        ),
    }
    short = "tin ngan gon sau day chi tam tu"                    # 8 words
    long_ = "phan tich chuyen sau ve tinh hinh kinh te xa hoi " * 6  # 54
    def payload(cid, i):
        return {
            "prompt": short if (cid + i) % 2 else long_,
            "deadline_ms": deadline_s * 1000,
        }

    for name, spec in specs.items():
        backend = FakeBackend(**spec["backend"])
        state = ServeState(
            backend,
            max_batch=args.max_batch,
            max_wait_s=args.max_wait_ms / 1000.0,
            max_queue_depth=64,
            trace_sample=1.0,
            trace_ring=64,
            **spec["state"],
        )
        server = make_server(state, "127.0.0.1", 0)
        threading.Thread(target=server.serve_forever, daemon=True).start()
        base = f"http://127.0.0.1:{server.server_address[1]}"
        loop = closed_loop(
            base, args.clients, args.per_client, deadline_s, payload
        )
        server.shutdown()
        server.server_close()
        hists = state.scheduler.metrics.histograms_snapshot()
        snap = state.scheduler.metrics.snapshot()
        state.close()
        arms[name] = {
            **loop,
            "ttft_p50_s": hists["ttft_seconds"]["p50"],
            "ttft_p99_s": hists["ttft_seconds"]["p99"],
            "e2e_p50_s": hists["e2e_seconds"]["p50"],
            "segments": snap.segments,
            "refills": snap.refills,
            "engine_seconds": round(snap.engine_seconds, 3),
            "avg_batch_occupancy": round(snap.avg_batch_occupancy, 2),
        }
        if name == "inflight":
            arms[name]["slot_occupancy_p50"] = hists["slot_occupancy"]["p50"]
    bd, infl = arms["batch_dispatch"], arms["inflight"]

    def gain(a, b):
        return round((a - b) / a * 100.0, 1) if a else 0.0

    return {
        "workload": f"{args.clients} closed-loop clients x "
                    f"{args.per_client} requests, identical load both arms; "
                    "engine-work parity at full occupancy (see phase doc)",
        "latency_model": {
            "batch_dispatch": specs["batch_dispatch"]["backend"],
            "inflight": specs["inflight"]["backend"],
        },
        **arms,
        "ttft_p50_improvement_pct": gain(bd["ttft_p50_s"], infl["ttft_p50_s"]),
        "ttft_p99_improvement_pct": gain(bd["ttft_p99_s"], infl["ttft_p99_s"]),
        "goodput_ratio": (
            round(infl["goodput_rps"] / bd["goodput_rps"], 3)
            if bd["goodput_rps"] else float("inf")
        ),
    }


def fused_phase(args) -> dict:
    """Fused multi-step decode trade study (--fused-segments N, swept over
    {1, 2, 4, 8}). Two loads per N, because the win and the cost live in
    different regimes:

    - SOLO arm (1 closed-loop client, long decodes): decode tokens per
      engine-second PER SLOT. At batch 1 there are no join dynamics at
      all, so the measurement isolates exactly what fusing buys: one host
      round-trip (and one per-dispatch overhead) now covers up to N
      on-device segments instead of one. This is the small-batch regime
      kernel looping targets, and the one a TPU serving stack sits in
      whenever traffic is thin.
    - MIXED arm (--fused-clients clients, 1:1 short/long): anchored TTFT
      and goodput. Joins, cancel/preempt polls, and stream deltas coarsen
      to one opportunity per fused dispatch, so a joiner waits up to N
      segment times for admission — and coarser join cadence desyncs rows
      so they lose batch-level step overlap with residents (rows decoding
      together share a step's cost; rows decoding alone pay it alone).
      The mixed arm reports that convoy cost per N instead of hiding it.
    - byte-identity probe per N (on the solo arm, unloaded): each
      distinct prompt's reply must equal the offline FakeBackend
      reference. The fused loop runs the SAME per-row update as N=1 —
      only host round-trip cadence changes — so any divergence is a
      correctness bug, not a tuning artifact.

    The exit guard first filters N>1 arms whose mixed-load TTFT p50
    regression (vs N=1) stays within --fused-max-ttft-pct, then picks the
    highest solo tokens ratio among them — which must clear
    --fused-min-tokens-ratio. Byte-identity must hold at every swept N,
    winner or not."""
    sweep = (1, 2, 4, 8)
    short = "tin ngan gon sau day chi tam tu"                     # 8 words
    long_ = "phan tich chuyen sau ve tinh hinh kinh te xa hoi " * 6  # 54
    distinct = [short, long_]
    reference = [FakeBackend().generate([p])[0] for p in distinct]
    deadline_s = args.deadline_s

    def mixed_payload(cid, i):
        return {
            "prompt": short if (cid + i) % 2 else long_,
            "deadline_ms": deadline_s * 1000,
        }

    def long_payload(cid, i):
        return {"prompt": long_, "deadline_ms": deadline_s * 1000}

    def make_state(n):
        backend = FakeBackend(
            batch_overhead_s=args.inflight_prefill_s,
            per_step_s=args.per_step_s,
            # finer segments than the r04 arm (default 4 vs 8): short
            # segments are what you WANT for join/cancel latency, and
            # they are exactly where per-dispatch overhead hurts most —
            # the regime fused decode exists to fix
            segment_words=args.fused_segment_words,
            segment_overhead_s=args.segment_overhead_s,
            per_slot_segment_s=args.per_slot_segment_s,
        )
        return ServeState(
            backend,
            max_batch=args.max_batch,
            max_wait_s=args.max_wait_ms / 1000.0,
            max_queue_depth=64,
            trace_sample=1.0,
            trace_ring=64,
            inflight=True,
            slots=args.fused_slots,
            fused_segments=n,
        )

    def run_arm(n, clients, per_client, payload_fn, probe_identity):
        state = make_state(n)
        server = make_server(state, "127.0.0.1", 0)
        threading.Thread(target=server.serve_forever, daemon=True).start()
        base = f"http://127.0.0.1:{server.server_address[1]}"
        byte_identical = None
        if probe_identity:
            # unloaded, before the measured window — determinism is the
            # claim, not a race
            probe = Client(base)
            replies = []
            for prompt in distinct:
                status, body = probe.post("/v1/generate", {"prompt": prompt})
                replies.append(
                    json.loads(body)["completions"][0]["text"]
                    if status == 200 else f"<http {status}>"
                )
            probe.close()
            byte_identical = replies == reference
        loop = closed_loop(base, clients, per_client, deadline_s, payload_fn)
        server.shutdown()
        server.server_close()
        hists = state.scheduler.metrics.histograms_snapshot()
        snap = state.scheduler.metrics.snapshot()
        state.close()
        arm = {
            **loop,
            "fused_segments": n,
            "ttft_p50_s": hists["ttft_seconds"]["p50"],
            "ttft_p99_s": hists["ttft_seconds"]["p99"],
            "segments": snap.segments,
            "fused_dispatches": snap.fused_dispatches,
            "segments_per_dispatch": (
                round(snap.segments / snap.fused_dispatches, 2)
                if snap.fused_dispatches else 0.0
            ),
            "engine_seconds": round(snap.engine_seconds, 3),
            "generated_tokens": snap.generated_tokens,
            "decode_tokens_per_engine_s_per_slot": (
                round(
                    snap.generated_tokens / snap.engine_seconds
                    / args.fused_slots, 2,
                )
                if snap.engine_seconds else 0.0
            ),
        }
        if byte_identical is not None:
            arm["byte_identical"] = byte_identical
        return arm

    solo, mixed = {}, {}
    for n in sweep:
        solo[f"n{n}"] = run_arm(
            n, 1, args.per_client, long_payload, probe_identity=True
        )
        mixed[f"n{n}"] = run_arm(
            n, args.fused_clients, args.per_client, mixed_payload,
            probe_identity=False,
        )

    solo_base = solo["n1"]["decode_tokens_per_engine_s_per_slot"]
    ttft_base = mixed["n1"]["ttft_p50_s"]
    for n in sweep:
        s, m = solo[f"n{n}"], mixed[f"n{n}"]
        s["tokens_ratio_vs_n1"] = (
            round(s["decode_tokens_per_engine_s_per_slot"] / solo_base, 3)
            if solo_base else 0.0
        )
        m["ttft_p50_regression_pct"] = (
            round((m["ttft_p50_s"] - ttft_base) / ttft_base * 100.0, 1)
            if ttft_base else 0.0
        )
    eligible = [
        n for n in sweep
        if n > 1
        and mixed[f"n{n}"]["ttft_p50_regression_pct"] <= args.fused_max_ttft_pct
    ]
    best_n = (
        max(eligible, key=lambda n: solo[f"n{n}"]["tokens_ratio_vs_n1"])
        if eligible else 0
    )
    return {
        "workload": {
            "solo": f"1 closed-loop client x {args.per_client} long "
                    "requests (batch-1 decode: pure dispatch "
                    "amortization, no join dynamics)",
            "mixed": f"{args.fused_clients} closed-loop clients x "
                     f"{args.per_client} requests, 1:1 short/long over "
                     f"{args.fused_slots} slots (join coarsening and "
                     "step-overlap loss land here)",
            "segment_words": args.fused_segment_words,
        },
        "sweep": list(sweep),
        "solo": solo,
        "mixed": mixed,
        "best_n": best_n,
        "best_tokens_ratio": (
            solo[f"n{best_n}"]["tokens_ratio_vs_n1"] if best_n else 0.0
        ),
        "best_ttft_p50_regression_pct": (
            mixed[f"n{best_n}"]["ttft_p50_regression_pct"] if best_n else 0.0
        ),
        "byte_identical_all_n": all(
            solo[f"n{n}"]["byte_identical"] for n in sweep
        ),
    }


def sharded_phase(args) -> dict:
    """DP-replica goodput scaling (ISSUE 11 tentpole): the r04 mixed
    short/long workload against the in-flight server at 1 vs 2 data
    replicas. Hermetic like every other phase — FakeBackend's DP model
    divides per-ROW marginal costs over replicas (rows spread across the
    data axis and run concurrently) while per-dispatch overheads and
    per-STEP depth costs are paid in full, so the measured scaling is the
    scheduling headroom replication actually buys, not a free-lunch cost
    model (byte-identity of the REAL sharded engine is pinned separately
    by tests/test_engine_sharded.py on a CPU mesh). The dp2 arm doubles
    the slot count (each replica holds the same per-replica batch) and
    carries mesh={data: 2} so the mesh gauges render; offered load is
    sized to saturate BOTH arms, making goodput capacity-bound."""
    deadline_s = args.deadline_s
    clients = max(args.clients, 3 * args.max_batch)
    short = "tin ngan gon sau day chi tam tu"                        # 8 words
    long_ = "phan tich chuyen sau ve tinh hinh kinh te xa hoi " * 6  # 54

    def payload(cid, i):
        return {
            "prompt": short if (cid + i) % 2 else long_,
            "deadline_ms": deadline_s * 1000,
        }

    arms = {}
    for name, rep in (("dp1", 1), ("dp2", 2)):
        backend = FakeBackend(
            batch_overhead_s=args.inflight_prefill_s,
            per_step_s=args.per_step_s,
            segment_words=args.segment_words,
            segment_overhead_s=args.segment_overhead_s,
            per_slot_segment_s=args.per_slot_segment_s,
            dp_replicas=rep,
        )
        state = ServeState(
            backend,
            max_batch=args.max_batch,
            max_wait_s=args.max_wait_ms / 1000.0,
            max_queue_depth=128,
            trace_sample=1.0,
            trace_ring=64,
            inflight=True,
            slots=args.max_batch * rep,
            mesh={"data": rep, "model": 1} if rep > 1 else None,
        )
        server = make_server(state, "127.0.0.1", 0)
        threading.Thread(target=server.serve_forever, daemon=True).start()
        base = f"http://127.0.0.1:{server.server_address[1]}"
        loop = closed_loop(base, clients, args.per_client, deadline_s, payload)
        mesh_gauges = []
        if rep > 1:
            # scrape the live server: the mesh gauges are part of what this
            # phase certifies (device count / axis sizes / per-replica
            # occupancy rendered from ServeState.mesh_state)
            u = urllib.parse.urlparse(base)
            conn = http.client.HTTPConnection(u.hostname, u.port, timeout=10)
            conn.request("GET", "/metrics")
            mesh_gauges = [
                l for l in conn.getresponse().read().decode().splitlines()
                if l.startswith("vnsum_serve_mesh_")
            ]
            conn.close()
        server.shutdown()
        server.server_close()
        hists = state.scheduler.metrics.histograms_snapshot()
        snap = state.scheduler.metrics.snapshot()
        state.close()
        arms[name] = {
            **loop,
            "slots": args.max_batch * rep,
            "ttft_p50_s": hists["ttft_seconds"]["p50"],
            "e2e_p50_s": hists["e2e_seconds"]["p50"],
            "segments": snap.segments,
            "refills": snap.refills,
            "engine_seconds": round(snap.engine_seconds, 3),
        }
        if rep > 1:
            arms[name]["mesh_gauges"] = mesh_gauges
    dp1, dp2 = arms["dp1"], arms["dp2"]
    return {
        "workload": f"{clients} closed-loop clients x {args.per_client} "
                    "requests, r04 mixed 1:1 short/long shape, identical "
                    "load both arms; in-flight serving at 1 vs 2 DP "
                    "replicas (2x slots, per-row costs divided, "
                    "per-dispatch/per-step costs in full)",
        **arms,
        "goodput_scaling": (
            round(dp2["goodput_rps"] / dp1["goodput_rps"], 3)
            if dp1["goodput_rps"] else float("inf")
        ),
    }


def fleet_phase(args) -> dict:
    """Replica-fleet front door (serve/router.py, ISSUE 16 tentpole):
    in-process engine workers behind an in-process RouterState — hermetic
    like every other phase, so the measured deltas are routing policy, not
    subprocess noise. Two experiments:

    1. **goodput scaling** — the r04 mixed short/long workload through the
       router at 1 vs 2 fake workers, offered load sized to saturate both
       arms: the front door + fan-out must actually buy capacity
       (>= --fleet-min-scaling), not just add a hop.
    2. **cache affinity** — a shared-prefix workload with 8 distinct
       ``cache_hint`` keys through 2 workers: rendezvous hashing must keep
       each hint's reuse on one worker, holding the aggregate prefix-cache
       hit rate within 10% of a single process that sees every request
       (>= --fleet-min-affinity of the single-process rate)."""
    from vnsum_tpu.serve.router import (
        RouterState,
        Worker as FleetWorker,
        make_router_server,
    )

    deadline_s = args.deadline_s
    short = "tin ngan gon sau day chi tam tu"                        # 8 words
    long_ = "phan tich chuyen sau ve tinh hinh kinh te xa hoi " * 6  # 54

    def run_fleet(n_workers, backend_kwargs, clients, per_client,
                  payload_fn, loop_deadline_s=None):
        """Closed-loop load through a router over N in-process workers ->
        (loop stats, per-worker request spread, aggregate cache tokens)."""
        workers, parts = [], []
        for k in range(n_workers):
            backend = FakeBackend(**backend_kwargs)
            state = ServeState(
                backend,
                max_batch=args.max_batch,
                max_wait_s=args.max_wait_ms / 1000.0,
                max_queue_depth=128,
                trace_sample=0.0,
            )
            server = make_server(state, "127.0.0.1", 0)
            threading.Thread(target=server.serve_forever,
                             daemon=True).start()
            workers.append(FleetWorker(f"w{k}", "127.0.0.1",
                                       server.server_address[1]))
            parts.append((backend, state, server))
        rstate = RouterState(workers, probe_interval_s=0.05,
                             probe_timeout_s=2.0, down_after=2, up_after=1)
        rstate.start()
        rserver = make_router_server(rstate, "127.0.0.1", 0)
        threading.Thread(target=rserver.serve_forever, daemon=True).start()
        rstate.wait_ready(timeout_s=10.0)
        base = f"http://127.0.0.1:{rserver.server_address[1]}"
        loop = closed_loop(base, clients, per_client,
                           loop_deadline_s or deadline_s, payload_fn)
        rserver.shutdown()
        rserver.server_close()
        rstate.close(drain_timeout_s=2.0)
        spread = [w.requests for w in workers]
        hit_tokens = prompt_tokens = 0
        for _backend, state, server in parts:
            server.shutdown()
            server.server_close()
            snap = state.scheduler.metrics.snapshot()
            hit_tokens += snap.cache_hit_tokens
            prompt_tokens += snap.prompt_tokens
            state.close()
        return loop, spread, hit_tokens, prompt_tokens

    # -- 1) goodput scaling: 1 vs 2 workers, identical saturating load ----
    # 6x max_batch clients: after a batch completes its responses round-trip
    # through the router before those clients resubmit, so each worker needs
    # ~2 full batches of standing queue (x2 workers) to never run a partial
    # batch while the window is in flight
    clients = max(args.clients, 6 * args.max_batch)
    # queue sojourn at single-worker saturation is ~clients/goodput which
    # brushes the default 2s SLA; the scaling arm measures throughput, not
    # deadline pressure, so give it slack
    scale_deadline_s = deadline_s * 2

    def mixed_payload(cid, i):
        return {
            "prompt": short if (cid + i) % 2 else long_,
            "deadline_ms": scale_deadline_s * 1000,
        }

    # per-worker capacity must be the bottleneck, not the front door: a
    # single proxying ThreadingHTTPServer tops out well above one worker's
    # throughput but not 2x a fast one, so the fleet arm charges a heavier
    # per-dispatch overhead than the single-process phases — the scaling
    # under test is worker fan-out, and it only shows when workers are
    # what saturates
    scale_kwargs = dict(batch_overhead_s=args.fleet_batch_overhead_s,
                        per_prompt_s=args.per_prompt_s)
    arms = {}
    for name, n in (("fleet1", 1), ("fleet2", 2)):
        loop, spread, _, _ = run_fleet(
            n, scale_kwargs, clients, args.per_client, mixed_payload,
            loop_deadline_s=scale_deadline_s,
        )
        arms[name] = {**loop, "workers": n, "worker_requests": spread}
    scaling = (
        round(arms["fleet2"]["goodput_rps"] / arms["fleet1"]["goodput_rps"],
              3)
        if arms["fleet1"]["goodput_rps"] else float("inf")
    )

    # -- 2) cache affinity: 8 hint keys, 2 workers vs 1 process -----------
    preambles = [
        f"Chủ đề {k}: " + "bối cảnh chuyên sâu về lĩnh vực này cần nắm "
        "trước khi tóm tắt. " * 24
        for k in range(8)
    ]

    def affinity_payload(cid, i):
        pre = preambles[cid % len(preambles)]
        return {
            "prompt": pre + f"Tài liệu {cid}-{i}: " + "nội dung riêng " * 8,
            "cache_hint": pre,
            "deadline_ms": deadline_s * 1000,
        }

    cache_kwargs = dict(
        batch_overhead_s=0.02,
        per_prompt_s=0.002,
        per_token_s=args.per_token_s,
        prefix_cache_blocks=4096,
        cache_block_tokens=16,
    )
    aff_clients = 16
    aff_per_client = max(6, args.per_client // 2)
    aff = {}
    for name, n in (("single", 1), ("fleet2", 2)):
        loop, spread, hit, prompt = run_fleet(
            n, cache_kwargs, aff_clients, aff_per_client, affinity_payload
        )
        aff[name] = {
            "goodput_rps": loop["goodput_rps"],
            "worker_requests": spread,
            "cache_hit_tokens": hit,
            "cache_hit_rate": round(hit / prompt, 4) if prompt else 0.0,
        }
    hit_ratio = (
        round(aff["fleet2"]["cache_hit_rate"]
              / aff["single"]["cache_hit_rate"], 3)
        if aff["single"]["cache_hit_rate"] else float("inf")
    )

    return {
        "workload": f"{clients} closed-loop clients x {args.per_client} "
                    "requests, r04 mixed 1:1 short/long shape through the "
                    "fleet router at 1 vs 2 workers; affinity arm: "
                    f"{aff_clients} clients x {aff_per_client} over 8 "
                    "cache_hint keys, 2 sticky workers vs 1 process",
        **arms,
        "goodput_scaling": scaling,
        "affinity": {**aff, "hit_rate_ratio": hit_ratio},
    }


def federation_phase(args) -> dict:
    """Fleet observability tax (serve/federation.py, ISSUE 19): identical
    saturating load through a 2-worker router with the federation scrape
    loop ON (fast cadence, so the bench is an upper bound on the shipped
    1 s default) vs OFF (``--no-federation``). The scrape loop runs on its
    own thread against each worker's JSON snapshot endpoint — the A/B
    charges exactly that: snapshot serialization on the workers plus
    scrape folding on the router, under load. Acceptance:
    ``--federation-max-overhead-pct`` (default 1%) of fleet goodput."""
    from vnsum_tpu.serve.router import (
        RouterState,
        Worker as FleetWorker,
        make_router_server,
    )

    deadline_s = args.deadline_s * 2
    short = "tin ngan gon sau day chi tam tu"
    long_ = "phan tich chuyen sau ve tinh hinh kinh te xa hoi " * 6

    def payload(cid, i):
        return {
            "prompt": short if (cid + i) % 2 else long_,
            "deadline_ms": deadline_s * 1000,
        }

    def run_arm(federate: bool):
        workers, parts = [], []
        for k in range(2):
            backend = FakeBackend(
                batch_overhead_s=args.fleet_batch_overhead_s,
                per_prompt_s=args.per_prompt_s,
            )
            state = ServeState(
                backend,
                max_batch=args.max_batch,
                max_wait_s=args.max_wait_ms / 1000.0,
                max_queue_depth=128,
                trace_sample=0.0,
            )
            server = make_server(state, "127.0.0.1", 0)
            threading.Thread(target=server.serve_forever,
                             daemon=True).start()
            workers.append(FleetWorker(f"w{k}", "127.0.0.1",
                                       server.server_address[1]))
            parts.append((state, server))
        rstate = RouterState(
            workers, probe_interval_s=0.05, probe_timeout_s=2.0,
            down_after=2, up_after=1,
            federate=federate,
            # 5x the shipped default cadence: the measured tax bounds it
            federation_interval_s=0.2,
        )
        rstate.start()
        rserver = make_router_server(rstate, "127.0.0.1", 0)
        threading.Thread(target=rserver.serve_forever, daemon=True).start()
        rstate.wait_ready(timeout_s=10.0)
        base = f"http://127.0.0.1:{rserver.server_address[1]}"
        clients = max(args.clients, 4 * args.max_batch)
        loop = closed_loop(base, clients, args.per_client, deadline_s,
                           payload)
        stats = (rstate.federation.stats_dict()
                 if rstate.federation is not None else None)
        rserver.shutdown()
        rserver.server_close()
        rstate.close(drain_timeout_s=2.0)
        for state, server in parts:
            server.shutdown()
            server.server_close()
            state.close()
        return {**loop, "federation_stats": stats}

    off = run_arm(False)
    on = run_arm(True)
    overhead_pct = (
        round(max(0.0, (off["goodput_rps"] - on["goodput_rps"])
                  / off["goodput_rps"] * 100.0), 2)
        if off["goodput_rps"] else 0.0
    )
    return {
        "workload": "2-worker fleet, saturating mixed short/long closed "
                    "loop; federation scrape loop at 200 ms cadence (5x "
                    "the shipped 1 s default) vs --no-federation",
        "federation_off": off,
        "federation_on": on,
        "federation_overhead_pct": overhead_pct,
    }


def qos_phase(args) -> dict:
    """Multi-tenant QoS under saturation (ISSUE 12 tentpole): the
    interactive tenant's ANCHORED TTFT p99 with a batch tenant saturating
    every slot vs its unloaded baseline. The lever is tier preemption +
    WFQ: interactive arrivals evict batch-tier residents within one
    segment and the WFQ pick admits them first, so the interactive tail
    tracks its own prefill instead of queueing through whole batch jobs.

    The batch tenant is the paper's own workload shape: a map-reduce
    fan-out whose prompts share one long template header, sent as a
    cache_hint — after warmup its admits prefill only the unique tail
    from the radix cache, so its interference is slot OCCUPANCY (what
    preemption reclaims) plus brief cached admits, not prefill monopoly.
    Interactive prompts are unique per request (never cache-warm), so
    the baseline TTFT is honest prefill work in both arms; preempted
    batch jobs re-admit against their PINNED header blocks — the
    pin-across-eviction path earning its keep. TTFT comes from the
    per-request records the responses carry inline (anchored at each
    joiner's own prefill end), parsed client-side off the request hot
    loop."""
    from vnsum_tpu.serve.qos import TenantTable, parse_tenant_specs

    slots = args.qos_slots
    # p99 over ~200 samples (2nd-worst, not the max) — the tail estimate
    # the acceptance criterion is judged on needs more samples than the
    # throughput phases
    per_client = max(2 * args.per_client, 30)
    i_words = "nguoi dung tuong tac hoi dap truc tuyen can tra loi " * 15
    header_b = ("mau nhiem vu tom tat chuan ap dung cho moi loai tai lieu "
                "kinh te xa hoi giao duc moi truong ") * 16
    backend_kw = dict(
        batch_overhead_s=0.002, per_token_s=0.0004,
        per_step_s=0.0005, segment_words=4,
        segment_overhead_s=0.0005, per_slot_segment_s=0.0002,
        prefix_cache_blocks=4096, cache_block_tokens=8,
    )
    arms = {}
    for name in ("unloaded", "loaded"):
        backend = FakeBackend(**backend_kw)
        state = ServeState(
            backend, max_batch=slots, max_wait_s=0.005,
            max_queue_depth=256, trace_sample=0.0,
            inflight=True, slots=slots,
            tenants=TenantTable(parse_tenant_specs(
                "interactive:8:0,batch:1:0:batch"
            )),
        )
        server = make_server(state, "127.0.0.1", 0)
        threading.Thread(target=server.serve_forever, daemon=True).start()
        base = f"http://127.0.0.1:{server.server_address[1]}"
        stop = threading.Event()
        count_lock = threading.Lock()
        batch_done = {"n": 0}
        batch_threads = []
        if name == "loaded":
            def batch_client(bid):
                c = Client(base)
                c.connect()
                n = 0
                while not stop.is_set():
                    n += 1
                    try:
                        status, _ = c.post(
                            "/v1/generate",
                            {"prompt": header_b
                             + f"phan cong {bid}-{n} noi dung rieng " * 3,
                             "cache_hint": header_b},
                            headers={"X-Tenant": "batch"},
                        )
                        if status == 200:
                            with count_lock:
                                batch_done["n"] += 1
                    except Exception:
                        if stop.is_set():
                            break
                c.close()
            batch_threads = [
                threading.Thread(target=batch_client, args=(bid,),
                                 daemon=True)
                for bid in range(args.qos_batch_clients)
            ]
            for t in batch_threads:
                t.start()
            time.sleep(0.4)  # reach steady saturation before measuring

        ttfts: list[float] = []
        lock = threading.Lock()
        clients = args.qos_interactive_clients
        barrier = threading.Barrier(clients + 1)

        def inter_client(cid):
            import random

            rng = random.Random(1000 + cid)  # seeded: reproducible load
            c = Client(base)
            c.connect()
            barrier.wait()
            for _i in range(per_client):
                # jittered think time breaks client lockstep (group-
                # prefill collisions would dominate the tail in both arms)
                # AND keeps interactive utilization well under saturation:
                # the criterion compares the loaded tail against an
                # unloaded baseline, which only means something when the
                # interactive tenant is not queueing behind itself
                time.sleep(rng.uniform(0.25, 0.45))
                # unique per request: interactive prompts never ride the
                # radix cache, so measured TTFT is real prefill work
                status, raw = c.post(
                    "/v1/generate",
                    {"prompt": f"cau hoi {cid}-{_i} " + i_words},
                    headers={"X-Tenant": "interactive"},
                )
                if status != 200:
                    continue
                rec = json.loads(raw)["completions"][0]["record"]
                if rec.get("ttft_anchored"):
                    with lock:
                        ttfts.append(rec["ttft_s"])
            c.close()

        threads = [
            threading.Thread(target=inter_client, args=(cid,))
            for cid in range(clients)
        ]
        for t in threads:
            t.start()
        barrier.wait()
        t0 = time.monotonic()
        for t in threads:
            t.join()
        wall = time.monotonic() - t0
        stop.set()
        for t in batch_threads:
            t.join(timeout=10)
        server.shutdown()
        server.server_close()
        snap = state.scheduler.metrics.snapshot()
        state.close()
        arms[name] = {
            "interactive_requests": clients * per_client,
            "ttft_samples": len(ttfts),
            **{f"ttft_{k}": v for k, v in _percentiles(ttfts).items()},
            "interactive_rps": round(len(ttfts) / wall, 2) if wall else 0.0,
            "batch_completed": batch_done["n"],
            "preemptions": snap.preemptions,
            "requeues": snap.requeues,
        }
    un, ld = arms["unloaded"], arms["loaded"]
    degradation_pct = (
        round((ld["ttft_p99_s"] - un["ttft_p99_s"])
              / un["ttft_p99_s"] * 100.0, 1)
        if un["ttft_p99_s"] else 0.0
    )
    return {
        "workload": f"{args.qos_interactive_clients} interactive clients x "
                    f"{per_client} requests (unique prompts, never "
                    "cache-warm, jittered think time); loaded arm adds "
                    f"{args.qos_batch_clients} closed-loop batch-tier "
                    f"clients saturating {slots} slots with shared-header "
                    "map-reduce jobs (radix-cached via cache_hint, so "
                    "their interference is slot occupancy + brief cached "
                    "admits; WFQ + preemption are the levers)",
        "tenants": "interactive:8:0, batch:1:0:batch",
        **arms,
        "interactive_ttft_p99_degradation_pct": degradation_pct,
    }


def journal_phase(args) -> dict:
    """Durable-serving overhead A/B (serve/journal.py): the offline
    closed-loop shape — identical latency model and load as the headline
    serve arm, tracing off — with the write-ahead journal off vs on. The
    journal writes one ACCEPT + one START + one COMPLETE record per request
    (flush-to-kernel each, fsync group-committed), so the goodput delta IS
    the durability tax; <2% is the acceptance bar.

    Each arm runs TWICE and keeps its best goodput: the ~6s measurement
    window jitters +/-1.5% run to run on a shared host (CFS throttling,
    unrelated wakeups) — the same order as the effect under test — so
    best-of-2 compares peak capability against peak capability instead of
    letting one unlucky draw decide the sign."""
    import shutil
    import tempfile

    lat = dict(batch_overhead_s=args.batch_overhead_s,
               per_prompt_s=args.per_prompt_s)
    arms = {}
    for name in ("journal_off", "journal_on"):
        best = None
        for _rep in range(2):
            journal_dir = tempfile.mkdtemp() if name == "journal_on" else None
            backend = FakeBackend(**lat)
            state = ServeState(
                backend,
                max_batch=args.max_batch,
                max_wait_s=args.max_wait_ms / 1000.0,
                max_queue_depth=64,
                trace_sample=0.0,
                journal_dir=journal_dir,
            )
            server = make_server(state, "127.0.0.1", 0)
            threading.Thread(target=server.serve_forever, daemon=True).start()
            base = f"http://127.0.0.1:{server.server_address[1]}"
            loop = closed_loop(
                base, args.clients, args.per_client, args.deadline_s
            )
            server.shutdown()
            server.server_close()
            state.close()  # drain + seal before reading the final counters
            if state.journal is not None:
                loop["journal_stats"] = state.journal.stats_dict()
                shutil.rmtree(journal_dir, ignore_errors=True)
            if best is None or loop["goodput_rps"] > best["goodput_rps"]:
                best = loop
        arms[name] = best
    on, off = arms["journal_on"], arms["journal_off"]
    overhead_pct = (
        round((off["goodput_rps"] - on["goodput_rps"])
              / off["goodput_rps"] * 100.0, 2)
        if off["goodput_rps"] else 0.0
    )
    return {
        "workload": f"{args.clients} closed-loop clients x "
                    f"{args.per_client} requests, identical offline load "
                    "both arms; journal_on adds the full WAL lifecycle "
                    "(accept/start/complete + group-commit fsync)",
        **arms,
        "journal_overhead_pct": overhead_pct,
    }


def cancel_phase(args) -> dict:
    """Request-cancellation phase (ISSUE 13 tentpole), two claims:

    (a) RECLAIM — with a batch tenant saturating the in-flight slots with
    long decodes, cancelling its outstanding requests (DELETE, the gang
    surface) hands the engine back to the remaining interactive clients
    within one segment boundary: their post-cancel goodput must recover to
    >=90% of an idle-arm baseline measured with no batch tenant at all.

    (b) UNUSED-PATH OVERHEAD — the cancel machinery's cost when nobody
    cancels: the r04 mixed in-flight closed loop with the per-boundary
    cancel sweeps enabled vs disabled (the scheduler's bench-only
    ``cancellation_enabled`` lever), best-of-2 per arm like the journal
    phase; the enabled arm must stay within the overhead bar (<1% is the
    acceptance target — the armed fast path is two attribute reads per
    segment boundary)."""
    from vnsum_tpu.serve.qos import TenantTable, parse_tenant_specs
    from vnsum_tpu.testing.chaos import http_delete

    slots = 4
    window_s = args.cancel_window_s
    backend_kw = dict(batch_overhead_s=0.004, segment_words=2,
                      segment_overhead_s=0.008, per_slot_segment_s=0.001)
    # interactive: 8-word outputs (4 segments); batch: 40-word outputs
    # (20 segments) — the long decodes whose cancellation frees the slots
    i_prompt = "cau hoi ngan can tra loi nhanh gon"
    b_prompt = "phan tich day du va chi tiet ve moi mat cua van de " * 10

    def make_state():
        return ServeState(
            FakeBackend(**backend_kw),
            max_batch=slots, max_wait_s=0.005, max_queue_depth=256,
            trace_sample=0.0, inflight=True, slots=slots,
            tenants=TenantTable(parse_tenant_specs(
                "interactive:8:0,batch:1:0:batch"
            )),
        )

    def run_interactive(base, stop, stamps, n_clients=4):
        """Closed-loop interactive clients; completion times -> stamps."""
        def client(cid):
            c = Client(base)
            c.connect()
            i = 0
            while not stop.is_set():
                i += 1
                try:
                    status, _ = c.post(
                        "/v1/generate", {"prompt": i_prompt},
                        headers={"X-Tenant": "interactive"},
                    )
                except Exception:
                    break
                if status == 200:
                    stamps.append(time.monotonic())
            c.close()
        threads = [threading.Thread(target=client, args=(cid,), daemon=True)
                   for cid in range(n_clients)]
        for t in threads:
            t.start()
        return threads

    def rate_in(stamps, t0, t1) -> float:
        n = sum(1 for t in list(stamps) if t0 <= t < t1)
        return n / (t1 - t0) if t1 > t0 else 0.0

    # -- idle baseline: interactive clients alone -------------------------
    state = make_state()
    server = make_server(state, "127.0.0.1", 0)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{server.server_address[1]}"
    stop = threading.Event()
    stamps: list[float] = []
    threads = run_interactive(base, stop, stamps)
    time.sleep(0.3)  # warmup
    t0 = time.monotonic()
    time.sleep(window_s)
    idle_rate = rate_in(stamps, t0, time.monotonic())
    stop.set()
    for t in threads:
        t.join(timeout=10)
    server.shutdown()
    server.server_close()
    state.close()

    # -- loaded arm: batch saturation, then gang-cancel -------------------
    state = make_state()
    server = make_server(state, "127.0.0.1", 0)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{server.server_address[1]}"
    u = urllib.parse.urlparse(base)
    stop = threading.Event()
    stop_batch = threading.Event()
    stamps = []
    in_flight: dict[int, str] = {}  # bid -> rid currently posted
    flight_lock = threading.Lock()

    def batch_client(bid):
        c = Client(base)
        c.connect()
        n = 0
        while not stop_batch.is_set():
            n += 1
            rid = f"bench-batch-{bid}-{n}"
            with flight_lock:
                in_flight[bid] = rid
            try:
                c.post("/v1/generate",
                       {"prompt": b_prompt, "request_id": rid},
                       headers={"X-Tenant": "batch"})
            except Exception:
                break
            with flight_lock:
                in_flight.pop(bid, None)
        c.close()

    batch_threads = [
        threading.Thread(target=batch_client, args=(bid,), daemon=True)
        for bid in range(args.cancel_batch_clients)
    ]
    for t in batch_threads:
        t.start()
    time.sleep(0.3)  # reach saturation
    threads = run_interactive(base, stop, stamps)
    t_loaded = time.monotonic()
    time.sleep(window_s)
    # THE CANCEL: stop the tenant's submissions and DELETE everything it
    # still has in flight (two sweeps catch posts racing the first)
    t_cancel = time.monotonic()
    stop_batch.set()
    for _sweep in range(2):
        with flight_lock:
            rids = list(in_flight.values())
        for rid in rids:
            try:
                http_delete(u.hostname, u.port, f"/v1/requests/{rid}",
                            timeout=5.0)
            except OSError:
                pass  # lint-allow[swallowed-exception]: a lost DELETE just leaves that job to finish; the recovery ratio below is the judge
        time.sleep(0.05)
    loaded_rate = rate_in(stamps, t_loaded, t_cancel)
    t_rec = time.monotonic()
    time.sleep(window_s)
    recovered_rate = rate_in(stamps, t_rec, time.monotonic())
    stop.set()
    for t in threads + batch_threads:
        t.join(timeout=10)
    server.shutdown()
    server.server_close()
    snap = state.scheduler.metrics.snapshot()
    state.close()

    # -- unused-path overhead A/B -----------------------------------------
    short = "tin ngan gon sau day chi tam tu"
    long_ = "phan tich chuyen sau ve tinh hinh kinh te xa hoi " * 6

    def payload(cid, i):
        return {"prompt": short if (cid + i) % 2 else long_,
                "deadline_ms": args.deadline_s * 1000}

    arms = {}
    for name, enabled in (("cancel_on", True), ("cancel_off", False)):
        best = None
        for _rep in range(2):
            backend = FakeBackend(
                batch_overhead_s=args.inflight_prefill_s,
                per_step_s=args.per_step_s,
                segment_words=args.segment_words,
                segment_overhead_s=args.segment_overhead_s,
                per_slot_segment_s=args.per_slot_segment_s,
            )
            ab_state = ServeState(
                backend, max_batch=args.max_batch,
                max_wait_s=args.max_wait_ms / 1000.0, max_queue_depth=64,
                trace_sample=0.0, inflight=True, slots=args.max_batch,
            )
            # bench-only lever: measure the armed fast path against the
            # same build with the sweeps compiled out of the loop
            ab_state.scheduler.cancellation_enabled = enabled
            ab_server = make_server(ab_state, "127.0.0.1", 0)
            threading.Thread(
                target=ab_server.serve_forever, daemon=True
            ).start()
            ab_base = f"http://127.0.0.1:{ab_server.server_address[1]}"
            loop = closed_loop(
                ab_base, args.clients, args.per_client, args.deadline_s,
                payload,
            )
            ab_server.shutdown()
            ab_server.server_close()
            ab_state.close()
            if best is None or loop["goodput_rps"] > best["goodput_rps"]:
                best = loop
        arms[name] = best
    on, off = arms["cancel_on"], arms["cancel_off"]
    overhead_pct = (
        round((off["goodput_rps"] - on["goodput_rps"])
              / off["goodput_rps"] * 100.0, 2)
        if off["goodput_rps"] else 0.0
    )
    return {
        "workload": f"reclaim: 4 interactive clients vs "
                    f"{args.cancel_batch_clients} batch clients saturating "
                    f"{slots} slots with 20-segment decodes; at t_cancel "
                    "the batch tenant stops and its in-flight requests are "
                    "DELETEd — post-cancel interactive goodput vs an "
                    "idle-arm baseline. Overhead: r04 mixed in-flight "
                    "closed loop, cancel sweeps on vs off, best-of-2",
        "idle_goodput_rps": round(idle_rate, 2),
        "loaded_goodput_rps": round(loaded_rate, 2),
        "recovered_goodput_rps": round(recovered_rate, 2),
        "recovery_ratio": (
            round(recovered_rate / idle_rate, 3) if idle_rate else 0.0
        ),
        "cancels": dict(snap.cancelled),
        "preemptions": snap.preemptions,
        "cancel_on": on,
        "cancel_off": off,
        "cancel_overhead_pct": overhead_pct,
    }


def slo_phase(args) -> dict:
    """Full-observability overhead A/B (ISSUE 14 tentpole): the r04 mixed
    in-flight closed loop with the ENTIRE production obs stack armed —
    request tracing, rolling windows + per-tenant usage ledger, flight
    recorder, and a four-objective SLO engine — against a build with all
    of it constructed away (trace_sample=0, windowed_metrics=False,
    flight_recorder=False, no --slo). The goodput delta IS the layer's
    cost; <2% is the acceptance bar, same as the journal's. Best-of-5 per
    arm with the reps INTERLEAVED (on, off, on, off, ...): the in-flight
    shape at ~100 rps jitters +/-2% run to run on this host — the same
    order as the bar — and host drift across a multi-minute bench
    (thermal, CFS) is monotone enough that back-to-back blocks of one arm
    bias the sign; alternating arms makes both sample the same drift.

    The armed arm also CERTIFIES the surfaces under load: /debug/slo must
    evaluate all four objectives, /v1/usage must carry the load's tokens,
    and the flight-recorder ring must hold the lifecycle — an A/B whose
    "on" arm silently measured a dormant layer would prove nothing."""
    short = "tin ngan gon sau day chi tam tu"
    long_ = "phan tich chuyen sau ve tinh hinh kinh te xa hoi " * 6

    def payload(cid, i):
        return {"prompt": short if (cid + i) % 2 else long_,
                "deadline_ms": args.deadline_s * 1000}

    backend_kw = dict(
        batch_overhead_s=args.inflight_prefill_s,
        per_step_s=args.per_step_s,
        segment_words=args.segment_words,
        segment_overhead_s=args.segment_overhead_s,
        per_slot_segment_s=args.per_slot_segment_s,
    )
    specs = {
        "obs_on": dict(
            trace_sample=1.0, trace_ring=64,
            slo="ttft_p99=0.5,e2e_p99=2.0,error_rate=0.01,"
                "availability=0.999",
        ),
        "obs_off": dict(trace_sample=0.0, windowed_metrics=False,
                        flight_recorder=False),
    }
    arms = {}
    surfaces = {}
    # best-of-5 interleaved (was 3): across full-bench reruns the 3-rep
    # best swung this measurement from -6.9% to +2.4% on this shared host
    # — more than the 2% bar in both directions — so the bar was judging
    # rep luck, not the layer; two extra reps per arm converge the bests
    for _rep in range(5):
        for name, spec in specs.items():
            state = ServeState(
                FakeBackend(**backend_kw),
                max_batch=args.max_batch,
                max_wait_s=args.max_wait_ms / 1000.0,
                max_queue_depth=64,
                inflight=True, slots=args.max_batch,
                **spec,
            )
            server = make_server(state, "127.0.0.1", 0)
            threading.Thread(target=server.serve_forever,
                             daemon=True).start()
            base = f"http://127.0.0.1:{server.server_address[1]}"
            loop = closed_loop(
                base, args.clients, args.per_client, args.deadline_s,
                payload,
            )
            if name == "obs_on" and not surfaces:
                # certify the armed surfaces against the live server once
                u = urllib.parse.urlparse(base)
                conn = http.client.HTTPConnection(u.hostname, u.port,
                                                  timeout=10)
                conn.request("GET", "/debug/slo")
                slo_d = json.loads(conn.getresponse().read())
                conn.request("GET", "/v1/usage")
                usage_d = json.loads(conn.getresponse().read())
                conn.close()
                recorder = state.recorder.stats_dict()
                tenants = usage_d["tenants"]
                surfaces = {
                    "slo_objectives": len(slo_d["objectives"]),
                    "slo_breached": slo_d["breached"],
                    "usage_requests": sum(
                        t["requests"] for t in tenants.values()
                    ),
                    "usage_generated_tokens": sum(
                        t["generated_tokens"] for t in tenants.values()
                    ),
                    "recorder_events": recorder["events"],
                }
            server.shutdown()
            server.server_close()
            state.close()
            best = arms.get(name)
            if best is None or loop["goodput_rps"] > best["goodput_rps"]:
                arms[name] = loop
    on, off = arms["obs_on"], arms["obs_off"]
    overhead_pct = (
        round((off["goodput_rps"] - on["goodput_rps"])
              / off["goodput_rps"] * 100.0, 2)
        if off["goodput_rps"] else 0.0
    )
    return {
        "workload": f"{args.clients} closed-loop clients x "
                    f"{args.per_client} requests, r04 mixed in-flight "
                    "shape, identical load both arms; obs_on = tracing + "
                    "rolling windows + usage ledger + flight recorder + "
                    "4-objective SLO engine, obs_off = all constructed "
                    "away; best-of-5 per arm, reps interleaved",
        "slo_spec": specs["obs_on"]["slo"],
        **arms,
        "surfaces": surfaces,
        "slo_overhead_pct": overhead_pct,
    }


def watchdog_phase(args) -> dict:
    """Watchdog overhead A/B (ISSUE 15 tentpole): the r04 mixed in-flight
    closed loop with liveness fully armed — heartbeat registry beaten from
    the queue's wait loops, a dispatch ticket (begin/end + token-derived
    budget) around every slot admit and decode segment, and the 10Hz
    monitor thread — against a build with the watchdog constructed away
    (watchdog=False). The goodput delta IS the healthy-path cost of the
    bounded-dispatch bookkeeping; <1% is the acceptance bar (tighter than
    the journal/SLO layers' 2%: this is per-SEGMENT arithmetic, not I/O).
    Best-of-5 per arm, reps interleaved, same drift rationale as the slo
    phase. The armed arm also certifies the surfaces: /healthz must carry
    the watchdog line with a live scheduler heartbeat, and the healthy
    path must finish with ZERO stalls — a false positive under clean load
    would be a recovery storm in production."""
    short = "tin ngan gon sau day chi tam tu"
    long_ = "phan tich chuyen sau ve tinh hinh kinh te xa hoi " * 6

    def payload(cid, i):
        return {"prompt": short if (cid + i) % 2 else long_,
                "deadline_ms": args.deadline_s * 1000}

    backend_kw = dict(
        batch_overhead_s=args.inflight_prefill_s,
        per_step_s=args.per_step_s,
        segment_words=args.segment_words,
        segment_overhead_s=args.segment_overhead_s,
        per_slot_segment_s=args.per_slot_segment_s,
    )
    specs = {
        "watchdog_on": dict(watchdog=True, watchdog_interval_s=0.1),
        "watchdog_off": dict(watchdog=False),
    }
    arms = {}
    surfaces = {}
    # best-of-5 (vs the slo phase's 3): the expected effect here is ~0.1%
    # — far BELOW this host's ±2% rep jitter — so the bar is really "the
    # best reps of both arms converge"; two extra reps per arm tighten
    # that materially for ~20s of bench time
    for _rep in range(5):
        for name, spec in specs.items():
            state = ServeState(
                FakeBackend(**backend_kw),
                max_batch=args.max_batch,
                max_wait_s=args.max_wait_ms / 1000.0,
                max_queue_depth=64,
                trace_sample=0.0,
                inflight=True, slots=args.max_batch,
                **spec,
            )
            server = make_server(state, "127.0.0.1", 0)
            threading.Thread(target=server.serve_forever,
                             daemon=True).start()
            base = f"http://127.0.0.1:{server.server_address[1]}"
            loop = closed_loop(
                base, args.clients, args.per_client, args.deadline_s,
                payload,
            )
            if name == "watchdog_on" and not surfaces:
                u = urllib.parse.urlparse(base)
                conn = http.client.HTTPConnection(u.hostname, u.port,
                                                  timeout=10)
                conn.request("GET", "/healthz")
                health = json.loads(conn.getresponse().read())
                conn.close()
                wd = state.watchdog.stats_dict()
                surfaces = {
                    "healthz_watchdog": health.get("watchdog"),
                    "stalls": sum(wd["stalls"].values()),
                    "hung_dispatches": wd["hung_dispatches"],
                    "heartbeats": sorted(wd["heartbeat_ages"]),
                }
            server.shutdown()
            server.server_close()
            state.close()
            best = arms.get(name)
            if best is None or loop["goodput_rps"] > best["goodput_rps"]:
                arms[name] = loop
    on, off = arms["watchdog_on"], arms["watchdog_off"]
    overhead_pct = (
        round((off["goodput_rps"] - on["goodput_rps"])
              / off["goodput_rps"] * 100.0, 2)
        if off["goodput_rps"] else 0.0
    )
    return {
        "workload": f"{args.clients} closed-loop clients x "
                    f"{args.per_client} requests, r04 mixed in-flight "
                    "shape, identical load both arms; watchdog_on = "
                    "heartbeats + per-dispatch budget tickets + 10Hz "
                    "monitor, watchdog_off = constructed away; best-of-5 "
                    "per arm, reps interleaved",
        **arms,
        "surfaces": surfaces,
        "watchdog_overhead_pct": overhead_pct,
    }


# -- structured jobs: gang-scheduled map->reduce vs the offline pipeline -----


_GANG_WORDS = ("báo cáo tổng hợp dữ liệu kinh tế xã hội vùng đồng bằng "
               "ven biển phát triển hạ tầng giao thông đô thị nông nghiệp "
               "công nghệ giáo dục y tế môi trường năng lượng").split()


def _gang_doc(d: int) -> str:
    """Deterministic multi-chunk document: past the mapreduce splitter's
    12000-token chunk budget so each summarize fans out into 2-3 map
    children plus a reduce. Lengths vary per doc so fan-out widths are
    ragged — the shape where a barrier waits on stragglers."""
    nwords = 12600 + 700 * (d % 3)
    body = " ".join(
        _GANG_WORDS[(d + k) % len(_GANG_WORDS)] for k in range(nwords)
    )
    return f"Tài liệu dài {d}.\n\n{body}"


class _BucketedOffline:
    """Capacity-fair offline comparator: the offline pipeline feeds the
    engine at most max_batch prompts per dispatch, so the barrier arm's
    generate() is split into max_batch buckets — without this the barrier
    arm would enjoy an unbounded device batch no hardware has, and the A/B
    would measure the fiction, not the scheduling."""

    def __init__(self, inner: FakeBackend, max_batch: int) -> None:
        self._inner = inner
        self._max_batch = max_batch

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def generate(self, prompts, *, max_new_tokens=None, config=None,
                 references=None, cache_hints=None):
        out = []
        for s in range(0, len(prompts), self._max_batch):
            e = s + self._max_batch
            out.extend(self._inner.generate(
                prompts[s:e], max_new_tokens=max_new_tokens, config=config,
                references=references[s:e] if references is not None else None,
                cache_hints=cache_hints[s:e] if cache_hints is not None else None,
            ))
        return out


def _gang_backend(args) -> FakeBackend:
    return FakeBackend(
        batch_overhead_s=args.batch_overhead_s,
        per_prompt_s=args.per_prompt_s,
        per_token_s=args.gang_per_token_s,
        prefix_cache_blocks=2048,
        cache_block_tokens=8,
    )


def _gang_serving_arm(args, docs: list[str], affinity: bool) -> dict:
    """Drive the docs through /v1/summarize with concurrent clients — each
    POST is a gang-admitted fan-out whose map/reduce rounds stream through
    the shared queue, packing across documents (and across phases: a
    finished doc's reduce rides the next map dispatch)."""
    backend = _gang_backend(args)
    state = ServeState(
        backend,
        max_batch=args.max_batch,
        max_wait_s=args.max_wait_ms / 1000.0,
        max_queue_depth=64,
        trace_sample=0.0,
    )
    state.scheduler.queue.gang_affinity = affinity
    server = make_server(state, "127.0.0.1", 0)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{server.server_address[1]}"

    summaries: dict[int, str] = {}
    errors: list[str] = []
    lock = threading.Lock()

    def run_client(cid: int) -> None:
        c = Client(base)
        c.connect()
        for d in range(cid, len(docs), args.gang_clients):
            status, raw = c.post("/v1/summarize", {
                "text": docs[d], "approach": "mapreduce",
                "request_id": f"bgang-{'on' if affinity else 'off'}-{d}",
            })
            with lock:
                if status == 200:
                    summaries[d] = json.loads(raw)["summary"]
                else:
                    errors.append(f"doc {d}: HTTP {status}")
        c.close()

    threads = [
        threading.Thread(target=run_client, args=(cid,), daemon=True)
        for cid in range(args.gang_clients)
    ]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.monotonic() - t0

    server.shutdown()
    server.server_close()
    snap = state.scheduler.metrics.snapshot()
    state.close()
    nb = len(backend.batch_sizes)
    return {
        "gang_affinity": affinity,
        "docs": len(summaries),
        "errors": errors,
        "wall_s": round(wall, 3),
        "docs_per_min": round(len(summaries) / wall * 60.0, 2) if wall else 0.0,
        "engine_calls": nb,
        "avg_batch_occupancy": (
            round(sum(backend.batch_sizes) / nb, 2) if nb else 0.0
        ),
        "cache_hit_rate": round(snap.cache_hit_rate, 4),
        "gangs_admitted": snap.gang_admitted,
        "gang_members": snap.gang_members,
        "gang_affinity_picks": snap.gang_affinity_picks,
        "_summaries": summaries,
    }


def gang_phase(args) -> dict:
    """Structured-jobs A/B (ISSUE 17 acceptance): the serving-path
    map->reduce — gang admission, gang-affinity batch packing, streaming
    reduce — against the OFFLINE pipeline shape (the blocking barrier
    strategy over a capacity-bucketed backend with the identical latency
    model). Same documents, same splitter config, byte-identical summaries
    required; the serving win is structural — host work (split/format/join)
    overlaps engine dispatches across concurrent documents, and streaming
    mixes reduces into later map batches instead of paying the barrier's
    extra dispatches. A second serving run with gang_affinity OFF isolates
    what sibling clustering itself contributes (recorded, no-regression
    guarded: on a homogeneous workload every map shares one template-header
    hint, so near-parity is the honest expectation)."""
    from vnsum_tpu.core.config import PipelineConfig, approach_defaults
    from vnsum_tpu.strategies import get_strategy

    docs = [_gang_doc(d) for d in range(args.gang_clients * args.gang_per_client)]

    # offline arm: one blocking summarize_batch pass, engine capacity-fair
    offline_backend = _gang_backend(args)
    cfg = PipelineConfig(approach="mapreduce",
                         **approach_defaults("mapreduce"))
    strat = get_strategy(
        "mapreduce", _BucketedOffline(offline_backend, args.max_batch), cfg
    )
    t0 = time.monotonic()
    offline_results = strat.summarize_batch(docs)
    offline_wall = time.monotonic() - t0
    nb = len(offline_backend.batch_sizes)
    offline = {
        "docs": len(docs),
        "wall_s": round(offline_wall, 3),
        "docs_per_min": (
            round(len(docs) / offline_wall * 60.0, 2) if offline_wall else 0.0
        ),
        "engine_calls": nb,
        "avg_batch_occupancy": (
            round(sum(offline_backend.batch_sizes) / nb, 2) if nb else 0.0
        ),
        "cache_stats": offline_backend.prefix_cache_stats(),
    }

    serving = _gang_serving_arm(args, docs, affinity=True)
    serving_off = _gang_serving_arm(args, docs, affinity=False)

    # byte identity: the streaming serving path must reproduce the offline
    # barrier's summaries exactly, per document
    mismatches = sorted(
        d for d, r in enumerate(offline_results)
        for arm in (serving, serving_off)
        if arm["_summaries"].get(d) != r.summary
    )
    for arm in (serving, serving_off):
        del arm["_summaries"]

    return {
        "workload": (
            f"{len(docs)} docs of 12.6-14k words (2-3 map chunks each), "
            f"{args.gang_clients} concurrent summarize clients x "
            f"{args.gang_per_client} docs vs one blocking offline "
            f"strategy pass over a max_batch-bucketed backend"
        ),
        "latency_model": {
            "batch_overhead_s": args.batch_overhead_s,
            "per_prompt_s": args.per_prompt_s,
            "per_token_s": args.gang_per_token_s,
        },
        "offline": offline,
        "serving": serving,
        "affinity_off": serving_off,
        "speedup_vs_offline": (
            round(serving["docs_per_min"] / offline["docs_per_min"], 3)
            if offline["docs_per_min"] else float("inf")
        ),
        "affinity_ratio": (
            round(serving["docs_per_min"] / serving_off["docs_per_min"], 3)
            if serving_off["docs_per_min"] else float("inf")
        ),
        "byte_identical": not mismatches and not serving["errors"]
        and not serving_off["errors"],
        "summary_mismatches": mismatches,
    }


# -- main --------------------------------------------------------------------


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--clients", type=int, default=16)
    p.add_argument("--per-client", type=int, default=25)
    p.add_argument("--deadline-s", type=float, default=2.0)
    # keep the model heavy enough that load-generator CPU (HTTP + JSON on
    # the same small host) is noise against engine time — a real TPU
    # summarize dispatch is ~1s/batch (BENCH round 5), so 100ms is still
    # conservatively LIGHT; a 40ms model let host jitter swing the ratio
    p.add_argument("--batch-overhead-s", type=float, default=0.100)
    p.add_argument("--per-prompt-s", type=float, default=0.005)
    p.add_argument("--max-batch", type=int, default=16)
    # window > one client re-post round trip on a small/noisy host: with a
    # 2.0s deadline and 180ms full-batch engine time, waiting up to 150ms
    # for company costs bounded latency but keeps occupancy near max_batch
    # even when CFS-throttled clients are slow to re-post (a 25ms window
    # fragmented batches to ~10 occupancy on a loaded 2-core box; the
    # server's own default stays 10ms — this is the throughput-biased
    # setting for a saturated closed loop)
    p.add_argument("--max-wait-ms", type=float, default=150.0)
    p.add_argument("--overload-workers", type=int, default=96)
    p.add_argument("--overload-s", type=float, default=3.0)
    # ~2.7 engine cycles at the default model: deep-queued requests expire
    # (deadline sheds) while the standing 96-worker backlog still overflows
    # the 64-deep queue (queue_full sheds) — a tighter deadline purges the
    # queue so fast the depth cap never trips and only one counter moves
    p.add_argument("--overload-deadline-s", type=float, default=0.5)
    p.add_argument("--per-token-s", type=float, default=0.00005,
                   help="shared-prefix arm: simulated prefill cost per "
                        "UNCACHED prompt token (prefix-cache hits skip it)")
    # in-flight arm latency split: a per-JOIN-GROUP admit prefill plus
    # per-segment dispatch overheads on top of the SYMMETRIC per-step
    # decode cost both arms pay (see inflight_phase's parity rationale)
    p.add_argument("--per-step-s", type=float, default=0.002)
    p.add_argument("--inflight-prefill-s", type=float, default=0.010)
    p.add_argument("--segment-words", type=int, default=8)
    p.add_argument("--segment-overhead-s", type=float, default=0.002)
    p.add_argument("--per-slot-segment-s", type=float, default=0.0005)
    p.add_argument("--fused-clients", type=int, default=4,
                   help="closed-loop clients for the fused sweep — small "
                        "on purpose: at low occupancy per-dispatch "
                        "overhead dominates and fusing has the most to "
                        "amortize")
    p.add_argument("--fused-slots", type=int, default=4)
    p.add_argument("--fused-segment-words", type=int, default=4,
                   help="segment granularity for the fused sweep — finer "
                        "than the r04 arm because short segments (good "
                        "join/cancel latency) maximize per-dispatch "
                        "overhead, the cost fused decode amortizes")
    p.add_argument("--fused-min-tokens-ratio", type=float, default=1.05,
                   help="exit non-zero unless the best N>1 fused arm "
                        "beats N=1 decode tokens/s-per-slot by this "
                        "ratio")
    p.add_argument("--fused-max-ttft-pct", type=float, default=150.0,
                   help="exit non-zero when the best fused arm's anchored "
                        "TTFT p50 regresses vs N=1 by more than this "
                        "percentage (joins coarsen to fused-dispatch "
                        "cadence; the regression must stay bounded)")
    p.add_argument("--inflight-min-ttft-gain", type=float, default=25.0,
                   help="exit non-zero when the in-flight arm's anchored "
                        "TTFT p50 improves less than this percentage")
    p.add_argument("--inflight-min-goodput", type=float, default=1.0,
                   help="exit non-zero when in-flight goodput falls below "
                        "this ratio of the batch-dispatch arm's")
    p.add_argument("--journal-max-overhead-pct", type=float, default=2.0,
                   help="exit non-zero when the journal-on arm's goodput "
                        "falls more than this percentage below journal-off "
                        "(CI smoke passes a softer floor: shared-runner "
                        "jitter swings single-digit percentages)")
    p.add_argument("--sharded-min-scaling", type=float, default=1.6,
                   help="exit non-zero when 2-DP-replica goodput scales "
                        "below this ratio on the mixed workload (CI smoke "
                        "passes a softer floor for shared-runner jitter)")
    # fleet phase knobs (front-door router over N engine workers)
    p.add_argument("--fleet-batch-overhead-s", type=float, default=0.25,
                   help="per-dispatch overhead charged by the fleet-phase "
                        "workers; heavier than the single-process phases "
                        "so worker capacity (not the router hop) is what "
                        "saturates, making the 1-vs-2-worker scaling "
                        "measure fan-out")
    p.add_argument("--fleet-min-scaling", type=float, default=1.6,
                   help="exit non-zero when 2-worker fleet goodput through "
                        "the router scales below this ratio vs 1 worker "
                        "(CI smoke passes a softer floor)")
    p.add_argument("--fleet-min-affinity", type=float, default=0.9,
                   help="exit non-zero when the 2-worker fleet's aggregate "
                        "prefix-cache hit rate falls below this fraction "
                        "of the single-process rate (cache-affinity "
                        "routing must keep hint reuse sticky)")
    # QoS phase knobs (multi-tenant weighted-fair scheduling + preemption)
    p.add_argument("--federation-max-overhead-pct", type=float, default=1.0,
                   help="max %% fleet goodput the federation scrape loop "
                        "may cost vs --no-federation on identical load "
                        "(measured at 5x the shipped cadence)")
    p.add_argument("--qos-slots", type=int, default=4)
    p.add_argument("--qos-interactive-clients", type=int, default=4)
    p.add_argument("--qos-batch-clients", type=int, default=12)
    p.add_argument("--qos-max-ttft-pct", type=float, default=25.0,
                   help="exit non-zero when the interactive tenant's "
                        "anchored TTFT p99 under batch saturation degrades "
                        "more than this percentage vs its unloaded "
                        "baseline (CI smoke passes a softer floor)")
    # cancellation phase knobs (cancel API + slot reclamation)
    p.add_argument("--cancel-window-s", type=float, default=2.0,
                   help="cancel phase: measurement window per regime "
                        "(idle / loaded / post-cancel)")
    p.add_argument("--cancel-batch-clients", type=int, default=8)
    p.add_argument("--cancel-min-recovery", type=float, default=0.9,
                   help="exit non-zero when post-cancel interactive "
                        "goodput recovers below this ratio of the idle "
                        "baseline (CI smoke passes a softer floor)")
    p.add_argument("--cancel-max-overhead-pct", type=float, default=1.0,
                   help="exit non-zero when the unused cancel machinery "
                        "costs more than this percentage of goodput "
                        "(sweeps on vs off, best-of-2; CI smoke passes a "
                        "softer floor for shared-runner jitter)")
    p.add_argument("--slo-max-overhead-pct", type=float, default=2.0,
                   help="exit non-zero when the full obs+SLO+usage+"
                        "recorder arm costs more than this percentage of "
                        "goodput vs the all-off arm (CI smoke passes a "
                        "softer floor for shared-runner jitter)")
    p.add_argument("--watchdog-max-overhead-pct", type=float, default=1.0,
                   help="exit non-zero when the watchdog-armed arm costs "
                        "more than this percentage of goodput vs the "
                        "watchdog-less arm (CI smoke passes a softer floor "
                        "for shared-runner jitter)")
    # structured-jobs phase knobs (gang-scheduled map->reduce fan-out)
    # 24 concurrent clients x 2 docs: the second doc per client is what
    # makes the feed CONTINUOUS — cohort 2's host work (split/format)
    # overlaps cohort 1's engine dispatches and cohort 1's reduces pack
    # into cohort 2's map batches; with one doc per client the run is a
    # single burst and the serving arm only ties the offline barrier
    p.add_argument("--gang-clients", type=int, default=24)
    p.add_argument("--gang-per-client", type=int, default=2)
    p.add_argument("--gang-per-token-s", type=float, default=0.000002,
                   help="gang phase: simulated prefill cost per uncached "
                        "prompt token — small because its map prompts are "
                        "~12k tokens (the shared-prefix phase's rate would "
                        "make each map dispatch ~600ms)")
    p.add_argument("--gang-min-speedup", type=float, default=1.05,
                   help="exit non-zero when serving-path map->reduce "
                        "docs/min falls below this ratio of the offline "
                        "blocking pipeline's (CI smoke passes a softer "
                        "floor for shared-runner jitter)")
    p.add_argument("--gang-min-affinity", type=float, default=0.9,
                   help="exit non-zero when the gang-affinity arm's "
                        "docs/min regresses below this ratio of the "
                        "affinity-off arm (near-parity is expected on the "
                        "homogeneous workload; this is a no-regression "
                        "guard, not a win claim)")
    p.add_argument("--out", default="BENCH_serving_r14.json")
    p.add_argument("--min-speedup", type=float, default=4.0,
                   help="exit non-zero below this goodput ratio (CI smoke "
                        "passes a softer floor: shared 2-core runners get "
                        "CFS-throttled mid-run, which only slows the serve "
                        "phase — the serial baseline is sleep-bound)")
    args = p.parse_args(argv)

    # per-request access logging costs real wall clock at bench rates and
    # measures the logger, not the scheduler
    logging.getLogger("vnsum.serve.http").setLevel(logging.WARNING)

    lat = dict(batch_overhead_s=args.batch_overhead_s,
               per_prompt_s=args.per_prompt_s)

    # 1) serial baseline
    serial_backend = FakeBackend(**lat)
    serial = make_serial_server(serial_backend)
    st = threading.Thread(target=serial.serve_forever, daemon=True)
    st.start()
    serial_base = f"http://127.0.0.1:{serial.server_address[1]}"
    print(f"serial baseline on {serial_base} ...", flush=True)
    serial_closed = closed_loop(
        serial_base, args.clients, args.per_client, args.deadline_s
    )
    serial.shutdown()
    serial.server_close()
    serial_closed["engine_batches"] = len(serial_backend.batch_sizes)
    serial_closed["avg_batch_occupancy"] = 1.0

    # 2) micro-batching serve server — same latency model. Tracing is OFF
    # (trace_sample=0): the goodput comparison is the acceptance criterion
    # for the obs layer's disabled-path overhead (< 2% vs the PR 1 shape);
    # the /metrics histograms are always on and snapshotted below anyway.
    serve_backend = FakeBackend(**lat)
    state = ServeState(
        serve_backend,
        max_batch=args.max_batch,
        max_wait_s=args.max_wait_ms / 1000.0,
        max_queue_depth=64,
        trace_sample=0.0,
    )
    server = make_server(state, "127.0.0.1", 0)
    vt = threading.Thread(target=server.serve_forever, daemon=True)
    vt.start()
    serve_base = f"http://127.0.0.1:{server.server_address[1]}"
    print(f"serve server on {serve_base} ...", flush=True)
    serve_closed = closed_loop(
        serve_base, args.clients, args.per_client, args.deadline_s
    )
    nb = len(serve_backend.batch_sizes)
    serve_closed["engine_batches"] = nb
    serve_closed["avg_batch_occupancy"] = (
        round(sum(serve_backend.batch_sizes) / nb, 2) if nb else 0.0
    )

    # 3) tracing-overhead arm: SAME latency model and load with full request
    # tracing on (trace_sample=1.0) — the goodput delta vs the untraced arm
    # IS the obs layer's cost, and this arm's histograms carry real anchored
    # TTFT quantiles (the untraced arm has no prefill anchor, so its TTFT
    # histogram is empty by design rather than e2e relabeled)
    traced_backend = FakeBackend(**lat)
    traced_state = ServeState(
        traced_backend,
        max_batch=args.max_batch,
        max_wait_s=args.max_wait_ms / 1000.0,
        max_queue_depth=64,
        trace_sample=1.0,
        trace_ring=64,
    )
    traced_server = make_server(traced_state, "127.0.0.1", 0)
    tt = threading.Thread(target=traced_server.serve_forever, daemon=True)
    tt.start()
    traced_base = f"http://127.0.0.1:{traced_server.server_address[1]}"
    print(f"traced serve server on {traced_base} ...", flush=True)
    serve_traced = closed_loop(
        traced_base, args.clients, args.per_client, args.deadline_s
    )
    traced_server.shutdown()
    traced_server.server_close()
    traced_hists = traced_state.scheduler.metrics.histograms_snapshot()
    traced_state.close()
    tracing_overhead_pct = (
        round(
            (serve_closed["goodput_rps"] - serve_traced["goodput_rps"])
            / serve_closed["goodput_rps"] * 100.0,
            2,
        )
        if serve_closed["goodput_rps"] else 0.0
    )

    # 4) overload: bounded queue + tight deadline -> typed sheds
    print("overload phase ...", flush=True)
    overload = overload_loop(
        serve_base, args.overload_workers, args.overload_s,
        args.overload_deadline_s,
    )
    u = urllib.parse.urlparse(serve_base)
    conn = http.client.HTTPConnection(u.hostname, u.port, timeout=10)
    conn.request("GET", "/metrics")
    metrics_text = conn.getresponse().read().decode()
    conn.close()
    shed_lines = [
        l for l in metrics_text.splitlines()
        if l.startswith("vnsum_serve_requests_shed_total")
    ]
    server.shutdown()
    server.server_close()
    state.close()

    # 5) shared-prefix workload: prefix-cache A/B (TTFT + goodput + hits)
    print("shared-prefix phase ...", flush=True)
    shared_prefix = shared_prefix_phase(args)

    # 6) in-flight batching A/B: slot-feeding vs batch dispatch
    print("in-flight phase ...", flush=True)
    inflight = inflight_phase(args)

    # 6b) fused multi-step decode: N-segment dispatch sweep (TTFT/goodput
    # trade study at small batch)
    print("fused phase ...", flush=True)
    fused = fused_phase(args)

    # 7) durable serving: write-ahead journal on/off overhead
    print("journal phase ...", flush=True)
    journal = journal_phase(args)

    # 8) multi-chip serving: DP-replica goodput scaling on the r04 shape
    print("sharded phase ...", flush=True)
    sharded = sharded_phase(args)

    # 8b) replica fleet: router fan-out scaling + cache-affinity routing
    print("fleet phase ...", flush=True)
    fleet = fleet_phase(args)

    # 8c) fleet observability: federation scrape loop on/off goodput A/B
    print("federation phase ...", flush=True)
    federation = federation_phase(args)

    # 9) multi-tenant QoS: interactive TTFT p99 under batch saturation
    print("qos phase ...", flush=True)
    qos = qos_phase(args)

    # 10) cancellation: slot reclaim on gang-cancel + unused-path overhead
    print("cancel phase ...", flush=True)
    cancel = cancel_phase(args)

    # 11) production observability: full SLO+usage+recorder stack on/off
    print("slo phase ...", flush=True)
    slo = slo_phase(args)

    # 12) liveness: watchdog heartbeat + dispatch-budget bookkeeping on/off
    print("watchdog phase ...", flush=True)
    watchdog = watchdog_phase(args)

    # 13) structured jobs: gang-scheduled streaming map->reduce vs the
    # offline blocking pipeline, plus the affinity on/off A/B
    print("gang phase ...", flush=True)
    gang = gang_phase(args)

    speedup = (
        serve_closed["goodput_rps"] / serial_closed["goodput_rps"]
        if serial_closed["goodput_rps"]
        else float("inf")
    )
    stats = state.scheduler.metrics.snapshot()
    out = {
        "bench": "serving_micro_batching_vs_serial",
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "latency_model": {
            **lat,
            "note": "FakeBackend device-dispatch model: fixed per-call + "
                    "marginal per-prompt cost; ratio is the result, not "
                    "absolute latency",
        },
        "policy": {
            "max_batch": args.max_batch,
            "max_wait_ms": args.max_wait_ms,
            "max_queue_depth": 64,
            "deadline_s": args.deadline_s,
        },
        "closed_loop": {
            "serial_baseline": serial_closed,
            "serve": serve_closed,
            "serve_traced": serve_traced,
            "goodput_speedup": round(speedup, 2),
            # the obs layer's measured cost: untraced vs fully-traced
            # goodput on the identical load (<2% is the acceptance bar)
            "tracing_overhead_pct": tracing_overhead_pct,
        },
        "overload": {
            **overload,
            "shed_counters": shed_lines,
        },
        "shared_prefix": shared_prefix,
        "inflight": inflight,
        "fused": fused,
        "journal": journal,
        "sharded": sharded,
        "fleet": fleet,
        "federation": federation,
        "qos": qos,
        "cancel": cancel,
        "slo": slo,
        "watchdog": watchdog,
        "gang": gang,
        "serving_stats": stats.to_dict(),
        # server-side histogram snapshots (vnsum_tpu.obs): bucket counts
        # plus bucket-derived p50/p95/p99 for queue wait, TTFT, e2e latency,
        # batch occupancy — quantiles from the same state /metrics scrapes,
        # not just the client-observed means above. The untraced arm's TTFT
        # histogram is empty by design (no prefill anchor); the traced arm
        # carries the real TTFT distribution
        "histograms": state.scheduler.metrics.histograms_snapshot(),
        "histograms_traced": traced_hists,
    }
    # atomic (write-temp + os.replace): the artifact is read back by the CI
    # no-worse guard — a crash mid-write must not leave a truncated JSON
    atomic_write_json(args.out, out)
    print(json.dumps(out["closed_loop"], indent=2))
    print(f"goodput speedup: {speedup:.2f}x "
          f"({serve_closed['goodput_rps']} vs {serial_closed['goodput_rps']} rps)")
    print(f"tracing overhead: {tracing_overhead_pct}% "
          f"({serve_traced['goodput_rps']} rps fully traced)")
    print(f"sheds under overload: {overload['shed']} "
          f"(metrics: {shed_lines})")
    print(
        f"shared-prefix: TTFT p50 "
        f"{shared_prefix['cache_off']['ttft_p50_s']}s -> "
        f"{shared_prefix['cache_on']['ttft_p50_s']}s "
        f"({shared_prefix['ttft_p50_improvement_pct']}% better), "
        f"goodput x{shared_prefix['goodput_ratio']}, "
        f"{shared_prefix['cache_on']['cache_hit_tokens']} hit tokens"
    )
    print(
        f"in-flight: TTFT p50 {inflight['batch_dispatch']['ttft_p50_s']}s -> "
        f"{inflight['inflight']['ttft_p50_s']}s "
        f"({inflight['ttft_p50_improvement_pct']}% better, p99 "
        f"{inflight['ttft_p99_improvement_pct']}%), goodput "
        f"x{inflight['goodput_ratio']}, {inflight['inflight']['refills']} "
        f"refills over {inflight['inflight']['segments']} segments"
    )
    best_solo = fused["solo"][f"n{fused['best_n']}"] if fused["best_n"] else None
    if best_solo:
        print(
            f"fused: best N={fused['best_n']} at "
            f"x{fused['best_tokens_ratio']} solo decode tokens/s-per-slot "
            f"vs N=1 ({best_solo['decode_tokens_per_engine_s_per_slot']} vs "
            f"{fused['solo']['n1']['decode_tokens_per_engine_s_per_slot']}; "
            f"{best_solo['segments_per_dispatch']} segments/dispatch), "
            f"mixed TTFT p50 regression "
            f"{fused['best_ttft_p50_regression_pct']}%, "
            f"byte_identical_all_n={fused['byte_identical_all_n']}"
        )
    else:
        print("fused: NO eligible N>1 arm (every mixed-load TTFT p50 "
              "regression exceeded --fused-max-ttft-pct)")
    print(
        f"journal overhead: {journal['journal_overhead_pct']}% "
        f"({journal['journal_on']['goodput_rps']} vs "
        f"{journal['journal_off']['goodput_rps']} rps, "
        f"{journal['journal_on']['journal_stats']['records']} records, "
        f"{journal['journal_on']['journal_stats']['fsyncs']} fsyncs)"
    )
    print(
        f"sharded: DP goodput x{sharded['goodput_scaling']} at 2 replicas "
        f"({sharded['dp2']['goodput_rps']} vs "
        f"{sharded['dp1']['goodput_rps']} rps)"
    )
    print(
        f"fleet: router goodput x{fleet['goodput_scaling']} at 2 workers "
        f"({fleet['fleet2']['goodput_rps']} vs "
        f"{fleet['fleet1']['goodput_rps']} rps); affinity hit-rate ratio "
        f"{fleet['affinity']['hit_rate_ratio']} "
        f"({fleet['affinity']['fleet2']['cache_hit_rate']} fleet vs "
        f"{fleet['affinity']['single']['cache_hit_rate']} single, spread "
        f"{fleet['affinity']['fleet2']['worker_requests']})"
    )
    print(
        f"federation: scrape-loop overhead "
        f"{federation['federation_overhead_pct']}% "
        f"({federation['federation_on']['goodput_rps']} vs "
        f"{federation['federation_off']['goodput_rps']} rps; "
        f"{federation['federation_on']['federation_stats']['scrapes']} "
        f"scrapes, "
        f"{federation['federation_on']['federation_stats']['errors']} "
        f"errors)"
    )
    print(
        f"qos: interactive TTFT p99 {qos['unloaded']['ttft_p99_s']}s "
        f"unloaded -> {qos['loaded']['ttft_p99_s']}s under batch "
        f"saturation ({qos['interactive_ttft_p99_degradation_pct']}% "
        f"degradation), {qos['loaded']['preemptions']} preemptions / "
        f"{qos['loaded']['batch_completed']} batch jobs completed"
    )
    print(
        f"cancel: interactive goodput {cancel['loaded_goodput_rps']} rps "
        f"under batch saturation -> {cancel['recovered_goodput_rps']} rps "
        f"after gang-cancel (x{cancel['recovery_ratio']} of the "
        f"{cancel['idle_goodput_rps']} rps idle baseline); unused-path "
        f"overhead {cancel['cancel_overhead_pct']}%"
    )
    print(
        f"slo: full obs+SLO+usage+recorder overhead "
        f"{slo['slo_overhead_pct']}% ({slo['obs_on']['goodput_rps']} vs "
        f"{slo['obs_off']['goodput_rps']} rps; "
        f"{slo['surfaces']['slo_objectives']} objectives evaluated, "
        f"{slo['surfaces']['usage_requests']} requests in the usage "
        f"ledger, {slo['surfaces']['recorder_events']} recorder events)"
    )
    print(
        f"watchdog: healthy-path overhead {watchdog['watchdog_overhead_pct']}% "
        f"({watchdog['watchdog_on']['goodput_rps']} vs "
        f"{watchdog['watchdog_off']['goodput_rps']} rps; "
        f"{watchdog['surfaces']['stalls']} stalls, heartbeats "
        f"{watchdog['surfaces']['heartbeats']})"
    )
    print(
        f"gang: serving map->reduce {gang['serving']['docs_per_min']} "
        f"docs/min vs offline {gang['offline']['docs_per_min']} "
        f"(x{gang['speedup_vs_offline']}), byte_identical="
        f"{gang['byte_identical']}; affinity on/off "
        f"x{gang['affinity_ratio']} "
        f"({gang['serving']['gang_affinity_picks']} affinity picks, "
        f"cache hit rate {gang['serving']['cache_hit_rate']} vs "
        f"{gang['affinity_off']['cache_hit_rate']})"
    )
    print(f"wrote {args.out}")
    ok = (
        speedup >= args.min_speedup
        # the offline/batch-dispatch path must stay the winner it was
        # (no-worse guard) AND the in-flight arm must beat it where it
        # claims to: anchored TTFT and goodput under identical load
        and inflight["ttft_p50_improvement_pct"] >= args.inflight_min_ttft_gain
        and inflight["goodput_ratio"] >= args.inflight_min_goodput
        # fused multi-step decode: the best N>1 arm must buy decode
        # throughput per slot at small batch with a BOUNDED anchored-TTFT
        # regression, outputs byte-identical at EVERY swept N, and the
        # fused arms must actually have fused (segments > dispatches)
        and fused["best_n"] > 0
        and fused["best_tokens_ratio"] >= args.fused_min_tokens_ratio
        and fused["best_ttft_p50_regression_pct"] <= args.fused_max_ttft_pct
        and fused["byte_identical_all_n"]
        and all(fused["solo"][f"n{n}"]["segments"]
                > fused["solo"][f"n{n}"]["fused_dispatches"]
                for n in fused["sweep"] if n > 1)
        # durability tax stays inside the acceptance bar
        and journal["journal_overhead_pct"] <= args.journal_max_overhead_pct
        # multi-chip serving: 2 DP replicas must actually scale goodput
        and sharded["goodput_scaling"] >= args.sharded_min_scaling
        # replica fleet: the front door must buy capacity at 2 workers and
        # cache-affinity routing must keep shared-prefix reuse sticky
        and fleet["goodput_scaling"] >= args.fleet_min_scaling
        and fleet["affinity"]["hit_rate_ratio"] >= args.fleet_min_affinity
        # fleet observability: the federation scrape loop must be ~free
        # against fleet goodput, and the armed arm must actually have
        # scraped cleanly (a loop that never ran proved nothing)
        and federation["federation_overhead_pct"]
        <= args.federation_max_overhead_pct
        and federation["federation_on"]["federation_stats"]["scrapes"] > 0
        and federation["federation_on"]["federation_stats"]["errors"] == 0
        # multi-tenant QoS: the interactive tail must hold under batch
        # saturation, and the preemption path must actually have fired
        # (a run that never preempted proved nothing)
        and qos["interactive_ttft_p99_degradation_pct"] <= args.qos_max_ttft_pct
        and qos["loaded"]["preemptions"] > 0
        # cancellation: the gang-cancel must hand the engine back (and
        # have actually cancelled something), and the machinery must be
        # ~free when unused
        and cancel["recovery_ratio"] >= args.cancel_min_recovery
        and sum(cancel["cancels"].values()) > 0
        and cancel["cancel_overhead_pct"] <= args.cancel_max_overhead_pct
        # full observability stack stays inside the overhead bar, and the
        # armed arm's surfaces actually carried the load (a dormant "on"
        # arm would make the A/B vacuous)
        and slo["slo_overhead_pct"] <= args.slo_max_overhead_pct
        and slo["surfaces"]["slo_objectives"] == 4
        and slo["surfaces"]["usage_requests"] > 0
        and slo["surfaces"]["recorder_events"] > 0
        # watchdog bookkeeping stays inside the healthy-path bar, the armed
        # arm's surfaces were live (heartbeat registered, /healthz line),
        # and clean load produced ZERO stalls (false-positive immunity)
        and watchdog["watchdog_overhead_pct"] <= args.watchdog_max_overhead_pct
        and watchdog["surfaces"]["stalls"] == 0
        and "scheduler" in watchdog["surfaces"]["heartbeats"]
        and watchdog["surfaces"]["healthz_watchdog"] is not None
        # structured jobs: the serving-path map->reduce must beat the
        # offline blocking pipeline on docs/min with BYTE-IDENTICAL
        # summaries, affinity must not cost throughput, and the affinity
        # pick must actually have clustered siblings (a run with zero
        # picks proved nothing about the mechanism)
        and gang["speedup_vs_offline"] >= args.gang_min_speedup
        and gang["byte_identical"]
        and gang["affinity_ratio"] >= args.gang_min_affinity
        and gang["serving"]["gang_affinity_picks"] > 0
    )
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
