"""The north star, measured — full 151-doc VN-LongSum-scale eval on ONE chip
(VERDICT r4 missing #1 / next #1).

BASELINE.md's target is the full 151-document evaluation (reference serial
loop: 50+ min for summarization alone, run_full_evaluation_pipeline.py:417
workload; target <10 min on v5e-8). Every prior artifact ran 16 or 4 docs
and extrapolated. This script RUNS it: the complete 151-doc mapreduce
pipeline (summarize + ROUGE/BERTScore/semantic eval + report) plus the
summarize phase of the other four approaches, on the same synthetic
VN-LongSum-shaped corpus (37k words/doc, ragged ±25%) with a real BPE
tokenizer, on one v5e chip.

Reuses bench.py's exact e2e configuration (e2e_engine_kwargs: llama32-3b
int8 + int8 KV, B=8, S=8192 bucket, sampled decode with a ragged EOS) so
the number is directly comparable to BENCH history.

Writes artifacts/north_star_151.json.
"""
from __future__ import annotations

import argparse
import gc
import json
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

REFERENCE_SUMMARIZE_MIN = 50.0  # BASELINE.md: reference full-eval summarize

from vnsum_tpu.core.artifacts import atomic_write_json  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="artifacts/north_star_151.json")
    ap.add_argument("--docs", type=int, default=151)
    ap.add_argument(
        "--approaches",
        default="mapreduce,truncated,iterative,mapreduce_hierarchical,"
                "mapreduce_critique,skeleton",
    )
    ap.add_argument("--engine-batch", type=int, default=0,
                    help="override e2e engine batch_size (0 = default)")
    ap.add_argument("--engine-chunk", type=int, default=-1,
                    help="override prefill_chunk_tokens (-1 = default)")
    ap.add_argument("--doc-group", type=int, default=32,
                    help="pipeline doc_group_size (32 measured best: one "
                         "giant group REGRESSES ~1.6x — see north-star "
                         "config_note; -1 = all docs in one group, 0 = "
                         "library default of 4x batch)")
    args = ap.parse_args()

    import bench
    from vnsum_tpu.backend.engine import TpuBackend
    from vnsum_tpu.core.config import (
        GenerationConfig,
        PipelineConfig,
    )
    from vnsum_tpu.core.jax_cache import enable_compilation_cache
    from vnsum_tpu.data.synthesize import synthesize_corpus
    from vnsum_tpu.models.fixtures import train_bpe_tokenizer
    from vnsum_tpu.pipeline.runner import PipelineRunner

    enable_compilation_cache()
    rec: dict = {
        "what": "full 151-doc VN-LongSum-scale eval, one v5e chip",
        "docs": args.docs,
    }

    import tempfile

    root = tempfile.mkdtemp(prefix="vnsum_northstar_")
    t0 = time.time()
    stats = synthesize_corpus(
        f"{root}/corpus", n_docs=args.docs,
        tokens_per_doc=bench.E2E_WORDS_PER_DOC, summary_tokens=714,
        seed=7, ragged=0.5,
    )
    rec["corpus"] = {
        "synth_seconds": round(time.time() - t0, 1),
        "avg_words_per_doc": round(
            stats["documents"]["avg_tokens_per_file"]
        ),
    }
    print(f"corpus: {rec['corpus']}", file=sys.stderr)

    t0 = time.time()
    doc_paths = sorted(Path(f"{root}/corpus/doc").glob("*.txt"))
    hf_tok = train_bpe_tokenizer(
        (p.read_text(encoding="utf-8") for p in doc_paths), vocab_size=4096
    )
    hf_tok.save_pretrained(f"{root}/tok")
    tok_spec = f"hf:{root}/tok"
    sample = doc_paths[0].read_text(encoding="utf-8")
    bytes_per_tok = len(sample.encode()) / len(hf_tok.encode(sample))
    rec["tokenizer"] = {
        "train_seconds": round(time.time() - t0, 1),
        "bytes_per_token": round(bytes_per_tok, 2),
    }

    ekw = bench.e2e_engine_kwargs(tok_spec, None)
    if args.engine_batch:
        ekw["batch_size"] = args.engine_batch
    if args.engine_chunk >= 0:
        ekw["prefill_chunk_tokens"] = args.engine_chunk
    rec["engine_overrides"] = {
        k: ekw[k] for k in ("batch_size", "prefill_chunk_tokens")
    }
    backend = TpuBackend(**ekw)

    # ragged-EOS probe (bench.py's procedure): sampled decode over a
    # random-init model needs a declared EOS that fires at scattered depths
    raw = b" ".join(
        p.read_text(encoding="utf-8").encode("utf-8") for p in doc_paths[:3]
    )
    step = int(7_300 * bytes_per_tok)
    probe = backend.generate(
        [
            "Tóm tắt: " + raw[i * step : (i + 1) * step].decode("utf-8", "ignore")
            for i in range(8)
        ],
        config=GenerationConfig(temperature=1.0, seed=11),
    )
    eos = bench._pick_ragged_eos(probe, backend.tok)
    backend.gen_cfg = GenerationConfig(
        max_new_tokens=128, temperature=1.0, seed=11, eos_ids=eos
    )
    rec["compile_seconds_probe_phase"] = round(
        backend.stats.compile_seconds, 1
    )

    approaches = args.approaches.split(",")
    per_approach: dict = {}
    out_p = Path(args.out)
    if out_p.exists():
        # partial rerun (e.g. refreshing only the mapreduce arm after an
        # engine-default change): keep previously measured approaches,
        # tagged with the config they ran under — and carry the mapreduce
        # run HISTORY and best_measured through too, so a rerun that skips
        # mapreduce doesn't silently drop the evidence behind the headline
        prev_all = json.loads(out_p.read_text())
        prev = prev_all.get("approaches", {})
        for k, v in prev.items():
            if k not in approaches:
                per_approach[k] = v
        if prev_all.get("mapreduce_run_history"):
            rec["mapreduce_run_history"] = prev_all["mapreduce_run_history"]
        if prev_all.get("best_measured"):
            rec["best_measured"] = prev_all["best_measured"]
    for approach in approaches:
        full_eval = approach == "mapreduce"  # the headline gets the full
        # eval chain; the other four run their summarize phase (VERDICT
        # wording), which is where the reference's 50 min went
        cfg = PipelineConfig(
            approach=approach,
            models=["llama3.2-3b"],
            backend="tpu",
            docs_dir=f"{root}/corpus/doc",
            summary_dir=f"{root}/corpus/summary",
            generated_summaries_dir=f"{root}/gen_{approach}",
            results_dir=f"{root}/results_{approach}",
            logs_dir=f"{root}/logs",
            chunk_size=7_800,
            chunk_overlap=200,
            iterative_chunk_size=7_800,
            iterative_chunk_overlap=200,
            token_max=6_000,
            max_new_tokens=128,
            # keep the pipeline's grouping in sync with the ENGINE batch:
            # batch_size=8 here left doc groups at 32 while the engine
            # dispatched 16-row batches — half-filled collapse rounds and a
            # 23-doc tail group at 2x the per-doc cost (run log,
            # pipeline_run_20260731_125629). One group = maximal dispatch
            # fill for the fixed 151-doc artifact workload.
            batch_size=ekw["batch_size"],
            doc_group_size=(args.docs if args.doc_group == -1
                            else args.doc_group),
            tokenizer=tok_spec,
            tree_json_path=f"{root}/corpus/document_tree.json",
        )
        runner = PipelineRunner(cfg, backend_factory=lambda model: backend)
        compile_before = backend.stats.compile_seconds
        # snapshot the engine counters so this approach's engine_stats are
        # DELTAS: one shared backend serves every approach, and cumulative
        # by_bucket/phase_seconds previously contaminated each row with all
        # the approaches (and the EOS probe) that ran before it
        bucket_before = dict(backend.stats.by_bucket)
        phase_before = dict(backend.stats.phase_seconds)
        generate_before = backend.stats.generate_seconds
        t0 = time.time()
        if full_eval:
            results = runner.run()
            elapsed = time.time() - t0
            rec_m = results.summarization["llama3.2-3b"]
            spans = results.tracing.get("spans", {})
            budget = {
                name: round(s["total_s"], 1)
                for name, s in spans.items()
                if name.split("/")[0] in ("analyze", "summarize", "evaluate")
            }
            ev = results.evaluation.get("llama3.2-3b", {})
            row = {
                "mode": "summarize+evaluate+report",
                "docs_ok": rec_m["successful"],
                "docs_failed": rec_m["failed"],
                "chunks": rec_m["total_chunks"],
                "wall_seconds": round(elapsed, 1),
                "wall_minutes": round(elapsed / 60, 2),
                "docs_per_min": round(
                    rec_m["successful"] / (elapsed / 60), 2
                ),
                "time_budget": budget,
                "rougeL_f1": ev.get("rouge_scores", {}).get("rougeL_f1"),
                "summarize_seconds": budget.get("summarize"),
            }
        else:
            rec_m = runner.run_summarization_for_model("llama3.2-3b")
            elapsed = time.time() - t0
            row = {
                "mode": "summarize-only",
                "docs_ok": rec_m.successful,
                "docs_failed": rec_m.failed,
                "chunks": rec_m.total_chunks,
                "llm_calls": sum(
                    d.llm_calls for d in rec_m.processing_details
                ),
                "wall_seconds": round(elapsed, 1),
                "wall_minutes": round(elapsed / 60, 2),
                "docs_per_min": round(rec_m.successful / (elapsed / 60), 2),
            }
        row["compile_seconds_in_phase"] = round(
            backend.stats.compile_seconds - compile_before, 1
        )
        # engine-level attribution: bucket mix + host/device phase seconds
        # (who ate the wall — dispatches, tokenize, or strategy host code),
        # as per-approach DELTAS against the snapshot above
        st = backend.stats
        row["engine_stats"] = {
            "by_bucket": {
                f"B{b}xS{s}": n - bucket_before.get((b, s), 0)
                for (b, s), n in sorted(st.by_bucket.items())
                if n - bucket_before.get((b, s), 0)
            },
            "phase_seconds": {
                k: round(v - phase_before.get(k, 0.0), 1)
                for k, v in sorted(st.phase_seconds.items())
            },
            "generate_seconds": round(
                st.generate_seconds - generate_before, 1
            ),
        }
        if row["docs_ok"] == 0:
            raise RuntimeError(f"{approach}: all documents failed")
        per_approach[approach] = row
        if approach == "mapreduce":
            # run-to-run history: the shared axon host's per-dispatch
            # latency varies hour to hour (tokenize_host on identical
            # code/data has measured 13.5-19.2 s), so single runs are
            # samples — keep them all, headline reports the latest and
            # best_measured the minimum
            # prior runs' entries were carried into rec by the resume block
            # up top, so a fresh measurement only ever APPENDS
            hist = rec.setdefault("mapreduce_run_history", [])
            hist.append({
                "timestamp": time.strftime("%Y-%m-%dT%H:%M:%S"),
                "wall_minutes": row["wall_minutes"],
                "generate_seconds":
                    row["engine_stats"]["generate_seconds"],
                "tokenize_host_s":
                    row["engine_stats"]["phase_seconds"].get(
                        "tokenize_host"),
            })
        print(f"{approach}: {json.dumps(row)}", file=sys.stderr)
        # checkpoint the artifact after every approach — a crash mid-run
        # must not lose measured phases (resume-by-file covers the rest)
        rec["approaches"] = per_approach
        rec["timestamp"] = time.strftime("%Y-%m-%dT%H:%M:%S")
        Path(args.out).parent.mkdir(parents=True, exist_ok=True)
        atomic_write_json(args.out, rec)
        gc.collect()

    # script-owned provenance: a partial rerun must never drop the
    # measurement conditions (a hand-added note was lost this way once)
    rec["config_note"] = (
        "measured under the round-5 FINAL stack: "
        f"engine batch_size={ekw['batch_size']}, "
        f"prefill_chunk_tokens={ekw.get('prefill_chunk_tokens')}, W8A8 "
        f"(quantize_act={ekw.get('quantize_act')}), group-major flash "
        "prefill kernel (bq=512/bk=2048 defaults at hd=128), batched host "
        "tokenization (engine encode_batch + splitter per-level counts), "
        f"doc_group_size={args.docs if args.doc_group == -1 else args.doc_group or '4x batch'}. "
        "Doc-group sweep: one giant 151-doc group regresses mapreduce "
        "~1.6x vs groups of 32 (recorded negative). Approaches absent "
        "from --approaches keep their previously measured rows."
    )
    mr = per_approach.get("mapreduce", {})
    hist = rec.get("mapreduce_run_history", [])
    if hist:
        rec["best_measured"] = min(hist, key=lambda h: h["wall_minutes"])
    if mr:
        rec["headline"] = {
            "full_eval_minutes_one_chip": mr["wall_minutes"],
            "summarize_minutes_one_chip": round(
                (mr.get("summarize_seconds") or 0) / 60, 2
            ),
            "reference_summarize_minutes": REFERENCE_SUMMARIZE_MIN,
            "vs_reference_summarize": round(
                REFERENCE_SUMMARIZE_MIN * 60
                / max(mr.get("summarize_seconds") or 1, 1), 2
            ),
            "note": (
                "single-chip measured run; the <10-min v5e-8 target "
                "projects from this with the MULTICHIP dryrun's DP scaling"
            ),
        }
    atomic_write_json(args.out, rec)
    print(json.dumps({"ok": True, "headline": rec.get("headline"),
                      "approaches": {
                          k: v["wall_minutes"] for k, v in per_approach.items()
                      }}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
