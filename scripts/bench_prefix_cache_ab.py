#!/usr/bin/env python
"""Hermetic A/B bench for the radix prefix KV cache (vnsum_tpu.cache).

    JAX_PLATFORMS=cpu python scripts/bench_prefix_cache_ab.py \
        --out BENCH_cache_r01.json

What it proves (the ISSUE 6 acceptance criteria):

1. **Lossless**: greedy outputs with the cache on are byte-identical to the
   uncached engine in the cold (insert), warm (resume-prefill), and
   post-eviction (tight block budget, constant churn) arms;
2. **Profitable on shared-prefix workloads**: replaying the map fan-out of
   an already-seen document (the multi-user / retry regime the serving
   layer exists for) skips >= 30% of prefill tokens on the warm pass, and
   the instrumented prefill phase — the TTFT driver — gets measurably
   faster. A supplementary hinted arm shows cache_hint bounding insertion
   to the shared template header (the cross-DOCUMENT regime): the pool
   holds only header blocks, and reuse equals the header share.

Hermetic setup: a tiny random-init Llama on CPU. Determinism is all that
byte-identity needs; no trained fixture required. The workload mirrors what
the strategies actually emit: map prompts formatted from the Vietnamese
MAPREDUCE_MAP template (strategies/prompts.py).
"""
from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from vnsum_tpu.strategies.prompts import MAPREDUCE_MAP, template_header  # noqa: E402

CONTENT = (
    "Quốc hội đã thông qua nghị quyết về phát triển kinh tế xã hội với "
    "nhiều giải pháp trọng tâm cho người dân ở các vùng khó khăn. "
)


def make_workload(n: int, rep: int):
    """Map-stage-shaped prompts: shared template header + unique content."""
    hint = template_header(MAPREDUCE_MAP)
    prompts = [
        MAPREDUCE_MAP.format(content=CONTENT * rep + f"Đoạn số {i}.")
        for i in range(n)
    ]
    return prompts, [hint] * n


def run_arm(backend, prompts, hints, label: str):
    st = backend.stats
    base_hit, base_miss = st.cache_hit_tokens, st.cache_miss_tokens
    t0 = time.time()
    outs = backend.generate(prompts, cache_hints=hints)  # hints may be None
    wall = time.time() - t0
    hit = st.cache_hit_tokens - base_hit
    miss = st.cache_miss_tokens - base_miss
    total = hit + miss if (hit + miss) else st.prompt_tokens
    return {
        "arm": label,
        "wall_s": round(wall, 3),
        "prompt_tokens": total,
        "cached_prefill_tokens": hit,
        "prefill_token_reduction": round(hit / total, 4) if total else 0.0,
        "outputs_preview": [o[:40] for o in outs[:2]],
    }, outs


def prefill_seconds(backend, prompts, hints, reps: int) -> float:
    """Min instrumented prefill-phase seconds over ``reps`` calls — the
    device-time TTFT driver, measured with the engine's own result-fetch
    sync (instrument=True), min-of-reps against CPU scheduling noise."""
    best = float("inf")
    for _ in range(reps):
        before = backend.stats.phase_seconds.get("prefill", 0.0)
        backend.generate(prompts, cache_hints=hints)
        best = min(
            best, backend.stats.phase_seconds.get("prefill", 0.0) - before
        )
    return best


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--out", default="BENCH_cache_r01.json")
    p.add_argument("--prompts", type=int, default=16)
    p.add_argument("--header-rep", type=int, default=4,
                   help="content repetitions (sets the unique-tail size)")
    p.add_argument("--max-new", type=int, default=24)
    p.add_argument("--cache-blocks", type=int, default=64)
    p.add_argument("--block-tokens", type=int, default=64)
    p.add_argument("--timing-reps", type=int, default=3)
    args = p.parse_args()

    from vnsum_tpu.backend.engine import TpuBackend
    from vnsum_tpu.models import jitted_init, tiny_llama
    from vnsum_tpu.models.llama import init_params

    cfg = tiny_llama(max_seq_len=2048)
    params = jitted_init(init_params, cfg, 0)
    prompts, hints = make_workload(args.prompts, args.header_rep)
    header_tokens = len(hints[0].encode("utf-8")) + 1
    prompt_tokens = [len(p.encode("utf-8")) + 1 for p in prompts]

    def backend(**kw):
        return TpuBackend(
            model_config=cfg, params=params, batch_size=8,
            max_new_tokens=args.max_new, seed=0, **kw,
        )

    # 1) uncached reference
    base = backend()
    plain, outs_ref = run_arm(base, prompts, hints, "uncached")
    run_arm(base, prompts, hints, "uncached_repeat")  # steady-state wall

    # 2) cached, unhinted: cold pass inserts whole prompts (LRU-managed),
    # warm pass resumes — the multi-user-same-document / retry regime
    cached = backend(cache_blocks=args.cache_blocks,
                     cache_block_tokens=args.block_tokens)
    cold, outs_cold = run_arm(cached, prompts, None, "cached_cold")
    warm, outs_warm = run_arm(cached, prompts, None, "cached_warm")
    pool = cached.prefix_cache_stats()

    # 2b) hinted: insertion bounded to the shared template header — the
    # cross-document regime where only the header recurs. Outputs must
    # still match; reuse equals the header share of each prompt.
    hinted = backend(cache_blocks=args.cache_blocks,
                     cache_block_tokens=args.block_tokens)
    run_arm(hinted, prompts, hints, "hinted_cold")
    hint_warm, outs_hint = run_arm(hinted, prompts, hints, "hinted_warm")
    hinted_pool = hinted.prefix_cache_stats()

    # 3) post-eviction: a pool too small for even one header, churned by an
    # unrelated workload between passes — outputs must never move
    tight = backend(cache_blocks=3, cache_block_tokens=args.block_tokens)
    run_arm(tight, prompts, None, "tight_cold")
    other = ["Văn bản hoàn toàn khác biệt. " * 30 + f"Tài liệu {i}."
             for i in range(8)]
    tight.generate(other)
    evict, outs_evict = run_arm(tight, prompts, None, "post_eviction")
    evictions = tight.prefix_cache_stats()["evictions"]

    # 4) TTFT driver: instrumented prefill-phase seconds, warm cache vs none
    inst_base = backend(instrument=True)
    t_plain = prefill_seconds(inst_base, prompts, None, args.timing_reps)
    inst_cached = backend(instrument=True, cache_blocks=args.cache_blocks,
                          cache_block_tokens=args.block_tokens)
    inst_cached.generate(prompts)  # warm the pool
    t_warm = prefill_seconds(inst_cached, prompts, None, args.timing_reps)
    ttft_speedup = t_plain / t_warm if t_warm else float("inf")

    identical = {
        "cold": outs_cold == outs_ref,
        "warm": outs_warm == outs_ref,
        "hinted_warm": outs_hint == outs_ref,
        "post_eviction": outs_evict == outs_ref,
    }
    reduction = warm["prefill_token_reduction"]
    checks = {
        "greedy_outputs_identical_all_arms": all(identical.values()),
        "prefill_token_reduction_ge_30pct": reduction >= 0.30,
        "prefill_phase_faster_with_cache": t_warm < t_plain,
        "eviction_exercised": evictions > 0,
    }
    result = {
        "bench": "prefix_cache_ab",
        "round": 1,
        "setup": {
            "model": "tiny_llama(max_seq_len=2048), random init, greedy",
            "workload": "MAPREDUCE_MAP header shared by every prompt + "
                        "unique Vietnamese content tails (map fan-out shape)",
            "prompts": args.prompts,
            "header_tokens": header_tokens,
            "prompt_tokens_mean": round(sum(prompt_tokens) / len(prompt_tokens), 1),
            "max_new_tokens": args.max_new,
            "cache": {"blocks": args.cache_blocks,
                      "block_tokens": args.block_tokens},
            "platform": "cpu-hermetic (token-count evidence; prefill "
                        "seconds are instrument=True phase times)",
        },
        "arms": {
            "uncached": plain,
            "cached_cold": cold,
            "cached_warm": warm,
            "hinted_warm": hint_warm,
            "post_eviction": evict,
        },
        "pool_after_warm": pool,
        "hinted_pool": hinted_pool,
        "eviction_arm": {"cache_blocks": 3, "evictions": evictions},
        "ttft_driver": {
            "prefill_s_uncached": round(t_plain, 4),
            "prefill_s_warm_cache": round(t_warm, 4),
            "prefill_speedup": round(ttft_speedup, 2),
            "reps": args.timing_reps,
        },
        "identical": identical,
        "checks": checks,
    }
    Path(args.out).write_text(
        json.dumps(result, indent=2, ensure_ascii=False) + "\n",
        encoding="utf-8",
    )
    print(json.dumps(checks, indent=2))
    print(
        f"warm pass: {warm['cached_prefill_tokens']} of "
        f"{warm['prompt_tokens']} prefill tokens served from cache "
        f"({reduction:.0%}); prefill phase {t_plain:.3f}s -> {t_warm:.3f}s "
        f"({ttft_speedup:.2f}x); {evictions} evictions in the tight arm"
    )
    ok = all(checks.values())
    print("PASS" if ok else "FAIL")
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
