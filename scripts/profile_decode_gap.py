"""Decompose the decode roofline gap (VERDICT r4 next #5).

BENCH_r04 decode runs at 0.72-0.74 of the HBM roofline at the e2e shape
(llama32-3b int8 + int8 KV, B=8, S=8192, C=8448, max_new=128) and nothing
attributed the missing ~26%. This script measures the SAME engine programs
with one knob changed per arm, all instrument=True (decode as one dispatch,
fetch-synced), so each delta isolates one term:

  A  baseline       — e2e_engine_kwargs exact (temperature 1.0, BPE-4096)
  B  greedy         — temperature 0.0: categorical-sampling cost = A - B
  C  vocab-8k       — model vocab_size 8192: lm_head/embed width cost
  D  window-256     — all layers sliding_window=256: decode attention now
                      reads ~256 cache positions instead of ~8300, so
                      cache-stream cost = A - D (weights+overheads remain)
  E  kernel-direct  — flash_decode_attention standalone on the full-size
                      int8 cache, 32 steps in one jit: the kernel's own
                      achieved HBM bandwidth, no model around it

Roofline bookkeeping per arm: mandatory decode bytes/step = int8 weight
bytes + K/V bytes up to fill + scale bytes. v5e numbers from bench.py
(819 GB/s, PERF.md measurement hygiene).

Writes artifacts/decode_gap_r5.json.
"""
from __future__ import annotations

import argparse
import dataclasses
import gc
import json
import sys
import tempfile
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

HBM_BYTES_PER_S = 819e9  # bench.py v5e-1 number


def weight_bytes(params) -> int:
    import jax

    return sum(int(l.nbytes) for l in jax.tree.leaves(params))


def cache_bytes(cfg, B: int, fill: int, quantized: bool) -> int:
    # decode attention streams K and V up to the fill point each step
    kv = cfg.n_layers * B * cfg.n_kv_heads * fill * cfg.head_dim * 2
    if not quantized:
        return kv * 2  # bf16
    return kv + cfg.n_layers * B * cfg.n_kv_heads * fill * 4 * 2  # int8+f32 scales


def run_arm(label: str, cfg, tok_spec, gen_cfg, prompts, max_new: int) -> dict:
    import numpy as np

    from vnsum_tpu.backend.engine import EngineStats, TpuBackend

    be = TpuBackend(
        model_config=cfg, tokenizer=tok_spec, batch_size=8,
        max_new_tokens=max_new, quantize=True, instrument=True,
    )
    t0 = time.time()
    be.generate(prompts, config=gen_cfg)  # compile + warm
    compile_s = time.time() - t0
    be.stats = EngineStats()
    t1 = time.time()
    be.generate(prompts, config=gen_cfg)
    wall = time.time() - t1
    st = be.stats
    steps = sum(d["steps"] for d in st.dispatches)
    dec = st.phase_seconds.get("decode", 0.0)
    pre = st.phase_seconds.get("prefill", 0.0)
    ms_per_step = dec / steps * 1e3 if steps else 0.0
    wb = weight_bytes(be.params)
    # average fill across the decode: S + max_new/2 — clamped to the sliding
    # window when every layer is windowed (arm D), since the kernel's DMA
    # clamp means positions beyond the window are never read
    S = st.dispatches[0]["S"] if st.dispatches else 0
    fill = S + max_new // 2
    if cfg.sliding_window and not any(cfg.layer_is_global):
        fill = min(fill, cfg.sliding_window)
    cb = cache_bytes(cfg, st.dispatches[0]["B"] if st.dispatches else 8,
                     fill, be.quantize_kv)
    mandatory = wb + cb
    roofline_ms = mandatory / HBM_BYTES_PER_S * 1e3
    row = {
        "label": label,
        "compile_and_warm_s": round(compile_s, 1),
        "wall_s": round(wall, 2),
        "prefill_s": round(pre, 2),
        "decode_s": round(dec, 3),
        "decode_steps": steps,
        "ms_per_step": round(ms_per_step, 3),
        "weight_bytes": wb,
        "cache_bytes_at_mid_fill": cb,
        "roofline_ms_per_step": round(roofline_ms, 3),
        "roofline_frac": round(roofline_ms / ms_per_step, 4) if ms_per_step else 0,
        "dispatches": st.dispatches,
    }
    print(f"{label}: {json.dumps({k: row[k] for k in ('decode_s','ms_per_step','roofline_frac')})}",
          file=sys.stderr)
    del be
    gc.collect()
    return row


def run_kernel_direct(cfg, B: int, C: int, steps: int = 32) -> dict:
    """flash_decode_attention alone on a full int8 cache: the kernel's own
    achieved bandwidth at the e2e cache shape."""
    import jax
    import jax.numpy as jnp

    from vnsum_tpu.models.llama import init_kv_cache
    from vnsum_tpu.ops.decode_attention import flash_decode_attention

    cache = init_kv_cache(cfg, B, C, quantized=True)
    # nonzero fill (values AND scales at 1.0) keeps the dequantized math
    # finite; bandwidth is layout-determined, not value-determined. The
    # cache is an ARGUMENT of the jitted loop — captured as a closure
    # constant it gets baked into the program (4 GB of lowering constants)
    # and the measurement stops being a pure HBM-stream read
    cache = {k: jnp.ones_like(v) for k, v in cache.items()}
    pad_lens = jnp.zeros((B,), jnp.int32)
    fill = jnp.int32(C - 1)
    H, hd = cfg.n_heads, cfg.head_dim
    L = cfg.n_layers

    def loop_fn(q, cache):
        def body(q, i):
            # cycle through the layers like the model does (i % L), so the
            # stream touches the whole stacked cache; q depends on the
            # previous output so steps serialize (no CSE)
            o = flash_decode_attention(
                q, cache, (i % L).astype(jnp.int32), pad_lens, fill,
                cfg.q_per_kv, None,
            )
            return o * 1e-3 + q, None

        return jax.lax.scan(body, q, jnp.arange(steps), length=steps)[0]

    q0 = jnp.ones((B, 1, H, hd), jnp.bfloat16)
    loop = jax.jit(loop_fn)
    import numpy as np

    np.asarray(loop(q0, cache))  # compile + warm
    t0 = time.time()
    out = loop(q0, cache)
    np.asarray(out)
    dt = time.time() - t0
    # one layer per step: bytes = B*KV*C*hd*2 int8 + scales
    per_step = B * cfg.n_kv_heads * C * cfg.head_dim * 2 + B * cfg.n_kv_heads * C * 4 * 2
    bw = per_step * steps / dt
    return {
        "label": "kernel_direct_layer0",
        "steps": steps,
        "seconds": round(dt, 3),
        "bytes_per_step_one_layer": per_step,
        "achieved_gb_per_s": round(bw / 1e9, 1),
        "frac_of_819": round(bw / HBM_BYTES_PER_S, 4),
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="artifacts/decode_gap_r5.json")
    ap.add_argument("--max-new", type=int, default=128)
    ap.add_argument("--arms", default="A,B,C,D,E")
    args = ap.parse_args()
    arms = set(args.arms.split(","))

    from vnsum_tpu.core.config import GenerationConfig
    from vnsum_tpu.core.jax_cache import enable_compilation_cache
    from vnsum_tpu.data.synthesize import synthesize_corpus
    from vnsum_tpu.models.fixtures import train_bpe_tokenizer
    from vnsum_tpu.models.llama import llama32_3b

    enable_compilation_cache()
    prompts: list[str] = []
    tok_spec = "byte"
    if arms & set("ABCD"):  # the kernel-direct arm needs none of this
        root = tempfile.mkdtemp(prefix="vnsum_decgap_")
        synthesize_corpus(
            f"{root}/corpus", n_docs=4, tokens_per_doc=9_000,
            summary_tokens=200, seed=7, ragged=0.0,
        )
        doc_paths = sorted(Path(f"{root}/corpus/doc").glob("*.txt"))
        hf_tok = train_bpe_tokenizer(
            (p.read_text(encoding="utf-8") for p in doc_paths),
            vocab_size=4096,
        )
        hf_tok.save_pretrained(f"{root}/tok")
        tok_spec = f"hf:{root}/tok"

        # 8 prompts that land in the S=8192 bucket (the e2e dominant shape)
        words = " ".join(
            p.read_text(encoding="utf-8") for p in doc_paths
        ).split()
        for i in range(8):
            seg = " ".join(words[i * 7000 : i * 7000 + 7400])
            prompts.append("Tóm tắt văn bản sau: " + seg)

    cfg = llama32_3b(max_seq_len=8448)
    sampled = GenerationConfig(temperature=1.0, seed=11)
    greedy = GenerationConfig(temperature=0.0)

    rows = []
    if "A" in arms:
        rows.append(run_arm("A_baseline", cfg, tok_spec, sampled, prompts,
                            args.max_new))
    if "B" in arms:
        rows.append(run_arm("B_greedy", cfg, tok_spec, greedy, prompts,
                            args.max_new))
    if "C" in arms:
        small_head = dataclasses.replace(cfg, vocab_size=8192)
        rows.append(run_arm("C_vocab8k", small_head, tok_spec, sampled,
                            prompts, args.max_new))
    if "D" in arms:
        windowed = dataclasses.replace(
            cfg, sliding_window=256,
            layer_is_global=(False,) * cfg.n_layers,
        )
        rows.append(run_arm("D_window256", windowed, tok_spec, sampled,
                            prompts, args.max_new))
    kernel_row = None
    if "E" in arms:
        kernel_row = run_kernel_direct(cfg, B=8, C=8448, steps=112)
        print(f"E: {json.dumps(kernel_row)}", file=sys.stderr)

    out_path = Path(args.out)
    if out_path.exists() and arms != set("ABCDE"):
        # partial rerun (e.g. --arms E after a fixed kernel-direct): keep
        # the measured rows that were not re-run
        prev = json.loads(out_path.read_text())
        have = {r["label"] for r in rows}
        rows = rows + [r for r in prev.get("arms", []) if r["label"] not in have]
        if kernel_row is None:
            kernel_row = prev.get("kernel_direct")
    rec = {
        "what": "decode roofline gap decomposition at the e2e shape",
        "hbm_bytes_per_s_assumed": HBM_BYTES_PER_S,
        "arms": rows,
        "kernel_direct": kernel_row,
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%S"),
    }
    by = {r["label"].split("_")[0]: r for r in rows}
    if {"A", "B", "C", "D"} <= set(by):
        a = by["A"]["ms_per_step"]
        rec["attribution_ms_per_step"] = {
            "total": a,
            "sampling_categorical": round(a - by["B"]["ms_per_step"], 3),
            "vocab_width_head": round(a - by["C"]["ms_per_step"], 3),
            "cache_stream_attention": round(a - by["D"]["ms_per_step"], 3),
            "weights_plus_residue": round(by["D"]["ms_per_step"], 3),
        }
    out = Path(args.out)
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_text(json.dumps(rec, indent=2))
    print(json.dumps({"ok": True, "arms": [r["label"] for r in rows]}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
