"""A/B: W8A8 prefill (s8xs8 MXU dots) vs weight-only int8, on the chip.

Measured motivation (PERF.md finding 14): the e2e is prefill-bound — 67% of
summarize at 0.53 bf16-MFU — and the chained-matmul microbench puts the
s8xs8 MXU path at 1.6x the bf16 rate (132.7 vs 83.1 TFLOP/s at 4096^3).
This script runs the REAL 3B prefill shape (B=8, S=8192, instrumented
split programs) both ways and records the prefill seconds; decode is
untouched by design (single-token forwards keep the exact path).

Writes artifacts/w8a8_ab.json.
"""
from __future__ import annotations

import gc
import json
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

_FILLER = "Quốc hội thông qua nghị quyết phát triển kinh tế xã hội. "


def run_arm(quantize_act: bool, params) -> dict:
    import numpy as np

    from vnsum_tpu.backend.engine import EngineStats, TpuBackend
    from vnsum_tpu.core.config import GenerationConfig
    from vnsum_tpu.models import llama32_3b

    be = TpuBackend(
        model_config=llama32_3b(max_seq_len=8448),
        tokenizer="byte",
        params=params,
        batch_size=8,
        max_new_tokens=128,
        quantize=True,
        quantize_act=quantize_act,
        instrument=True,
    )
    gen = GenerationConfig(temperature=1.0, seed=11)
    body = _FILLER * (8100 // len(_FILLER.encode()) + 1)
    prompts = [f"tài liệu {i}: {body}"[:8100] for i in range(8)]
    be.generate(prompts, config=gen)  # compile + warm
    be.stats = EngineStats()
    rounds = 3
    t0 = time.time()
    for r in range(rounds):
        be.generate([f"vòng {r} " + p for p in prompts], config=gen)
    wall = time.time() - t0
    st = be.stats
    arm = {
        "quantize_act": quantize_act,
        # snapshot: the sanity generate below appends a fresh-bucket (and
        # compile-polluted) dispatch that must not land in the record
        "dispatches": list(st.dispatches),
        "prefill_s": round(st.phase_seconds.get("prefill", 0.0), 2),
        "decode_s": round(st.phase_seconds.get("decode", 0.0), 2),
        "wall_s": round(wall, 1),
        "prefill_tokens_per_sec": round(
            sum(d["B"] * d["S"] for d in st.dispatches)
            / max(st.phase_seconds.get("prefill", 0.0), 1e-9), 1,
        ),
    }
    # first-token sanity across a couple of rows: outputs remain text
    outs = be.generate(prompts[:2], config=gen)
    arm["outputs_nonempty"] = sum(bool(o) for o in outs)
    del be
    gc.collect()
    return arm


def main() -> int:
    from vnsum_tpu.core.jax_cache import enable_compilation_cache
    from vnsum_tpu.models import jitted_init, llama32_3b
    from vnsum_tpu.models.llama import init_params

    enable_compilation_cache()
    params = jitted_init(init_params, llama32_3b(max_seq_len=8448), 0)

    rec: dict = {"shape": "B=8, S=8192 bucket, 128 sampled new tokens, "
                          "llama3.2-3b int8 weights"}
    for qa in (False, True):
        rec["w8a8" if qa else "weight_only"] = run_arm(qa, params)
        print(rec["w8a8" if qa else "weight_only"], file=sys.stderr)
    rec["prefill_speedup"] = round(
        rec["weight_only"]["prefill_s"] / max(rec["w8a8"]["prefill_s"], 1e-9),
        3,
    )
    out = REPO / "artifacts" / "w8a8_ab.json"
    out.write_text(json.dumps(rec, indent=2))
    print(json.dumps({"ok": True, "prefill_speedup": rec["prefill_speedup"]}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
