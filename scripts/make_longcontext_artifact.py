"""Produce the long-context capability artifact.

Exercises the flagship capability the reference fundamentally lacks (its
truncated strategy cuts every document to 16384−2048 tokens,
runners/run_summarization_ollama.py:8-13): REAL trained weights, documents
LONGER than the model's one-chip max_seq_len, summarized in ONE un-truncated
forward via ring-attention prefill + seq-sharded decode, then scored with
ROUGE against reference summaries.

The model is the same tiny real-format HF checkpoint the quality-parity
artifact uses (models.fixtures, LM-trained on the corpus so greedy decoding
emits corpus-like Vietnamese) — but built with a SMALL max_position window so
the synthesized documents genuinely exceed the one-chip ceiling, and run over
an 8-virtual-device (data=2, seq=4) mesh: the exact mesh program a v5e-8
would execute.

Usage: JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python scripts/make_longcontext_artifact.py
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

# must be set before any jax import (tests/conftest.py recipe)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--out", default=str(REPO / "artifacts/longcontext_e2e_tiny.json")
    )
    ap.add_argument("--docs", type=int, default=4)
    ap.add_argument("--tokens-per-doc", type=int, default=900)
    ap.add_argument("--train-steps", type=int, default=300)
    args = ap.parse_args()

    import jax

    from vnsum_tpu.core.config import PipelineConfig
    from vnsum_tpu.data.synthesize import synthesize_corpus
    from vnsum_tpu.models.fixtures import make_tiny_hf_checkpoint
    from vnsum_tpu.pipeline.runner import PipelineRunner

    work = Path(tempfile.mkdtemp(prefix="longctx_"))
    t0 = time.time()
    corpus_stats = synthesize_corpus(
        work / "corpus", n_docs=args.docs,
        tokens_per_doc=args.tokens_per_doc, summary_tokens=80, seed=3,
    )
    docs = [
        p.read_text(encoding="utf-8")
        for p in sorted((work / "corpus/doc").glob("*.txt"))
    ]
    # one-chip ceiling 512 tokens; the ~900-word docs run 1.3-2k BPE tokens
    one_chip_ceiling = 512
    ckpt_info = make_tiny_hf_checkpoint(
        work / "ckpt", docs, vocab_size=1024,
        max_seq_len=one_chip_ceiling, train_steps=args.train_steps,
    )

    cfg = PipelineConfig(
        approach="truncated",
        models=["tiny-long"],
        backend="tpu",
        long_context=True,
        mesh_shape={"data": 2, "seq": 4},
        allow_cpu_mesh=True,  # 8-way mesh on the 1-chip host runs on CPU
        weights_dir=str(work / "ckpt"),
        max_context=4096,
        max_new_tokens=96,
        batch_size=2,
        docs_dir=str(work / "corpus/doc"),
        summary_dir=str(work / "corpus/summary"),
        generated_summaries_dir=str(work / "gen"),
        results_dir=str(work / "results"),
        logs_dir=str(work / "logs"),
    )
    runner = PipelineRunner(cfg)
    results = runner.run()

    model = cfg.models[0]
    evaluation = results.evaluation.get(model, {})
    summarization = results.summarization.get(model, {})
    samples = sorted(runner._output_dir(model).glob("*.txt"))
    if not samples or not summarization.get("successful"):
        raise RuntimeError(f"long-context run failed: {summarization}")

    # document lengths in the checkpoint's OWN BPE tokens, to prove they
    # exceed the one-chip ceiling
    from transformers import AutoTokenizer

    hf_tok = AutoTokenizer.from_pretrained(str(work / "ckpt"))
    doc_bpe_lens = [len(hf_tok.encode(d)) for d in docs]
    # enforce the artifact's headline claims — a parameter choice that
    # falsifies them must fail the run, not write a misleading artifact
    if not all(n > one_chip_ceiling for n in doc_bpe_lens):
        raise RuntimeError(
            f"doc lengths {doc_bpe_lens} do not all exceed the one-chip "
            f"ceiling ({one_chip_ceiling}); raise --tokens-per-doc"
        )
    strategy_cut = cfg.max_context - cfg.max_new_tokens
    if any(n > strategy_cut for n in doc_bpe_lens):
        raise RuntimeError(
            f"doc lengths {doc_bpe_lens} exceed the truncated strategy's "
            f"cut ({strategy_cut}); the 'UN-truncated' claim would be false "
            "— raise --max-context or lower --tokens-per-doc"
        )

    artifact = {
        "what": (
            "long-context capability chain: REAL trained HF checkpoint "
            "(max_position_embeddings=512, the one-chip ceiling) -> "
            "--long-context truncated pipeline over a (data=2, seq=4) mesh "
            "-> every document summarized UN-truncated in one ring-prefill "
            "forward -> ROUGE. The reference cuts all inputs to its 16k "
            "context (runners/run_summarization_ollama.py:8-13); this "
            "framework's ceiling scales with the mesh seq axis."
        ),
        "mesh": {"data": 2, "seq": 4},
        "jax_devices": len(jax.devices("cpu")),
        "one_chip_max_seq_len": one_chip_ceiling,
        "doc_bpe_token_lengths": doc_bpe_lens,
        "all_docs_exceed_one_chip_ceiling": all(
            n > one_chip_ceiling for n in doc_bpe_lens
        ),
        "corpus": {
            "docs": corpus_stats["documents"]["total_files"],
            "avg_doc_words": corpus_stats["documents"]["avg_tokens_per_file"],
        },
        "checkpoint": ckpt_info,
        "summarization": {
            k: summarization.get(k)
            for k in ("successful", "failed", "total_chunks", "total_time")
        },
        "evaluation": evaluation,
        "sample_generated_summary": samples[0].read_text(encoding="utf-8")[:400],
        "wall_seconds": round(time.time() - t0, 1),
        "tpu_note": (
            "run on 8 virtual CPU devices (no multi-chip hardware on this "
            "host); the compiled program is the same SPMD module a v5e-8 "
            "executes — see tests/test_backend_long_context.py for the "
            "greedy-parity proofs"
        ),
    }
    out = Path(args.out)
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_text(
        json.dumps(artifact, indent=1, ensure_ascii=False), encoding="utf-8"
    )
    print(json.dumps({
        "rougeL": evaluation.get("rouge_scores", {}).get("rougeL_f1"),
        "docs_exceed_ceiling": artifact["all_docs_exceed_one_chip_ceiling"],
        "out": str(out),
        "wall_seconds": artifact["wall_seconds"],
    }))


if __name__ == "__main__":
    main()
