"""Quality cost of the lossy fast-path knobs, measured on trained weights
(VERDICT r4 #2 / missing #2).

The on-chip default config is lossy twice over — int8 weights and an int8 KV
cache — and W8A8 prefill (opt-in) adds per-token activation rounding, yet
until this artifact nothing measured what any of that does to generation
quality on TRAINED weights. Here, for each of the four reference model
families (fixtures.TRAINED_FAMILIES at kernel-compatible shapes —
head_dim 128 so the REAL Pallas fast path runs on chip):

  arm f32_dense   — float32 params, dense attention: the exact oracle
  arm bf16_flash  — bf16 + flash kernels (no int8): numeric-format drift
  arm w8          — int8 weights, bf16 KV
  arm w8kv8       — int8 weights + int8 KV cache (the e2e DEFAULT)
  arm w8a8        — + W8A8 prefill (the opt-in knob VERDICT asks about)

Each arm greedy-generates the same >=100 prompts; quality = exact
string-agreement rate and ROUGE-1/L against the f32 oracle's output.

Secondary (real scale): random-init llama32-3b on chip, last-position
top-1/top-5 agreement of prefill logits across the int8 arms (w8 as base).

Decision rule (recorded in the artifact): promote W8A8 to the e2e default
iff, aggregated over families, its agreement rate is within 3 points and
its ROUGE-L within 0.01 of the w8kv8 arm it would replace.

Writes artifacts/quality_lossy_ab.json.
"""
from __future__ import annotations

import argparse
import gc
import json
import sys
import tempfile
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

ARMS = ("f32_dense", "bf16_flash", "w8", "w8kv8", "w8a8")


def build_backend(arm: str, ckpt: str, batch: int, max_new: int):
    import jax.numpy as jnp

    from vnsum_tpu.backend.engine import TpuBackend
    from vnsum_tpu.models.convert import load_hf_checkpoint

    dtype = jnp.float32 if arm == "f32_dense" else jnp.bfloat16
    cfg, params = load_hf_checkpoint(ckpt, dtype=dtype)
    kw: dict = dict(
        model_config=cfg, params=params, tokenizer=f"hf:{ckpt}",
        batch_size=batch, max_new_tokens=max_new,
    )
    if arm == "f32_dense":
        kw.update(flash=False, quantize_kv=False)
    elif arm == "bf16_flash":
        kw.update(quantize_kv=False)
    elif arm == "w8":
        kw.update(quantize=True, quantize_kv=False)
    elif arm == "w8kv8":
        kw.update(quantize=True, quantize_kv=True)
    elif arm == "w8a8":
        kw.update(quantize=True, quantize_kv=True, quantize_act=True)
    return TpuBackend(**kw)


def rouge_l_f(a: str, b: str) -> float:
    from vnsum_tpu.eval.rouge import RougeScorer

    return RougeScorer(["rougeL"], keep_unicode=True).score(a, b)["rougeL"].fmeasure


def family_ab(family: str, prompts: list[str], max_new: int) -> dict:
    from vnsum_tpu.models.fixtures import (
        KERNEL_SHAPE_OVERRIDES,
        train_tiny_family,
    )

    ckpt = tempfile.mkdtemp(prefix=f"vnsum_qab_{family}_")
    train_tiny_family(family, ckpt, steps=60,
                      overrides=KERNEL_SHAPE_OVERRIDES)

    outs: dict[str, list[str]] = {}
    timings: dict[str, float] = {}
    for arm in ARMS:
        be = build_backend(arm, ckpt, batch=8, max_new=max_new)
        t0 = time.time()
        outs[arm] = be.generate(prompts)
        timings[arm] = round(time.time() - t0, 1)
        del be
        gc.collect()

    oracle = outs["f32_dense"]
    nonempty = sum(1 for o in oracle if o)
    row: dict = {
        "prompts": len(prompts),
        "oracle_nonempty": nonempty,
        "oracle_mean_chars": round(
            sum(len(o) for o in oracle) / len(oracle), 1
        ),
        "arm_seconds": timings,
        "arms": {},
    }
    for arm in ARMS[1:]:
        agree = sum(1 for a, b in zip(oracle, outs[arm]) if a == b)
        rl = [rouge_l_f(a, b) for a, b in zip(oracle, outs[arm]) if a or b]
        row["arms"][arm] = {
            "string_agreement": round(agree / len(prompts), 4),
            "rougeL_vs_f32_mean": round(sum(rl) / len(rl), 4) if rl else 1.0,
        }
    print(f"{family}: {json.dumps(row['arms'])}", file=sys.stderr)
    return row


def secondary_3b() -> dict:
    """Random-init 3B on chip: last-position prefill logits across int8
    arms; top-1/top-5 agreement vs the w8 arm (incremental effect of the KV
    cache + W8A8 knobs at the real scale, where no f32 oracle fits)."""
    import dataclasses

    import jax
    import jax.numpy as jnp
    import numpy as np

    from vnsum_tpu.models import jitted_init
    from vnsum_tpu.models.llama import (
        forward,
        init_kv_cache,
        llama32_3b,
        prefill_attention_mask,
        prefill_positions,
    )
    from vnsum_tpu.models.quant import quantize_params
    from vnsum_tpu.ops.flash_attention import flash_prefill_attention

    B, S = 2, 1024
    cfg = llama32_3b(max_seq_len=S + 64)
    from vnsum_tpu.models.llama import init_params

    params = jitted_init(init_params, cfg, seed=3)
    params_q = jax.jit(quantize_params)(params)
    del params
    gc.collect()

    rng = np.random.default_rng(5)
    tokens = jnp.asarray(
        rng.integers(0, 4096, size=(B, S), dtype=np.int32)
    )
    pads = jnp.zeros((B,), jnp.int32)
    C = S

    def last_logits(w8a8: bool, quant_kv: bool):
        c = dataclasses.replace(cfg, w8a8_prefill=w8a8)

        def fn(p):
            cache = init_kv_cache(c, B, C, quantized=quant_kv)

            def stacked(q, cache_, layer_idx):
                return flash_prefill_attention(
                    q, cache_, layer_idx, pads, c.q_per_kv, None
                )

            lg, _ = forward(
                p, c, tokens, prefill_positions(pads, S), cache, 0,
                prefill_attention_mask(pads, S, C),
                stacked_attention_fn=stacked,
            )
            # last 64 positions -> 128 argmax samples (B=2), not just 2
            return lg[:, -64:, :]

        return np.asarray(jax.jit(fn)(params_q), np.float32)

    arms = {
        "w8": last_logits(False, False),
        "w8kv8": last_logits(False, True),
        "w8a8": last_logits(True, True),
    }
    base = arms["w8"].reshape(-1, cfg.vocab_size)
    out = {"B": B, "S": S, "positions_sampled": int(base.shape[0])}
    for name in ("w8kv8", "w8a8"):
        lg = arms[name].reshape(-1, cfg.vocab_size)
        top1 = float(np.mean(lg.argmax(-1) == base.argmax(-1)))
        k = 5
        t5b = np.argsort(base, -1)[:, -k:]
        t5a = np.argsort(lg, -1)[:, -k:]
        over = np.mean([
            len(set(t5a[i]) & set(t5b[i])) / k for i in range(base.shape[0])
        ])
        out[name] = {
            "top1_agreement_vs_w8": round(top1, 4),
            "top5_overlap_vs_w8": round(float(over), 4),
            "max_abs_logit_delta": round(
                float(np.max(np.abs(lg - base))), 4
            ),
        }
    print(f"3b secondary: {json.dumps(out)}", file=sys.stderr)
    return out


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="artifacts/quality_lossy_ab.json")
    ap.add_argument("--prompts", type=int, default=112)
    ap.add_argument("--max-new", type=int, default=32)
    ap.add_argument("--families", default="llama,qwen3,gemma3,phi")
    ap.add_argument("--skip-3b", action="store_true")
    args = ap.parse_args()

    from vnsum_tpu.core.jax_cache import enable_compilation_cache
    from vnsum_tpu.models.fixtures import GEN_CORPUS

    enable_compilation_cache()

    # >=100 distinct prompts: corpus-sentence prefixes of varying lengths —
    # trained fixtures continue them with corpus-like text, so greedy
    # outputs are non-degenerate
    words: list[str] = []
    for t in GEN_CORPUS[:3]:
        words.extend(t.split())
    prompts = []
    i = 0
    while len(prompts) < args.prompts:
        ln = 4 + (i * 3) % 12
        start = (i * 7) % max(1, len(words) - ln)
        prompts.append(" ".join(words[start : start + ln]))
        i += 1
    prompts = list(dict.fromkeys(prompts))[: args.prompts]

    rec: dict = {
        "what": "lossy-knob quality A/B on trained four-family fixtures",
        "arms": list(ARMS),
        "families": {},
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%S"),
    }
    for family in args.families.split(","):
        rec["families"][family] = family_ab(family, prompts, args.max_new)

    if not args.skip_3b:
        rec["secondary_3b_random_init"] = secondary_3b()

    # aggregate + the W8A8 decision
    def agg(arm: str, key: str) -> float:
        vals = [
            f["arms"][arm][key] for f in rec["families"].values()
        ]
        return round(sum(vals) / len(vals), 4)

    summary = {
        arm: {
            "string_agreement_mean": agg(arm, "string_agreement"),
            "rougeL_vs_f32_mean": agg(arm, "rougeL_vs_f32_mean"),
        }
        for arm in ARMS[1:]
    }
    rec["summary"] = summary
    promote = (
        summary["w8a8"]["string_agreement_mean"]
        >= summary["w8kv8"]["string_agreement_mean"] - 0.03
        and summary["w8a8"]["rougeL_vs_f32_mean"]
        >= summary["w8kv8"]["rougeL_vs_f32_mean"] - 0.01
    )
    rec["w8a8_decision"] = {
        "promote_to_default": bool(promote),
        "rule": "within 3pp agreement and 0.01 rougeL of w8kv8, aggregated",
    }

    out = Path(args.out)
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_text(json.dumps(rec, indent=2))
    print(json.dumps({"ok": True, "summary": summary,
                      "w8a8_promote": bool(promote)}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
