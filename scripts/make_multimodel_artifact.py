"""Multi-family comparative sweep on the chip.

The reference's core experiment is one pipeline run sweeping four model
families (run_full_evaluation_pipeline.py:960-962: llama3.2:3b, gemma3:4b,
qwen3:8b, phi4:14b — all through one serial Ollama endpoint). This artifact
demonstrates the same capability natively: ONE PipelineRunner invocation
sweeping three ARCHITECTURE FAMILIES (Llama GQA, Qwen3 QK-norm, Gemma3
sliding-window sandwich-norm) through the TPU engine back to back,
summarizing and evaluating the same corpus.

Random-init weights at reduced scale (the chip holds one family at a time;
family coverage, not quality, is what this proves — the quality chain is
artifacts/parity_e2e_tiny.json and the 3B runbook). Writes
artifacts/multimodel_sweep.json.
"""
from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="artifacts/multimodel_sweep.json")
    ap.add_argument("--docs", type=int, default=4)
    args = ap.parse_args()

    import dataclasses

    from vnsum_tpu.core.config import PipelineConfig
    from vnsum_tpu.core.jax_cache import enable_compilation_cache
    from vnsum_tpu.data.synthesize import synthesize_corpus
    from vnsum_tpu.models import MODEL_REGISTRY
    from vnsum_tpu.models.llama import gemma3_4b, llama32_3b, qwen3_0p6b
    from vnsum_tpu.pipeline.runner import PipelineRunner
    import tempfile

    enable_compilation_cache()
    root = tempfile.mkdtemp(prefix="vnsum_mm_")
    synthesize_corpus(
        f"{root}/c", n_docs=args.docs, tokens_per_doc=6_000,
        summary_tokens=200, seed=9,
    )

    # one family per entry, scaled so each fits the chip comfortably next
    # to the previous family's compiled programs: Llama at the 3B
    # architecture with reduced layers (head_dim 128 keeps the Pallas
    # kernels on — llama32_1b's head_dim=64 forces the dense path, whose
    # one-off S=4096 compile is exactly what this host's remote-compile
    # service struggles with); Qwen3-0.6B real shape; Gemma3 at 4B
    # architecture with reduced layers (sliding/global interleave intact)
    MODEL_REGISTRY["sweep-llama-8l"] = lambda: dataclasses.replace(
        llama32_3b(max_seq_len=4352), n_layers=8
    )
    MODEL_REGISTRY["sweep-qwen3-0.6b"] = lambda: qwen3_0p6b(max_seq_len=4352)
    MODEL_REGISTRY["sweep-gemma3-8l"] = lambda: dataclasses.replace(
        gemma3_4b(max_seq_len=4352),
        n_layers=8,
        layer_is_global=tuple((i + 1) % 6 == 0 for i in range(8)),
    )

    cfg = PipelineConfig(
        approach="mapreduce",
        models=["sweep-llama-8l", "sweep-qwen3-0.6b", "sweep-gemma3-8l"],
        backend="tpu",
        docs_dir=f"{root}/c/doc",
        summary_dir=f"{root}/c/summary",
        generated_summaries_dir=f"{root}/gen",
        results_dir=f"{root}/results",
        logs_dir=f"{root}/logs",
        chunk_size=3_800,
        chunk_overlap=100,
        token_max=3_000,
        max_new_tokens=64,
        batch_size=4,
        tokenizer="byte",
    )
    runner = PipelineRunner(cfg)
    t0 = time.time()
    results = runner.run()
    elapsed = time.time() - t0

    rec: dict = {
        "families": {
            "sweep-llama-8l": "Llama GQA (3B architecture, 8 layers)",
            "sweep-qwen3-0.6b": "Qwen3 QK-norm (0.6B real shape)",
            "sweep-gemma3-8l": (
                "Gemma3 sandwich norms + GeGLU + sliding/global interleave "
                "(4B architecture, 8 layers)"
            ),
        },
        "per_model": {},
        "seconds_total": round(elapsed, 1),
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%S"),
    }
    ok = 0
    for model, r in results.summarization.items():
        rec["per_model"][model] = {
            "status": r.get("status"),
            "docs_ok": r.get("successful", 0),
            "chunks": r.get("total_chunks", 0),
            "seconds": round(r.get("total_time", 0.0), 1),
        }
        ev = results.evaluation.get(model, {})
        if "rouge_scores" in ev:
            rec["per_model"][model]["rougeL"] = round(
                ev["rouge_scores"]["rougeL_f1"], 4
            )
        # an evidence artifact must be COMPLETE: summarization succeeded
        # for every doc AND the evaluation pass produced its metrics
        ok += (
            r.get("successful", 0) == args.docs
            and "rougeL" in rec["per_model"][model]
        )
    if ok != len(cfg.models):
        raise RuntimeError(f"sweep incomplete: {rec['per_model']}")

    out = Path(args.out)
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_text(json.dumps(rec, indent=2))
    print(json.dumps({"ok": True, "seconds_total": rec["seconds_total"],
                      "families": len(cfg.models)}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
