"""Multi-family comparative sweep on the chip.

The reference's core experiment is one pipeline run sweeping four model
families (run_full_evaluation_pipeline.py:960-962: llama3.2:3b, gemma3:4b,
qwen3:8b, phi4:14b — all through one serial Ollama endpoint). This artifact
demonstrates the same capability natively, in two parts:

1. ONE PipelineRunner invocation sweeping three ARCHITECTURE FAMILIES
   (Llama GQA, Qwen3 QK-norm, Gemma3 sliding-window sandwich-norm) through
   the TPU engine back to back, summarizing and evaluating the same corpus.
   Perf columns only — random weights make quality columns noise
   (VERDICT r3 weak #4), so none are recorded.
2. REAL-SHAPE probes (VERDICT r3 #3): the actual 34-layer gemma3-4b and
   40-layer phi4:14b configs, int8, on the chip — tokens/s and memory
   high-water for the largest (B, S) that fits, with the OOM boundary
   trail for everything that didn't. Weights are random int8 initialized
   DIRECTLY in the quantized layout (models.quant.init_params_quantized):
   a bf16 tree + quantize would need 3x the bytes and can never fit 14B
   on one 16 GB chip.

Writes artifacts/multimodel_sweep.json.
"""
from __future__ import annotations

import argparse
import gc
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

# Vietnamese filler for byte-tokenizer perf prompts (bytes == tokens)
_FILLER = (
    "Quốc hội đã thông qua nghị quyết về phát triển kinh tế xã hội "
    "trong giai đoạn tới với nhiều nội dung quan trọng. "
)


def probe_real_shape(label: str, cfg_factory, ladder, max_new: int = 64) -> dict:
    """Try (B, S) shapes big-to-small; return a perf row for the first that
    runs plus the failure trail (the OOM boundary is data, not an error)."""
    import jax

    from vnsum_tpu.backend.engine import EngineStats, TpuBackend
    from vnsum_tpu.models import jitted_init
    from vnsum_tpu.models.quant import init_params_quantized

    attempts: list = []
    for B, S in ladder:
        params = be = None
        try:
            cfg = cfg_factory(max_seq_len=S + 2 * max_new)
            t0 = time.time()
            params = jitted_init(init_params_quantized, cfg, seed=0)
            weight_bytes = sum(
                int(l.nbytes) for l in jax.tree.leaves(params)
            )
            # instrument=True: split prefill/decode programs give exact
            # per-phase seconds + decode step counts (robust to a random
            # model's early EOS exits)
            be = TpuBackend(
                model_config=cfg, params=params, tokenizer="byte",
                batch_size=B, max_new_tokens=max_new, instrument=True,
            )
            body = (_FILLER * (S // len(_FILLER.encode()) + 1)).encode()
            prompts = [
                (f"tài liệu {i}: ".encode() + body)[: S - 16].decode(
                    "utf-8", "ignore"
                )
                for i in range(B)
            ]
            be.generate(prompts, max_new_tokens=max_new)  # compile + warm
            compile_s = time.time() - t0
            be.stats = EngineStats()
            t1 = time.time()
            rounds = 2
            for r in range(rounds):
                be.generate(
                    [f"vòng {r} " + p for p in prompts],
                    max_new_tokens=max_new,
                )
            dt = time.time() - t1
            st = be.stats
            pre = st.phase_seconds.get("prefill", 0.0)
            dec = st.phase_seconds.get("decode", 0.0)
            padded = sum(d["B"] * d["S"] for d in st.dispatches)
            steps = sum(d["steps"] for d in st.dispatches)
            row = {
                "status": "success", "B": B, "S": S, "max_new": max_new,
                "layers": cfg.n_layers,
                "weight_bytes": weight_bytes,
                "warm_seconds": round(dt, 2),
                "prefill_s": round(pre, 2),
                "decode_s": round(dec, 2),
                "prefill_tokens_per_sec": round(padded / pre, 1) if pre else 0,
                "decode_steps": steps,
                "decode_steps_per_sec": round(steps / dec, 1) if dec else 0,
                "compile_and_warm_seconds": round(compile_s, 1),
                "attempts": attempts,
            }
            try:  # plugin may not expose allocator stats — best effort
                ms = jax.local_devices()[0].memory_stats() or {}
                for k in ("bytes_in_use", "peak_bytes_in_use"):
                    if k in ms:
                        row[k] = int(ms[k])
            except Exception:
                pass
            print(f"{label}: {row}", file=sys.stderr)
            return row
        except Exception as e:  # OOM / compile-service failure: step down
            attempts.append({"B": B, "S": S, "error": str(e)[:300]})
            print(f"{label} B={B} S={S} failed: {str(e)[:160]}", file=sys.stderr)
        finally:
            del params, be
            gc.collect()
    return {"status": "did_not_fit", "attempts": attempts}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="artifacts/multimodel_sweep.json")
    ap.add_argument("--docs", type=int, default=4)
    args = ap.parse_args()

    import dataclasses

    from vnsum_tpu.core.config import PipelineConfig
    from vnsum_tpu.core.jax_cache import enable_compilation_cache
    from vnsum_tpu.data.synthesize import synthesize_corpus
    from vnsum_tpu.models import MODEL_REGISTRY
    from vnsum_tpu.models.llama import gemma3_4b, llama32_3b, qwen3_0p6b
    from vnsum_tpu.pipeline.runner import PipelineRunner
    import tempfile

    enable_compilation_cache()
    root = tempfile.mkdtemp(prefix="vnsum_mm_")
    synthesize_corpus(
        f"{root}/c", n_docs=args.docs, tokens_per_doc=6_000,
        summary_tokens=200, seed=9,
    )

    # one family per entry, scaled so each fits the chip comfortably next
    # to the previous family's compiled programs: Llama at the 3B
    # architecture with reduced layers (head_dim 128 keeps the Pallas
    # kernels on — llama32_1b's head_dim=64 forces the dense path, whose
    # one-off S=4096 compile is exactly what this host's remote-compile
    # service struggles with); Qwen3-0.6B real shape; Gemma3 at 4B
    # architecture with reduced layers (sliding/global interleave intact)
    MODEL_REGISTRY["sweep-llama-8l"] = lambda: dataclasses.replace(
        llama32_3b(max_seq_len=4352), n_layers=8
    )
    MODEL_REGISTRY["sweep-qwen3-0.6b"] = lambda: qwen3_0p6b(max_seq_len=4352)
    MODEL_REGISTRY["sweep-gemma3-8l"] = lambda: dataclasses.replace(
        gemma3_4b(max_seq_len=4352),
        n_layers=8,
        layer_is_global=tuple((i + 1) % 6 == 0 for i in range(8)),
    )

    def make_cfg(tag: str) -> PipelineConfig:
        return PipelineConfig(
            approach="mapreduce",
            models=["sweep-llama-8l", "sweep-qwen3-0.6b", "sweep-gemma3-8l"],
            backend="tpu",
            docs_dir=f"{root}/c/doc",
            summary_dir=f"{root}/c/summary",
            generated_summaries_dir=f"{root}/gen_{tag}",
            results_dir=f"{root}/results_{tag}",
            logs_dir=f"{root}/logs",
            chunk_size=3_800,
            chunk_overlap=100,
            token_max=3_000,
            max_new_tokens=64,
            batch_size=4,
            tokenizer="byte",
        )

    # TWO passes: the first compiles every per-family program (first-compile
    # cost is wildly family-dependent — the r4 artifact recorded
    # sweep-gemma3-8l at 50.1 s vs sweep-llama-8l at 26.9 s, and the r5
    # profile (artifacts/sweep_anomaly_profile.json) showed steady-state
    # PARITY: the whole 1.9x was compile pollution in total_time, not a
    # kernel fallback. The second pass is the measured one.
    PipelineRunner(make_cfg("warm")).run()
    cfg = make_cfg("meas")
    runner = PipelineRunner(cfg)
    t0 = time.time()
    results = runner.run()
    elapsed = time.time() - t0

    rec: dict = {
        "measurement": (
            "second (warm) pipeline pass — compile excluded; see "
            "artifacts/sweep_anomaly_profile.json for the per-phase "
            "instrumented comparison and the r4 1.9x attribution"
        ),
        "families": {
            "sweep-llama-8l": "Llama GQA (3B architecture, 8 layers)",
            "sweep-qwen3-0.6b": "Qwen3 QK-norm (0.6B real shape)",
            "sweep-gemma3-8l": (
                "Gemma3 sandwich norms + GeGLU + sliding/global interleave "
                "(4B architecture, 8 layers)"
            ),
        },
        "per_model": {},
        "seconds_total": round(elapsed, 1),
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%S"),
    }
    ok = 0
    for model, r in results.summarization.items():
        rec["per_model"][model] = {
            "status": r.get("status"),
            "docs_ok": r.get("successful", 0),
            "chunks": r.get("total_chunks", 0),
            "seconds": round(r.get("total_time", 0.0), 1),
        }
        # quality columns deliberately absent: random weights make ROUGE
        # noise (VERDICT r3 weak #4); the eval pass still ran (checked
        # below) — the quality chain lives in the parity artifacts
        ok += (
            r.get("successful", 0) == args.docs
            and "rouge_scores" in results.evaluation.get(model, {})
        )
    if ok != len(cfg.models):
        raise RuntimeError(f"sweep incomplete: {rec['per_model']}")

    # release the pipeline engines before the real-shape probes — phi4:14b
    # int8 needs nearly the whole chip
    del runner, results
    gc.collect()

    from vnsum_tpu.models.llama import phi4_14b

    rec["real_shapes"] = {
        "gemma3-4b": probe_real_shape(
            "gemma3-4b", gemma3_4b,
            ladder=[(8, 4096), (4, 4096), (4, 2048), (2, 1024)],
        ),
        "phi4-14b": probe_real_shape(
            "phi4-14b", phi4_14b,
            ladder=[(2, 2048), (1, 1024), (1, 512)],
        ),
    }
    if rec["real_shapes"]["phi4-14b"]["status"] != "success":
        # the boundary itself is the finding: record the 2-chip spec that
        # would carry it (megatron TP over the model axis halves every
        # matmul weight and the KV heads per chip)
        rec["real_shapes"]["phi4-14b"]["two_chip_tp_spec"] = (
            "mesh {'model': 2}: parallel.sharding.param_shardings shards "
            "wq/wk/wv/w_gate/w_up on the head/intermediate axis, wo/w_down "
            "on the input axis, lm_head on vocab; ~7.1 GB int8 weights per "
            "chip + per-chip KV (10 kv-heads -> 5/chip) fits two v5e chips "
            "with the same engine code (TpuBackend(mesh=...))"
        )

    out = Path(args.out)
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_text(json.dumps(rec, indent=2))
    print(json.dumps({"ok": True, "seconds_total": rec["seconds_total"],
                      "families": len(cfg.models),
                      "real_shapes": {
                          k: v["status"] for k, v in rec["real_shapes"].items()
                      }}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
