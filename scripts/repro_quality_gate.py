"""Pinned reproduction kit for the reference's quality gate.

The reference's headline quality number is ROUGE-L 0.3053 for mapreduce +
llama3.2:3b on the VN-LongSum dataset
(/root/reference/evaluation_results/first_dataset/mapreduce/
llama3_2_3b_results.json, summary_statistics.rouge_scores). Pretrained 3B
weights are not present on this host, so the gate cannot be *scored* here —
this script pins everything else so that on any machine with the weights it
is ONE command:

    python scripts/repro_quality_gate.py \
        --weights-dir /path/to/Llama-3.2-3B-Instruct \
        --docs-dir data_1/doc --summary-dir data_1/summary \
        --preset vn-longsum --approach mapreduce \
        --reference-json /path/to/llama3_2_3b_results.json

It runs the full pipeline (summarize → ROUGE/BERTScore/semsim [+ G-Eval
when a judge is configured]) with the reference's exact knobs, then diffs
our results JSON against the reference results file FIELD-FOR-FIELD
(schema must match; numeric deltas reported per metric).

Presets mirror the reference configs verbatim:
- vn-longsum: run_full_evaluation_pipeline.py:993-1027 (chunk 12000 /
  overlap 200 / token_max 10000 / max_new 1024; critique raises max_new to
  2048; truncated uses max_context 16384).
- law: the second-dataset run's recorded config (evaluation_results/
  second_dataset/mapreduce/pipeline_results_20250608_022112.json
  pipeline_info.config: chunk 1200 / overlap 50 / token_max 1000 /
  max_new 512).
"""
from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

# preset -> PipelineConfig overrides applied ON TOP of approach_defaults()
PRESETS = {
    "vn-longsum": {"max_new_tokens": 1024},
    "law": {
        "chunk_size": 1200,
        "chunk_overlap": 50,
        "token_max": 1000,
        "max_new_tokens": 512,
    },
}


def schema_diff(reference: dict, ours: dict, path: str = "") -> dict:
    """Field-for-field comparison of nested stat dicts: every reference key
    must exist in ours with the same type shape; numeric pairs get deltas."""
    missing: list[str] = []
    extra: list[str] = []
    mismatched: list[str] = []
    deltas: dict[str, dict] = {}

    def walk(ref, got, p):
        if isinstance(ref, dict):
            if not isinstance(got, dict):
                missing.append(p or "<root>")
                return
            for k, v in ref.items():
                walk(v, got.get(k, _MISSING), f"{p}.{k}" if p else k)
            for k in got:
                if k not in ref:
                    extra.append(f"{p}.{k}" if p else k)
        elif got is _MISSING:
            missing.append(p)
        elif isinstance(ref, (int, float)) and isinstance(got, (int, float)):
            deltas[p] = {
                "reference": ref,
                "ours": got,
                "delta": round(float(got) - float(ref), 6),
            }
        elif isinstance(ref, (int, float)) or isinstance(got, (int, float)):
            # one side numeric, the other not (string/null/dict) — a
            # corrupted metric must fail the gate, not slip between buckets
            mismatched.append(f"{p} (ours: {type(got).__name__})")

    _MISSING = object()
    walk(reference, ours, path)
    return {
        "schema_ok": not missing and not mismatched,
        "missing_fields": missing,
        "type_mismatches": mismatched,
        "extra_fields": extra,
        "metric_deltas": deltas,
    }


def build_config(args) -> "PipelineConfig":
    from vnsum_tpu.core.config import PipelineConfig, approach_defaults

    overrides = dict(approach_defaults(args.approach))
    overrides.update(PRESETS[args.preset])
    if args.max_new_tokens:
        overrides["max_new_tokens"] = args.max_new_tokens
    cfg = PipelineConfig(
        approach=args.approach,
        models=[args.model],
        backend=args.backend,
        docs_dir=args.docs_dir,
        summary_dir=args.summary_dir,
        generated_summaries_dir=str(Path(args.out) / "generated_summaries"),
        results_dir=str(Path(args.out) / "results"),
        logs_dir=str(Path(args.out) / "logs"),
        max_samples=args.max_samples,
        batch_size=args.batch_size,
        quantize=args.quantize and args.backend == "tpu",
        weights_dir=args.weights_dir if args.backend == "tpu" else None,
        tree_json_path=args.tree_json or str(
            Path(args.docs_dir).parent / "document_tree.json"
        ),
        **overrides,
    )
    if args.embedding_dir:
        cfg.evaluation.embedding_dir = args.embedding_dir
    if args.include_llm_eval:
        cfg.evaluation.include_llm_eval = True
    if args.judge_backend:
        cfg.evaluation.include_llm_eval = True
        cfg.evaluation.judge_backend = args.judge_backend
    return cfg


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--weights-dir", help="local HF checkpoint dir (3B gate)")
    ap.add_argument("--docs-dir", required=True)
    ap.add_argument("--summary-dir", required=True)
    ap.add_argument("--approach", default="mapreduce",
                    choices=["mapreduce", "iterative", "truncated",
                             "mapreduce_critique", "mapreduce_hierarchical"])
    ap.add_argument("--preset", default="vn-longsum", choices=sorted(PRESETS))
    ap.add_argument("--model", default="llama3.2-3b")
    ap.add_argument("--backend", default="tpu",
                    help="tpu (default) or fake (CI smoke of this kit)")
    ap.add_argument("--reference-json",
                    help="reference *_results.json to diff field-for-field")
    ap.add_argument("--out", default="repro_gate_out")
    ap.add_argument("--max-samples", type=int)
    ap.add_argument("--max-new-tokens", type=int)
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--quantize", action="store_true", default=True)
    ap.add_argument("--no-quantize", dest="quantize", action="store_false")
    ap.add_argument("--embedding-dir",
                    help="local all-MiniLM-L6-v2 dir for BASELINE-comparable "
                         "BERTScore/semsim")
    ap.add_argument("--include-llm-eval", action="store_true")
    ap.add_argument("--judge-backend",
                    help="local Backend spec for an offline G-Eval judge "
                         "(implies --include-llm-eval)")
    ap.add_argument("--tree-json")
    args = ap.parse_args(argv)

    if args.backend == "tpu" and not args.weights_dir:
        ap.error("--weights-dir is required with backend=tpu (the gate is a "
                 "pretrained-weights number); use --backend fake for a "
                 "plumbing smoke test")

    from vnsum_tpu.pipeline.runner import PipelineRunner, model_name_safe

    cfg = build_config(args)
    runner = PipelineRunner(cfg)
    results = runner.run()

    rec = results.summarization.get(args.model, {})
    if rec.get("successful", 0) == 0:
        print(json.dumps({"ok": False, "error": "no documents summarized"}))
        return 1

    ours_path = (
        Path(cfg.results_dir) / f"{model_name_safe(args.model)}_results.json"
    )
    ours = json.loads(ours_path.read_text())
    verdict: dict = {
        "ok": True,
        "approach": args.approach,
        "preset": args.preset,
        "docs_ok": rec.get("successful"),
        "results_json": str(ours_path),
        "summary_statistics": ours.get("summary_statistics"),
    }
    if args.reference_json:
        ref = json.loads(Path(args.reference_json).read_text())
        # second-dataset files nest stats under results.evaluation.<model>
        ref_stats = ref.get("summary_statistics")
        if ref_stats is None:
            ev = ref.get("results", {}).get("evaluation", {})
            model_key = next(iter(ev), None)
            ref_stats = (ev.get(model_key, {}) or {}).get("metrics", {}).get(
                "summary_statistics"
            )
        if ref_stats is None:
            print(json.dumps({"ok": False,
                              "error": "no summary_statistics in reference"}))
            return 1
        verdict["diff"] = schema_diff(
            ref_stats, ours.get("summary_statistics", {})
        )
        verdict["ok"] = verdict["diff"]["schema_ok"]
    print(json.dumps(verdict, ensure_ascii=False))
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
