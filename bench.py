"""Benchmark: map-step throughput + end-to-end pipelines on one TPU chip.

Phases, one shared set of int8 Llama-3.2-3B weights:

1. **Map-step microbench** — batched map-phase generation (bucket-1024
   prompts + 128 new tokens, batch 96), the engine doing what the reference
   does serially over HTTP. Reference total throughput is ~0.25 chunks/s
   (BASELINE.md, llama3.2:3b iterative — its best 3B number).
2. **End-to-end mapreduce pipeline** — synthesize a corpus at TRUE
   VN-LongSum per-doc scale (avg 36,959 words / ~210k bytes per doc,
   metadata/doc_metadata.json), then run the real `PipelineRunner`: split →
   batched map → collapse rounds → final reduce → write summaries → ROUGE +
   BERTScore + semsim evaluation, with sampled ragged-EOS decode so the
   termination/compaction behavior matches a real checkpoint's. Wall-clock
   covers ALL of it; vs_baseline is docs/min against the reference's
   fastest 3B run on the same-sized docs (20.0 s/doc).
3. **Other strategies** — iterative, hierarchical, and mapreduce_critique
   summarize-only runs on the same corpus (4 docs), against their
   BASELINE.md rows. With the 16k truncated row
   (artifacts/bench_16k.json) every one of the five approaches has an
   on-chip measurement.

Prints ONE JSON line: the map-step metric stays the headline (comparable
across rounds), with the pipeline numbers nested under "e2e",
"e2e_iterative", and "e2e_hierarchical".
"""
from __future__ import annotations

import json
import sys
import tempfile
import time

REFERENCE_CHUNKS_PER_SEC = 0.25  # BASELINE.md: llama3.2:3b iterative, total
# reference wall-clock on the SAME per-doc text volume: llama3.2:3b
# iterative, 151 docs in 3014 s = 20.0 s/doc (BASELINE.md; its fastest 3B
# run — mapreduce was only timed with qwen3:8b at 65.8 s/doc)
REFERENCE_DOCS_PER_MIN = 3.01

# e2e corpus shape: TRUE VN-LongSum scale per document —
# /root/reference/metadata/doc_metadata.json: avg 36,959 words / 166,920
# chars (~210k bytes) / 54,566 Qwen-tokens per doc. 16 docs keeps the bench
# round under ~10 min; docs/min extrapolates linearly in doc count
E2E_DOCS = 16
E2E_WORDS_PER_DOC = 37_000  # reference's average_words_per_file

# bench chip: TPU v5e ("TPU v5 lite") — bf16 MXU peak and HBM bandwidth used
# for the MFU / roofline fields (VERDICT r3 #6). The weights are int8 but
# the matmuls accumulate from bf16 activations, so bf16 peak is the honest
# denominator.
PEAK_FLOPS_BF16 = 197e12
HBM_BYTES_PER_S = 819e9


def run_map_step_bench(backend) -> dict:
    prompt_tokens = 1000  # buckets to S=1024
    batch = backend.batch_size
    rounds = 3

    base = (
        "Bạn là một chuyên gia tóm tắt nội dung. "
        "Vui lòng viết một bản tóm tắt chi tiết cho đoạn văn bản sau bằng tiếng Việt. "
    )
    filler = "Quốc hội đã thông qua nghị quyết về phát triển kinh tế xã hội. "
    prompt = base + filler * ((prompt_tokens - len(base.encode())) // len(filler.encode()))
    prompts = [prompt + f" (tài liệu {i})" for i in range(batch)]

    t0 = time.time()
    backend.generate(prompts, max_new_tokens=128)  # compile + warmup
    print(f"warmup (incl. compile): {time.time() - t0:.1f}s", file=sys.stderr)

    t1 = time.time()
    done = 0
    for r in range(rounds):
        outs = backend.generate(
            [p + f" vòng {r}" for p in prompts], max_new_tokens=128
        )
        done += len(outs)
    elapsed = time.time() - t1

    stats = backend.stats
    print(
        f"map bench: {done} chunks in {elapsed:.1f}s; engine totals: "
        f"{stats.prompt_tokens} prompt tok, {stats.generated_tokens} gen tok, "
        f"{stats.tokens_per_second:.0f} tok/s overall",
        file=sys.stderr,
    )
    return {"chunks_per_sec": done / elapsed}


def _pick_ragged_eos(outs: list[str], tok, budget: int = 128) -> tuple[int, ...]:
    """Pick the token id whose per-row frequency makes the EXPECTED
    termination step ~budget/3 under sampled decode: with ~f occurrences per
    ``budget``-token row, per-step hit probability is ~f/budget, so
    E[termination] ~ budget/f. f~3 puts the average stop around step 40 of
    128 — most rows finish well before the budget at scattered depths (the
    shape real summaries produce), which is also what gives tail compaction
    something to harvest."""
    from collections import Counter

    rows = [tok.encode(o) for o in outs if o]
    rows = [r for r in rows if r]
    if not rows:
        return (10,)
    counts: Counter = Counter()
    for r in rows:
        counts.update(r)
    target = 3.0 * len(rows)  # ~3 occurrences per row on average
    best = min(counts, key=lambda b: (abs(counts[b] - target), b))
    # Round-4 comparability note: the tokenizer's NATIVE eos is now always a
    # terminator too (the ADVICE-r3 sampleability fix). For the trained-BPE
    # bench tokenizer that adds a ~1/4096-per-step hazard on top of this
    # picked token's ~3/128 — a <2% shift in expected termination depth, so
    # r04 docs/min stays workload-comparable with the committed r03 numbers.
    return (int(best),)


def e2e_engine_kwargs(tok_spec, params) -> dict:
    """ONE copy of the e2e engine configuration — the headline e2e run, the
    instrumented budget pass, and the weight-only A/B row must all measure
    the same shape (chunk_size 7800 -> S=8192 bucket, B=8 at the HBM
    ceiling, int8 weights).

    W8A8 prefill is the DEFAULT here as of round 5: its quality cost is
    measured and bounded (artifacts/quality_lossy_ab.json — within 0.5pp
    string agreement / 0.005 ROUGE-L of the int8-weights+int8-KV arm on
    the four trained family fixtures, per the pre-registered promotion
    rule), and it buys 1.25x on the dominant prefill dispatch
    (artifacts/w8a8_ab.json, PERF.md finding 18). The weight-only-exact
    path stays one flag away (quantize_act=False) and keeps its own bench
    row.

    B=16 + chunked prefill is ALSO the round-5 default: whole-prompt
    prefill transients were what capped the batch at 8 next to the int8 KV
    cache; prefill_chunk_tokens=2048 caps them at a chunk's worth, and the
    measured A/B (artifacts/b16_chunked_prefill.json) shows one B=16
    dispatch beating two B=8 dispatches 1.10x overall (decode 1.36x —
    weight reads amortize over twice the rows; prefill flat; exact same
    math, engine-level chunked==whole equivalence test)."""
    from vnsum_tpu.models import llama32_3b

    return dict(
        model_config=llama32_3b(max_seq_len=8448),
        tokenizer=tok_spec,
        params=params,
        batch_size=16,
        max_new_tokens=128,
        quantize=True,
        quantize_act=True,
        prefill_chunk_tokens=2048,
    )


def run_e2e_bench(params) -> tuple[dict, str, object, str, tuple]:
    # returns (metrics, corpus root, live backend, tokenizer spec, eos ids)
    from vnsum_tpu.backend.engine import TpuBackend
    from vnsum_tpu.core.config import GenerationConfig, PipelineConfig
    from vnsum_tpu.data.synthesize import synthesize_corpus
    from vnsum_tpu.pipeline.runner import PipelineRunner

    root = tempfile.mkdtemp(prefix="vnsum_bench_")
    t0 = time.time()
    stats = synthesize_corpus(
        f"{root}/corpus", n_docs=E2E_DOCS, tokens_per_doc=E2E_WORDS_PER_DOC,
        summary_tokens=714, seed=7, ragged=0.5,
    )
    print(
        f"e2e corpus: {E2E_DOCS} docs, "
        f"avg {stats['documents']['avg_tokens_per_file']:.0f} words "
        f"(synth {time.time() - t0:.1f}s)",
        file=sys.stderr,
    )

    # The QUALITY-RUN configuration tokenizes with the checkpoint's HF BPE
    # tokenizer (pipeline --weights-dir path), not raw bytes — and byte
    # tokens cost ~4-6x the forward passes per unit of text. Train a
    # byte-level BPE on this corpus (seconds; the fixture trainer the
    # parity artifact uses) so the e2e bench measures the real
    # configuration. Compression is reported: the synthetic grammar
    # compresses better (~5.7 B/tok) than real VN under Llama BPE
    # (~3.8 B/tok), so tokens/doc lands near ~44k vs VN-LongSum's 54.5k —
    # same words and chars per doc, ~20% fewer model tokens.
    import pathlib as _pl

    from vnsum_tpu.models.fixtures import train_bpe_tokenizer

    t0 = time.time()
    doc_paths = sorted(_pl.Path(f"{root}/corpus/doc").glob("*.txt"))
    hf_tok = train_bpe_tokenizer(
        (p.read_text(encoding="utf-8") for p in doc_paths), vocab_size=4096
    )
    hf_tok.save_pretrained(f"{root}/tok")
    tok_spec = f"hf:{root}/tok"
    sample_text = doc_paths[0].read_text(encoding="utf-8")
    bytes_per_tok = len(sample_text.encode()) / len(hf_tok.encode(sample_text))
    print(
        f"e2e tokenizer: BPE vocab {len(hf_tok)}, "
        f"{bytes_per_tok:.2f} bytes/token (train {time.time() - t0:.1f}s)",
        file=sys.stderr,
    )

    # chunk_size 7800 BPE tokens lands prompts in the S=8192 bucket; int8 KV
    # keeps 8 rows of 8320-token cache (+ int8 weights + the ~4 GB of
    # prefill transients at S=8192) inside one v5e chip — B=16 OOMs.
    # continuous="auto" correctly resolves to the ONE-SHOT program at B=8:
    # the measured A/B (artifacts/compaction_ab.json) shows the segmented
    # path losing ~33% token-normalized at this shape
    backend = TpuBackend(**e2e_engine_kwargs(tok_spec, params))
    cfg = PipelineConfig(
        approach="mapreduce",
        models=["llama3.2-3b"],
        backend="tpu",
        docs_dir=f"{root}/corpus/doc",
        summary_dir=f"{root}/corpus/summary",
        generated_summaries_dir=f"{root}/gen",
        results_dir=f"{root}/results",
        logs_dir=f"{root}/logs",
        chunk_size=7_800,
        chunk_overlap=200,
        # collapse budget in whitespace WORDS (reference-parity gating);
        # capped low enough that a worst-case all-ASCII grouping still fits
        # the model's 8320-byte-token input — reduce prompts must never be
        # silently truncated by the engine
        token_max=6_000,
        max_new_tokens=128,
        batch_size=16,
        tokenizer=tok_spec,
    )
    # random-init weights never emit the true EOS, so decode would always
    # pay the full budget and early-exit would sit idle — and under GREEDY
    # decode the rollouts degenerate (round 2's summaries were all empty:
    # the near-constant argmax stream hit its EOS at position 0). Run the
    # e2e with SAMPLED decode instead: temperature 1.0 over a random-init
    # model gives high-entropy streams, and _pick_ragged_eos declares the
    # token id observed ~3x per probe row as EOS (expected termination
    # ~budget/3), so rows finish early at scattered depths — the workload
    # shape a real checkpoint produces — and summaries stay non-empty for a
    # realistic evaluation pass.
    # Probe slices come from several docs' concatenation (one doc is ~210 KB
    # but 8 slices of ~7.3k BPE tokens need ~330 KB), sliced by BYTES scaled
    # by the measured compression so every probe prompt lands in the S=8192
    # bucket the pipeline uses (pre-warming its compile).
    raw = b" ".join(
        p.read_text(encoding="utf-8").encode("utf-8") for p in doc_paths[:6]
    )
    step = int(7_300 * bytes_per_tok)  # ~7.3k BPE tokens -> S=8192 bucket
    nb = backend.batch_size  # probe at FULL batch so the dominant
    # (B, S=8192) bucket's program is the one warmed
    assert len(raw) >= nb * step, (len(raw), step)
    probe_prompts = [
        "Tóm tắt: " + raw[i * step : (i + 1) * step].decode("utf-8", "ignore")
        for i in range(nb)
    ]
    probe = backend.generate(
        probe_prompts, config=GenerationConfig(temperature=1.0, seed=11)
    )
    eos = _pick_ragged_eos(probe, backend.tok)
    backend.gen_cfg = GenerationConfig(
        max_new_tokens=128, temperature=1.0, seed=11, eos_ids=eos
    )
    print(f"e2e ragged-eos token id: {eos}", file=sys.stderr)

    runner = PipelineRunner(cfg, backend_factory=lambda model: backend)

    t1 = time.time()
    results = runner.run()
    elapsed = time.time() - t1

    # itemized wall-clock budget (tracer spans) — the e2e number is only
    # actionable with its breakdown (where does non-generation time go?)
    spans = results.tracing.get("spans", {})
    budget = {
        name: round(s["total_s"], 1)
        for name, s in spans.items()
        if name in (
            "analyze", "summarize", "evaluate",
            "evaluate/embedder_init", "evaluate/embed",
            "evaluate/bertscore", "evaluate/rouge",
        )
    }
    for name, secs in sorted(budget.items()):
        print(f"e2e span {name}: {secs}s", file=sys.stderr)

    rec = results.summarization["llama3.2-3b"]
    total_chunks = rec["total_chunks"]
    docs = rec["successful"]
    if not docs:
        raise RuntimeError(f"e2e bench: all documents failed — see {root}/logs")
    chunks_per_sec = total_chunks / elapsed
    ok_names = {
        d["filename"] for d in rec["processing_details"]
        if d["status"] == "success"
    }
    input_bytes = sum(
        p.stat().st_size for p in doc_paths if p.name in ok_names
    )
    ev = results.evaluation.get("llama3.2-3b", {})
    rougel = ev.get("rouge_scores", {}).get("rougeL_f1", float("nan"))
    print(
        f"e2e pipeline: {docs} docs / {total_chunks} chunks in {elapsed:.1f}s "
        f"(map+collapse+reduce+eval); engine: {backend.stats.batches} batches, "
        f"{backend.stats.compactions} compactions, "
        f"{backend.stats.tokens_per_second:.0f} tok/s; rougeL={rougel:.4f}",
        file=sys.stderr,
    )
    docs_per_min = docs / (elapsed / 60)
    return {
        "chunks_per_sec": round(chunks_per_sec, 4),
        "docs_per_min": round(docs_per_min, 2),
        "seconds_total": round(elapsed, 1),
        "chunks": total_chunks,
        "docs": docs,
        "avg_doc_bytes": round(input_bytes / max(docs, 1)),
        "input_bytes_per_sec": round(input_bytes / elapsed),
        "compactions": backend.stats.compactions,
        # docs/min against the reference run on same-sized documents
        # (llama3.2:3b iterative, 20.0 s/doc) — the honest end-to-end ratio
        "vs_baseline": round(docs_per_min / REFERENCE_DOCS_PER_MIN, 2),
        "vs_baseline_chunks": round(
            chunks_per_sec / REFERENCE_CHUNKS_PER_SEC, 2
        ),
        "time_budget": budget,
    }, root, backend, tok_spec, eos


def run_device_budget(params, root: str, tok_spec, eos) -> dict:
    """Per-phase DEVICE time inside summarize (VERDICT r3 #1): rerun 4 docs
    of the same mapreduce workload on an instrument=True engine — split
    prefill/decode programs with a result-fetch sync between phases (same
    traced bodies as the one-shot program) — then turn the per-dispatch
    {B, S, steps} records into MFU / HBM-roofline numbers.

    The pipeline runs TWICE: the first pass compiles every bucket the
    workload touches (split programs are new in this mode), the second is
    the measured one — so phase times carry no compile pollution."""
    import pathlib as _pl

    from vnsum_tpu.backend.engine import EngineStats, TpuBackend
    from vnsum_tpu.core.config import GenerationConfig, PipelineConfig
    from vnsum_tpu.pipeline.runner import PipelineRunner

    backend = TpuBackend(
        **e2e_engine_kwargs(tok_spec, params), instrument=True
    )
    if eos is None:
        # standalone use (scripts/measure_device_budget.py): run the same
        # ragged-EOS probe the e2e phase does, on this backend — which also
        # warms the dominant S=8192 bucket's split programs
        doc_paths = sorted(_pl.Path(f"{root}/corpus/doc").glob("*.txt"))
        raw = b" ".join(
            p.read_text(encoding="utf-8").encode("utf-8")
            for p in doc_paths[:3]
        )
        sample = doc_paths[0].read_text(encoding="utf-8")
        bpt = len(sample.encode()) / max(backend.count_tokens(sample), 1)
        step = int(7_300 * bpt)
        n = max(1, min(8, len(raw) // step))
        probe = backend.generate(
            [
                "Tóm tắt: "
                + raw[i * step : (i + 1) * step].decode("utf-8", "ignore")
                for i in range(n)
            ],
            config=GenerationConfig(temperature=1.0, seed=11),
        )
        eos = _pick_ragged_eos(probe, backend.tok)
        print(f"device budget ragged-eos: {eos}", file=sys.stderr)
    backend.gen_cfg = GenerationConfig(
        max_new_tokens=128, temperature=1.0, seed=11, eos_ids=eos
    )

    def make_cfg(tag: str) -> PipelineConfig:
        return PipelineConfig(
            approach="mapreduce",
            models=["llama3.2-3b"],
            backend="tpu",
            docs_dir=f"{root}/corpus/doc",
            summary_dir=f"{root}/corpus/summary",
            generated_summaries_dir=f"{root}/{tag}",
            results_dir=f"{root}/results",
            logs_dir=f"{root}/logs",
            chunk_size=7_800,
            chunk_overlap=200,
            token_max=6_000,
            max_new_tokens=128,
            batch_size=16,
            tokenizer=tok_spec,
            max_samples=4,
        )

    for tag in ("gen_budget_warm", "gen_budget"):
        if tag == "gen_budget":  # measured pass starts from clean counters
            backend.stats = EngineStats()
        runner = PipelineRunner(
            make_cfg(tag), backend_factory=lambda model: backend
        )
        t0 = time.time()
        rec = runner.run_summarization_for_model("llama3.2-3b")
        wall = time.time() - t0
    if not rec.successful:
        raise RuntimeError("device budget pass: all documents failed")

    st = backend.stats
    pre = st.phase_seconds.get("prefill", 0.0)
    dec = st.phase_seconds.get("decode", 0.0)
    tok_h = st.phase_seconds.get("tokenize_host", 0.0)
    pack_h = st.phase_seconds.get("pack_host", 0.0)

    # FLOP / byte model from the engine's actual dispatch shapes
    import jax

    cfg_m = backend.cfg
    live_params = backend.params  # == the shared weights when passed in
    leaves = jax.tree.leaves(live_params)
    n_params = sum(int(l.size) for l in leaves)
    weight_bytes = sum(int(l.nbytes) for l in leaves)
    # embedding rows are gathered, not multiplied, during the body; with
    # tied embeddings the same table returns as the LM head and is only
    # applied to the LAST position (last_only prefill) — either way the
    # per-prompt-token matmul FLOPs come from the non-embed body
    embed = live_params["embed"]  # {"q","s"} when int8-quantized
    n_body = n_params - int(
        embed["q"].size if isinstance(embed, dict) else embed.size
    )
    ahd = cfg_m.n_layers * cfg_m.n_heads * cfg_m.head_dim
    pre_flops = sum(
        d["B"] * d["S"] * 2 * n_body        # dense matmuls, 2 FLOP/MAC
        # causal attention at the same 2-FLOP/MAC convention: QK^T + PV are
        # 2 * (2*hd*S^2/2) per head = 2*hd*S^2
        + d["B"] * 2 * ahd * d["S"] ** 2
        for d in st.dispatches
    )
    mfu_prefill = pre_flops / (pre * PEAK_FLOPS_BF16) if pre else 0.0

    # decode is HBM-bound: every step streams the full weight set plus each
    # row's valid KV cache (int8 + per-(token, head) f32 scales when the
    # quantized-cache kernels are active)
    kv_elt = 1 if backend.quantize_kv else 2
    kv_scale = 4 if backend.quantize_kv else 0
    per_tok_layer = 2 * cfg_m.n_kv_heads * (cfg_m.head_dim * kv_elt + kv_scale)
    dec_bytes = sum(
        d["steps"]
        * (
            weight_bytes
            + d["B"] * cfg_m.n_layers * per_tok_layer
            * (d["S"] + d["steps"] / 2)
        )
        for d in st.dispatches
    )
    roofline = dec_bytes / (dec * HBM_BYTES_PER_S) if dec else 0.0

    # one-shot comparison pass (VERDICT r4 weak #5): the SAME 4 docs through
    # a production (instrument=False) engine sharing these weights, so the
    # few-percent structural delta of the split instrument programs is
    # MEASURED on identical input rather than asserted from compaction_ab
    oneshot = TpuBackend(**e2e_engine_kwargs(tok_spec, live_params))
    oneshot.gen_cfg = backend.gen_cfg
    # warm pass first (trace + cache-load), mirroring the instrument arm's
    # two-pass discipline — otherwise the delta is swamped by compile
    for tag in ("gen_budget_oneshot_warm", "gen_budget_oneshot"):
        t0 = time.time()
        rec_1 = PipelineRunner(
            make_cfg(tag), backend_factory=lambda model: oneshot
        ).run_summarization_for_model("llama3.2-3b")
        oneshot_wall = time.time() - t0
    if not rec_1.successful:
        raise RuntimeError("one-shot comparison pass: all documents failed")

    out = {
        "docs": rec.successful,
        "chunks": rec.total_chunks,
        "wall_s": round(wall, 1),
        "oneshot_wall_s": round(oneshot_wall, 1),
        "instrument_overhead_frac": round(wall / oneshot_wall - 1, 4),
        "prefill_s": round(pre, 1),
        "decode_s": round(dec, 1),
        "tokenize_host_s": round(tok_h, 1),
        "pack_host_s": round(pack_h, 1),
        "other_host_s": round(wall - pre - dec - tok_h - pack_h, 1),
        "decode_steps": sum(d["steps"] for d in st.dispatches),
        "dispatches": st.dispatches,
        "mfu_prefill": round(mfu_prefill, 4),
        "decode_roofline_frac": round(roofline, 4),
        "peak_flops_bf16": PEAK_FLOPS_BF16,
        "hbm_bytes_per_s": HBM_BYTES_PER_S,
    }
    print(f"device budget: {out}", file=sys.stderr)
    return out


def run_strategy_bench(backend, approach: str, root: str, tok_spec) -> dict:
    """Summarization-phase timing for the other strategies on the SAME
    corpus + engine + compiled programs (VERDICT r2 #5): 4 docs,
    summarize-only — the reference's comparable numbers are its
    summarization records (BASELINE.md: iterative llama3.2:3b 20.0 s/doc;
    hierarchical phi4:14b 211 s/doc)."""
    from vnsum_tpu.core.config import PipelineConfig
    from vnsum_tpu.pipeline.runner import PipelineRunner

    cfg = PipelineConfig(
        approach=approach,
        models=["llama3.2-3b"],
        backend="tpu",
        docs_dir=f"{root}/corpus/doc",
        summary_dir=f"{root}/corpus/summary",
        generated_summaries_dir=f"{root}/gen_{approach}",
        results_dir=f"{root}/results",
        logs_dir=f"{root}/logs",
        chunk_size=7_800,
        chunk_overlap=200,
        iterative_chunk_size=7_800,
        iterative_chunk_overlap=200,
        token_max=6_000,
        max_new_tokens=128,
        batch_size=16,
        tokenizer=tok_spec,
        max_samples=4,
        tree_json_path=f"{root}/corpus/document_tree.json",
    )
    runner = PipelineRunner(cfg, backend_factory=lambda model: backend)
    t0 = time.time()
    rec = runner.run_summarization_for_model("llama3.2-3b")
    elapsed = time.time() - t0
    docs = rec.successful
    out = {
        "docs": docs,
        "chunks": rec.total_chunks,
        "llm_calls": sum(d.llm_calls for d in rec.processing_details),
        "seconds": round(elapsed, 1),
        "docs_per_min": round(docs / (elapsed / 60), 2) if docs else 0.0,
        "compactions": backend.stats.compactions,  # cumulative engine stat
    }
    print(f"{approach} bench: {out}", file=sys.stderr)
    if not docs:
        raise RuntimeError(f"{approach} bench: all documents failed")
    return out


def main() -> int:
    from vnsum_tpu.backend.engine import TpuBackend
    from vnsum_tpu.models import llama32_3b

    # measured sweet spot on v5e with the vectorized Pallas decode kernel +
    # int8 KV cache (B=64: 14.9, B=96: 15.8, B=128: OOM); the int8 cache
    # freed enough HBM for 96 rows
    backend = TpuBackend(
        model_config=llama32_3b(max_seq_len=4096),
        tokenizer="byte",
        batch_size=96,
        max_new_tokens=128,
        quantize=True,
    )

    map_res = run_map_step_bench(backend)

    # release the B=96 map-bench programs before the e2e phase: their
    # executables (and any buffers they pin) otherwise stay resident next to
    # the e2e engine's own programs, squeezing the evaluation encoder into
    # fragmented HBM (round-2's 442s eval tail)
    params = backend.params
    del backend
    import gc

    gc.collect()

    # ONE engine (weights already quantized, programs already compiled)
    # serves the e2e run and all three extra strategy phases
    e2e_res, corpus_root, e2e_backend, tok_spec, eos = run_e2e_bench(params)
    iter_res = run_strategy_bench(
        e2e_backend, "iterative", corpus_root, tok_spec
    )
    hier_res = run_strategy_bench(
        e2e_backend, "mapreduce_hierarchical", corpus_root, tok_spec
    )
    crit_res = run_strategy_bench(
        e2e_backend, "mapreduce_critique", corpus_root, tok_spec
    )

    # release the main engine's executables before the remaining phases
    # (same HBM-fragmentation reasoning as the map->e2e handoff above)
    del e2e_backend
    gc.collect()

    # weight-only-exact A/B at the e2e workload (4 docs, summarize-only):
    # W8A8 is the headline default since round 5 (quality bound:
    # artifacts/quality_lossy_ab.json); this row keeps the exact path's
    # cost visible so the 1.25x prefill claim stays continuously measured
    from vnsum_tpu.core.config import GenerationConfig

    exact_backend = TpuBackend(
        **{**e2e_engine_kwargs(tok_spec, params), "quantize_act": False},
        generation=GenerationConfig(
            max_new_tokens=128, temperature=1.0, seed=11, eos_ids=eos
        ),
    )
    exact_res = run_strategy_bench(
        exact_backend, "mapreduce", corpus_root, tok_spec
    )
    del exact_backend
    gc.collect()

    budget_res = run_device_budget(params, corpus_root, tok_spec, eos)

    chunks_per_sec = map_res["chunks_per_sec"]
    print(
        json.dumps(
            {
                "metric": "map_step_chunks_per_sec_per_chip_llama32_3b",
                "value": round(chunks_per_sec, 4),
                "unit": "chunks/s",
                "vs_baseline": round(chunks_per_sec / REFERENCE_CHUNKS_PER_SEC, 2),
                "mfu_prefill": budget_res["mfu_prefill"],
                "decode_roofline_frac": budget_res["decode_roofline_frac"],
                "e2e": e2e_res,
                "e2e_iterative": iter_res,
                "e2e_hierarchical": hier_res,
                "e2e_critique": crit_res,
                "e2e_weight_only_mapreduce": exact_res,
                "device_budget": budget_res,
            }
        )
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
