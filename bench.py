"""Benchmark: batched map-step generation throughput on one TPU chip.

Measures the engine doing what the reference does serially over HTTP: map-
phase summarization calls (prompt -> generated continuation) on Llama-3.2-3B.
The reference's best 3B-class throughput is ~0.25 chunks/sec TOTAL (VN-LongSum
iterative, llama3.2:3b, BASELINE.md); here a "chunk" is one map call
(bucket-1024 prompt + 128 generated tokens, batch 48, int8 weights — a
conservative quantization next to the reference's 4-bit Ollama defaults).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "chunks/s", "vs_baseline": N/0.25}
"""
from __future__ import annotations

import json
import sys
import time

REFERENCE_CHUNKS_PER_SEC = 0.25  # BASELINE.md: llama3.2:3b iterative, total


def main() -> int:
    from vnsum_tpu.backend.engine import TpuBackend
    from vnsum_tpu.models import llama32_3b

    prompt_tokens = 1000  # buckets to S=1024
    max_new = 128
    # measured sweet spot on v5e with the vectorized Pallas decode kernel +
    # int8 KV cache (B=64: 14.9, B=96: 15.8, B=128: OOM); the int8 cache
    # freed enough HBM for 96 rows
    batch = 96
    rounds = 3

    backend = TpuBackend(
        model_config=llama32_3b(max_seq_len=4096),
        tokenizer="byte",
        batch_size=batch,
        max_new_tokens=max_new,
        quantize=True,
    )

    base = (
        "Bạn là một chuyên gia tóm tắt nội dung. "
        "Vui lòng viết một bản tóm tắt chi tiết cho đoạn văn bản sau bằng tiếng Việt. "
    )
    filler = "Quốc hội đã thông qua nghị quyết về phát triển kinh tế xã hội. "
    prompt = base + filler * ((prompt_tokens - len(base.encode())) // len(filler.encode()))
    prompts = [prompt + f" (tài liệu {i})" for i in range(batch)]

    t0 = time.time()
    backend.generate(prompts)  # compile + warmup
    warmup = time.time() - t0
    print(f"warmup (incl. compile): {warmup:.1f}s", file=sys.stderr)

    t1 = time.time()
    done = 0
    for r in range(rounds):
        outs = backend.generate(
            [p + f" vòng {r}" for p in prompts]
        )
        done += len(outs)
    elapsed = time.time() - t1

    chunks_per_sec = done / elapsed
    stats = backend.stats
    print(
        f"{done} chunks in {elapsed:.1f}s; engine totals: "
        f"{stats.prompt_tokens} prompt tok, {stats.generated_tokens} gen tok, "
        f"{stats.tokens_per_second:.0f} tok/s overall",
        file=sys.stderr,
    )
    print(
        json.dumps(
            {
                "metric": "map_step_chunks_per_sec_per_chip_llama32_3b",
                "value": round(chunks_per_sec, 4),
                "unit": "chunks/s",
                "vs_baseline": round(chunks_per_sec / REFERENCE_CHUNKS_PER_SEC, 2),
            }
        )
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
