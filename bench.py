"""Benchmark: map-step throughput + end-to-end pipeline on one TPU chip.

Two phases, one shared set of int8 Llama-3.2-3B weights:

1. **Map-step microbench** — batched map-phase generation (bucket-1024
   prompts + 128 new tokens, batch 96), the engine doing what the reference
   does serially over HTTP. Reference total throughput is ~0.25 chunks/s
   (BASELINE.md, llama3.2:3b iterative — its best 3B number).
2. **End-to-end pipeline** — synthesize a VN-LongSum-shaped corpus (ragged
   ~54k byte-token docs, the reference's avg doc size in our token metric),
   then run the real `PipelineRunner` mapreduce path: split → batched map →
   collapse rounds → final reduce → write summaries → ROUGE + BERTScore +
   semsim evaluation. Wall-clock covers ALL of it, mirroring the reference's
   pipeline_results_*.json end-to-end timings (~0.076-0.25 chunks/s total).

Prints ONE JSON line: the map-step metric stays the headline (comparable
across rounds), with the e2e numbers nested under "e2e":
  {"metric": ..., "value": N, "unit": "chunks/s", "vs_baseline": N/0.25,
   "e2e": {"chunks_per_sec": ..., "docs_per_min": ..., "vs_baseline": ...}}
"""
from __future__ import annotations

import json
import sys
import tempfile
import time

REFERENCE_CHUNKS_PER_SEC = 0.25  # BASELINE.md: llama3.2:3b iterative, total

# e2e corpus shape: ragged docs averaging ~54k byte tokens (VN-LongSum's
# 54,566-token mean, metadata/doc_metadata.json, measured in our byte-token
# metric); 48 docs keeps the bench under ~5 min — docs/min extrapolates
E2E_DOCS = 48
E2E_WORDS_PER_DOC = 9_000  # ~54-57k bytes of Vietnamese text


def run_map_step_bench(backend) -> dict:
    prompt_tokens = 1000  # buckets to S=1024
    batch = backend.batch_size
    rounds = 3

    base = (
        "Bạn là một chuyên gia tóm tắt nội dung. "
        "Vui lòng viết một bản tóm tắt chi tiết cho đoạn văn bản sau bằng tiếng Việt. "
    )
    filler = "Quốc hội đã thông qua nghị quyết về phát triển kinh tế xã hội. "
    prompt = base + filler * ((prompt_tokens - len(base.encode())) // len(filler.encode()))
    prompts = [prompt + f" (tài liệu {i})" for i in range(batch)]

    t0 = time.time()
    backend.generate(prompts, max_new_tokens=128)  # compile + warmup
    print(f"warmup (incl. compile): {time.time() - t0:.1f}s", file=sys.stderr)

    t1 = time.time()
    done = 0
    for r in range(rounds):
        outs = backend.generate(
            [p + f" vòng {r}" for p in prompts], max_new_tokens=128
        )
        done += len(outs)
    elapsed = time.time() - t1

    stats = backend.stats
    print(
        f"map bench: {done} chunks in {elapsed:.1f}s; engine totals: "
        f"{stats.prompt_tokens} prompt tok, {stats.generated_tokens} gen tok, "
        f"{stats.tokens_per_second:.0f} tok/s overall",
        file=sys.stderr,
    )
    return {"chunks_per_sec": done / elapsed}


def _pick_ragged_eos(outs: list[str]) -> tuple[int, ...]:
    """Pick the output byte whose row coverage is closest to 50% — present
    in some rows but not others, so declaring it EOS produces genuinely
    ragged termination."""
    from collections import Counter

    rows = [o.encode("utf-8", "ignore") for o in outs if o]
    if not rows:
        return (10,)
    counts: Counter = Counter()
    for r in rows:
        counts.update(set(r))
    target = len(rows) / 2
    best = min(counts, key=lambda b: (abs(counts[b] - target), b))
    return (int(best),)


def run_e2e_bench(params) -> dict:
    from vnsum_tpu.backend.engine import TpuBackend
    from vnsum_tpu.core.config import GenerationConfig, PipelineConfig
    from vnsum_tpu.data.synthesize import synthesize_corpus
    from vnsum_tpu.models import llama32_3b
    from vnsum_tpu.pipeline.runner import PipelineRunner

    root = tempfile.mkdtemp(prefix="vnsum_bench_")
    t0 = time.time()
    stats = synthesize_corpus(
        f"{root}/corpus", n_docs=E2E_DOCS, tokens_per_doc=E2E_WORDS_PER_DOC,
        summary_tokens=714, seed=7, ragged=0.5,
    )
    print(
        f"e2e corpus: {E2E_DOCS} docs, "
        f"avg {stats['documents']['avg_tokens_per_file']:.0f} words "
        f"(synth {time.time() - t0:.1f}s)",
        file=sys.stderr,
    )

    # chunk_size 7800 byte tokens lands prompts in the S=8192 bucket; int8 KV
    # keeps 8 rows of 8320-token cache (+ int8 weights + the ~4 GB of
    # prefill transients at S=8192) inside one v5e chip — B=16 OOMs
    backend = TpuBackend(
        model_config=llama32_3b(max_seq_len=8448),
        tokenizer="byte",
        params=params,  # shared with the map bench — no re-init/re-quantize
        batch_size=8,
        max_new_tokens=128,
        quantize=True,
        segment_tokens=32,  # engage continuous scheduling + tail compaction
        min_batch=2,
    )
    cfg = PipelineConfig(
        approach="mapreduce",
        models=["llama3.2-3b"],
        backend="tpu",
        docs_dir=f"{root}/corpus/doc",
        summary_dir=f"{root}/corpus/summary",
        generated_summaries_dir=f"{root}/gen",
        results_dir=f"{root}/results",
        logs_dir=f"{root}/logs",
        chunk_size=7_800,
        chunk_overlap=200,
        # collapse budget in whitespace WORDS (reference-parity gating);
        # capped low enough that a worst-case all-ASCII grouping still fits
        # the model's 8320-byte-token input — reduce prompts must never be
        # silently truncated by the engine
        token_max=6_000,
        max_new_tokens=128,
        batch_size=8,
        tokenizer="byte",
    )
    # random-init weights never emit the true EOS, so decode would always
    # pay the full budget and early-exit/compaction would sit idle — and
    # under GREEDY decode the rollouts degenerate (round 2's summaries were
    # all empty: the near-constant argmax stream hit the probed EOS byte at
    # position 0). Run the e2e with SAMPLED decode instead: temperature 1.0
    # over a random-init model gives high-entropy byte streams, so declaring
    # a ~50%-coverage byte as EOS terminates rows raggedly at varied depths
    # — the workload shape a real checkpoint produces — and summaries stay
    # non-empty for a realistic evaluation pass. Sampling is
    # compaction-safe since round 3 (per-row counter-based RNG).
    sample_doc = open(f"{root}/corpus/doc/doc_000.txt", encoding="utf-8").read()
    # slice by BYTES (the engine's token metric): char slices of Vietnamese
    # run ~1.3 bytes/char and would land the probe in a bucket the pipeline
    # never uses, wasting its compile instead of pre-warming S=8192
    raw = sample_doc.encode("utf-8")
    probe_prompts = [
        "Tóm tắt: " + raw[i * 7000 : (i + 1) * 7000].decode("utf-8", "ignore")
        for i in range(8)
    ]
    probe = backend.generate(
        probe_prompts, config=GenerationConfig(temperature=1.0, seed=11)
    )
    eos = _pick_ragged_eos(probe)
    backend.gen_cfg = GenerationConfig(
        max_new_tokens=128, temperature=1.0, seed=11, eos_ids=eos
    )
    print(f"e2e ragged-eos byte: {eos}", file=sys.stderr)

    runner = PipelineRunner(cfg, backend_factory=lambda model: backend)

    t1 = time.time()
    results = runner.run()
    elapsed = time.time() - t1

    # itemized wall-clock budget (tracer spans) — the e2e number is only
    # actionable with its breakdown (where does non-generation time go?)
    spans = results.tracing.get("spans", {})
    budget = {
        name: round(s["total_s"], 1)
        for name, s in spans.items()
        if name in (
            "analyze", "summarize", "evaluate",
            "evaluate/embedder_init", "evaluate/embed",
            "evaluate/bertscore", "evaluate/rouge",
        )
    }
    for name, secs in sorted(budget.items()):
        print(f"e2e span {name}: {secs}s", file=sys.stderr)

    rec = results.summarization["llama3.2-3b"]
    total_chunks = rec["total_chunks"]
    docs = rec["successful"]
    if not docs:
        raise RuntimeError(f"e2e bench: all documents failed — see {root}/logs")
    chunks_per_sec = total_chunks / elapsed
    ev = results.evaluation.get("llama3.2-3b", {})
    rougel = ev.get("rouge_scores", {}).get("rougeL_f1", float("nan"))
    print(
        f"e2e pipeline: {docs} docs / {total_chunks} chunks in {elapsed:.1f}s "
        f"(map+collapse+reduce+eval); engine: {backend.stats.batches} batches, "
        f"{backend.stats.compactions} compactions, "
        f"{backend.stats.tokens_per_second:.0f} tok/s; rougeL={rougel:.4f}",
        file=sys.stderr,
    )
    return {
        "chunks_per_sec": round(chunks_per_sec, 4),
        "docs_per_min": round(docs / (elapsed / 60), 2),
        "seconds_total": round(elapsed, 1),
        "chunks": total_chunks,
        "docs": docs,
        "compactions": backend.stats.compactions,
        "vs_baseline": round(chunks_per_sec / REFERENCE_CHUNKS_PER_SEC, 2),
        "time_budget": budget,
    }


def main() -> int:
    from vnsum_tpu.backend.engine import TpuBackend
    from vnsum_tpu.models import llama32_3b

    # measured sweet spot on v5e with the vectorized Pallas decode kernel +
    # int8 KV cache (B=64: 14.9, B=96: 15.8, B=128: OOM); the int8 cache
    # freed enough HBM for 96 rows
    backend = TpuBackend(
        model_config=llama32_3b(max_seq_len=4096),
        tokenizer="byte",
        batch_size=96,
        max_new_tokens=128,
        quantize=True,
    )

    map_res = run_map_step_bench(backend)

    # release the B=96 map-bench programs before the e2e phase: their
    # executables (and any buffers they pin) otherwise stay resident next to
    # the e2e engine's own programs, squeezing the evaluation encoder into
    # fragmented HBM (round-2's 442s eval tail)
    params = backend.params
    del backend
    import gc

    gc.collect()

    e2e_res = run_e2e_bench(params)

    chunks_per_sec = map_res["chunks_per_sec"]
    print(
        json.dumps(
            {
                "metric": "map_step_chunks_per_sec_per_chip_llama32_3b",
                "value": round(chunks_per_sec, 4),
                "unit": "chunks/s",
                "vs_baseline": round(chunks_per_sec / REFERENCE_CHUNKS_PER_SEC, 2),
                "e2e": e2e_res,
            }
        )
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
