"""In-flight slot loop over the REAL engine (CPU, tiny model).

The contract under test is the tentpole's correctness claim: a request's
greedy output is byte-identical to a solo one-shot generate() no matter when
it joined the resident batch, who it decoded next to, or which slot it
landed in — and a sampled request's stream depends only on (loop seed,
request uid, row-local step), never on join timing or companions.
"""
from __future__ import annotations

import numpy as np
import pytest

from vnsum_tpu.backend.engine import TpuBackend
from vnsum_tpu.core.config import GenerationConfig
from vnsum_tpu.models import tiny_llama

PROMPTS = [
    "văn bản một về kinh tế",
    "hai",
    "văn bản thứ ba dài hơn một chút về xã hội",
    "bốn bốn",
    "năm năm năm",
    "sáu và bảy",
]


def make_backend(**kw):
    kw.setdefault("model_config", tiny_llama(max_seq_len=128))
    kw.setdefault("tokenizer", "byte")
    kw.setdefault("batch_size", 8)
    kw.setdefault("max_new_tokens", 24)
    kw.setdefault("seed", 1)
    kw.setdefault("segment_tokens", 4)
    return TpuBackend(**kw)


def drain(loop, outs, max_segments=64):
    for _ in range(max_segments):
        res = loop.step()
        for c in res.completions:
            outs[c.key] = c.text
        if loop.active == 0:
            return
    raise AssertionError("slot loop did not drain")


def ragged_eos_config(max_new=24):
    """A GenerationConfig whose extra EOS fires at scattered depths, so
    rows FINISH at different segments and freed slots actually refill
    mid-flight (the same probe trick as the continuous-scheduling tests)."""
    probe = make_backend()
    outs = probe.generate(PROMPTS)
    tok = probe.tok
    ids = [tok.encode(o, add_bos=False) for o in outs if o]
    longest = max(ids, key=len)
    return GenerationConfig(
        eos_ids=(tok.eos_id, longest[len(longest) // 2]),
        max_new_tokens=max_new,
    )


# -- greedy byte-identity ----------------------------------------------------


def test_greedy_matches_solo_with_staggered_joins_and_leaves():
    gen = ragged_eos_config()
    solo_backend = make_backend()
    solo = [solo_backend.generate([p], config=gen)[0] for p in PROMPTS]

    b = make_backend()
    loop = b.start_slot_loop(4, config=gen)
    outs: dict[int, str] = {}
    adm, rej = loop.admit([(i, PROMPTS[i], None) for i in (0, 1, 2)])
    # 3 joiners bucket to Bj=4, which fits the 4 free slots (the filler row
    # lands on the spare free slot and stays free)
    assert rej == [] and len(adm) == 3
    # rows decode; at each boundary refill whatever waits
    pending = [i for i in range(len(PROMPTS))
               if i not in {a.key for a in adm}]
    for _ in range(64):
        res = loop.step()
        for c in res.completions:
            outs[c.key] = c.text
        if pending and loop.free:
            adm, rej = loop.admit([(i, PROMPTS[i], None) for i in pending])
            assert rej == []
            for a in adm:
                pending.remove(a.key)
        if not pending and loop.active == 0:
            break
    assert loop.active == 0 and not pending
    assert [outs[i] for i in range(len(PROMPTS))] == solo
    # raggedness really happened: termination depths differ
    assert len({len(s) for s in solo}) > 1
    # and the loop really refilled (more admissions than one batch's worth)
    assert loop.refills == len(PROMPTS)


def test_slots_at_different_depths_decode_together():
    """A late joiner decodes next to residents that are several segments
    deep — its output must equal its solo run (per-row budgets, per-row
    masks)."""
    b = make_backend()
    solo = make_backend().generate([PROMPTS[3]])[0]
    loop = b.start_slot_loop(4)
    loop.admit([(0, PROMPTS[0], None), (1, PROMPTS[2], None)])
    loop.step()
    loop.step()  # residents now ~8 tokens deep
    adm, _ = loop.admit([(3, PROMPTS[3], None)])
    assert len(adm) == 1
    outs: dict[int, str] = {}
    drain(loop, outs)
    assert outs[3] == solo


# -- sampled-stream stability ------------------------------------------------


def test_sampled_stream_independent_of_join_timing_and_companions():
    """Same loop seed + same request uid => identical sampled stream, even
    when the request joins at a different segment, into a different slot,
    next to different companions. Streams key on (loop seed, uid, row-local
    t), so none of those may matter."""
    gen = GenerationConfig(temperature=1.0, seed=7, max_new_tokens=24)
    target = PROMPTS[2]

    # scenario A: target admitted together with a companion (uid 1, slot 1)
    a = make_backend()
    loop_a = a.start_slot_loop(4, config=gen)
    loop_a.admit([(0, PROMPTS[0], None), ("t", target, None)])
    outs_a: dict = {}
    drain(loop_a, outs_a)

    # scenario B: different companion admitted first and decoded 2 segments
    # deep; target joins mid-flight (still uid 1, different slot history)
    b = make_backend()
    loop_b = b.start_slot_loop(4, config=gen)
    loop_b.admit([(0, PROMPTS[4], None)])
    loop_b.step()
    loop_b.step()
    adm, _ = loop_b.admit([("t", target, None)])
    assert len(adm) == 1
    outs_b: dict = {}
    drain(loop_b, outs_b)

    assert outs_a["t"] == outs_b["t"]
    # the companions differed, so this was not a trivially identical run
    assert outs_a[0] != "" or outs_b[0] != ""


# -- prefix-cache interaction ------------------------------------------------


def test_refill_resumes_from_prefix_cache_under_eviction_churn():
    """Joiners resume prefill from the radix cache while LRU eviction
    churns the (tiny) block pool — outputs stay byte-identical to a
    cache-less backend's solo runs."""
    header = "tiêu đề chung của các tài liệu dài: "
    prompts = [header + f"nội dung {i} " * 3 for i in range(6)]
    solo_backend = make_backend()
    solo = [solo_backend.generate([p])[0] for p in prompts]

    b = make_backend(cache_blocks=6, cache_block_tokens=16)
    loop = b.start_slot_loop(4)
    outs: dict[int, str] = {}
    pending = list(range(len(prompts)))
    adm, _ = loop.admit([(i, prompts[i], header) for i in pending[:2]])
    for a in adm:
        pending.remove(a.key)
    for _ in range(64):
        res = loop.step()
        for c in res.completions:
            outs[c.key] = c.text
        if pending and loop.free:
            adm, rej = loop.admit(
                [(i, prompts[i], header) for i in pending]
            )
            assert rej == []
            for a in adm:
                pending.remove(a.key)
        if not pending and loop.active == 0:
            break
    assert [outs[i] for i in range(len(prompts))] == solo
    # the pool really churned: insertions exceeded the budget
    st = b.prefix_cache.stats_dict()
    assert st["evictions"] > 0 or st["blocks_used"] <= 6


# -- preemption (serve/qos.py priority tiers) --------------------------------


def test_evict_frees_slots_and_readmit_is_byte_identical():
    """Mid-decode eviction on the REAL loop: the victim's slot frees at the
    next segment, survivors are unaffected, and re-admitting the evicted
    prompt restarts it to a byte-identical greedy output — the preemption
    round-trip losslessness claim on real engine state."""
    b = make_backend()
    solo = [make_backend().generate([p])[0] for p in PROMPTS[:2]]
    loop = b.start_slot_loop(2)
    adm, _ = loop.admit([(i, PROMPTS[i], None) for i in (0, 1)])
    assert len(adm) == 2
    loop.step()  # a couple of segments of real decode progress
    loop.step()
    victim = adm[0].key
    evs = loop.evict([victim])
    assert [e.key for e in evs] == [victim]
    assert loop.free == 1 and victim not in loop.outstanding()
    outs: dict[int, str] = {}
    drain(loop, outs)                       # survivor finishes undisturbed
    assert outs[1] == solo[1]
    adm2, _ = loop.admit([(0, PROMPTS[0], None)])  # the requeue's re-admit
    assert len(adm2) == 1
    drain(loop, outs)
    assert outs[0] == solo[0]


def test_evict_pins_prefix_blocks_until_released():
    """Eviction with the radix cache armed returns a live pin: the
    victim's cached prefix is unevictable until the scheduler-side release
    — and releasing restores the pre-eviction pin level."""
    header = "tiêu đề chung: "
    b = make_backend(cache_blocks=8, cache_block_tokens=16)
    loop = b.start_slot_loop(2)
    adm, rej = loop.admit([(0, header + "nội dung một hai", header)])
    assert len(adm) == 1 and rej == []
    loop.step()
    assert b.prefix_cache.index.pinned_blocks == 0  # admit released its pins
    evs = loop.evict([adm[0].key])
    assert evs[0].pin is not None
    assert b.prefix_cache.index.pinned_blocks > 0   # held across eviction
    cache, match = evs[0].pin
    cache.release(match)
    assert b.prefix_cache.index.pinned_blocks == 0
    loop.close()


def test_partial_outputs_are_prefixes_of_the_final_text():
    """The streaming harvest: per-segment partial detok of a resident row
    extends monotonically into exactly the harvested completion text."""
    b = make_backend()
    loop = b.start_slot_loop(2)
    adm, _ = loop.admit([(0, PROMPTS[2], None)])
    assert len(adm) == 1
    key = adm[0].key
    snapshots = []
    final = {}
    for _ in range(64):
        res = loop.step()
        for c in res.completions:
            final[c.key] = c.text
        if loop.active:
            part = loop.partial_outputs([key])
            if part:
                snapshots.append(part[id(key)])
        if not loop.active:
            break
    assert final[0] == make_backend().generate([PROMPTS[2]])[0]
    grown = [s for s in snapshots if s]
    assert grown, "no partial text surfaced during decode"
    for a, bnext in zip(grown, grown[1:]):
        assert bnext.startswith(a)
    assert final[0].startswith(grown[-1])


# -- fused multi-step decode (--fused-segments) ------------------------------


@pytest.mark.parametrize("fused", [2, 4])
def test_fused_byte_identity_vs_n1_with_staggered_joins(fused):
    """N on-device segments per host dispatch run the SAME per-row update
    as N=1 — only the host round-trip cadence changes — so greedy outputs
    must stay byte-identical under staggered joins and ragged EOS exits,
    while the segments/dispatches counters diverge by the fusing win."""
    gen = ragged_eos_config()

    def run(n):
        b = make_backend()
        loop = b.start_slot_loop(4, config=gen, fused_segments=n)
        outs: dict[int, str] = {}
        adm, rej = loop.admit([(i, PROMPTS[i], None) for i in (0, 1, 2)])
        assert rej == []
        pending = [i for i in range(len(PROMPTS))
                   if i not in {a.key for a in adm}]
        for _ in range(64):
            res = loop.step()
            for c in res.completions:
                outs[c.key] = c.text
            if pending and loop.free:
                adm, rej = loop.admit(
                    [(i, PROMPTS[i], None) for i in pending]
                )
                assert rej == []
                for a in adm:
                    pending.remove(a.key)
            if not pending and loop.active == 0:
                break
        assert loop.active == 0 and not pending
        return [outs[i] for i in range(len(PROMPTS))], loop

    base, base_loop = run(1)
    fused_outs, loop = run(fused)
    assert fused_outs == base
    # at N=1 every dispatch is one segment; fused really amortized: more
    # on-device segments retired than host round-trips, and fewer
    # round-trips than the unfused run needed
    assert base_loop.segments == base_loop.fused_dispatches
    assert loop.segments > loop.fused_dispatches
    assert loop.fused_dispatches < base_loop.fused_dispatches


def test_fused_early_stop_and_device_segment_accounting():
    """The fused while_loop stops on-device the moment every row is done:
    a single resident retires in ONE host round-trip even at fused=8, and
    device_segments reports the segments actually run — never the fused
    bound — so the histogram sees real amortization, not the knob."""
    solo = make_backend().generate([PROMPTS[2]])[0]
    b = make_backend()
    loop = b.start_slot_loop(2, fused_segments=8)
    adm, _ = loop.admit([(0, PROMPTS[2], None)])
    assert len(adm) == 1
    res = loop.step()
    assert loop.active == 0 and loop.fused_dispatches == 1
    assert [c.text for c in res.completions] == [solo]
    # ceil(tokens / segment_tokens) segments ran on device, strictly under
    # the fused bound of 8 (max_new=24, segment_tokens=4 -> at most 6)
    assert res.device_segments == -(-res.new_tokens // b.segment_tokens)
    assert 1 <= res.device_segments <= 6
    assert loop.segments == res.device_segments
    loop.close()


def test_fused_partial_outputs_ride_the_boundary_snapshot():
    """Streaming partials at fused cadence are served from the coalesced
    boundary fetch (no extra device sync) and still extend monotonically
    into the final text."""
    b = make_backend()
    loop = b.start_slot_loop(2, fused_segments=2)
    adm, _ = loop.admit([(0, PROMPTS[2], None)])
    assert len(adm) == 1
    key = adm[0].key
    snapshots = []
    final = {}
    for _ in range(64):
        res = loop.step()
        for c in res.completions:
            final[c.key] = c.text
        if loop.active:
            part = loop.partial_outputs([key])
            if part:
                snapshots.append(part[id(key)])
        if not loop.active:
            break
    assert final[0] == make_backend().generate([PROMPTS[2]])[0]
    grown = [s for s in snapshots if s]
    assert grown, "no partial text surfaced during fused decode"
    for a, bnext in zip(grown, grown[1:]):
        assert bnext.startswith(a)
    assert final[0].startswith(grown[-1])


# -- slot bookkeeping --------------------------------------------------------


def test_oversized_prompt_rejected_for_oneshot_fallback():
    b = make_backend()
    loop = b.start_slot_loop(2, prompt_tokens=64)
    assert loop.S == 64
    big = "x" * 200  # 200 byte tokens + bos > 64
    adm, rej = loop.admit([("big", big, None), ("ok", "nhỏ", None)])
    assert rej == ["big"]
    assert [a.key for a in adm] == ["ok"]
    outs: dict = {}
    drain(loop, outs)
    assert outs["ok"] == make_backend().generate(["nhỏ"])[0]


def test_join_bucket_never_exceeds_free_slots():
    b = make_backend()
    loop = b.start_slot_loop(4)
    loop.admit([(0, PROMPTS[0], None)])     # 1 busy, 3 free
    adm, _ = loop.admit([(i, PROMPTS[i], None) for i in (1, 2, 3)])
    # 3 joiners bucket to Bj=4 > 3 free -> clamped to a clean power of two
    assert len(adm) == 2 and loop.free == 1
    adm2, _ = loop.admit([(3, PROMPTS[3], None)])
    assert len(adm2) == 1 and loop.free == 0
    outs: dict = {}
    drain(loop, outs)
    assert set(outs) == {0, 1, 2, 3}


def test_closed_loop_refuses_work():
    b = make_backend()
    loop = b.start_slot_loop(2)
    loop.close()
    with pytest.raises(RuntimeError, match="closed"):
        loop.admit([(0, PROMPTS[0], None)])
    with pytest.raises(RuntimeError, match="closed"):
        loop.step()


def test_slot_count_must_divide_mesh_data_axis():
    """The resident batch rows shard over `data`, so a slot count the axis
    does not divide is a config error at loop construction, not an XLA
    divisibility failure mid-serve."""
    b = make_backend()

    class FakeMesh:  # engine only reads .shape before building the loop
        shape = {"data": 3}

    b.mesh = FakeMesh()
    with pytest.raises(ValueError, match="divisible by the mesh data axis"):
        b.start_slot_loop(4)
