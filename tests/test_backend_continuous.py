"""Continuous scheduling (segmented decode + tail compaction).

Greedy parity: each row's token stream depends only on its own cache, so
the continuous path must produce byte-identical output to the one-shot
while_loop path, including when compaction rebatches mid-generation."""
import jax.numpy as jnp
import numpy as np
import pytest

from vnsum_tpu.backend.engine import TpuBackend
from vnsum_tpu.core.config import GenerationConfig
from vnsum_tpu.models import tiny_llama


def make_backend(continuous, **kw):
    return TpuBackend(
        model_config=tiny_llama(max_seq_len=128),
        tokenizer="byte",
        batch_size=8,
        max_new_tokens=24,
        seed=1,
        continuous=continuous,
        **kw,
    )


PROMPTS = [
    "văn bản một về kinh tế",
    "hai",
    "văn bản thứ ba dài hơn một chút về xã hội",
    "bốn bốn",
    "năm năm năm",
    "sáu",
]


def test_continuous_matches_oneshot_greedy():
    plain = make_backend(False)
    cont = make_backend(True, segment_tokens=4, min_batch=1)
    np.testing.assert_array_equal(
        plain.generate(PROMPTS), cont.generate(PROMPTS)
    )


def test_continuous_with_ragged_eos_and_compaction():
    """Force ragged termination by declaring a COMMON token as EOS: rows
    finish at different steps, compaction must fire, and outputs still
    match the one-shot path exactly."""
    # find a token that actually appears early in greedy rollouts
    probe = make_backend(False)
    outs = probe.generate(PROMPTS)
    tok = probe.tok
    ids = [tok.encode(o, add_bos=False) for o in outs if o]
    assert ids, "probe produced no output; pick a different seed"
    # a token from the middle of the longest rollout => some rows hit it
    # early, others late or never
    longest = max(ids, key=len)
    eos_extra = longest[len(longest) // 2]
    gen = GenerationConfig(eos_ids=(tok.eos_id, eos_extra), max_new_tokens=24)

    plain = make_backend(False)
    cont = make_backend(True, segment_tokens=4, min_batch=1)
    a = plain.generate(PROMPTS, config=gen)
    b = cont.generate(PROMPTS, config=gen)
    np.testing.assert_array_equal(a, b)
    # raggedness check: termination steps must differ across rows
    lens = {len(x) for x in a}
    assert len(lens) > 1, a


def test_compaction_fires_and_is_counted():
    probe = make_backend(False)
    outs = probe.generate(PROMPTS)
    tok = probe.tok
    longest = max(
        (tok.encode(o, add_bos=False) for o in outs if o), key=len
    )
    gen = GenerationConfig(
        eos_ids=(tok.eos_id, longest[len(longest) // 2]), max_new_tokens=24
    )
    cont = make_backend(True, segment_tokens=2, min_batch=1)
    cont.generate(PROMPTS, config=gen)
    assert cont.stats.compactions >= 1


def test_continuous_single_prompt():
    cont = make_backend(True, segment_tokens=4, min_batch=1)
    plain = make_backend(False)
    np.testing.assert_array_equal(
        plain.generate(["một văn bản"]), cont.generate(["một văn bản"])
    )


def test_continuous_auto_policy_is_oneshot():
    """continuous='auto' resolves to the one-shot program: the measured A/B
    (PERF.md finding 13, artifacts/compaction_ab.json) shows the segmented
    path losing token-normalized at every tested shape. Explicit True still
    enables it."""
    auto = TpuBackend(
        model_config=tiny_llama(max_seq_len=128), batch_size=32,
        max_new_tokens=8,
    )
    assert auto.continuous is False
    forced = TpuBackend(
        model_config=tiny_llama(max_seq_len=128), batch_size=4,
        max_new_tokens=8, continuous=True,
    )
    assert forced.continuous is True


def test_sampled_continuous_matches_oneshot():
    """Sampled decode is compaction-safe since round 3: each row's stream is
    keyed by (seed, row uid, step) — counter-based, independent of batch
    position — so the segmented path with tail compaction must reproduce the
    one-shot sampled output bit-exactly."""
    gen = GenerationConfig(temperature=0.8, max_new_tokens=24, seed=5)
    plain = make_backend(False)
    a = plain.generate(PROMPTS, config=gen)
    cont = make_backend(True, segment_tokens=4, min_batch=1)
    b = cont.generate(PROMPTS, config=gen)
    np.testing.assert_array_equal(a, b)
    assert cont._seg_fns  # the segmented path actually ran


def test_sampled_compaction_fires_and_matches():
    """Force ragged sampled termination so compaction fires mid-stream, and
    check outputs still match the one-shot program. Sampled streams are
    counter-based — a same-seed rerun replays the probe's streams exactly —
    so declaring ids observed EARLY in most probe rows as EOS pins most
    rows' termination points near the start, guaranteeing the live set
    shrinks below the compaction threshold well before the budget."""
    gen0 = GenerationConfig(temperature=0.9, max_new_tokens=24, seed=3)
    probe = make_backend(False)
    outs = probe.generate(PROMPTS, config=gen0)
    tok = probe.tok
    ids = [tok.encode(o, add_bos=False) for o in outs if len(o) > 4]
    assert len(ids) >= 4, outs
    # position-4 byte of four rows => those rows stop by step ~5 in the
    # replay, leaving <= 2 rows live for the rest of the 24-token budget
    eos_extra = {row[4] for row in ids[:4]}
    gen = gen0.with_(eos_ids=(tok.eos_id, *sorted(eos_extra)))

    plain = make_backend(False)
    a = plain.generate(PROMPTS, config=gen)
    cont = make_backend(True, segment_tokens=2, min_batch=1)
    b = cont.generate(PROMPTS, config=gen)
    np.testing.assert_array_equal(a, b)
    assert cont.stats.compactions >= 1
    assert len({len(x) for x in a}) > 1, a
