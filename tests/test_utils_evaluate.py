"""Tests for the simple folder-vs-folder evaluator CLI (capability match for
the reference's utils/evaluate_summaries.py:27-106, SURVEY.md §2 C10)."""
import json

import pytest

from vnsum_tpu.eval import EmbeddingModel
from vnsum_tpu.models.encoder import tiny_encoder
from vnsum_tpu.utils.evaluate_summaries import (
    evaluate_summaries,
    format_report,
    main,
)


@pytest.fixture()
def folders(tmp_path):
    gen = tmp_path / "gen"
    ref = tmp_path / "ref"
    gen.mkdir()
    ref.mkdir()
    pairs = {
        "a.txt": ("tóm tắt văn bản một", "tóm tắt văn bản một"),
        "b.txt": ("nội dung hoàn toàn khác", "tóm tắt văn bản hai"),
    }
    for name, (g, r) in pairs.items():
        (gen / name).write_text(g, encoding="utf-8")
        (ref / name).write_text(r, encoding="utf-8")
    (gen / "unpaired.txt").write_text("không có tham chiếu", encoding="utf-8")
    return gen, ref


def test_rouge_only(folders):
    gen, ref = folders
    res = evaluate_summaries(gen, ref, skip_bert=True)
    assert res["num_pairs"] == 2  # unpaired file skipped
    # a.txt is identical -> perfect rouge1
    assert res["per_file"]["a.txt"]["rouge1"]["f1"] == pytest.approx(1.0)
    agg = res["aggregate"]
    assert set(agg) == {"rouge1", "rouge2", "rougeL"}
    assert 0.0 < agg["rouge1"]["f1"] <= 1.0


def test_with_bert_scores(folders):
    gen, ref = folders
    embedder = EmbeddingModel(config=tiny_encoder(), max_len=32, batch_size=2)
    res = evaluate_summaries(gen, ref, embedding_model=embedder)
    assert "bert" in res["aggregate"]
    assert "bert" in res["per_file"]["a.txt"]
    # identical pair must score at least as high as the mismatched pair
    assert (
        res["per_file"]["a.txt"]["bert"]["f1"]
        >= res["per_file"]["b.txt"]["bert"]["f1"]
    )


def test_empty_intersection_raises(tmp_path):
    gen = tmp_path / "gen"
    ref = tmp_path / "ref"
    gen.mkdir()
    ref.mkdir()
    (gen / "x.txt").write_text("a")
    (ref / "y.txt").write_text("b")
    with pytest.raises(ValueError, match="no common filenames"):
        evaluate_summaries(gen, ref, skip_bert=True)


def test_cli_main_writes_output(folders, tmp_path, capsys):
    gen, ref = folders
    out = tmp_path / "results" / "eval.json"
    rc = main([str(gen), str(ref), "--skip-bert", "--output", str(out)])
    assert rc == 0
    printed = capsys.readouterr().out
    assert "Evaluated 2 summary pairs" in printed
    assert "rouge1" in printed
    data = json.loads(out.read_text())
    assert data["num_pairs"] == 2
    assert "aggregate" in data and "per_file" in data


def test_format_report_shows_all_metrics(folders):
    gen, ref = folders
    res = evaluate_summaries(gen, ref, skip_bert=True, max_samples=1)
    assert res["num_pairs"] == 1
    report = format_report(res)
    for m in ("rouge1", "rouge2", "rougeL"):
        assert m in report
