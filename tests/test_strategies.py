import pytest

from vnsum_tpu.backend import FakeBackend
from vnsum_tpu.core import PipelineConfig
from vnsum_tpu.strategies import (
    HierarchicalStrategy,
    IterativeStrategy,
    MapReduceCritiqueStrategy,
    MapReduceStrategy,
    TruncatedStrategy,
    get_strategy,
    split_by_token_budget,
)
from vnsum_tpu.text import RecursiveTokenSplitter
from vnsum_tpu.text.tokenizer import whitespace_token_count


def make_doc(n_paras=30, words_per=40):
    return "\n\n".join(
        " ".join(f"từ{p}_{w}" for w in range(words_per)) for p in range(n_paras)
    )


def word_splitter(chunk_size=100, overlap=0):
    return RecursiveTokenSplitter(
        chunk_size, overlap, length_function=whitespace_token_count
    )


def test_split_by_token_budget():
    texts = ["a " * 10, "b " * 10, "c " * 10]
    groups = split_by_token_budget([t.strip() for t in texts], 20)
    assert [len(g) for g in groups] == [2, 1]
    # oversized single text gets its own group
    groups = split_by_token_budget(["x " * 50, "y"], 20)
    assert len(groups) == 2


def test_mapreduce_single_doc():
    fb = FakeBackend(summary_words=10)
    st = MapReduceStrategy(fb, word_splitter(), token_max=1000)
    doc = make_doc()
    res = st.summarize(doc)
    assert res.summary
    assert res.num_chunks > 1
    # map prompts contain chunk text; last call is the final reduce
    assert "tập hợp các bản tóm tắt" in fb.calls[-1]


def test_mapreduce_collapse_loop_terminates():
    # tiny token_max forces collapse rounds; summaries shrink -> terminates
    fb = FakeBackend(summary_words=30)
    st = MapReduceStrategy(fb, word_splitter(), token_max=60)
    res = st.summarize(make_doc(40, 40))
    assert res.summary
    assert res.rounds >= 1


def test_mapreduce_batch_matches_single():
    docs = [make_doc(10, 20), make_doc(15, 25)]
    fb1 = FakeBackend(summary_words=12)
    st1 = MapReduceStrategy(fb1, word_splitter(), token_max=500)
    singles = [st1.summarize(d).summary for d in docs]
    fb2 = FakeBackend(summary_words=12)
    st2 = MapReduceStrategy(fb2, word_splitter(), token_max=500)
    batch = [r.summary for r in st2.summarize_batch(docs)]
    assert batch == singles


def test_truncated():
    fb = FakeBackend(summary_words=8)
    st = TruncatedStrategy(fb, max_context=200, max_new_tokens=50)
    doc = "xin chào " * 500
    res = st.summarize(doc)
    assert res.num_chunks == 1 and res.llm_calls == 1
    # prompt was truncated to max_context - max_new_tokens tokens (bytes here)
    assert len(fb.calls[0].encode()) < 600


def test_iterative_sequential_refinement():
    fb = FakeBackend(summary_words=15)
    st = IterativeStrategy(fb, word_splitter(50))
    doc = make_doc(10, 30)
    res = st.summarize(doc)
    assert res.num_chunks > 1
    assert res.rounds == res.num_chunks
    # first call is the initial prompt, later ones are refine prompts
    assert "nền tảng" in fb.calls[0]
    assert "biên tập viên" in fb.calls[1]


def test_iterative_batch_lockstep():
    docs = [make_doc(4, 30), make_doc(8, 30)]
    fb = FakeBackend(summary_words=15)
    st = IterativeStrategy(fb, word_splitter(50))
    rs = st.summarize_batch(docs)
    assert rs[0].num_chunks < rs[1].num_chunks
    assert all(r.summary for r in rs)


def test_critique_accept_path():
    # scripted: map x2, reduce, critique says no issues -> no refine, final
    # reduce + critique accept again
    fb = FakeBackend(
        responses=[
            "tóm tắt 1", "tóm tắt 2",          # map (2 chunks)
            "tóm tắt cuối", "Không có vấn đề",  # final reduce + critique accept
        ]
    )
    st = MapReduceCritiqueStrategy(fb, word_splitter(50), token_max=1000)
    doc = make_doc(4, 20)
    res = st.summarize(doc)
    assert res.summary == "tóm tắt cuối"


def test_critique_refine_path():
    fb = FakeBackend(
        responses=[
            "tóm tắt 1", "tóm tắt 2",
            "tóm tắt cuối", "Thiếu thông tin về sự kiện X", "tóm tắt đã sửa",
        ]
    )
    st = MapReduceCritiqueStrategy(fb, word_splitter(50), token_max=1000)
    res = st.summarize(make_doc(4, 20))
    assert res.summary == "tóm tắt đã sửa"
    # the refine prompt carried the critique text
    assert any("sự kiện X" in c for c in fb.calls)


def test_critique_iteration_cap_skips_critique():
    fb = FakeBackend(summary_words=20)
    st = MapReduceCritiqueStrategy(
        fb, word_splitter(50), token_max=40, max_critique_iterations=1
    )
    res = st.summarize(make_doc(20, 30))
    assert res.summary
    assert res.rounds >= 1


def make_tree():
    return {
        "type": "Document",
        "text": "Tài liệu",
        "children": [
            {
                "type": "Header",
                "text": "Chương 1",
                "children": [
                    {"type": "Paragraph", "text": "nội dung một " * 30},
                    {"type": "Paragraph", "text": "nội dung hai " * 30},
                ],
            },
            {
                "type": "Header",
                "text": "Chương 2",
                "children": [{"type": "Paragraph", "text": "nội dung ba " * 30}],
            },
        ],
    }


def test_hierarchical_tree_collapse():
    fb = FakeBackend(summary_words=10)
    st = HierarchicalStrategy(fb, chunk_size=100, chunk_overlap=0, max_depth=2)
    tree = make_tree()
    res = st.summarize_tree(tree)
    assert res.summary
    # tree fully collapsed: children all Paragraphs now
    assert all(c["type"] == "Paragraph" for c in tree["children"])
    # polish prompt ran last
    assert "biên tập viên" in fb.calls[-1]


def test_hierarchical_plain_text_fallback():
    fb = FakeBackend(summary_words=10)
    st = HierarchicalStrategy(fb, chunk_size=100, chunk_overlap=0)
    res = st.summarize("văn bản thuần túy " * 100)
    assert res.summary


def test_get_strategy_factory():
    cfg = PipelineConfig()
    fb = FakeBackend()
    for name in (
        "mapreduce", "mapreduce_critique", "iterative", "truncated",
        "mapreduce_hierarchical",
    ):
        st = get_strategy(name, fb, cfg)
        assert st.name == name
    with pytest.raises(ValueError):
        get_strategy("nope", fb, cfg)


def test_chunk_clamp_75_percent():
    fb = FakeBackend()
    st = HierarchicalStrategy(fb, chunk_size=999999, max_context=1000)
    assert st.chunk_size == 750


def test_llm_calls_are_true_per_document():
    """VERDICT r1 #8: llm_calls must be the document's own call count, not
    the batch total smeared onto every result."""
    small, big = make_doc(1, 5), make_doc(30, 30)
    fb = FakeBackend(summary_words=10)
    st = MapReduceStrategy(fb, word_splitter(), token_max=1000)
    r_small, r_big = st.summarize_batch([small, big])
    # small doc: 1 map + 1 final reduce; big doc: many maps + final
    assert r_small.llm_calls == r_small.num_chunks + 1
    assert r_big.llm_calls >= r_big.num_chunks + 1
    assert r_big.llm_calls > r_small.llm_calls
    # totals reconcile with the backend's actual call count
    assert r_small.llm_calls + r_big.llm_calls == len(fb.calls)


def test_mapreduce_finals_merge_into_collapse_rounds():
    """Tail packing (VERDICT r4 weak #3): a doc whose map summaries already
    fit token_max must submit its final reduce IN THE SAME backend call as
    the collapse round of docs still over budget — no trailing half-batch
    final round."""

    class RecordingBackend(FakeBackend):
        def __init__(self, **kw):
            super().__init__(**kw)
            self.call_batches: list[list[str]] = []

        def generate(self, prompts, **kw):
            self.call_batches.append(list(prompts))
            return super().generate(prompts, **kw)

    fb = RecordingBackend(summary_words=30)
    st = MapReduceStrategy(fb, word_splitter(chunk_size=40), token_max=60)
    # doc 0: many chunks -> over budget -> collapse rounds; doc 1: one chunk
    big, small = make_doc(40, 40), "một đoạn ngắn gọn duy nhất"
    results = st.summarize_batch([big, small])
    assert results[0].rounds >= 1 and results[1].rounds == 0
    assert results[0].summary and results[1].summary

    # the round after map must carry doc 1's final alongside doc 0's
    # collapse groups: batch with >1 prompt where one is a final-style
    # reduce over doc 1's single summary
    post_map = fb.call_batches[1]
    assert len(post_map) >= 2  # collapse groups + the merged final
    # and outputs must match the sequential formulation (single-doc runs)
    fb_a = FakeBackend(summary_words=30)
    alone_big = MapReduceStrategy(
        fb_a, word_splitter(chunk_size=40), token_max=60
    ).summarize(big)
    fb_b = FakeBackend(summary_words=30)
    alone_small = MapReduceStrategy(
        fb_b, word_splitter(chunk_size=40), token_max=60
    ).summarize(small)
    assert results[0].summary == alone_big.summary
    assert results[1].summary == alone_small.summary


def test_from_config_accepts_backend_without_batch_token_counting():
    """Duck-typed backends that only implement count_tokens must still
    construct (and split) via from_config — the splitter falls back to its
    scalar length path (ADVICE round 5)."""

    class ScalarOnlyBackend:
        name = "scalar-only"

        def __init__(self):
            self._fake = FakeBackend()

        def count_tokens(self, text):
            return whitespace_token_count(text)

        def generate(self, prompts, **kw):
            return self._fake.generate(prompts, **kw)

    cfg = PipelineConfig(chunk_size=60, chunk_overlap=0, token_max=120,
                         iterative_chunk_size=60, iterative_chunk_overlap=0)
    backend = ScalarOnlyBackend()
    for cls in (MapReduceStrategy, IterativeStrategy):
        strat = cls.from_config(backend, cfg)
        res = strat.summarize(make_doc(n_paras=6, words_per=30))
        assert res.summary
        assert res.num_chunks >= 2
