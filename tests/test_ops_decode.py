"""Pallas decode-attention kernel vs the dense cache attention (interpret
mode on CPU; the kernel's semantics must match _attention with the decode
mask pad_b <= j <= fill)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from vnsum_tpu.models.llama import _attention, decode_attention_mask
from vnsum_tpu.ops.decode_attention import flash_decode_attention, supports_decode


def make_case(L, B, KV, C, H, hd, seed=0):
    kq, kk, kv = jax.random.split(jax.random.key(seed), 3)
    q = jax.random.normal(kq, (B, 1, H, hd), jnp.float32)
    k_all = jax.random.normal(kk, (L, B, KV, C, hd), jnp.float32)
    v_all = jax.random.normal(kv, (L, B, KV, C, hd), jnp.float32)
    return q, {"k": k_all, "v": v_all}


@pytest.mark.parametrize("layer", [0, 2])
@pytest.mark.parametrize("fill,pads", [(37, [0, 5]), (63, [0, 0]), (8, [3, 8])])
def test_decode_kernel_matches_dense(layer, fill, pads):
    L, B, KV, C, H, hd = 3, 2, 2, 64, 4, 128
    q, cache = make_case(L, B, KV, C, H, hd, seed=layer)
    pad = jnp.asarray(pads, jnp.int32)

    mask = decode_attention_mask(pad, fill, C)
    dense = _attention(q, cache["k"][layer], cache["v"][layer], mask, H // KV)
    kernel = flash_decode_attention(
        q, cache, layer, pad, fill, H // KV, block_k=16, interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(dense), np.asarray(kernel), rtol=2e-5, atol=2e-5
    )


def test_decode_kernel_ignores_past_fill_garbage():
    """Slots past fill must not leak in even if they hold huge values."""
    L, B, KV, C, H, hd = 1, 1, 1, 32, 2, 128
    q, cache = make_case(L, B, KV, C, H, hd, seed=7)
    fill = 9
    poisoned = {
        "k": cache["k"].at[:, :, :, fill + 1 :, :].set(30.0),  # huge scores
        "v": cache["v"].at[:, :, :, fill + 1 :, :].set(1e9),
    }
    pad = jnp.zeros((B,), jnp.int32)
    clean = flash_decode_attention(
        q, cache, 0, pad, fill, H // KV, block_k=8, interpret=True
    )
    poisoned = flash_decode_attention(
        q, poisoned, 0, pad, fill, H // KV, block_k=8,
        interpret=True,
    )
    np.testing.assert_allclose(np.asarray(clean), np.asarray(poisoned))


@pytest.mark.parametrize("win,fill", [(1, 37), (8, 37), (16, 8), (64, 37)])
def test_decode_windowed_matches_dense(win, fill):
    """Sliding-window decode: kernel vs dense with the slot-space window
    (k_slot > fill - win), including win > fill (window not yet binding)."""
    L, B, KV, C, H, hd = 1, 2, 2, 64, 4, 128
    q, cache = make_case(L, B, KV, C, H, hd, seed=13)
    pad = jnp.asarray([0, 3], jnp.int32)
    mask = decode_attention_mask(pad, fill, C) & (
        jnp.arange(C)[None, None, :] > fill - win
    )
    dense = _attention(q, cache["k"][0], cache["v"][0], mask, H // KV)
    kernel = flash_decode_attention(
        q, cache, 0, pad, fill, H // KV, jnp.int32(win),
        block_k=16, interpret=True,
    )
    np.testing.assert_allclose(
        np.asarray(dense), np.asarray(kernel), rtol=2e-5, atol=2e-5
    )


def test_decode_windowed_ignores_below_window_garbage():
    """Below-window slots must not leak in even with huge values — they are
    DMA-clamped away, not just masked."""
    L, B, KV, C, H, hd = 1, 1, 1, 64, 2, 128
    q, cache = make_case(L, B, KV, C, H, hd, seed=7)
    fill, win = 40, 8
    poisoned = {
        "k": cache["k"].at[:, :, :, : fill - win + 1, :].set(30.0),
        "v": cache["v"].at[:, :, :, : fill - win + 1, :].set(1e9),
    }
    pad = jnp.zeros((B,), jnp.int32)
    clean = flash_decode_attention(
        q, cache, 0, pad, fill, H // KV, jnp.int32(win),
        block_k=8, interpret=True,
    )
    dirty = flash_decode_attention(
        q, poisoned, 0, pad, fill, H // KV, jnp.int32(win),
        block_k=8, interpret=True,
    )
    np.testing.assert_allclose(np.asarray(clean), np.asarray(dirty))


def quantize_case(cache):
    """Round-trip the float case through the int8 cache format."""
    from vnsum_tpu.models.llama import _quantize_kv

    k8, ks = jax.vmap(_quantize_kv)(cache["k"])  # vmap over L
    v8, vs = jax.vmap(_quantize_kv)(cache["v"])
    return {"k": k8, "v": v8, "ks": ks, "vs": vs}


@pytest.mark.parametrize("fill,pads", [(37, [0, 5]), (8, [3, 8])])
def test_decode_kernel_int8_cache_matches_dequantized_dense(fill, pads):
    """The in-kernel dequant (scores x ks, probs x vs) must equal dense
    attention over the explicitly dequantized cache."""
    from vnsum_tpu.models.llama import dequantize_cache_layer

    L, B, KV, C, H, hd = 2, 2, 2, 64, 4, 128
    q, cache = make_case(L, B, KV, C, H, hd, seed=11)
    qcache = quantize_case(cache)
    pad = jnp.asarray(pads, jnp.int32)

    kd, vd = dequantize_cache_layer(qcache, 1)
    mask = decode_attention_mask(pad, fill, C)
    dense = _attention(q, kd, vd, mask, H // KV)
    kernel = flash_decode_attention(
        q, qcache, 1, pad, fill, H // KV, block_k=16, interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(dense), np.asarray(kernel), rtol=2e-5, atol=2e-5
    )


def test_prefill_kernel_int8_cache_matches_dequantized_dense():
    from vnsum_tpu.models.llama import (
        dequantize_cache_layer,
        prefill_attention_mask,
    )
    from vnsum_tpu.ops.flash_attention import flash_prefill_attention

    L, B, S, C, KV, H, hd = 2, 2, 32, 48, 2, 4, 128
    kq = jax.random.key(21)
    q = jax.random.normal(kq, (B, S, H, hd), jnp.float32)
    _, cache = make_case(L, B, KV, C, H, hd, seed=21)
    qcache = quantize_case(cache)
    pad = jnp.asarray([0, 7], jnp.int32)

    kd, vd = dequantize_cache_layer(qcache, 0)
    mask = prefill_attention_mask(pad, S, C)
    dense = _attention(q, kd, vd, mask, H // KV)
    flash = flash_prefill_attention(
        q, qcache, 0, pad, H // KV, block_q=16, block_k=16, interpret=True
    )
    for b in range(B):
        np.testing.assert_allclose(
            np.asarray(dense)[b, int(pad[b]):],
            np.asarray(flash)[b, int(pad[b]):],
            rtol=2e-5, atol=2e-5,
        )


def test_int8_cache_quantization_roundtrip_accuracy():
    """Per-(token, head) scales keep relative error ~1/127."""
    from vnsum_tpu.models.llama import _quantize_kv

    x = jax.random.normal(jax.random.key(3), (2, 4, 16, 128), jnp.float32) * 5
    q8, s = _quantize_kv(x)
    deq = q8.astype(jnp.float32) * s[..., None]
    err = jnp.abs(deq - x).max() / jnp.abs(x).max()
    assert float(err) < 1.5 / 127


def test_supports_decode():
    assert supports_decode(1152, 128)
    assert not supports_decode(1152, 64)  # head_dim not a lane multiple
    assert supports_decode(1151, 128)     # any C via ceil-div grid


def test_engine_decode_kernel_path_matches_dense_cpu():
    """Engine with the decode kernel forced on (interpret path not available
    in-engine; emulate by comparing forward() with/without stacked fn)."""
    from vnsum_tpu.models import init_kv_cache, init_params, tiny_llama
    from vnsum_tpu.models.llama import forward, prefill_positions

    cfg = tiny_llama(max_seq_len=64)
    params = init_params(jax.random.key(0), cfg)
    B, S, C = 2, 8, 16
    tokens = jnp.ones((B, S), jnp.int32)
    pad = jnp.asarray([0, 2], jnp.int32)
    cache = init_kv_cache(cfg, B, C)
    from vnsum_tpu.models.llama import prefill_attention_mask

    logits, cache = forward(
        params, cfg, tokens, prefill_positions(pad, S), cache, 0,
        prefill_attention_mask(pad, S, C), last_only=True,
    )
    cur = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)

    t = 0
    mask_t = decode_attention_mask(pad, S + t, C)
    pos = (S - pad) + t

    def stacked(q, cache, layer_idx):
        return flash_decode_attention(
            q, cache, layer_idx, pad, S + t, cfg.q_per_kv,
            block_k=8, interpret=True,
        )

    ref, _ = forward(params, cfg, cur[:, None], pos[:, None], cache, S + t, mask_t)
    got, _ = forward(
        params, cfg, cur[:, None], pos[:, None], cache, S + t, mask_t,
        stacked_attention_fn=stacked,
    )
    np.testing.assert_allclose(np.asarray(ref), np.asarray(got), rtol=2e-4, atol=2e-4)


def test_decode_return_partials_normalize_to_direct():
    """return_partials exposes the unnormalized (o, m, l) state; o/l must
    equal the kernel's own normalized output (the long-context LSE merge
    depends on this contract)."""
    L, B, KV, C, H, hd = 2, 2, 2, 64, 4, 128
    q, cache = make_case(L, B, KV, C, H, hd, seed=17)
    pad = jnp.asarray([0, 5], jnp.int32)
    fill = 40
    direct = flash_decode_attention(
        q, cache, 1, pad, fill, H // KV, block_k=16, interpret=True
    )
    o, m, l = flash_decode_attention(
        q, cache, 1, pad, fill, H // KV, block_k=16, interpret=True,
        return_partials=True,
    )
    assert o.shape == (B, H, hd) and m.shape == l.shape == (B, H)
    normalized = o / np.maximum(np.asarray(l), 1e-30)[..., None]
    np.testing.assert_allclose(
        normalized, np.asarray(direct)[:, 0], rtol=2e-5, atol=2e-5
    )


def test_decode_partials_fully_masked_rows_are_inert():
    """A row whose pad covers the whole cache (an empty shard in the
    long-context merge) must come back with l=0 so the cross-shard merge
    ignores it."""
    L, B, KV, C, H, hd = 1, 2, 1, 32, 2, 128
    q, cache = make_case(L, B, KV, C, H, hd, seed=3)
    pad = jnp.asarray([0, 32], jnp.int32)  # row 1: everything padded out
    o, m, l = flash_decode_attention(
        q, cache, 0, pad, 31, H // KV, block_k=8, interpret=True,
        return_partials=True,
    )
    assert np.asarray(l)[1].max() == 0.0
    assert np.asarray(l)[0].min() > 0.0


def test_prefill_kernel_int8_cache_bf16_queries_close_to_f32():
    """The PRODUCTION prefill configuration — bf16 queries against the int8
    quantized cache — must track the f32-query/dequantized-dense oracle to
    bf16 rounding. Guards the quantized+bf16 interaction specifically: the
    in-kernel order is (scores x ks) and (p x vs) in f32 BEFORE p drops to
    bf16 for the PV dot; applying vs after the cast, or casting the f32
    scales themselves, would pass the f32-only parity tests but corrupt
    this path (code-review finding, round 5)."""
    from vnsum_tpu.models.llama import (
        dequantize_cache_layer,
        prefill_attention_mask,
    )
    from vnsum_tpu.ops.flash_attention import flash_prefill_attention

    L, B, S, C, KV, H, hd = 2, 2, 32, 48, 2, 4, 128
    q = jax.random.normal(jax.random.key(33), (B, S, H, hd), jnp.float32)
    _, cache = make_case(L, B, KV, C, H, hd, seed=33)
    qcache = quantize_case(cache)
    pad = jnp.asarray([0, 5], jnp.int32)

    kd, vd = dequantize_cache_layer(qcache, 1)
    mask = prefill_attention_mask(pad, S, C)
    oracle = _attention(q, kd, vd, mask, H // KV)
    flash_bf16 = flash_prefill_attention(
        q.astype(jnp.bfloat16), qcache, 1, pad, H // KV,
        block_q=16, block_k=16, interpret=True,
    )
    assert flash_bf16.dtype == jnp.bfloat16
    for b in range(B):
        np.testing.assert_allclose(
            np.asarray(oracle, np.float32)[b, int(pad[b]):],
            np.asarray(flash_bf16, np.float32)[b, int(pad[b]):],
            rtol=0.05, atol=0.05,
        )


# -- multi-position verify kernel (speculative decoding) ---------------------


def make_verify_case(L, B, KV, C, Sq, H, hd, seed=0):
    kq, kk, kv = jax.random.split(jax.random.key(seed), 3)
    q = jax.random.normal(kq, (B, Sq, H, hd), jnp.float32)
    k_all = jax.random.normal(kk, (L, B, KV, C, hd), jnp.float32)
    v_all = jax.random.normal(kv, (L, B, KV, C, hd), jnp.float32)
    return q, {"k": k_all, "v": v_all}


@pytest.mark.parametrize("layer", [0, 2])
@pytest.mark.parametrize(
    "fills,pads", [([10, 40], [0, 5]), ([58, 12], [3, 0]), ([7, 7], [2, 2])]
)
def test_verify_kernel_matches_dense(layer, fills, pads):
    """flash_spec_verify_attention vs _attention under the verify mask:
    per-row fills, multiple query positions per row."""
    from vnsum_tpu.models.llama import verify_attention_mask
    from vnsum_tpu.ops.decode_attention import flash_spec_verify_attention

    L, B, KV, C, Sq, H, hd = 3, 2, 2, 64, 5, 4, 128
    q, cache = make_verify_case(L, B, KV, C, Sq, H, hd, seed=layer)
    pad = jnp.asarray(pads, jnp.int32)
    fill = jnp.asarray(fills, jnp.int32)

    mask = verify_attention_mask(pad, fill, Sq, C)
    dense = _attention(q, cache["k"][layer], cache["v"][layer], mask, H // KV)
    kernel = flash_spec_verify_attention(
        q, cache, layer, pad, fill, H // KV, block_k=16, interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(dense), np.asarray(kernel), rtol=2e-5, atol=2e-5
    )


def test_verify_kernel_ignores_beyond_limit_garbage():
    """Slots past each row's per-query limit must not leak in — including
    slots BETWEEN two rows' differing fills (the rollback region)."""
    from vnsum_tpu.ops.decode_attention import flash_spec_verify_attention

    L, B, KV, C, Sq, H, hd = 1, 2, 1, 32, 3, 2, 128
    q, cache = make_verify_case(L, B, KV, C, Sq, H, hd, seed=9)
    fills = jnp.asarray([6, 20], jnp.int32)
    pad = jnp.zeros((B,), jnp.int32)
    # poison row 0 beyond ITS visibility (limit 6+3-1=8) but inside row 1's
    poisoned = {
        "k": cache["k"].at[:, 0, :, 9:, :].set(30.0),
        "v": cache["v"].at[:, 0, :, 9:, :].set(1e9),
    }
    clean = flash_spec_verify_attention(
        q, cache, 0, pad, fills, H // KV, block_k=8, interpret=True
    )
    dirty = flash_spec_verify_attention(
        q, poisoned, 0, pad, fills, H // KV, block_k=8, interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(clean)[0], np.asarray(dirty)[0]
    )


def test_verify_kernel_int8_cache_matches_dequantized_dense():
    from vnsum_tpu.models.llama import (
        _quantize_kv,
        dequantize_cache_layer,
        verify_attention_mask,
    )
    from vnsum_tpu.ops.decode_attention import flash_spec_verify_attention

    L, B, KV, C, Sq, H, hd = 2, 2, 2, 64, 4, 4, 128
    q, cache = make_verify_case(L, B, KV, C, Sq, H, hd, seed=3)
    k8, ks = jax.vmap(_quantize_kv)(cache["k"])
    v8, vs = jax.vmap(_quantize_kv)(cache["v"])
    qcache = {"k": k8, "v": v8, "ks": ks, "vs": vs}
    pad = jnp.asarray([0, 4], jnp.int32)
    fills = jnp.asarray([30, 55], jnp.int32)

    kd, vd = dequantize_cache_layer(qcache, 1)
    mask = verify_attention_mask(pad, fills, Sq, C)
    dense = _attention(q, kd.astype(q.dtype), vd.astype(q.dtype), mask, H // KV)
    kernel = flash_spec_verify_attention(
        q, qcache, 1, pad, fills, H // KV, block_k=16, interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(dense), np.asarray(kernel), rtol=2e-5, atol=2e-5
    )


@pytest.mark.parametrize("win", [4, 16])
def test_verify_kernel_windowed_matches_dense(win):
    """Sliding-window verify: per-query window floor (fill_b + s - win)."""
    from vnsum_tpu.models.llama import verify_attention_mask
    from vnsum_tpu.ops.decode_attention import flash_spec_verify_attention

    L, B, KV, C, Sq, H, hd = 1, 2, 2, 64, 3, 4, 128
    q, cache = make_verify_case(L, B, KV, C, Sq, H, hd, seed=5)
    pad = jnp.asarray([0, 2], jnp.int32)
    fills = jnp.asarray([20, 44], jnp.int32)

    limit = fills[:, None] + jnp.arange(Sq)[None, :]
    mask = verify_attention_mask(pad, fills, Sq, C) & (
        jnp.arange(C)[None, None, :] > (limit[:, :, None] - win)
    )
    dense = _attention(q, cache["k"][0], cache["v"][0], mask, H // KV)
    kernel = flash_spec_verify_attention(
        q, cache, 0, pad, fills, H // KV, window=jnp.int32(win),
        block_k=16, interpret=True,
    )
    np.testing.assert_allclose(
        np.asarray(dense), np.asarray(kernel), rtol=2e-5, atol=2e-5
    )
