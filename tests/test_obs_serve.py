"""Integration tests for end-to-end request tracing through the serving
path: span propagation across the scheduler thread under concurrency (ids
never cross-contaminate), TTFT/e2e histogram emission, request-id
consistency across response header / trace dump / ServeRequestRecord, the
/debug/trace endpoint, and the tracing-disabled overhead guard."""
from __future__ import annotations

import json
import threading
import urllib.error
import urllib.request

import pytest

from vnsum_tpu.backend.fake import FakeBackend
from vnsum_tpu.obs import ObsHub, RequestTrace
from vnsum_tpu.serve import MicroBatchScheduler
from vnsum_tpu.serve.server import ServeState, make_server

DOC = "\n\n".join(
    f"Đoạn văn {i}: " + "nội dung tiếng Việt có dấu thanh. " * 25
    for i in range(4)
)


# -- span propagation across scheduler threads -------------------------------


def test_concurrent_traces_never_cross_contaminate():
    """N requests submitted from N threads coalesce into shared engine
    batches; every request's spans must land on ITS OWN trace with its own
    id — the trace rides the ServeRequest across the queue handoff, so no
    thread-local confusion is possible."""
    hub = ObsHub(sample=1.0, ring=64)
    sched = MicroBatchScheduler(
        FakeBackend(), max_batch=8, max_wait_s=0.25, obs=hub
    )
    try:
        n = 6
        barrier = threading.Barrier(n)
        results = [None] * n

        def worker(i):
            barrier.wait()
            results[i] = sched.submit(
                f"tai lieu {i} " * 10, trace_id=f"client-{i}"
            ).result(timeout=30)

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        # all coalesced into one engine batch, yet ids stayed per-request
        assert all(r.record.batch_size == n for r in results)
        assert [r.record.trace_id for r in results] == [
            f"client-{i}" for i in range(n)
        ]
        reqs, batches = hub.snapshot()
        assert {t.trace_id for t in reqs} == {f"client-{i}" for i in range(n)}
        for tr in reqs:
            ids = {
                s.args["request_id"]
                for s in tr.spans
                if s.name == "queue_wait" and s.args
            }
            assert len(ids) == 1  # exactly one queue-level id per trace
            names = {s.name for s in tr.spans}
            assert {"queue_wait", "engine", "postprocess", "request"} <= names
        # the shared batch is one track with the fake's phase events on it
        assert len(batches) == 1 and batches[0].occupancy == n
        assert [e.name for e in batches[0].events] == ["prefill", "decode"]
    finally:
        sched.close()


def test_batch_prefill_anchors_ttft_between_queue_wait_and_total():
    backend = FakeBackend(batch_overhead_s=0.05, per_prompt_s=0.01)
    hub = ObsHub(sample=1.0)
    sched = MicroBatchScheduler(
        backend, max_batch=4, max_wait_s=0.0, obs=hub
    )
    try:
        rec = sched.submit("do ttft " * 5).result(timeout=30).record
        # prefill (50ms) ends before decode (10ms) does: TTFT must sit
        # strictly inside [queue_wait, total]
        assert rec.queue_wait_s <= rec.ttft_s <= rec.total_s
        assert rec.ttft_s < rec.queue_wait_s + rec.engine_s
    finally:
        sched.close()


def test_scheduler_owned_traces_finish_on_shed():
    import time

    hub = ObsHub(sample=1.0)
    sched = MicroBatchScheduler(
        FakeBackend(), max_batch=4, max_wait_s=0.0, obs=hub
    )
    try:
        from vnsum_tpu.serve import RequestShed

        with pytest.raises(RequestShed):
            sched.submit("het han ", deadline=time.monotonic() - 1.0)
        reqs, _ = hub.snapshot()
        assert len(reqs) == 1 and reqs[0].status == "shed:deadline"
    finally:
        sched.close()


def test_owned_sampling_decision_is_not_redrawn_per_prompt():
    """An entry point that sampled its request OUT (trace=None,
    trace_owned=True) must not have the scheduler re-draw per fanned-out
    prompt — that would fragment one request into single-prompt traces and
    inflate the configured sample rate."""
    hub = ObsHub(sample=1.0, ring=64)
    sched = MicroBatchScheduler(
        FakeBackend(), max_batch=8, max_wait_s=0.1, obs=hub
    )
    try:
        outs = sched.generate_sync(
            [f"phan manh {i} " * 5 for i in range(4)],
            trace=None, trace_owned=True,
        )
        assert len(outs) == 4
        reqs, _ = hub.snapshot()
        assert reqs == []  # no scheduler-owned traces were conjured
    finally:
        sched.close()


# -- overhead guard: tracing disabled = no per-request allocations -----------


def test_disabled_tracing_allocates_no_traces_and_emits_nothing():
    before = RequestTrace.allocations
    sched = MicroBatchScheduler(FakeBackend(), max_batch=4, max_wait_s=0.01,
                                obs=None)
    try:
        for i in range(8):
            c = sched.submit(f"khong theo doi {i} " * 6).result(timeout=30)
            assert c.record.status == "ok"
            assert c.record.trace_id  # correlation ids still flow
    finally:
        sched.close()
    # zero RequestTrace objects constructed anywhere in the process while
    # 8 requests (and their tokens) were served: the disabled path's cost
    # is `is None` checks, not per-token or per-request tracing state
    assert RequestTrace.allocations == before


# -- HTTP: ids, histograms, /debug/trace -------------------------------------


@pytest.fixture()
def serve_url():
    state = ServeState(
        FakeBackend(batch_overhead_s=0.005),
        max_batch=8, max_wait_s=0.005, trace_sample=1.0, trace_ring=64,
    )
    server = make_server(state, "127.0.0.1", 0)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    yield f"http://127.0.0.1:{server.server_address[1]}", state
    server.shutdown()
    server.server_close()
    state.close()


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as resp:
        return resp.status, dict(resp.headers), resp.read()


def _post(url, payload, headers=None):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=60) as resp:
        return resp.status, dict(resp.headers), json.loads(resp.read())


def test_request_id_consistent_across_header_body_record_and_trace(serve_url):
    base, state = serve_url
    status, headers, d = _post(
        base + "/v1/generate",
        {"prompt": "xin chào " * 8, "request_id": "my-req-42"},
    )
    assert status == 200
    assert headers["X-Request-Id"] == "my-req-42"
    assert d["request_id"] == "my-req-42"
    (c,) = d["completions"]
    assert c["record"]["trace_id"] == "my-req-42"
    assert c["record"]["ttft_s"] >= 0.0
    # the same id names the request's track in the trace dump
    _, _, body = _get(base + "/debug/trace")
    doc = json.loads(body)
    procs = {
        e["args"]["name"] for e in doc["traceEvents"]
        if e["ph"] == "M" and e["name"] == "process_name"
    }
    assert "request my-req-42" in procs


def test_request_id_from_header_and_generated_fallback(serve_url):
    base, _ = serve_url
    _, headers, d = _post(
        base + "/v1/generate", {"prompt": "một " * 6},
        headers={"X-Request-Id": "hdr-id-7"},
    )
    assert headers["X-Request-Id"] == "hdr-id-7" == d["request_id"]
    _, headers, d = _post(base + "/v1/generate", {"prompt": "hai " * 6})
    assert d["request_id"] and headers["X-Request-Id"] == d["request_id"]


def test_bad_request_id_is_400(serve_url):
    base, _ = serve_url
    for bad in (17, "", "x" * 200):
        with pytest.raises(urllib.error.HTTPError) as exc:
            _post(base + "/v1/generate", {"prompt": "x", "request_id": bad})
        assert exc.value.code == 400


def test_summarize_returns_request_id_and_one_trace_for_all_rounds(serve_url):
    base, state = serve_url
    status, headers, d = _post(
        base + "/v1/summarize",
        {"text": DOC, "approach": "mapreduce", "request_id": "sum-1"},
    )
    assert status == 200 and d["request_id"] == "sum-1"
    assert headers["X-Request-Id"] == "sum-1"
    reqs, _ = state.obs.snapshot()
    tr = next(t for t in reqs if t.trace_id == "sum-1")
    # every strategy-round prompt recorded onto this ONE trace, each on its
    # own sub-track
    engine_spans = [s for s in tr.spans if s.name == "engine"]
    assert len(engine_spans) == d["llm_calls"]
    assert len({s.track for s in engine_spans}) == len(engine_spans)


def test_metrics_histograms_have_nonempty_buckets(serve_url):
    base, _ = serve_url
    for i in range(3):
        _post(base + "/v1/generate", {"prompt": f"đo {i} " * 6})
    _, _, body = _get(base + "/metrics")
    text = body.decode()
    for name in ("vnsum_serve_queue_wait_seconds",
                 "vnsum_serve_ttft_seconds",
                 "vnsum_serve_e2e_seconds",
                 "vnsum_serve_batch_occupancy"):
        assert f'{name}_bucket{{le="+Inf"}} 3' in text, name
        assert f"{name}_count 3" in text
        assert f"{name}_sum" in text
    assert "vnsum_serve_spec_accepted_per_step_bucket" in text
    assert "vnsum_serve_spec_acceptance_rolling 0" in text
    assert "vnsum_serve_tokens_per_second_rolling" in text


def test_spec_histograms_flow_from_fake_spec_records():
    state = ServeState(
        FakeBackend(spec_k=4, spec_acceptance=0.5),
        max_batch=4, max_wait_s=0.005,
    )
    server = make_server(state, "127.0.0.1", 0)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{server.server_address[1]}"
    try:
        _post(base + "/v1/generate",
              {"prompt": "nguồn " * 10, "reference": "nguồn " * 10})
        _, _, body = _get(base + "/metrics")
        text = body.decode()
        assert "vnsum_serve_spec_accepted_per_step_count 1" in text
        # rolling acceptance reflects the fake's 0.5 rate
        assert "vnsum_serve_spec_acceptance_rolling 0.5" in text
    finally:
        server.shutdown()
        server.server_close()
        state.close()


def test_debug_trace_is_perfetto_loadable_with_batch_and_request_tracks(
    serve_url,
):
    base, _ = serve_url
    _post(base + "/v1/generate", {"prompt": "dấu vết " * 6})
    status, headers, body = _get(base + "/debug/trace")
    assert status == 200
    assert headers["Content-Type"] == "application/json"
    doc = json.loads(body)
    assert doc["displayTimeUnit"] == "ms"
    pids = set()
    for e in doc["traceEvents"]:
        assert e["ph"] in ("X", "M")
        assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
        if e["ph"] == "X":
            assert e["ts"] >= 0 and e["dur"] >= 0
        pids.add(e["pid"])
    assert 1 in pids          # engine process (batch tracks)
    assert any(p >= 100 for p in pids)  # at least one request process
    slice_names = {e["name"] for e in doc["traceEvents"] if e["ph"] == "X"}
    assert "queue_wait" in slice_names and "prefill" in slice_names


def test_debug_trace_404_when_tracing_disabled():
    state = ServeState(FakeBackend(), max_batch=2, max_wait_s=0.005,
                       trace_sample=0.0)
    server = make_server(state, "127.0.0.1", 0)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{server.server_address[1]}"
    try:
        assert state.obs is None
        with pytest.raises(urllib.error.HTTPError) as exc:
            _get(base + "/debug/trace")
        assert exc.value.code == 404
        # histograms stay on even with tracing off...
        _post(base + "/v1/generate", {"prompt": "vẫn đo " * 6})
        _, _, body = _get(base + "/metrics")
        text = body.decode()
        assert 'vnsum_serve_e2e_seconds_bucket{le="+Inf"} 1' in text
        # ...EXCEPT TTFT, which has no prefill anchor without a batch trace:
        # an unanchored fallback would just be e2e relabeled
        assert "vnsum_serve_ttft_seconds_count 0" in text
    finally:
        server.shutdown()
        server.server_close()
        state.close()
