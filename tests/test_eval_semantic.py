import numpy as np
import pytest

from vnsum_tpu.eval import EmbeddingModel, SemanticEvaluator, bert_scores
from vnsum_tpu.eval.geval import LLMJudge, _parse_score
from vnsum_tpu.models.encoder import tiny_encoder


@pytest.fixture(scope="module")
def embedder():
    return EmbeddingModel(config=tiny_encoder(), max_len=64, batch_size=4)


def test_identical_texts_similarity_one(embedder):
    embs = embedder.sentence_embeddings(["văn bản a", "văn bản a"])
    assert np.dot(embs[0], embs[1]) == pytest.approx(1.0, abs=1e-5)
    assert np.linalg.norm(embs[0]) == pytest.approx(1.0, abs=1e-5)


def test_bert_score_identical_is_one(embedder):
    scores = bert_scores(embedder, ["một hai ba"], ["một hai ba"])
    assert scores[0].f1 == pytest.approx(1.0, abs=1e-5)
    assert scores[0].precision == pytest.approx(scores[0].recall, abs=1e-5)


def test_bert_score_empty_text_is_finite(embedder):
    for cand, ref in [("some text", ""), ("", "ref text"), ("", "")]:
        s = bert_scores(embedder, [cand], [ref])[0]
        assert np.isfinite(s.precision) and np.isfinite(s.recall)
        assert np.isfinite(s.f1)


def test_bert_score_differs_for_different_texts(embedder):
    same = bert_scores(embedder, ["một hai ba"], ["một hai ba"])[0].f1
    diff = bert_scores(embedder, ["một hai ba"], ["bốn năm sáu bảy tám"])[0].f1
    assert diff < same


def test_evaluator_end_to_end(tmp_path, embedder):
    gen = tmp_path / "gen"
    ref = tmp_path / "ref"
    gen.mkdir()
    ref.mkdir()
    for i in range(3):
        (gen / f"d{i}.txt").write_text(f"tóm tắt văn bản số {i}", encoding="utf-8")
        (ref / f"d{i}.txt").write_text(f"văn bản tham chiếu số {i}", encoding="utf-8")
    (ref / "unpaired.txt").write_text("x", encoding="utf-8")

    ev = SemanticEvaluator(embedding_model=embedder)
    out = tmp_path / "results.json"
    results = ev.evaluate_folders(gen, ref, output=out)

    stats = results["summary_statistics"]
    assert set(stats) >= {"semantic_similarity", "rouge_scores", "bert_scores"}
    assert len(results["detailed_results"]) == 3
    assert all(0 <= d["rouge1_f"] <= 1 for d in results["detailed_results"])
    assert out.exists()


def test_evaluator_max_samples(tmp_path, embedder):
    gen = tmp_path / "g"
    ref = tmp_path / "r"
    gen.mkdir()
    ref.mkdir()
    for i in range(5):
        (gen / f"d{i}.txt").write_text("a b c", encoding="utf-8")
        (ref / f"d{i}.txt").write_text("a b d", encoding="utf-8")
    ev = SemanticEvaluator(embedding_model=embedder)
    results = ev.evaluate_pairs(
        {f"d{i}.txt": "a" for i in range(5)},
        {f"d{i}.txt": "a" for i in range(5)},
        max_samples=2,
    )
    assert len(results["detailed_results"]) == 2


def test_evaluator_no_overlap_raises(embedder):
    ev = SemanticEvaluator(embedding_model=embedder)
    with pytest.raises(ValueError):
        ev.evaluate_pairs({"a.txt": "x"}, {"b.txt": "y"})


def test_geval_score_parsing():
    assert _parse_score('{"score": 4, "reason": "ok"}') == pytest.approx(0.75)
    assert _parse_score("Score: 1") == pytest.approx(0.0)
    assert _parse_score("5") == pytest.approx(1.0)
    assert _parse_score("no score here 9000") is None


def test_geval_with_fake_backend():
    from vnsum_tpu.backend import FakeBackend

    fb = FakeBackend(responses=['{"score": 5}', '{"score": 3}'] * 2)
    judge = LLMJudge(backend=fb)
    stats = judge.evaluate(
        {"a.txt": "tóm tắt", "b.txt": "tóm tắt b"},
        {"a.txt": "tham chiếu", "b.txt": "tham chiếu b"},
    )
    assert stats["llm_successful_cases"] == 2
    assert stats["llm_failed_cases"] == 0
    assert stats["llm_correctness_mean"] == pytest.approx(1.0)
    assert stats["llm_coherence_mean"] == pytest.approx(0.5)


def test_geval_contains_failures():
    from vnsum_tpu.backend import FakeBackend

    fb = FakeBackend(responses=["garbage", "garbage", '{"score": 5}', '{"score": 5}'])
    judge = LLMJudge(backend=fb)
    stats = judge.evaluate(
        {"a.txt": "x", "b.txt": "y"}, {"a.txt": "x", "b.txt": "y"}
    )
    assert stats["llm_failed_cases"] == 1
    assert stats["llm_successful_cases"] == 1


def test_judge_requires_target():
    with pytest.raises(ValueError):
        LLMJudge()
