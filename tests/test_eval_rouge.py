import random

import pytest

from vnsum_tpu.eval.rouge import PorterStemmer, RougeScorer, tokenize

rouge_score = pytest.importorskip("rouge_score")
from rouge_score import rouge_scorer as rs  # noqa: E402

VIET_SAMPLES = [
    (
        "Quốc hội đã thông qua nghị quyết về phát triển kinh tế xã hội.",
        "Nghị quyết phát triển kinh tế xã hội được Quốc hội thông qua hôm nay.",
    ),
    (
        "Tài liệu nói về phương pháp học tập Feynman và cách áp dụng.",
        "Phương pháp Feynman giúp học tập hiệu quả hơn.",
    ),
    ("", "một văn bản"),
    ("một văn bản", ""),
    ("giống hệt nhau", "giống hệt nhau"),
]

ENG_SAMPLES = [
    (
        "the quick brown foxes were jumping over the lazy dogs repeatedly",
        "quick foxes jumped over lazy dogs",
    ),
    (
        "nationalization of the rational organization was controversial",
        "the organization was rationally nationalized",
    ),
]


@pytest.mark.parametrize("target,pred", VIET_SAMPLES + ENG_SAMPLES)
def test_matches_rouge_score_package(target, pred):
    ours = RougeScorer(["rouge1", "rouge2", "rougeL"], use_stemmer=True)
    theirs = rs.RougeScorer(["rouge1", "rouge2", "rougeL"], use_stemmer=True)
    a = ours.score(target, pred)
    b = theirs.score(target, pred)
    for key in ("rouge1", "rouge2", "rougeL"):
        assert a[key].precision == pytest.approx(b[key].precision, abs=1e-9)
        assert a[key].recall == pytest.approx(b[key].recall, abs=1e-9)
        assert a[key].fmeasure == pytest.approx(b[key].fmeasure, abs=1e-9)


def test_matches_rouge_score_random_word_soup():
    random.seed(42)
    vocab = [
        "tóm", "tắt", "kinh", "tế", "học", "summary", "nation", "running",
        "flies", "happiness", "điểm", "2024", "caresses", "ponies", "meeting",
    ]
    ours = RougeScorer(["rouge1", "rouge2", "rougeL"], use_stemmer=True)
    theirs = rs.RougeScorer(["rouge1", "rouge2", "rougeL"], use_stemmer=True)
    for _ in range(25):
        t = " ".join(random.choices(vocab, k=random.randint(3, 30)))
        p = " ".join(random.choices(vocab, k=random.randint(3, 30)))
        a, b = ours.score(t, p), theirs.score(t, p)
        for key in ("rouge1", "rouge2", "rougeL"):
            assert a[key].fmeasure == pytest.approx(b[key].fmeasure, abs=1e-9), (t, p)


def test_porter_stemmer_against_nltk():
    nltk = pytest.importorskip("nltk")
    from nltk.stem.porter import PorterStemmer as NltkPorter

    # rouge_score constructs PorterStemmer() -> default NLTK_EXTENSIONS mode
    theirs = NltkPorter()
    ours = PorterStemmer()
    words = [
        "caresses", "ponies", "ties", "caress", "cats", "feed", "agreed",
        "plastered", "bled", "motoring", "sing", "conflated", "troubled",
        "sized", "hopping", "tanned", "falling", "hissing", "fizzed",
        "failing", "filing", "happy", "sky", "relational", "conditional",
        "rational", "valenci", "hesitanci", "digitizer", "conformabli",
        "radicalli", "differentli", "vileli", "analogousli", "vietnamization",
        "predication", "operator", "feudalism", "decisiveness", "hopefulness",
        "callousness", "formaliti", "sensitiviti", "sensibiliti", "triplicate",
        "formative", "formalize", "electriciti", "electrical", "hopeful",
        "goodness", "revival", "allowance", "inference", "airliner",
        "gyroscopic", "adjustable", "defensible", "irritant", "replacement",
        "adjustment", "dependent", "adoption", "homologou", "communism",
        "activate", "angulariti", "homologous", "effective", "bowdlerize",
        "probate", "rate", "cease", "controll", "roll", "summarization",
        "ties", "dies", "flies", "spied", "died", "enjoy", "happy", "skies",
        "dying", "lying", "tying", "news", "innings", "sky", "crying",
        "possibli", "analogi", "geologi", "beautifulli", "controlling",
    ]
    for w in words:
        if len(w) <= 3:
            continue
        assert ours.stem(w) == theirs.stem(w), w


def test_porter_stemmer_fuzz_against_nltk():
    import itertools
    import random as rnd

    pytest.importorskip("nltk")
    from nltk.stem.porter import PorterStemmer as NltkPorter

    theirs = NltkPorter()
    ours = PorterStemmer()
    rnd.seed(7)
    suffixes = [
        "s", "es", "ies", "ied", "ed", "ing", "eed", "y", "alli", "bli",
        "logi", "fulli", "ational", "ization", "ness", "ful", "icate",
        "ative", "ion", "ment", "ous", "ive", "ize", "iti", "e", "ll", "",
    ]
    stems = [
        "caress", "poni", "t", "tr", "cri", "controll", "happ", "enjo",
        "nation", "rat", "hopp", "fail", "feud", "sens", "analog", "geo",
        "f", "xyz", "aa", "oate", "vi",
    ]
    words = ["".join(p) for p in itertools.product(stems, suffixes)]
    words += [
        "".join(rnd.choices("abcdefgilmnoprstuyz", k=rnd.randint(4, 12)))
        for _ in range(3000)
    ]
    mismatches = [w for w in words if ours.stem(w) != theirs.stem(w)]
    assert not mismatches, mismatches[:20]


def test_tokenize_ascii_stripping_matches_reference_behavior():
    # Vietnamese diacritics are stripped by the rouge_score tokenizer — the
    # reference's committed numbers were produced this way
    assert tokenize("Tóm tắt 2024!", use_stemmer=False) == ["t", "m", "t", "t", "2024"]
