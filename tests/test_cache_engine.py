"""Engine resume-prefill + serving integration for the prefix KV cache.

The acceptance bar (ISSUE 6): greedy outputs must be byte-identical to the
uncached path in cached, uncached, and post-eviction arms; eviction under a
tight block budget must never corrupt live rows, including under concurrent
scheduler traffic; hit accounting must reach ServeRequestRecord and
/metrics.
"""
import threading

import numpy as np
import pytest

pytest.importorskip("jax")

from vnsum_tpu.backend.engine import TpuBackend
from vnsum_tpu.backend.fake import FakeBackend
from vnsum_tpu.models import jitted_init
from vnsum_tpu.models.llama import init_params, tiny_llama

HEADER = (
    "Ban la mot chuyen gia tom tat noi dung. "
    "Vui long viet mot ban tom tat chi tiet cho van ban sau day. " * 2
)
PROMPTS = [HEADER + f"Noi dung rieng biet so {i}: cau chuyen lang que {i}." for i in range(4)]


@pytest.fixture(scope="module")
def cfg():
    return tiny_llama(max_seq_len=512)


@pytest.fixture(scope="module")
def params(cfg):
    return jitted_init(init_params, cfg, 0)


@pytest.fixture(scope="module")
def reference_outputs(cfg, params):
    base = TpuBackend(
        model_config=cfg, params=params, batch_size=4, max_new_tokens=16
    )
    return base.generate(PROMPTS)


def make_backend(cfg, params, **kw):
    kw.setdefault("cache_blocks", 32)
    kw.setdefault("cache_block_tokens", 64)
    return TpuBackend(
        model_config=cfg, params=params, batch_size=4, max_new_tokens=16, **kw
    )


def test_resume_outputs_byte_identical(cfg, params, reference_outputs):
    b = make_backend(cfg, params)
    cold = b.generate(PROMPTS)
    assert cold == reference_outputs          # miss path: plain prefill
    assert b.take_cache_report() == [0] * 4   # nothing cached yet
    warm = b.generate(PROMPTS)
    assert warm == reference_outputs          # hit path: resume prefill
    report = b.take_cache_report()
    assert all(r > 0 for r in report)
    assert b.stats.cache_hit_tokens == sum(report)
    st = b.prefix_cache_stats()
    assert st["blocks_used"] > 0
    # the skip is bounded by the true prefix length
    for r, p in zip(report, PROMPTS):
        assert r <= len(p.encode()) + 1


def test_resume_identical_in_continuous_mode(cfg, params, reference_outputs):
    b = make_backend(cfg, params, continuous=True, segment_tokens=8)
    assert b.generate(PROMPTS) == reference_outputs
    assert b.generate(PROMPTS) == reference_outputs
    assert b.stats.cache_hit_tokens > 0


def test_post_eviction_outputs_byte_identical(cfg, params, reference_outputs):
    # 3 blocks of 64 tokens cannot hold even one full header: constant
    # allocation/eviction churn, outputs must never move
    b = make_backend(cfg, params, cache_blocks=3)
    other = ["Van ban hoan toan khac biet " * 12 + f"so {i}" for i in range(4)]
    assert b.generate(PROMPTS) == reference_outputs
    b.generate(other)                      # churn the pool
    assert b.generate(PROMPTS) == reference_outputs
    assert b.prefix_cache_stats()["evictions"] > 0
    assert b.prefix_cache_stats()["blocks_used"] <= 3


def test_cache_hint_bounds_insertion(cfg, params):
    b = make_backend(cfg, params, cache_blocks=32, cache_block_tokens=32)
    hint = HEADER
    b.generate(PROMPTS, cache_hints=[hint] * len(PROMPTS))
    hint_tokens = len(hint.encode()) + 1  # + BOS
    # only hint-covered blocks entered the pool, not the unique tails
    assert b.prefix_cache_stats()["blocks_used"] <= hint_tokens // 32
    # and hits still land (prompts share exactly the hinted header)
    b.generate(PROMPTS, cache_hints=[hint] * len(PROMPTS))
    assert b.stats.cache_hit_tokens > 0


def test_mixed_lengths_group_by_suffix(cfg, params):
    """Short cold prompts and long warm prompts coexist: ordering by
    uncovered suffix keeps outputs correct (identical to an uncached run of
    the same mixed workload)."""
    mixed = PROMPTS + ["Cau hoi ngan."] * 2
    base = TpuBackend(
        model_config=cfg, params=params, batch_size=4, max_new_tokens=16
    )
    want = base.generate(mixed)
    b = make_backend(cfg, params)
    assert b.generate(mixed) == want
    assert b.generate(mixed) == want


def test_cache_pool_requires_tp_divisible_kv_heads(cfg, params):
    """The block pool shards KV heads over `model`; an indivisible config
    must fail loudly at construction (mirroring shard_params' check), not
    as a raw XLA error on the first gather."""
    from vnsum_tpu.parallel import make_mesh

    mesh = make_mesh({"data": 1, "model": 3, "seq": 1}, platform="cpu")
    with pytest.raises(ValueError, match="n_kv_heads"):
        TpuBackend(
            model_config=cfg, params=params, mesh=mesh,
            max_new_tokens=16, cache_blocks=8,
        )


def test_spec_call_bypasses_cache(cfg, params):
    from vnsum_tpu.core.config import GenerationConfig

    b = make_backend(cfg, params)
    b.generate(PROMPTS)
    outs = b.generate(
        PROMPTS, config=GenerationConfig(spec_k=4), references=PROMPTS
    )
    assert b.take_cache_report() == []  # spec path: no cache attribution
    assert len(outs) == len(PROMPTS)


# -- FakeBackend mirror ------------------------------------------------------


def test_fake_backend_cache_contract():
    fb = FakeBackend(prefix_cache_blocks=16, cache_block_tokens=4)
    prompts = ["chung toi cung mot tieu de dai " * 3 + f"duy nhat {i}" for i in range(3)]
    fb.generate(prompts)
    assert fb.take_cache_report() == [0, 0, 0]  # first pass: all misses...
    # ...except identical re-submissions, which now hit
    fb.generate(prompts)
    report = fb.take_cache_report()
    assert all(r > 0 for r in report)
    assert fb.cached_prefix_tokens(prompts[0]) > 0
    st = fb.prefix_cache_stats()
    assert st["blocks_used"] > 0 and st["blocks_total"] == 16


def test_fake_backend_honors_cache_hint():
    fb = FakeBackend(prefix_cache_blocks=64, cache_block_tokens=2)
    hint = "mot hai ba bon"  # 4 words -> 2 blocks
    prompts = [hint + f" phan duoi khac nhau hoan toan so {i} a b c d" for i in range(2)]
    fb.generate(prompts, cache_hints=[hint, hint])
    assert fb.cache_hints_seen == [hint, hint]
    assert fb.prefix_cache_stats()["blocks_used"] == 2  # hint-bounded
    fb.generate(prompts, cache_hints=[hint, hint])
    assert fb.take_cache_report() == [4, 4]


def test_fake_backend_cache_off_by_default():
    fb = FakeBackend()
    fb.generate(["xin chao"])
    assert fb.take_cache_report() == []
    assert fb.prefix_cache_stats() is None
    assert fb.cached_prefix_tokens("xin chao") == 0


# -- serving integration -----------------------------------------------------


def test_queue_bills_only_uncached_tokens():
    from vnsum_tpu.serve.queue import RequestQueue, RequestShed, ServeRequest

    q = RequestQueue(max_depth=8, max_queued_tokens=10)
    q.submit(ServeRequest(prompt="a", est_tokens=6))
    # 9 estimated tokens but 5 cached: 4 billable -> fits the budget
    q.submit(ServeRequest(prompt="b", est_tokens=9, cached_tokens=5))
    assert q.queued_tokens == 10
    # an uncached twin of the same size sheds
    with pytest.raises(RequestShed):
        q.submit(ServeRequest(prompt="c", est_tokens=9))


def test_scheduler_attributes_cache_hits_to_records_and_metrics():
    from vnsum_tpu.serve.scheduler import MicroBatchScheduler

    fb = FakeBackend(prefix_cache_blocks=64, cache_block_tokens=2)
    sched = MicroBatchScheduler(
        fb, max_batch=4, max_wait_s=0.005, max_queued_tokens=10_000
    )
    try:
        prompt = "tieu de chung cua tat ca cac yeu cau " * 3 + "duoi khac"
        c1 = sched.submit(prompt).result(timeout=5)
        assert c1.record.cached_prompt_tokens == 0
        # warm: the same prompt now hits; the submit-time probe discounts it
        c2 = sched.submit(prompt).result(timeout=5)
        assert c2.record.cached_prompt_tokens > 0
        assert 0 < c2.record.cache_hit_rate <= 1.0
        snap = sched.metrics.snapshot()
        assert snap.cache_hit_tokens == c2.record.cached_prompt_tokens
        text = sched.metrics.render_prometheus(
            cache_stats=fb.prefix_cache_stats()
        )
        assert "vnsum_serve_cache_hit_tokens_total" in text
        assert "vnsum_serve_cache_blocks_used" in text
        assert "vnsum_serve_cache_evictions_total" in text
    finally:
        sched.close()


def test_eviction_never_corrupts_under_concurrent_traffic():
    """Acceptance: a 6-block pool under 4 threads x 3 distinct shared-prefix
    workloads churns eviction constantly; every completion must still equal
    the deterministic FakeBackend output for its prompt."""
    from vnsum_tpu.serve.scheduler import MicroBatchScheduler

    fb = FakeBackend(prefix_cache_blocks=6, cache_block_tokens=2)
    oracle = FakeBackend()  # no cache: the ground-truth transformer
    sched = MicroBatchScheduler(fb, max_batch=4, max_wait_s=0.002)
    headers = [f"tieu de so {h} lap lai nhieu lan cho nhom nay " for h in range(3)]
    errors = []

    def client(tid):
        try:
            for i in range(12):
                h = headers[(tid + i) % len(headers)]
                prompt = h * 2 + f"phan than bai rieng {tid} {i} con lai"
                got = sched.submit(prompt, cache_hint=h * 2).result(timeout=10)
                want = oracle.generate([prompt])[0]
                if got.text != want:
                    errors.append((prompt, got.text, want))
        except Exception as e:  # pragma: no cover - the assertion target
            errors.append(e)

    threads = [threading.Thread(target=client, args=(t,)) for t in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # deterministic churn for the eviction assert: the concurrent phase
    # CAN legally evict nothing if every dispatch lands as a full batch
    # (all three headers' chains matched and pinned at insert time, so
    # insertion skips rather than evicts). With the pool full of resident
    # headers and nothing pinned anymore, a fresh prefix MUST evict.
    fresh = "tieu de moi hoan toan khac biet chua tung thay " * 2
    prompt = fresh + "phan duoi cung rieng biet"
    got = sched.submit(prompt, cache_hint=fresh).result(timeout=10)
    assert got.text == oracle.generate([prompt])[0]
    sched.close()
    assert not errors
    st = fb.prefix_cache_stats()
    assert st["evictions"] > 0          # the budget really was tight
    assert st["blocks_used"] <= 6


def test_http_cache_hint_and_metrics_end_to_end():
    """POST /v1/generate with a cache_hint; the second identical request's
    record reports cached tokens and /metrics carries the cache series."""
    import json
    import urllib.request

    from vnsum_tpu.serve.server import ServeState, make_server

    state = ServeState(
        FakeBackend(prefix_cache_blocks=64, cache_block_tokens=2),
        max_batch=4, max_wait_s=0.005,
    )
    server = make_server(state, "127.0.0.1", 0)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    base = f"http://127.0.0.1:{server.server_address[1]}"
    try:
        hint = "tieu de dung chung giua cac yeu cau"
        body = json.dumps({
            "prompt": hint + " phan noi dung rieng cua yeu cau nay",
            "cache_hint": hint,
        }).encode()

        def post():
            req = urllib.request.Request(
                base + "/v1/generate", data=body,
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=10) as r:
                return json.loads(r.read())

        first = post()["completions"][0]["record"]
        assert first["cached_prompt_tokens"] == 0
        second = post()["completions"][0]["record"]
        assert second["cached_prompt_tokens"] > 0
        assert second["cache_hit_rate"] > 0
        with urllib.request.urlopen(base + "/metrics", timeout=10) as r:
            metrics = r.read().decode()
        assert "vnsum_serve_cache_hit_tokens_total" in metrics
        assert "vnsum_serve_cache_blocks_total 64" in metrics
    finally:
        server.shutdown()
        server.server_close()
        state.close()


def test_take_batch_clusters_by_cache_hint():
    from vnsum_tpu.serve.queue import RequestQueue, ServeRequest

    q = RequestQueue(max_depth=16)
    for hint in ("A", "B", "A", "B", "A"):
        q.submit(ServeRequest(prompt=f"p{hint}", cache_hint=hint))
    batch = q.take_batch(max_batch=3, max_wait_s=0.0)
    assert [r.cache_hint for r in batch] == ["A", "A", "A"]
    batch2 = q.take_batch(max_batch=3, max_wait_s=0.0)
    assert [r.cache_hint for r in batch2] == ["B", "B"]
