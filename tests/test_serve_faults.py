"""Fault-tolerant serving acceptance: seeded fault injection driving the
supervisor's recovery paths — retry with backoff, batch bisection /
poison quarantine, the degradation ladder with recovery probes, radix-pin
and slot hygiene across crashes, typed drain-overrun sheds, and the
brownout 503 contract over the live HTTP front-end. Everything hermetic
(FakeBackend + vnsum_tpu.testing.faults); the cardinal assertion repeated
throughout: EVERY future resolves — success, typed failure, or typed shed —
no hangs."""
from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from vnsum_tpu.backend.fake import FakeBackend
from vnsum_tpu.serve import (
    EngineSupervisor,
    FailureClass,
    InflightScheduler,
    MicroBatchScheduler,
    RequestFailed,
    RequestShed,
    RetryPolicy,
    Rung,
    ShedReason,
)
from vnsum_tpu.serve.supervisor import FatalEngineError, classify_failure
from vnsum_tpu.testing.faults import (
    FaultPlan,
    FaultSpec,
    InjectedResourceExhausted,
    injected,
    parse_plan,
    plan_from_env,
)

FAST = RetryPolicy(max_attempts=3, backoff_base_s=0.005, backoff_max_s=0.05,
                   jitter=0.0)


def _supervised(backend=None, *, policy=FAST, max_batch=8, max_wait_s=0.2,
                cls=MicroBatchScheduler, **sup_kw):
    backend = backend or FakeBackend()
    sup = EngineSupervisor(policy, **sup_kw)
    sched = cls(backend, max_batch=max_batch, max_wait_s=max_wait_s,
                supervisor=sup)
    return backend, sup, sched


def _collect(futs, timeout=30):
    """Resolve every future: (value-or-exception per future). Raises on a
    HANG — the one outcome nothing in this suite may produce."""
    out = []
    for f in futs:
        try:
            out.append(f.result(timeout=timeout))
        except Exception as e:  # noqa: BLE001 - recorded for assertions
            out.append(e)
    return out


# -- fault plan mechanics ----------------------------------------------------


def test_fault_plan_is_deterministic_per_seed():
    def schedule(seed):
        plan = FaultPlan(
            [FaultSpec(site="s", kind="raise", probability=0.5)], seed=seed
        )
        hits = []
        for i in range(50):
            try:
                plan.fire("s")
                hits.append(False)
            except RuntimeError:
                hits.append(True)
        return hits

    a, b, c = schedule(7), schedule(7), schedule(8)
    assert a == b  # same seed -> identical firing schedule
    assert a != c  # different seed -> different schedule
    assert any(a) and not all(a)


def test_fault_plan_env_format_and_times_cap(monkeypatch):
    monkeypatch.setenv(
        "VNSUM_FAULTS",
        "seed=7;fake.dispatch:resource@every_n=2,times=1;"
        "fake.prefill:poison@match=DOC-13",
    )
    plan = plan_from_env()
    assert plan is not None and plan.seed == 7
    with pytest.raises(InjectedResourceExhausted):
        plan.fire("fake.dispatch")
        plan.fire("fake.dispatch")
    # times=1: the every_n rule is spent
    plan.fire("fake.dispatch")
    plan.fire("fake.dispatch")
    # poison needs its match present in the dispatch
    plan.fire("fake.prefill", prompts=["van ban lanh"])
    with pytest.raises(RuntimeError, match="poison"):
        plan.fire("fake.prefill", prompts=["tieu de DOC-13 xau"])
    assert [k for _s, k, _n in plan.fired] == ["resource", "poison"]


def test_parse_plan_rejects_malformed():
    with pytest.raises(ValueError):
        parse_plan("not-a-spec")
    with pytest.raises(ValueError):
        parse_plan("site:poison")  # poison without match
    with pytest.raises(ValueError):
        parse_plan("site:raise@bogus=1")
    # a selector-less non-poison spec would never fire — the plan must
    # refuse to arm vacuously instead of letting CI pass green untested
    with pytest.raises(ValueError, match="on_call"):
        parse_plan("site:raise")


def test_classifier():
    assert classify_failure(RuntimeError("boom")) is FailureClass.TRANSIENT
    assert classify_failure(MemoryError()) is FailureClass.RESOURCE
    assert (classify_failure(RuntimeError("RESOURCE_EXHAUSTED: oom"))
            is FailureClass.RESOURCE)
    assert classify_failure(ValueError("bad")) is FailureClass.POISON
    assert classify_failure(FatalEngineError("gone")) is FailureClass.FATAL
    e = RuntimeError("x")
    e.fatal = True
    assert classify_failure(e) is FailureClass.FATAL


# -- one-shot path: retry / bisect / quarantine ------------------------------


def test_transient_crash_retries_and_every_future_resolves():
    backend, sup, sched = _supervised()
    plan = FaultPlan([FaultSpec(site="fake.dispatch", kind="raise", on_call=1)])
    try:
        with injected(plan):
            futs = [sched.submit(f"tai lieu {i} " * 10) for i in range(5)]
            outs = _collect(futs)
        assert all(c.record.status == "ok" for c in outs)
        # outputs identical to an unfaulted backend — the retry re-ran the
        # same prompts, it didn't corrupt them
        fresh = FakeBackend()
        for i, c in enumerate(outs):
            assert c.text == fresh.generate([f"tai lieu {i} " * 10])[0]
        s = sched.metrics.snapshot()
        assert s.failures.get("transient") == 1
        assert s.retries == 5 and s.completed == 5 and s.errors == 0
        assert s.backoff_seconds > 0
    finally:
        sched.close()


def test_poison_request_is_bisected_out_and_only_it_fails():
    backend, sup, sched = _supervised(
        policy=RetryPolicy(max_attempts=2, backoff_base_s=0.005, jitter=0.0)
    )
    plan = FaultPlan(
        [FaultSpec(site="fake.dispatch", kind="poison", match="DOC-POISON")]
    )
    try:
        prompts = [f"van ban sach {i} " * 8 for i in range(6)]
        prompts[3] = "van ban DOC-POISON doc hai " * 8
        with injected(plan):
            futs = [sched.submit(p) for p in prompts]
            res = _collect(futs)
        # ONLY the poison request failed, typed, with the POISON class
        for i, r in enumerate(res):
            if i == 3:
                assert isinstance(r, RequestFailed)
                assert r.failure_class is FailureClass.POISON
            else:
                assert r.record.status == "ok"
        s = sched.metrics.snapshot()
        assert s.bisects >= 1 and s.quarantined == 1
        assert s.completed == 5 and s.errors == 1
    finally:
        sched.close()


def test_immediate_poison_class_skips_retries():
    """A PERMANENT_ERRORS-class failure (ValueError) bisects straight away:
    no retry budget is burned re-running a deterministic input error."""
    class Picky(FakeBackend):
        def generate(self, prompts, **kw):
            if any("hong" in p for p in prompts):
                raise ValueError("malformed input row")
            return super().generate(prompts, **kw)

    backend, sup, sched = _supervised(Picky())
    try:
        prompts = ["lanh a " * 6, "bi hong " * 6, "lanh b " * 6]
        futs = [sched.submit(p) for p in prompts]
        res = _collect(futs)
        assert isinstance(res[1], RequestFailed)
        assert res[1].failure_class is FailureClass.POISON
        assert res[0].record.status == "ok" and res[2].record.status == "ok"
        s = sched.metrics.snapshot()
        assert s.retries == 0  # bisection only — no backoff retries
        assert s.failures.get("poison", 0) >= 1
    finally:
        sched.close()


def test_fatal_failure_fails_whole_group_without_retry():
    backend, sup, sched = _supervised()
    plan = FaultPlan([FaultSpec(site="fake.dispatch", kind="fatal",
                                on_call=1)])
    try:
        with injected(plan):
            futs = [sched.submit(f"chet {i} " * 5) for i in range(3)]
            res = _collect(futs)
        assert all(isinstance(r, RequestFailed) for r in res)
        assert all(r.failure_class is FailureClass.FATAL for r in res)
        assert sched.metrics.snapshot().retries == 0
        # the scheduler thread survived: next submit still served
        ok = sched.submit("van song " * 5).result(timeout=30)
        assert ok.record.status == "ok"
    finally:
        sched.close()


def test_unsupervised_scheduler_keeps_raw_error_contract():
    """supervisor=None is the pre-supervision contract: the raw error on
    every rider, no retries — what the direct-API tests pin."""
    sched = MicroBatchScheduler(FakeBackend(), max_batch=4, max_wait_s=0.1)
    plan = FaultPlan([FaultSpec(site="fake.dispatch", kind="raise",
                                every_n=1)])
    try:
        with injected(plan):
            futs = [sched.submit(f"tho {i} " * 5) for i in range(2)]
            res = _collect(futs)
        assert all(type(r).__name__ == "InjectedFault" for r in res)
    finally:
        sched.close()


def test_expired_deadline_during_backoff_is_shed_not_redispatched():
    backend, sup, sched = _supervised(
        policy=RetryPolicy(max_attempts=5, backoff_base_s=0.2,
                           backoff_max_s=0.2, jitter=0.0)
    )
    plan = FaultPlan([FaultSpec(site="fake.dispatch", kind="raise",
                                every_n=1, times=2)])
    try:
        with injected(plan):
            f = sched.submit("gap rut " * 5,
                             deadline=time.monotonic() + 0.1)
            with pytest.raises(RequestShed) as exc:
                f.result(timeout=30)
        assert exc.value.reason is ShedReason.DEADLINE
    finally:
        sched.close()


# -- degradation ladder ------------------------------------------------------


def test_resource_burst_steps_ladder_down_and_probe_recovers():
    backend, sup, sched = _supervised(
        policy=RetryPolicy(max_attempts=6, backoff_base_s=0.005, jitter=0.0),
        resource_strikes_per_step=2, probe_interval_s=0.15,
    )
    plan = FaultPlan([
        FaultSpec(site="fake.dispatch", kind="resource", on_call=1),
        FaultSpec(site="fake.dispatch", kind="resource", on_call=2),
    ])
    try:
        with injected(plan):
            futs = [sched.submit(f"qua tai {i} " * 6) for i in range(6)]
            outs = _collect(futs)
        assert all(c.record.status == "ok" for c in outs)
        assert sup.rung == Rung.REDUCED_BATCH
        # REDUCED_BATCH halves dispatch width: post-step-down batches are
        # no wider than max_batch // 2
        step_down_sizes = backend.batch_sizes[1:]
        assert step_down_sizes and max(step_down_sizes) <= 4
        s = sched.metrics.snapshot()
        assert s.degraded_steps == 1
        assert s.failures.get("resource_exhausted") == 2
        time.sleep(0.2)
        ok = sched.submit("hoi phuc " * 5).result(timeout=30)
        assert ok.record.status == "ok"
        assert sup.rung == Rung.HEALTHY
        assert sched.metrics.snapshot().degraded_recoveries == 1
    finally:
        sched.close()


def test_no_spec_rung_drops_references_no_cache_rung_stops_inserts():
    backend = FakeBackend(prefix_cache_blocks=64, cache_block_tokens=4,
                          spec_k=4)
    _, sup, sched = _supervised(backend)
    try:
        # healthy: references ride, inserts happen
        sched.submit("mot tieu de chung rat dai " * 4 + "duoi mot",
                     reference="mot tieu de chung").result(timeout=30)
        assert backend.references_seen[-1] == "mot tieu de chung"
        used0 = backend.prefix_index.stats_dict()["blocks_used"]
        assert used0 > 0
        # force NO_CACHE_INSERT (implies NO_SPEC)
        for _ in range(6):
            sup.note_failure(FailureClass.RESOURCE)
        assert sup.rung >= Rung.NO_CACHE_INSERT
        sched.submit("mot tieu de chung rat dai " * 4 + "duoi hai la khac",
                     reference="mot tieu de chung").result(timeout=30)
        # spec reference dropped by the dispatch gate
        assert backend.references_seen[-1] is None
        # no new blocks inserted, but the cached prefix still served
        d = backend.prefix_index.stats_dict()
        assert d["blocks_used"] == used0
        rec = sched.metrics.snapshot()
        assert rec.cache_hit_tokens > 0
    finally:
        sched.close()


def test_brownout_sheds_typed_with_retry_after_and_heals():
    backend, sup, sched = _supervised(
        resource_strikes_per_step=1, probe_interval_s=0.1,
        brownout_retry_after_s=2.5,
    )
    try:
        for _ in range(4):
            sup.note_failure(FailureClass.RESOURCE)
        assert sup.rung == Rung.BROWNOUT
        with pytest.raises(RequestShed) as exc:
            sched.submit("bi chan " * 4)
        assert exc.value.reason is ShedReason.BROWNOUT
        assert exc.value.retry_after_s == 2.5
        # internal fan-out of already-admitted work still runs
        c = sched.submit("noi bo " * 4, internal=True).result(timeout=30)
        assert c.record.status == "ok"
        # the admission knock itself probes recovery after the interval
        time.sleep(0.12)
        ok = sched.submit("mo lai " * 4).result(timeout=30)
        assert ok.record.status == "ok"
        assert sup.rung < Rung.BROWNOUT
    finally:
        sched.close()


# -- in-flight path ----------------------------------------------------------


def test_inflight_segment_crash_retries_all_resolve():
    backend = FakeBackend(segment_words=4)
    _, sup, sched = _supervised(backend, cls=InflightScheduler)
    plan = FaultPlan([FaultSpec(site="fake.slot_step", kind="raise",
                                on_call=2)])
    try:
        with injected(plan):
            futs = [sched.submit(f"tai lieu {i} van ban dai " * 6)
                    for i in range(4)]
            outs = _collect(futs)
        assert all(c.record.status == "ok" for c in outs)
        fresh = FakeBackend(segment_words=4)
        for i, c in enumerate(outs):
            assert c.text == fresh.generate(
                [f"tai lieu {i} van ban dai " * 6]
            )[0]
        s = sched.metrics.snapshot()
        assert s.retries >= 1 and s.failures.get("transient") == 1
        # slots freed: the crashed loop was dropped, nothing resident
        total, busy = sched.slot_state()
        assert busy == 0
    finally:
        sched.close()


def test_inflight_poison_resident_quarantined_others_survive():
    backend = FakeBackend(segment_words=4)
    _, sup, sched = _supervised(
        backend, cls=InflightScheduler,
        policy=RetryPolicy(max_attempts=2, backoff_base_s=0.005, jitter=0.0),
    )
    # the poison prompt crashes BOTH the slot loop's segments and the
    # one-shot retry path, so quarantine must come from bisection
    plan = FaultPlan([
        FaultSpec(site="fake.slot_step", kind="poison", match="DOC-POISON"),
        FaultSpec(site="fake.dispatch", kind="poison", match="DOC-POISON"),
    ])
    try:
        prompts = [f"van ban {i} rat dai nhieu chu " * 6 for i in range(4)]
        prompts[2] = "van ban DOC-POISON doc hai " * 6
        with injected(plan):
            futs = [sched.submit(p) for p in prompts]
            res = _collect(futs)
        assert isinstance(res[2], RequestFailed)
        assert res[2].failure_class is FailureClass.POISON
        for i in (0, 1, 3):
            assert res[i].record.status == "ok"
        assert sched.metrics.snapshot().quarantined == 1
    finally:
        sched.close()


def test_inflight_admit_crash_recovers():
    backend = FakeBackend(segment_words=4)
    _, sup, sched = _supervised(backend, cls=InflightScheduler)
    plan = FaultPlan([FaultSpec(site="fake.slot_admit", kind="raise",
                                on_call=1)])
    try:
        with injected(plan):
            futs = [sched.submit(f"nhap cuoc {i} " * 6) for i in range(3)]
            outs = _collect(futs)
        assert all(c.record.status == "ok" for c in outs)
    finally:
        sched.close()


# -- resource hygiene across crashes -----------------------------------------


def test_radix_pins_return_to_prebatch_level_after_crash():
    backend = FakeBackend(prefix_cache_blocks=64, cache_block_tokens=4)
    _, sup, sched = _supervised(backend)
    try:
        header = "tieu de dung chung rat dai on dinh " * 4
        sched.submit(header + "duoi mot").result(timeout=30)
        assert backend.prefix_index.pinned_blocks == 0
        # crash WHILE the cache pass holds pins (the fake.prefill site), on
        # every attempt: the request is eventually quarantined, and not one
        # pin may leak across all those crashed dispatches
        plan = FaultPlan([FaultSpec(site="fake.prefill", kind="raise",
                                    every_n=1)])
        with injected(plan):
            f = sched.submit(header + "duoi hai khac biet")
            res = _collect([f])
        assert isinstance(res[0], RequestFailed)
        assert backend.prefix_index.pinned_blocks == 0
        # and the cache still works afterwards
        c = sched.submit(header + "duoi ba").result(timeout=30)
        assert c.record.status == "ok"
        assert backend.prefix_index.pinned_blocks == 0
    finally:
        sched.close()


# -- drain overrun -----------------------------------------------------------


class _HungBackend(FakeBackend):
    """generate() blocks until released — a wedged engine dispatch."""

    def __init__(self, **kw):
        super().__init__(**kw)
        self.release = threading.Event()

    def generate(self, prompts, **kw):
        self.release.wait(timeout=10)
        return super().generate(prompts, **kw)


def test_drain_overrun_sheds_queued_and_inflight_futures_typed():
    backend = _HungBackend()
    sched = MicroBatchScheduler(backend, max_batch=1, max_wait_s=0.0)
    futs = [sched.submit(f"ket cung {i} " * 4) for i in range(3)]
    t0 = time.monotonic()
    sched.close(drain=True, timeout=0.3)
    assert time.monotonic() - t0 < 5.0
    for f in futs:  # every future resolves with the typed shed — no hangs
        with pytest.raises(RequestShed) as exc:
            f.result(timeout=5)
        assert exc.value.reason is ShedReason.SHUTDOWN
    shed = sched.metrics.snapshot().shed
    assert shed.get("shutdown", 0) == 3
    backend.release.set()


def test_inflight_drain_overrun_sheds_resident_slots():
    class HungSegments(FakeBackend):
        def __init__(self):
            super().__init__(segment_words=2)
            self.release = threading.Event()

        def start_slot_loop(self, *a, **kw):
            loop = super().start_slot_loop(*a, **kw)
            orig = loop.step

            def slow_step():
                self.release.wait(timeout=10)
                return orig()

            loop.step = slow_step
            return loop

    backend = HungSegments()
    sched = InflightScheduler(backend, slots=2, max_wait_s=0.05)
    futs = [sched.submit(f"ngu quen {i} nhieu tu lam " * 8)
            for i in range(2)]
    time.sleep(0.3)  # let the loop admit them before closing
    sched.close(drain=True, timeout=0.3)
    for f in futs:
        with pytest.raises(RequestShed) as exc:
            f.result(timeout=5)
        assert exc.value.reason is ShedReason.SHUTDOWN
    backend.release.set()


# -- HTTP contract -----------------------------------------------------------


@pytest.fixture()
def degraded_server():
    from vnsum_tpu.serve.server import ServeState, make_server

    sup = EngineSupervisor(FAST, resource_strikes_per_step=1,
                           probe_interval_s=30.0, brownout_retry_after_s=3.0)
    state = ServeState(FakeBackend(), max_batch=4, max_wait_s=0.005,
                       supervisor=sup)
    server = make_server(state, "127.0.0.1", 0)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    yield f"http://127.0.0.1:{server.server_address[1]}", state, sup
    server.shutdown()
    server.server_close()
    state.close()


def test_brownout_is_http_503_with_retry_after_and_healthz_reports_rung(
    degraded_server,
):
    base, state, sup = degraded_server
    # healthy first
    with urllib.request.urlopen(base + "/healthz", timeout=10) as resp:
        d = json.loads(resp.read())
    assert d["status"] == "ok" and d["degraded_rung"] == 0
    for _ in range(4):
        sup.note_failure(FailureClass.RESOURCE)
    assert sup.rung == Rung.BROWNOUT
    with urllib.request.urlopen(base + "/healthz", timeout=10) as resp:
        d = json.loads(resp.read())
    assert d["status"] == "degraded" and d["degraded_rung"] == 4
    assert d["degraded"] == "brownout"
    req = urllib.request.Request(
        base + "/v1/generate",
        data=json.dumps({"prompt": "xin chao " * 5}).encode(),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    with pytest.raises(urllib.error.HTTPError) as exc:
        urllib.request.urlopen(req, timeout=10)
    assert exc.value.code == 503
    assert exc.value.headers["Retry-After"] == "3"
    body = json.loads(exc.value.read())
    assert body["reason"] == "brownout" and body["retry_after_s"] == 3.0
    # the rung gauge is on /metrics
    with urllib.request.urlopen(base + "/metrics", timeout=10) as resp:
        text = resp.read().decode()
    assert "vnsum_serve_degraded_rung 4" in text


# -- end-to-end seeded plan (the acceptance scenario) ------------------------


def test_seeded_fault_plan_end_to_end_metrics_and_outcomes():
    """ISSUE 9 acceptance: crash on dispatch N + one poison request + a
    RESOURCE_EXHAUSTED burst, under one seeded plan. Zero unresolved
    futures, ONLY the poison request failed, the ladder stepped down and
    recovered, and /metrics shows all of it."""
    backend = FakeBackend()
    sup = EngineSupervisor(
        RetryPolicy(max_attempts=4, backoff_base_s=0.005,
                    backoff_max_s=0.02, jitter=0.25, seed=11),
        resource_strikes_per_step=2, probe_interval_s=0.15,
    )
    sched = MicroBatchScheduler(backend, max_batch=4, max_wait_s=0.05,
                                supervisor=sup)
    plan = FaultPlan([
        FaultSpec(site="fake.dispatch", kind="raise", on_call=2),
        FaultSpec(site="fake.dispatch", kind="resource", on_call=4),
        FaultSpec(site="fake.dispatch", kind="resource", on_call=5),
        FaultSpec(site="fake.dispatch", kind="poison", match="DOC-POISON"),
    ], seed=11)
    try:
        prompts = [f"tai lieu so {i} noi dung " * 8 for i in range(24)]
        prompts[13] = "tai lieu DOC-POISON hong " * 8
        with injected(plan):
            futs = []
            for p in prompts:
                futs.append(sched.submit(p))
                time.sleep(0.002)
            res = _collect(futs)
        # zero unresolved futures (collect would have timed out), and only
        # the poison request failed
        failed = [i for i, r in enumerate(res) if isinstance(r, Exception)]
        assert failed == [13]
        assert isinstance(res[13], RequestFailed)
        assert res[13].failure_class is FailureClass.POISON
        assert all(r.record.status == "ok"
                   for i, r in enumerate(res) if i != 13)
        s = sched.metrics.snapshot()
        assert s.completed == 23 and s.errors == 1
        assert s.degraded_steps >= 1  # the resource burst stepped down
        assert s.retries >= 1 and s.bisects >= 1 and s.quarantined == 1
        # recovery: quiet traffic after the burst climbs back to HEALTHY
        deadline = time.monotonic() + 5.0
        while sup.rung != Rung.HEALTHY and time.monotonic() < deadline:
            time.sleep(0.16)
            sched.submit("tham do hoi phuc " * 4).result(timeout=30)
        assert sup.rung == Rung.HEALTHY
        text = sched.metrics.render_prometheus(degraded_rung=int(sup.rung))
        assert 'vnsum_serve_fault_failures_total{class="resource_exhausted"} 2' in text
        assert "vnsum_serve_degraded_steps_total 1" in text
        assert "vnsum_serve_degraded_recoveries_total 1" in text
        assert "vnsum_serve_degraded_rung 0" in text
        assert "vnsum_serve_fault_quarantined_total 1" in text
    finally:
        sched.close()


def test_healthy_path_pays_no_extra_dispatches_under_supervision():
    """Supervision off the hot path: with no faults, a supervised scheduler
    performs EXACTLY the dispatches an unsupervised one does."""
    runs = []
    for supervised in (False, True):
        backend = FakeBackend()
        sup = EngineSupervisor(FAST) if supervised else None
        sched = MicroBatchScheduler(backend, max_batch=4, max_wait_s=0.2,
                                    supervisor=sup)
        try:
            barrier = threading.Barrier(8)
            futs = [None] * 8

            def worker(i):
                barrier.wait()
                futs[i] = sched.submit(f"deu nhau {i} " * 6)

            threads = [threading.Thread(target=worker, args=(i,))
                       for i in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            outs = _collect(futs)
            assert all(c.record.status == "ok" for c in outs)
            runs.append(sorted(backend.batch_sizes))
        finally:
            sched.close()
    assert runs[0] == runs[1]
    assert sum(runs[1]) == 8  # no request dispatched twice
