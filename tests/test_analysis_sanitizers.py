"""Runtime-sanitizer acceptance over the REAL serving stack:

- lock-order detector green (and actually watching) under concurrent
  scheduler traffic and under cache-eviction churn — the two paths ISSUE 7
  names as deadlock suspects;
- transfer-guard mode green over a hermetic TpuBackend prefill/decode run
  (one-shot AND continuous), with byte-identical outputs;
- the disabled-mode no-op guarantee: with sanitizers off the serve/cache
  locks are plain ``threading.Lock`` objects — no wrapper, zero extra
  acquisitions on the scheduler hot path — so serving goodput
  (BENCH_serving_r03) is untouched by this machinery existing.

CPU caveat (documented in analysis/sanitizers.py): device<->host on CPU JAX
is zero-copy, so the transfer guard cannot fire there — these tests verify
the guarded path stays green and the real jax context is installed; the
blocking behavior itself is asserted only on accelerator backends.
"""
from __future__ import annotations

import contextlib
import threading

import numpy as np
import pytest

pytest.importorskip("jax")

from vnsum_tpu.analysis import sanitizers
from vnsum_tpu.backend.fake import FakeBackend
from vnsum_tpu.serve.metrics import ServeMetrics
from vnsum_tpu.serve.queue import RequestQueue
from vnsum_tpu.serve.scheduler import MicroBatchScheduler


@pytest.fixture
def lock_sanitizer(monkeypatch):
    monkeypatch.setenv("VNSUM_SANITIZERS", "lock")
    sanitizers.lock_graph().reset()
    yield
    sanitizers.lock_graph().reset()


# -- lock order under the real concurrent paths ------------------------------


def test_lock_order_green_under_concurrent_scheduler(lock_sanitizer):
    """The PR 1 coalescing path with every lock tracked: queue cond,
    metrics, obs hub/trace — concurrent submits must complete with zero
    wait-for cycles, and the graph must prove it was actually watching."""
    from vnsum_tpu.obs import ObsHub

    sched = MicroBatchScheduler(
        FakeBackend(), max_batch=8, max_wait_s=0.05, obs=ObsHub(sample=1.0),
    )
    try:
        assert isinstance(sched.queue._lock, sanitizers.TrackedLock)
        barrier = threading.Barrier(6)
        errors = []

        def worker(i):
            barrier.wait()
            try:
                sched.submit(f"tai lieu {i} " * 10).result(timeout=30)
            except Exception as e:  # noqa: BLE001 - assertion target
                errors.append(e)

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    finally:
        sched.close()
    assert not errors
    assert sanitizers.lock_order_violations() == []
    # the detector saw the queue-lock -> metrics-lock nesting (on_admit
    # runs under the queue cond) — the graph is populated, not idle
    edges = sanitizers.lock_graph().edges()
    assert "serve.metrics" in edges.get("serve.queue", set())


def test_lock_order_green_under_cache_eviction_traffic(lock_sanitizer):
    """PR 4's eviction-under-traffic path — ISSUE 7's prime deadlock
    suspect: a tight radix pool churning evictions on the scheduler thread
    while submit-side threads probe it for admission billing. Must stay
    cycle-free with the radix lock in the tracked graph."""
    fb = FakeBackend(prefix_cache_blocks=6, cache_block_tokens=2)
    oracle = FakeBackend()
    sched = MicroBatchScheduler(
        fb, max_batch=4, max_wait_s=0.002,
        # a token budget forces cached_prefix_tokens probes (radix lock)
        # from the submitting threads, concurrent with engine-side inserts
        max_queued_tokens=100_000,
    )
    headers = [f"tieu de so {h} lap lai nhieu lan " for h in range(3)]
    errors = []

    def client(tid):
        try:
            for i in range(10):
                h = headers[(tid + i) % len(headers)]
                prompt = h * 2 + f"phan rieng {tid} {i} con lai"
                got = sched.submit(prompt, cache_hint=h * 2).result(timeout=15)
                want = oracle.generate([prompt])[0]
                if got.text != want:
                    errors.append((prompt, got.text, want))
        except Exception as e:  # pragma: no cover - assertion target
            errors.append(e)

    threads = [threading.Thread(target=client, args=(t,)) for t in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    sched.close()
    assert not errors
    assert sanitizers.lock_order_violations() == []
    assert fb.prefix_cache_stats()["evictions"] > 0  # churn really happened
    assert isinstance(
        fb.prefix_index._lock, sanitizers.TrackedLock
    )  # the radix lock was in the tracked graph, not a bystander


# -- transfer guard over a hermetic engine run -------------------------------


@pytest.fixture(scope="module")
def tiny():
    from vnsum_tpu.models import jitted_init
    from vnsum_tpu.models.llama import init_params, tiny_llama

    cfg = tiny_llama(max_seq_len=256)
    return cfg, jitted_init(init_params, cfg, 0)


def test_transfer_guard_green_over_engine_decode_prefill(tiny, monkeypatch):
    """Acceptance: sanitizer transfer mode passes over hermetic one-shot
    AND continuous prefill/decode runs, byte-identical to unsanitized —
    every hot-loop sync is an explicit (lint-acknowledged) device_get."""
    from vnsum_tpu.backend.engine import TpuBackend

    cfg, params = tiny
    prompts = [f"van ban nguon so {i} can tom tat ngay" for i in range(3)]

    monkeypatch.delenv("VNSUM_SANITIZERS", raising=False)
    base = TpuBackend(model_config=cfg, params=params, batch_size=4,
                      max_new_tokens=8)
    want = base.generate(prompts)

    monkeypatch.setenv("VNSUM_SANITIZERS", "transfer")
    one_shot = TpuBackend(model_config=cfg, params=params, batch_size=4,
                          max_new_tokens=8)
    assert one_shot.generate(prompts) == want
    segmented = TpuBackend(model_config=cfg, params=params, batch_size=4,
                           max_new_tokens=8, continuous=True,
                           segment_tokens=4)
    assert segmented.generate(prompts) == want


def test_transfer_guard_context_selection(monkeypatch):
    monkeypatch.delenv("VNSUM_SANITIZERS", raising=False)
    assert isinstance(
        sanitizers.hot_path_transfer_guard(), contextlib.nullcontext
    )
    monkeypatch.setenv("VNSUM_SANITIZERS", "transfer")
    assert not isinstance(
        sanitizers.hot_path_transfer_guard(), contextlib.nullcontext
    )


def test_transfer_guard_explicit_fetch_always_passes(monkeypatch):
    import jax
    import jax.numpy as jnp

    monkeypatch.setenv("VNSUM_SANITIZERS", "transfer")
    x = jnp.arange(4)
    with sanitizers.hot_path_transfer_guard():
        assert jax.device_get(x).tolist() == [0, 1, 2, 3]
        try:
            np.asarray(x)
            implicit_blocked = False
        except Exception:  # noqa: BLE001 - jax raises a backend error type
            implicit_blocked = True
    if jax.default_backend() != "cpu":
        # on accelerators the implicit d2h must error; CPU is zero-copy and
        # unguardable — the context installation is still exercised above
        assert implicit_blocked


# -- disabled mode is a true no-op (the bench guard, ISSUE 7 satellite) ------


def test_sanitizers_disabled_are_noops(monkeypatch):
    """With VNSUM_SANITIZERS unset, serve/cache locks are PLAIN
    threading.Lock objects (no wrapper exists at all — zero extra
    acquisitions on the scheduler hot path) and the wait-for graph stays
    empty across real traffic, so serving goodput is untouched."""
    from vnsum_tpu.cache.radix import RadixIndex

    monkeypatch.delenv("VNSUM_SANITIZERS", raising=False)
    sanitizers.lock_graph().reset()
    plain = type(threading.Lock())
    assert type(RequestQueue()._lock) is plain
    assert type(ServeMetrics()._lock) is plain
    assert type(RadixIndex(4, 2)._lock) is plain

    sched = MicroBatchScheduler(FakeBackend(), max_batch=4, max_wait_s=0.01)
    try:
        assert type(sched.queue._lock) is plain
        futs = [sched.submit(f"tai lieu {i} " * 8) for i in range(5)]
        for f in futs:
            f.result(timeout=30)
    finally:
        sched.close()
    assert sanitizers.lock_graph().edges() == {}
    assert sanitizers.lock_order_violations() == []
