"""Durable serving: write-ahead journal record properties (CRC, torn
tails, rotation under concurrent writers, replay idempotence), scheduler
lifecycle integration, restart replay byte-identity, the HTTP poll
surface, and the chaos helpers' seeded determinism."""
from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from vnsum_tpu.backend.fake import FakeBackend
from vnsum_tpu.serve.journal import RequestJournal, _encode
from vnsum_tpu.serve.queue import RequestShed, ServeRequest
from vnsum_tpu.serve.scheduler import MicroBatchScheduler
from vnsum_tpu.serve.server import ServeState, make_server
from vnsum_tpu.testing.chaos import KillSchedule, free_port


def _req(prompt="văn bản cần tóm tắt " * 8, trace_id="t-1", **kw):
    return ServeRequest(prompt=prompt, trace_id=trace_id, **kw)


def _segments(directory):
    return sorted(directory.glob("journal.*.jsonl"))


# -- record / recovery properties -------------------------------------------


def test_lifecycle_roundtrip_and_reopen(tmp_path):
    j = RequestJournal(tmp_path)
    rid = j.accept(_req(trace_id="a"))
    assert rid == "a"
    j.start(rid)
    j.complete(rid, "kết quả tóm tắt", gen_tokens=3)
    rid2 = j.accept(_req(trace_id="b"))
    j.fail(rid2, "shed:deadline", "expired")
    j.close()  # no seal: simulated crash

    j2 = RequestJournal(tmp_path)
    (a,) = j2.lookup("a")
    assert a.status == "complete" and a.text == "kết quả tóm tắt"
    assert a.gen_tokens == 3
    (b,) = j2.lookup("b")
    assert b.status == "failed" and b.reason == "shed:deadline"
    assert j2.pending() == 0 and not j2.recovered_sealed
    j2.close()


def test_fanout_rids_and_lookup_children(tmp_path):
    j = RequestJournal(tmp_path)
    rids = [j.accept(_req(trace_id="req")) for _ in range(3)]
    assert rids == ["req", "req#1", "req#2"]
    assert {e.rid for e in j.lookup("req")} == set(rids)
    # a different trace never leaks into the prefix match
    j.accept(_req(trace_id="req2"))
    assert {e.rid for e in j.lookup("req")} == set(rids)
    j.close()


def test_crc_rejects_torn_tail(tmp_path):
    j = RequestJournal(tmp_path)
    j.accept(_req(trace_id="keep"))
    j.complete("keep", "done")
    j.accept(_req(trace_id="torn"))
    j.close()
    # tear the last record mid-line, like a kill mid-write leaves it
    (seg,) = _segments(tmp_path)
    data = seg.read_bytes()
    seg.write_bytes(data[:-17])

    entries, sealed, torn = RequestJournal.read_state(tmp_path)
    assert torn == 1
    assert "torn" not in entries  # the torn ACCEPT is dropped, not garbage
    assert entries["keep"].status == "complete"


def test_crc_rejects_corrupt_record_and_stops_trusting_segment(tmp_path):
    j = RequestJournal(tmp_path)
    for t in ("a", "b", "c"):
        j.accept(_req(trace_id=t))
    j.close()
    (seg,) = _segments(tmp_path)
    lines = seg.read_bytes().splitlines(keepends=True)
    # flip a byte inside record b's JSON body: CRC must catch it and the
    # reader must stop trusting everything after it in this segment
    lines[1] = lines[1][:15] + b"X" + lines[1][16:]
    seg.write_bytes(b"".join(lines))

    entries, _sealed, torn = RequestJournal.read_state(tmp_path)
    assert torn == 1
    assert set(entries) == {"a"}


def test_sealed_journal_compacts_on_reopen(tmp_path):
    j = RequestJournal(tmp_path, max_segment_bytes=400)
    for i in range(8):
        rid = j.accept(_req(trace_id=f"r{i}"))
        j.complete(rid, f"out-{i}")
    assert j.rotations > 0 and len(_segments(tmp_path)) > 1
    j.seal()
    j.close()

    j2 = RequestJournal(tmp_path)
    assert j2.recovered_sealed
    # compaction rewrote live state into ONE fresh segment (atomically)
    assert len(_segments(tmp_path)) == 1
    for i in range(8):
        (e,) = j2.lookup(f"r{i}")
        assert e.status == "complete" and e.text == f"out-{i}"
    j2.close()


def test_rotation_under_concurrent_writers(tmp_path):
    j = RequestJournal(tmp_path, max_segment_bytes=2048)
    n_threads, per_thread = 6, 40
    errors = []

    def writer(t):
        try:
            for i in range(per_thread):
                rid = j.accept(_req(trace_id=f"w{t}-{i}"))
                j.start(rid)
                j.complete(rid, f"text-{t}-{i}")
        except Exception as e:  # pragma: no cover - the assertion below
            errors.append(e)

    threads = [threading.Thread(target=writer, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    assert j.rotations > 0  # the property under test actually exercised
    j.close()

    # every record survives rotation, exactly once, with its final state
    entries, _sealed, torn = RequestJournal.read_state(tmp_path)
    assert torn == 0
    assert len(entries) == n_threads * per_thread
    for t in range(n_threads):
        for i in range(per_thread):
            e = entries[f"w{t}-{i}"]
            assert e.status == "complete" and e.text == f"text-{t}-{i}"


def test_accept_is_idempotent_per_rid(tmp_path):
    j = RequestJournal(tmp_path)
    req = _req(trace_id="once")
    j.accept(req)
    before = j.records
    # replay resubmission path: journal_rid preset -> no duplicate ACCEPT
    j.accept(req)
    assert j.records == before
    assert len(j.lookup("once")) == 1
    j.close()


def test_take_unfinished_hands_each_entry_out_once(tmp_path):
    j = RequestJournal(tmp_path)
    j.accept(_req(trace_id="u1"))
    j.accept(_req(trace_id="u2"))
    rid = j.accept(_req(trace_id="done"))
    j.complete(rid, "x")
    j.close()

    j2 = RequestJournal(tmp_path)
    first = {e.rid for e in j2.take_unfinished()}
    assert first == {"u1", "u2"}
    # replaying twice enqueues once: the second take returns nothing
    assert j2.take_unfinished() == []
    j2.close()


def test_terminal_eviction_keeps_unfinished_and_bounds_history(tmp_path):
    j = RequestJournal(tmp_path, keep_terminal=5)
    j.accept(_req(trace_id="open"))
    for i in range(12):
        rid = j.accept(_req(trace_id=f"d{i}"))
        j.complete(rid, "x")
    assert j.pending() == 1  # the open entry is never evicted
    assert len(j.lookup("open")) == 1
    assert sum(1 for i in range(12) if j.lookup(f"d{i}")) <= 5
    j.close()


def test_torn_tail_then_append_continues_cleanly(tmp_path):
    """A recovered-then-compacted journal is immediately writable and the
    pre-tear state survives the next generation too."""
    j = RequestJournal(tmp_path)
    j.accept(_req(trace_id="old"))
    j.close()
    (seg,) = _segments(tmp_path)
    seg.write_bytes(seg.read_bytes() + b"deadbeef {torn")  # garbage tail

    j2 = RequestJournal(tmp_path)
    assert j2.torn_records == 1
    rid = j2.accept(_req(trace_id="new"))
    j2.complete(rid, "ok")
    j2.seal()
    j2.close()
    entries, sealed, torn = RequestJournal.read_state(tmp_path)
    assert sealed and torn == 0  # compaction dropped the garbage for good
    assert set(entries) == {"old", "new"}


# -- scheduler integration ---------------------------------------------------


def test_scheduler_journals_full_lifecycle(tmp_path):
    j = RequestJournal(tmp_path)
    sched = MicroBatchScheduler(FakeBackend(), max_batch=4, max_wait_s=0.005,
                                journal=j)
    fut = sched.submit("nội dung " * 10, trace_id="life")
    out = fut.result(timeout=10)
    sched.close()
    (e,) = j.lookup("life")
    assert e.status == "complete" and e.text == out.text
    j.close()


def test_scheduler_journals_engine_failure_typed(tmp_path):
    j = RequestJournal(tmp_path)

    class Exploding(FakeBackend):
        def generate(self, prompts, **kw):
            raise RuntimeError("engine down")

    sched = MicroBatchScheduler(Exploding(), max_batch=4, max_wait_s=0.005,
                                journal=j)
    fut = sched.submit("x " * 5, trace_id="boom")
    with pytest.raises(RuntimeError):
        fut.result(timeout=10)
    sched.close()
    (e,) = j.lookup("boom")
    assert e.status == "failed" and e.reason == "error"
    j.close()


def test_queue_shed_of_admitted_request_is_journaled_failed(tmp_path):
    j = RequestJournal(tmp_path)
    slow = FakeBackend(batch_overhead_s=0.2)
    sched = MicroBatchScheduler(slow, max_batch=1, max_wait_s=0.0, journal=j)
    # head occupies the engine; the second request's deadline expires queued
    f1 = sched.submit("đầu " * 5, trace_id="head")
    f2 = sched.submit("hết hạn " * 5, trace_id="late",
                      deadline=time.monotonic() + 0.05)
    with pytest.raises(RequestShed):
        f2.result(timeout=10)
    f1.result(timeout=10)
    sched.close()
    (e,) = j.lookup("late")
    assert e.status == "failed" and e.reason == "shed:deadline"
    j.close()


def test_admission_shed_is_never_journaled(tmp_path):
    j = RequestJournal(tmp_path)
    slow = FakeBackend(batch_overhead_s=0.2)
    sched = MicroBatchScheduler(slow, max_batch=1, max_wait_s=0.0,
                                max_queue_depth=1, journal=j)
    f1 = sched.submit("a " * 5, trace_id="in")
    time.sleep(0.05)  # f1 is now inside the 0.2s engine dispatch
    f2 = sched.submit("b " * 5, trace_id="queued")  # fills the depth-1 queue
    with pytest.raises(RequestShed):
        # never accepted -> the ledger owes it nothing (the client got a
        # synchronous typed 429; at-least-once starts at ACCEPT)
        sched.submit("c " * 5, trace_id="shed-me")
    f1.result(timeout=10)
    f2.result(timeout=10)
    sched.close()
    j.close()
    entries, _, _ = RequestJournal.read_state(tmp_path)
    assert {"in", "queued"} <= set(entries)
    assert "shed-me" not in entries


# -- restart replay ----------------------------------------------------------


def test_restart_replays_unfinished_byte_identically(tmp_path):
    prompt = "văn bản dang dở cần phát lại " * 6
    # life 1: accept lands in the journal, process "dies" before dispatch
    j = RequestJournal(tmp_path)
    j.accept(_req(prompt=prompt, trace_id="replay-me"))
    j.close()  # crash: no terminal record, no seal

    # life 2: ServeState replays through the normal path
    state = ServeState(FakeBackend(), max_batch=4, max_wait_s=0.005,
                       trace_sample=0.0, journal_dir=str(tmp_path))
    assert state.replay_journal() == 1
    t_end = time.monotonic() + 10
    while state.journal.pending() and time.monotonic() < t_end:
        time.sleep(0.01)
    (e,) = state.journal.lookup("replay-me")
    assert e.status == "complete"
    # byte-identity: the replayed output equals an uninterrupted run's
    assert e.text == FakeBackend().generate([prompt])[0]
    # idempotence at the state level: a second replay enqueues nothing
    assert state.replay_journal() == 0
    state.close()


def test_replay_restores_config_and_expires_stale_deadlines(tmp_path):
    from vnsum_tpu.core.config import GenerationConfig

    j = RequestJournal(tmp_path)
    cfg = GenerationConfig(temperature=0.0, seed=123, top_k=4)
    j.accept(_req(prompt="có cấu hình " * 5, trace_id="cfg",
                  config=cfg))
    j.accept(_req(prompt="đã hết hạn " * 5, trace_id="stale",
                  deadline=time.monotonic() - 1.0))
    j.close()

    state = ServeState(FakeBackend(), max_batch=4, max_wait_s=0.005,
                       trace_sample=0.0, journal_dir=str(tmp_path))
    assert state.replay_journal() == 1  # the stale one fails without enqueue
    (stale,) = state.journal.lookup("stale")
    assert stale.status == "failed" and stale.reason == "shed:deadline"
    t_end = time.monotonic() + 10
    while state.journal.pending() and time.monotonic() < t_end:
        time.sleep(0.01)
    (e,) = state.journal.lookup("cfg")
    assert e.status == "complete"
    state.close()


# -- HTTP surface ------------------------------------------------------------


@pytest.fixture()
def journal_serve(tmp_path):
    state = ServeState(FakeBackend(), max_batch=8, max_wait_s=0.005,
                       trace_sample=0.0, journal_dir=str(tmp_path))
    server = make_server(state, "127.0.0.1", 0)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    yield f"http://127.0.0.1:{server.server_address[1]}", state
    server.shutdown()
    server.server_close()
    state.close()


def _post(url, payload):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    with urllib.request.urlopen(req, timeout=60) as resp:
        return resp.status, json.loads(resp.read())


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as resp:
        return resp.status, json.loads(resp.read())


def test_poll_endpoint_serves_journaled_result(journal_serve):
    base, state = journal_serve
    status, d = _post(base + "/v1/generate",
                      {"prompt": "xin chào " * 10, "request_id": "poll-me"})
    assert status == 200
    text = d["completions"][0]["text"]
    status, d = _get(base + "/v1/requests/poll-me")
    assert status == 200
    assert d["status"] == "completed"
    assert d["entries"][0]["text"] == text


def test_poll_unknown_id_is_404(journal_serve):
    base, _ = journal_serve
    with pytest.raises(urllib.error.HTTPError) as exc:
        _get(base + "/v1/requests/never-seen")
    assert exc.value.code == 404


def test_poll_without_journal_is_404():
    state = ServeState(FakeBackend(), max_batch=4, max_wait_s=0.005,
                       trace_sample=0.0)
    server = make_server(state, "127.0.0.1", 0)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{server.server_address[1]}"
    try:
        with pytest.raises(urllib.error.HTTPError) as exc:
            _get(base + "/v1/requests/x")
        assert exc.value.code == 404
    finally:
        server.shutdown()
        server.server_close()
        state.close()


def test_journal_metrics_rendered(journal_serve):
    base, state = journal_serve
    _post(base + "/v1/generate", {"prompt": "đo lường " * 8})
    with urllib.request.urlopen(base + "/metrics", timeout=10) as resp:
        text = resp.read().decode()
    assert "vnsum_serve_journal_records_total" in text
    assert "vnsum_serve_journal_pending 0" in text


def test_inflight_scheduler_journals_slot_completions(tmp_path):
    j = RequestJournal(tmp_path)
    state = ServeState(
        FakeBackend(segment_words=4), max_batch=4, max_wait_s=0.005,
        trace_sample=0.0, inflight=True,
    )
    # swap the journal in (ServeState builds from journal_dir; here we hand
    # the scheduler one directly to keep the in-flight path isolated)
    state.scheduler.journal = j
    fut = state.scheduler.submit("từng đoạn " * 12, trace_id="slots")
    out = fut.result(timeout=10)
    state.close()
    (e,) = j.lookup("slots")
    assert e.status == "complete" and e.text == out.text
    j.close()


# -- chaos helpers -----------------------------------------------------------


def test_kill_schedule_is_seeded_and_covers_required_kinds():
    a = KillSchedule(seed=7, kills=3)
    b = KillSchedule(seed=7, kills=3)
    assert a.describe() == b.describe()  # replayable from the seed
    kinds = {p.kind for p in a.points}
    assert kinds == {"mid_load", "mid_drain"}
    assert KillSchedule(seed=8, kills=3).describe() != a.describe()


def test_free_port_binds():
    port = free_port()
    assert 0 < port < 65536


def test_encode_lines_are_newline_framed():
    raw = _encode({"e": "accept", "rid": "x", "prompt": "có dấu ư"})
    assert raw.endswith(b"\n") and raw[8:9] == b" "
    assert b"\n" not in raw[:-1]  # one record, one line — framing invariant

# -- inspection CLI (python -m vnsum_tpu.serve.journal) ----------------------


def _sealed_fixture(tmp_path):
    """A sealed journal with one of each fate: a COMPLETE, a typed FAIL,
    and one unfinished ACCEPT (the handoff debt the CLI must surface)."""
    j = RequestJournal(tmp_path)
    done = j.accept(_req(prompt="đã xong " * 4, trace_id="cli-done"))
    j.start(done)
    j.complete(done, "kết quả", 3)
    bad = j.accept(_req(prompt="hỏng " * 4, trace_id="cli-bad"))
    j.fail(bad, "engine:boom", "giả lập")
    j.accept(_req(prompt="dang dở " * 4, trace_id="cli-open"))
    j.seal()
    j.close()


def test_journal_cli_dumps_sealed_fixture(tmp_path, capsys):
    from vnsum_tpu.serve.journal import _main

    _sealed_fixture(tmp_path)
    assert _main([str(tmp_path)]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["sealed"] is True and out["torn_records"] == 0
    assert out["entries"] == 3 and out["live"] == 1 and out["terminal"] == 2
    assert out["by_status"] == {"complete": 1, "failed": 1, "accept": 1}
    (open_,) = out["unfinished_accepts"]
    assert open_["rid"] == "cli-open" and open_["status"] == "accept"
    # the dumped payload is the full replayable ACCEPT record
    assert open_["payload"]["prompt"].startswith("dang dở")
    assert "max_new_tokens" in open_["payload"]


def test_journal_cli_subprocess_and_bad_dir(tmp_path):
    import subprocess
    import sys

    _sealed_fixture(tmp_path)
    proc = subprocess.run(
        [sys.executable, "-m", "vnsum_tpu.serve.journal", str(tmp_path)],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stderr
    assert json.loads(proc.stdout)["live"] == 1
    proc = subprocess.run(
        [sys.executable, "-m", "vnsum_tpu.serve.journal",
         str(tmp_path / "missing")],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 2
    # last stderr line: runpy may prepend a sys.modules RuntimeWarning
    err = json.loads(proc.stderr.strip().splitlines()[-1])
    assert "not a directory" in err["error"]


# -- cross-process journal handoff -------------------------------------------


@pytest.mark.slow
def test_cross_process_handoff_completes_byte_identically(tmp_path):
    """The fleet failover invariant, minus the router: SIGKILL worker A
    mid-flight, read its journal from the outside, re-dispatch every
    unfinished ACCEPT onto an unrelated worker B over plain HTTP, and the
    completions byte-match an uninterrupted run. This is exactly what
    RouterState._handoff does — pinned here as a two-process protocol
    test so a journal/payload schema drift fails loudly."""
    from vnsum_tpu.serve.router import request_body_from_payload
    from vnsum_tpu.testing.chaos import ServerProcess, http_json

    dir_a = tmp_path / "worker-a"
    dir_b = tmp_path / "worker-b"
    a = ServerProcess(free_port(), journal_dir=str(dir_a),
                      extra_args=["--fake-batch-overhead-ms", "3000"])
    a.start()
    prompts = [f"bản tin bị bỏ dở số {i} " * 4 for i in range(3)]
    try:
        a.wait_healthy()
        threads = [
            threading.Thread(
                target=lambda p=p, i=i: http_json(
                    "POST", "127.0.0.1", a.port, "/v1/generate",
                    {"prompt": p, "request_id": f"handoff-{i}",
                     "max_new_tokens": 16},
                    timeout=30.0,
                ),
                daemon=True,
            )
            for i, p in enumerate(prompts)
        ]
        for t in threads:
            t.start()
        # let the ACCEPTs hit A's journal while the 3s batch overhead
        # keeps every request non-terminal
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            entries, _, _ = RequestJournal.read_state(dir_a)
            if len(entries) == len(prompts):
                break
            time.sleep(0.05)
    finally:
        a.sigkill()  # the crash under test: no drain, no seal

    entries, sealed, _ = RequestJournal.read_state(dir_a)
    assert sealed is False
    unfinished = [e for e in entries.values() if not e.terminal]
    assert len(unfinished) == len(prompts)

    b = ServerProcess(free_port(), journal_dir=str(dir_b))
    b.start()
    try:
        b.wait_healthy()
        for e in unfinished:
            path, body, headers = request_body_from_payload(e.rid, e.payload)
            status, resp = http_json("POST", "127.0.0.1", b.port, path,
                                     body, timeout=30.0)
            assert status == 200, resp
            text = resp["completions"][0]["text"]
            # byte-identity against an uninterrupted in-process run of
            # the SAME journaled payload
            assert text == FakeBackend().generate(
                [e.payload["prompt"]],
                max_new_tokens=e.payload.get("max_new_tokens"),
            )[0]
        b.sigterm()
        assert b.wait_exit(30.0) == 0  # graceful: drain + seal
    finally:
        if b.alive:
            b.sigkill()
    _, sealed_b, _ = RequestJournal.read_state(dir_b)
    assert sealed_b is True
