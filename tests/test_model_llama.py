import jax
import jax.numpy as jnp
import numpy as np
import pytest

from vnsum_tpu.models import (
    forward,
    init_kv_cache,
    init_params,
    sample_logits,
    tiny_llama,
)
from vnsum_tpu.models.llama import (
    decode_attention_mask,
    prefill_attention_mask,
    prefill_positions,
)


@pytest.fixture(scope="module")
def setup():
    cfg = tiny_llama()
    params = init_params(jax.random.key(0), cfg)
    return cfg, params


def full_forward_logits(cfg, params, tokens):
    """No-padding full-sequence forward; cache sized exactly to S."""
    B, S = tokens.shape
    cache = init_kv_cache(cfg, B, S)
    pad = jnp.zeros((B,), jnp.int32)
    mask = prefill_attention_mask(pad, S, S)
    pos = prefill_positions(pad, S)
    logits, _ = forward(params, cfg, tokens, pos, cache, 0, mask)
    return logits


def test_forward_shapes(setup):
    cfg, params = setup
    tokens = jnp.arange(12, dtype=jnp.int32).reshape(2, 6) % cfg.vocab_size
    logits = full_forward_logits(cfg, params, tokens)
    assert logits.shape == (2, 6, cfg.vocab_size)
    assert logits.dtype == jnp.float32
    assert bool(jnp.all(jnp.isfinite(logits)))


def test_causality(setup):
    """Changing a later token must not affect earlier logits."""
    cfg, params = setup
    t1 = jnp.array([[5, 6, 7, 8, 9, 10]], dtype=jnp.int32)
    t2 = t1.at[0, 5].set(99)
    l1 = full_forward_logits(cfg, params, t1)
    l2 = full_forward_logits(cfg, params, t2)
    np.testing.assert_allclose(l1[0, :5], l2[0, :5], rtol=1e-5)
    assert not np.allclose(l1[0, 5], l2[0, 5])


def test_kv_cache_decode_matches_full_forward(setup):
    """Incremental decode through the cache == recomputing from scratch."""
    cfg, params = setup
    S, extra = 8, 5
    C = S + extra
    prompt = jnp.array([list(range(10, 10 + S))], dtype=jnp.int32)
    pad = jnp.zeros((1,), jnp.int32)

    cache = init_kv_cache(cfg, 1, C)
    mask = prefill_attention_mask(pad, S, C)
    pos = prefill_positions(pad, S)
    logits, cache = forward(params, cfg, prompt, pos, cache, 0, mask)
    cur = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)

    seq = prompt
    for t in range(extra):
        seq = jnp.concatenate([seq, cur[:, None]], axis=1)
        # reference: full forward over the growing sequence
        ref_logits = full_forward_logits(cfg, params, seq)
        ref_next = jnp.argmax(ref_logits[:, -1], axis=-1)

        mask_t = decode_attention_mask(pad, S + t, C)
        pos_t = jnp.array([[S + t]], dtype=jnp.int32)
        logits, cache = forward(
            params, cfg, cur[:, None], pos_t, cache, S + t, mask_t
        )
        inc_next = jnp.argmax(logits[:, -1], axis=-1)
        assert int(inc_next[0]) == int(ref_next[0]), f"diverged at step {t}"
        cur = inc_next.astype(jnp.int32)


def test_left_padding_invariance(setup):
    """A left-padded prompt must produce the same last-token logits as the
    same prompt unpadded (masks + clipped positions do their job)."""
    cfg, params = setup
    ids = [7, 8, 9, 10]
    S = 8
    unpadded = jnp.array([ids], dtype=jnp.int32)
    l_ref = full_forward_logits(cfg, params, unpadded)[0, -1]

    padded = jnp.array([[0] * (S - len(ids)) + ids], dtype=jnp.int32)
    pad = jnp.array([S - len(ids)], jnp.int32)
    cache = init_kv_cache(cfg, 1, S)
    logits, _ = forward(
        params,
        cfg,
        padded,
        prefill_positions(pad, S),
        cache,
        0,
        prefill_attention_mask(pad, S, S),
    )
    np.testing.assert_allclose(np.asarray(l_ref), np.asarray(logits[0, -1]), rtol=2e-4, atol=2e-4)


def test_rope_scaling_toggle():
    cfg_on = tiny_llama(use_llama3_rope_scaling=True, max_seq_len=64)
    params = init_params(jax.random.key(1), cfg_on)
    tokens = jnp.ones((1, 4), jnp.int32)
    out = full_forward_logits(cfg_on, params, tokens)
    assert bool(jnp.all(jnp.isfinite(out)))


def test_sampling_greedy_and_temperature():
    logits = jnp.array([[0.0, 5.0, 1.0], [3.0, 0.0, -1.0]], jnp.float32)
    key = jax.random.key(0)
    greedy = sample_logits(logits, key, temperature=0.0)
    assert greedy.tolist() == [1, 0]
    sampled = sample_logits(jnp.tile(logits[:1], (64, 1)), key, temperature=2.0)
    assert set(np.asarray(sampled).tolist()) - {0, 1, 2} == set()
    topk = sample_logits(jnp.tile(logits[:1], (64, 1)), key, temperature=5.0, top_k=1)
    assert set(np.asarray(topk).tolist()) == {1}
    topp = sample_logits(
        jnp.tile(logits[:1], (64, 1)), key, temperature=0.5, top_p=0.5
    )
    assert set(np.asarray(topp).tolist()) == {1}
