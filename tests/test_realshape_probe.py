"""Hermetic exercise of scripts/make_multimodel_artifact.probe_real_shape:
the (B, S) ladder must return a perf row for the first shape that runs and
record the failure trail for shapes that don't — the OOM boundary is data
(VERDICT r3 #3), so the recording logic needs CI coverage without a chip."""
import importlib.util
import pathlib

import pytest

from vnsum_tpu.models import tiny_llama

_SCRIPT = (
    pathlib.Path(__file__).resolve().parent.parent
    / "scripts" / "make_multimodel_artifact.py"
)
spec = importlib.util.spec_from_file_location("make_multimodel", _SCRIPT)
mm = importlib.util.module_from_spec(spec)
spec.loader.exec_module(mm)


@pytest.mark.slow
def test_probe_real_shape_success_row():
    row = mm.probe_real_shape(
        "tiny", lambda **kw: tiny_llama(**kw), ladder=[(2, 256)], max_new=8
    )
    assert row["status"] == "success"
    assert row["B"] == 2 and row["S"] == 256 and row["layers"] == 2
    assert row["weight_bytes"] > 0
    # prefill_s is rounded to 2 decimals and can legitimately be 0.0 for a
    # tiny model on a fast host — assert presence, not magnitude
    assert row["decode_steps"] > 0 and row["prefill_s"] >= 0
    assert row["prefill_tokens_per_sec"] >= 0
    assert row["attempts"] == []


@pytest.mark.slow
def test_probe_real_shape_ladder_steps_down_and_records_failures():
    def factory(**kw):
        # max_seq_len = S + 2*max_new; the first ladder entry asks for a
        # sequence the config cannot hold -> constructor raises, the probe
        # must record it and step down
        cfg = tiny_llama(**kw)
        if cfg.max_seq_len > 300:
            raise RuntimeError("synthetic OOM for the big shape")
        return cfg

    row = mm.probe_real_shape(
        "tiny", factory, ladder=[(4, 1024), (2, 256)], max_new=8
    )
    assert row["status"] == "success" and row["B"] == 2
    assert len(row["attempts"]) == 1
    assert row["attempts"][0]["B"] == 4
    assert "synthetic OOM" in row["attempts"][0]["error"]


@pytest.mark.slow
def test_probe_real_shape_did_not_fit():
    def factory(**kw):
        raise RuntimeError("nothing fits")

    row = mm.probe_real_shape(
        "tiny", factory, ladder=[(2, 256), (1, 128)], max_new=8
    )
    assert row["status"] == "did_not_fit"
    assert len(row["attempts"]) == 2
