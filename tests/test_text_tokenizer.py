from vnsum_tpu.text import ByteTokenizer, get_tokenizer, whitespace_token_count


def test_byte_roundtrip_vietnamese():
    tok = ByteTokenizer()
    s = "Tóm tắt tài liệu tiếng Việt: đầy đủ dấu thanh — ắằẳẵặ."
    assert tok.decode(tok.encode(s)) == s


def test_bos_and_specials():
    tok = ByteTokenizer()
    ids = tok.encode("ab", add_bos=True)
    assert ids[0] == tok.bos_id
    assert tok.decode(ids) == "ab"
    assert tok.vocab_size % 128 == 0
    assert len({tok.bos_id, tok.eos_id, tok.pad_id}) == 3


def test_count_matches_encode():
    tok = ByteTokenizer()
    s = "xin chào việt nam"
    assert tok.count(s) == len(tok.encode(s))


def test_whitespace_count_is_reference_metric():
    assert whitespace_token_count("một  hai\nba") == 3
    assert whitespace_token_count("") == 0


def test_factory():
    assert get_tokenizer("byte").vocab_size == 384
    try:
        get_tokenizer("nope")
        raise AssertionError("expected ValueError")
    except ValueError:
        pass
