from vnsum_tpu.text import ByteTokenizer, get_tokenizer, whitespace_token_count


def test_byte_roundtrip_vietnamese():
    tok = ByteTokenizer()
    s = "Tóm tắt tài liệu tiếng Việt: đầy đủ dấu thanh — ắằẳẵặ."
    assert tok.decode(tok.encode(s)) == s


def test_bos_and_specials():
    tok = ByteTokenizer()
    ids = tok.encode("ab", add_bos=True)
    assert ids[0] == tok.bos_id
    assert tok.decode(ids) == "ab"
    assert tok.vocab_size % 128 == 0
    assert len({tok.bos_id, tok.eos_id, tok.pad_id}) == 3


def test_count_matches_encode():
    tok = ByteTokenizer()
    s = "xin chào việt nam"
    assert tok.count(s) == len(tok.encode(s))


def test_whitespace_count_is_reference_metric():
    assert whitespace_token_count("một  hai\nba") == 3
    assert whitespace_token_count("") == 0


def test_factory():
    assert get_tokenizer("byte").vocab_size == 384
    try:
        get_tokenizer("nope")
        raise AssertionError("expected ValueError")
    except ValueError:
        pass


def test_encode_batch_matches_encode():
    import pytest

    from vnsum_tpu.text.tokenizer import ByteTokenizer

    bt = ByteTokenizer()
    texts = ["xin chào", "tóm tắt văn bản", ""]
    assert bt.encode_batch(texts) == [bt.encode(t) for t in texts]
    assert bt.encode_batch(texts, add_bos=True) == [
        bt.encode(t, add_bos=True) for t in texts
    ]
    assert bt.count_batch(texts) == [bt.count(t) for t in texts]

    # HF fast tokenizer: batch call must agree with per-text calls
    tokenizers = pytest.importorskip("tokenizers")  # noqa: F841
    from vnsum_tpu.models.fixtures import train_bpe_tokenizer
    from vnsum_tpu.text.tokenizer import HFTokenizer
    import tempfile

    hf = train_bpe_tokenizer(["xin chào việt nam tóm tắt văn bản"] * 4,
                             vocab_size=384)
    d = tempfile.mkdtemp()
    hf.save_pretrained(d)
    tok = HFTokenizer(d)
    texts = ["xin chào", "tóm tắt văn bản dài hơn một chút", ""]
    assert tok.encode_batch(texts) == [tok.encode(t) for t in texts]
    assert tok.encode_batch(texts, add_bos=True) == [
        tok.encode(t, add_bos=True) for t in texts
    ]
    assert tok.count_batch(texts) == [tok.count(t) for t in texts]
