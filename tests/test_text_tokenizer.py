import pytest

from vnsum_tpu.text import ByteTokenizer, get_tokenizer, whitespace_token_count


def _shipped_templates():
    """Every prompt template the strategies format, with its header — the
    cache_hint surface prefix caching (vnsum_tpu.cache) depends on."""
    from vnsum_tpu.strategies import prompts as P

    return {
        name: (tpl, P.template_header(tpl))
        for name, tpl in vars(P).items()
        if name.isupper() and isinstance(tpl, str) and "{" in tpl
    }


def test_template_headers_are_string_prefixes():
    """The cache_hint each strategy passes must literally prefix the prompt
    it formats — template_header guarantees it by slicing before the first
    placeholder, but the templates themselves must not open with one."""
    templates = _shipped_templates()
    assert len(templates) >= 10  # all reference prompts present
    content = "Nội dung văn bản tiếng Việt có dấu thanh."
    fills = {
        "content": content, "docs": content, "summary": content,
        "original_chunks": content, "current_summary": content,
        "critique": content, "reference_content": content,
        "context": content, "existing_answer": content, "text": content,
        "point": content,
    }
    for name, (tpl, head) in templates.items():
        assert tpl.format(**{
            k: v for k, v in fills.items() if "{" + k + "}" in tpl
        }).startswith(head), name


@pytest.mark.parametrize("tok_kind", ["byte", "bpe"])
def test_template_tokenization_is_prefix_stable(tok_kind):
    """tokenize(header + content) must START WITH tokenize(header) for every
    shipped template — prefix caching is unsound otherwise (a cached header
    block would hold KV for token ids the real prompt doesn't contain).
    Checked for the default byte tokenizer (exact by construction: UTF-8
    bytes never merge) AND a trained HF BPE (merges could cross the
    boundary; the headers end at newline/colon boundaries precisely so they
    don't)."""
    templates = _shipped_templates()
    contents = [
        "Quốc hội đã thông qua nghị quyết về phát triển kinh tế xã hội.",
        "a",  # single ASCII char: the hardest boundary for BPE merges
        "\nxuống dòng trước nội dung",
    ]
    if tok_kind == "byte":
        tok = ByteTokenizer()
    else:
        pytest.importorskip("tokenizers")
        import tempfile

        from vnsum_tpu.models.fixtures import train_bpe_tokenizer
        from vnsum_tpu.text.tokenizer import HFTokenizer

        corpus = ["Bạn là một chuyên gia tóm tắt nội dung tiếng Việt."] * 4 + [
            t for t, _ in templates.values()
        ]
        hf = train_bpe_tokenizer(corpus, vocab_size=512)
        d = tempfile.mkdtemp()
        hf.save_pretrained(d)
        tok = HFTokenizer(d)
    for name, (_, head) in templates.items():
        if not head:
            continue
        head_ids = tok.encode(head, add_bos=True)
        for content in contents:
            full_ids = tok.encode(head + content, add_bos=True)
            assert full_ids[: len(head_ids)] == head_ids, (name, content)


def test_byte_roundtrip_vietnamese():
    tok = ByteTokenizer()
    s = "Tóm tắt tài liệu tiếng Việt: đầy đủ dấu thanh — ắằẳẵặ."
    assert tok.decode(tok.encode(s)) == s


def test_bos_and_specials():
    tok = ByteTokenizer()
    ids = tok.encode("ab", add_bos=True)
    assert ids[0] == tok.bos_id
    assert tok.decode(ids) == "ab"
    assert tok.vocab_size % 128 == 0
    assert len({tok.bos_id, tok.eos_id, tok.pad_id}) == 3


def test_count_matches_encode():
    tok = ByteTokenizer()
    s = "xin chào việt nam"
    assert tok.count(s) == len(tok.encode(s))


def test_whitespace_count_is_reference_metric():
    assert whitespace_token_count("một  hai\nba") == 3
    assert whitespace_token_count("") == 0


def test_factory():
    assert get_tokenizer("byte").vocab_size == 384
    try:
        get_tokenizer("nope")
        raise AssertionError("expected ValueError")
    except ValueError:
        pass


def test_encode_batch_matches_encode():
    import pytest

    from vnsum_tpu.text.tokenizer import ByteTokenizer

    bt = ByteTokenizer()
    texts = ["xin chào", "tóm tắt văn bản", ""]
    assert bt.encode_batch(texts) == [bt.encode(t) for t in texts]
    assert bt.encode_batch(texts, add_bos=True) == [
        bt.encode(t, add_bos=True) for t in texts
    ]
    assert bt.count_batch(texts) == [bt.count(t) for t in texts]

    # HF fast tokenizer: batch call must agree with per-text calls
    tokenizers = pytest.importorskip("tokenizers")  # noqa: F841
    from vnsum_tpu.models.fixtures import train_bpe_tokenizer
    from vnsum_tpu.text.tokenizer import HFTokenizer
    import tempfile

    hf = train_bpe_tokenizer(["xin chào việt nam tóm tắt văn bản"] * 4,
                             vocab_size=384)
    d = tempfile.mkdtemp()
    hf.save_pretrained(d)
    tok = HFTokenizer(d)
    texts = ["xin chào", "tóm tắt văn bản dài hơn một chút", ""]
    assert tok.encode_batch(texts) == [tok.encode(t) for t in texts]
    assert tok.encode_batch(texts, add_bos=True) == [
        tok.encode(t, add_bos=True) for t in texts
    ]
    assert tok.count_batch(texts) == [tok.count(t) for t in texts]
