import numpy as np
import pytest

from vnsum_tpu.backend import FakeBackend, get_backend
from vnsum_tpu.core.config import GenerationConfig
from vnsum_tpu.models import tiny_llama


@pytest.fixture(scope="module")
def engine():
    from vnsum_tpu.backend.engine import TpuBackend

    return TpuBackend(
        model_config=tiny_llama(max_seq_len=128),
        batch_size=4,
        max_new_tokens=8,
    )


def test_generate_returns_one_string_per_prompt(engine):
    outs = engine.generate(["xin chào", "tài liệu dài hơn một chút", "a"])
    assert len(outs) == 3
    assert all(isinstance(o, str) for o in outs)


def test_generate_deterministic(engine):
    a = engine.generate(["một văn bản"])
    b = engine.generate(["một văn bản"])
    assert a == b


def test_order_preserved_across_buckets(engine):
    # 5 prompts, batch_size 4 -> two batches, sorted by length internally
    prompts = ["aaaa " * 12, "b", "cc", "ddd " * 20, "e"]
    outs = engine.generate(prompts)
    # same prompts individually must give identical strings (order mapping ok)
    for p, o in zip(prompts, outs):
        assert engine.generate([p])[0] == o


def test_batch_padding_invariance(engine):
    """A prompt's output must not depend on its batch neighbors."""
    alone = engine.generate(["nội dung cần tóm tắt"])[0]
    together = engine.generate(
        ["nội dung cần tóm tắt", "một prompt khác dài hơn hẳn để đổi bucket " * 3]
    )[0]
    assert alone == together


def test_stats_accumulate(engine):
    before = engine.stats.prompts
    engine.generate(["x", "y"])
    assert engine.stats.prompts == before + 2
    assert engine.stats.generated_tokens > 0
    assert engine.stats.batches > 0


def test_empty_prompt_list(engine):
    assert engine.generate([]) == []


def test_truncates_overlong_prompt(engine):
    # max_seq_len 128, max_new 8 -> inputs capped at 120 tokens
    out = engine.generate(["z" * 1000], max_new_tokens=8)
    assert isinstance(out[0], str)
    assert engine.stats.prompt_tokens <= 10_000


def test_factory_and_fake():
    fb = get_backend("fake")
    assert isinstance(fb, FakeBackend)
    out = fb.generate(["Tóm tắt:\n<content>\nmột hai ba bốn năm\n</content>"])
    assert out == ["một hai ba bốn năm"]
    with pytest.raises(ValueError):
        get_backend("gpu")


def test_fake_scripted():
    fb = FakeBackend(responses=["r1", "r2"])
    assert fb.generate(["a"]) == ["r1"]
    assert fb.generate(["b"]) == ["r2"]
    with pytest.raises(RuntimeError):
        fb.generate(["c"])


def test_mesh_sharded_generation_matches_single_device():
    """TP+DP sharded engine must produce identical tokens to unsharded."""
    from vnsum_tpu.backend.engine import TpuBackend
    from vnsum_tpu.parallel import make_mesh

    cfg = tiny_llama(max_seq_len=128)
    plain = TpuBackend(model_config=cfg, batch_size=4, max_new_tokens=6, seed=3)
    mesh = make_mesh({"data": 2, "model": 2, "seq": 1}, platform="cpu")
    sharded = TpuBackend(
        model_config=cfg, batch_size=4, max_new_tokens=6, mesh=mesh, seed=3
    )
    prompts = ["văn bản một", "văn bản thứ hai dài hơn", "ba", "bốn bốn bốn"]
    np.testing.assert_array_equal(
        plain.generate(prompts), sharded.generate(prompts)
    )


def test_mesh_sharded_quantized_generation_matches_single_device():
    """int8 weights + TP/DP mesh: scales shard with their output channels, so
    sharded quantized decode must match unsharded quantized decode exactly."""
    from vnsum_tpu.backend.engine import TpuBackend
    from vnsum_tpu.parallel import make_mesh

    cfg = tiny_llama(max_seq_len=128)
    plain = TpuBackend(
        model_config=cfg, batch_size=4, max_new_tokens=6, seed=3, quantize=True
    )
    mesh = make_mesh({"data": 2, "model": 2, "seq": 1}, platform="cpu")
    sharded = TpuBackend(
        model_config=cfg,
        batch_size=4,
        max_new_tokens=6,
        mesh=mesh,
        seed=3,
        quantize=True,
    )
    prompts = ["văn bản một", "văn bản thứ hai dài hơn", "ba", "bốn bốn bốn"]
    np.testing.assert_array_equal(
        plain.generate(prompts), sharded.generate(prompts)
    )


def test_mesh_flash_quantized_continuous_matches_single_device():
    """The full fast path (Pallas prefill+decode kernels via shard_map, int8
    KV cache, continuous scheduling) must emit the same tokens under a
    (data, model) mesh as on a single device — the round-1 guards that
    locked the kernels out of meshes are gone (VERDICT r1 'what's weak' #2)."""
    from vnsum_tpu.backend.engine import TpuBackend
    from vnsum_tpu.parallel import make_mesh

    cfg = tiny_llama(max_seq_len=128)
    kw = dict(
        model_config=cfg, batch_size=4, max_new_tokens=6, seed=3,
        flash=True, quantize_kv=True, interpret=True, continuous=True,
        segment_tokens=2, min_batch=1,
    )
    plain = TpuBackend(**kw)
    mesh = make_mesh({"data": 2, "model": 2, "seq": 1}, platform="cpu")
    sharded = TpuBackend(mesh=mesh, **kw)
    prompts = ["văn bản một", "văn bản thứ hai dài hơn", "ba", "bốn bốn bốn"]
    np.testing.assert_array_equal(
        plain.generate(prompts), sharded.generate(prompts)
    )


def test_mesh_flash_oneshot_matches_single_device():
    """Same as above for the one-shot (non-continuous) program."""
    from vnsum_tpu.backend.engine import TpuBackend
    from vnsum_tpu.parallel import make_mesh

    cfg = tiny_llama(max_seq_len=128)
    kw = dict(
        model_config=cfg, batch_size=4, max_new_tokens=6, seed=3,
        flash=True, quantize_kv=True, interpret=True, continuous=False,
    )
    plain = TpuBackend(**kw)
    mesh = make_mesh({"data": 2, "model": 2, "seq": 1}, platform="cpu")
    sharded = TpuBackend(mesh=mesh, **kw)
    prompts = ["văn bản một", "văn bản thứ hai dài hơn", "ba", "bốn bốn bốn"]
    np.testing.assert_array_equal(
        plain.generate(prompts), sharded.generate(prompts)
    )


def test_mesh_continuous_compaction_fires_and_matches():
    """Tail compaction under a mesh: when most rows finish early the batch
    is halved (respecting data-axis divisibility) and outputs still match
    the single-device engine."""
    from vnsum_tpu.backend.engine import TpuBackend
    from vnsum_tpu.parallel import make_mesh

    cfg = tiny_llama(max_seq_len=128)
    kw = dict(
        model_config=cfg, batch_size=4, max_new_tokens=12, seed=3,
        flash=True, quantize_kv=True, interpret=True, continuous=True,
        segment_tokens=2, min_batch=1,
    )
    prompts = ["văn bản một", "văn bản thứ hai dài hơn", "ba", "bốn bốn bốn"]
    probe = TpuBackend(**kw)
    outs = probe.generate(prompts)
    firsts = {probe.tok.encode(o)[0] for o in outs if o}
    if len(firsts) < 2:
        pytest.skip("random model gives <2 distinct first tokens")
    # make all but one row stop at its first token -> compaction must fire
    eos_ids = tuple(sorted(firsts))[:-1]
    gen = GenerationConfig(temperature=0.0, eos_ids=eos_ids)

    plain = TpuBackend(**kw)
    mesh = make_mesh({"data": 2, "model": 2, "seq": 1}, platform="cpu")
    sharded = TpuBackend(mesh=mesh, **kw)
    a = plain.generate(prompts, max_new_tokens=12, config=gen)
    b = sharded.generate(prompts, max_new_tokens=12, config=gen)
    np.testing.assert_array_equal(a, b)
    assert sharded.stats.compactions > 0
    # divisibility: every post-compaction batch must still split over data=2
    assert sharded.stats.compacted_batch_sizes
    assert all(B % 2 == 0 for B in sharded.stats.compacted_batch_sizes)


def test_early_exit_matches_reference_rollout(engine):
    """The while_loop decode (early exit on all-EOS) must emit exactly what a
    token-by-token host rollout of the same greedy policy emits."""
    import jax
    import jax.numpy as jnp

    from vnsum_tpu.models import forward, init_kv_cache
    from vnsum_tpu.models.llama import (
        decode_attention_mask,
        prefill_attention_mask,
        prefill_positions,
    )

    cfg = engine.cfg
    tok = engine.tok
    prompt = "văn bản nguồn để tóm tắt"
    ids = tok.encode(prompt, add_bos=True)
    max_new = engine.max_new_tokens

    S = len(ids)
    C = S + max_new
    tokens = jnp.asarray([ids], jnp.int32)
    pad = jnp.zeros((1,), jnp.int32)
    cache = init_kv_cache(cfg, 1, C)
    logits, cache = forward(
        engine.params, cfg, tokens, prefill_positions(pad, S), cache, 0,
        prefill_attention_mask(pad, S, C), last_only=True,
    )
    cur = int(jnp.argmax(logits[0, -1]))
    emitted = []
    for t in range(max_new):
        if cur == tok.eos_id:
            break
        emitted.append(cur)
        mask_t = decode_attention_mask(pad, S + t, C)
        logits, cache = forward(
            engine.params, cfg, jnp.asarray([[cur]], jnp.int32),
            jnp.asarray([[S + t]], jnp.int32) - pad[:, None], cache, S + t,
            mask_t,
        )
        cur = int(jnp.argmax(logits[0, -1]))
    expected = tok.decode(emitted).strip()

    assert engine.generate([prompt])[0] == expected


def test_eos_early_exit_stops_output(engine):
    """Forcing EOS to the first greedily-chosen token stops decode right
    after it, and the custom stop token is STRIPPED from the decoded text
    like the native EOS (ADVICE r2: it is emitted into the raw buffer
    before the done check, but must never leak into the summary)."""
    prompt = "một đoạn văn"
    full = engine.generate([prompt])[0]
    if not full:
        pytest.skip("greedy output empty for this random model")
    first_id = engine.tok.encode(full)[0]
    out = engine.generate(
        [prompt],
        max_new_tokens=engine.max_new_tokens,
        config=GenerationConfig(temperature=0.0, eos_ids=(first_id,)),
    )[0]
    assert out == ""
    assert len(out) < len(full)


def test_custom_eos_mid_stream_is_stripped(engine):
    """A custom stop token hit mid-stream cuts the text there and does not
    itself appear in the output."""
    prompt = "một đoạn văn"
    full = engine.generate([prompt])[0]
    ids = engine.tok.encode(full, add_bos=False)
    if len(ids) < 3:
        pytest.skip("rollout too short for a mid-stream stop")
    stop = ids[2]
    out = engine.generate(
        [prompt],
        max_new_tokens=engine.max_new_tokens,
        config=GenerationConfig(temperature=0.0, eos_ids=(stop,)),
    )[0]
    expect = engine.tok.decode(ids[: ids.index(stop)]).strip()
    assert out == expect


def test_sampled_batches_draw_fresh_randomness():
    """VERDICT r1 #6: per-batch seeds derive from (config seed, engine seed,
    dispatch index) — repeated sampled calls must differ, while a same-seed
    rerun on a fresh engine replays bit-exactly."""
    from vnsum_tpu.backend.engine import TpuBackend

    def fresh():
        return TpuBackend(
            model_config=tiny_llama(max_seq_len=128),
            batch_size=4, max_new_tokens=16, seed=5, continuous=False,
        )

    gen = GenerationConfig(temperature=1.0, seed=11, max_new_tokens=16)
    a = fresh()
    first = a.generate(["một văn bản"], config=gen)
    second = a.generate(["một văn bản"], config=gen)
    assert first != second  # dispatch counter advanced -> new randomness

    b = fresh()
    assert b.generate(["một văn bản"], config=gen) == first
    assert b.generate(["một văn bản"], config=gen) == second

    # a different GenerationConfig.seed changes the stream (knob is honored)
    c = fresh()
    assert c.generate(["một văn bản"], config=gen.with_(seed=99)) != first


def test_instrument_mode_matches_oneshot_and_records_budget():
    """instrument=True must be observability-only: identical outputs to the
    one-shot program (same _make_parts bodies), with per-phase device times
    and per-dispatch {B, S, steps} records filled in."""
    from vnsum_tpu.backend.engine import TpuBackend

    cfg = tiny_llama(max_seq_len=128)
    kw = dict(model_config=cfg, batch_size=4, max_new_tokens=8, seed=3)
    plain = TpuBackend(**kw)
    inst = TpuBackend(instrument=True, **kw)
    prompts = ["văn bản một", "hai dài hơn một chút", "ba", "bốn"]
    assert plain.generate(prompts) == inst.generate(prompts)
    st = inst.stats
    assert st.phase_seconds.get("prefill", 0) > 0
    assert st.phase_seconds.get("decode", 0) > 0
    assert "tokenize_host" in st.phase_seconds
    assert st.compactions == 0  # instrument pins the batch
    (d,) = st.dispatches
    assert d["B"] == 4 and d["steps"] <= 8 and d["decode_s"] >= 0


def test_sampling_vocab_keeps_terminators_sampleable():
    """ADVICE r3 (medium): the decodable-vocab cap must not mask EOS. For
    ByteTokenizer (eos=257 above the 256 decodable bytes) the sampling limit
    extends to cover the terminators, with the text-invisible ids between
    blocked."""
    from vnsum_tpu.backend.base import sampling_vocab
    from vnsum_tpu.text.tokenizer import ByteTokenizer

    tok = ByteTokenizer()
    limit, allowed = sampling_vocab(tok, 384, (tok.eos_id,))
    assert limit == 258
    assert allowed is not None and allowed.shape == (258,)
    assert allowed[:256].all()      # raw bytes stay sampleable
    assert not allowed[256]         # BOS blocked (text-invisible)
    assert allowed[257]             # EOS sampleable

    # custom stop tokens extend the limit the same way
    limit2, allowed2 = sampling_vocab(tok, 384, (tok.eos_id, 300))
    assert limit2 == 301 and allowed2[300] and not allowed2[258:300].any()

    # HF-style tokenizer (decodable == head) needs no mask at all
    class Full:
        vocab_size = 512

    assert sampling_vocab(Full(), 512, (511,)) == (512, None)


def test_sampling_vocab_warns_on_unsampleable_terminator(caplog):
    """ADVICE r3 (low): a terminator at/above the model head can never fire —
    that must be loud, not a silent run-to-budget."""
    import logging

    from vnsum_tpu.backend.base import sampling_vocab
    from vnsum_tpu.text.tokenizer import ByteTokenizer

    from vnsum_tpu.backend import base as backend_base

    backend_base._warned_unsampleable.clear()
    # the vnsum root stops propagating once core.logging installs its own
    # handler (no double emission); caplog captures at the GLOBAL root, so
    # re-enable propagation for the capture window
    vroot = logging.getLogger("vnsum")
    old_propagate = vroot.propagate
    vroot.propagate = True
    try:
        with caplog.at_level(logging.WARNING, logger="vnsum.backend"):
            limit, allowed = sampling_vocab(ByteTokenizer(), 200, (257,))
            # per-bucket program rebuilds must not repeat the warning
            sampling_vocab(ByteTokenizer(), 200, (257,))
    finally:
        vroot.propagate = old_propagate
    assert caplog.text.count("terminator ids [257]") == 1
    assert limit == 200 and allowed is None  # decodable clamps to the head


def test_native_eos_terminates_sampled_decode():
    """A ByteTokenizer model CAN now stop early on its native EOS: over a
    sampled batch with a real budget, at least one row must draw EOS=257 and
    terminate before max_new (pre-fix this was impossible — eos sat above
    the decodable cap and every row always burned the full budget)."""
    from vnsum_tpu.backend.engine import TpuBackend

    be = TpuBackend(
        model_config=tiny_llama(max_seq_len=256), tokenizer="byte",
        batch_size=8, max_new_tokens=128, seed=0, continuous=False,
    )
    # near-uniform random-init logits give p(EOS) ~ 1/258 per draw; over
    # 16 rows x 128 steps the no-early-stop probability is ~3e-4, and the
    # pinned seeds make each run deterministic besides
    prompts = [f"văn bản số {i}" for i in range(8)]
    stopped_short = False
    for seed in (3, 4):
        before = be.stats.generated_tokens
        outs = be.generate(
            prompts, config=GenerationConfig(temperature=1.0, seed=seed)
        )
        assert len(outs) == 8
        stopped_short |= (be.stats.generated_tokens - before) < 8 * 128
    assert stopped_short


def test_sampling_restricted_to_tokenizer_vocab():
    """A model head larger than the tokenizer vocab must never emit ids the
    tokenizer cannot decode (they would vanish at detok, yielding empty
    summaries — round-3 bench regression). Checked on the RAW id stream of
    the compiled program: every sampled id must be a raw byte, a terminator,
    or pad — never BOS or the [258, 2048) filler range. (EOS itself became
    sampleable in the ADVICE-r3 fix, so string-length heuristics no longer
    prove anything: a row may legitimately stop at any step.)"""
    from vnsum_tpu.backend.engine import TpuBackend

    cfg = tiny_llama(vocab_size=2048)  # model vocab >> byte-tokenizer vocab
    be = TpuBackend(
        model_config=cfg, tokenizer="byte", batch_size=2, max_new_tokens=16,
        seed=0, continuous=False,
    )
    gen = GenerationConfig(temperature=1.0, seed=9)
    encoded = [be.tok.encode(p, add_bos=True) for p in ["văn bản", "hai"]]
    tokens, pads, B, S = be._pack_group([0, 1], encoded, 16)
    fn = be._get_fn(B, S, 16, gen)
    out = np.asarray(fn(be.params, tokens, pads, 123))
    sampleable = set(range(256)) | {be.tok.eos_id, be.tok.pad_id}
    assert set(np.unique(out).tolist()) <= sampleable, np.unique(out)


def test_score_choices_matches_forward_oracle(engine):
    """score_choices must pick the same digit an independent forward pass
    ranks highest among the choice ids (the constrained G-Eval judge's
    correctness contract)."""
    import jax.numpy as jnp

    from vnsum_tpu.models.llama import (
        forward,
        init_kv_cache,
        prefill_attention_mask,
        prefill_positions,
    )

    prompt = 'đánh giá bản tóm tắt này.\n{"score": '
    choices = ["1", "2", "3", "4", "5"]
    picked = engine.score_choices([prompt], choices)
    assert len(picked) == 1 and 0 <= picked[0] < 5

    ids = engine.tok.encode(prompt, add_bos=True)
    S = len(ids)
    cfg = engine.cfg
    tokens = jnp.asarray([ids], dtype=jnp.int32)
    pads = jnp.zeros((1,), dtype=jnp.int32)
    cache = init_kv_cache(cfg, 1, S)
    logits, _ = forward(
        engine.params, cfg, tokens, prefill_positions(pads, S), cache, 0,
        prefill_attention_mask(pads, S, S), last_only=True,
    )
    choice_ids = [engine.tok.encode(c)[0] for c in choices]
    oracle = int(np.argmax(np.asarray(logits)[0, -1, choice_ids]))
    assert picked[0] == oracle


def test_score_choices_batch_invariance(engine):
    """A prompt's chosen index must not depend on its batch neighbors or
    bucket (mirrors test_batch_padding_invariance for the choice path)."""
    prompts = [
        'tóm tắt A.\n{"score": ',
        'một bản tóm tắt dài hơn hẳn để đổi bucket ' * 3 + '\n{"score": ',
        'B\n{"score": ',
    ]
    choices = ["1", "2", "3", "4", "5"]
    together = engine.score_choices(prompts, choices)
    alone = [engine.score_choices([p], choices)[0] for p in prompts]
    assert together == alone


def test_score_choices_rejects_bad_choices(engine):
    with pytest.raises(ValueError):
        engine.score_choices(["x"], ["1", "1"])  # same first token
    with pytest.raises(ValueError):
        engine.score_choices(["x"], ["ok", ""])  # empty choice


def test_constrained_judge_scores_every_case(engine):
    """LLMJudge(constrained=True) over the engine must parse a real score
    for EVERY case — the engine-as-judge path that free decode could not
    deliver on an untrained model (VERDICT r4 missing #4)."""
    from vnsum_tpu.eval.geval import LLMJudge

    judge = LLMJudge(backend=engine, constrained=True)
    generated = {"a.txt": "tóm tắt một", "b.txt": "tóm tắt hai"}
    references = {"a.txt": "tham chiếu một", "b.txt": "tham chiếu hai"}
    stats = judge.evaluate(generated, references)
    assert stats["llm_successful_cases"] == 2
    assert stats["llm_failed_cases"] == 0
    assert 0.0 <= stats["llm_correctness_mean"] <= 1.0
    assert 0.0 <= stats["llm_coherence_mean"] <= 1.0


def test_constrained_judge_requires_capable_backend():
    from vnsum_tpu.backend.fake import FakeBackend
    from vnsum_tpu.eval.geval import LLMJudge

    with pytest.raises(ValueError):
        LLMJudge(backend=FakeBackend(), constrained=True)


def test_chunked_prefill_matches_whole_prompt():
    """prefill_chunk_tokens must not change ANY output: same cache state,
    same first token, same greedy continuation — on both the dense and the
    (interpret-mode) kernel path. This is the correctness gate for the
    B=16 memory headroom the chunking exists to buy."""
    from vnsum_tpu.backend.engine import TpuBackend

    cfg = tiny_llama(max_seq_len=256)
    prompts = [
        "văn bản một " * 14,
        "hai " * 3,
        "một tài liệu dài hơn hẳn những cái khác " * 4,
    ]
    outs = {}
    for tag, kw in {
        "whole": dict(),
        "chunked": dict(prefill_chunk_tokens=128),
        "chunked_flash": dict(
            prefill_chunk_tokens=128, flash=True, interpret=True
        ),
        "whole_flash": dict(flash=True, interpret=True),
    }.items():
        be = TpuBackend(
            model_config=cfg, batch_size=4, max_new_tokens=12, **kw
        )
        outs[tag] = be.generate(prompts)
    assert outs["chunked"] == outs["whole"]
    assert outs["chunked_flash"] == outs["whole_flash"]


def test_chunked_prefill_rejects_bad_multiple():
    from vnsum_tpu.backend.engine import TpuBackend

    with pytest.raises(ValueError):
        TpuBackend(model_config=tiny_llama(), prefill_chunk_tokens=100)
