"""BERT-family encoder conversion: embedding parity against transformers.

The correctness anchor for real-encoder metrics (VERDICT r1 #4): a tiny
random HF BertModel is converted and both models must produce near-identical
token embeddings; a saved checkpoint round-trips through safetensors and
EmbeddingModel.from_hf must reproduce sentence-transformers-style mean-pooled
embeddings (reference encoders: evaluate/evaluate_summaries_semantic.py:
128-133, :577-582).
"""
from __future__ import annotations

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax.numpy as jnp

from vnsum_tpu.models.convert_encoder import (
    convert_torch_encoder,
    encoder_config_from_hf,
    load_hf_encoder,
)
from vnsum_tpu.models.encoder import encode, mean_pool

HF_CFG = dict(
    vocab_size=512,
    hidden_size=64,
    num_hidden_layers=2,
    num_attention_heads=4,
    intermediate_size=128,
    max_position_embeddings=128,
    layer_norm_eps=1e-12,
)

CORPUS = [
    "Nền kinh tế Việt Nam tăng trưởng nhanh trong quý một.",
    "Chính phủ ban hành nghị định mới về thuế thu nhập.",
    "Người dân thành phố Hồ Chí Minh đón lễ hội lớn.",
    "Các doanh nghiệp xuất khẩu gạo đạt kỷ lục mới.",
] * 4


@pytest.fixture(scope="module")
def hf_model():
    torch.manual_seed(0)
    cfg = transformers.BertConfig(**{
        "vocab_size": HF_CFG["vocab_size"],
        "hidden_size": HF_CFG["hidden_size"],
        "num_hidden_layers": HF_CFG["num_hidden_layers"],
        "num_attention_heads": HF_CFG["num_attention_heads"],
        "intermediate_size": HF_CFG["intermediate_size"],
        "max_position_embeddings": HF_CFG["max_position_embeddings"],
    })
    return transformers.BertModel(cfg).eval()


@pytest.fixture(scope="module")
def converted(hf_model):
    cfg = encoder_config_from_hf(HF_CFG)
    params = convert_torch_encoder(hf_model, cfg)
    return cfg, params


def _token_batch(seed=0, B=3, S=12, vocab=512):
    rng = np.random.default_rng(seed)
    toks = rng.integers(5, vocab, size=(B, S)).astype(np.int32)
    mask = np.ones((B, S), dtype=bool)
    mask[1, 8:] = False  # ragged lengths exercise the attention mask
    mask[2, 5:] = False
    toks[~mask] = 0
    return toks, mask


def test_token_embedding_parity(hf_model, converted):
    cfg, params = converted
    toks, mask = _token_batch()
    ours = np.asarray(encode(params, cfg, jnp.asarray(toks), jnp.asarray(mask)))
    with torch.no_grad():
        theirs = hf_model(
            input_ids=torch.tensor(toks, dtype=torch.long),
            attention_mask=torch.tensor(mask, dtype=torch.long),
        ).last_hidden_state.numpy()
    # only compare unmasked positions (padded positions are garbage-in on
    # both sides but attend differently)
    np.testing.assert_allclose(ours[mask], theirs[mask], atol=2e-5)


def test_segment_embedding_folded(hf_model, converted):
    """token_type_embeddings[0] must be folded into the word table."""
    cfg, params = converted
    folded = np.asarray(params["tok_embed"][7])
    sd = hf_model.state_dict()
    expect = (
        sd["embeddings.word_embeddings.weight"][7]
        + sd["embeddings.token_type_embeddings.weight"][0]
    ).numpy()
    np.testing.assert_allclose(folded, expect, atol=1e-6)


def test_checkpoint_roundtrip_and_sentence_parity(tmp_path):
    """save_pretrained → load_hf_encoder → EmbeddingModel.from_hf must equal
    torch BertModel + attention-mask mean pooling (the sentence-transformers
    recipe) on real tokenized Vietnamese text."""
    from vnsum_tpu.eval.embedding import EmbeddingModel
    from vnsum_tpu.models.fixtures import make_tiny_hf_encoder_checkpoint

    ckpt = tmp_path / "tiny_bert"
    make_tiny_hf_encoder_checkpoint(ckpt, CORPUS, vocab_size=512)

    model = EmbeddingModel.from_hf(str(ckpt), batch_size=4)
    texts = CORPUS[:3] + ["một câu hoàn toàn mới về thời tiết"]
    ours = model.sentence_embeddings(texts)

    hf_tok = transformers.AutoTokenizer.from_pretrained(str(ckpt))
    hf_model = transformers.AutoModel.from_pretrained(str(ckpt)).eval()
    enc = hf_tok(texts, padding=True, return_tensors="pt")
    with torch.no_grad():
        out = hf_model(**enc).last_hidden_state
    m = enc["attention_mask"].unsqueeze(-1).float()
    pooled = (out * m).sum(1) / m.sum(1).clamp(min=1.0)
    theirs = torch.nn.functional.normalize(pooled, dim=-1).numpy()

    np.testing.assert_allclose(ours, theirs, atol=3e-5)
    # embeddings are discriminative: self-sim > cross-sim
    sims = ours @ ours.T
    assert sims[0, 0] > sims[0, 3]


def test_load_hf_encoder_config(tmp_path):
    from vnsum_tpu.models.fixtures import make_tiny_hf_encoder_checkpoint

    ckpt = tmp_path / "tiny_bert"
    info = make_tiny_hf_encoder_checkpoint(ckpt, CORPUS, vocab_size=512)
    cfg, params = load_hf_encoder(str(ckpt))
    assert cfg.vocab_size == info["vocab_size"]
    assert params["layers"]["wq"].shape == (2, 64, 64)


def test_pipeline_embedding_dir_end_to_end(tmp_path):
    """--embedding-dir chain: pipeline eval runs with a converted real-format
    BERT checkpoint instead of random init."""
    from vnsum_tpu.core.config import PipelineConfig
    from vnsum_tpu.data.synthesize import synthesize_corpus
    from vnsum_tpu.models.fixtures import make_tiny_hf_encoder_checkpoint
    from vnsum_tpu.pipeline.cli import build_parser, config_from_args
    from vnsum_tpu.pipeline.runner import PipelineRunner

    synthesize_corpus(
        tmp_path / "corpus", n_docs=3, tokens_per_doc=200, summary_tokens=30,
        seed=2,
    )
    docs = [
        p.read_text(encoding="utf-8")
        for p in sorted((tmp_path / "corpus/doc").glob("*.txt"))
    ]
    make_tiny_hf_encoder_checkpoint(tmp_path / "bert", docs, vocab_size=512)

    args = build_parser().parse_args([
        "--backend", "fake",
        "--embedding-dir", str(tmp_path / "bert"),
        "--docs-dir", str(tmp_path / "corpus/doc"),
        "--summary-dir", str(tmp_path / "corpus/summary"),
        "--generated-summaries-dir", str(tmp_path / "gen"),
        "--results-dir", str(tmp_path / "results"),
        "--chunk-size", "100",
        "--max-new-tokens", "16",
    ])
    cfg = config_from_args(args)
    assert cfg.evaluation.embedding_dir == str(tmp_path / "bert")
    cfg.logs_dir = str(tmp_path / "logs")
    results = PipelineRunner(cfg).run()
    ev = results.evaluation["llama3.2:3b"]
    assert 0.0 <= ev["bert_scores"]["bert_f1"] <= 1.0
    assert -1.0 <= ev["semantic_similarity"]["mean"] <= 1.0
