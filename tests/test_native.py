import random

import pytest

from vnsum_tpu import native
from vnsum_tpu.eval.rouge import PorterStemmer, RougeScorer
from vnsum_tpu.text.splitter import RecursiveTokenSplitter

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library not built"
)


def test_stemmer_matches_python():
    py = PorterStemmer()
    rnd = random.Random(11)
    words = [
        "caresses", "ponies", "ties", "dying", "controlling", "happiness",
        "summarization", "geologi", "beautifulli", "rate", "cease",
    ] + [
        "".join(rnd.choices("abcdefgilmnoprstuyz", k=rnd.randint(3, 12)))
        for _ in range(2000)
    ]
    bad = [w for w in words if native.porter_stem_native(w) != py.stem(w)]
    assert not bad, bad[:10]


def test_rouge_matches_python_fuzz():
    py = RougeScorer(["rouge1", "rouge2", "rougeL"], use_native=False)
    cpp = RougeScorer(["rouge1", "rouge2", "rougeL"], use_native=True)
    rnd = random.Random(5)
    vocab = [
        "tóm", "tắt", "kinh", "tế", "summary", "nation", "running", "2024",
        "điểm", "học", "flies", "meeting", "quốc", "hội",
    ]
    cases = [("", ""), ("a", ""), ("", "b"), ("giống nhau", "giống nhau")]
    for _ in range(60):
        t = " ".join(rnd.choices(vocab, k=rnd.randint(0, 40)))
        p = " ".join(rnd.choices(vocab, k=rnd.randint(0, 40)))
        cases.append((t, p))
    for t, p in cases:
        a, b = py.score(t, p), cpp.score(t, p)
        for key in ("rouge1", "rouge2", "rougeL"):
            assert a[key].precision == pytest.approx(b[key].precision, abs=1e-12), (t, p, key)
            assert a[key].recall == pytest.approx(b[key].recall, abs=1e-12)
            assert a[key].fmeasure == pytest.approx(b[key].fmeasure, abs=1e-12)


def test_rouge_corpus_batch():
    targets = ["một hai ba", "bốn năm"]
    preds = ["một hai", "bốn năm sáu"]
    batch = native.rouge_corpus_native(targets, preds)
    for (t, p), res in zip(zip(targets, preds), batch):
        single = native.rouge_score_native(t, p)
        assert res == single
    with pytest.raises(ValueError):
        native.rouge_corpus_native(["a"], ["b", "c"])


def test_count_words_matches_python():
    samples = ["", "một", "một  hai\nba\tbốn", "  lead trail  ", "x " * 50]
    for s in samples:
        assert native.count_words(s) == len(s.split()), repr(s)


def test_split_matches_python_splitter():
    rnd = random.Random(3)
    sents = [
        "Quốc hội thông qua nghị quyết",
        "Chính phủ đẩy mạnh đầu tư",
        "Người dân được hỗ trợ",
    ]
    for _ in range(10):
        paras = []
        for _ in range(rnd.randint(1, 12)):
            paras.append(
                ". ".join(rnd.choice(sents) for _ in range(rnd.randint(1, 6)))
                + "."
            )
        text = "\n\n".join(paras)
        for chunk, ov in [(80, 0), (120, 20), (50, 10)]:
            py = RecursiveTokenSplitter(
                chunk, ov, length_function=lambda s: len(s.encode("utf-8"))
            ).split_text(text)
            cpp = native.split_text_bytes(text, chunk, ov)
            assert cpp == py, (chunk, ov, text[:60])


def test_split_empty_and_oversized():
    assert native.split_text_bytes("", 100, 0) == []
    # an unbreakable run falls through to char splitting
    out = native.split_text_bytes("x" * 300, 50, 0)
    assert all(len(c.encode()) <= 50 for c in out)
    assert "".join(out) == "x" * 300


def test_split_oversized_multibyte_run_respects_codepoints():
    text = "ă" * 200  # 2-byte codepoints, no separators
    out = native.split_text_bytes(text, 51, 0)
    assert "".join(out) == text  # decodable => no mid-codepoint cuts
    py = RecursiveTokenSplitter(
        51, 0, length_function=lambda s: len(s.encode("utf-8"))
    ).split_text(text)
    assert out == py


def test_split_heavy_overlap_retries_buffer():
    text = ". ".join(f"câu {i} dài" for i in range(400))
    out = native.split_text_bytes(text, 100, 80)
    py = RecursiveTokenSplitter(
        100, 80, length_function=lambda s: len(s.encode("utf-8"))
    ).split_text(text)
    assert out == py


def test_nul_handling():
    with pytest.raises(ValueError):
        native.split_text_bytes("a\x00b", 10, 0)
    # RougeScorer transparently falls back to Python for NUL pairs
    sc = RougeScorer(["rouge1"], use_native=True)
    py = RougeScorer(["rouge1"], use_native=False)
    t, p = "a b c", "a\x00b c"
    assert sc.score(t, p)["rouge1"] == py.score(t, p)["rouge1"]


def test_stemmer_wrapper_parity_on_case_and_unicode():
    py = PorterStemmer()
    assert native.porter_stem_native("Running") == py.stem("Running")
    assert native.porter_stem_native("việc") == py.stem("việc")
