"""Multi-host runtime helpers (single-process semantics; the multi-slice
branches are exercised up to their guard rails — real DCN needs real pods)."""
import jax
import pytest

from vnsum_tpu.parallel import (
    barrier,
    init_distributed,
    is_primary,
    make_hybrid_mesh,
    process_count,
)


def test_init_distributed_local_noop(monkeypatch):
    for var in ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES",
                "TPU_WORKER_HOSTNAMES", "MEGASCALE_COORDINATOR_ADDRESS",
                "SLURM_JOB_NUM_NODES", "OMPI_COMM_WORLD_SIZE"):
        monkeypatch.delenv(var, raising=False)
    assert init_distributed() is False  # local mode, nothing wired


def test_init_distributed_autodetect_fails_soft(monkeypatch):
    """A cluster-looking env with an already-up backend must degrade to
    local mode, not crash (explicit config would propagate instead)."""
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("JAX_NUM_PROCESSES", raising=False)
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "host1,host2")
    assert init_distributed() is False


def test_cluster_env_detection(monkeypatch):
    from vnsum_tpu.parallel.distributed import _cluster_env_detected

    for var in ("TPU_WORKER_HOSTNAMES", "MEGASCALE_COORDINATOR_ADDRESS",
                "SLURM_JOB_NUM_NODES", "OMPI_COMM_WORLD_SIZE"):
        monkeypatch.delenv(var, raising=False)
    assert _cluster_env_detected() is False
    monkeypatch.setenv("SLURM_JOB_NUM_NODES", "1")
    assert _cluster_env_detected() is False  # one node != a cluster
    monkeypatch.setenv("SLURM_JOB_NUM_NODES", "4")
    assert _cluster_env_detected() is True
    monkeypatch.delenv("SLURM_JOB_NUM_NODES")
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "h1,h2")
    assert _cluster_env_detected() is True


def test_primary_and_count_single_process():
    assert process_count() == 1
    assert is_primary() is True
    barrier("test")  # must be a no-op, not hang


def test_hybrid_mesh_falls_back_to_single_slice():
    mesh = make_hybrid_mesh(
        ici={"data": 2, "model": 2, "seq": 2}, dcn={}, platform="cpu"
    )
    assert dict(zip(mesh.axis_names, mesh.devices.shape)) == {
        "data": 2, "model": 2, "seq": 2,
    }


def test_hybrid_mesh_rejects_unknown_axis():
    with pytest.raises(ValueError, match="unknown mesh axes"):
        make_hybrid_mesh(ici={"expert": 2})


def test_hybrid_mesh_requires_processes_for_dcn():
    with pytest.raises(ValueError, match="slices over DCN"):
        make_hybrid_mesh(ici={"model": 2}, dcn={"data": 4}, platform="cpu")


def test_hybrid_mesh_sharded_computation_runs():
    """A jit over the fallback hybrid mesh must execute (GSPMD path)."""
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = make_hybrid_mesh(ici={"data": 4, "model": 2}, platform="cpu")
    x = jnp.arange(32, dtype=jnp.float32).reshape(8, 4)
    y = jax.device_put(x, NamedSharding(mesh, P("data", None)))
    out = jax.jit(lambda a: (a * 2).sum())(y)
    assert float(out) == float(x.sum() * 2)
