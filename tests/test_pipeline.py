import json
from pathlib import Path

import pytest

from vnsum_tpu.core import PipelineConfig
from vnsum_tpu.eval import EmbeddingModel
from vnsum_tpu.models.encoder import tiny_encoder
from vnsum_tpu.pipeline.cli import build_parser, config_from_args
from vnsum_tpu.pipeline.runner import PipelineRunner, model_name_safe


@pytest.fixture()
def workspace(tmp_path):
    docs = tmp_path / "doc"
    refs = tmp_path / "summary"
    docs.mkdir()
    refs.mkdir()
    for i in range(3):
        (docs / f"d{i}.txt").write_text(
            "\n\n".join(f"đoạn {i}-{p} " + "nội dung " * 20 for p in range(6)),
            encoding="utf-8",
        )
        (refs / f"d{i}.txt").write_text(f"tóm tắt tham chiếu {i}", encoding="utf-8")
    return tmp_path


def make_config(ws, **kw):
    base = dict(
        approach="mapreduce",
        models=["fake-model"],
        backend="fake",
        docs_dir=str(ws / "doc"),
        summary_dir=str(ws / "summary"),
        generated_summaries_dir=str(ws / "generated_summaries"),
        results_dir=str(ws / "evaluation_results"),
        logs_dir=str(ws / "logs"),
        chunk_size=50,
        chunk_overlap=5,
        token_max=60,
        batch_size=4,
    )
    base.update(kw)
    return PipelineConfig(**base)


def small_embedder():
    return EmbeddingModel(config=tiny_encoder(), max_len=64, batch_size=4)


def test_full_pipeline_fake_backend(workspace):
    cfg = make_config(workspace)
    runner = PipelineRunner(cfg, embedding_model=small_embedder())
    results = runner.run()

    out_dir = Path(f"{cfg.generated_summaries_dir}_mapreduce_fake-model")
    assert sorted(p.name for p in out_dir.glob("*.txt")) == [
        "d0.txt", "d1.txt", "d2.txt",
    ]
    rec = results.summarization["fake-model"]
    assert rec["successful"] == 3 and rec["failed"] == 0
    assert rec["total_chunks"] > 3
    ev = results.evaluation["fake-model"]
    assert "rouge_scores" in ev
    # persisted artifacts
    saved = list(Path(cfg.results_dir).glob("pipeline_results_*.json"))
    assert len(saved) == 1
    per_model = Path(cfg.results_dir) / "fake-model_results.json"
    assert per_model.exists()
    data = json.loads(per_model.read_text())
    assert len(data["detailed_results"]) == 3
    # report must not crash and must include metrics
    assert "rouge1/2/L" in runner.report()


def test_resume_skips_existing(workspace):
    cfg = make_config(workspace)
    out_dir = Path(f"{cfg.generated_summaries_dir}_mapreduce_fake-model")
    out_dir.mkdir(parents=True)
    (out_dir / "d0.txt").write_text("đã có sẵn", encoding="utf-8")

    runner = PipelineRunner(cfg, embedding_model=small_embedder())
    rec = runner.run_summarization_for_model("fake-model")
    assert rec.total_documents == 2  # d0 skipped
    assert (out_dir / "d0.txt").read_text(encoding="utf-8") == "đã có sẵn"


def test_docs_without_reference_are_skipped(workspace):
    (workspace / "doc" / "orphan.txt").write_text("no ref", encoding="utf-8")
    cfg = make_config(workspace)
    runner = PipelineRunner(cfg, embedding_model=small_embedder())
    rec = runner.run_summarization_for_model("fake-model")
    assert rec.total_documents == 3


def test_failed_model_is_contained(workspace):
    cfg = make_config(workspace, models=["boom", "fake-model"])

    calls = {"n": 0}

    def factory(model):
        from vnsum_tpu.backend import FakeBackend

        if model == "boom":
            raise RuntimeError("backend construction exploded")
        return FakeBackend(summary_words=10)

    runner = PipelineRunner(cfg, backend_factory=factory, embedding_model=small_embedder())
    results = runner.run()
    assert results.summarization["boom"]["status"] == "failed"
    assert results.summarization["fake-model"]["successful"] == 3


def test_max_samples(workspace):
    cfg = make_config(workspace, max_samples=1)
    runner = PipelineRunner(cfg, embedding_model=small_embedder())
    rec = runner.run_summarization_for_model("fake-model")
    assert rec.total_documents == 1


def test_hierarchical_with_tree_json(workspace):
    tree = {
        "d0.txt": {
            "type": "Document",
            "text": "Tài liệu 0",
            "children": [
                {
                    "type": "Header",
                    "text": "Chương",
                    "children": [{"type": "Paragraph", "text": "nội dung " * 30}],
                }
            ],
        }
    }
    tree_path = workspace / "tree.json"
    tree_path.write_text(json.dumps(tree, ensure_ascii=False), encoding="utf-8")
    cfg = make_config(
        workspace, approach="mapreduce_hierarchical", tree_json_path=str(tree_path)
    )
    runner = PipelineRunner(cfg, embedding_model=small_embedder())
    rec = runner.run_summarization_for_model("fake-model")
    # d0 via tree, d1/d2 via plain-text fallback
    assert rec.successful == 3


def test_all_approaches_run(workspace):
    for approach in (
        "mapreduce", "mapreduce_critique", "iterative", "truncated",
        "mapreduce_hierarchical",
    ):
        cfg = make_config(workspace, approach=approach)
        runner = PipelineRunner(cfg, embedding_model=small_embedder())
        rec = runner.run_summarization_for_model("fake-model")
        assert rec.successful == 3, approach


def test_model_name_safe():
    assert model_name_safe("llama3.2:3b") == "llama3_2_3b"


def test_cli_config():
    args = build_parser().parse_args(
        [
            "--approach", "mapreduce_critique", "--backend", "fake",
            "--models", "m1", "m2", "--mesh", "data=2,model=4",
            "--max-samples", "5",
        ]
    )
    cfg = config_from_args(args)
    assert cfg.approach == "mapreduce_critique"
    assert cfg.max_new_tokens == 2048  # critique override
    assert cfg.mesh_shape == {"data": 2, "model": 4}
    assert cfg.models == ["m1", "m2"]
    assert cfg.max_samples == 5


def test_utils_tools(tmp_path):
    from vnsum_tpu.utils.calculate_tokens import process_folder
    from vnsum_tpu.utils.clean_summaries import clean_summaries

    d = tmp_path / "sums"
    d.mkdir()
    (d / "a.txt").write_text("<think>bí mật</think>tóm tắt", encoding="utf-8")
    (d / "b.txt").write_text("sạch sẵn", encoding="utf-8")

    stats = process_folder(d)
    assert stats["summary"]["total_files"] == 2
    assert stats["files"]["b.txt"]["words"] == 2

    out = clean_summaries(d, preview=True)
    assert out["changed"] == ["a.txt"]
    assert "<think>" in (d / "a.txt").read_text(encoding="utf-8")  # preview untouched

    clean_summaries(d)
    assert (d / "a.txt").read_text(encoding="utf-8") == "tóm tắt"


def test_cli_long_context_and_quantize_flags():
    args = build_parser().parse_args([
        "--approach", "truncated", "--backend", "tpu",
        "--long-context", "--quantize",
        "--mesh", "data=2,seq=4",
        "--max-context", "65536",
    ])
    cfg = config_from_args(args)
    assert cfg.long_context and cfg.quantize
    assert cfg.max_context == 65536
    assert cfg.mesh_shape == {"data": 2, "seq": 4}
