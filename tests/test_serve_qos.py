"""Multi-tenant QoS (serve/qos.py): WFQ math in isolation, typed quota
sheds with Retry-After, the single-tenant FIFO fall-through, priority-tier
preemption with journal lifecycle + prefix-pin hygiene, and the
GET /v1/requests/<id> state aggregation."""
from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from vnsum_tpu.backend.fake import FakeBackend
from vnsum_tpu.serve import (
    InflightScheduler,
    RequestQueue,
    RequestShed,
    ServeRequest,
    ShedReason,
    TenantSpec,
    TenantTable,
    TokenBucket,
    parse_tenant_specs,
)
from vnsum_tpu.serve.qos import _NAME_RE
from vnsum_tpu.serve.server import ServeState, make_server


def make_table(spec="interactive:4:0,batch:1:0:batch", **kw):
    return TenantTable(parse_tenant_specs(spec), **kw)


def req(prompt, tenant="", tier="interactive", tokens=10, **kw):
    return ServeRequest(prompt=prompt, tenant=tenant, tier=tier,
                        est_tokens=tokens, **kw)


# -- spec parsing ------------------------------------------------------------


def test_parse_tenant_specs_full_form():
    specs = parse_tenant_specs("fast:8:1000,slow:1:50:batch")
    assert specs["fast"].weight == 8 and specs["fast"].tier == "interactive"
    assert specs["slow"].token_rate == 50 and specs["slow"].tier == "batch"


def test_zero_weight_is_rejected():
    with pytest.raises(ValueError, match="weight"):
        parse_tenant_specs("muted:0:100")
    with pytest.raises(ValueError, match="weight"):
        TenantSpec("neg", weight=-1)


def test_parse_rejects_duplicates_bad_tier_and_empty():
    with pytest.raises(ValueError, match="duplicate"):
        parse_tenant_specs("a:1:0,a:2:0")
    with pytest.raises(ValueError, match="tier"):
        parse_tenant_specs("a:1:0:turbo")
    with pytest.raises(ValueError):
        parse_tenant_specs("   ")


# -- token bucket ------------------------------------------------------------


def test_token_bucket_burst_then_rate():
    b = TokenBucket(rate=100.0, burst=50.0)
    t0 = 1000.0
    assert b.take(50, t0) is None           # full burst spends at once
    retry = b.take(10, t0)                  # bucket dry: typed refusal
    assert retry == pytest.approx(0.1)      # 10 tokens / 100 per s
    assert b.take(10, t0 + 0.1) is None     # refilled exactly that much
    # refill never exceeds burst
    assert b.take(50, t0 + 1000.0) is None
    assert b.take(1, t0 + 1000.0) == pytest.approx(0.01)


def test_token_bucket_oversized_request_is_billed_the_burst():
    # a request larger than the whole burst must not be refused forever
    b = TokenBucket(rate=10.0, burst=20.0)
    assert b.take(10_000, 0.0) is None      # drains the bucket, admitted
    assert b.take(1, 0.0) == pytest.approx(0.1)


def test_unlimited_tenant_never_sheds():
    b = TokenBucket(rate=0.0, burst=1.0)
    for _ in range(100):
        assert b.take(10_000) is None


# -- deficit round robin -----------------------------------------------------


def test_drr_proportionality_over_long_run():
    """Weights 3:1 with both tenants permanently backlogged -> the token
    share of what select() hands out converges to 3:1."""
    table = TenantTable(parse_tenant_specs("heavy:3:0,light:1:0"),
                        quantum_tokens=64)
    took = {"heavy": 0, "light": 0}
    for _round in range(200):
        backlog = (
            [req(f"h{_round}-{i}", tenant="heavy", tokens=50)
             for i in range(8)]
            + [req(f"l{_round}-{i}", tenant="light", tokens=50)
               for i in range(8)]
        )
        for r in table.select(backlog, 4):
            took[r.tenant] += r.est_tokens
    ratio = took["heavy"] / took["light"]
    assert 2.5 <= ratio <= 3.5, (took, ratio)


def test_drr_preserves_fifo_within_tenant_and_never_returns_empty():
    table = make_table("a:1:0,b:1:0")
    backlog = [req(f"a{i}", tenant="a") for i in range(4)] + [
        req(f"b{i}", tenant="b") for i in range(4)
    ]
    picked = table.select(list(backlog), 8)
    assert len(picked) == 8
    for tenant in ("a", "b"):
        order = [r.prompt for r in picked if r.tenant == tenant]
        assert order == sorted(order)  # a0..a3 / b0..b3 in FIFO order
    assert table.select([req("x", tenant="a")], 4)  # non-empty in -> out


def test_select_serves_undeclared_tenants_instead_of_spinning():
    """A candidate whose tenant the table never declared (journal replay
    after a --tenants change) must be scheduled as a weight-1 tenant, not
    spin the pick forever with the queue lock held."""
    table = make_table("known:2:0")
    backlog = [req(f"g{i}", tenant="ghost") for i in range(3)] + [
        req(f"k{i}", tenant="known") for i in range(3)
    ]
    picked = table.select(backlog, 6)
    assert sorted(r.prompt for r in picked) == sorted(
        r.prompt for r in backlog
    )
    # and a backlog that is ONLY ghosts still drains
    only_ghosts = [req(f"o{i}", tenant="phantom") for i in range(2)]
    assert len(table.select(only_ghosts, 2)) == 2
    # a label-unsafe request-carried name is sanitized, never raised on —
    # the take path must serve (the HTTP layer 400s these before the queue,
    # but library callers reach select() directly)
    unsafe = [req("u0", tenant='team "a"\n'), req("u1", tenant="known")]
    assert len(table.select(unsafe, 2)) == 2
    assert all(_NAME_RE.fullmatch(name) for name in table.stats())


def test_interactive_tier_always_picked_before_batch():
    table = make_table()
    backlog = [req(f"b{i}", tenant="batch", tier="batch") for i in range(6)]
    backlog += [req(f"i{i}", tenant="interactive") for i in range(2)]
    picked = table.select(backlog, 4)
    assert [r.tenant for r in picked[:2]] == ["interactive", "interactive"]


# -- queue integration -------------------------------------------------------


def test_single_tenant_fall_through_identical_to_fifo():
    """With one tenant (or no table) the queue's take order — including the
    cache-hint clustering — must be byte-identical to the pre-QoS FIFO."""
    def fill(q):
        for i in range(6):
            hint = "chung" if i % 2 else "khac"
            q.submit(ServeRequest(prompt=f"p{i}", cache_hint=hint,
                                  tenant="solo"))
        return [r.prompt for r in q.take_upto(4)]

    plain = RequestQueue(max_depth=16)
    tabled = RequestQueue(max_depth=16,
                          tenants=make_table("solo:2:0"))
    assert fill(plain) == fill(tabled)


def test_wfq_pick_in_take_batch_and_take_upto():
    """Both take paths route through the DRR pick: with two tenants
    backlogged, a take returns interactive-tier work first regardless of
    arrival order."""
    q = RequestQueue(max_depth=32, tenants=make_table())
    for i in range(4):
        q.submit(req(f"batch{i}", tenant="batch", tier="batch"))
    for i in range(2):
        q.submit(req(f"inter{i}", tenant="interactive"))
    got = q.take_batch(3, max_wait_s=0.0)
    assert [r.prompt for r in got[:2]] == ["inter0", "inter1"]
    got2 = q.take_upto(4)
    assert all(r.tenant == "batch" for r in got2)
    # FIFO preserved within the batch tenant
    assert [r.prompt for r in got2] == sorted(r.prompt for r in got2)


def test_quota_shed_is_typed_with_refill_retry_after():
    table = TenantTable(parse_tenant_specs("metered:1:100"))
    q = RequestQueue(max_depth=32, tenants=table)
    q.submit(req("dau tien", tenant="metered", tokens=200))  # burst spends
    with pytest.raises(RequestShed) as exc:
        q.submit(req("qua han muc", tenant="metered", tokens=100))
    assert exc.value.reason is ShedReason.QUOTA
    assert exc.value.retry_after_s == pytest.approx(1.0, rel=0.2)


def test_backlog_sheds_carry_depth_derived_retry_after():
    q = RequestQueue(max_depth=2)
    q.submit(req("a"))
    q.submit(req("b"))
    with pytest.raises(RequestShed) as exc:
        q.submit(req("c"))
    assert exc.value.reason is ShedReason.QUEUE_FULL
    assert exc.value.retry_after_s >= 1.0
    qt = RequestQueue(max_depth=8, max_queued_tokens=15)
    qt.submit(req("a", tokens=10))
    with pytest.raises(RequestShed) as exc:
        qt.submit(req("b", tokens=10))
    assert exc.value.reason is ShedReason.TOKEN_BUDGET
    assert exc.value.retry_after_s >= 1.0


def test_deadline_shed_carries_retry_after():
    q = RequestQueue(max_depth=8)
    with pytest.raises(RequestShed) as exc:
        q.submit(req("het han", deadline=time.monotonic() - 1))
    assert exc.value.reason is ShedReason.DEADLINE
    assert exc.value.retry_after_s == 1.0


# -- preemption --------------------------------------------------------------


def make_inflight(**kw):
    backend = FakeBackend(
        segment_words=4, segment_overhead_s=0.005, batch_overhead_s=0.01,
        **kw.pop("backend_kw", {}),
    )
    kw.setdefault("slots", 2)
    kw.setdefault("max_wait_s", 0.01)
    kw.setdefault("tenants", make_table())
    return backend, InflightScheduler(backend, **kw)


def test_preemption_interactive_reclaims_slots():
    """Two batch-tier jobs saturate both slots; an interactive arrival must
    preempt one within a segment and complete FIRST, while the preempted
    job still completes byte-identically to an unpreempted run (also rerun
    under VNSUM_SANITIZERS=all in CI — the tenant-table lock joins the
    lock-order graph here)."""
    backend, sched = make_inflight()
    try:
        long_prompt = "phan tich chuyen sau noi dung " * 12
        b_futs = [
            sched.submit(long_prompt + f" so {i}", tenant="batch",
                         tier="batch")
            for i in range(2)
        ]
        time.sleep(0.03)  # both resident, a few segments deep
        t0 = time.monotonic()
        i_c = sched.submit("ngan gon", tenant="interactive").result(timeout=30)
        interactive_wall = time.monotonic() - t0
        b_cs = [f.result(timeout=30) for f in b_futs]
        snap = sched.metrics.snapshot()
        assert snap.preemptions >= 1 and snap.requeues >= 1
        assert i_c.record.status == "ok"
        # lossless round trip: the preempted batch runs restart and finish
        # byte-identical to an uninterrupted run
        for i, c in enumerate(b_cs):
            ref = FakeBackend().generate([long_prompt + f" so {i}"])[0]
            assert c.text == ref
        # the interactive request did not wait out a batch job's decode
        assert interactive_wall < max(c.record.total_s for c in b_cs)
    finally:
        sched.close()


def test_preemption_lands_at_fused_dispatch_boundary():
    """Fused decode (N=4) coarsens preemption polls to host-dispatch
    cadence: an interactive arrival still reclaims a slot at the next
    fused boundary, and the preempted batch job replays byte-identically
    — the eviction round trip is lossless at every fused cadence."""
    backend, sched = make_inflight(
        fused_segments=4, backend_kw=dict(per_step_s=0.002),
    )
    try:
        long_prompt = "phan tich chuyen sau noi dung hop nhat " * 12
        b_futs = [
            sched.submit(long_prompt + f" so {i}", tenant="batch",
                         tier="batch")
            for i in range(2)
        ]
        time.sleep(0.04)  # both resident, a fused dispatch or so deep
        i_c = sched.submit("ngan gon", tenant="interactive").result(timeout=30)
        b_cs = [f.result(timeout=30) for f in b_futs]
        snap = sched.metrics.snapshot()
        assert snap.preemptions >= 1 and snap.requeues >= 1
        assert snap.fused_dispatches > 0
        assert i_c.record.status == "ok"
        for i, c in enumerate(b_cs):
            ref = FakeBackend().generate([long_prompt + f" so {i}"])[0]
            assert c.text == ref
    finally:
        sched.close()


def test_preemption_pins_prefix_blocks_and_releases_them():
    """Eviction pins the victim's cached prefix (it survives LRU while
    requeued) and every pin is released by terminal resolution."""
    backend, sched = make_inflight(
        backend_kw=dict(prefix_cache_blocks=64, cache_block_tokens=4),
    )
    try:
        long_prompt = "tai lieu can tom tat rat dai " * 10
        b_fut = sched.submit(long_prompt, tenant="batch", tier="batch")
        sched.submit(long_prompt + " hai", tenant="batch", tier="batch")
        time.sleep(0.03)
        deadline = time.monotonic() + 30
        sched.submit("uu tien", tenant="interactive").result(timeout=30)
        while sched.metrics.snapshot().preemptions < 1:
            assert time.monotonic() < deadline, "no preemption happened"
            time.sleep(0.005)
        b_fut.result(timeout=30)
    finally:
        sched.close()
    # all pins (admission + preemption) returned: nothing left uneviciable
    assert backend.prefix_index.pinned_blocks == 0
    assert sched.metrics.snapshot().preemptions >= 1


def test_sampled_batch_requests_are_never_preempted():
    """A SAMPLED row's stream keys on its slot-admission uid, so a restart
    would draw different text — sampled batch requests keep their slots
    and only greedy ones are evicted."""
    from vnsum_tpu.core.config import GenerationConfig

    backend, sched = make_inflight(slots=1)
    try:
        cfg = GenerationConfig(temperature=0.7, seed=3)
        b_fut = sched.submit("nen lay mau ngau nhien " * 10, tenant="batch",
                             tier="batch", config=cfg)
        time.sleep(0.03)
        # same batch key required to target the resident loop: the
        # interactive prompt rides the same config
        i_fut = sched.submit("khan", tenant="interactive", config=cfg)
        assert b_fut.result(timeout=30).record.status == "ok"
        assert i_fut.result(timeout=30).record.status == "ok"
        assert sched.metrics.snapshot().preemptions == 0
    finally:
        sched.close()


def test_preempt_budget_bounds_starvation():
    """A batch request evicted preempt_budget times becomes non-evictable
    and completes even under constant interactive pressure."""
    backend, sched = make_inflight(slots=1, preempt_budget=2)
    try:
        b_fut = sched.submit("cong viec nen dai " * 10, tenant="batch",
                             tier="batch")
        stop = threading.Event()

        def pressure():
            while not stop.is_set():
                try:
                    sched.submit("gap", tenant="interactive").result(timeout=30)
                except RequestShed:
                    return
        t = threading.Thread(target=pressure, daemon=True)
        t.start()
        c = b_fut.result(timeout=30)
        stop.set()
        t.join(timeout=10)
        assert c.text == FakeBackend().generate(["cong viec nen dai " * 10])[0]
        assert sched.metrics.snapshot().preemptions <= 2
    finally:
        sched.close()


def test_preemption_journal_lifecycle(tmp_path):
    """PREEMPTED + REQUEUED ride the journal, the entry ends in exactly one
    terminal state, and the raw segments carry the typed events."""
    from vnsum_tpu.serve.journal import RequestJournal

    journal = RequestJournal(tmp_path)
    backend, sched = make_inflight(journal=journal)
    try:
        b_fut = sched.submit("nen dai phai cho " * 10, tenant="batch",
                             tier="batch", trace_id="job-batch")
        sched.submit("nen hai cho lau " * 10, tenant="batch", tier="batch")
        time.sleep(0.03)
        sched.submit("khan", tenant="interactive").result(timeout=30)
        b_fut.result(timeout=30)
        deadline = time.monotonic() + 30
        while journal.pending() and time.monotonic() < deadline:
            time.sleep(0.01)
    finally:
        sched.close()
        journal.close()
    entries, _sealed, torn = RequestJournal.read_state(tmp_path)
    assert torn == 0
    entry = entries["job-batch"]
    assert entry.status == "complete"  # exactly one terminal state
    raw = b"".join(p.read_bytes() for p in sorted(tmp_path.glob("*.jsonl")))
    assert b'"e":"preempted"' in raw and b'"e":"requeued"' in raw


# -- HTTP surface ------------------------------------------------------------


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as resp:
        return resp.status, json.loads(resp.read())


def _post(url, payload, headers=None):
    req_ = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
        method="POST",
    )
    with urllib.request.urlopen(req_, timeout=30) as resp:
        return resp.status, json.loads(resp.read())


@pytest.fixture()
def qos_server(tmp_path):
    state = ServeState(
        FakeBackend(segment_words=4, segment_overhead_s=0.002),
        max_batch=4, max_wait_s=0.005, inflight=True, slots=4,
        journal_dir=str(tmp_path / "journal"),
        tenants=TenantTable(
            parse_tenant_specs(
                "interactive:8:0,batch:1:0:batch,metered:1:40"
            )
        ),
    )
    server = make_server(state, "127.0.0.1", 0)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{server.server_address[1]}", state
    server.shutdown()
    server.server_close()
    state.close()


def test_unknown_tenant_is_typed_400(qos_server):
    base, _ = qos_server
    with pytest.raises(urllib.error.HTTPError) as exc:
        _post(base + "/v1/generate", {"prompt": "ai do"},
              headers={"X-Tenant": "nobody"})
    assert exc.value.code == 400
    assert "unknown tenant" in json.loads(exc.value.read())["error"]


def test_missing_header_lands_on_default_tenant(qos_server):
    base, state = qos_server
    status, _ = _post(base + "/v1/generate", {"prompt": "vo danh " * 4})
    assert status == 200
    snap = state.scheduler.metrics.snapshot()
    assert snap.tenant_requests.get("default", 0) >= 1


def test_quota_shed_has_retry_after_header(qos_server):
    base, _ = qos_server
    # burst = 2x rate = 80 word-tokens; two 60-word prompts overflow it
    _post(base + "/v1/generate", {"prompt": "dinh muc " * 30},
          headers={"X-Tenant": "metered"})
    with pytest.raises(urllib.error.HTTPError) as exc:
        _post(base + "/v1/generate", {"prompt": "vuot muc " * 30},
              headers={"X-Tenant": "metered"})
    assert exc.value.code == 429
    body = json.loads(exc.value.read())
    assert body["reason"] == "quota"
    assert int(exc.value.headers["Retry-After"]) >= 1


def test_deadline_shed_has_retry_after_header(qos_server):
    base, _ = qos_server
    with pytest.raises(urllib.error.HTTPError) as exc:
        _post(base + "/v1/generate", {"prompt": "tre", "deadline_ms": 0})
    assert exc.value.code == 429
    assert int(exc.value.headers["Retry-After"]) >= 1


def test_queue_full_shed_has_retry_after_header():
    state = ServeState(
        FakeBackend(batch_overhead_s=0.2), max_batch=1, max_wait_s=0.005,
        max_queue_depth=1,
    )
    server = make_server(state, "127.0.0.1", 0)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{server.server_address[1]}"
    try:
        def fire():
            # lint-allow[swallowed-exception]: background load may itself shed or race shutdown — only the foreground 429 below is asserted
            try:
                _post(base + "/v1/generate", {"prompt": "giu cho " * 4})
            except Exception:
                pass
        threads = [threading.Thread(target=fire) for _ in range(6)]
        for t in threads:
            t.start()
        saw_429 = None
        for _ in range(40):
            try:
                _post(base + "/v1/generate", {"prompt": "day hang " * 4})
            except urllib.error.HTTPError as e:
                if e.code == 429:
                    saw_429 = e
                    break
            time.sleep(0.01)
        assert saw_429 is not None, "queue never filled"
        assert int(saw_429.headers["Retry-After"]) >= 1
        assert json.loads(saw_429.read())["reason"] in (
            "queue_full", "token_budget"
        )
        for t in threads:
            t.join(timeout=30)
    finally:
        server.shutdown()
        server.server_close()
        state.close()


def test_healthz_echoes_tenants_and_metrics_render_qos_rows(qos_server):
    base, _ = qos_server
    _post(base + "/v1/generate", {"prompt": "do dac " * 4},
          headers={"X-Tenant": "interactive"})
    _, health = _get(base + "/healthz")
    assert health["tenants"]["batch"]["tier"] == "batch"
    with urllib.request.urlopen(base + "/metrics", timeout=10) as resp:
        text = resp.read().decode()
    assert "vnsum_serve_qos_tenants 4" in text  # 3 declared + default
    assert 'vnsum_serve_qos_requests_total{tenant="interactive"}' in text
    assert 'vnsum_serve_qos_quota_sheds_total{tenant="metered"}' in text
    assert 'vnsum_serve_qos_bucket_tokens{tenant="metered"}' in text
    assert "vnsum_serve_qos_preemptions_total" in text
    assert "vnsum_serve_qos_requeues_total" in text
    assert 'vnsum_serve_requests_shed_total{reason="quota"}' in text


# -- GET /v1/requests/<id> lifecycle states ----------------------------------


def _seed(journal, rid, prompt="van ban"):
    r = ServeRequest(prompt=prompt, trace_id=rid)
    journal.accept(r)
    return r


def test_request_status_reports_each_lifecycle_state(qos_server):
    base, state = qos_server
    j = state.journal
    cases = {
        "st-accepted": [],
        "st-started": ["start"],
        "st-streaming": ["start", "streaming"],
        "st-preempted": ["start", "preempt"],
        "st-requeued": ["start", "preempt", "requeue"],
    }
    for rid, steps in cases.items():
        _seed(j, rid)
        for step in steps:
            getattr(j, step)(rid)
    for rid, expected in (
        ("st-accepted", "accepted"), ("st-started", "started"),
        ("st-streaming", "streaming"), ("st-preempted", "preempted"),
        ("st-requeued", "requeued"),
    ):
        _, body = _get(base + f"/v1/requests/{rid}")
        assert body["status"] == expected, (rid, body)
        assert body["entries"][0]["status"] in (
            "accept", "start", "streaming", "preempted", "requeued"
        )


def test_request_status_aggregates_fanout_states(qos_server):
    base, state = qos_server
    j = state.journal
    # fan-out: one sibling preempted, one actively streaming -> the
    # aggregate says streaming (something is moving)
    _seed(j, "fan-a", "mot")
    _seed(j, "fan-a", "hai")  # becomes fan-a#1
    j.preempt("fan-a")
    j.start("fan-a#1")
    j.streaming("fan-a#1")
    _, body = _get(base + "/v1/requests/fan-a")
    assert body["status"] == "streaming" and len(body["entries"]) == 2
    # both siblings parked by preemption, one already requeued -> requeued
    _seed(j, "fan-b", "ba")
    _seed(j, "fan-b", "bon")
    for rid in ("fan-b", "fan-b#1"):
        j.start(rid)
        j.preempt(rid)
    j.requeue("fan-b#1")
    _, body = _get(base + "/v1/requests/fan-b")
    assert body["status"] == "requeued"
    # a failed sibling still fails the fan-out whatever the others do
    _seed(j, "fan-c", "nam")
    _seed(j, "fan-c", "sau")
    j.preempt("fan-c")
    j.fail("fan-c#1", "poison")
    _, body = _get(base + "/v1/requests/fan-c")
    assert body["status"] == "failed"


def test_preempted_state_survives_compacting_reopen(tmp_path):
    from vnsum_tpu.serve.journal import RequestJournal

    j = RequestJournal(tmp_path)
    _seed(j, "dur-1")
    j.start("dur-1")
    j.preempt("dur-1")
    j.close()
    j2 = RequestJournal(tmp_path)  # reopen compacts
    try:
        entries = j2.lookup("dur-1")
        assert entries and entries[0].status == "preempted"
        # still replayable: take_unfinished hands it out exactly once
        assert [e.rid for e in j2.take_unfinished()] == ["dur-1"]
        assert j2.take_unfinished() == []
    finally:
        j2.close()
