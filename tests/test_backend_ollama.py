"""OllamaBackend behavior tests with a stubbed `requests` module — payload
parity with the reference's OllamaLLM (SURVEY.md §2 C2) plus the retry
policy the reference lacks (§5 "Failure detection ... No retries anywhere")."""
import sys
import types

import pytest

from vnsum_tpu.backend.ollama import OllamaBackend
from vnsum_tpu.core.config import GenerationConfig


class FakeResponse:
    def __init__(self, payload=None, status=200):
        self._payload = payload or {}
        self.status_code = status

    def raise_for_status(self):
        if self.status_code >= 400:
            raise self._requests.HTTPError(response=self)

    def json(self):
        return self._payload


@pytest.fixture()
def fake_requests(monkeypatch):
    mod = types.ModuleType("requests")

    class ConnectionError(Exception):
        pass

    class Timeout(Exception):
        pass

    class HTTPError(Exception):
        def __init__(self, response=None):
            self.response = response

    mod.ConnectionError = ConnectionError
    mod.Timeout = Timeout
    mod.HTTPError = HTTPError
    mod.calls = []
    mod.responses = []

    def post(url, json=None, timeout=None):
        mod.calls.append({"url": url, "json": json, "timeout": timeout})
        item = mod.responses.pop(0)
        if isinstance(item, Exception):
            raise item
        item._requests = mod
        return item

    def get(url, timeout=None):
        mod.calls.append({"url": url, "json": None, "timeout": timeout})
        item = mod.responses.pop(0)
        item._requests = mod
        return item

    mod.post = post
    mod.get = get
    monkeypatch.setitem(sys.modules, "requests", mod)
    return mod


def test_payload_parity(fake_requests):
    """POST body matches the reference OllamaLLM (mapreduce.py:37-49 +
    critique.py's think:false + num_predict option)."""
    fake_requests.responses = [FakeResponse({"response": "<think>x</think>KQ"})]
    be = OllamaBackend(model="llama3.2:3b", url="http://h:1/")
    out = be.generate(["xin chào"], max_new_tokens=77)
    assert out == ["KQ"]  # thinking tokens cleaned
    call = fake_requests.calls[0]
    assert call["url"] == "http://h:1/api/generate"
    body = call["json"]
    assert body["model"] == "llama3.2:3b"
    assert body["prompt"] == "xin chào"
    assert body["stream"] is False
    assert body["think"] is False
    assert body["options"]["num_predict"] == 77


def test_generation_config_options(fake_requests):
    fake_requests.responses = [FakeResponse({"response": "ok"})]
    be = OllamaBackend()
    cfg = GenerationConfig(temperature=0.7, top_k=40, top_p=0.9, seed=11)
    be.generate(["p"], config=cfg)
    opts = fake_requests.calls[0]["json"]["options"]
    assert opts["temperature"] == 0.7
    assert opts["top_k"] == 40
    assert opts["top_p"] == 0.9
    assert opts["seed"] == 11


def test_retries_transient_then_succeeds(fake_requests, monkeypatch):
    monkeypatch.setattr("time.sleep", lambda s: None)
    fake_requests.responses = [
        fake_requests.ConnectionError("down"),
        fake_requests.ConnectionError("still down"),
        FakeResponse({"response": "ok"}),
    ]
    be = OllamaBackend(max_retries=3, retry_backoff=0)
    assert be.generate(["p"]) == ["ok"]
    assert len(fake_requests.calls) == 3


def test_timeout_not_retried(fake_requests, monkeypatch):
    """A read timeout (600 s default) is not transient — retrying it would
    stall the pipeline ~40 min/prompt on a hung server."""
    monkeypatch.setattr("time.sleep", lambda s: None)
    fake_requests.responses = [fake_requests.Timeout("hung")]
    be = OllamaBackend(max_retries=3, retry_backoff=0)
    with pytest.raises(fake_requests.Timeout):
        be.generate(["p"])
    assert len(fake_requests.calls) == 1


def test_negative_max_retries_clamped(fake_requests):
    fake_requests.responses = [FakeResponse({"response": "ok"})]
    be = OllamaBackend(max_retries=-1)
    assert be.max_retries == 0
    assert be.generate(["p"]) == ["ok"]


def test_retries_5xx_but_not_4xx(fake_requests, monkeypatch):
    monkeypatch.setattr("time.sleep", lambda s: None)
    fake_requests.responses = [
        FakeResponse(status=500),
        FakeResponse({"response": "ok"}),
    ]
    be = OllamaBackend(max_retries=2, retry_backoff=0)
    assert be.generate(["p"]) == ["ok"]

    fake_requests.calls.clear()
    fake_requests.responses = [FakeResponse(status=404)]
    with pytest.raises(fake_requests.HTTPError):
        be.generate(["p"])
    assert len(fake_requests.calls) == 1  # no retry on client error


def test_retries_exhausted_raises(fake_requests, monkeypatch):
    monkeypatch.setattr("time.sleep", lambda s: None)
    fake_requests.responses = [fake_requests.ConnectionError("down")] * 3
    be = OllamaBackend(max_retries=2, retry_backoff=0)
    with pytest.raises(fake_requests.ConnectionError):
        be.generate(["p"])
    assert len(fake_requests.calls) == 3


def test_health_check(fake_requests):
    fake_requests.responses = [
        FakeResponse({"models": [{"name": "llama3.2:3b"}, {"name": "qwen3:8b"}]})
    ]
    assert OllamaBackend().health_check() == ["llama3.2:3b", "qwen3:8b"]


def test_split_connect_read_timeouts_on_every_request(fake_requests):
    """A dead host must fail at the TCP handshake (seconds), not burn the
    600 s read budget: every HTTP call passes the (connect, read) tuple."""
    fake_requests.responses = [FakeResponse({"response": "ok"})]
    be = OllamaBackend(timeout=600.0, connect_timeout=3.5)
    assert be.generate(["p"]) == ["ok"]
    assert fake_requests.calls[0]["timeout"] == (3.5, 600.0)

    fake_requests.calls.clear()
    fake_requests.responses = [FakeResponse({"models": []})]
    be.health_check()
    assert fake_requests.calls[0]["timeout"] == (3.5, 10)


def test_retry_backoff_is_jittered_and_bounded(fake_requests, monkeypatch):
    """Retries from `concurrency` pool workers must not re-slam a
    recovering server in lockstep: delays carry multiplicative jitter in
    [base, base * (1 + jitter)]."""
    delays = []
    monkeypatch.setattr("time.sleep", lambda s: delays.append(s))
    fake_requests.responses = [
        fake_requests.ConnectionError("down"),
        fake_requests.ConnectionError("down"),
        FakeResponse({"response": "ok"}),
    ]
    be = OllamaBackend(max_retries=3, retry_backoff=1.0, retry_jitter=0.5)
    assert be.generate(["p"]) == ["ok"]
    assert len(delays) == 2
    # exponential base doubles; each delay within its jitter band
    assert 1.0 <= delays[0] <= 1.5
    assert 2.0 <= delays[1] <= 3.0
