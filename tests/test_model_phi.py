"""Phi-4 family support: Llama-shaped math, fused-projection checkpoints.

The reference's largest model sweep entry is phi4:14b
(run_full_evaluation_pipeline.py:960-962), Ollama-only there. HF Phi-3/4
checkpoints fuse attention into one qkv_proj and the MLP into
gate_up_proj; models.convert adapts them to the shared converter. Parity
anchor: transformers Phi3ForCausalLM on a tiny config.
"""
from __future__ import annotations

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax.numpy as jnp

from vnsum_tpu.models.convert import config_from_hf, load_hf_checkpoint
from vnsum_tpu.models.llama import (
    forward,
    init_kv_cache,
    phi4_14b,
    prefill_attention_mask,
    prefill_positions,
)

HF_CFG = dict(
    vocab_size=384,
    hidden_size=64,
    intermediate_size=128,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,
    max_position_embeddings=256,
    rope_theta=10000.0,
    rms_norm_eps=1e-5,
    tie_word_embeddings=False,
    model_type="phi3",
    # Phi3Config defaults pad/bos/eos to 32k-range ids; keep them in-vocab
    pad_token_id=0,
    bos_token_id=1,
    eos_token_id=2,
)


@pytest.fixture(scope="module")
def hf_checkpoint(tmp_path_factory):
    torch.manual_seed(0)
    cfg = transformers.Phi3Config(**{
        k: v for k, v in HF_CFG.items() if k != "model_type"
    })
    model = transformers.Phi3ForCausalLM(cfg).eval()
    out = tmp_path_factory.mktemp("phi") / "ckpt"
    model.save_pretrained(out, safe_serialization=True)
    return model, str(out)


def test_phi_fused_checkpoint_logit_parity(hf_checkpoint):
    """load_hf_checkpoint must split qkv_proj/gate_up_proj correctly: full
    prefill logits match the HF forward."""
    model, ckpt = hf_checkpoint
    cfg, params = load_hf_checkpoint(ckpt, dtype=jnp.float32)
    assert not cfg.tie_embeddings and not cfg.qk_norm
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size, (2, 20), dtype=np.int32)

    B, S = tokens.shape
    pad = np.zeros((B,), np.int32)
    cache = init_kv_cache(cfg, B, S)
    ours, _ = forward(
        params, cfg, jnp.asarray(tokens),
        prefill_positions(jnp.asarray(pad), S), cache, 0,
        prefill_attention_mask(jnp.asarray(pad), S, S),
    )
    with torch.no_grad():
        theirs = model(torch.from_numpy(tokens).long()).logits.float().numpy()
    np.testing.assert_allclose(np.asarray(ours), theirs, atol=2e-4, rtol=2e-3)


def test_phi_partial_rotary_rejected():
    cfg = dict(HF_CFG, partial_rotary_factor=0.5)
    with pytest.raises(NotImplementedError):
        config_from_hf(cfg)


def test_phi4_registry_shapes():
    cfg = phi4_14b()
    assert (cfg.dim, cfg.n_layers, cfg.n_heads, cfg.n_kv_heads) == (
        5120, 40, 40, 10,
    )
    assert not cfg.tie_embeddings


def test_phi_engine_generate(hf_checkpoint):
    """Converted fused checkpoint runs the engine end to end."""
    from vnsum_tpu.backend.engine import TpuBackend

    _, ckpt = hf_checkpoint
    cfg, params = load_hf_checkpoint(ckpt, dtype=jnp.float32)
    be = TpuBackend(
        model_config=cfg, tokenizer="byte", params=params, batch_size=2,
        max_new_tokens=8, seed=0,
    )
    outs = be.generate(["văn bản một", "hai"])
    assert len(outs) == 2 and all(isinstance(o, str) for o in outs)
