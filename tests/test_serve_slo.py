"""SLO engine + usage ledger + flight recorder (ISSUE 14 tentpole).

Unit level: spec parsing, burn-rate math over a synthetic clock,
edge-triggered breaches firing the recorder, the capped tenant-label
registry, and the per-tenant ledger. HTTP level: /debug/slo,
/debug/flightrecorder, /v1/usage, the /healthz SLO line, the
vnsum_serve_slo_*/usage_*/recorder_*/scrape_seconds metrics, and
OpenMetrics-style exemplars on the latency buckets.

Acceptance scenario (the ISSUE criterion): seeded resource-fault injection
drives the degradation ladder to brownout on a live journaled server — the
brownout entry dumps the flight recorder, the dump's typed event sequence
matches the journal's records per request, and /debug/slo reports the
burn-rate breach with an exemplar trace_id resolvable via /debug/trace."""
from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from vnsum_tpu.backend.fake import FakeBackend
from vnsum_tpu.core.results import ServeRequestRecord
from vnsum_tpu.obs.recorder import FlightRecorder
from vnsum_tpu.serve.metrics import ServeMetrics
from vnsum_tpu.serve.queue import ShedReason
from vnsum_tpu.serve.slo import SloEngine, parse_slo_spec
from vnsum_tpu.serve.usage import OTHER_LABEL, TenantLabelRegistry

# -- spec parsing -------------------------------------------------------------


def test_parse_slo_spec_full_form():
    objs = parse_slo_spec(
        "ttft_p99=0.5,e2e_p99=30,error_rate=0.01,availability=0.999"
    )
    assert set(objs) == {"ttft_p99", "e2e_p99", "error_rate", "availability"}
    assert objs["ttft_p99"].kind == "latency"
    assert objs["ttft_p99"].allowed == pytest.approx(0.01)
    assert objs["ttft_p99"].metric == "ttft_seconds"
    assert objs["e2e_p99"].threshold == 30.0
    assert objs["error_rate"].allowed == 0.01
    assert objs["availability"].allowed == pytest.approx(0.001)
    # three-digit quantiles parse too
    assert parse_slo_spec("e2e_p999=60")["e2e_p999"].allowed == pytest.approx(
        0.001
    )
    assert parse_slo_spec("queue_wait_p95=0.1")[
        "queue_wait_p95"
    ].metric == "queue_wait_seconds"


@pytest.mark.parametrize("bad", [
    "", "ttft_p99", "nope_p99=1", "ttft_p99=fast", "ttft_p99=0",
    "error_rate=1.5", "availability=0", "ttft_p99=1,ttft_p99=2",
    # p100 must be rejected loudly, not silently misparsed as p10
    "ttft_p100=0.5",
])
def test_parse_slo_spec_rejects_malformed(bad):
    with pytest.raises(ValueError):
        parse_slo_spec(bad)


# -- engine math over a synthetic clock ---------------------------------------


class Clock:
    def __init__(self, t: float = 1000.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t


def rec_ok(i: int, ttft: float, e2e: float) -> ServeRequestRecord:
    return ServeRequestRecord(
        request_id=i, status="ok", trace_id=f"r{i}", ttft_s=ttft,
        ttft_anchored=True, total_s=e2e,
    )


def test_burn_rates_budget_and_edge_triggered_breach(tmp_path):
    clk = Clock()
    m = ServeMetrics(horizon_s=600.0, sub_windows=60, clock=clk)
    recorder = FlightRecorder(directory=tmp_path)
    eng = SloEngine(
        parse_slo_spec("ttft_p99=0.5,error_rate=0.01"), m,
        fast_window_s=60.0, slow_window_s=600.0,
        recorder=recorder, interval_s=0,
    )
    for i in range(100):
        m.observe_request(rec_ok(i, 0.05, 0.1))
    st = eng.evaluate(now=clk.t)
    assert st["windowed"] and not st["breached"]
    obj = st["objectives"]["ttft_p99"]
    assert obj["burn_fast"] == 0.0 and obj["burn_slow"] == 0.0
    assert obj["compliance"] == 1.0 and obj["budget_remaining"] == 1.0
    # a slow burst: 50 of 150 miss the 0.5s target -> burn ~= 33x budget
    for i in range(50):
        m.observe_request(rec_ok(100 + i, 2.0, 2.5))
    st = eng.evaluate(now=clk.t)
    obj = st["objectives"]["ttft_p99"]
    assert obj["burn_fast"] == pytest.approx(100 / 3, rel=1e-6)
    assert obj["compliance"] == pytest.approx(2 / 3, rel=1e-6)
    assert obj["budget_remaining"] == 0.0
    assert obj["breaching"] and st["breached"]
    assert st["breaches_total"] == 1
    # the exemplar names a VIOLATING request (one of the 2.0s ones)
    assert int(obj["exemplar_trace_id"][1:]) >= 100
    assert st["last_breach"]["objectives"] == ["ttft_p99"]
    # the breach fired the recorder: a typed slo_breach event + one dump
    # (written on a throwaway thread so probe handlers never block on
    # fsync — poll briefly)
    deadline = time.monotonic() + 5.0
    while (time.monotonic() < deadline
           and not list(tmp_path.glob("flight_slo_fast_burn_*.json"))):
        time.sleep(0.01)
    dumps = list(tmp_path.glob("flight_slo_fast_burn_*.json"))
    assert len(dumps) == 1
    kinds = [e["kind"] for e in recorder.snapshot()["events"]]
    assert "slo_breach" in kinds
    # edge-triggered: still breaching, no second count, no second dump
    st = eng.evaluate(now=clk.t)
    assert st["breaches_total"] == 1
    time.sleep(0.05)
    assert len(list(tmp_path.glob("flight_slo_fast_burn_*.json"))) == 1
    # recovery: fresh compliant traffic after the fast window rolls past
    clk.t += 120.0
    for i in range(50):
        m.observe_request(rec_ok(200 + i, 0.05, 0.1))
    st = eng.evaluate(now=clk.t)
    assert not st["breached"]
    assert st["objectives"]["ttft_p99"]["burn_fast"] == 0.0


def test_error_rate_and_availability_objectives():
    clk = Clock()
    m = ServeMetrics(horizon_s=600.0, sub_windows=60, clock=clk)
    eng = SloEngine(
        parse_slo_spec("error_rate=0.1,availability=0.9"), m,
        fast_window_s=60.0, slow_window_s=600.0, interval_s=0,
    )
    # empty windows are vacuously compliant — an idle server is not failing
    st = eng.evaluate(now=clk.t)
    assert all(o["burn_fast"] == 0.0 for o in st["objectives"].values())
    for i in range(8):
        m.observe_request(rec_ok(i, 0.01, 0.05))
    m.observe_request(ServeRequestRecord(request_id=8, status="error"))
    m.observe_shed(ShedReason.QUEUE_FULL)
    st = eng.evaluate(now=clk.t)
    # error_rate: 1 error / 9 resolved = 0.111 over a 0.1 budget
    assert st["objectives"]["error_rate"]["burn_fast"] == pytest.approx(
        (1 / 9) / 0.1
    )
    # availability counts the shed too: 2 bad / 10 outcomes over 0.1
    assert st["objectives"]["availability"]["burn_fast"] == pytest.approx(
        (2 / 10) / 0.1
    )


def test_engine_without_windows_reports_unwindowed():
    m = ServeMetrics(windowed=False)
    eng = SloEngine(parse_slo_spec("error_rate=0.01"), m, interval_s=0)
    st = eng.evaluate()
    assert st == {"objectives": {}, "breached": False, "breaches_total": 0,
                  "windowed": False}


# -- tenant label registry + usage ledger ------------------------------------


def test_label_registry_caps_and_overflows():
    reg = TenantLabelRegistry(cap=2, seed=["alpha"])
    assert reg.canonical("alpha") == "alpha"
    assert reg.canonical("beta") == "beta"
    # cap reached: every new name collapses into the overflow label
    assert reg.canonical("gamma") == OTHER_LABEL
    assert reg.canonical("delta") == OTHER_LABEL
    assert reg.canonical("gamma") == OTHER_LABEL  # counted once
    assert reg.overflowed == 2
    # the overflow label itself is idempotent and never counts as an
    # overflowed tenant (render paths re-feed canonical ledger keys)
    assert reg.canonical(OTHER_LABEL) == OTHER_LABEL
    assert reg.overflowed == 2
    # tracked names never merge retroactively
    assert reg.canonical("alpha") == "alpha"
    assert set(reg.tracked()) == {"alpha", "beta"}
    # hostile charset sanitizes instead of corrupting the exposition
    assert '"' not in reg.canonical('evil"name\n')


def test_usage_ledger_tracks_per_tenant_counters_and_latency():
    clk = Clock()
    m = ServeMetrics(clock=clk)
    m.observe_submit(tenant="team-a")
    m.observe_submit(tenant="team-b")
    rec = rec_ok(1, 0.05, 0.2)
    rec.prompt_tokens, rec.generated_tokens = 100, 40
    rec.cached_prompt_tokens = 30
    m.observe_request(rec, tenant="team-a")
    m.observe_request(ServeRequestRecord(request_id=2, status="error"),
                      tenant="team-b")
    m.observe_shed(ShedReason.QUOTA, tenant="team-b")
    m.observe_cancel("queued", tenant="team-b")
    m.observe_preemption(tenant="team-b")
    m.observe_requeue(tenant="team-b")
    usage = m.usage_snapshot()
    a, b = usage["team-a"], usage["team-b"]
    assert a["requests"] == 1 and a["completed"] == 1
    assert a["prompt_tokens"] == 100 and a["generated_tokens"] == 40
    assert a["cached_tokens_saved"] == 30
    assert a["ttft"]["count"] == 1 and a["ttft"]["p99_s"] <= 0.1
    assert a["e2e"]["count"] == 1
    assert b["errors"] == 1 and b["sheds"] == 1 and b["cancels"] == 1
    assert b["preemptions"] == 1 and b["requeues"] == 1
    assert b["ttft"]["count"] == 0
    # the empty-tenant default lands on "default"
    m.observe_submit()
    assert m.usage_snapshot()["default"]["requests"] == 1


def test_flight_recorder_ring_bounds_and_dump_throttle(tmp_path):
    r = FlightRecorder(capacity=16, directory=tmp_path,
                       min_dump_interval_s=60.0)
    for i in range(40):
        r.record("admit", rid=f"t{i}")
    snap = r.snapshot()
    assert len(snap["events"]) == 16
    assert snap["events_recorded"] == 40 and snap["events_dropped"] == 24
    # seqs are monotone and the ring keeps the NEWEST events
    seqs = [e["seq"] for e in snap["events"]]
    assert seqs == sorted(seqs) and seqs[-1] == 40
    p = r.dump("test")
    assert p is not None and json.loads(p.read_text())["reason"] == "test"
    # throttled: a second dump for the same reason inside the interval
    assert r.dump("test") is None
    assert r.dump("other") is not None
    assert r.stats_dict()["dumps"] == 2
    # no directory = ring only, dump no-ops
    assert FlightRecorder().dump("x") is None


# -- HTTP surfaces ------------------------------------------------------------


def _get(url, headers=None):
    req = urllib.request.Request(url, headers=headers or {})
    with urllib.request.urlopen(req, timeout=10) as resp:
        return resp.status, resp.read()


def _post(url, payload, headers=None):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=60) as resp:
        return resp.status, json.loads(resp.read())


@pytest.fixture()
def slo_server(tmp_path):
    from vnsum_tpu.serve.qos import TenantTable, parse_tenant_specs
    from vnsum_tpu.serve.server import ServeState, make_server

    state = ServeState(
        FakeBackend(), max_batch=8, max_wait_s=0.005,
        trace_sample=1.0,
        tenants=TenantTable(parse_tenant_specs("team-a:4:0,team-b:1:0")),
        slo="ttft_p99=5,e2e_p99=30,error_rate=0.5,availability=0.5",
        slo_fast_s=30.0, slo_slow_s=300.0,
        flight_dir=str(tmp_path / "flight"),
    )
    server = make_server(state, "127.0.0.1", 0)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{server.server_address[1]}", state
    server.shutdown()
    server.server_close()
    state.close()


def test_http_slo_usage_recorder_surfaces(slo_server):
    base, state = slo_server
    for i in range(3):
        status, _ = _post(base + "/v1/generate",
                          {"prompt": f"xin chao {i} " * 6},
                          headers={"X-Tenant": "team-a"})
        assert status == 200
    _post(base + "/v1/generate", {"prompt": "mot cau hoi " * 4},
          headers={"X-Tenant": "team-b"})

    # /healthz: schema satellite (uptime, start stamp, version, SLO line)
    _, body = _get(base + "/healthz")
    h = json.loads(body)
    assert h["uptime_s"] >= 0 and "started_at" in h and h["version"]
    assert h["slo"].startswith("ok (4 objectives")

    # /debug/slo: full objective detail, nothing breaching
    _, body = _get(base + "/debug/slo")
    d = json.loads(body)
    assert set(d["objectives"]) == {"ttft_p99", "e2e_p99", "error_rate",
                                    "availability"}
    assert not d["breached"]
    assert d["config"]["fast_window_s"] == 30.0

    # /v1/usage: both tenants with counters + windowed latency
    _, body = _get(base + "/v1/usage")
    u = json.loads(body)["tenants"]
    assert u["team-a"]["requests"] == 3 and u["team-a"]["completed"] == 3
    assert u["team-b"]["requests"] == 1
    assert u["team-a"]["e2e"]["count"] == 3
    _, body = _get(base + "/v1/usage?tenant=team-b")
    assert list(json.loads(body)["tenants"]) == ["team-b"]
    with pytest.raises(urllib.error.HTTPError) as exc:
        _get(base + "/v1/usage?tenant=ghost")
    assert exc.value.code == 404

    # /debug/flightrecorder: admit/dispatch/complete events with rids
    _, body = _get(base + "/debug/flightrecorder")
    fr = json.loads(body)
    kinds = {e["kind"] for e in fr["events"]}
    assert {"admit", "dispatch", "complete"} <= kinds
    assert any(e.get("tenant") == "team-a" for e in fr["events"]
               if e["kind"] == "admit")

    # /metrics: slo gauges, usage series (registry-canonical labels),
    # recorder counters, the scrape self-metric, and exemplars
    _, body = _get(base + "/metrics")
    text = body.decode()
    assert 'vnsum_serve_slo_compliance{objective="ttft_p99"}' in text
    assert 'vnsum_serve_slo_burn_rate{objective="e2e_p99",window="fast"}' in text
    assert "vnsum_serve_slo_breached 0" in text
    assert 'vnsum_serve_usage_requests_total{tenant="team-a"} 3' in text
    assert 'vnsum_serve_usage_e2e_p99_seconds{tenant="team-a"}' in text
    assert "vnsum_serve_recorder_events_total" in text
    assert "vnsum_serve_scrape_seconds_count" in text
    # a classic text-format scrape (no negotiation) carries NO exemplars —
    # the 0.0.4 parser rejects a trailing `# {...}` and drops the scrape
    assert '# {trace_id="' not in text
    # an OpenMetrics-negotiated scrape gets the exemplars + the EOF marker
    _, body = _get(base + "/metrics",
                   headers={"Accept": "application/openmetrics-text"})
    om = body.decode()
    assert '# {trace_id="' in om
    assert om.endswith("# EOF\n")
    # second scrape: the first ones' cost has landed in scrape_seconds
    _, body = _get(base + "/metrics")
    for line in body.decode().splitlines():
        if line.startswith("vnsum_serve_scrape_seconds_count"):
            assert int(line.rsplit(" ", 1)[1]) >= 1


def test_slo_endpoints_404_when_unconfigured():
    from vnsum_tpu.serve.server import ServeState, make_server

    state = ServeState(FakeBackend(), max_batch=4, max_wait_s=0.005,
                       flight_recorder=False, windowed_metrics=False)
    server = make_server(state, "127.0.0.1", 0)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{server.server_address[1]}"
    try:
        for path in ("/debug/slo", "/debug/flightrecorder", "/v1/usage"):
            with pytest.raises(urllib.error.HTTPError) as exc:
                _get(base + path)
            assert exc.value.code == 404
        # the all-off arm renders no slo/usage/recorder series at all
        _, body = _get(base + "/metrics")
        text = body.decode()
        assert "vnsum_serve_slo_" not in text
        assert "vnsum_serve_usage_" not in text
        assert "vnsum_serve_recorder_" not in text
    finally:
        server.shutdown()
        server.server_close()
        state.close()


# -- the acceptance scenario --------------------------------------------------


def test_seeded_degradation_produces_matching_dump_and_breach(tmp_path):
    """Fault injection drives the ladder to brownout: the brownout entry
    dumps the flight recorder, the dump's typed event sequence matches the
    journal's records, and /debug/slo reports the breach with an exemplar
    trace_id resolvable via /debug/trace."""
    from vnsum_tpu.serve.server import ServeState, make_server
    from vnsum_tpu.serve.supervisor import EngineSupervisor, RetryPolicy, Rung
    from vnsum_tpu.testing.faults import FaultPlan, FaultSpec, injected

    flight = tmp_path / "flight"
    state = ServeState(
        FakeBackend(batch_overhead_s=0.003),
        max_batch=4, max_wait_s=0.005,
        trace_sample=1.0,
        supervisor=EngineSupervisor(
            RetryPolicy(max_attempts=2, backoff_base_s=0.001,
                        backoff_max_s=0.002, jitter=0.0),
            resource_strikes_per_step=1, probe_interval_s=120.0,
        ),
        journal_dir=str(tmp_path / "journal"),
        # e2e target far below any real latency: every SUCCESSFUL request
        # burns the latency budget, so the breach carries a latency
        # exemplar; the error storm burns error_rate alongside
        slo="e2e_p99=0.0001,error_rate=0.05",
        slo_fast_s=5.0, slo_slow_s=50.0,
        flight_dir=str(flight),
    )
    server = make_server(state, "127.0.0.1", 0)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{server.server_address[1]}"
    good = [f"good-{i}" for i in range(3)]
    bad = [f"bad-{i}" for i in range(6)]
    try:
        for rid in good:
            status, _ = _post(base + "/v1/generate",
                              {"prompt": "lanh manh " * 5,
                               "request_id": rid})
            assert status == 200
        plan = FaultPlan([FaultSpec(site="fake.dispatch", kind="resource",
                                    every_n=1)])
        with injected(plan):
            for rid in bad:
                try:
                    _post(base + "/v1/generate",
                          {"prompt": "su co " * 5, "request_id": rid})
                except urllib.error.HTTPError as e:
                    assert e.code in (500, 503)
                if state.supervisor.rung >= Rung.BROWNOUT:
                    break
        deadline = time.monotonic() + 10.0
        while (time.monotonic() < deadline
               and not list(flight.glob("flight_brownout_*.json"))):
            time.sleep(0.05)
        assert state.supervisor.rung >= Rung.BROWNOUT

        # (1) the brownout dump exists and is well-formed
        dumps = list(flight.glob("flight_brownout_*.json"))
        assert len(dumps) == 1
        dump = json.loads(dumps[0].read_text())
        assert dump["reason"] == "brownout" and dump["events"]
        rungs = [e for e in dump["events"] if e["kind"] == "rung_change"]
        assert rungs and rungs[-1]["to_rung"] == int(Rung.BROWNOUT)
        assert [e["to_rung"] for e in rungs] == sorted(
            e["to_rung"] for e in rungs
        )

        # (2) the recorder's event sequence matches the journal's typed
        # records: every journaled request admits before its terminal
        # event, and the terminal kinds agree
        events = state.recorder.snapshot()["events"]
        terminal_kind = {"complete": "complete", "failed": "failed"}
        for rid in good + bad:
            entries = state.journal.lookup(rid)
            if not entries:
                continue  # shed at admission (post-brownout): never accepted
            [entry] = entries
            mine = [e for e in events if e.get("rid") == rid]
            assert mine and mine[0]["kind"] == "admit", rid
            if entry.status in terminal_kind:
                assert mine[-1]["kind"] == terminal_kind[entry.status], rid
                assert mine[-1]["seq"] > mine[0]["seq"]
        assert all(state.journal.lookup(r)[0].status == "complete"
                   for r in good)
        journaled_bad = [r for r in bad if state.journal.lookup(r)]
        assert journaled_bad
        assert all(state.journal.lookup(r)[0].status == "failed"
                   for r in journaled_bad)
        # the fault storm itself is on the tape
        kinds = {e["kind"] for e in events}
        assert "fault" in kinds

        # (3) /debug/slo reports the breach, with a latency exemplar
        # resolvable via /debug/trace
        _, body = _get(base + "/debug/slo")
        d = json.loads(body)
        assert d["breached"]
        obj = d["objectives"]["e2e_p99"]
        assert obj["breaching"] and obj["burn_fast"] >= 10.0
        ex = obj["exemplar_trace_id"]
        assert ex in good  # only successful requests observe e2e
        _, body = _get(base + "/debug/trace")
        assert f"request {ex}" in body.decode()
        # the breach's dump runs on a detached daemon thread (a probe
        # handler must never block on fsync) — poll for the file
        deadline = time.monotonic() + 10.0
        while (time.monotonic() < deadline
               and not list(flight.glob("flight_slo_fast_burn_*.json"))):
            time.sleep(0.05)
        assert list(flight.glob("flight_slo_fast_burn_*.json"))

        # /healthz carries the breach verdict
        _, body = _get(base + "/healthz")
        assert json.loads(body)["slo"].startswith("BREACH")
    finally:
        server.shutdown()
        server.server_close()
        state.close()
    # SIGTERM-drain satellite: close() dumped the full tape too
    assert list(flight.glob("flight_drain_*.json"))
