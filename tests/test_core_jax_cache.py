"""enable_compilation_cache: idempotent per directory, re-points on a new
explicit directory, honors the "off"/""/"0" opt-outs, and explicit choices
(enable OR disable) survive the library-internal no-arg ensure-enabled calls
(ADVICE r3: first-call-wins previously swallowed later explicit config)."""
import jax
import pytest

from vnsum_tpu.core import jax_cache


@pytest.fixture()
def _restore_cache_config():
    before_state = jax_cache._state
    before_cfg = jax.config.jax_compilation_cache_dir
    yield
    jax_cache._state = before_state
    jax.config.update("jax_compilation_cache_dir", before_cfg)


def test_repoints_on_new_explicit_dir(tmp_path, _restore_cache_config):
    a, b = str(tmp_path / "a"), str(tmp_path / "b")
    assert jax_cache.enable_compilation_cache(a) is True
    assert jax.config.jax_compilation_cache_dir == a
    # same dir: idempotent no-op
    assert jax_cache.enable_compilation_cache(a) is True
    # different explicit dir: re-points instead of being silently ignored
    assert jax_cache.enable_compilation_cache(b) is True
    assert jax.config.jax_compilation_cache_dir == b
    # library-internal no-arg ensure-enabled calls must NOT re-point an
    # active cache back to the env/default resolution
    assert jax_cache.enable_compilation_cache() is True
    assert jax.config.jax_compilation_cache_dir == b


def test_explicit_disable_survives_no_arg_calls(tmp_path, _restore_cache_config):
    a = str(tmp_path / "a")
    assert jax_cache.enable_compilation_cache(a) is True
    assert jax_cache.enable_compilation_cache("off") is False
    assert jax.config.jax_compilation_cache_dir is None
    # backend construction's ensure-enabled call must not undo the opt-out
    assert jax_cache.enable_compilation_cache() is False
    assert jax.config.jax_compilation_cache_dir is None
    # a later explicit dir re-enables
    assert jax_cache.enable_compilation_cache(a) is True
    assert jax.config.jax_compilation_cache_dir == a


@pytest.mark.parametrize("val", ["", "0", "off"])
def test_every_documented_disable_value_disables(val, _restore_cache_config):
    jax_cache._state = None
    assert jax_cache.enable_compilation_cache(val) is False
    assert jax_cache.enable_compilation_cache() is False
