"""Hermetic serving-layer tests: the micro-batching scheduler over the
deterministic FakeBackend — coalescing, max-wait flush, deadline shedding,
admission control, and graceful shutdown. No HTTP here (test_serve_server.py
covers the front-end); these drive the scheduler API directly so failures
point at scheduling policy, not socket plumbing."""
from __future__ import annotations

import threading
import time

import pytest

from vnsum_tpu.backend.fake import FakeBackend
from vnsum_tpu.core.config import GenerationConfig
from vnsum_tpu.serve import (
    MicroBatchScheduler,
    RequestQueue,
    RequestShed,
    ServeRequest,
    ShedReason,
)


def _submit_concurrently(sched, prompts, **kw):
    """Submit each prompt from its own thread, all released together, and
    return the completions in submission order."""
    barrier = threading.Barrier(len(prompts))
    results = [None] * len(prompts)
    errors = [None] * len(prompts)

    def worker(i, p):
        barrier.wait()
        try:
            results[i] = sched.submit(p, **kw).result(timeout=30)
        except Exception as e:  # noqa: BLE001 - recorded for assertions
            errors[i] = e

    threads = [
        threading.Thread(target=worker, args=(i, p))
        for i, p in enumerate(prompts)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return results, errors


# -- coalescing --------------------------------------------------------------


def test_concurrent_requests_coalesce_into_one_engine_batch():
    backend = FakeBackend()
    # generous max_wait so every concurrent submitter makes the first batch
    sched = MicroBatchScheduler(backend, max_batch=8, max_wait_s=0.25)
    try:
        prompts = [f"tai lieu {i} " * 10 for i in range(6)]
        results, errors = _submit_concurrently(sched, prompts)
        assert errors == [None] * 6
        # every request answered with ITS OWN completion, order-preserving
        for p, c in zip(prompts, results):
            assert c.text == FakeBackend().generate([p])[0]
        assert backend.batch_sizes == [6]  # one shared engine dispatch
        recs = [c.record for c in results]
        assert all(r.batch_size == 6 for r in recs)
        assert all(r.status == "ok" for r in recs)
    finally:
        sched.close()


def test_incompatible_generation_params_do_not_coalesce():
    backend = FakeBackend()
    sched = MicroBatchScheduler(backend, max_batch=8, max_wait_s=0.1)
    try:
        f1 = sched.submit("van ban a " * 5, max_new_tokens=64)
        f2 = sched.submit("van ban b " * 5, max_new_tokens=128)
        f3 = sched.submit(
            "van ban c " * 5, max_new_tokens=64,
            config=GenerationConfig(temperature=0.7),
        )
        for f in (f1, f2, f3):
            f.result(timeout=30)
        # three distinct batch keys -> three engine calls
        assert sorted(backend.batch_sizes) == [1, 1, 1]
    finally:
        sched.close()


def test_max_batch_splits_oversized_bursts():
    backend = FakeBackend()
    sched = MicroBatchScheduler(backend, max_batch=4, max_wait_s=0.25)
    try:
        results, errors = _submit_concurrently(
            sched, [f"doan {i} " * 8 for i in range(10)]
        )
        assert errors == [None] * 10
        assert sum(backend.batch_sizes) == 10
        assert max(backend.batch_sizes) <= 4
    finally:
        sched.close()


# -- max-wait flush ----------------------------------------------------------


def test_lone_request_flushes_after_max_wait():
    backend = FakeBackend()
    sched = MicroBatchScheduler(backend, max_batch=64, max_wait_s=0.05)
    try:
        t0 = time.monotonic()
        c = sched.submit("mot cau don le " * 5).result(timeout=30)
        elapsed = time.monotonic() - t0
        assert c.record.batch_size == 1
        # flushed by the max-wait timer, far below any "wait for a full
        # batch" horizon; generous ceiling for slow CI hosts
        assert elapsed < 2.0
        assert c.record.queue_wait_s >= 0.0
    finally:
        sched.close()


# -- deadline shedding -------------------------------------------------------


def test_expired_deadline_is_shed_at_admission():
    sched = MicroBatchScheduler(FakeBackend(), max_batch=4, max_wait_s=0.01)
    try:
        with pytest.raises(RequestShed) as exc:
            sched.submit("qua han " * 5, deadline=time.monotonic() - 0.001)
        assert exc.value.reason is ShedReason.DEADLINE
        assert sched.metrics.snapshot().shed == {"deadline": 1}
    finally:
        sched.close()


def test_deadline_expiring_in_queue_is_shed_not_served():
    # max_batch=1 + slow engine: the first request occupies the scheduler
    # long enough for the second's deadline to expire while queued
    backend = FakeBackend(batch_overhead_s=0.15)
    sched = MicroBatchScheduler(backend, max_batch=1, max_wait_s=0.0)
    try:
        f1 = sched.submit("cham nhung den dich " * 5)
        f2 = sched.submit(
            "het han trong hang doi " * 5,
            deadline=time.monotonic() + 0.03,
        )
        assert f1.result(timeout=30).record.status == "ok"
        with pytest.raises(RequestShed) as exc:
            f2.result(timeout=30)
        assert exc.value.reason is ShedReason.DEADLINE
        # the shed request never reached the engine
        assert sum(backend.batch_sizes) == 1
        assert sched.metrics.snapshot().shed.get("deadline") == 1
    finally:
        sched.close()


# -- admission control -------------------------------------------------------


def test_queue_full_sheds_with_typed_reason():
    backend = FakeBackend(batch_overhead_s=0.2)
    sched = MicroBatchScheduler(
        backend, max_batch=1, max_wait_s=0.0, max_queue_depth=2
    )
    try:
        # wait until the scheduler has taken the first request into the
        # (slow) engine, then fill the queue behind it: the next submit
        # must shed
        futs = [sched.submit("giu cho 0 " * 5)]
        deadline = time.monotonic() + 2.0
        while sched.queue.depth > 0 and time.monotonic() < deadline:
            time.sleep(0.005)
        futs += [sched.submit(f"giu cho {i} " * 5) for i in (1, 2)]
        with pytest.raises(RequestShed) as exc:
            sched.submit("bi loai " * 5)
        assert exc.value.reason is ShedReason.QUEUE_FULL
        for f in futs:
            assert f.result(timeout=30).record.status == "ok"
        assert sched.metrics.snapshot().shed.get("queue_full") == 1
    finally:
        sched.close()


def test_token_budget_sheds_but_empty_queue_always_admits():
    backend = FakeBackend(batch_overhead_s=0.2)
    # whitespace token counting: each prompt below is 40 tokens
    sched = MicroBatchScheduler(
        backend, max_batch=1, max_wait_s=0.0, max_queued_tokens=50
    )
    try:
        big = "tu " * 40
        futs = [sched.submit(big)]  # dispatches immediately
        deadline = time.monotonic() + 2.0
        while sched.queue.depth > 0 and time.monotonic() < deadline:
            time.sleep(0.005)
        futs.append(sched.submit(big))  # empty queue admits regardless
        with pytest.raises(RequestShed) as exc:
            sched.submit(big)  # 40 queued + 40 > 50 -> shed
        assert exc.value.reason is ShedReason.TOKEN_BUDGET
        for f in futs:
            assert f.result(timeout=30).record.status == "ok"
    finally:
        sched.close()


def test_internal_fanout_bypasses_depth_budget():
    # a strategy round wider than the queue's depth budget must complete:
    # admission applies at the request level (check_admission), not to the
    # fan-out of already-admitted work
    backend = FakeBackend()
    sched = MicroBatchScheduler(
        backend, max_batch=4, max_wait_s=0.0, max_queue_depth=3
    )
    try:
        qb = sched.backend_view()
        outs = qb.generate([f"chunk {i} cua tai lieu dai " * 4 for i in range(10)])
        assert len(outs) == 10 and all(outs)
        assert sched.metrics.snapshot().shed == {}
        # the request-level gate still enforces the budget for NEW requests
        # while the queue is saturated
        sched.queue.check_admission(0)  # idle queue admits
    finally:
        sched.close()


def test_check_admission_sheds_when_queue_full():
    backend = FakeBackend(batch_overhead_s=0.2)
    sched = MicroBatchScheduler(
        backend, max_batch=1, max_wait_s=0.0, max_queue_depth=2
    )
    try:
        futs = [sched.submit("lap day 0 " * 5)]
        deadline = time.monotonic() + 2.0
        while sched.queue.depth > 0 and time.monotonic() < deadline:
            time.sleep(0.005)
        futs += [sched.submit(f"lap day {i} " * 5) for i in (1, 2)]
        with pytest.raises(RequestShed) as exc:
            sched.check_admission(10)
        assert exc.value.reason is ShedReason.QUEUE_FULL
        assert sched.metrics.snapshot().shed.get("queue_full") == 1
        for f in futs:
            f.result(timeout=30)
    finally:
        sched.close()


# -- error containment -------------------------------------------------------


def test_engine_failure_propagates_without_killing_the_scheduler():
    class Exploding(FakeBackend):
        def generate(self, prompts, **kw):
            if any("no" in p for p in prompts):
                raise RuntimeError("boom")
            return super().generate(prompts, **kw)

    sched = MicroBatchScheduler(Exploding(), max_batch=1, max_wait_s=0.0)
    try:
        bad = sched.submit("no tung ")
        with pytest.raises(RuntimeError, match="boom"):
            bad.result(timeout=30)
        # scheduler thread survived and keeps serving
        ok = sched.submit("van hoat dong " * 5).result(timeout=30)
        assert ok.record.status == "ok"
        stats = sched.metrics.snapshot()
        assert stats.errors == 1 and stats.completed == 1
    finally:
        sched.close()


def test_short_output_batch_fails_all_futures_instead_of_stranding_tail():
    class Truncating(FakeBackend):
        def generate(self, prompts, **kw):
            return super().generate(prompts, **kw)[:-1]  # drop one output

    sched = MicroBatchScheduler(Truncating(), max_batch=4, max_wait_s=0.05)
    try:
        futs = [sched.submit(f"thieu dau ra {i} " * 3) for i in range(3)]
        for f in futs:  # every future resolves (with the error) — no hangs
            with pytest.raises(RuntimeError, match="outputs for a batch"):
                f.result(timeout=30)
        # scheduler thread survived the malformed batch: the next submit is
        # still processed (and resolved, with the same typed error) — not
        # stranded behind a dead thread
        nxt = sched.submit("van song " * 4)
        with pytest.raises(RuntimeError, match="outputs for a batch"):
            nxt.result(timeout=30)
    finally:
        sched.close()


# -- graceful shutdown -------------------------------------------------------


def test_close_drains_queued_requests():
    backend = FakeBackend(batch_overhead_s=0.05)
    sched = MicroBatchScheduler(backend, max_batch=1, max_wait_s=0.0)
    futs = [sched.submit(f"thoat em dem {i} " * 5) for i in range(4)]
    sched.close(drain=True)
    # every admitted request completed (none shed), scheduler thread gone
    for f in futs:
        assert f.result(timeout=1).record.status == "ok"
    assert sum(backend.batch_sizes) == 4
    assert not sched._thread.is_alive()
    # post-close submissions shed with the typed SHUTDOWN reason
    with pytest.raises(RequestShed) as exc:
        sched.submit("den muon ")
    assert exc.value.reason is ShedReason.SHUTDOWN


def test_close_without_drain_sheds_pending():
    backend = FakeBackend(batch_overhead_s=0.1)
    sched = MicroBatchScheduler(backend, max_batch=1, max_wait_s=0.0)
    futs = [sched.submit(f"huy bo {i} " * 5) for i in range(3)]
    sched.close(drain=False)
    outcomes = []
    for f in futs:
        try:
            outcomes.append(f.result(timeout=1).record.status)
        except RequestShed as e:
            outcomes.append(e.reason.value)
    # the in-flight batch may finish; everything still queued is shed
    assert "shutdown" in outcomes
    assert sched.metrics.snapshot().shed.get("shutdown", 0) >= 1


# -- queue unit behavior -----------------------------------------------------


def test_request_queue_batch_key_and_fifo():
    q = RequestQueue(max_depth=8)
    a = ServeRequest(prompt="a", max_new_tokens=32)
    b = ServeRequest(prompt="b", max_new_tokens=32)
    c = ServeRequest(prompt="c", max_new_tokens=64)
    for r in (a, b, c):
        q.submit(r)
    batch = q.take_batch(max_batch=8, max_wait_s=0.0)
    # head-of-line key wins; the incompatible request stays queued
    assert [r.prompt for r in batch] == ["a", "b"]
    assert q.depth == 1
    assert q.take_batch(max_batch=8, max_wait_s=0.0)[0].prompt == "c"


def test_metrics_prometheus_rendering():
    sched = MicroBatchScheduler(FakeBackend(), max_batch=2, max_wait_s=0.01)
    try:
        sched.submit("do dac " * 5).result(timeout=30)
        text = sched.metrics.render_prometheus(queue_depth=0, queued_tokens=0)
    finally:
        sched.close()
    assert "vnsum_serve_requests_total 1" in text
    assert "vnsum_serve_requests_completed_total 1" in text
    assert 'vnsum_serve_requests_shed_total{reason="deadline"} 0' in text
    assert "vnsum_serve_batches_total 1" in text
    assert "vnsum_serve_queue_wait_seconds_bucket" in text
    assert "vnsum_serve_queue_depth 0" in text
