"""Lint-framework acceptance: per-rule fixtures (positive + negative +
suppression), suppression hygiene, the bidirectional metrics-doc rule over
a fixture tree, a seeded lock-order inversion the runtime detector must
catch, and the self-check — the CLI must exit 0 over this repo itself
(every suppression in the codebase carries a written reason)."""
from __future__ import annotations

import json
import subprocess
import sys
import textwrap
import threading
from pathlib import Path

import pytest

from vnsum_tpu.analysis import sanitizers
from vnsum_tpu.analysis.core import run_paths

REPO_ROOT = Path(__file__).resolve().parents[1]


def lint(tmp_path, src: str, rules=None):
    f = tmp_path / "snippet.py"
    f.write_text(textwrap.dedent(src), encoding="utf-8")
    return run_paths([f], root=tmp_path, rules=rules)


# -- guarded-by --------------------------------------------------------------


GUARDED_SRC = """
    import threading

    class Box:
        def __init__(self):
            self._lock = threading.Lock()
            self.items = []  # guarded by: _lock

        def good(self):
            with self._lock:
                self.items.append(1)

        def bad(self):
            self.items.append(2)

        def _drain_locked(self):
            # *_locked convention: caller holds the lock
            return len(self.items)
"""


def test_guarded_by_flags_unlocked_access_only(tmp_path):
    findings = lint(tmp_path, GUARDED_SRC, rules=["guarded-by"])
    assert len(findings) == 1
    assert findings[0].rule == "guarded-by"
    assert "bad" in findings[0].message and "items" in findings[0].message


def test_guarded_by_suppression_with_reason_clears(tmp_path):
    src = GUARDED_SRC.replace(
        "self.items.append(2)",
        "self.items.append(2)  # lint-allow[guarded-by]: "
        "single-writer fixture, lock not needed",
    )
    assert lint(tmp_path, src, rules=["guarded-by"]) == []


def test_guarded_by_accepts_lock_aliases(tmp_path):
    src = """
        import threading

        class Q:
            def __init__(self):
                self._lock = threading.Lock()
                self._cond = threading.Condition(self._lock)
                self.items = []  # guarded by: _cond, _lock

            def via_cond(self):
                with self._cond:
                    self.items.append(1)

            def via_lock(self):
                with self._lock:
                    return len(self.items)
    """
    assert lint(tmp_path, src, rules=["guarded-by"]) == []


# -- host-sync-in-hot-path ---------------------------------------------------


def test_host_sync_flags_only_hot_functions(tmp_path):
    src = """
        import numpy as np

        # hot path
        def decode_loop(x):
            y = x.block_until_ready()
            return np.asarray(y), x.item()

        def cold(x):
            return np.asarray(x)
    """
    findings = lint(tmp_path, src, rules=["host-sync-in-hot-path"])
    assert len(findings) == 3  # block_until_ready + np.asarray + .item
    assert all("decode_loop" in f.message for f in findings)


def test_host_sync_suppression_needs_reason(tmp_path):
    src = """
        import numpy as np

        # hot path
        def decode_loop(x):
            # lint-allow[host-sync-in-hot-path]: fetch is the loop's exit condition
            return np.asarray(x)
    """
    assert lint(tmp_path, src) == []
    bare = src.replace(": fetch is the loop's exit condition", ":")
    findings = lint(tmp_path, bare)
    rules = {f.rule for f in findings}
    # the un-reasoned suppression no longer silences, AND is itself flagged
    assert rules == {"host-sync-in-hot-path", "suppression"}


def test_suppression_hygiene_unknown_rule(tmp_path):
    findings = lint(tmp_path, "x = 1  # lint-allow[not-a-rule]: because\n")
    assert [f.rule for f in findings] == ["suppression"]
    assert "unknown rule" in findings[0].message


# -- donation-safety ---------------------------------------------------------


def test_donation_flags_reuse_after_donate(tmp_path):
    src = """
        import jax

        def step(c):
            return c

        def run(cache):
            fn = jax.jit(step, donate_argnums=(0,))
            out = fn(cache)
            return cache.sum() + out
    """
    findings = lint(tmp_path, src, rules=["donation-safety"])
    assert len(findings) == 1
    assert "'cache'" in findings[0].message


def test_donation_rebinding_from_results_is_safe(tmp_path):
    src = """
        import jax

        def step(c):
            return c

        def run(cache):
            fn = jax.jit(step, donate_argnums=(0,))
            cache = fn(cache)
            return cache.sum()
    """
    assert lint(tmp_path, src, rules=["donation-safety"]) == []


# -- jit-recompile-hazard ----------------------------------------------------


def test_recompile_flags_branch_on_traced_arg(tmp_path):
    src = """
        import jax

        @jax.jit
        def f(a, b):
            if a > 0:
                return b
            return -b
    """
    findings = lint(tmp_path, src, rules=["jit-recompile-hazard"])
    assert len(findings) == 1
    assert "'a'" in findings[0].message


def test_recompile_allows_is_none_and_statics(tmp_path):
    src = """
        import jax

        def f(a, cache):
            if cache is None:
                cache = a
            return cache

        def g(a, n):
            if n > 0:
                return a
            return -a

        ff = jax.jit(f)
        gg = jax.jit(g, static_argnums=(1,))
    """
    assert lint(tmp_path, src, rules=["jit-recompile-hazard"]) == []


def test_recompile_flags_fstring_in_jitted_fn(tmp_path):
    src = """
        import jax
        from functools import partial

        @partial(jax.jit, static_argnums=(1,))
        def f(a, n):
            name = f"step-{n}"
            return a
    """
    findings = lint(tmp_path, src, rules=["jit-recompile-hazard"])
    assert len(findings) == 1
    assert "f-string" in findings[0].message


# -- metrics-doc (project rule) ----------------------------------------------


def _metrics_tree(tmp_path, readme: str) -> Path:
    serve = tmp_path / "vnsum_tpu" / "serve"
    serve.mkdir(parents=True)
    (serve / "metrics.py").write_text(textwrap.dedent("""
        _reg("a_total", "counter", "a")
        _reg("lat_seconds", "histogram", "latency")
    """), encoding="utf-8")
    (tmp_path / "README.md").write_text(readme, encoding="utf-8")
    return tmp_path


def test_metrics_doc_bidirectional(tmp_path):
    root = _metrics_tree(
        tmp_path,
        "| vnsum_serve_a_total | vnsum_serve_lat_seconds_bucket |"
        " vnsum_serve_ghost_total |",
    )
    findings = run_paths([], root=root, rules=["metrics-doc"])
    # a_total documented; histogram's _bucket series satisfies lat_seconds;
    # ghost_total exists only in the README -> exactly one finding
    assert len(findings) == 1
    assert "ghost_total" in findings[0].message and "README" in findings[0].path


def test_metrics_doc_missing_registration_direction(tmp_path):
    root = _metrics_tree(tmp_path, "| vnsum_serve_a_total |")
    findings = run_paths([], root=root, rules=["metrics-doc"])
    assert len(findings) == 1
    assert "lat_seconds" in findings[0].message
    assert findings[0].path.endswith("metrics.py")


# -- lock-order detector (seeded inversion) ----------------------------------


def test_lock_order_detector_catches_seeded_inversion(monkeypatch):
    monkeypatch.setenv("VNSUM_SANITIZERS", "lock")
    sanitizers.lock_graph().reset()
    try:
        a = sanitizers.make_lock("fixture.A")
        b = sanitizers.make_lock("fixture.B")
        assert isinstance(a, sanitizers.TrackedLock)

        def worker():  # thread 1 teaches the graph A -> B
            with a:
                with b:
                    pass

        t = threading.Thread(target=worker)
        t.start()
        t.join()
        # thread 2 (here) attempts B -> A: the inverse ordering must raise
        # at the acquisition that would introduce the deadlock — no actual
        # interleaving/hang is needed for detection
        with pytest.raises(sanitizers.LockOrderError):
            with b:
                with a:
                    pass
        assert sanitizers.lock_order_violations()
        # one inconsistent ordering reports once, not forever: the edge was
        # recorded, so replaying the same order proceeds without raising
        with b:
            with a:
                pass
    finally:
        sanitizers.lock_graph().reset()


def test_lock_order_trylock_records_no_edges(monkeypatch):
    monkeypatch.setenv("VNSUM_SANITIZERS", "lock")
    sanitizers.lock_graph().reset()
    try:
        a = sanitizers.make_lock("fixture.C")
        b = sanitizers.make_lock("fixture.D")
        with a:
            assert b.acquire(blocking=False)
            b.release()
        assert sanitizers.lock_graph().edges() == {}
    finally:
        sanitizers.lock_graph().reset()


# -- CLI / self-check --------------------------------------------------------


def test_cli_json_output_and_exit_code(tmp_path):
    (tmp_path / "snippet.py").write_text(textwrap.dedent("""
        import numpy as np

        # hot path
        def decode_loop(x):
            return np.asarray(x)
    """), encoding="utf-8")
    proc = subprocess.run(
        [sys.executable, "-m", "vnsum_tpu.analysis", "--json",
         "--root", str(tmp_path), str(tmp_path)],
        capture_output=True, text=True, cwd=REPO_ROOT,
    )
    assert proc.returncode == 1
    findings = json.loads(proc.stdout)
    assert findings[0]["rule"] == "host-sync-in-hot-path"


def test_cli_fails_loudly_on_bad_path(tmp_path):
    """A typo'd path must exit 2 with an error, never 'ok: no findings' —
    otherwise a renamed directory silently turns the CI gate vacuous."""
    proc = subprocess.run(
        [sys.executable, "-m", "vnsum_tpu.analysis", "does_not_exist"],
        capture_output=True, text=True, cwd=REPO_ROOT,
    )
    assert proc.returncode == 2
    assert "does_not_exist" in proc.stderr


def test_repo_is_clean_under_its_own_lint():
    """Acceptance: `python -m vnsum_tpu.analysis vnsum_tpu/ scripts/` exits
    0 on this repo — every annotation holds and every suppression carries a
    written reason."""
    proc = subprocess.run(
        [sys.executable, "-m", "vnsum_tpu.analysis", "vnsum_tpu", "scripts"],
        capture_output=True, text=True, cwd=REPO_ROOT,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


# -- swallowed-exception -----------------------------------------------------


def _serve_lint(tmp_path, src: str):
    """The swallowed-exception rule is scoped to vnsum_tpu/{serve,backend}/ —
    fixtures must live on such a path to be checked at all."""
    d = tmp_path / "vnsum_tpu" / "serve"
    d.mkdir(parents=True, exist_ok=True)
    f = d / "snippet.py"
    f.write_text(textwrap.dedent(src), encoding="utf-8")
    return run_paths([f], root=tmp_path, rules=["swallowed-exception"])


SWALLOWED_SRC = """
    def handler(req, logger):
        try:
            dispatch(req)
        except Exception:
            logger.exception("oops")   # swallowed: future never resolves
"""


def test_swallowed_exception_flags_log_and_continue(tmp_path):
    findings = _serve_lint(tmp_path, SWALLOWED_SRC)
    assert len(findings) == 1
    assert findings[0].rule == "swallowed-exception"


def test_swallowed_exception_accepts_resolution_forms(tmp_path):
    findings = _serve_lint(tmp_path, """
        def a(req):
            try:
                dispatch(req)
            except Exception as e:
                req.future.set_exception(e)       # resolves the future

        def b(req):
            try:
                dispatch(req)
            except Exception:
                raise                              # re-raises

        def c(self, req):
            try:
                dispatch(req)
            except Exception as e:
                self._resolve_errored([req], e)    # resolver-helper convention

        def d(self):
            try:
                return primary()
            except TypeError:
                return fallback()                  # explicit fallback value

        def e(self, req):
            try:
                dispatch(req)
            except Exception as exc:
                self._json({"error": str(exc)}, 500)  # HTTP layer answers
    """)
    assert findings == []


def test_swallowed_exception_suppression_and_scope(tmp_path):
    # a reasoned lint-allow clears it
    findings = _serve_lint(tmp_path, """
        def handler(req, logger):
            try:
                dispatch(req)
            # lint-allow[swallowed-exception]: nothing was taken, nothing to resolve
            except Exception:
                logger.exception("oops")
    """)
    assert findings == []
    # outside serve/ and backend/, the same code is out of scope
    f = tmp_path / "other.py"
    f.write_text(textwrap.dedent(SWALLOWED_SRC), encoding="utf-8")
    assert run_paths([f], root=tmp_path,
                     rules=["swallowed-exception"]) == []


# -- metric-label-cardinality ------------------------------------------------


LABEL_SRC = """
    def render(self, lines, tenant, registry):
        lines.append(f'x_total{{tenant="{tenant}"}} 1')            # raw: flagged
        lines.append(f'y_total{{tenant="{registry.canonical(tenant)}"}} 1')
        for stage in ("queued", "resident"):
            lines.append(f'z_total{{stage="{stage}"}} 1')          # literal loop: fine
        for reason in SomeEnum:
            lines.append(f'w_total{{reason="{reason.value}"}} 1')  # enum .value: fine
        lines.append(f'plain interpolation with no label {tenant}')
"""


def test_label_cardinality_flags_raw_dynamic_label_only(tmp_path):
    findings = _serve_lint_rule(tmp_path, LABEL_SRC,
                                ["metric-label-cardinality"])
    assert len(findings) == 1
    assert findings[0].rule == "metric-label-cardinality"
    assert 'tenant="..."' in findings[0].message
    assert "canonical" in findings[0].message


def test_label_cardinality_scoped_to_serve(tmp_path):
    # the same raw emission outside vnsum_tpu/serve/ is out of scope
    f = tmp_path / "vnsum_tpu" / "obs" / "snippet.py"
    f.parent.mkdir(parents=True, exist_ok=True)
    f.write_text(textwrap.dedent(LABEL_SRC), encoding="utf-8")
    assert run_paths([f], root=tmp_path,
                     rules=["metric-label-cardinality"]) == []


def test_label_cardinality_suppression_with_reason_clears(tmp_path):
    src = LABEL_SRC.replace(
        "lines.append(f'x_total{{tenant=\"{tenant}\"}} 1')",
        "# lint-allow[metric-label-cardinality]: fixture set is bounded\n"
        "        lines.append(f'x_total{{tenant=\"{tenant}\"}} 1')",
    )
    assert _serve_lint_rule(tmp_path, src,
                            ["metric-label-cardinality"]) == []


WORKER_LABEL_SRC = """
    def render(self, lines, name, registry):
        lines.append(f'a_total{{worker="{registry.canonical(name)}"}} 1')
        lines.append(f'b_total{{worker="{canonical(name)}"}} 1')
        lines.append(f'c_total{{worker="{name}"}} 1')              # raw: flagged
        for worker in SomeEnum:
            lines.append(f'd_total{{worker="{worker.value}"}} 1')  # enum: flagged
        for wname in ("w0", "w1"):
            lines.append(f'e_total{{worker="{wname}"}} 1')         # loop: flagged
"""


def test_label_cardinality_worker_requires_canonical_call(tmp_path):
    """Fleet worker= labels are held to the STRICT form: only a
    canonical(...) call on the roster registry proves the emission agrees
    with the bounded worker set — the enum and literal-loop escapes that
    clear other labels do NOT clear worker=."""
    findings = _serve_lint_rule(tmp_path, WORKER_LABEL_SRC,
                                ["metric-label-cardinality"])
    assert len(findings) == 3
    assert all('worker="..."' in f.message for f in findings)
    assert all("worker-roster" in f.message for f in findings)
    flagged_lines = sorted(f.line for f in findings)
    src_lines = textwrap.dedent(WORKER_LABEL_SRC).splitlines()
    assert ["c_total", "d_total", "e_total"] == [
        next(tok for tok in ("a_total", "b_total", "c_total",
                             "d_total", "e_total")
             if tok in src_lines[ln - 1])
        for ln in flagged_lines
    ]


def test_label_cardinality_worker_canonical_forms_clear(tmp_path):
    src = """
    def render(self, lines, rows, registry):
        for r in rows:
            name = r["name"]
            lines.append(
                f'up{{worker="{registry.canonical(name, touch=False)}"}} 1'
            )
    """
    assert _serve_lint_rule(tmp_path, src,
                            ["metric-label-cardinality"]) == []


def _serve_lint_rule(tmp_path, src: str, rules):
    d = tmp_path / "vnsum_tpu" / "serve"
    d.mkdir(parents=True, exist_ok=True)
    f = d / "snippet.py"
    f.write_text(textwrap.dedent(src), encoding="utf-8")
    return run_paths([f], root=tmp_path, rules=rules)


# -- unbounded-blocking-wait --------------------------------------------------


UNBOUNDED_WAIT_SRC = """
    def loop(self, cond, ev, fut, q, d):
        cond.wait()                      # flagged: timeout-less Condition
        ev.wait()                        # flagged: timeout-less Event
        fut.result()                     # flagged: timeout-less Future
        q.get()                          # flagged: blocking Queue.get
        cond.wait(timeout=0.1)           # bounded: fine
        ev.wait(2.0)                     # positional timeout: fine
        fut.result(timeout=5)            # bounded: fine
        q.get(timeout=1.0)               # bounded: fine
        d.get("key")                     # dict.get with args: never matches
        d.get("key", None)               # ditto
        fut.result(timeout=None)         # spelled-out unboundedness: flagged
        ev.wait(None)                    # positional None: flagged too
"""


def test_unbounded_wait_flags_every_timeoutless_primitive(tmp_path):
    findings = _serve_lint_rule(tmp_path, UNBOUNDED_WAIT_SRC,
                                ["unbounded-blocking-wait"])
    assert len(findings) == 6
    assert {f.rule for f in findings} == {"unbounded-blocking-wait"}
    # one finding per offending line, in order: wait/wait/result/get and
    # the two spelled-out Nones (keyword and positional) at the end
    assert [f.line for f in findings] == [3, 4, 5, 6, 13, 14]


def test_unbounded_wait_scoped_to_serve(tmp_path):
    # the same code under backend/ (or anywhere else) is out of scope —
    # backends block inside device runtimes the lint cannot see anyway
    f = tmp_path / "vnsum_tpu" / "backend" / "snippet.py"
    f.parent.mkdir(parents=True, exist_ok=True)
    f.write_text(textwrap.dedent(UNBOUNDED_WAIT_SRC), encoding="utf-8")
    assert run_paths([f], root=tmp_path,
                     rules=["unbounded-blocking-wait"]) == []


def test_unbounded_wait_suppression_with_reason_clears(tmp_path):
    findings = _serve_lint_rule(tmp_path, """
        def handler(self, fut):
            # lint-allow[unbounded-blocking-wait]: request futures are resolved by every scheduler path
            return fut.result()
    """, ["unbounded-blocking-wait"])
    assert findings == []


# -- durable-write -----------------------------------------------------------


DURABLE_GOOD = """
    import os
    import tempfile

    # durable
    def atomic_write(path, text):
        fd, tmp = tempfile.mkstemp(dir=".")
        with os.fdopen(fd, "w") as f:
            f.write(text)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
"""

DURABLE_MISSING_FSYNC = """
    import os
    import tempfile

    def caller(path, text):  # unmarked helper: not checked
        open(path, "w").write(text)

    # durable
    def sloppy_write(path, text):
        fd, tmp = tempfile.mkstemp(dir=".")
        with os.fdopen(fd, "w") as f:
            f.write(text)
            f.flush()
        os.replace(tmp, path)
"""

DURABLE_APPEND_ONLY = """
    import os

    # durable: compaction-style rewrite
    def rewrite(path, lines):
        with open(path + ".tmp", "wb") as f:
            f.writelines(lines)
            f.flush()
            os.fsync(f.fileno())
        os.replace(path + ".tmp", path)

    def plain_append(f, line):  # no marker, no sequence required
        f.write(line)
"""


def test_durable_write_full_sequence_is_clean(tmp_path):
    assert lint(tmp_path, DURABLE_GOOD, rules=["durable-write"]) == []
    assert lint(tmp_path, DURABLE_APPEND_ONLY, rules=["durable-write"]) == []


def test_durable_write_flags_missing_op_and_names_it(tmp_path):
    findings = lint(tmp_path, DURABLE_MISSING_FSYNC, rules=["durable-write"])
    assert len(findings) == 1
    assert findings[0].rule == "durable-write"
    assert "sloppy_write" in findings[0].message
    assert "fsync" in findings[0].message
    # the unmarked sloppy caller is out of scope by design
    assert "caller" not in findings[0].message


def test_durable_write_suppression_with_reason_clears(tmp_path):
    src = DURABLE_MISSING_FSYNC.replace(
        "# durable",
        "# durable\n    # lint-allow[durable-write]: fixture exercises suppression",
    )
    assert lint(tmp_path, src, rules=["durable-write"]) == []


def test_durable_write_marker_must_be_the_word(tmp_path):
    # prose that merely mentions durability must not arm the check
    src = """
    def notes():
        # durability is handled by the caller via atomic_write
        return 1
    """
    assert lint(tmp_path, src, rules=["durable-write"]) == []


# -- device-pinning ----------------------------------------------------------


DEVICE_PIN_SRC = """
    import jax

    def place(x, mesh, sharding):
        d = jax.devices()[0]                 # hard pin
        y = jax.device_put(x)                # implicit default device
        ok1 = jax.device_put(x, sharding)    # explicit placement: fine
        ok2 = jax.device_put(x, device=d)    # explicit device kw: fine
        ok3 = jax.devices()                  # enumeration alone: fine
        return y, ok1, ok2, ok3
"""


def _lint_at(tmp_path, rel: str, src: str, rules=None):
    f = tmp_path / rel
    f.parent.mkdir(parents=True, exist_ok=True)
    f.write_text(textwrap.dedent(src), encoding="utf-8")
    return run_paths([f], root=tmp_path, rules=rules)


def test_device_pinning_flags_pin_and_bare_device_put(tmp_path):
    findings = _lint_at(
        tmp_path, "backend/snippet.py", DEVICE_PIN_SRC,
        rules=["device-pinning"],
    )
    assert len(findings) == 2
    assert {"device-pinning"} == {f.rule for f in findings}
    msgs = " ".join(f.message for f in findings)
    assert "hard-pins" in msgs and "default device" in msgs


def test_device_pinning_scoped_to_backend_and_cache(tmp_path):
    # cache/ is in scope; parallel/ (mesh construction) is not
    assert len(_lint_at(
        tmp_path, "cache/snippet.py", DEVICE_PIN_SRC,
        rules=["device-pinning"],
    )) == 2
    assert _lint_at(
        tmp_path, "parallel/snippet.py", DEVICE_PIN_SRC,
        rules=["device-pinning"],
    ) == []


def test_device_pinning_suppression_with_reason_clears(tmp_path):
    src = DEVICE_PIN_SRC.replace(
        "d = jax.devices()[0]",
        "# lint-allow[device-pinning]: fixture pins deliberately\n"
        "        d = jax.devices()[0]",
    ).replace(
        "y = jax.device_put(x)",
        "# lint-allow[device-pinning]: fixture places deliberately\n"
        "        y = jax.device_put(x)",
    )
    assert _lint_at(
        tmp_path, "backend/snippet.py", src, rules=["device-pinning"]
    ) == []
