"""CI smoke of scripts/repro_quality_gate.py (VERDICT r3 #7): the pinned
quality-gate kit must run the full pipeline on a fake backend and diff our
summary_statistics field-for-field against the reference results schema —
including llm_scores via a local Backend-protocol judge (VERDICT r3 #8)."""
import importlib.util
import json
import pathlib

import pytest

from vnsum_tpu.data.synthesize import synthesize_corpus

_SCRIPT = (
    pathlib.Path(__file__).resolve().parent.parent
    / "scripts" / "repro_quality_gate.py"
)
spec = importlib.util.spec_from_file_location("repro_quality_gate", _SCRIPT)
repro = importlib.util.module_from_spec(spec)
spec.loader.exec_module(repro)

# the reference gate file's exact summary_statistics schema
# (evaluation_results/first_dataset/mapreduce/llama3_2_3b_results.json)
REF_STATS = {
    "semantic_similarity": {"mean": 0.82, "std": 0.05, "min": 0.60, "max": 0.91},
    "rouge_scores": {
        "rouge1_f1": 0.6713, "rouge2_f1": 0.3480, "rougeL_f1": 0.3053,
    },
    "bert_scores": {
        "bert_precision": 0.687, "bert_recall": 0.684, "bert_f1": 0.685,
    },
    "llm_scores": {
        "llm_correctness_mean": 0.23, "llm_correctness_std": 0.09,
        "llm_correctness_min": 0.0, "llm_correctness_max": 0.5,
        "llm_coherence_mean": 0.69, "llm_coherence_std": 0.12,
        "llm_coherence_min": 0.0, "llm_coherence_max": 0.8,
        "llm_successful_cases": 151, "llm_failed_cases": 0,
        "llm_total_cases_processed": 151,
    },
}


def test_repro_gate_fake_backend_schema_parity(tmp_path, capsys):
    synthesize_corpus(
        f"{tmp_path}/c", n_docs=3, tokens_per_doc=300, summary_tokens=40,
        seed=5,
    )
    ref = tmp_path / "reference_results.json"
    ref.write_text(json.dumps({"summary_statistics": REF_STATS}))

    rc = repro.main([
        "--docs-dir", f"{tmp_path}/c/doc",
        "--summary-dir", f"{tmp_path}/c/summary",
        "--backend", "fake",
        "--preset", "law",
        "--judge-backend", "fake",
        "--reference-json", str(ref),
        "--out", f"{tmp_path}/out",
    ])
    verdict = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0, verdict
    assert verdict["ok"] and verdict["diff"]["schema_ok"], verdict
    assert verdict["diff"]["missing_fields"] == []
    # llm column flowed end to end through the local Backend judge
    stats = verdict["summary_statistics"]
    assert stats["llm_scores"]["llm_successful_cases"] == 3
    assert stats["llm_scores"]["llm_failed_cases"] == 0
    # deltas recorded for every numeric reference field
    assert "rouge_scores.rougeL_f1" in verdict["diff"]["metric_deltas"]


def test_repro_gate_requires_weights_for_tpu(tmp_path):
    with pytest.raises(SystemExit):
        repro.main([
            "--docs-dir", "x", "--summary-dir", "y", "--backend", "tpu",
        ])


def test_schema_diff_flags_missing_and_extra():
    ref = {"a": {"b": 1.0, "c": 2.0}}
    ours = {"a": {"b": 1.5, "d": 9}}
    d = repro.schema_diff(ref, ours)
    assert not d["schema_ok"]
    assert d["missing_fields"] == ["a.c"]
    assert d["extra_fields"] == ["a.d"]
    assert d["metric_deltas"]["a.b"]["delta"] == 0.5
