"""The committed real-Vietnamese fixture (data/vi_eval) through the full
quality chain (VERDICT r4 #8, closing the C15 partial).

Synthetic corpora exercise shapes, not language: uniform word lengths, no
diacritics, no real compression ratios. These tests run the actual pipeline
(split → summarize → ROUGE/semantic → report) over six hand-written
Vietnamese document/summary pairs, and pin the Unicode behaviors the chain
depends on (diacritics surviving the splitter and byte tokenizer, the ROUGE
tokenizer keeping Vietnamese letters whole — rouge_score parity).
"""
from __future__ import annotations

import json
import unicodedata
from pathlib import Path

import pytest

from vnsum_tpu.core import PipelineConfig
from vnsum_tpu.eval import EmbeddingModel
from vnsum_tpu.eval.rouge import RougeScorer, tokenize
from vnsum_tpu.models.encoder import tiny_encoder
from vnsum_tpu.pipeline.runner import PipelineRunner
from vnsum_tpu.text.splitter import RecursiveTokenSplitter
from vnsum_tpu.text.tokenizer import ByteTokenizer, whitespace_token_count

FIXTURE = Path(__file__).resolve().parent.parent / "data" / "vi_eval"
DOC_NAMES = sorted(p.name for p in (FIXTURE / "doc").glob("*.txt"))


def test_fixture_shape():
    """Six committed pairs, matched by filename, with real length contrast
    (docs several-hundred words, summaries a ~4-8x compression)."""
    assert len(DOC_NAMES) >= 6
    for name in DOC_NAMES:
        doc = (FIXTURE / "doc" / name).read_text(encoding="utf-8")
        ref = (FIXTURE / "summary" / name).read_text(encoding="utf-8")
        d, r = whitespace_token_count(doc), whitespace_token_count(ref)
        assert d >= 300, (name, d)
        assert 40 <= r <= d // 2, (name, r)


def test_diacritics_survive_splitter_and_byte_tokenizer():
    doc = (FIXTURE / "doc" / DOC_NAMES[0]).read_text(encoding="utf-8")
    splitter = RecursiveTokenSplitter(400, 40, length_function=len)
    chunks = splitter.split_text(doc)
    assert len(chunks) > 1
    # every chunk round-trips the byte tokenizer losslessly (NFC preserved)
    tok = ByteTokenizer()
    for c in chunks:
        assert tok.decode(tok.encode(c)) == c
    # splitting must not orphan combining marks: recombined text contains
    # the same NFC codepoint multiset as the original (minus nothing)
    joined = "".join(chunks)
    assert set(unicodedata.normalize("NFC", joined)) == set(
        unicodedata.normalize("NFC", doc)
    )


def test_rouge_vietnamese_tokenization_modes():
    """Default = rouge_score parity: the ASCII-only tokenizer strips
    diacritic codepoints, shredding Vietnamese words — exactly what the
    reference's rouge_score numbers are computed on, so it must stay.
    keep_unicode=True scores whole Vietnamese words instead."""
    text = "Tóm tắt nội dung chuyển đổi số ở Việt Nam"
    parity = tokenize(text, use_stemmer=False)
    assert "tóm" not in parity and "dung" in parity  # ASCII fragments only

    uni = tokenize(text, use_stemmer=False, keep_unicode=True)
    assert uni[:2] == ["tóm", "tắt"] and "việt" in uni
    # NFD input (combining marks) must tokenize identically — \w does not
    # match Mn, so without NFC normalization NFD text would shred
    nfd = unicodedata.normalize("NFD", text)
    assert tokenize(nfd, use_stemmer=False, keep_unicode=True) == uni

    # both modes: identical Vietnamese text scores 1.0 against itself, and
    # keep_unicode separates near-words parity would conflate
    for kw in (False, True):
        scorer = RougeScorer(["rouge1"], keep_unicode=kw)
        s = scorer.score("tóm tắt tiếng việt", "tóm tắt tiếng việt")
        assert s["rouge1"].fmeasure == 1.0
    a, b = "bán", "bàn"  # distinct words, same ASCII skeleton "b n"
    assert RougeScorer(["rouge1"]).score(a, b)["rouge1"].fmeasure == 1.0
    assert (
        RougeScorer(["rouge1"], keep_unicode=True).score(a, b)["rouge1"].fmeasure
        == 0.0
    )
    # native path refuses keep_unicode explicitly (ASCII tokenizer in C++)
    with pytest.raises(ValueError):
        RougeScorer(["rouge1"], use_native=True, keep_unicode=True)


def test_pipeline_over_vi_eval(tmp_path):
    """Full run over the committed fixture: every doc summarized, ROUGE and
    semantic columns populated, per-doc results persisted, report renders."""
    cfg = PipelineConfig(
        approach="mapreduce",
        models=["fake-model"],
        backend="fake",
        docs_dir=str(FIXTURE / "doc"),
        summary_dir=str(FIXTURE / "summary"),
        generated_summaries_dir=str(tmp_path / "gen"),
        results_dir=str(tmp_path / "results"),
        logs_dir=str(tmp_path / "logs"),
        chunk_size=150,
        chunk_overlap=20,
        token_max=120,
        batch_size=4,
    )
    runner = PipelineRunner(
        cfg,
        embedding_model=EmbeddingModel(
            config=tiny_encoder(), max_len=64, batch_size=4
        ),
    )
    results = runner.run()
    rec = results.summarization["fake-model"]
    assert rec["successful"] == len(DOC_NAMES) and rec["failed"] == 0
    assert rec["total_chunks"] > len(DOC_NAMES)  # real docs actually split

    ev = results.evaluation["fake-model"]
    r1 = ev["rouge_scores"]["rouge1_f1"]
    # extractive fake summaries over REAL text share vocabulary with the
    # hand-written references — ROUGE-1 must clear a language-level floor
    # (synthetic bytes score ~0 here), and generated files must keep their
    # diacritics
    assert r1 > 0.1, ev["rouge_scores"]
    out_dir = Path(f"{cfg.generated_summaries_dir}_mapreduce_fake-model")
    gen0 = (out_dir / DOC_NAMES[0]).read_text(encoding="utf-8")
    assert any(ord(ch) > 127 for ch in gen0)  # diacritics intact end-to-end

    per_model = Path(cfg.results_dir) / "fake-model_results.json"
    data = json.loads(per_model.read_text())
    assert len(data["detailed_results"]) == len(DOC_NAMES)
    assert "rouge1/2/L" in runner.report()
