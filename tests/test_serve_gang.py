"""Structured jobs (serve/gang.py): gang admission + membership journal,
the queue's gang-affinity pick (the bench A/B lever), per-phase progress on
the poll surface, POISON-degraded partial results, gang-cancel mid-reduce,
journal replay of a half-finished gang, and whole-gang preemption with
byte-identity — the group-level contracts ISSUE 17 adds on top of the
``trace_id#N`` fan-out ledger."""
from __future__ import annotations

import http.client
import json
import threading
import time
import urllib.parse

import pytest

from vnsum_tpu.backend.fake import FakeBackend
from vnsum_tpu.serve import (
    EngineSupervisor,
    InflightScheduler,
    MicroBatchScheduler,
    RetryPolicy,
    TenantTable,
    parse_tenant_specs,
)
from vnsum_tpu.serve.gang import GangRegistry
from vnsum_tpu.serve.journal import RequestJournal, aggregate_status
from vnsum_tpu.serve.queue import RequestCancelled, RequestQueue, ServeRequest
from vnsum_tpu.serve.scheduler import QueuedBackend
from vnsum_tpu.serve.server import ServeState, make_server
from vnsum_tpu.testing.faults import FaultPlan, FaultSpec, injected

FAST = RetryPolicy(max_attempts=2, backoff_base_s=0.005, backoff_max_s=0.05,
                   jitter=0.0)


def wait_for(pred, timeout_s: float = 15.0, interval_s: float = 0.01):
    t_end = time.monotonic() + timeout_s
    while time.monotonic() < t_end:
        if pred():
            return True
        time.sleep(interval_s)
    return pred()


def _req(base, method, path, payload=None, headers=None):
    u = urllib.parse.urlparse(base)
    conn = http.client.HTTPConnection(u.hostname, u.port, timeout=60)
    try:
        body = json.dumps(payload) if payload is not None else None
        conn.request(method, path, body=body,
                     headers={"Content-Type": "application/json",
                              **(headers or {})})
        resp = conn.getresponse()
        raw = resp.read()
        return resp.status, json.loads(raw) if raw else None
    finally:
        conn.close()


def _serve(tmp_path, **kw):
    kw.setdefault("max_batch", 8)
    kw.setdefault("max_wait_s", 0.005)
    kw.setdefault("journal_dir", str(tmp_path / "journal"))
    state = ServeState(FakeBackend(), **kw)
    server = make_server(state, "127.0.0.1", 0)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    return f"http://127.0.0.1:{server.server_address[1]}", state, server


# big enough that the mapreduce splitter yields SEVERAL map chunks under
# the default chunk budget (12000 whitespace tokens on the FakeBackend) —
# the tests below assert a real fan-out, not a single-chunk degenerate case
DOC = "\n\n".join(
    f"Đoạn {i}: " + "nội dung dài cần tóm tắt kỹ lưỡng. " * 200
    for i in range(18)
)


# -- registry lifecycle -------------------------------------------------------


def test_gang_registry_lifecycle_and_journal_roundtrip(tmp_path):
    j = RequestJournal(tmp_path)
    reg = GangRegistry(journal=j)
    h = reg.open("g1", tenant="acme")
    reg.open("g1")  # idempotent: a retry rejoins, never forks a 2nd group
    assert reg.active() == 1

    reg.note_member("g1", "g1", "map")
    reg.note_member("g1", "g1#1", "map")
    assert reg.flush("g1") == 2  # one typed GANG record for the round
    assert reg.flush("g1") == 0  # nothing new -> no append
    assert j.gang_info("g1") == {
        "members": {"g1": "map", "g1#1": "map"}, "partial": False,
    }

    reg.note_member("g1", "g1#2", "reduce")
    reg.mark_partial("g1")
    reg.mark_partial("g1")  # idempotent: one degradation record
    info = reg.lookup("g1")
    assert info["partial"] is True and len(info["members"]) == 3

    # membership noted for an unknown gang is a silent no-op (shed child)
    reg.note_member("khong-co", "x", "map")
    assert reg.lookup("khong-co") is None

    # finish flushes the straggler first — the ledger never loses members
    h.finish()
    assert reg.active() == 0 and reg.lookup("g1") is None
    assert j.gang_info("g1")["members"]["g1#2"] == "reduce"
    j.close()

    # the read-only audit view (chaos soak) sees the same truth
    gangs = RequestJournal.read_gangs(tmp_path)
    assert gangs["g1"]["partial"] is True
    assert len(gangs["g1"]["members"]) == 3

    # restore() pre-seeds replayed groups as flushed, partiality intact
    reg2 = GangRegistry()
    assert reg2.restore({"g1": {"members": {"a": "map"}, "partial": True}}) == 1
    assert reg2.lookup("g1") == {"members": {"a": "map"}, "partial": True}
    assert reg2.restore({"g1": {"members": {}, "partial": False}}) == 0


# -- queue affinity pick ------------------------------------------------------


def _row(prompt, gang=""):
    return ServeRequest(prompt=prompt, est_tokens=1, gang_id=gang)


def test_gang_affinity_pick_clusters_siblings():
    """An over-full take keeps the head row's gang together — siblings land
    in ONE slot generation (warm shared prefix, whole-gang preemption)."""
    q = RequestQueue(max_depth=16)
    order = [("a0", "ga"), ("b0", "gb"), ("a1", "ga"), ("b1", "gb"),
             ("a2", "ga")]
    for p, g in order:
        q.submit(_row(p, g))
    batch = q.take_batch(3, 0.0)
    assert [r.prompt for r in batch] == ["a0", "a1", "a2"]
    # the other gang drains next, still whole
    assert [r.prompt for r in q.take_batch(3, 0.0)] == ["b0", "b1"]


def test_gang_affinity_off_restores_fifo_packing():
    """queue.gang_affinity = False (--no-gang-affinity) is the bench A/B
    lever: same queue content, pre-gang FIFO-prefix packing."""
    q = RequestQueue(max_depth=16)
    q.gang_affinity = False
    for p, g in [("a0", "ga"), ("b0", "gb"), ("a1", "ga"), ("b1", "gb"),
                 ("a2", "ga")]:
        q.submit(_row(p, g))
    batch = q.take_batch(3, 0.0)
    assert [r.prompt for r in batch] == ["a0", "b0", "a1"]


# -- poll surface: per-phase progress (satellite 1) ---------------------------


def test_request_status_reports_per_phase_progress(tmp_path):
    base, state, server = _serve(tmp_path)
    try:
        status, resp = _req(base, "POST", "/v1/summarize",
                            {"text": DOC, "approach": "mapreduce",
                             "request_id": "sj-1"})
        assert status == 200 and resp["summary"]
        assert "partial" not in resp  # clean run: no degradation marker

        status, body = _req(base, "GET", "/v1/requests/sj-1")
        assert status == 200 and body["status"] == "completed"
        gang = body["gang"]
        assert gang["partial"] is False
        phases = gang["phases"]
        # schema regression: exact per-phase keys — a polling client parses
        # these, so a rename is a breaking change
        assert set(phases) == {"map", "reduce"}
        for ph in phases.values():
            assert set(ph) == {"total", "done", "failed", "running",
                               "streaming"}
            assert ph["done"] == ph["total"] > 0
            assert ph["failed"] == ph["running"] == ph["streaming"] == 0
        assert gang["members"] == sum(p["total"] for p in phases.values())
        assert phases["map"]["total"] >= 2  # it actually fanned out

        # gang counters made it to the aggregate snapshot + scrape surface
        snap = state.scheduler.metrics.snapshot()
        assert snap.gang_admitted >= 1
        assert snap.gang_members >= gang["members"]
        u = urllib.parse.urlparse(base)
        conn = http.client.HTTPConnection(u.hostname, u.port, timeout=30)
        conn.request("GET", "/metrics")
        text = conn.getresponse().read().decode()
        conn.close()
        assert "vnsum_serve_gang_admitted_total" in text
        assert "vnsum_serve_gang_active 0" in text  # handle finished
        assert state.scheduler.gangs.active() == 0
    finally:
        server.shutdown()
        server.server_close()
        state.close()


# -- degraded results: POISON member -> partial (satellite 2) -----------------


def test_poison_member_degrades_to_partial_terminal_state(tmp_path):
    doc = "\n\n".join(
        f"Đoạn {i}: " + ("DOC-POISON doc hai. " if i == 3 else
                         "nội dung dài cần tóm tắt kỹ lưỡng. ") * 200
        for i in range(18)
    )
    base, state, server = _serve(
        tmp_path, supervisor=EngineSupervisor(FAST),
    )
    try:
        plan = FaultPlan(
            [FaultSpec(site="fake.dispatch", kind="poison",
                       match="DOC-POISON")]
        )
        with injected(plan):
            status, resp = _req(base, "POST", "/v1/summarize",
                                {"text": doc, "approach": "mapreduce",
                                 "request_id": "pj-1"})
        # degraded, not failed: the reduce ran over the survivors and the
        # reply says so inline
        assert status == 200
        assert resp["partial"] is True and resp["summary"]

        # the journal agrees terminally: FAILED child + COMPLETE siblings,
        # all terminal -> the shared fold answers "partial"
        assert wait_for(lambda: all(
            e.terminal for e in state.journal.lookup("pj-1")))
        entries = state.journal.lookup("pj-1")
        assert aggregate_status(entries) == "partial"
        assert any(e.status == "failed" for e in entries)
        assert state.journal.gang_info("pj-1")["partial"] is True

        status, body = _req(base, "GET", "/v1/requests/pj-1")
        assert status == 200 and body["status"] == "partial"
        assert body["gang"]["partial"] is True
        assert body["gang"]["phases"]["map"]["failed"] == 1
        assert state.scheduler.metrics.snapshot().gang_partials == 1
    finally:
        server.shutdown()
        server.server_close()
        state.close()


# -- gang-cancel mid-reduce (satellite 3a) ------------------------------------


def test_gang_cancel_mid_reduce(tmp_path):
    """Cancel lands between the map round and the reduce's dispatch: the
    reduce resolves typed-cancelled, the completed maps stay COMPLETE, and
    the shared fold answers \"cancelled\" for the parent aggregate."""
    journal = RequestJournal(tmp_path / "j")
    backend = FakeBackend(batch_overhead_s=0.25)
    sched = MicroBatchScheduler(backend, max_batch=1, max_wait_s=0.001,
                                journal=journal)
    try:
        handle = sched.admit_gang("gc-1")
        qb = QueuedBackend(sched, trace_id="gc-1", gang="gc-1")
        maps = qb.submit_round(["chunk mot " * 8, "chunk hai " * 8],
                               phase="map")
        texts = [qb.harvest(f) for f in maps]
        assert all(texts)
        # park a blocker on the single-dispatch engine so the reduce stays
        # QUEUED long enough for the cancel to win the race
        blocker = sched.submit("giu dong co " * 10, trace_id="blk-1")
        assert wait_for(lambda: len(backend.batch_sizes) >= 3)
        (rfut,) = qb.submit_round(["tong hop: " + " ".join(texts)],
                                  phase="reduce")
        res = sched.cancel("gc-1")
        assert res["known"] and res["cancelled_queued"] == 1
        with pytest.raises(RequestCancelled) as exc:
            rfut.result(timeout=15)
        assert exc.value.stage == "queued"
        handle.finish()
        assert blocker.result(timeout=15).text  # the bystander survives
    finally:
        sched.close()
        journal.close()

    # the gang's ledger: membership round-trips, maps complete, reduce
    # cancelled, and the group folds to "cancelled" — never "completed"
    gangs = RequestJournal.read_gangs(tmp_path / "j")
    assert set(gangs["gc-1"]["members"].values()) == {"map", "reduce"}
    entries, _sealed, _torn = RequestJournal.read_state(tmp_path / "j")
    mine = [e for rid, e in entries.items() if rid.split("#")[0] == "gc-1"]
    assert len(mine) == 3 and all(e.terminal for e in mine)
    assert aggregate_status(mine) == "cancelled"


# -- journal replay of a half-finished gang (satellite 3b) --------------------


def test_replay_restores_half_finished_gang(tmp_path):
    """Crash after the maps completed but before the reduce ran: replay
    must rebuild the LIVE group from the typed GANG records (not trace
    prefixes), re-run only the reduce, and finish byte-identical."""
    jdir = tmp_path / "journal"
    j = RequestJournal(jdir)
    reduce_prompt = "tong hop cac y chinh " * 8
    rids = []
    for i, (prompt, phase) in enumerate([
        ("phan mot " * 8, "map"),
        ("phan hai " * 8, "map"),
        (reduce_prompt, "reduce"),
    ]):
        r = ServeRequest(prompt=prompt, trace_id="g-1", gang_id="g-1",
                         gang_phase=phase)
        rids.append(j.accept(r))
    assert rids == ["g-1", "g-1#1", "g-1#2"]
    j.gang("g-1", [(rid, ph) for rid, ph in
                   zip(rids, ["map", "map", "reduce"])])
    for rid in rids[:2]:
        j.start(rid)
        j.complete(rid, f"xong {rid}", gen_tokens=2)
    j.close()  # no seal: simulated crash with the reduce still pending

    state = ServeState(FakeBackend(), max_batch=4, max_wait_s=0.005,
                       journal_dir=str(jdir))
    server = make_server(state, "127.0.0.1", 0)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{server.server_address[1]}"
    try:
        # the gang is restored BEFORE any entry is re-enqueued
        restored = state.replay_journal()
        assert restored == 1  # only the reduce was unfinished
        live = state.scheduler.gangs.lookup("g-1")
        assert live is not None and len(live["members"]) == 3

        assert wait_for(lambda: all(
            e.terminal for e in state.journal.lookup("g-1")))
        by_rid = {e.rid: e for e in state.journal.lookup("g-1")}
        # byte-identity: the replayed reduce matches an uninterrupted run
        assert by_rid["g-1#2"].status == "complete"
        assert by_rid["g-1#2"].text == FakeBackend().generate(
            [reduce_prompt])[0]
        # completed maps were NOT re-run (their texts are the pre-crash
        # ones, and replay enqueued exactly one request)
        assert by_rid["g-1"].text == "xong g-1"

        status, body = _req(base, "GET", "/v1/requests/g-1")
        assert status == 200 and body["status"] == "completed"
        phases = body["gang"]["phases"]
        assert phases["map"] == {"total": 2, "done": 2, "failed": 0,
                                 "running": 0, "streaming": 0}
        assert phases["reduce"]["done"] == 1
    finally:
        server.shutdown()
        server.server_close()
        state.close()


# -- whole-gang preemption (satellite 3c) -------------------------------------


def test_preemption_evicts_whole_gang_byte_identical():
    """One interactive arrival needs ONE slot, but the resident fan-out is
    a gang: eviction takes the WHOLE group (never strands a half-finished
    fan-out holding pins), both members requeue, and their final outputs
    stay byte-identical to an unpreempted run."""
    tenants = TenantTable(parse_tenant_specs("interactive:4:0,batch:1:0:batch"))
    backend = FakeBackend(segment_words=4, segment_overhead_s=0.005,
                          batch_overhead_s=0.01)
    sched = InflightScheduler(backend, slots=2, max_wait_s=0.01,
                              tenants=tenants)
    try:
        handle = sched.admit_gang("gp-1", tenant="batch")
        prompts = ["phan tich chuyen sau noi dung " * 12 + f" so {i}"
                   for i in range(2)]
        futs = [
            sched.submit(p, tenant="batch", tier="batch", gang="gp-1",
                         gang_phase="map")
            for p in prompts
        ]
        time.sleep(0.03)  # both gang members resident, a few segments deep
        i_c = sched.submit("ngan gon", tenant="interactive").result(timeout=30)
        assert i_c.record.status == "ok"
        texts = [f.result(timeout=30).text for f in futs]
        handle.finish()
        snap = sched.metrics.snapshot()
        # demand was ONE slot; the gang granularity evicted BOTH members
        # together and counted one whole-gang preemption
        assert snap.gang_preemptions >= 1
        assert snap.preemptions >= 2 and snap.preemptions % 2 == 0
        assert snap.requeues == snap.preemptions  # nobody stranded
        for p, text in zip(prompts, texts):
            assert text == FakeBackend().generate([p])[0]
    finally:
        sched.close()
