"""Orbax train-state checkpointing: save -> restore resumes bit-exact on the
same mesh (vnsum_tpu/train/checkpoint.py)."""
from __future__ import annotations

import numpy as np
import pytest

import jax

from vnsum_tpu.models.llama import tiny_llama
from vnsum_tpu.parallel import make_mesh
from vnsum_tpu.train import TrainCheckpointer, TrainConfig, Trainer


@pytest.fixture(scope="module")
def mesh():
    return make_mesh({"data": 2, "model": 2}, platform="cpu")


def _tokens(seed: int, cfg):
    rng = np.random.default_rng(seed)
    return rng.integers(0, cfg.vocab_size, size=(4, 32), dtype=np.int32)


def test_save_restore_resumes_bit_exact(tmp_path, mesh):
    cfg = tiny_llama()
    tc = TrainConfig(remat=False)

    a = Trainer(cfg, mesh, tc, seed=7)
    a.step(_tokens(0, cfg))
    a.step(_tokens(1, cfg))

    ckpt = TrainCheckpointer(tmp_path / "ckpt")
    saved_step = ckpt.save(a)
    assert saved_step == 2
    loss_a = a.step(_tokens(2, cfg))

    # fresh trainer with different seed -> different params until restore;
    # after restore, replaying the same batch must reproduce a's loss exactly
    b = Trainer(cfg, mesh, tc, seed=99)
    restored = ckpt.restore(b)
    assert restored == 2
    loss_b = b.step(_tokens(2, cfg))
    assert loss_b == pytest.approx(loss_a, abs=1e-6)
    ckpt.close()


def test_restore_latest_and_specific_step(tmp_path, mesh):
    cfg = tiny_llama()
    t = Trainer(cfg, mesh, TrainConfig(remat=False), seed=3)
    ckpt = TrainCheckpointer(tmp_path / "ckpt2", max_to_keep=2)
    t.step(_tokens(0, cfg))
    ckpt.save(t)
    t.step(_tokens(1, cfg))
    ckpt.save(t)
    assert ckpt.latest_step() == 2
    assert set(ckpt.all_steps()) == {1, 2}

    t2 = Trainer(cfg, mesh, TrainConfig(remat=False), seed=4)
    assert ckpt.restore(t2, step=1) == 1
    assert t2.step_count == 1
    ckpt.close()


def test_restore_missing_raises(tmp_path, mesh):
    cfg = tiny_llama()
    t = Trainer(cfg, mesh, TrainConfig(remat=False), seed=5)
    ckpt = TrainCheckpointer(tmp_path / "empty")
    with pytest.raises(FileNotFoundError):
        ckpt.restore(t)
    ckpt.close()


def test_restored_shardings_preserved(tmp_path, mesh):
    cfg = tiny_llama()
    t = Trainer(cfg, mesh, TrainConfig(remat=False), seed=6)
    t.step(_tokens(0, cfg))
    ckpt = TrainCheckpointer(tmp_path / "ckpt3")
    ckpt.save(t)
    t2 = Trainer(cfg, mesh, TrainConfig(remat=False), seed=8)
    ckpt.restore(t2)
    for orig, rest in zip(
        jax.tree.leaves(t.params), jax.tree.leaves(t2.params)
    ):
        assert orig.sharding == rest.sharding
        np.testing.assert_array_equal(np.asarray(orig), np.asarray(rest))
    ckpt.close()
