"""Window math (obs/window.py): the property the SLO engine stands on —
merging a WindowedHistogram's live sub-windows equals the cumulative
histogram over the same observations — plus expiry (old windows drop out
of quantiles), exemplar aging, and the WindowedCounter mirror."""
from __future__ import annotations

import random

import pytest

from vnsum_tpu.obs.histogram import TTFT_BUCKETS_S, Histogram
from vnsum_tpu.obs.window import WindowedCounter, WindowedHistogram

BOUNDS = TTFT_BUCKETS_S


def hist_state(h: Histogram) -> tuple:
    return (tuple(h.counts), round(h.sum, 9), h.count)


# -- the merge == cumulative property -----------------------------------------


@pytest.mark.parametrize("seed", [0, 1, 2, 3, 4])
def test_merged_subwindows_equal_cumulative_within_horizon(seed):
    """Property: as long as no observation has expired, merging the ring
    IS the cumulative histogram — same bucket counts, sum, and therefore
    identical quantiles. Randomized times/values over many sub-window
    boundaries; seeded, so a failure replays."""
    rng = random.Random(seed)
    wh = WindowedHistogram(BOUNDS, horizon_s=60.0, sub_windows=12)
    cum = Histogram(BOUNDS)
    t0 = rng.uniform(0, 1000.0)
    # all observations land within horizon - sub_s of each other (a span
    # any wider can straddle one more sub-window than the ring holds), in
    # nondecreasing time order (the ring recycles slots as time advances —
    # going back in time is not part of the contract)
    times = sorted(t0 + rng.uniform(0.0, 54.9) for _ in range(300))
    last = times[-1]
    for t in times:
        v = rng.choice([rng.uniform(0, 0.05), rng.uniform(0.05, 2.0),
                        rng.uniform(2.0, 30.0)])  # spread across buckets
        wh.observe(v, now=t)
        cum.observe(v)
    merged = wh.merged(now=last)
    assert hist_state(merged) == hist_state(cum)
    for q in (0.5, 0.9, 0.95, 0.99):
        assert merged.percentile(q) == cum.percentile(q)
    assert merged.fraction_le(0.5) == cum.fraction_le(0.5)


def test_expired_windows_drop_out_of_quantiles():
    wh = WindowedHistogram(BOUNDS, horizon_s=60.0, sub_windows=6)
    # a burst of SLOW observations early...
    for i in range(50):
        wh.observe(8.0, now=100.0 + i * 0.1)
    assert wh.merged(now=110.0).percentile(0.99) > 5.0
    # ...then only fast ones after the slow burst expired
    for i in range(50):
        wh.observe(0.01, now=200.0 + i * 0.1)
    h = wh.merged(now=210.0)
    assert h.count == 50
    assert h.percentile(0.99) < 0.1  # the 8s tail is GONE, not averaged in
    # partial expiry: read at a time where the slow burst is half-aged out
    wh2 = WindowedHistogram(BOUNDS, horizon_s=60.0, sub_windows=6)
    wh2.observe(8.0, now=100.0)
    wh2.observe(0.01, now=130.0)
    h_both = wh2.merged(now=140.0)   # both inside the horizon
    assert h_both.count == 2
    h_late = wh2.merged(now=185.0)   # 8s obs now > horizon old
    assert h_late.count == 1 and h_late.percentile(0.99) < 0.1


def test_narrow_window_reads_subset_of_horizon():
    """merged(window_s) covers only the most recent sub-windows — the
    fast/slow burn split reads one ring at two widths."""
    wh = WindowedHistogram(BOUNDS, horizon_s=100.0, sub_windows=10)
    wh.observe(5.0, now=10.0)     # old
    wh.observe(0.02, now=95.0)    # recent
    slow = wh.merged(now=99.0)
    fast = wh.merged(window_s=10.0, now=99.0)
    assert slow.count == 2
    assert fast.count == 1 and fast.percentile(0.5) < 0.1


def test_ring_slot_recycling_is_exact():
    """Writing more than a full horizon later lands in a RESET slot — no
    bleed-through from the expired occupant of the same ring position."""
    wh = WindowedHistogram(BOUNDS, horizon_s=10.0, sub_windows=5)
    wh.observe(1.0, now=1.0)
    # same slot (epoch 0 and epoch 5 both map to slot 0), one horizon later
    wh.observe(0.01, now=11.0)
    h = wh.merged(now=11.0)
    assert h.count == 1
    assert h.percentile(0.99) < 0.1


def test_exemplars_attach_and_age_out():
    wh = WindowedHistogram(BOUNDS, horizon_s=60.0, sub_windows=6)
    wh.observe(8.0, now=100.0, exemplar="req-slow")
    wh.observe(0.01, now=101.0, exemplar="req-fast")
    ex = wh.exemplars(now=110.0)
    ids = [e[0] for e in ex if e is not None]
    assert set(ids) == {"req-slow", "req-fast"}
    # a narrower window ages the old exemplar out
    ex = wh.exemplars(window_s=5.0, now=110.0)
    assert [e[0] for e in ex if e is not None] == []
    # past the horizon everything ages out
    assert all(e is None for e in wh.exemplars(now=300.0))


def test_windowed_counter_mirrors_and_expires():
    wc = WindowedCounter(horizon_s=60.0, sub_windows=6)
    for i in range(10):
        wc.add("completed", now=100.0 + i * 2)  # spans two sub-windows
    wc.add("errors", 3, now=105.0)
    assert wc.total("completed", now=119.0) == 10
    assert wc.total("errors", now=119.0) == 3
    assert 0 < wc.total("completed", window_s=10.0, now=119.0) < 10
    assert wc.total("completed", now=300.0) == 0
    assert wc.total("never", now=110.0) == 0


def test_bad_construction_rejected():
    with pytest.raises(ValueError):
        WindowedHistogram(BOUNDS, horizon_s=0)
    with pytest.raises(ValueError):
        WindowedCounter(sub_windows=0)


# -- Histogram extensions the windows rely on ---------------------------------


def test_histogram_merge_reset_and_fraction_le():
    a = Histogram(BOUNDS)
    b = Histogram(BOUNDS)
    for v in (0.01, 0.2, 3.0, 100.0):
        a.observe(v)
    b.observe(0.04)
    a.merge_from(b)
    ref = Histogram(BOUNDS)
    for v in (0.01, 0.2, 3.0, 100.0, 0.04):
        ref.observe(v)
    assert hist_state(a) == hist_state(ref)
    with pytest.raises(ValueError):
        a.merge_from(Histogram((1.0, 2.0)))
    # fraction_le: interpolated, +Inf tail counts as violating
    h = Histogram((1.0, 2.0))
    for v in (0.5, 1.5, 5.0):
        h.observe(v)
    assert h.fraction_le(1.0) == pytest.approx(1 / 3)
    assert h.fraction_le(1.5) == pytest.approx(0.5)  # half of bucket 2
    assert h.fraction_le(10.0) == pytest.approx(2 / 3)  # tail never counts
    assert Histogram(BOUNDS).fraction_le(1.0) == 1.0  # vacuous when empty
    h.reset()
    assert h.count == 0 and sum(h.counts) == 0 and h.sum == 0.0
