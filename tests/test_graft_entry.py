import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import __graft_entry__ as graft


def test_dryrun_multichip_8():
    graft.dryrun_multichip(8)


def test_dryrun_multichip_4():
    graft.dryrun_multichip(4)
