"""Serving HTTP front-end tests over a live ThreadingHTTPServer with the
FakeBackend: /v1/generate, /v1/summarize, /healthz, /metrics, and the typed
429 shed contract."""
from __future__ import annotations

import json
import threading
import urllib.error
import urllib.request

import pytest

from vnsum_tpu.backend.fake import FakeBackend
from vnsum_tpu.serve.server import ServeState, make_server

DOC = "\n\n".join(
    f"Đoạn văn {i}: " + "nội dung tiếng Việt có dấu thanh. " * 25
    for i in range(4)
)


@pytest.fixture()
def serve_url():
    state = ServeState(FakeBackend(), max_batch=8, max_wait_s=0.005)
    server = make_server(state, "127.0.0.1", 0)  # ephemeral port
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    yield f"http://127.0.0.1:{server.server_address[1]}", state
    server.shutdown()
    server.server_close()
    state.close()


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as resp:
        return resp.status, resp.read()


def _post(url, payload):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    with urllib.request.urlopen(req, timeout=60) as resp:
        return resp.status, json.loads(resp.read())


def test_healthz(serve_url):
    base, _ = serve_url
    status, body = _get(base + "/healthz")
    d = json.loads(body)
    assert status == 200
    assert d["status"] == "ok" and d["backend"] == "fake"
    assert d["queue_depth"] == 0 and d["closed"] is False


def test_healthz_schema_regression(serve_url):
    """The /healthz response schema is a contract probes parse: the
    uptime/version/start-stamp satellite fields must keep their names and
    types, and the SLO line appears exactly when --slo is configured."""
    import re

    base, _ = serve_url
    _, body = _get(base + "/healthz")
    d = json.loads(body)
    # field presence + types
    assert isinstance(d["uptime_s"], (int, float)) and d["uptime_s"] >= 0
    assert isinstance(d["version"], str) and d["version"]
    from vnsum_tpu import __version__

    assert d["version"] == __version__
    # start wall-clock stamp: ISO seconds resolution, explicitly UTC
    assert re.fullmatch(r"\d{4}-\d{2}-\d{2}T\d{2}:\d{2}:\d{2}Z",
                        d["started_at"])
    # no --slo -> no slo line (probes must not see a phantom verdict)
    assert "slo" not in d
    # uptime advances between polls
    import time as _time

    _time.sleep(0.05)
    _, body = _get(base + "/healthz")
    assert json.loads(body)["uptime_s"] >= d["uptime_s"]


def test_generate_single_and_batch(serve_url):
    base, state = serve_url
    status, d = _post(base + "/v1/generate", {"prompt": "xin chào " * 10})
    assert status == 200
    (c,) = d["completions"]
    assert c["text"]
    assert c["record"]["status"] == "ok" and c["record"]["batch_size"] >= 1
    status, d = _post(
        base + "/v1/generate", {"prompts": ["một " * 8, "hai " * 8]}
    )
    assert status == 200 and len(d["completions"]) == 2


def test_generate_validation(serve_url):
    base, _ = serve_url
    for payload in ({}, {"prompt": ""}, {"prompts": []}, {"prompts": [1]}):
        with pytest.raises(urllib.error.HTTPError) as exc:
            _post(base + "/v1/generate", payload)
        assert exc.value.code == 400


def test_bad_numeric_fields_are_400_not_engine_errors(serve_url):
    # type-bad knobs must be rejected at the door (400), not forwarded into
    # the scheduler where they'd fail the batch and count as engine errors
    base, state = serve_url
    for payload in (
        {"prompt": "x", "temperature": "hot"},
        {"prompt": "x", "deadline_ms": "soon"},
        {"prompt": "x", "max_new_tokens": "many"},
        {"prompt": "x", "max_new_tokens": 1.5},
        {"prompt": "x", "top_k": True},
    ):
        with pytest.raises(urllib.error.HTTPError) as exc:
            _post(base + "/v1/generate", payload)
        assert exc.value.code == 400
    with pytest.raises(urllib.error.HTTPError) as exc:
        _post(base + "/v1/summarize", {"text": DOC, "max_new_tokens": "many"})
    assert exc.value.code == 400
    stats = state.scheduler.metrics.snapshot()
    assert stats.errors == 0 and stats.submitted == 0


def test_generate_expired_deadline_is_429_shed(serve_url):
    base, _ = serve_url
    with pytest.raises(urllib.error.HTTPError) as exc:
        _post(base + "/v1/generate",
              {"prompt": "trễ hạn " * 5, "deadline_ms": 0})
    assert exc.value.code == 429
    body = json.loads(exc.value.read())
    assert body["error"] == "shed" and body["reason"] == "deadline"
    # even sheds carry the correlation id (satellite: request-id plumbing)
    assert body["request_id"]


def test_summarize_full_strategy_with_serving_record(serve_url):
    base, _ = serve_url
    status, d = _post(
        base + "/v1/summarize", {"text": DOC, "approach": "mapreduce"}
    )
    assert status == 200
    assert d["approach"] == "mapreduce" and d["summary"]
    assert d["num_chunks"] >= 1 and d["llm_calls"] >= 1
    assert d["serving"]["llm_requests"] == d["llm_calls"]
    assert d["serving"]["engine_s"] >= 0
    assert d["serving"]["generated_tokens"] > 0


def test_summarize_max_new_tokens_override(serve_url):
    base, state = serve_url
    # the override builds an uncached strategy carrying the budget; the
    # shared per-approach cache stays on the approach default
    status, d = _post(
        base + "/v1/summarize",
        {"text": DOC, "approach": "mapreduce", "max_new_tokens": 77},
    )
    assert status == 200 and d["summary"]
    strat = state.strategy_for("mapreduce", 77)
    assert strat.max_new_tokens == 77
    assert state.strategy_for("mapreduce").max_new_tokens != 77


def test_summarize_validation(serve_url):
    base, _ = serve_url
    with pytest.raises(urllib.error.HTTPError) as exc:
        _post(base + "/v1/summarize", {"text": "   "})
    assert exc.value.code == 400
    with pytest.raises(urllib.error.HTTPError) as exc:
        _post(base + "/v1/summarize", {"text": "x", "approach": "nope"})
    assert exc.value.code == 400
    assert "approaches" in json.loads(exc.value.read())


def test_concurrent_summarize_requests_share_engine_batches():
    # own server with a WIDE coalescing window: the assertion is about
    # packing, and the handler threads racing to submit must not lose to
    # scheduler flushes on a slow/throttled CI host (5ms flaked there)
    state = ServeState(FakeBackend(), max_batch=8, max_wait_s=0.25)
    server = make_server(state, "127.0.0.1", 0)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{server.server_address[1]}"
    try:
        n = 4
        barrier = threading.Barrier(n)
        out = [None] * n

        def worker(i):
            barrier.wait()
            out[i] = _post(
                base + "/v1/summarize", {"text": DOC, "approach": "truncated"}
            )

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert all(status == 200 and d["summary"] for status, d in out)
        # truncated = 1 LLM call per request; the scheduler should have
        # packed the 4 concurrent calls into fewer dispatches than requests
        assert len(state.backend.batch_sizes) < n
        assert sum(state.backend.batch_sizes) == n
    finally:
        server.shutdown()
        server.server_close()
        state.close()


def test_metrics_endpoint_exposes_serving_counters(serve_url):
    base, _ = serve_url
    _post(base + "/v1/generate", {"prompt": "đo lường " * 6})
    status, body = _get(base + "/metrics")
    text = body.decode()
    assert status == 200
    assert "vnsum_serve_requests_total" in text
    assert "vnsum_serve_batches_total" in text
    assert "vnsum_serve_engine_seconds_total" in text
    assert 'vnsum_serve_requests_shed_total{reason="queue_full"}' in text
    assert "vnsum_serve_queue_wait_seconds_count" in text


def test_unknown_routes_404(serve_url):
    base, _ = serve_url
    with pytest.raises(urllib.error.HTTPError) as exc:
        _get(base + "/nope")
    assert exc.value.code == 404
    with pytest.raises(urllib.error.HTTPError) as exc:
        _post(base + "/v1/nope", {})
    assert exc.value.code == 404


# -- malformed-body hardening (typed 400s, never the 500 engine path) --------


def _raw_post(base, path, body: bytes, content_length: int | None = None):
    """POST with full control over the bytes and the Content-Length header
    (urllib always sets a correct length, which several of these cases must
    violate on purpose). Returns (status, parsed-or-raw body)."""
    import http.client
    import urllib.parse

    u = urllib.parse.urlparse(base)
    conn = http.client.HTTPConnection(u.hostname, u.port, timeout=30)
    try:
        conn.putrequest("POST", path)
        conn.putheader("Content-Type", "application/json")
        conn.putheader(
            "Content-Length",
            str(len(body) if content_length is None else content_length),
        )
        conn.endheaders()
        conn.send(body)
        resp = conn.getresponse()
        raw = resp.read()
        try:
            return resp.status, json.loads(raw)
        except ValueError:
            return resp.status, raw
    finally:
        conn.close()


def test_invalid_utf8_body_is_400(serve_url):
    # json.loads raises UnicodeDecodeError (not JSONDecodeError) here; an
    # uncaught one used to surface as a 500
    base, _ = serve_url
    status, body = _raw_post(base, "/v1/generate", b'{"prompt": "\xff\xfe"}')
    assert status == 400
    assert "UTF-8" in body["error"]


def test_invalid_json_body_is_400(serve_url):
    base, _ = serve_url
    status, body = _raw_post(base, "/v1/generate", b'{"prompt": "x"')
    assert status == 400
    assert body["error"] == "invalid JSON"


def test_oversized_declared_body_is_413_typed(serve_url):
    # refused on the DECLARED length, before buffering a byte
    base, _ = serve_url
    status, body = _raw_post(
        base, "/v1/generate", b"{}", content_length=64 * 1024 * 1024
    )
    assert status == 413
    assert body["error"] == "request body too large"


def test_unknown_fields_are_400_with_the_field_named(serve_url):
    base, state = serve_url
    for path, payload in (
        ("/v1/generate", {"prompt": "x " * 4, "temperatre": 0.5}),
        ("/v1/summarize", {"text": "x " * 4, "aproach": "mapreduce"}),
    ):
        with pytest.raises(urllib.error.HTTPError) as exc:
            _post(base + path, payload)
        assert exc.value.code == 400
        err = json.loads(exc.value.read())
        assert "unknown field" in err["error"]
    # a typo'd knob must never have reached the engine as a silent default
    assert state.scheduler.metrics.snapshot().errors == 0


def test_all_documented_fields_still_accepted(serve_url):
    # the allowlist must not reject anything the API documents
    base, _ = serve_url
    status, d = _post(base + "/v1/generate", {
        "prompt": "đầy đủ " * 6, "max_new_tokens": 16, "temperature": 0.0,
        "top_k": 1, "top_p": 1.0, "seed": 3, "spec_k": 0,
        "deadline_ms": 30000, "request_id": "full-1",
        "reference": "tham khảo", "cache_hint": "đầy đủ",
    })
    assert status == 200 and d["completions"][0]["text"]
    status, d = _post(base + "/v1/summarize", {
        "text": DOC, "approach": "truncated", "max_new_tokens": 32,
        "deadline_ms": 60000, "request_id": "full-2",
    })
    assert status == 200 and d["summary"]


def test_mesh_surface_on_healthz_and_metrics():
    """A mesh-built server echoes its topology on /healthz and renders the
    mesh gauges — including per-DP-replica occupancy in in-flight mode."""
    state = ServeState(
        FakeBackend(), max_batch=4, max_wait_s=0.005,
        inflight=True, slots=4, mesh={"data": 2, "model": 2},
    )
    server = make_server(state, "127.0.0.1", 0)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{server.server_address[1]}"
    try:
        _, body = _get(base + "/healthz")
        d = json.loads(body)
        assert d["mesh"] == {"devices": 4, "data": 2, "model": 2}
        _, body = _get(base + "/metrics")
        text = body.decode()
        assert "vnsum_serve_mesh_devices 4" in text
        assert "vnsum_serve_mesh_data_parallel 2" in text
        assert "vnsum_serve_mesh_model_parallel 2" in text
        assert "vnsum_serve_mesh_replica_occupancy" in text
    finally:
        server.shutdown()
        server.server_close()
        state.close()


def test_single_chip_server_renders_no_mesh_gauges(serve_url):
    base, _ = serve_url
    _, body = _get(base + "/healthz")
    assert "mesh" not in json.loads(body)
    _, body = _get(base + "/metrics")
    assert "vnsum_serve_mesh_" not in body.decode()


# -- /readyz: routability, distinct from /healthz liveness -------------------


def _get_readyz(base):
    try:
        with urllib.request.urlopen(base + "/readyz", timeout=10) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_readyz_ready_when_serving(serve_url):
    base, _ = serve_url
    status, body = _get_readyz(base)
    assert status == 200 and body["status"] == "ready"


def test_readyz_draining_is_typed_503(serve_url):
    """A draining server still answers /healthz (alive) but /readyz must
    say 503 draining — the router takes it out of rotation, not for dead."""
    base, state = serve_url
    state.scheduler.close()
    status, body = _get_readyz(base)
    assert status == 503
    assert body["error"] == "not_ready" and body["reason"] == "draining"
    # liveness stays answerable: the split IS the contract
    status, _ = _get(base + "/healthz")
    assert status == 200


def test_readyz_brownout_is_typed_503(serve_url):
    from types import SimpleNamespace

    from vnsum_tpu.serve.supervisor import Rung

    base, state = serve_url
    saved = state.supervisor
    state.supervisor = SimpleNamespace(rung=Rung.BROWNOUT)
    try:
        status, body = _get_readyz(base)
        assert status == 503 and body["reason"] == "brownout"
        state.supervisor = SimpleNamespace(rung=Rung.NO_SPEC)
        status, body = _get_readyz(base)
        assert status == 200  # any rung short of brownout stays routable
    finally:
        state.supervisor = saved


def test_readyz_pre_replay_until_journal_replayed(tmp_path):
    """A journal-armed server is NOT routable until startup replay has
    re-enqueued its unfinished ACCEPTs — fresh traffic must not race
    crash recovery. The standalone CLI replays before binding the port;
    this pins the state machine the router's probe loop observes."""
    state = ServeState(FakeBackend(), max_batch=4, max_wait_s=0.005,
                       journal_dir=str(tmp_path))
    server = make_server(state, "127.0.0.1", 0)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    base = f"http://127.0.0.1:{server.server_address[1]}"
    try:
        status, body = _get_readyz(base)
        assert status == 503 and body["reason"] == "pre_replay"
        assert body["retry_after_s"] == 1.0
        state.replay_journal()
        status, body = _get_readyz(base)
        assert status == 200 and body["status"] == "ready"
    finally:
        server.shutdown()
        server.server_close()
        state.close()
