"""Watchdog acceptance: hang/stall detection math under a synthetic clock,
false-positive immunity for slow-but-progressing dispatches, wedged-dispatch
recovery (one-shot riders typed HUNG; slot-loop teardown + requeue with
byte-identical rebuilt outputs), helper/lock escalation sealing the journal,
the drain-beats-sleep fix, and the /debug/stacks + /healthz surfaces.
Everything hermetic (FakeBackend + the fault plan's `hang` kind); the
cardinal assertion, as everywhere in serve/: EVERY future resolves."""
from __future__ import annotations

import json
import threading
import time
import urllib.request

import pytest

from vnsum_tpu.backend.fake import FakeBackend
from vnsum_tpu.serve import (
    FailureClass,
    InflightScheduler,
    MicroBatchScheduler,
    RequestFailed,
    RequestJournal,
    Watchdog,
)
from vnsum_tpu.serve.supervisor import EngineSupervisor, RetryPolicy
from vnsum_tpu.serve.watchdog import Stall, snapshot_stacks
from vnsum_tpu.testing.faults import FaultPlan, FaultSpec, injected

FAST = RetryPolicy(max_attempts=2, backoff_base_s=0.005, backoff_max_s=0.02,
                   jitter=0.0)


def _wait_until(cond, timeout_s: float = 5.0) -> None:
    """Poll a racy cross-thread counter: the recovery hook resolves the
    riders BEFORE the watchdog thread increments its own bookkeeping, so a
    test that just unblocked on a future may read the counter early."""
    deadline = time.monotonic() + timeout_s
    while not cond() and time.monotonic() < deadline:
        time.sleep(0.01)
    assert cond()


class FakeClock:
    def __init__(self, t: float = 100.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


# -- detection math (synthetic clock, no threads, no sleeps) -----------------


def test_heartbeat_stall_detection_and_classification():
    clock = FakeClock()
    wd = Watchdog(loop_deadline_s=5.0, helper_deadline_s=20.0, clock=clock)
    loop_hb = wd.register("loop-thread", kind="loop")
    helper_hb = wd.register("helper-thread", kind="helper")
    assert wd.check() == []
    clock.advance(4.9)
    assert wd.check() == []  # inside every deadline
    clock.advance(0.2)  # loop 5.1s quiet, helper well inside 20s
    stalls = wd.check()
    assert [(s.kind, s.name) for s in stalls] == [("lock", "loop-thread")]
    assert stalls[0].stalled_for_s == pytest.approx(5.1)
    assert stalls[0].limit_s == 5.0
    # flagged once: the same wedge does not re-fire every interval
    assert wd.check() == []
    # beating clears the flag; a NEW stall fires again
    loop_hb.beat()
    assert wd.check() == []
    clock.advance(5.5)
    assert [(s.kind, s.name) for s in wd.check()] == [
        ("lock", "loop-thread")
    ]
    # the helper finally goes quiet past ITS deadline -> helper-classified
    helper_hb.beat()
    clock.advance(20.1)
    kinds = {(s.kind, s.name) for s in wd.check()}
    assert ("helper", "helper-thread") in kinds


def test_dispatch_budget_math_and_false_positive_immunity():
    clock = FakeClock()
    wd = Watchdog(loop_deadline_s=2.0, dispatch_base_s=10.0,
                  dispatch_per_token_s=0.01, clock=clock)
    wd.register("scheduler", kind="loop")
    # budget scales with token work: 10s base + 0.01 * 2000 = 30s
    assert wd.dispatch_budget(2000) == pytest.approx(30.0)
    t = wd.begin_dispatch("scheduler", "one_shot", wd.dispatch_budget(2000),
                          riders=("req-1",), tokens=2000)
    # a SLOW dispatch inside its budget is never a stall, even when the
    # loop heartbeat is long past its own deadline (it cannot beat while
    # dispatching — the ticket suspends the heartbeat check)
    clock.advance(29.0)
    assert wd.check() == []
    wd.end_dispatch(t)
    # after a clean end the heartbeat check resumes (and the loop IS stale
    # now — it has not beaten in 29s); that reads as a lock stall, which is
    # correct: nothing is dispatching and the thread went quiet
    stalls = wd.check()
    assert [s.kind for s in stalls] == ["lock"]


def test_dispatch_past_budget_is_hung_and_fires_once():
    clock = FakeClock()
    wd = Watchdog(loop_deadline_s=100.0, dispatch_base_s=5.0,
                  dispatch_per_token_s=0.0, clock=clock)
    wd.register("scheduler", kind="loop")
    ticket = wd.begin_dispatch("scheduler", "one_shot", 5.0,
                               riders=("req-9",), tokens=64)
    clock.advance(5.2)
    stalls = wd.check()
    assert [(s.kind, s.name) for s in stalls] == [("dispatch", "scheduler")]
    assert stalls[0].ticket is ticket
    assert stalls[0].detail["riders"] == ["req-9"]
    # the hung ticket was consumed: no re-fire, and the abandoned thread's
    # late end_dispatch is a harmless no-op
    assert wd.check() == []
    wd.end_dispatch(ticket)
    assert wd.check() == []


def test_fused_segment_budget_math_and_false_positive_immunity():
    """--fused-segments N holds the host for up to N on-device segments
    per ticket: the slot_segment budget scales by N (a fused dispatch
    inside it is never a stall) and a dispatch past the SCALED budget
    still trips as a real HUNG."""
    clock = FakeClock()
    wd = Watchdog(loop_deadline_s=100.0, segment_budget_s=2.0, clock=clock)
    wd.register("scheduler", kind="loop")
    assert wd.segment_budget() == pytest.approx(2.0)
    assert wd.segment_budget(4) == pytest.approx(8.0)
    t = wd.begin_dispatch("scheduler", "slot_segment", wd.segment_budget(4))
    clock.advance(7.5)  # would be HUNG at N=1; inside the N=4 budget
    assert wd.check() == []
    wd.end_dispatch(t)
    t2 = wd.begin_dispatch("scheduler", "slot_segment", wd.segment_budget(4))
    clock.advance(8.2)  # past even the scaled budget -> real hang
    stalls = wd.check()
    assert [(s.kind, s.name) for s in stalls] == [("dispatch", "scheduler")]
    assert stalls[0].ticket is t2
    wd.end_dispatch(t2)


def test_unregister_stops_monitoring():
    clock = FakeClock()
    wd = Watchdog(loop_deadline_s=1.0, clock=clock)
    wd.register("scheduler", kind="loop")
    wd.unregister("scheduler")  # clean drain: not a stall
    clock.advance(60.0)
    assert wd.check() == []


# -- stall handling: dumps, stacks, counters ---------------------------------


def test_stall_dump_carries_thread_stacks(tmp_path):
    wd = Watchdog(loop_deadline_s=1.0, dump_dir=tmp_path)
    stall = Stall(kind="lock", name="scheduler", stalled_for_s=3.0,
                  limit_s=1.0)
    wd.handle(stall)
    dumps = list(tmp_path.glob("watchdog_lock_*.json"))
    assert len(dumps) == 1
    d = json.loads(dumps[0].read_text())
    assert d["stall"]["thread"] == "scheduler"
    assert d["stall"]["stalled_for_s"] == 3.0
    # the snapshot must contain THIS thread with a real Python stack
    me = threading.current_thread().name
    names = {t["name"] for t in d["stacks"]}
    assert me in names
    mine = next(t for t in d["stacks"] if t["name"] == me)
    assert any("test_stall_dump_carries_thread_stacks" in ln
               for ln in mine["stack"])
    assert wd.stalls_total["lock"] == 1
    assert wd.last_stall["kind"] == "lock"


def test_snapshot_stacks_sees_a_parked_thread():
    release = threading.Event()

    def parked():
        release.wait(timeout=30)  # the wedge under observation

    t = threading.Thread(target=parked, name="parked-for-test", daemon=True)
    t.start()
    time.sleep(0.05)
    try:
        stacks = snapshot_stacks()
        park = next(s for s in stacks if s["name"] == "parked-for-test")
        assert any("parked" in ln for ln in park["stack"])
    finally:
        release.set()


# -- recovery: hung one-shot dispatch ----------------------------------------


def test_hung_oneshot_riders_resolve_typed_and_scheduler_recovers():
    wd = Watchdog(interval_s=0.03, loop_deadline_s=5.0, dispatch_base_s=0.25,
                  dispatch_per_token_s=0.0)
    wd.start()
    sup = EngineSupervisor(FAST, resource_strikes_per_step=1)
    backend = FakeBackend()
    sched = MicroBatchScheduler(backend, max_batch=4, max_wait_s=0.01,
                                supervisor=sup, watchdog=wd)
    plan = FaultPlan([FaultSpec(site="fake.dispatch", kind="hang",
                                on_call=1, delay_s=0.0)])
    try:
        with injected(plan):
            fut = sched.submit("treo may mot hai ba bon")
            with pytest.raises(RequestFailed) as exc:
                fut.result(timeout=10)
            assert exc.value.failure_class is FailureClass.HUNG
            # the replacement thread serves new work (the hang is spent)
            fut2 = sched.submit("<content>\nphuc hoi ngay sau do\n</content>")
            assert "phuc hoi" in fut2.result(timeout=10).text
        assert wd.stalls_total["dispatch"] == 1
        assert wd.hung_dispatches_total == 1
        _wait_until(lambda: wd.recoveries_total == 1)
        # the ladder took the resource strike (strikes_per_step=1)
        assert int(sup.rung) >= 1
        # typed HUNG is a counted failure class
        assert sched.metrics.snapshot().failures.get("hung") == 1
    finally:
        plan.release_hangs()
        sched.close(timeout=5)
        wd.close()


def test_hung_dispatch_journals_typed_failed(tmp_path):
    wd = Watchdog(interval_s=0.03, loop_deadline_s=5.0, dispatch_base_s=0.25,
                  dispatch_per_token_s=0.0)
    wd.start()
    journal = RequestJournal(tmp_path)
    sched = MicroBatchScheduler(FakeBackend(), max_batch=2, max_wait_s=0.01,
                                journal=journal, watchdog=wd)
    plan = FaultPlan([FaultSpec(site="fake.dispatch", kind="hang",
                                on_call=1, delay_s=0.0)])
    try:
        with injected(plan):
            fut = sched.submit("ket trong dong co", trace_id="hung-1")
            with pytest.raises(RequestFailed):
                fut.result(timeout=10)
        entries = journal.lookup("hung-1")
        assert entries and entries[0].status == "failed"
        assert entries[0].reason == "hung"
    finally:
        plan.release_hangs()
        sched.close(timeout=5)
        journal.close()
        wd.close()


# -- recovery: hung slot loop -> teardown + requeue + byte-identity ----------


def test_slot_loop_rebuild_byte_identity_for_requeued_requests():
    prompts = [
        f"<content>\nvan ban {i} mot hai ba bon nam sau bay tam\n</content>"
        for i in range(3)
    ]
    reference = FakeBackend(segment_words=2).generate(prompts)

    wd = Watchdog(interval_s=0.03, loop_deadline_s=5.0, dispatch_base_s=5.0,
                  segment_budget_s=0.25)
    wd.start()
    backend = FakeBackend(segment_words=2, segment_overhead_s=0.005)
    sched = InflightScheduler(backend, slots=4, max_wait_s=0.02, watchdog=wd)
    plan = FaultPlan([FaultSpec(site="fake.slot_step", kind="hang",
                                on_call=2, delay_s=0.0)])
    try:
        with injected(plan):
            futs = [sched.submit(p) for p in prompts]
            outs = [f.result(timeout=15).text for f in futs]
        # requeued residents complete byte-identically on the rebuilt loop
        assert outs == reference
        assert wd.stalls_total["dispatch"] == 1
        _wait_until(lambda: wd.recoveries_total == 1)
        stats = sched.metrics.snapshot()
        assert stats.requeues >= 3  # every resident went back via requeue
    finally:
        plan.release_hangs()
        sched.close(timeout=5)
        wd.close()


def test_fused_slot_loop_hang_recovery_byte_identity():
    """A hang inside a FUSED dispatch (N=2): the N-scaled budget keeps
    healthy fused dispatches unflagged, the wedged one trips exactly once,
    and the rebuilt loop (same fused_segments) replays every requeued
    resident byte-identically."""
    prompts = [
        f"<content>\nhop nhat {i} mot hai ba bon nam sau bay tam\n</content>"
        for i in range(3)
    ]
    reference = FakeBackend(segment_words=2).generate(prompts)

    wd = Watchdog(interval_s=0.03, loop_deadline_s=5.0, dispatch_base_s=5.0,
                  segment_budget_s=0.25)
    wd.start()
    backend = FakeBackend(segment_words=2, segment_overhead_s=0.005)
    sched = InflightScheduler(backend, slots=4, max_wait_s=0.02, watchdog=wd,
                              fused_segments=2)
    plan = FaultPlan([FaultSpec(site="fake.slot_step", kind="hang",
                                on_call=2, delay_s=0.0)])
    try:
        with injected(plan):
            futs = [sched.submit(p) for p in prompts]
            outs = [f.result(timeout=15).text for f in futs]
        assert outs == reference
        assert wd.stalls_total["dispatch"] == 1
        _wait_until(lambda: wd.recoveries_total == 1)
        stats = sched.metrics.snapshot()
        assert stats.requeues >= 3
        # the post-recovery traffic really ran fused
        assert stats.fused_dispatches > 0
    finally:
        plan.release_hangs()
        sched.close(timeout=5)
        wd.close()


def test_hung_slot_admit_requeues_pending_and_serves():
    wd = Watchdog(interval_s=0.03, loop_deadline_s=5.0, dispatch_base_s=0.25,
                  dispatch_per_token_s=0.0)
    wd.start()
    backend = FakeBackend(segment_words=4)
    sched = InflightScheduler(backend, slots=4, max_wait_s=0.02, watchdog=wd)
    plan = FaultPlan([FaultSpec(site="fake.slot_admit", kind="hang",
                                on_call=1, delay_s=0.0)])
    try:
        with injected(plan):
            futs = [
                sched.submit(
                    f"<content>\ncho doi {i} roi van xong\n</content>"
                )
                for i in range(2)
            ]
            outs = [f.result(timeout=15).text for f in futs]
        assert all("cho doi" in o for o in outs)
        _wait_until(lambda: wd.recoveries_total == 1)
    finally:
        plan.release_hangs()
        sched.close(timeout=5)
        wd.close()


# -- escalation: helper/lock stalls seal the journal -------------------------


def test_helper_stall_escalation_seals_journal(tmp_path):
    clock = FakeClock()
    sealed = threading.Event()
    journal = RequestJournal(tmp_path)

    def escalate(stall):
        # what the HTTP server wires (minus os._exit): seal so restart
        # replay starts from a marked ledger
        assert stall.kind == "helper"
        journal.seal()
        sealed.set()

    wd = Watchdog(loop_deadline_s=5.0, helper_deadline_s=10.0, clock=clock,
                  on_escalate=escalate)
    wd.register("journal-fsync", kind="helper")
    clock.advance(11.0)
    for s in wd.tick():
        pass
    assert sealed.is_set()
    journal.close()
    _entries, is_sealed, _torn = RequestJournal.read_state(tmp_path)
    assert is_sealed


def test_mid_fsync_hang_classifies_as_lock_stall():
    """A hang inside the journal's group-commit fsync wedges the scheduler
    thread OUTSIDE any dispatch ticket — the watchdog must classify it as
    a lock stall (escalation territory: a replacement thread would
    deadlock on the held journal lock), never as a dispatch."""
    import tempfile

    escalations = []
    wd = Watchdog(interval_s=0.05, loop_deadline_s=0.4,
                  dispatch_base_s=30.0,
                  on_escalate=lambda s: escalations.append(s))
    wd.start()
    with tempfile.TemporaryDirectory() as d:
        journal = RequestJournal(d, fsync_interval_s=0.0)
        sched = MicroBatchScheduler(FakeBackend(), max_batch=2,
                                    max_wait_s=0.01, journal=journal,
                                    watchdog=wd)
        plan = FaultPlan([FaultSpec(site="journal.fsync", kind="hang",
                                    on_call=1, delay_s=1.2)])
        try:
            with injected(plan):
                fut = sched.submit("ket trong fsync mot hai ba")
                # the hang self-releases after 1.2s; the request then
                # completes — liveness was lost and found
                fut.result(timeout=10)
            deadline = time.monotonic() + 5
            while not escalations and time.monotonic() < deadline:
                time.sleep(0.02)
            assert escalations and escalations[0].kind == "lock"
            assert escalations[0].name == "scheduler"
        finally:
            plan.release_hangs()
            sched.close(timeout=5)
            journal.close()
            wd.close()


# -- drain beats an in-flight sleep (the latent-gap fix) ---------------------


def test_drain_wins_over_injected_latency_sleep():
    """A latency fault far longer than the drain budget must not stall a
    graceful close: request_drain aborts the simulated sleep, the rider
    completes (outputs are sleep-independent), and close returns fast."""
    backend = FakeBackend()
    sched = MicroBatchScheduler(backend, max_batch=2, max_wait_s=0.01)
    plan = FaultPlan([FaultSpec(site="fake.dispatch", kind="latency",
                                on_call=1, delay_s=30.0)])
    with injected(plan):
        fut = sched.submit("<content>\nngu lau qua thi thoi\n</content>")
        time.sleep(0.15)  # let the dispatch enter its 30s injected sleep
        t0 = time.monotonic()
        sched.close(drain=True, timeout=10.0)
        assert time.monotonic() - t0 < 5.0  # not the 30s sleep, not 10s
    assert "ngu lau" in fut.result(timeout=5).text


def test_drain_wins_over_latency_model_sleep():
    backend = FakeBackend(batch_overhead_s=30.0)
    sched = MicroBatchScheduler(backend, max_batch=2, max_wait_s=0.01)
    fut = sched.submit("<content>\nmo hinh tre cao van phai thoat\n</content>")
    time.sleep(0.15)
    t0 = time.monotonic()
    sched.close(drain=True, timeout=10.0)
    assert time.monotonic() - t0 < 5.0
    assert "mo hinh" in fut.result(timeout=5).text


# -- HTTP surfaces: /debug/stacks, /healthz watchdog line, /metrics ----------


@pytest.fixture()
def watchdog_server():
    from vnsum_tpu.serve.server import ServeState, make_server

    state = ServeState(FakeBackend(), max_batch=4, max_wait_s=0.005,
                       trace_sample=0.0, watchdog_interval_s=0.1,
                       watchdog_exit_on_escalate=False)
    server = make_server(state, "127.0.0.1", 0)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    base = f"http://127.0.0.1:{server.server_address[1]}"
    try:
        yield base, state
    finally:
        server.shutdown()
        server.server_close()
        state.close(drain_timeout_s=5)


def _get(url: str):
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.status, json.loads(r.read())


def test_debug_stacks_and_healthz_watchdog_line(watchdog_server):
    base, state = watchdog_server
    status, body = _get(base + "/debug/stacks")
    assert status == 200
    names = {t["name"] for t in body["threads"]}
    assert "vnsum-serve-scheduler" in names
    assert "vnsum-serve-watchdog" in names
    sched_stack = next(t for t in body["threads"]
                       if t["name"] == "vnsum-serve-scheduler")
    assert any("take_batch" in ln for ln in sched_stack["stack"])
    assert body["watchdog"]["stalls_total"] == 0
    assert "scheduler" in body["watchdog"]["threads"]

    _, health = _get(base + "/healthz")
    assert "watchdog" in health
    assert health["watchdog"]["threads"]["scheduler"] < 30.0
    assert health["watchdog"]["stalls_total"] == 0

    with urllib.request.urlopen(base + "/metrics", timeout=10) as r:
        text = r.read().decode()
    assert 'vnsum_serve_watchdog_stalls_total{kind="dispatch"} 0' in text
    assert "vnsum_serve_watchdog_recoveries_total 0" in text
    assert "vnsum_serve_watchdog_hung_dispatches_total 0" in text
    assert 'vnsum_serve_watchdog_heartbeat_age_seconds{thread="scheduler"}' \
        in text
