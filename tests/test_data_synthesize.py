"""Corpus synthesizer: VN-LongSum-shaped docs/summaries/tree/metadata
(ref metadata/doc_metadata.json shape; tree format
runners/run_summarization_ollama_mapreduce_hierarchical.py:202-239)."""
import json

from vnsum_tpu.data.synthesize import synthesize_corpus
from vnsum_tpu.text import DocumentTree
from vnsum_tpu.text.tokenizer import whitespace_token_count


def test_corpus_layout_and_stats(tmp_path):
    stats = synthesize_corpus(
        tmp_path, n_docs=4, tokens_per_doc=600, summary_tokens=60, seed=1
    )
    docs = sorted((tmp_path / "doc").glob("*.txt"))
    sums = sorted((tmp_path / "summary").glob("*.txt"))
    assert len(docs) == len(sums) == 4
    assert docs[0].name == sums[0].name  # paired by filename
    assert stats["documents"]["total_files"] == 4
    # ragged but near target
    for row in stats["documents"]["files"]:
        assert 200 < row["tokens"] < 1200
    for row in stats["summaries"]["files"]:
        assert row["tokens"] <= 75
    # Vietnamese diacritics present
    text = docs[0].read_text(encoding="utf-8")
    assert any(ch in text for ch in "ếạảịộơư")
    meta = json.loads(
        (tmp_path / "metadata" / "doc_metadata.json").read_text
        (encoding="utf-8")
    )
    assert meta["total_tokens"] == stats["documents"]["total_tokens"]


def test_tree_json_loads_and_covers_all_docs(tmp_path):
    synthesize_corpus(tmp_path, n_docs=3, tokens_per_doc=500, seed=2)
    tree = DocumentTree.load(tmp_path / "document_tree.json")
    assert len(tree) == 3
    node = tree.get("doc_000.txt")
    assert node["type"] == "Document"
    headers = node["children"]
    assert headers and all(h["type"] == "Header" for h in headers)
    paragraphs = [p for h in headers for p in h["children"]]
    assert paragraphs and all(p["type"] == "Paragraph" for p in paragraphs)
    # tree paragraphs reconstruct the doc body
    doc_text = (tmp_path / "doc" / "doc_000.txt").read_text(encoding="utf-8")
    for p in paragraphs[:3]:
        assert p["text"] in doc_text


def test_deterministic_by_seed(tmp_path):
    a = synthesize_corpus(tmp_path / "a", n_docs=2, tokens_per_doc=400, seed=7)
    b = synthesize_corpus(tmp_path / "b", n_docs=2, tokens_per_doc=400, seed=7)
    assert a == b
    ta = (tmp_path / "a/doc/doc_000.txt").read_text(encoding="utf-8")
    tb = (tmp_path / "b/doc/doc_000.txt").read_text(encoding="utf-8")
    assert ta == tb


def test_summary_is_extractive_of_doc_leads(tmp_path):
    synthesize_corpus(tmp_path, n_docs=1, tokens_per_doc=500, seed=3)
    doc = (tmp_path / "doc/doc_000.txt").read_text(encoding="utf-8")
    summary = (tmp_path / "summary/doc_000.txt").read_text(encoding="utf-8")
    assert whitespace_token_count(summary) < whitespace_token_count(doc)
    # each summary sentence except the canned closer comes from the doc
    sentences = [s.strip() + "." for s in summary.split(".") if s.strip()]
    in_doc = sum(s in doc for s in sentences)
    assert in_doc >= len(sentences) - 1


def test_hierarchical_pipeline_on_synthesized_tree(tmp_path):
    """VERDICT r1 #7: the hierarchical strategy consumes the synthesizer's
    document_tree.json end to end (real multi-section trees, not hand-built
    fixtures) — reference tree consumption:
    runners/run_summarization_ollama_mapreduce_hierarchical.py:202-239."""
    from vnsum_tpu.backend import FakeBackend
    from vnsum_tpu.core.config import PipelineConfig
    from vnsum_tpu.pipeline.runner import PipelineRunner

    synthesize_corpus(
        tmp_path / "c", n_docs=3, tokens_per_doc=600, summary_tokens=60,
        seed=9,
    )
    cfg = PipelineConfig(
        approach="mapreduce_hierarchical",
        models=["fake"],
        backend="fake",
        docs_dir=str(tmp_path / "c/doc"),
        summary_dir=str(tmp_path / "c/summary"),
        generated_summaries_dir=str(tmp_path / "gen"),
        results_dir=str(tmp_path / "results"),
        logs_dir=str(tmp_path / "logs"),
        tree_json_path=str(tmp_path / "c/document_tree.json"),
        chunk_size=200,
        chunk_overlap=20,
        max_depth=2,
        max_new_tokens=24,
    )
    runner = PipelineRunner(cfg, backend_factory=lambda *a, **k: FakeBackend())
    results = runner.run()
    rec = results.summarization["fake"]
    assert rec["successful"] == 3 and rec["failed"] == 0
    # multi-section trees mean several chunks/calls per doc
    for d in rec["processing_details"]:
        assert d["llm_calls"] >= 2
