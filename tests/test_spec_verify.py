"""Speculative verify-step correctness (vnsum_tpu.spec + engine spec path):
greedy spec decode must emit EXACTLY the plain decode token stream — on the
dense path, on the (interpret-mode) Pallas verify kernel path, with custom
stop tokens, and with acceptance actually firing (oracle reference).

Deliberately in the FAST tier (ROADMAP tier-1): the module compiles a
handful of tiny-model programs, each a few seconds on CPU, and shares one
engine fixture across tests.
"""
import numpy as np
import pytest

from vnsum_tpu.core.config import GenerationConfig
from vnsum_tpu.models import tiny_llama

PROMPTS = [
    "văn bản một về kinh tế",
    "hai " * 5,
    "một tài liệu dài hơn hẳn về pháp luật",
]
REFS = [
    "văn bản một về kinh tế xã hội và phát triển bền vững",
    None,  # no reference: the row must degrade to plain one-token steps
    "một tài liệu dài hơn hẳn về pháp luật và đời sống",
]


@pytest.fixture(scope="module")
def engine():
    from vnsum_tpu.backend.engine import TpuBackend

    return TpuBackend(
        model_config=tiny_llama(max_seq_len=256),
        batch_size=4,
        max_new_tokens=12,
        seed=0,
    )


def test_greedy_spec_matches_plain_decode(engine):
    plain = engine.generate(PROMPTS)
    spec = engine.generate(
        PROMPTS, config=GenerationConfig(spec_k=4), references=REFS
    )
    assert spec == plain
    report = engine.take_spec_report()
    assert len(report) == len(PROMPTS)
    assert report[1].draft_tokens == 0  # no reference, nothing proposed
    assert all(r.verify_steps > 0 for r in report)
    # second read is empty — the report is consumed
    assert engine.take_spec_report() == []


def test_spec_k_zero_keeps_the_plain_path(engine):
    """spec_k=0 (the default) must not even enter the spec scheduler:
    outputs byte-identical, no report, no spec counters."""
    before = engine.stats.spec_verify_steps
    plain = engine.generate(PROMPTS)
    with_refs = engine.generate(PROMPTS, references=REFS)  # spec_k defaults 0
    assert with_refs == plain
    assert engine.take_spec_report() == []
    assert engine.stats.spec_verify_steps == before


def test_greedy_spec_matches_plain_on_flash_kernel_path():
    """The multi-position Pallas verify kernel (interpret mode on CPU) must
    preserve the greedy stream too — this is the production TPU path."""
    from vnsum_tpu.backend.engine import TpuBackend

    kw = dict(
        model_config=tiny_llama(max_seq_len=256), batch_size=4,
        max_new_tokens=10, seed=0, flash=True, interpret=True,
    )
    be = TpuBackend(**kw)
    plain = be.generate(PROMPTS)
    spec = be.generate(
        PROMPTS, config=GenerationConfig(spec_k=3), references=REFS
    )
    assert spec == plain


def test_oracle_reference_is_accepted(engine):
    """Feed the row's own greedy continuation back as the reference: the
    drafter proposes exactly what the model will emit, so acceptance must
    fire and the output must STILL be byte-identical. This pins the whole
    accept path (multi-token emission, per-row fills, rollback bookkeeping)
    with a deterministic >1-token-per-step workload."""
    prompt = "một đoạn văn nguồn"
    plain = engine.generate([prompt])[0]
    if len(engine.tok.encode(plain, add_bos=False)) < 4:
        pytest.skip("greedy output too short to exercise acceptance")
    spec = engine.generate(
        [prompt], config=GenerationConfig(spec_k=4), references=[plain]
    )
    assert spec[0] == plain
    (rec,) = engine.take_spec_report()
    assert rec.accepted_tokens > 0
    # acceptance strictly compresses steps: fewer verify forwards than
    # emitted tokens
    emitted = len(engine.tok.encode(plain, add_bos=False))
    assert rec.verify_steps < emitted + 1


def test_custom_eos_stops_and_strips_under_spec(engine):
    """A custom stop token must terminate a speculative row mid-stream and
    be stripped from the text, exactly like plain decode (the terminator
    may arrive inside an ACCEPTED draft run, not only as the step token)."""
    prompt = "một đoạn văn"
    full = engine.generate([prompt])[0]
    ids = engine.tok.encode(full, add_bos=False)
    if len(ids) < 3:
        pytest.skip("rollout too short for a mid-stream stop")
    stop = ids[2]
    gen = GenerationConfig(temperature=0.0, eos_ids=(stop,), spec_k=4)
    # oracle reference makes the drafter propose the stop token inside a
    # draft run, exercising the emission cut
    out = engine.generate([prompt], config=gen, references=[full])[0]
    expect = engine.tok.decode(ids[: ids.index(stop)]).strip()
    assert out == expect


def test_spec_batch_invariance(engine):
    """A row's spec output must not depend on its batch neighbors (mirrors
    the plain engine's padding-invariance contract)."""
    gen = GenerationConfig(spec_k=4)
    alone = engine.generate([PROMPTS[0]], config=gen, references=[REFS[0]])[0]
    together = engine.generate(PROMPTS, config=gen, references=REFS)[0]
    assert alone == together


def test_sampled_spec_terminates_and_reports(engine):
    """Temperature sampling through the rejection-acceptance path: outputs
    are not required to match plain decode bit-for-bit (different
    randomness consumption), but decoding must terminate, respect the
    budget, and report coherent counters."""
    gen = GenerationConfig(spec_k=4, temperature=1.0, seed=11)
    outs = engine.generate(PROMPTS, config=gen, references=REFS)
    assert len(outs) == len(PROMPTS)
    report = engine.take_spec_report()
    for r in report:
        assert 0 <= r.accepted_tokens <= r.draft_tokens
        assert r.verify_steps <= 12  # every step retires >= 1 token


def test_mismatched_references_rejected(engine):
    with pytest.raises(ValueError, match="references must align"):
        engine.generate(
            PROMPTS, config=GenerationConfig(spec_k=2), references=["x"]
        )


def test_fake_backend_spec_contract():
    """FakeBackend mirrors the engine's spec surface so serve/strategy tests
    run without a model: references recorded, synthetic per-prompt records
    at the configured acceptance, report cleared on read."""
    from vnsum_tpu.backend.fake import FakeBackend

    fb = FakeBackend(spec_k=4, spec_acceptance=0.5)
    outs = fb.generate(
        ["Tóm tắt:\n<content>\nmột hai ba\n</content>", "b"],
        references=["một hai ba", None],
    )
    assert len(outs) == 2
    assert fb.references_seen == ["một hai ba", None]
    rep = fb.take_spec_report()
    assert len(rep) == 2
    assert rep[0].draft_tokens > 0
    assert rep[0].accepted_tokens == rep[0].draft_tokens // 2
    assert rep[1].draft_tokens == 0  # no reference
    assert fb.take_spec_report() == []
    # spec off -> empty report, references still accepted silently
    fb2 = FakeBackend()
    fb2.generate(["a"], references=["r"])
    assert fb2.take_spec_report() == []


def test_strategies_thread_chunk_references_to_backend():
    """The mapreduce map round must hand each chunk to the backend as that
    prompt's reference — the seam speculation rides end to end."""
    from vnsum_tpu.backend.fake import FakeBackend
    from vnsum_tpu.strategies.mapreduce import MapReduceStrategy
    from vnsum_tpu.text.splitter import RecursiveTokenSplitter
    from vnsum_tpu.text.tokenizer import whitespace_token_count

    fb = FakeBackend(spec_k=2)
    splitter = RecursiveTokenSplitter(
        40, 5, length_function=whitespace_token_count
    )
    st = MapReduceStrategy(fb, splitter, token_max=60)
    doc = " ".join(f"từ{i}" for i in range(120))
    res = st.summarize(doc)
    assert res.summary
    assert len(fb.references_seen) == len(fb.calls)
    # every map-round reference is a chunk of the document
    n_chunks = res.num_chunks
    for ref in fb.references_seen[:n_chunks]:
        assert ref and ref in doc


def test_serve_scheduler_attributes_spec_metrics():
    """References ride ServeRequests through the micro-batching scheduler;
    per-request records carry drafting stats and /metrics exports the
    counters (the ISSUE's acceptance-rate observability contract)."""
    from vnsum_tpu.backend.fake import FakeBackend
    from vnsum_tpu.serve.scheduler import MicroBatchScheduler

    fb = FakeBackend(spec_k=4, spec_acceptance=0.25)
    sched = MicroBatchScheduler(fb, max_batch=4, max_wait_s=0.005)
    try:
        comps = sched.generate_sync(
            ["Tóm tắt:\n<content>\nmột hai ba bốn\n</content>"] * 2,
            references=["một hai ba bốn", None],
        )
        recs = [c.record for c in comps]
        assert recs[0].draft_tokens > 0
        assert recs[0].accepted_tokens == recs[0].draft_tokens // 4
        assert recs[1].draft_tokens == 0
        snap = sched.metrics.snapshot()
        assert snap.draft_tokens == recs[0].draft_tokens
        assert snap.accepted_tokens == recs[0].accepted_tokens
        prom = sched.metrics.render_prometheus()
        assert f"vnsum_serve_spec_draft_tokens_total {snap.draft_tokens}" in prom
        assert (
            f"vnsum_serve_spec_accepted_tokens_total {snap.accepted_tokens}"
            in prom
        )
        assert "vnsum_serve_spec_acceptance_rate 0.25" in prom
    finally:
        sched.close()


def test_w8a8_prefill_does_not_quantize_the_verify_forward():
    """Code-review regression: the spec verify forward is multi-token but
    decode-phase — it must NOT trip the w8a8_prefill S>1 gate, or greedy
    spec outputs diverge from plain decode under quantize_act."""
    from vnsum_tpu.backend.engine import TpuBackend

    kw = dict(
        model_config=tiny_llama(max_seq_len=256), batch_size=4,
        max_new_tokens=10, seed=0, quantize=True, quantize_act=True,
    )
    be = TpuBackend(**kw)
    plain = be.generate(PROMPTS)
    spec = be.generate(
        PROMPTS, config=GenerationConfig(spec_k=4), references=REFS
    )
    assert spec == plain


def test_server_default_spec_k_survives_other_knobs():
    """Code-review regression: a request customizing only sampling knobs
    must not silently wipe the server's --spec-k default (the fresh config
    REPLACES the backend default wholesale)."""
    from vnsum_tpu.serve.server import _gen_config_from

    cfg = _gen_config_from({"temperature": 0.7}, default_spec_k=8)
    assert cfg.spec_k == 8 and cfg.temperature == 0.7
    # explicit opt-out wins over the default
    assert _gen_config_from({"spec_k": 0}, default_spec_k=8).spec_k == 0
    # no knobs at all -> None -> the backend's own default config applies
    assert _gen_config_from({}, default_spec_k=8) is None


def test_all_refless_group_takes_the_plain_path():
    """Code-review regression: when a spec call's length-sorted grouping
    puts all the reference-less rows in one group, that group must not pay
    the (k+1)-wide verify forward — it routes to plain decode; its report
    rows come back zeroed and aligned, while the referenced group still
    speculates. An all-empty references list never enters spec at all."""
    from vnsum_tpu.backend.engine import TpuBackend

    be = TpuBackend(
        model_config=tiny_llama(max_seq_len=256), batch_size=2,
        max_new_tokens=8, seed=0,
    )
    # two short refless prompts group together; two long ones carry refs
    prompts = ["a", "b", "một tài liệu dài " * 4, "văn bản nguồn khá dài " * 4]
    refs = [None, None, prompts[2], prompts[3]]
    gen = GenerationConfig(spec_k=4)

    plain = be.generate(prompts)
    spec = be.generate(prompts, config=gen, references=refs)
    assert spec == plain
    report = be.take_spec_report()
    assert len(report) == 4
    assert all(r.verify_steps == 0 for r in report[:2])   # plain-path group
    assert all(r.verify_steps > 0 for r in report[2:])    # spec group

    # an entirely refless call is spec-off: empty report, no counters moved
    before = be.stats.spec_verify_steps
    out = be.generate(prompts[:2], config=gen, references=[None, ""])
    assert out == plain[:2]
    assert be.take_spec_report() == []
    assert be.stats.spec_verify_steps == before
