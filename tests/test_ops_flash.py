import jax
import jax.numpy as jnp
import numpy as np
import pytest

from vnsum_tpu.models.llama import _attention, prefill_attention_mask
from vnsum_tpu.ops.flash_attention import flash_prefill_attention, supports_flash


def make_case(L, B, S, C, H, KV, hd, seed=0):
    kq, kk, kv = jax.random.split(jax.random.key(seed), 3)
    q = jax.random.normal(kq, (B, S, H, hd), jnp.float32)
    k_all = jnp.zeros((L, B, KV, C, hd), jnp.float32)
    v_all = jnp.zeros((L, B, KV, C, hd), jnp.float32)
    # fill only the prefill region like the engine does
    k_all = k_all.at[:, :, :, :S].set(
        jax.random.normal(kk, (L, B, KV, S, hd), jnp.float32)
    )
    v_all = v_all.at[:, :, :, :S].set(
        jax.random.normal(kv, (L, B, KV, S, hd), jnp.float32)
    )
    return q, {"k": k_all, "v": v_all}


@pytest.mark.parametrize("layer", [0, 1])
@pytest.mark.parametrize("pads", [[0, 0], [3, 17]])
def test_flash_matches_dense(layer, pads):
    L, B, S, C, H, KV, hd = 2, 2, 32, 64, 4, 2, 128
    q, cache = make_case(L, B, S, C, H, KV, hd, seed=layer)
    pad = jnp.asarray(pads, jnp.int32)
    mask = prefill_attention_mask(pad, S, C)
    dense = _attention(q, cache["k"][layer], cache["v"][layer], mask, H // KV)
    flash = flash_prefill_attention(
        q, cache, layer, pad, H // KV, interpret=True
    )
    # compare only non-pad rows (pad rows are garbage on both paths)
    for b in range(B):
        np.testing.assert_allclose(
            np.asarray(dense)[b, pads[b] :],
            np.asarray(flash)[b, pads[b] :],
            rtol=2e-5,
            atol=2e-5,
        )


def test_flash_ragged_blocks():
    """S and C with NO large divisors: ceil-div grid + tail masking must
    still match dense (the old divisor-picker collapsed to 32-wide blocks
    at such shapes)."""
    L, B, S, C, H, KV, hd = 1, 1, 45, 61, 2, 1, 128
    q, cache = make_case(L, B, S, C, H, KV, hd, seed=3)
    pad = jnp.asarray([5], jnp.int32)
    mask = prefill_attention_mask(pad, S, C)
    dense = _attention(q, cache["k"][0], cache["v"][0], mask, H // KV)
    flash = flash_prefill_attention(
        q, cache, 0, pad, H // KV, block_q=16, block_k=16, interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(dense)[0, 5:], np.asarray(flash)[0, 5:], rtol=2e-5, atol=2e-5
    )


def test_flash_multiple_k_blocks():
    L, B, S, C, H, KV, hd = 1, 1, 64, 192, 2, 1, 128
    q, cache = make_case(L, B, S, C, H, KV, hd, seed=3)
    pad = jnp.asarray([5], jnp.int32)
    mask = prefill_attention_mask(pad, S, C)
    dense = _attention(q, cache["k"][0], cache["v"][0], mask, H // KV)
    flash = flash_prefill_attention(
        q, cache, 0, pad, H // KV, block_q=32, block_k=64, interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(dense)[0, 5:], np.asarray(flash)[0, 5:], rtol=2e-5, atol=2e-5
    )


@pytest.mark.parametrize("win", [1, 8, 24])
def test_flash_windowed_matches_dense(win):
    """Sliding-window clamp (Gemma local layers): kernel vs the dense path's
    slot-space window mask (models.llama._block: k_slot > q_slot - window),
    on shapes where below-window whole blocks get clamped/elided."""
    L, B, S, C, H, KV, hd = 1, 2, 45, 61, 2, 1, 128
    q, cache = make_case(L, B, S, C, H, KV, hd, seed=9)
    pads = [0, 5]
    pad = jnp.asarray(pads, jnp.int32)
    mask = prefill_attention_mask(pad, S, C)
    in_window = jnp.arange(C)[None, :] > jnp.arange(S)[:, None] - win
    dense = _attention(
        q, cache["k"][0], cache["v"][0], mask & in_window[None], H // KV
    )
    flash = flash_prefill_attention(
        q, cache, 0, pad, H // KV, jnp.int32(win),
        block_q=16, block_k=16, interpret=True,
    )
    for b in range(B):
        np.testing.assert_allclose(
            np.asarray(dense)[b, pads[b]:],
            np.asarray(flash)[b, pads[b]:],
            rtol=2e-5, atol=2e-5,
        )


def test_flash_window_zero_is_global():
    """window=0 must be bit-identical to the no-window call (global layers
    share the compiled program with sliding ones)."""
    L, B, S, C, H, KV, hd = 1, 1, 45, 61, 2, 1, 128
    q, cache = make_case(L, B, S, C, H, KV, hd, seed=4)
    pad = jnp.asarray([5], jnp.int32)
    a = flash_prefill_attention(
        q, cache, 0, pad, H // KV, block_q=16, block_k=16, interpret=True
    )
    b = flash_prefill_attention(
        q, cache, 0, pad, H // KV, jnp.int32(0),
        block_q=16, block_k=16, interpret=True,
    )
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_supports_flash():
    assert supports_flash(1024, 1152, 128)
    assert supports_flash(1001, 1153, 256)  # any S/C via ceil-div grids
    assert not supports_flash(1024, 1152, 64)  # head_dim not a lane multiple


def test_forward_remat_with_attention_fn():
    """remat must treat attention_fn as static, not a traced operand."""
    from vnsum_tpu.models import forward, init_kv_cache, init_params, tiny_llama
    from vnsum_tpu.models.llama import _attention, prefill_positions

    cfg = tiny_llama()
    params = init_params(jax.random.key(0), cfg)
    tokens = jnp.ones((1, 8), jnp.int32)
    pad = jnp.zeros((1,), jnp.int32)
    cache = init_kv_cache(cfg, 1, 8)
    mask = prefill_attention_mask(pad, 8, 8)
    logits, _ = forward(
        params, cfg, tokens, prefill_positions(pad, 8), cache, 0, mask,
        remat=True,
        attention_fn=lambda q, k, v, m, g: _attention(q, k, v, m, g),
    )
    assert bool(jnp.isfinite(logits).all())


def test_unsupported_head_dim_raises():
    L, B, S, C, H, KV, hd = 1, 1, 8, 16, 2, 1, 64
    q, cache = make_case(L, B, S, C, H, KV, hd)
    with pytest.raises(ValueError):
        flash_prefill_attention(
            q, cache, 0, jnp.zeros((1,), jnp.int32), 2
        )


@pytest.mark.parametrize("lo,hi", [(16, 32), (32, 45), (0, 16)])
def test_flash_q_offset_matches_full(lo, hi):
    """Chunked prefill: the kernel run on query slice [lo:hi) with
    q_offset=lo must reproduce the corresponding rows of the whole-prompt
    run (the cache already holds everything the chunk may attend to —
    exactly the state the engine's chunk loop produces)."""
    L, B, S, C, H, KV, hd = 2, 2, 45, 64, 4, 2, 128
    q, cache = make_case(L, B, S, C, H, KV, hd, seed=7)
    pad = jnp.asarray([0, 6], jnp.int32)
    full = flash_prefill_attention(
        q, cache, 1, pad, H // KV, block_q=16, block_k=16, interpret=True
    )
    chunk = flash_prefill_attention(
        q[:, lo:hi], cache, 1, pad, H // KV, None, jnp.int32(lo),
        block_q=16, block_k=16, interpret=True,
    )
    for b in range(2):
        valid = max(0, int(pad[b]) - lo)  # rows below the pad are garbage
        np.testing.assert_allclose(
            np.asarray(full)[b, lo + valid : hi],
            np.asarray(chunk)[b, valid:],
            rtol=2e-5, atol=2e-5,
        )


def test_flash_q_offset_with_window():
    """Sliding window + offset: chunk rows still see exactly the last
    `win` slots (slot-space window is offset-invariant)."""
    L, B, S, C, H, KV, hd = 1, 1, 40, 48, 2, 1, 128
    q, cache = make_case(L, B, S, C, H, KV, hd, seed=9)
    pad = jnp.asarray([0], jnp.int32)
    win = jnp.int32(8)
    full = flash_prefill_attention(
        q, cache, 0, pad, H // KV, win, block_q=8, block_k=8, interpret=True
    )
    lo, hi = 24, 40
    chunk = flash_prefill_attention(
        q[:, lo:hi], cache, 0, pad, H // KV, win, jnp.int32(lo),
        block_q=8, block_k=8, interpret=True,
    )
    np.testing.assert_allclose(
        np.asarray(full)[0, lo:hi], np.asarray(chunk)[0],
        rtol=2e-5, atol=2e-5,
    )


def test_flash_bf16_compute_dtype_close_to_f32():
    """The kernel computes its dots in the QUERY dtype (f32 tests exact;
    the engine's bf16 gets the MXU full-rate path — the f32 in-kernel dots
    previously made attention 39% of prefill device time for ~18% of its
    FLOPs, artifacts/prefill_gap.json). bf16 inputs must stay within bf16
    rounding of the f32 oracle: f32 accumulation bounds the error at the
    input-rounding level (~1e-2), not O(sqrt(K)) growth."""
    L, B, S, C, H, KV, hd = 2, 2, 32, 64, 4, 2, 128
    q, cache = make_case(L, B, S, C, H, KV, hd, seed=5)
    pad = jnp.asarray([0, 3], jnp.int32)
    oracle = flash_prefill_attention(q, cache, 1, pad, H // KV, interpret=True)
    bf = flash_prefill_attention(
        q.astype(jnp.bfloat16),
        {k: v.astype(jnp.bfloat16) for k, v in cache.items()},
        1, pad, H // KV, interpret=True,
    )
    assert bf.dtype == jnp.bfloat16
    for b in range(B):
        np.testing.assert_allclose(
            np.asarray(oracle, np.float32)[b, int(pad[b]):],
            np.asarray(bf, np.float32)[b, int(pad[b]):],
            rtol=0.05, atol=0.05,
        )


def test_vmem_guard_shrinks_bq_for_wide_groups():
    """G=16 bottoms the bk guard at 512; the continuation must shrink bq
    (not compile-OOM) and the interpreted kernel still matches dense."""
    L, B, S, C, H, KV, hd = 1, 1, 16, 16, 16, 1, 128
    q, cache = make_case(L, B, S, C, H, KV, hd, seed=5)
    pad = jnp.zeros((B,), jnp.int32)
    mask = prefill_attention_mask(pad, S, C)
    dense = _attention(q, cache["k"][0], cache["v"][0], mask, H // KV)
    flash = flash_prefill_attention(
        q, cache, 0, pad, H // KV, interpret=True
    )
    np.testing.assert_allclose(np.asarray(flash), np.asarray(dense),
                               rtol=2e-3, atol=2e-3)


def test_vmem_guard_rejects_explicit_overrides_with_geometry():
    """An explicit block_q that exceeds the scoped-VMEM ceiling must raise
    a ValueError naming the geometry instead of a Mosaic compile OOM."""
    L, B, S, C, H, KV, hd = 1, 1, 4096, 4096, 16, 1, 128
    q = jnp.zeros((B, S, H, hd), jnp.float32)
    cache = {
        "k": jnp.zeros((L, B, KV, C, hd), jnp.float32),
        "v": jnp.zeros((L, B, KV, C, hd), jnp.float32),
    }
    with pytest.raises(ValueError, match="scoped-VMEM.*G=16"):
        flash_prefill_attention(
            q, cache, 0, jnp.zeros((B,), jnp.int32), H // KV,
            block_q=512, block_k=2048,
        )
