"""Hermetic exercise of bench.py's instrumented device-budget phase: the
real run_device_budget flow (two-pass pipeline, split prefill/decode timing,
FLOP + HBM-byte models) on a tiny model and corpus, CPU-only. Guards the
shape of BENCH_r{N}.json's "device_budget" record without TPU hardware."""
import pytest

import bench as bench_mod
from vnsum_tpu.data.synthesize import synthesize_corpus
from vnsum_tpu.models import tiny_llama


@pytest.mark.slow
def test_run_device_budget_tiny(tmp_path, monkeypatch):
    root = str(tmp_path)
    synthesize_corpus(
        f"{root}/corpus", n_docs=2, tokens_per_doc=300, summary_tokens=40,
        seed=3,
    )
    import vnsum_tpu.models as models

    monkeypatch.setattr(
        models, "llama32_3b", lambda **kw: tiny_llama(max_seq_len=512)
    )
    out = bench_mod.run_device_budget(None, root, "byte", (10,))
    assert out["docs"] == 2 and out["chunks"] >= 2
    assert out["prefill_s"] > 0 and out["decode_s"] > 0
    assert out["dispatches"] and all(
        d["steps"] <= 128 for d in out["dispatches"]
    )
    assert 0 <= out["mfu_prefill"] < 1.0
    assert out["decode_roofline_frac"] >= 0
    # phase sum cannot exceed the measured wall clock
    assert (
        out["prefill_s"] + out["decode_s"] <= out["wall_s"] + 0.5
    )
