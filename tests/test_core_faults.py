"""Fault injection + retry policy: containment proofs the reference never
had (SURVEY.md §5 — no retries anywhere, failure handling = per-model
try/except)."""
import pytest

from vnsum_tpu.backend.fake import FakeBackend
from vnsum_tpu.core.faults import (
    FaultInjectingBackend,
    FaultPlan,
    FaultRule,
    RetryingBackend,
    call_with_retries,
)


def flaky(plan_rules, **kw):
    return FaultInjectingBackend(FakeBackend(**kw), FaultPlan(rules=plan_rules))


def test_fault_on_call_index():
    be = flaky([FaultRule(on_call=2)])
    assert be.generate(["<content>a b c</content>"])  # call 1 fine
    with pytest.raises(RuntimeError, match="injected fault"):
        be.generate(["x"])
    assert be.generate(["y"])  # call 3 fine again


def test_fault_every_n_and_corruption():
    be = flaky([FaultRule(kind="corrupt", every_n=2, corruption="hỏng")])
    ok = be.generate(["<content>một hai</content>"])
    bad = be.generate(["<content>một hai</content>"])
    assert ok == ["một hai"] and bad == ["hỏng"]


def test_fault_probability_deterministic():
    plan = FaultPlan(rules=[FaultRule(probability=0.5)], seed=7)
    fired = []
    for i in range(20):
        rule = plan.check()
        fired.append(rule is not None)
    plan2 = FaultPlan(rules=[FaultRule(probability=0.5)], seed=7)
    fired2 = [plan2.check() is not None for _ in range(20)]
    assert fired == fired2 and any(fired) and not all(fired)


def test_retrying_backend_recovers(monkeypatch):
    monkeypatch.setattr("time.sleep", lambda s: None)
    be = RetryingBackend(flaky([FaultRule(on_call=1)]), max_retries=1, backoff=0)
    assert be.generate(["<content>a b</content>"]) == ["a b"]


def test_retrying_backend_gives_up(monkeypatch):
    monkeypatch.setattr("time.sleep", lambda s: None)
    be = RetryingBackend(
        flaky([FaultRule(every_n=1)]), max_retries=2, backoff=0
    )
    with pytest.raises(RuntimeError):
        be.generate(["x"])
    assert be.plan.calls == 3  # 1 try + 2 retries, all injected


def test_call_with_retries_passthrough():
    calls = []

    def fn():
        calls.append(1)
        return "ok"

    assert call_with_retries(fn, max_retries=3) == "ok"
    assert len(calls) == 1


@pytest.fixture()
def workspace(tmp_path):
    docs = tmp_path / "doc"
    refs = tmp_path / "summary"
    docs.mkdir(), refs.mkdir()
    for i in range(3):
        (docs / f"d{i}.txt").write_text(
            "Quốc hội đã thông qua nghị quyết quan trọng. " * 30,
            encoding="utf-8",
        )
        (refs / f"d{i}.txt").write_text("Tóm tắt.", encoding="utf-8")
    return tmp_path


def faulty_pipeline(ws, rules, **cfg_kw):
    from vnsum_tpu.core.config import PipelineConfig
    from vnsum_tpu.eval import EmbeddingModel
    from vnsum_tpu.models.encoder import tiny_encoder
    from vnsum_tpu.pipeline.runner import PipelineRunner

    cfg = PipelineConfig(
        approach="mapreduce", backend="fake", models=["m"],
        docs_dir=str(ws / "doc"), summary_dir=str(ws / "summary"),
        generated_summaries_dir=str(ws / "gen"),
        results_dir=str(ws / "res"), logs_dir=str(ws / "logs"),
        chunk_size=80, chunk_overlap=5, token_max=200, batch_size=3,
        retry_backoff=0, **cfg_kw,
    )
    factory = lambda model: FaultInjectingBackend(
        FakeBackend(), FaultPlan(rules=rules)
    )
    return PipelineRunner(
        cfg,
        backend_factory=factory,
        embedding_model=EmbeddingModel(
            config=tiny_encoder(), max_len=64, batch_size=4
        ),
    )


def test_pipeline_batch_retry_recovers(workspace, monkeypatch):
    """A transient engine fault on one batch must be retried and the run
    must complete with every document successful."""
    monkeypatch.setattr("time.sleep", lambda s: None)
    runner = faulty_pipeline(
        workspace, [FaultRule(on_call=1)], max_batch_retries=1
    )
    results = runner.run()
    rec = results.summarization["m"]
    assert rec["successful"] == 3 and rec["failed"] == 0


def test_pipeline_persistent_fault_contained(workspace, monkeypatch):
    """A persistent fault exhausts retries: the batch's docs are recorded
    failed, and the run still completes with a results record."""
    monkeypatch.setattr("time.sleep", lambda s: None)
    runner = faulty_pipeline(
        workspace, [FaultRule(every_n=1)], max_batch_retries=1
    )
    results = runner.run()
    rec = results.summarization["m"]
    assert rec["failed"] == 3 and rec["successful"] == 0
    assert all(d["status"] == "failed" for d in rec["processing_details"])


def test_retrying_backend_fails_fast_on_permanent_error(monkeypatch):
    """ValueError etc. are programming/input errors — no backoff retries
    (ADVICE r1: mirror the pipeline's PERMANENT_ERRORS fail-fast filter)."""
    calls = []

    class Bad:
        name = "bad"

        def generate(self, prompts, **kw):
            calls.append(1)
            raise ValueError("bad config")

    monkeypatch.setattr("time.sleep", lambda s: None)
    be = RetryingBackend(Bad(), max_retries=3, backoff=0)
    with pytest.raises(ValueError):
        be.generate(["x"])
    assert len(calls) == 1


def test_retrying_backend_retries_json_decode_error(monkeypatch):
    """json.JSONDecodeError subclasses ValueError but is a garbled-body
    transient — it must be retried, not fail-fasted."""
    import json

    calls = []

    class Flaky:
        name = "flaky"

        def generate(self, prompts, **kw):
            calls.append(1)
            if len(calls) == 1:
                raise json.JSONDecodeError("truncated", "{", 1)
            return ["ok"]

    monkeypatch.setattr("time.sleep", lambda s: None)
    be = RetryingBackend(Flaky(), max_retries=2, backoff=0)
    assert be.generate(["x"]) == ["ok"]
    assert len(calls) == 2
