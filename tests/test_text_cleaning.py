from vnsum_tpu.text import clean_thinking_tokens


def test_strips_think_block():
    s = "<think>secret plan</think>Tóm tắt: nội dung chính."
    assert clean_thinking_tokens(s) == "Tóm tắt: nội dung chính."


def test_strips_all_variants_case_insensitive():
    s = (
        "<THINKING>a</THINKING>x<Thought>b</Thought>y"
        "<reasoning>c</reasoning>z<Analysis>d</Analysis>w"
    )
    assert clean_thinking_tokens(s) == "xyzw"


def test_multiline_blocks_and_whitespace_normalization():
    s = "A<think>\nline1\nline2\n</think>\n\n\n\nB"
    assert clean_thinking_tokens(s) == "A\n\nB"


def test_empty_and_none_safe():
    assert clean_thinking_tokens("") == ""


def test_collapse_whitespace_variant():
    s = "a\n\nb\tc"
    assert clean_thinking_tokens(s, collapse_whitespace=True) == "a b c"


def test_unclosed_tag_left_alone():
    s = "<think>never closed"
    assert clean_thinking_tokens(s) == "<think>never closed"
