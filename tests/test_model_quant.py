"""Weight-only int8 quantization: round-trip accuracy, forward fidelity, and
engine integration (models/quant.py)."""
from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from vnsum_tpu.backend.engine import TpuBackend
from vnsum_tpu.core.config import GenerationConfig
from vnsum_tpu.models.llama import (
    forward_train,
    init_params,
    tiny_llama,
)
from vnsum_tpu.models.quant import (
    dequantize_params,
    is_quantized,
    quantize_params,
)


@pytest.fixture(scope="module")
def model():
    cfg = tiny_llama()
    params = init_params(jax.random.key(0), cfg)
    return cfg, params


def test_round_trip_error_bounded(model):
    _, params = model
    qp = quantize_params(params)
    assert is_quantized(qp)
    deq = dequantize_params(qp)
    for name in ("wq", "wo", "w_down"):
        w = np.asarray(params["layers"][name], np.float32)
        d = np.asarray(deq["layers"][name])
        # per-channel int8: error bounded by half a quantization step
        step = np.abs(w).max() / 127.0
        assert np.abs(w - d).max() <= step * 0.51
    # norms pass through untouched
    np.testing.assert_array_equal(
        np.asarray(qp["layers"]["attn_norm"]),
        np.asarray(params["layers"]["attn_norm"]),
    )


def test_quantized_forward_close(model):
    cfg, params = model
    qp = quantize_params(params)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 16), np.int32)
    )
    ref = np.asarray(forward_train(params, cfg, tokens, remat=False))
    quant = np.asarray(forward_train(qp, cfg, tokens, remat=False))
    # int8 weight-only should track full precision closely on logits
    cos = np.sum(ref * quant, -1) / (
        np.linalg.norm(ref, axis=-1) * np.linalg.norm(quant, axis=-1)
    )
    assert cos.min() > 0.999
    # greedy choice agreement on the vast majority of positions
    agree = (ref.argmax(-1) == quant.argmax(-1)).mean()
    assert agree > 0.9


def test_untied_lm_head_quantization():
    cfg = tiny_llama(tie_embeddings=False)
    params = init_params(jax.random.key(1), cfg)
    qp = quantize_params(params)
    assert "lm_head" in qp and is_quantized(qp)
    tokens = jnp.asarray(
        np.random.default_rng(1).integers(0, cfg.vocab_size, (1, 8), np.int32)
    )
    ref = np.asarray(forward_train(params, cfg, tokens, remat=False))
    quant = np.asarray(forward_train(qp, cfg, tokens, remat=False))
    assert np.corrcoef(ref.ravel(), quant.ravel())[0, 1] > 0.999


def test_engine_quantized_generation(model):
    cfg, _ = model
    backend = TpuBackend(
        model_config=cfg,
        tokenizer="byte",
        batch_size=2,
        max_new_tokens=8,
        quantize=True,
        flash=False,
        generation=GenerationConfig(temperature=0.0),
    )
    outs = backend.generate(["Xin chào Việt Nam.", "Quốc hội đã họp."])
    assert len(outs) == 2
    assert all(isinstance(o, str) for o in outs)
    # deterministic across calls (greedy, fixed seed)
    outs2 = backend.generate(["Xin chào Việt Nam.", "Quốc hội đã họp."])
    assert outs == outs2


def test_quantized_param_specs_match_tree():
    """The quantized PartitionSpec tree must be structurally identical to a
    quantized param tree, with each scale spec = weight spec minus the
    contracted axes (so scales shard with their output channels)."""
    from jax.sharding import PartitionSpec as P

    from vnsum_tpu.models import init_params
    from vnsum_tpu.models.llama import LlamaConfig
    from vnsum_tpu.models.quant import quantize_params
    from vnsum_tpu.parallel.sharding import param_specs

    cfg = LlamaConfig(
        vocab_size=64, dim=16, n_layers=2, n_heads=4, n_kv_heads=2,
        head_dim=4, intermediate=32, max_seq_len=32,
        use_llama3_rope_scaling=False, tie_embeddings=False,
    )
    qparams = quantize_params(init_params(jax.random.key(0), cfg))
    specs = param_specs(tie_embeddings=False, quantized=True)
    # same tree structure, and every spec rank matches its leaf rank
    flat_p = jax.tree.structure(qparams)
    flat_s = jax.tree.structure(specs, is_leaf=lambda x: isinstance(x, P))
    assert flat_p == flat_s
    for leaf, spec in zip(
        jax.tree.leaves(qparams),
        jax.tree.leaves(specs, is_leaf=lambda x: isinstance(x, P)),
    ):
        assert leaf.ndim == len(spec), (leaf.shape, spec)


def test_init_params_quantized_runs_engine():
    """Direct-int8 random init (no bf16 tree ever resident — the only way a
    14B fits one chip) must produce the exact quantize_params layout and
    drive the engine end to end."""
    import jax

    from vnsum_tpu.backend.engine import TpuBackend
    from vnsum_tpu.models import jitted_init, tiny_llama
    from vnsum_tpu.models.quant import init_params_quantized, is_quantized

    cfg = tiny_llama(max_seq_len=128)
    params = jitted_init(init_params_quantized, cfg, seed=1)
    assert is_quantized(params)
    assert params["layers"]["wq"]["q"].dtype == jax.numpy.int8
    be = TpuBackend(
        model_config=cfg, params=params, batch_size=2, max_new_tokens=6
    )
    outs = be.generate(["văn bản", "hai"])
    assert len(outs) == 2 and all(isinstance(o, str) for o in outs)


def test_w8a8_proj_exact_on_rounded_activations():
    """_proj(act_quant=True) must equal the EXACT computation over the
    int8-rounded activations and dequantized weights — the only loss is the
    activation rounding itself. Checked for all four einsum shapes the
    decoder uses."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from vnsum_tpu.models.llama import _proj
    from vnsum_tpu.models.quant import _quantize

    rng = jax.random.PRNGKey(0)
    B, S, D, H, hd, I = 2, 4, 32, 4, 8, 48
    cases = [
        ("bsd,dhk->bshk", (B, S, D), (D, H, hd), (0,)),
        ("bshk,hkd->bsd", (B, S, H, hd), (H, hd, D), (0, 1)),
        ("bsd,di->bsi", (B, S, D), (D, I), (0,)),
        ("bsi,id->bsd", (B, S, I), (I, D), (0,)),
    ]
    for sub, xs, ws, contract in cases:
        kx, kw, rng = jax.random.split(rng, 3)
        x = jax.random.normal(kx, xs, jnp.float32)
        w = jax.random.normal(kw, ws, jnp.float32)
        wq = _quantize(w, contract)
        got = np.asarray(_proj(sub, x, wq, act_quant=True))

        # reference: round x per token over its contracted trailing dims,
        # then the exact f32 einsum against the dequantized weight
        axes = tuple(range(len(xs) - len(contract), len(xs)))
        amax = np.max(np.abs(np.asarray(x)), axis=axes, keepdims=True)
        s = np.maximum(amax, 1e-8) / 127.0
        x_r = np.clip(np.round(np.asarray(x) / s), -127, 127) * s
        sdeq = np.asarray(wq["s"])
        for a in sorted(contract):
            sdeq = np.expand_dims(sdeq, a)
        w_deq = np.asarray(wq["q"], np.float32) * sdeq
        want = np.einsum(sub, x_r, w_deq)
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_w8a8_engine_runs_and_rejects_without_int8_weights():
    import pytest

    from vnsum_tpu.backend.engine import TpuBackend
    from vnsum_tpu.models import tiny_llama

    cfg = tiny_llama(max_seq_len=128)
    kw = dict(model_config=cfg, batch_size=2, max_new_tokens=8, seed=0)
    with pytest.raises(ValueError, match="quantize_act"):
        TpuBackend(quantize_act=True, **kw)
    w8a8 = TpuBackend(quantize=True, quantize_act=True, **kw)
    outs = w8a8.generate(["một văn bản dài hơn", "hai"])
    assert len(outs) == 2 and all(isinstance(o, str) for o in outs)
    assert w8a8.cfg.w8a8_prefill


def test_w8a8_single_token_forward_bit_identical():
    """The S>1 gate's precise claim, tested at the forward level: a
    SINGLE-token forward (what every decode step is) must be bit-identical
    with and without w8a8_prefill — and a multi-token forward must differ
    (the flag actually does something)."""
    import dataclasses

    import jax
    import jax.numpy as jnp
    import numpy as np

    from vnsum_tpu.models import init_kv_cache, tiny_llama
    from vnsum_tpu.models.llama import (
        decode_attention_mask,
        forward,
        init_params,
        prefill_attention_mask,
        prefill_positions,
    )
    from vnsum_tpu.models.quant import quantize_params

    cfg_a = tiny_llama(max_seq_len=128)
    cfg_b = dataclasses.replace(cfg_a, w8a8_prefill=True)
    params = quantize_params(init_params(jax.random.key(0), cfg_a))
    B, C = 2, 16
    pad = jnp.zeros((B,), jnp.int32)

    # single token at decode position: identical graphs -> identical bits
    tok1 = jnp.asarray([[5], [9]], jnp.int32)
    cache = init_kv_cache(cfg_a, B, C)
    mask1 = decode_attention_mask(pad, 0, C)
    out_a, _ = forward(params, cfg_a, tok1, pad[:, None], cache, 0, mask1)
    out_b, _ = forward(params, cfg_b, tok1, pad[:, None], cache, 0, mask1)
    np.testing.assert_array_equal(np.asarray(out_a), np.asarray(out_b))

    # multi-token prefill: the act-quant rounding must show up
    S = 8
    toks = jnp.tile(jnp.arange(S, dtype=jnp.int32)[None], (B, 1)) + 3
    cache = init_kv_cache(cfg_a, B, C)
    maskS = prefill_attention_mask(pad, S, C)
    pos = prefill_positions(pad, S)
    pre_a, _ = forward(params, cfg_a, toks, pos, cache, 0, maskS)
    pre_b, _ = forward(params, cfg_b, toks, pos, cache, 0, maskS)
    assert not np.array_equal(np.asarray(pre_a), np.asarray(pre_b))


def test_w8a8_mesh_sharded_matches_single_device():
    """W8A8 prefill under a (data, model) mesh: the s8xs8 einsums partition
    like any dot, and sharded outputs must equal unsharded exactly (same
    rounding both sides)."""
    import numpy as np

    from vnsum_tpu.backend.engine import TpuBackend
    from vnsum_tpu.models import tiny_llama
    from vnsum_tpu.parallel import make_mesh

    cfg = tiny_llama(max_seq_len=128)
    kw = dict(
        model_config=cfg, batch_size=4, max_new_tokens=6, seed=3,
        quantize=True, quantize_act=True,
    )
    plain = TpuBackend(**kw)
    mesh = make_mesh({"data": 2, "model": 2, "seq": 1}, platform="cpu")
    sharded = TpuBackend(mesh=mesh, **kw)
    prompts = ["văn bản một", "văn bản thứ hai dài hơn", "ba", "bốn bốn"]
    np.testing.assert_array_equal(
        plain.generate(prompts), sharded.generate(prompts)
    )
