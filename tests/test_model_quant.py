"""Weight-only int8 quantization: round-trip accuracy, forward fidelity, and
engine integration (models/quant.py)."""
from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from vnsum_tpu.backend.engine import TpuBackend
from vnsum_tpu.core.config import GenerationConfig
from vnsum_tpu.models.llama import (
    forward_train,
    init_params,
    tiny_llama,
)
from vnsum_tpu.models.quant import (
    dequantize_params,
    is_quantized,
    quantize_params,
)


@pytest.fixture(scope="module")
def model():
    cfg = tiny_llama()
    params = init_params(jax.random.key(0), cfg)
    return cfg, params


def test_round_trip_error_bounded(model):
    _, params = model
    qp = quantize_params(params)
    assert is_quantized(qp)
    deq = dequantize_params(qp)
    for name in ("wq", "wo", "w_down"):
        w = np.asarray(params["layers"][name], np.float32)
        d = np.asarray(deq["layers"][name])
        # per-channel int8: error bounded by half a quantization step
        step = np.abs(w).max() / 127.0
        assert np.abs(w - d).max() <= step * 0.51
    # norms pass through untouched
    np.testing.assert_array_equal(
        np.asarray(qp["layers"]["attn_norm"]),
        np.asarray(params["layers"]["attn_norm"]),
    )


def test_quantized_forward_close(model):
    cfg, params = model
    qp = quantize_params(params)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 16), np.int32)
    )
    ref = np.asarray(forward_train(params, cfg, tokens, remat=False))
    quant = np.asarray(forward_train(qp, cfg, tokens, remat=False))
    # int8 weight-only should track full precision closely on logits
    cos = np.sum(ref * quant, -1) / (
        np.linalg.norm(ref, axis=-1) * np.linalg.norm(quant, axis=-1)
    )
    assert cos.min() > 0.999
    # greedy choice agreement on the vast majority of positions
    agree = (ref.argmax(-1) == quant.argmax(-1)).mean()
    assert agree > 0.9


def test_untied_lm_head_quantization():
    cfg = tiny_llama(tie_embeddings=False)
    params = init_params(jax.random.key(1), cfg)
    qp = quantize_params(params)
    assert "lm_head" in qp and is_quantized(qp)
    tokens = jnp.asarray(
        np.random.default_rng(1).integers(0, cfg.vocab_size, (1, 8), np.int32)
    )
    ref = np.asarray(forward_train(params, cfg, tokens, remat=False))
    quant = np.asarray(forward_train(qp, cfg, tokens, remat=False))
    assert np.corrcoef(ref.ravel(), quant.ravel())[0, 1] > 0.999


def test_engine_quantized_generation(model):
    cfg, _ = model
    backend = TpuBackend(
        model_config=cfg,
        tokenizer="byte",
        batch_size=2,
        max_new_tokens=8,
        quantize=True,
        flash=False,
        generation=GenerationConfig(temperature=0.0),
    )
    outs = backend.generate(["Xin chào Việt Nam.", "Quốc hội đã họp."])
    assert len(outs) == 2
    assert all(isinstance(o, str) for o in outs)
    # deterministic across calls (greedy, fixed seed)
    outs2 = backend.generate(["Xin chào Việt Nam.", "Quốc hội đã họp."])
    assert outs == outs2


def test_quantized_param_specs_match_tree():
    """The quantized PartitionSpec tree must be structurally identical to a
    quantized param tree, with each scale spec = weight spec minus the
    contracted axes (so scales shard with their output channels)."""
    from jax.sharding import PartitionSpec as P

    from vnsum_tpu.models import init_params
    from vnsum_tpu.models.llama import LlamaConfig
    from vnsum_tpu.models.quant import quantize_params
    from vnsum_tpu.parallel.sharding import param_specs

    cfg = LlamaConfig(
        vocab_size=64, dim=16, n_layers=2, n_heads=4, n_kv_heads=2,
        head_dim=4, intermediate=32, max_seq_len=32,
        use_llama3_rope_scaling=False, tie_embeddings=False,
    )
    qparams = quantize_params(init_params(jax.random.key(0), cfg))
    specs = param_specs(tie_embeddings=False, quantized=True)
    # same tree structure, and every spec rank matches its leaf rank
    flat_p = jax.tree.structure(qparams)
    flat_s = jax.tree.structure(specs, is_leaf=lambda x: isinstance(x, P))
    assert flat_p == flat_s
    for leaf, spec in zip(
        jax.tree.leaves(qparams),
        jax.tree.leaves(specs, is_leaf=lambda x: isinstance(x, P)),
    ):
        assert leaf.ndim == len(spec), (leaf.shape, spec)


def test_init_params_quantized_runs_engine():
    """Direct-int8 random init (no bf16 tree ever resident — the only way a
    14B fits one chip) must produce the exact quantize_params layout and
    drive the engine end to end."""
    import jax

    from vnsum_tpu.backend.engine import TpuBackend
    from vnsum_tpu.models import jitted_init, tiny_llama
    from vnsum_tpu.models.quant import init_params_quantized, is_quantized

    cfg = tiny_llama(max_seq_len=128)
    params = jitted_init(init_params_quantized, cfg, seed=1)
    assert is_quantized(params)
    assert params["layers"]["wq"]["q"].dtype == jax.numpy.int8
    be = TpuBackend(
        model_config=cfg, params=params, batch_size=2, max_new_tokens=6
    )
    outs = be.generate(["văn bản", "hai"])
    assert len(outs) == 2 and all(isinstance(o, str) for o in outs)
