"""n-gram reference drafter (vnsum_tpu.spec.drafter) on Vietnamese text:
syllable-heavy inputs with diacritics, no-match rows, draft-length clamping
at the reference end, and jnp/host implementation equivalence.

Fast tier: pure array ops, no model compiles.
"""
import numpy as np
import pytest

from vnsum_tpu.spec import (
    NO_TOKEN,
    encode_references,
    history_tail,
    propose_drafts,
    propose_drafts_host,
)
from vnsum_tpu.text.tokenizer import get_tokenizer


def _pack(rows, fill=NO_TOKEN):
    R = max(len(r) for r in rows)
    out = np.full((len(rows), R), fill, dtype=np.int32)
    lens = np.zeros((len(rows),), dtype=np.int32)
    for i, r in enumerate(rows):
        out[i, : len(r)] = r
        lens[i] = len(r)
    return out, lens


def _tail(rows, n):
    out = np.full((len(rows), n), NO_TOKEN, dtype=np.int32)
    for i, r in enumerate(rows):
        take = r[-n:]
        out[i, n - len(take):] = take
    return out


def test_vietnamese_syllables_draft_the_continuation():
    """A diacritic-heavy Vietnamese sentence encodes to multi-byte UTF-8
    sequences; matching the emitted suffix must propose the exact byte
    continuation from the reference."""
    tok = get_tokenizer("byte")
    text = "Quốc hội đã thông qua nghị quyết về phát triển kinh tế xã hội."
    ids = tok.encode(text, add_bos=False)
    assert len(ids) > len(text)  # diacritics: multi-byte syllables

    ref, lens = _pack([ids])
    # emitted stream so far = the first 12 reference tokens; the 8-byte
    # suffix "c hội" occurs once, so the match is unambiguous ("hội" alone
    # also ends the sentence — a shorter tail would legitimately draft from
    # the LATER occurrence under the tie-break rule)
    tail = _tail([ids[:12]], 8)
    drafts, n = propose_drafts(ref, lens, tail, 8)
    drafts, n = np.asarray(drafts), np.asarray(n)
    assert n[0] == 8
    np.testing.assert_array_equal(drafts[0], ids[12:20])

    # the repeated-syllable case: a short tail ending at "hội" prefers the
    # sentence-final occurrence, whose continuation is the closing "."
    tail_short = _tail([ids[:12]], 4)
    drafts_s, n_s = propose_drafts(ref, lens, tail_short, 8)
    assert int(np.asarray(n_s)[0]) == 1
    assert bytes([int(np.asarray(drafts_s)[0, 0])]) == b"."


def test_no_match_and_no_reference_rows_propose_nothing():
    tok = get_tokenizer("byte")
    ids = tok.encode("văn bản nguồn về kinh tế", add_bos=False)
    ref, lens = _pack([ids, ids])
    lens[1] = 0  # row 1: no reference at all (ref tokens present but dead)
    # row 0's tail shares no byte with the reference
    tail = np.full((2, 3), NO_TOKEN, dtype=np.int32)
    tail[0] = [1, 2, 3]
    tail[1, -1] = ids[0]
    drafts, n = propose_drafts(ref, lens, tail, 4)
    assert np.asarray(n).tolist() == [0, 0]
    assert np.asarray(drafts).sum() == 0


def test_draft_length_clamps_at_reference_end():
    """A match near the end proposes only what remains; a match AT the end
    proposes nothing (no continuation exists)."""
    ref, lens = _pack([[10, 11, 12, 13, 14], [20, 21, 22]])
    tail = _tail([[12, 13], [21, 22]], 2)
    drafts, n = propose_drafts(ref, lens, tail, 4)
    drafts, n = np.asarray(drafts), np.asarray(n)
    assert n[0] == 1  # only token 14 remains after ..12,13
    assert drafts[0, 0] == 14
    assert n[1] == 0  # ..21,22 ends the reference


def test_longest_match_beats_shorter_and_later_position_breaks_ties():
    # token 5 appears twice; the 3-gram [7, 8, 5] appears once — the longer
    # match must win even though a later bare 5 exists
    ref, lens = _pack([[7, 8, 5, 30, 31, 9, 5, 40, 41]])
    tail = _tail([[7, 8, 5]], 3)
    drafts, n = propose_drafts(ref, lens, tail, 2)
    np.testing.assert_array_equal(np.asarray(drafts)[0], [30, 31])
    # a pure 1-gram tail of 5 matches both occurrences: the LATER one wins
    tail1 = _tail([[5]], 3)
    drafts1, n1 = propose_drafts(ref, lens, tail1, 2)
    np.testing.assert_array_equal(np.asarray(drafts1)[0], [40, 41])


def test_jnp_and_host_drafters_agree_on_random_cases():
    rng = np.random.default_rng(7)
    for trial in range(20):
        B = int(rng.integers(1, 5))
        R = int(rng.integers(4, 40))
        N = int(rng.integers(1, 5))
        k = int(rng.integers(1, 6))
        ref = rng.integers(0, 6, size=(B, R)).astype(np.int32)
        lens = rng.integers(0, R + 1, size=(B,)).astype(np.int32)
        tail = rng.integers(0, 6, size=(B, N)).astype(np.int32)
        # sprinkle NO_TOKEN padding into some tails (short histories)
        for b in range(B):
            cut = int(rng.integers(0, N))
            tail[b, :cut] = NO_TOKEN
        dj, nj = propose_drafts(ref, lens, tail, k)
        dh, nh = propose_drafts_host(ref, lens, tail, k)
        np.testing.assert_array_equal(np.asarray(nj), nh, err_msg=f"trial {trial}")
        np.testing.assert_array_equal(np.asarray(dj), dh, err_msg=f"trial {trial}")


def test_encode_references_truncates_and_handles_none():
    tok = get_tokenizer("byte")
    long = "tài liệu " * 100
    ref, lens = encode_references(tok, [long, None, "ngắn"], max_ref_tokens=64)
    assert ref.shape[1] == 64
    assert lens[0] == 64
    assert lens[1] == 0
    assert (ref[1] == NO_TOKEN).all()
    assert lens[2] == len(tok.encode("ngắn", add_bos=False))


def test_history_tail_pads_short_streams():
    out = np.array([[1, 2, 3, 0], [7, 0, 0, 0]], dtype=np.int32)
    tail = history_tail(out, np.array([3, 1]), np.array([9, 5]), 3)
    np.testing.assert_array_equal(tail, [[2, 3, 9], [NO_TOKEN, 7, 5]])


def test_drafted_tokens_never_include_reference_padding():
    """Drafts past n_draft are 0-filled, never NO_TOKEN — they must stay
    feedable to a forward pass as inert filler."""
    ref, lens = _pack([[3, 4]])
    tail = _tail([[3]], 2)
    drafts, n = propose_drafts(ref, lens, tail, 6)
    drafts = np.asarray(drafts)
    assert int(np.asarray(n)[0]) == 1
    assert (drafts[0, 1:] == 0).all()
    assert (drafts >= 0).all()
