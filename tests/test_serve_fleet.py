"""Replica-fleet router tests: in-process engine workers (ServeState +
FakeBackend on ephemeral ports) behind an in-process RouterState — routing
spread, cache-affinity stickiness, end-to-end request-id propagation,
inline journal-handoff failover, startup replay, the typed /readyz
contract, front-door sheds, and the router /metrics surface. Process-level
chaos (SIGKILL mid-load, rolling restarts) lives in
scripts/chaos_soak.py --fleet; these tests pin the mechanism."""
from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
import zlib

import pytest

from vnsum_tpu.backend.fake import FakeBackend
from vnsum_tpu.serve.journal import RequestJournal, aggregate_status
from vnsum_tpu.serve.router import (
    RouterState,
    Worker,
    _RouterRequest,
    make_router_server,
    request_body_from_payload,
)
from vnsum_tpu.serve.server import ServeState, make_server
from vnsum_tpu.testing.chaos import free_port, http_delete, http_json


def _spawn_inproc_worker(name: str):
    """One in-process engine worker: full ServeState over FakeBackend on
    an ephemeral port — the /v1/* surface the router proxies to, without
    subprocess startup cost."""
    state = ServeState(FakeBackend(), max_batch=8, max_wait_s=0.005)
    server = make_server(state, "127.0.0.1", 0)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    port = server.server_address[1]
    return Worker(name, "127.0.0.1", port), (server, state, thread)


def _mark_up(state: RouterState) -> None:
    with state._lock:
        for w in state.workers:
            w.up = True


@pytest.fixture()
def fleet(tmp_path):
    """Two in-process workers behind a journaled router (probe loop ON,
    fast cadence). Yields (base_url, router_state, workers)."""
    w0, h0 = _spawn_inproc_worker("w0")
    w1, h1 = _spawn_inproc_worker("w1")
    state = RouterState(
        [w0, w1],
        journal_dir=tmp_path / "router",
        probe_interval_s=0.05,
        probe_timeout_s=2.0,
        down_after=2,
        up_after=1,
        tenants={"alpha": "interactive", "beta": "batch"},
    )
    state.start()
    server = make_router_server(state, "127.0.0.1", 0)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    state.wait_ready(timeout_s=10.0)
    yield f"http://127.0.0.1:{server.server_address[1]}", state, [w0, w1]
    server.shutdown()
    server.server_close()
    state.close(drain_timeout_s=5.0)
    for server_, sstate, _t in (h0, h1):
        server_.shutdown()
        server_.server_close()
        sstate.close()


def _post(url, payload, headers=None):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=60) as resp:
        return resp.status, json.loads(resp.read()), dict(resp.headers)


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as resp:
        return resp.status, resp.read()


def test_router_proxies_generate_and_summarize(fleet):
    base, state, _workers = fleet
    status, body, _ = _post(base + "/v1/generate",
                            {"prompt": "xin chào fleet",
                             "max_new_tokens": 8, "request_id": "f-gen"})
    assert status == 200
    assert body["request_id"] == "f-gen"
    assert body["completions"][0]["text"]
    status, body, _ = _post(base + "/v1/summarize",
                            {"text": "nội dung tiếng Việt có dấu. " * 30,
                             "request_id": "f-sum"})
    assert status == 200
    assert body["summary"] and body["approach"]
    # both landed in the GLOBAL ledger as completed
    for rid in ("f-gen", "f-sum"):
        assert aggregate_status(state.journal.lookup(rid)) == "completed"


def test_least_loaded_spreads_across_workers(fleet):
    base, _state, workers = fleet
    for i in range(8):
        status, _, _ = _post(base + "/v1/generate",
                             {"prompt": f"tin số {i}",
                              "request_id": f"spread-{i}"})
        assert status == 200
    counts = [w.requests for w in workers]
    assert sum(counts) == 8
    # no-affinity traffic must not pile onto one worker
    assert all(c > 0 for c in counts)


def test_cache_affinity_is_sticky(fleet):
    base, _state, workers = fleet
    before = [w.requests for w in workers]
    for i in range(6):
        status, _, _ = _post(
            base + "/v1/generate",
            {"prompt": f"cùng tiền tố, đuôi {i}",
             "cache_hint": "shared-prefix-A", "request_id": f"aff-{i}"},
        )
        assert status == 200
    deltas = [w.requests - b for w, b in zip(workers, before)]
    # rendezvous hashing: one worker took all six, the other none
    assert sorted(deltas) == [0, 6]


def test_request_id_and_tenant_propagate_end_to_end(fleet):
    """Satellite: ONE id crosses the router->worker hop — the client's
    X-Request-Id is the router's journal rid, the response echo, AND the
    worker-side trace id visible in that worker's /debug/trace ring."""
    base, state, workers = fleet
    rid = "trace-me-e2e"
    status, body, headers = _post(
        base + "/v1/generate",
        {"prompt": "định danh xuyên suốt"},
        headers={"X-Request-Id": rid, "X-Tenant": "alpha"},
    )
    assert status == 200
    assert body["request_id"] == rid
    assert headers["X-Request-Id"] == rid
    # the worker journaled/traced the SAME id (no router-side rewrite)
    assert body["completions"][0]["record"]["trace_id"] == rid
    found = False
    for w in workers:
        s, raw = _get(f"http://{w.host}:{w.port}/debug/trace")
        if s == 200 and rid in raw.decode():
            found = True
    assert found, "request id never appeared in any worker's trace ring"
    # the router ledger holds the same rid, completed
    assert aggregate_status(state.journal.lookup(rid)) == "completed"
    # tenant accounting happened at the front door
    s, raw = _get(base + "/healthz")
    assert json.loads(raw)["tenant_requests"].get("alpha", 0) >= 1


def test_unknown_tenant_is_typed_400(fleet):
    base, _state, _workers = fleet
    req = urllib.request.Request(
        base + "/v1/generate",
        data=json.dumps({"prompt": "x"}).encode(),
        headers={"Content-Type": "application/json", "X-Tenant": "ghost"},
        method="POST",
    )
    with pytest.raises(urllib.error.HTTPError) as exc:
        urllib.request.urlopen(req, timeout=10)
    assert exc.value.code == 400
    body = json.loads(exc.value.read())
    assert "ghost" in body["error"] and "alpha" in body["tenants"]


def test_stream_is_typed_501(fleet):
    base, _state, _workers = fleet
    req = urllib.request.Request(
        base + "/v1/generate",
        data=json.dumps({"prompt": "x", "stream": True}).encode(),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    with pytest.raises(urllib.error.HTTPError) as exc:
        urllib.request.urlopen(req, timeout=10)
    assert exc.value.code == 501
    assert json.loads(exc.value.read())["error"] == "stream_unsupported"


def _hint_for(workers, target_name: str) -> str:
    """A cache_hint whose rendezvous hash lands on ``target_name``."""
    for i in range(1000):
        hint = f"hint-{i}"
        best = max(workers, key=lambda w: zlib.crc32(
            f"{hint}|{w.name}".encode()
        ))
        if best.name == target_name:
            return hint
    raise AssertionError("no hint found")  # pragma: no cover


def test_inline_failover_replays_onto_survivor(tmp_path):
    """A worker that dies with the client still on the line: the proxy
    thread claims the journaled rids and re-dispatches onto the survivor —
    the client sees a 200, never the death."""
    live, handles = _spawn_inproc_worker("live")
    dead = Worker("dead", "127.0.0.1", free_port())  # nothing listening
    state = RouterState([dead, live], journal_dir=tmp_path / "router")
    # no probe loop: both marked up by hand so the dead endpoint is
    # deterministically picked first via affinity
    _mark_up(state)
    with state._lock:
        state._replay_started = state._replay_done = True
    server = make_router_server(state, "127.0.0.1", 0)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    base = f"http://127.0.0.1:{server.server_address[1]}"
    try:
        hint = _hint_for([dead, live], "dead")
        status, body, _ = _post(
            base + "/v1/generate",
            {"prompt": "sống sót qua failover", "cache_hint": hint,
             "request_id": "failover-1"},
        )
        assert status == 200
        text = body["completions"][0]["text"]
        assert aggregate_status(state.journal.lookup("failover-1")) \
            == "completed"
        assert dead.failovers >= 1 and live.requests >= 1
        # byte-identical to a direct hit on the survivor (deterministic
        # greedy engine + same payload)
        s2, direct, _ = _post(
            f"http://{live.host}:{live.port}/v1/generate",
            {"prompt": "sống sót qua failover", "cache_hint": hint},
        )
        assert s2 == 200 and direct["completions"][0]["text"] == text
    finally:
        server.shutdown()
        server.server_close()
        state.close(drain_timeout_s=2.0)
        handles[0].shutdown()
        handles[0].server_close()
        handles[1].close()


def test_failover_preserves_trace_identity_on_survivor(tmp_path):
    """Regression: the journal-handoff replay after a worker death must
    carry the ORIGINAL trace id onto the survivor — the client-facing
    request id, the X-Request-Id response header, and the survivor's own
    span ring all name the same trace, so the merged fleet trace can join
    the pre- and post-failover halves."""
    live, handles = _spawn_inproc_worker("live")
    dead = Worker("dead", "127.0.0.1", free_port())
    state = RouterState([dead, live], journal_dir=tmp_path / "router")
    _mark_up(state)
    with state._lock:
        state._replay_started = state._replay_done = True
    server = make_router_server(state, "127.0.0.1", 0)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    base = f"http://127.0.0.1:{server.server_address[1]}"
    try:
        hint = _hint_for([dead, live], "dead")
        status, body, resp_headers = _post(
            base + "/v1/generate",
            {"prompt": "giữ nguyên dấu vết", "cache_hint": hint,
             "request_id": "trace-keep-1"},
        )
        assert status == 200
        assert body["request_id"] == "trace-keep-1"
        assert resp_headers.get("X-Request-Id") == "trace-keep-1"
        # the survivor's span ring traced the replayed hop under the
        # ORIGINAL id (not a router-minted replacement); the worker's
        # trace finishes in its handler's finally — after the response
        # bytes — so poll briefly
        _srv, live_state, _t = handles
        deadline = time.monotonic() + 5.0
        survivor_ids: set = set()
        while time.monotonic() < deadline:
            survivor_ids = {t.trace_id
                            for t in live_state.obs.snapshot()[0]}
            if "trace-keep-1" in survivor_ids:
                break
            time.sleep(0.02)
        assert "trace-keep-1" in survivor_ids
        # and the router's own ring joined the same id, so the two halves
        # stitch into one merged trace
        router_ids = {t.trace_id for t in state.obs.snapshot()[0]}
        assert "trace-keep-1" in router_ids
    finally:
        server.shutdown()
        server.server_close()
        state.close(drain_timeout_s=2.0)
        handles[0].shutdown()
        handles[0].server_close()
        handles[1].close()


def test_startup_replay_hands_unfinished_accepts_to_workers(tmp_path):
    """Router-restart recovery: unfinished ACCEPTs in the router's own
    journal re-dispatch once a worker is routable, and the replayed
    completion is byte-identical to a direct engine answer."""
    jdir = tmp_path / "router"
    journal = RequestJournal(jdir, fsync_interval_s=0.0)
    req = _RouterRequest(trace_id="replay-me",
                         prompt="bản tin chưa hoàn thành",
                         max_new_tokens=12)
    rid = journal.accept(req)
    journal.start(rid)
    journal.close()
    assert rid == "replay-me"

    live, handles = _spawn_inproc_worker("live")
    state = RouterState([live], journal_dir=jdir, probe_interval_s=0.05)
    state.start()
    try:
        state.wait_ready(timeout_s=10.0)
        t_end = time.monotonic() + 10.0
        while time.monotonic() < t_end:
            if aggregate_status(state.journal.lookup(rid)) == "completed":
                break
            time.sleep(0.02)
        entries = {e.rid: e for e in state.journal.lookup(rid)}
        assert entries[rid].terminal and entries[rid].status == "complete"
        s, direct, _ = _post(
            f"http://{live.host}:{live.port}/v1/generate",
            {"prompt": "bản tin chưa hoàn thành", "max_new_tokens": 12},
        )
        assert s == 200
        assert entries[rid].to_dict()["text"] \
            == direct["completions"][0]["text"]
    finally:
        state.close(drain_timeout_s=2.0)
        handles[0].shutdown()
        handles[0].server_close()
        handles[1].close()


def test_router_readyz_typed_states(tmp_path):
    """/readyz on the router: pre_replay before the journal replays,
    no_worker with nothing routable, ready, then draining — each a typed
    reason a load balancer can branch on."""
    live, handles = _spawn_inproc_worker("live")
    state = RouterState([live], journal_dir=tmp_path / "router",
                        probe_interval_s=0.05)
    try:
        ready, reason = state.readiness()
        assert (ready, reason) == (False, "pre_replay")
        with state._lock:
            state._replay_started = state._replay_done = True
        ready, reason = state.readiness()
        assert (ready, reason) == (False, "no_worker")
        _mark_up(state)
        ready, reason = state.readiness()
        assert (ready, reason) == (True, "ready")
        with state._lock:
            state._draining = True
        ready, reason = state.readiness()
        assert (ready, reason) == (False, "draining")
        with state._lock:
            state._draining = False
    finally:
        state.close(drain_timeout_s=1.0)
        handles[0].shutdown()
        handles[0].server_close()
        handles[1].close()


def test_front_door_saturation_is_typed_429(fleet):
    base, state, _workers = fleet
    state.max_inflight = 0  # saturate the front door
    try:
        req = urllib.request.Request(
            base + "/v1/generate",
            data=json.dumps({"prompt": "x"}).encode(),
            headers={"Content-Type": "application/json"}, method="POST",
        )
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req, timeout=10)
        assert exc.value.code == 429
        body = json.loads(exc.value.read())
        assert body["reason"] == "queue_full"
        assert exc.value.headers["Retry-After"]
    finally:
        state.max_inflight = 256


def test_router_metrics_surface(fleet):
    """The router /metrics renders only registered names (doc-lint parity
    with the worker surface) and carries per-worker + journal series."""
    base, _state, _workers = fleet
    _post(base + "/v1/generate", {"prompt": "đo lường"})
    status, raw = _get(base + "/metrics")
    assert status == 200
    text = raw.decode()
    from vnsum_tpu.serve.metrics import metric_names

    registered = set(metric_names())
    for line in text.splitlines():
        if line.startswith("#") or not line.strip():
            continue
        name = line.split("{")[0].split(" ")[0]
        for suffix in ("_bucket", "_sum", "_count"):
            # histogram sample names derive from a registered base
            if name not in registered and name.endswith(suffix):
                name = name[: -len(suffix)]
        assert name in registered, line
    assert 'vnsum_serve_router_requests_total{worker="w0"}' in text
    assert 'vnsum_serve_router_sheds_total{reason="queue_full"}' in text
    assert "vnsum_serve_journal_pending" in text
    assert "vnsum_serve_router_workers_up 2" in text
    # fleet federation re-exports ride the same surface
    assert "vnsum_serve_federation_scrapes_total" in text
    assert 'vnsum_serve_fleet_incidents_total{reason="failover"} 0' in text


def test_cancel_routes_to_ledger(fleet):
    """DELETE on a completed rid answers from the global ledger (terminal
    entries stay terminal — cancel is idempotent, not destructive)."""
    base, state, _workers = fleet
    _post(base + "/v1/generate", {"prompt": "hủy tôi đi",
                                  "request_id": "cancel-me"})
    port = int(base.rsplit(":", 1)[1])
    status, body = http_json("GET", "127.0.0.1", port,
                             "/v1/requests/cancel-me")
    assert status == 200 and body["status"] == "completed"
    status, body = http_delete("127.0.0.1", port,
                               "/v1/requests/cancel-me")
    assert status == 200
    assert aggregate_status(state.journal.lookup("cancel-me")) \
        == "completed"


def test_rolling_restart_endpoint_answers_202(fleet):
    """Unspawned (externally managed) workers: the rolling restart
    accepts, then skips every worker it does not own. The full
    drain-one-restart-one path over real subprocesses runs in
    scripts/chaos_soak.py --fleet."""
    base, state, _workers = fleet
    status, body, _ = _post(base + "/admin/rolling-restart", {})
    assert status == 202 and body["status"] == "rolling"
    t_end = time.monotonic() + 5.0
    while time.monotonic() < t_end:
        with state._lock:
            rolling = state._rolling
        if not rolling:
            break
        time.sleep(0.02)
    result = state.rolling_restart()
    assert result["status"] == "done"
    assert result["skipped"] == ["w0", "w1"] and not result["restarted"]


def test_request_body_from_payload_round_trip():
    """The handoff inverse: journal payload -> re-POST body keeps the
    fields the /v1/* surface accepts and nothing it rejects (summarize
    must not regrow sampling knobs — unknown fields are a typed 400)."""
    payload = {
        "prompt": "văn bản", "max_new_tokens": 32,
        "config": {"temperature": 0.7, "top_k": 40, "top_p": None,
                   "seed": 7, "spec_k": 2, "eos_ids": [0]},
        "reference": None, "cache_hint": "h1", "trace_id": "t",
        "deadline_unix": time.time() + 30.0, "tenant": "alpha",
    }
    path, body, headers = request_body_from_payload("rid-1", payload)
    assert path == "/v1/generate"
    assert body["prompt"] == "văn bản" and body["cache_hint"] == "h1"
    assert body["temperature"] == 0.7 and body["seed"] == 7
    assert "top_p" not in body and "eos_ids" not in body
    assert 0 < body["deadline_ms"] <= 30_000
    assert headers == {"X-Request-Id": "rid-1", "X-Tenant": "alpha"}

    spayload = {"prompt": "tóm tắt dài", "approach": "refine",
                "max_new_tokens": 64, "trace_id": "t2",
                "deadline_unix": None}
    path, body, headers = request_body_from_payload("rid-2", spayload)
    assert path == "/v1/summarize"
    assert body == {"request_id": "rid-2", "max_new_tokens": 64,
                    "text": "tóm tắt dài", "approach": "refine"}
