"""End-to-end request cancellation (ISSUE 13 tentpole): DELETE semantics
across every lifecycle stage, QoS accounting unwind, slot reclamation
without requeue, the cooperative one-shot flag, the journal's typed
CANCELLED terminal, disconnect-triggered cancels, heartbeats, and
Last-Event-ID resume."""
from __future__ import annotations

import http.client
import json
import threading
import time
import urllib.request

import pytest

from vnsum_tpu.backend.fake import FakeBackend
from vnsum_tpu.serve import InflightScheduler, MicroBatchScheduler
from vnsum_tpu.serve.journal import RequestJournal
from vnsum_tpu.serve.qos import TenantTable, parse_tenant_specs
from vnsum_tpu.serve.queue import RequestCancelled
from vnsum_tpu.serve.server import ServeState, make_server


def wait_for(pred, timeout_s: float = 10.0, interval_s: float = 0.01):
    t_end = time.monotonic() + timeout_s
    while time.monotonic() < t_end:
        if pred():
            return True
        time.sleep(interval_s)
    return pred()


# -- scheduler-level lifecycle stages ----------------------------------------


def test_cancel_queued_request_resolves_typed_and_journals(tmp_path):
    journal = RequestJournal(tmp_path / "j")
    backend = FakeBackend(batch_overhead_s=0.15)
    sched = MicroBatchScheduler(backend, max_batch=1, max_wait_s=0.001,
                                journal=journal)
    try:
        f1 = sched.submit("giu dong co ban " * 10, trace_id="busy-1")
        # wait until the engine is actually busy so c-1 stays queued
        assert wait_for(lambda: backend.batch_sizes)
        f2 = sched.submit("yeu cau se bi huy " * 10, trace_id="c-1")
        res = sched.cancel("c-1")
        assert res["known"] and res["cancelled_queued"] == 1
        with pytest.raises(RequestCancelled) as exc:
            f2.result(timeout=10)
        assert exc.value.stage == "queued"
        assert f1.result(timeout=10).text  # the survivor completes
        assert sched.queue.depth == 0
        snap = sched.metrics.snapshot()
        assert snap.cancelled.get("queued") == 1
        # idempotent: a second cancel of the same id answers known, 0 new
        res2 = sched.cancel("c-1")
        assert res2["known"] and res2["cancelled_queued"] == 0
    finally:
        sched.close()
        journal.close()
    entries, _sealed, _torn = RequestJournal.read_state(tmp_path / "j")
    assert entries["c-1"].status == "cancelled"
    assert entries["busy-1"].status == "complete"


def test_cancel_queued_refunds_tenant_token_bucket():
    tenants = TenantTable(parse_tenant_specs(
        "paid:4:1000"))  # rate 1000 tok/s, burst 2000
    backend = FakeBackend(batch_overhead_s=0.2)
    sched = MicroBatchScheduler(backend, max_batch=1, max_wait_s=0.001,
                                tenants=tenants)
    try:
        sched.submit("giu dong co " * 10, trace_id="busy-t")
        assert wait_for(lambda: backend.batch_sizes)
        prompt = "muoi tu trong cau nay de tinh phi dung khong nhi " * 5  # 50
        tokens = backend.count_tokens(prompt)
        before = tenants.stats()["paid"]["bucket_tokens"]
        sched.submit(prompt, trace_id="c-t", tenant="paid")
        after_admit = tenants.stats()["paid"]["bucket_tokens"]
        # the admission billed: the bucket is down by the bill minus
        # whatever refilled while submit ran (1000 tok/s — allow 25ms of
        # elapsed wall clock; a loaded host can stall this thread for
        # several ms between the bill and this read)
        assert after_admit <= before - tokens + 25
        sched.cancel("c-t")
        refunded = tenants.stats()["paid"]["bucket_tokens"]
        # the bill came back (refill noise over the test's ms timescale is
        # positive, so >= the pre-admit level minus a rounding hair)
        assert refunded >= before - 1
    finally:
        sched.close()


def test_cancel_resident_slot_reclaimed_without_requeue_or_pins(tmp_path):
    journal = RequestJournal(tmp_path / "j")
    backend = FakeBackend(segment_words=2, segment_overhead_s=0.02,
                          prefix_cache_blocks=64, cache_block_tokens=4)
    sched = InflightScheduler(backend, slots=2, max_wait_s=0.001,
                              journal=journal)
    try:
        fut = sched.submit("van ban dai can tom tat " * 12, trace_id="r-1")
        # resident: segments are being dispatched for it
        assert wait_for(lambda: sched.metrics.snapshot().segments >= 2)
        sched.cancel("r-1")
        with pytest.raises(RequestCancelled) as exc:
            fut.result(timeout=10)
        assert exc.value.stage == "resident"
        snap = sched.metrics.snapshot()
        assert snap.cancelled.get("resident") == 1
        assert snap.requeues == 0 and snap.preemptions == 0  # NOT a preempt
        # the slot is free again and no prefix pins leaked
        assert wait_for(lambda: sched.slot_state()[1] == 0)
        assert backend.prefix_cache_stats()["pinned_blocks"] == 0
    finally:
        sched.close()
        journal.close()
    entries, _sealed, _torn = RequestJournal.read_state(tmp_path / "j")
    assert entries["r-1"].status == "cancelled"


def test_cancel_resident_lands_within_one_fused_window():
    """--fused-segments coarsens the cancel sweep to host-dispatch
    cadence: a resident cancel must land at the NEXT fused boundary —
    at most the in-flight dispatch plus one, never several windows — and
    reclaim the slot without a requeue."""
    backend = FakeBackend(segment_words=2, segment_overhead_s=0.02)
    sched = InflightScheduler(backend, slots=2, max_wait_s=0.01,
                              fused_segments=4)
    try:
        fut = sched.submit("van ban dai can tom tat " * 12, trace_id="fz-1")
        assert wait_for(
            lambda: sched.metrics.snapshot().fused_dispatches >= 1
        )
        before = sched.metrics.snapshot().fused_dispatches
        sched.cancel("fz-1")
        with pytest.raises(RequestCancelled) as exc:
            fut.result(timeout=10)
        assert exc.value.stage == "resident"
        snap = sched.metrics.snapshot()
        # the sweep ran right after the in-flight dispatch retired: at most
        # one more full fused window elapsed before the cancel landed
        assert snap.fused_dispatches - before <= 2
        assert snap.cancelled.get("resident") == 1
        assert snap.requeues == 0
        assert wait_for(lambda: sched.slot_state()[1] == 0)
    finally:
        sched.close()


def test_cancel_dispatched_one_shot_cooperative_abort(tmp_path):
    """A cancelled one-shot batch stops burning (simulated) device time at
    the next segment boundary instead of decoding to completion, and the
    outcome is typed CANCELLED — never COMPLETE."""
    journal = RequestJournal(tmp_path / "j")
    # ~40-word extractive output x 60ms/step = ~2.4s of decode if not cut
    backend = FakeBackend(per_step_s=0.06, segment_words=1)
    sched = MicroBatchScheduler(backend, max_batch=4, max_wait_s=0.001,
                                journal=journal)
    try:
        t0 = time.monotonic()
        fut = sched.submit("noi dung rat dai se bi huy giua chung " * 8,
                           trace_id="d-1")
        assert wait_for(lambda: backend.batch_sizes)  # dispatch entered
        sched.cancel("d-1")
        with pytest.raises(RequestCancelled) as exc:
            fut.result(timeout=10)
        assert exc.value.stage in ("dispatched", "queued")
        assert time.monotonic() - t0 < 2.0  # aborted well before full decode
        assert backend.cancel_aborts >= 1
        assert sched.metrics.snapshot().cancelled
    finally:
        sched.close()
        journal.close()
    entries, _sealed, _torn = RequestJournal.read_state(tmp_path / "j")
    assert entries["d-1"].status == "cancelled"


def test_cancelled_request_never_resurrected_by_replay(tmp_path):
    journal = RequestJournal(tmp_path / "j")
    backend = FakeBackend(batch_overhead_s=0.15)
    sched = MicroBatchScheduler(backend, max_batch=1, max_wait_s=0.001,
                                journal=journal)
    try:
        sched.submit("giu dong co " * 8, trace_id="busy-r")
        assert wait_for(lambda: backend.batch_sizes)
        fut = sched.submit("se bi huy truoc khi chay " * 8, trace_id="z-1")
        sched.cancel("z-1")
        with pytest.raises(RequestCancelled):
            fut.result(timeout=10)
    finally:
        sched.close()
        journal.close()
    # a reopen COMPACTS the journal: CANCELLED must survive compaction and
    # stay out of the replay set
    reopened = RequestJournal(tmp_path / "j")
    try:
        unfinished = reopened.take_unfinished()
        assert [e.rid for e in unfinished] == []
        assert "z-1" not in {e.rid for e in unfinished}
    finally:
        reopened.close()
    entries, _sealed, _torn = RequestJournal.read_state(tmp_path / "j")
    assert entries["z-1"].status == "cancelled"


# -- HTTP surface -------------------------------------------------------------


@pytest.fixture()
def cancel_server(tmp_path):
    # ~30ms/segment x 20 segments = ~600ms decode per request: long enough
    # that a disconnect at the second event plus the 0.3s idle window lands
    # MID-decode (the cancel must reclaim a live slot, not observe a finish)
    state = ServeState(
        FakeBackend(segment_words=2, segment_overhead_s=0.03,
                    batch_overhead_s=0.005, prefix_cache_blocks=64,
                    cache_block_tokens=4),
        max_batch=4, max_wait_s=0.005, inflight=True, slots=4,
        journal_dir=str(tmp_path / "journal"),
        stream_heartbeat_s=0.05, stream_idle_timeout_s=0.3,
    )
    server = make_server(state, "127.0.0.1", 0)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{server.server_address[1]}", state
    server.shutdown()
    server.server_close()
    state.close()


def _req(base, method, path, payload=None, headers=None):
    import urllib.parse

    u = urllib.parse.urlparse(base)
    conn = http.client.HTTPConnection(u.hostname, u.port, timeout=30)
    try:
        body = json.dumps(payload) if payload is not None else None
        conn.request(method, path, body=body,
                     headers={"Content-Type": "application/json",
                              **(headers or {})})
        resp = conn.getresponse()
        raw = resp.read()
        return resp.status, json.loads(raw) if raw else None
    finally:
        conn.close()


def test_delete_unknown_id_is_typed_404_and_get_regression(cancel_server):
    base, _state = cancel_server
    status, body = _req(base, "DELETE", "/v1/requests/khong-ton-tai")
    assert status == 404 and "error" in body
    # regression: GET of an unknown id is a typed 404, never a 500
    status, body = _req(base, "GET", "/v1/requests/khong-ton-tai")
    assert status == 404 and "error" in body


def test_delete_completed_request_is_idempotent(cancel_server):
    base, _state = cancel_server
    status, _ = _req(base, "POST", "/v1/generate",
                     {"prompt": "ngan gon", "request_id": "done-1"})
    assert status == 200
    for _ in range(2):  # idempotent: same answer both times
        status, body = _req(base, "DELETE", "/v1/requests/done-1")
        assert status == 200
        assert body["status"] == "completed"
        assert body["cancelled_queued"] == 0


def test_delete_gang_cancels_summarize_fanout(cancel_server):
    base, state = cancel_server
    doc = "\n\n".join(
        f"Đoạn {i}: " + "nội dung dài cần tóm tắt kỹ lưỡng. " * 30
        for i in range(6)
    )
    results: dict = {}

    def run():
        try:
            results["resp"] = _req(
                base, "POST", "/v1/summarize",
                {"text": doc, "approach": "mapreduce",
                 "request_id": "gang-1"},
            )
        # worker thread: surface any client error to the assertion below
        except Exception as e:  # pragma: no cover - diagnostic aid
            results["error"] = e

    worker = threading.Thread(target=run, daemon=True)
    worker.start()
    # wait until the fan-out is journaled, then cancel the gang
    assert wait_for(
        lambda: len(state.journal.lookup("gang-1")) >= 2, timeout_s=15
    )
    status, body = _req(base, "DELETE", "/v1/requests/gang-1")
    assert status == 200
    worker.join(timeout=30)
    assert not worker.is_alive()
    status, resp = results["resp"]
    assert status == 409 and resp["error"] == "cancelled"
    # the poll surface aggregates cancelled across the fan-out children
    assert wait_for(
        lambda: _req(base, "GET", "/v1/requests/gang-1")[1]["status"]
        == "cancelled", timeout_s=15,
    )
    entries = state.journal.lookup("gang-1")
    assert all(e.status in ("cancelled", "complete") for e in entries)
    assert any(e.status == "cancelled" for e in entries)


def _read_sse_partial(base, payload, n_events: int, headers=None):
    """POST a streaming request, read ~n_events SSE frames, then DROP the
    connection without finishing — the disconnecting client."""
    import urllib.parse

    u = urllib.parse.urlparse(base)
    conn = http.client.HTTPConnection(u.hostname, u.port, timeout=30)
    conn.request("POST", "/v1/generate", body=json.dumps(payload),
                 headers={"Content-Type": "application/json",
                          **(headers or {})})
    resp = conn.getresponse()
    assert resp.status == 200
    frames = 0
    buf = b""
    while frames < n_events:
        chunk = resp.fp.read1(4096)
        if not chunk:
            break
        buf += chunk
        frames = buf.count(b"\n\n")
    # drop the connection mid-stream (http.client hands the socket to the
    # response for Connection: close replies, so close through it)
    resp.close()
    conn.close()
    return buf.decode(errors="replace")


def test_disconnect_mid_stream_cancels_after_idle_window(cancel_server):
    base, state = cancel_server
    _read_sse_partial(
        base,
        {"prompt": "van ban rat dai can nhieu phan doan de tom tat " * 10,
         "stream": True, "request_id": "dis-1"},
        n_events=2,
    )
    # the 0.3s idle window expires -> the sweep cancels and reclaims
    assert wait_for(
        lambda: state.scheduler.metrics.snapshot().cancel_disconnects >= 1,
        timeout_s=10,
    )
    assert wait_for(lambda: state.scheduler.slot_state()[1] == 0)
    assert wait_for(
        lambda: state.journal.lookup("dis-1")[0].status == "cancelled"
    )
    snap = state.scheduler.metrics.snapshot()
    assert snap.cancelled  # a stage counter moved
    assert snap.requeues == 0


@pytest.fixture()
def resume_server(tmp_path):
    # a WIDE idle window: the resume tests exercise reattach correctness,
    # not the sweep's timing — a slow CI box must not cancel under them
    state = ServeState(
        FakeBackend(segment_words=2, segment_overhead_s=0.02,
                    batch_overhead_s=0.005),
        max_batch=4, max_wait_s=0.005, inflight=True, slots=4,
        stream_heartbeat_s=0.05, stream_idle_timeout_s=10.0,
    )
    server = make_server(state, "127.0.0.1", 0)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{server.server_address[1]}", state
    server.shutdown()
    server.server_close()
    state.close()


def test_stream_resume_with_last_event_id_preserves_identity(resume_server):
    base, _state = resume_server
    prompt = "tai lieu can tom tat theo tung phan doan mot " * 10
    expect = FakeBackend().generate([prompt])[0]
    head = _read_sse_partial(
        base, {"prompt": prompt, "stream": True, "request_id": "res-1"},
        n_events=2,
    )
    # the events read before the drop carry ids (the resume token)
    assert "id: " in head
    # reconnect within the idle window: snapshot + live deltas + done
    status_headers = {"Last-Event-ID": "1"}
    import urllib.parse

    u = urllib.parse.urlparse(base)
    conn = http.client.HTTPConnection(u.hostname, u.port, timeout=60)
    conn.request(
        "POST", "/v1/generate",
        body=json.dumps({"prompt": prompt, "stream": True,
                         "request_id": "res-1"}),
        headers={"Content-Type": "application/json", **status_headers},
    )
    resp = conn.getresponse()
    assert resp.status == 200
    raw = resp.read().decode()
    conn.close()
    events = []
    for frame in raw.split("\n\n"):
        name = data = None
        for line in frame.splitlines():
            if line.startswith("event: "):
                name = line[len("event: "):]
            elif line.startswith("data: "):
                data = json.loads(line[len("data: "):])
        if name:
            events.append((name, data))
    assert events[0][0] == "snapshot"
    assert events[-1][0] == "done"
    reassembled = events[0][1]["text"] + "".join(
        p["text"] for n, p in events if n == "delta"
    )
    assert reassembled == expect
    assert events[-1][1]["completions"][0]["text"] == expect
    assert _state.scheduler.metrics.snapshot().stream_resumes >= 1


def test_resume_unknown_stream_is_typed_404(resume_server):
    base, _state = resume_server
    status, body = _req(
        base, "POST", "/v1/generate",
        {"prompt": "bat ky", "stream": True, "request_id": "ghost-9"},
        headers={"Last-Event-ID": "5"},
    )
    assert status == 404 and "error" in body


def test_heartbeat_frames_emitted_on_quiet_stream(cancel_server):
    """Heartbeats need real quiet: saturate every slot with long requests
    first, so the streaming request sits queued (no deltas flowing) while
    the 50ms keepalive cadence emits comment frames."""
    base, state = cancel_server
    fillers = [
        threading.Thread(
            target=_req, args=(base, "POST", "/v1/generate"),
            kwargs={"payload": {"prompt": f"chiem cho {i} " * 40}},
            daemon=True,
        )
        for i in range(4)
    ]
    for t in fillers:
        t.start()
    assert wait_for(lambda: state.scheduler.slot_state()[1] == 4)
    import urllib.parse

    u = urllib.parse.urlparse(base)
    conn = http.client.HTTPConnection(u.hostname, u.port, timeout=60)
    conn.request(
        "POST", "/v1/generate",
        body=json.dumps({"prompt": "noi dung cham rai " * 30,
                         "stream": True}),
        headers={"Content-Type": "application/json"},
    )
    resp = conn.getresponse()
    raw = resp.read().decode()
    conn.close()
    for t in fillers:
        t.join(timeout=30)
    assert ": heartbeat" in raw
    assert state.scheduler.metrics.snapshot().stream_heartbeats >= 1


def test_nonstream_waiter_of_cancelled_request_gets_409(cancel_server):
    base, state = cancel_server
    results: dict = {}

    def run():
        results["resp"] = _req(
            base, "POST", "/v1/generate",
            {"prompt": "cho doi den khi bi huy " * 12,
             "request_id": "w-409"},
        )

    worker = threading.Thread(target=run, daemon=True)
    worker.start()
    assert wait_for(lambda: state.journal.lookup("w-409"))
    status, _ = _req(base, "DELETE", "/v1/requests/w-409")
    assert status == 200
    worker.join(timeout=30)
    status, body = results["resp"]
    assert status == 409
    assert body["error"] == "cancelled"
    assert body["request_id"] == "w-409"
