"""Mesh-sharded serving engine byte-identity (ISSUE 11 tentpole).

Every serving path — one-shot generate, chunked prefill + radix
resume-prefill under eviction churn, spec decode, and the in-flight slot
loop with staggered joins — must produce byte-identical greedy outputs on a
multi-device mesh and on a single chip. Runs on a >=4 virtual-device CPU
mesh (conftest forces 8 for the full suite; the CI `multichip-serving` step
runs this file alone under XLA_FLAGS=--xla_force_host_platform_device_count=4,
so every mesh here uses at most 4 devices).

Tier-1 fast on purpose: tiny model, byte tokenizer, short budgets.
"""
from __future__ import annotations

import pytest

from vnsum_tpu.backend.engine import TpuBackend
from vnsum_tpu.core.config import GenerationConfig
from vnsum_tpu.models import tiny_llama
from vnsum_tpu.parallel import make_mesh

HEADER = "tieu de chung cua cac tai lieu dai: " * 6  # >128 shared byte tokens
PROMPTS = [HEADER + f"noi dung rieng {i} " * 4 for i in range(6)]
SHORT = [
    "văn bản một về kinh tế",
    "hai",
    "văn bản thứ ba dài hơn một chút",
    "bốn bốn",
]


def make_backend(mesh=None, **kw):
    kw.setdefault("model_config", tiny_llama(max_seq_len=512))
    kw.setdefault("tokenizer", "byte")
    kw.setdefault("batch_size", 4)
    kw.setdefault("max_new_tokens", 16)
    kw.setdefault("seed", 1)
    kw.setdefault("segment_tokens", 4)
    return TpuBackend(mesh=mesh, **kw)


def tp_dp_mesh():
    return make_mesh({"data": 2, "model": 2, "seq": 1}, platform="cpu")


def dp_mesh():
    return make_mesh({"data": 4, "model": 1, "seq": 1}, platform="cpu")


@pytest.fixture(scope="module")
def reference_outputs():
    return make_backend().generate(PROMPTS)


# -- one-shot ----------------------------------------------------------------


def test_oneshot_tp_dp_matches_single_chip(reference_outputs):
    assert make_backend(mesh=tp_dp_mesh()).generate(PROMPTS) == reference_outputs


def test_oneshot_dp_only_matches_single_chip(reference_outputs):
    assert make_backend(mesh=dp_mesh()).generate(PROMPTS) == reference_outputs


# -- chunked prefill + radix resume under eviction churn ---------------------


def test_chunked_prefill_and_radix_resume_match_under_churn(reference_outputs):
    """The sharded block pool (KV heads over `model`) serves resume-prefill
    byte-identically while LRU eviction churns a deliberately tiny pool —
    and chunked prefill rides the same program. Two passes: the second must
    actually hit the cache."""
    b = make_backend(
        mesh=tp_dp_mesh(), cache_blocks=6, cache_block_tokens=64,
        prefill_chunk_tokens=128,
    )
    hints = [HEADER] * len(PROMPTS)
    assert b.generate(PROMPTS, cache_hints=hints) == reference_outputs
    assert b.generate(PROMPTS, cache_hints=hints) == reference_outputs
    assert b.stats.cache_hit_tokens > 0  # resume really fired
    st = b.prefix_cache.stats_dict()
    assert st["blocks_used"] <= 6
    # the pool shards KV heads over `model`, replicated elsewhere
    spec = b.prefix_cache.store.pool["k"].sharding.spec
    assert tuple(spec) == (None, None, "model", None, None)


def test_dp_resume_matches_single_chip_cached_run(reference_outputs):
    """Cached-resume parity on a data-only mesh (the pure-DP replica
    shape): outputs equal both the uncached single-chip reference and a
    cached single-chip run."""
    single = make_backend(cache_blocks=8, cache_block_tokens=64)
    hints = [HEADER] * len(PROMPTS)
    single.generate(PROMPTS, cache_hints=hints)
    warm_single = single.generate(PROMPTS, cache_hints=hints)
    b = make_backend(mesh=dp_mesh(), cache_blocks=8, cache_block_tokens=64)
    b.generate(PROMPTS, cache_hints=hints)
    warm_sharded = b.generate(PROMPTS, cache_hints=hints)
    assert warm_single == warm_sharded == reference_outputs
    assert b.stats.cache_hit_tokens > 0


# -- in-flight slot loop -----------------------------------------------------


def _ragged_eos_config(max_new=16):
    """Extra EOS at a mid-output token id so rows finish at different
    segments and freed slots really refill (the probe trick the in-flight
    engine tests use)."""
    probe = make_backend()
    outs = probe.generate(SHORT)
    tok = probe.tok
    ids = [tok.encode(o, add_bos=False) for o in outs if o]
    longest = max(ids, key=len)
    return GenerationConfig(
        eos_ids=(tok.eos_id, longest[len(longest) // 2]),
        max_new_tokens=max_new,
    )


@pytest.mark.parametrize("mesh_fn", [tp_dp_mesh, dp_mesh])
def test_slot_loop_staggered_joins_match_solo(mesh_fn):
    """Requests joining the sharded resident batch at different segment
    boundaries, into different slots, next to different companions, each
    match their single-chip solo run byte-for-byte."""
    gen = _ragged_eos_config()
    solo_backend = make_backend()
    solo = [solo_backend.generate([p], config=gen)[0] for p in SHORT]

    b = make_backend(mesh=mesh_fn())
    loop = b.start_slot_loop(4, config=gen)
    outs: dict[int, str] = {}
    adm, rej = loop.admit([(i, SHORT[i], None) for i in (0, 1)])
    assert rej == [] and len(adm) == 2
    pending = [i for i in range(len(SHORT)) if i not in {a.key for a in adm}]
    for _ in range(64):
        res = loop.step()
        for c in res.completions:
            outs[c.key] = c.text
        if pending and loop.free:
            adm, rej = loop.admit([(i, SHORT[i], None) for i in pending])
            assert rej == []
            for a in adm:
                pending.remove(a.key)
        if not pending and loop.active == 0:
            break
    assert loop.active == 0 and not pending
    assert [outs[i] for i in range(len(SHORT))] == solo
    # raggedness really happened (joins were staggered, not one batch)
    assert loop.refills == len(SHORT)


def test_slot_loop_sharded_resume_from_cache(reference_outputs):
    """Joiners resume prefill from the sharded block pool mid-flight; the
    admissions report real cached tokens and outputs match the reference."""
    b = make_backend(mesh=tp_dp_mesh(), cache_blocks=16, cache_block_tokens=64)
    loop = b.start_slot_loop(4)
    outs: dict[int, str] = {}
    adm, _ = loop.admit([(i, PROMPTS[i], HEADER) for i in (0, 1)])
    assert len(adm) == 2
    loop.step()
    adm2, _ = loop.admit([(i, PROMPTS[i], HEADER) for i in (2, 3)])
    assert len(adm2) == 2
    # the first pair seeded the pool; mid-flight joiners resume from it
    assert all(a.cached_tokens > 0 for a in adm2)
    for _ in range(64):
        res = loop.step()
        for c in res.completions:
            outs[c.key] = c.text
        if loop.active == 0:
            break
    assert [outs[i] for i in range(4)] == reference_outputs[:4]


def test_join_bucket_respects_data_axis():
    """With data=2, a single joiner still buckets to Bj=2 (one filler row)
    and an admit with fewer free slots than DP rows waits instead of
    building an indivisible join batch."""
    b = make_backend(mesh=tp_dp_mesh())
    loop = b.start_slot_loop(4)
    adm, rej = loop.admit([(0, SHORT[0], None)])
    assert rej == [] and len(adm) == 1    # Bj=2: joiner + filler both fit
    adm, rej = loop.admit([(1, SHORT[1], None), (2, SHORT[2], None)])
    assert len(adm) == 2                  # 3 free -> data_size*2^0 = 2 taken
    # 1 free slot < data_size=2: admission defers to the next boundary
    adm, rej = loop.admit([(3, SHORT[3], None)])
    assert adm == [] and rej == []
    outs: dict[int, str] = {}
    for _ in range(64):
        res = loop.step()
        for c in res.completions:
            outs[c.key] = c.text
        if loop.active == 0:
            break
    assert set(outs) == {0, 1, 2}


# -- speculative decoding ----------------------------------------------------


def test_spec_decode_dp_matches_plain_and_tp_degrades():
    """Spec decoding runs its dense verify path on a data-only mesh
    (byte-identical greedy) and degrades typed to plain decode under model
    sharding — without forcing anything else single-chip."""
    gen = GenerationConfig(spec_k=4)
    prompts = SHORT[:4]
    refs = [p + " va phat trien ben vung" for p in prompts]
    want = make_backend().generate(prompts)

    dp = make_backend(mesh=dp_mesh())
    assert dp.generate(prompts, config=gen, references=refs) == want
    assert dp.stats.spec_verify_steps > 0          # spec really ran
    assert len(dp.take_spec_report()) == len(prompts)

    tp = make_backend(mesh=tp_dp_mesh())
    assert tp.generate(prompts, config=gen, references=refs) == want
    assert tp.stats.spec_verify_steps == 0         # degraded to plain decode
