"""Fleet observability tests: metrics/SLO federation at the router
(scrape loop, clock-offset estimation, aggregation-kind discipline),
cross-process trace stitching into ONE merged Chrome trace — including a
mid-request failover whose pre/post halves share a trace id — and
correlated incident capture (bundle well-formedness + the causally-
ordered timeline the report CLI renders)."""
from __future__ import annotations

import json
import threading
import time
import urllib.request

import pytest

from vnsum_tpu.backend.fake import FakeBackend
from vnsum_tpu.obs.histogram import Histogram
from vnsum_tpu.serve.federation import (
    INCIDENT_REASONS,
    WorkerSample,
    fold_incident_bundle,
)
from vnsum_tpu.serve.router import RouterState, Worker, make_router_server
from vnsum_tpu.serve.server import ServeState, make_server
from vnsum_tpu.testing.chaos import free_port


def _spawn_worker(name: str, **kw):
    state = ServeState(FakeBackend(), max_batch=8, max_wait_s=0.005, **kw)
    server = make_server(state, "127.0.0.1", 0)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    return Worker(name, "127.0.0.1", server.server_address[1]), \
        (server, state, thread)


def _mark_up(state: RouterState) -> None:
    with state._lock:
        for w in state.workers:
            w.up = True
        state._replay_started = state._replay_done = True


def _post(url, payload, headers=None):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=60) as resp:
        return resp.status, json.loads(resp.read()), dict(resp.headers)


def _get_json(url):
    with urllib.request.urlopen(url, timeout=10) as resp:
        return resp.status, json.loads(resp.read())


@pytest.fixture()
def fedfleet(tmp_path):
    """Two in-process workers (one with an SLO engine) behind a router
    with federation + incident capture ON, probe loop OFF — scrapes run
    deterministically via scrape_all(). Yields (base, router, workers,
    handles)."""
    w0, h0 = _spawn_worker("w0", slo="e2e_p99=30,availability=0.9")
    w1, h1 = _spawn_worker("w1")
    state = RouterState(
        [w0, w1],
        journal_dir=tmp_path / "router",
        tenants={"alpha": "interactive", "beta": "batch"},
        incident_dir=tmp_path / "incidents",
        incident_min_interval_s=0.0,
    )
    _mark_up(state)
    server = make_router_server(state, "127.0.0.1", 0)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    yield (f"http://127.0.0.1:{server.server_address[1]}", state,
           [w0, w1], (h0, h1))
    server.shutdown()
    server.server_close()
    state.close(drain_timeout_s=2.0)
    for server_, sstate, _t in (h0, h1):
        server_.shutdown()
        server_.server_close()
        sstate.close()


# -- worker snapshot surface ---------------------------------------------------


def test_worker_obs_snapshot_surface(fedfleet):
    base, _state, workers, _handles = fedfleet
    w = workers[0]
    _post(f"http://{w.host}:{w.port}/v1/generate",
          {"prompt": "bản tin quan trắc", "request_id": "obs-1"})
    # the worker finishes its request trace in the handler's finally —
    # after the response bytes — so poll for the finished span briefly
    deadline = time.monotonic() + 5.0
    while True:
        status, snap = _get_json(
            f"http://{w.host}:{w.port}/debug/obs/snapshot")
        if any(t["trace_id"] == "obs-1" for t in snap.get("traces", [])) \
                or time.monotonic() > deadline:
            break
        time.sleep(0.02)
    assert status == 200
    assert snap["ready"] is True and snap["readyz_reason"] == "ready"
    assert 0.0 < snap["mono_now"] <= time.monotonic()
    assert snap["counters"]["requests_total"] >= 1
    assert "e2e_seconds" in snap["hists"] and "ttft_seconds" in snap["hists"]
    # this worker runs an SLO engine — the federation payload carries it
    assert snap["slo"]["breached"] is False
    assert "availability" in snap["slo"]["objectives"]
    assert any(t["trace_id"] == "obs-1" for t in snap["traces"])
    assert snap["watchdog"]["max_heartbeat_age_s"] >= 0.0


# -- federation scrape + rollups -----------------------------------------------


def test_scrape_estimates_clock_offset_and_rolls_up(fedfleet):
    base, state, workers, _handles = fedfleet
    for i in range(4):
        _post(base + "/v1/generate",
              {"prompt": f"tin số {i}", "request_id": f"roll-{i}"})
    fed = state.federation
    fed.scrape_all()
    # same-process monotonic clocks: the RTT-midpoint offset estimate must
    # land within the scrape round trip of zero
    for name in ("w0", "w1"):
        s = fed.sample(name)
        assert s is not None and s.error is None
        assert abs(s.clock_offset_s) <= s.scrape_s + 0.01
    rollup = fed.fleet_rollup()
    # counters summed across the roster == what the workers report
    per_worker_total = 0
    for _w, (_srv, sstate, _t) in zip(workers, _handles):
        per_worker_total += sstate.metrics.federation_snapshot()[
            "counters"]["requests_total"]
    assert rollup["counters"]["requests_total"] == per_worker_total >= 4
    # histograms merged, not averaged: fleet e2e count == sum of workers
    assert rollup["hists"]["e2e_seconds"].count == per_worker_total
    # gauges stay per-worker
    for name in ("w0", "w1"):
        row = rollup["per_worker"][name]
        assert row["ready"] is True and row["stale"] is False
        assert "queue_depth" in row and "clock_offset_s" in row
    assert "slo_burn_fast_max" in rollup["per_worker"]["w0"]


def test_scrape_error_keeps_previous_payload(fedfleet):
    _base, state, _workers, _handles = fedfleet
    fed = state.federation
    fed.scrape_all()
    dead = Worker("ghost", "127.0.0.1", free_port())
    s = fed.scrape_one(dead)
    assert s.error is not None and s.payload is None
    # a never-scraped-successfully worker contributes a stale row, while a
    # previously-good worker keeps its last payload on a refused scrape
    good = fed.sample("w0")
    real_w0 = next(w for w in state.workers if w.name == "w0")
    bad_w0 = Worker("w0", "127.0.0.1", free_port())
    s2 = fed.scrape_one(bad_w0)
    assert s2.error is not None
    assert s2.payload is not None  # previous good payload retained
    assert s2.payload is good.payload
    fed.scrape_one(real_w0)  # restore


def test_histogram_merge_skew_skipped_and_counted(fedfleet):
    _base, state, _workers, _handles = fedfleet
    fed = state.federation
    fed.scrape_all()
    # a version-skewed worker on a different bucket ladder: its hists are
    # skipped (never mis-binned), counted into merge_errors
    skewed = Histogram((0.5, 1.0))
    skewed.observe(0.2)
    payload = {
        "mono_now": time.monotonic(), "ready": True,
        "readyz_reason": "ready", "queue_depth": 0,
        "counters": {"requests_total": 1},
        "hists": {"e2e_seconds": skewed.state_dict()},
    }
    with fed._lock:
        fed._samples["zz-skew"] = WorkerSample(
            "zz-skew", payload, time.monotonic(), 0.001, 0.0, None)
    before = fed.stats_dict()["merge_errors"]
    rollup = fed.fleet_rollup()
    assert fed.stats_dict()["merge_errors"] == before + 1
    # the skewed worker's counters still sum; its buckets do not
    assert rollup["counters"]["requests_total"] >= 1
    assert rollup["hists"]["e2e_seconds"].bounds != skewed.bounds
    with fed._lock:
        del fed._samples["zz-skew"]


# -- fleet /debug/slo + /v1/usage ----------------------------------------------


def test_fleet_slo_view_attributes_burn(fedfleet):
    base, state, _workers, _handles = fedfleet
    _post(base + "/v1/generate", {"prompt": "đo lường slo"})
    state.federation.scrape_all()
    status, slo = _get_json(base + "/debug/slo")
    assert status == 200
    assert slo["role"] == "router" and slo["breached"] is False
    # only w0 runs an SLO engine — attribution lists exactly it
    assert [r["worker"] for r in slo["burn_attribution"]] == ["w0"]
    assert slo["workers"]["w0"]["stale"] is False
    assert "objectives" in slo["workers"]["w0"]
    assert slo["workers"]["w1"]["slo"] is None


def test_fleet_usage_sums_tenants_and_maxes_quantiles(fedfleet):
    base, state, _workers, _handles = fedfleet
    for i in range(3):
        _post(base + "/v1/generate", {"prompt": f"dùng {i}"},
              headers={"X-Tenant": "alpha"})
    state.federation.scrape_all()
    status, usage = _get_json(base + "/v1/usage")
    assert status == 200 and usage["role"] == "router"
    tenants = usage["tenants"]
    total = sum(row.get("requests", 0) for row in tenants.values())
    assert total >= 3
    # summed counters equal the per-worker breakdown the view also ships
    per_worker = sum(
        row.get("requests", 0)
        for wrows in usage["workers"].values() for row in wrows.values()
    )
    assert total == per_worker
    # quantile merge is the conservative max, never a sum: each merged
    # quantile equals some worker's quantile
    for tenant, row in tenants.items():
        e2e = row.get("e2e")
        if not e2e or not e2e.get("count"):
            continue
        worker_p95 = [
            wrows[tenant]["e2e"]["p95_s"]
            for wrows in usage["workers"].values() if tenant in wrows
        ]
        assert e2e["p95_s"] == pytest.approx(max(worker_p95))


# -- /healthz per-worker summary (satellite 1) ---------------------------------


def test_healthz_carries_worker_summary_block(fedfleet):
    base, state, _workers, _handles = fedfleet
    _post(base + "/v1/generate", {"prompt": "khối tóm tắt"})
    state.federation.scrape_all()
    status, health = _get_json(base + "/healthz")
    assert status == 200
    rows = {r["name"]: r for r in health["workers"]}
    for name in ("w0", "w1"):
        s = rows[name]["summary"]
        assert s["ready"] is True and s["readyz"] == "ready"
        assert s["rung"] == 0
        assert s["inflight"] == 0
        assert s["watchdog_max_heartbeat_age_s"] >= 0.0
        assert s["last_markdown_reason"] == ""
        assert s["sample_age_s"] is not None
    assert health["federation"]["scrapes"] >= 2
    assert health["incidents"] == {}


# -- merged trace stitching ----------------------------------------------------


def _trace_processes(doc: dict) -> dict[str, dict]:
    """process_name -> {"pid", "thread_names", "spans"} from a merged
    Chrome trace."""
    procs: dict[int, dict] = {}
    for e in doc["traceEvents"]:
        if e["ph"] == "M" and e["name"] == "process_name":
            procs.setdefault(e["pid"], {"name": e["args"]["name"],
                                        "thread_names": set(), "spans": []})
    for e in doc["traceEvents"]:
        p = procs.get(e["pid"])
        if p is None:
            continue
        if e["ph"] == "M" and e["name"] == "thread_name":
            p["thread_names"].add(e["args"]["name"])
        elif e["ph"] == "X":
            p["spans"].append(e)
    return {p["name"]: p for p in procs.values()}


def test_debug_trace_stitches_router_and_worker_spans(fedfleet):
    base, _state, _workers, _handles = fedfleet
    # a FAN-OUT request: two prompts journal as st-1 + st-1#1 and fan out
    # per-prompt sub-tracks on the worker — exactly the shape whose spans
    # straddle processes
    _post(base + "/v1/generate",
          {"prompts": ["ghép dấu vết liên tiến trình",
                       "nhánh song song thứ hai"],
           "request_id": "st-1"})
    # the worker half finishes just after the response bytes — retry the
    # stitch until both sources contribute
    deadline = time.monotonic() + 5.0
    while True:
        status, doc = _get_json(base + "/debug/trace")
        procs = _trace_processes(doc)
        p = procs.get("request st-1")
        srcs = ({sp["args"]["source"] for sp in p["spans"]}
                if p else set())
        if len(srcs) >= 2 or time.monotonic() > deadline:
            break
        time.sleep(0.02)
    assert status == 200
    p = procs["request st-1"]
    # ONE Perfetto process holds the router hop AND the worker hop
    sources = {sp["args"]["source"] for sp in p["spans"]}
    assert "router" in sources and sources & {"w0", "w1"}
    assert "router:request" in p["thread_names"]
    assert any(t.endswith(":request") and not t.startswith("router")
               for t in p["thread_names"])
    # the fan-out's per-prompt sub-tracks ride along under the same pid
    assert any(":prompt " in t for t in p["thread_names"])
    # the worker half carries the router's propagated parent span
    worker_spans = [sp for sp in p["spans"]
                    if sp["args"]["source"] != "router"]
    assert any(sp["args"].get("parent_span") == "router:st-1"
               for sp in worker_spans)
    # offsets applied: every span lands at a non-negative rebased ts
    assert all(sp["ts"] >= 0 for sp in p["spans"])


def test_debug_trace_failover_halves_share_one_trace_id(tmp_path):
    """The acceptance trace: a request whose first hop dies mid-flight is
    replayed onto the survivor, and the merged /debug/trace shows BOTH the
    failed proxy attempt (router span, outcome=failover) and the
    survivor's worker spans under one Perfetto process."""
    import zlib

    live, handle = _spawn_worker("live")
    dead = Worker("dead", "127.0.0.1", free_port())
    state = RouterState([dead, live], journal_dir=tmp_path / "router",
                        incident_dir=tmp_path / "incidents",
                        incident_min_interval_s=0.0)
    _mark_up(state)
    server = make_router_server(state, "127.0.0.1", 0)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{server.server_address[1]}"
    try:
        hint = next(
            h for h in (f"hint-{i}" for i in range(1000))
            if max([dead, live], key=lambda w: zlib.crc32(
                f"{h}|{w.name}".encode())).name == "dead"
        )
        status, body, _ = _post(
            base + "/v1/generate",
            {"prompt": "nửa trước và nửa sau", "cache_hint": hint,
             "request_id": "fo-trace"})
        assert status == 200
        deadline = time.monotonic() + 5.0
        while True:
            s, doc = _get_json(base + "/debug/trace")
            procs = _trace_processes(doc)
            p = procs.get("request fo-trace")
            if (p and any(sp["args"]["source"] == "live"
                          for sp in p["spans"])) \
                    or time.monotonic() > deadline:
                break
            time.sleep(0.02)
        assert s == 200
        p = procs["request fo-trace"]
        router_spans = [sp for sp in p["spans"]
                        if sp["args"]["source"] == "router"]
        assert any(sp["name"] == "proxy"
                   and sp["args"].get("outcome") == "failover"
                   for sp in router_spans)
        # the survivor's post-failover half sits in the SAME process
        assert any(sp["args"]["source"] == "live" for sp in p["spans"])
        # the death also fired a failover incident with a bundle on disk
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            bundles = list((tmp_path / "incidents").glob("inc_*"))
            if bundles and (bundles[0] / "manifest.json").exists():
                break
            time.sleep(0.05)
        assert state.incidents.counts_snapshot().get("failover", 0) >= 1
    finally:
        server.shutdown()
        server.server_close()
        state.close(drain_timeout_s=2.0)
        handle[0].shutdown()
        handle[0].server_close()
        handle[1].close()


# -- incident capture ----------------------------------------------------------


def test_operator_incident_bundle_and_timeline(fedfleet, tmp_path):
    base, state, _workers, _handles = fedfleet
    for i in range(3):
        _post(base + "/v1/generate",
              {"prompt": f"sự cố {i}", "request_id": f"inc-{i}"})
    state.federation.scrape_all()
    inc = state.incidents.trigger("operator", detail="test trigger",
                                  sync=True)
    assert inc is not None and inc.startswith("inc_")
    bundle = tmp_path / "incidents" / inc
    manifest = json.loads((bundle / "manifest.json").read_text())
    assert manifest["incident"] == inc
    assert manifest["reason"] == "operator"
    assert manifest["workers_collected"] == 2
    for name in ("w0", "w1"):
        entry = manifest["workers"][name]
        assert entry["file"] == f"worker_{name}.json"
        assert "clock_offset_s" in entry
        wdoc = json.loads((bundle / entry["file"]).read_text())
        assert wdoc["source"] == name and wdoc["incident"] == inc
        assert wdoc["stacks"]
        assert wdoc["flightrecorder"]["events"]  # dispatch events
    rdoc = json.loads((bundle / "router.json").read_text())
    assert rdoc["source"] == "router"
    assert any(e["kind"] == "incident" and e.get("incident") == inc
               for e in rdoc["flightrecorder"]["events"])
    # folded timeline: events from router + both workers, monotone wall
    report = fold_incident_bundle(bundle)
    assert report["incident"] == inc
    assert set(report["sources"]) == {"router", "w0", "w1"}
    assert all(report["sources"][s]["events"] > 0
               for s in ("router", "w0", "w1"))
    walls = [e["wall"] for e in report["events"]]
    assert walls == sorted(walls) and walls
    # the report CLI renders the same fold
    from scripts.incident_report import main as report_main, render_text
    text = render_text(report, limit=10)
    assert inc in text and "router" in text
    assert report_main([str(bundle)]) == 0
    # routing decisions appear in the merged timeline
    assert any(e["source"] == "router" and e["kind"] == "route"
               for e in report["events"])
    # the fired incident shows up on /healthz and /metrics
    _s, health = _get_json(base + "/healthz")
    assert health["incidents"]["operator"] >= 1
    with urllib.request.urlopen(base + "/metrics", timeout=10) as resp:
        text = resp.read().decode()
    assert 'vnsum_serve_fleet_incidents_total{reason="operator"} 1' in text


def test_incident_throttle_and_disabled_paths(fedfleet, tmp_path):
    _base, state, _workers, _handles = fedfleet
    assert set(INCIDENT_REASONS) == {"slo_fast_burn", "markdown",
                                     "failover", "operator"}
    state.incidents.min_interval_s = 60.0
    first = state.incidents.trigger("markdown", detail="w0: probe",
                                    sync=True)
    assert first is not None
    # same reason inside the throttle window: dropped
    assert state.incidents.trigger("markdown", sync=True) is None
    # a different reason is NOT throttled by markdown's stamp
    assert state.incidents.trigger("operator", sync=True) is not None
    assert state.incidents.counts_snapshot()["markdown"] == 1
    # no incident_dir -> triggers are a typed no-op
    from vnsum_tpu.serve.federation import IncidentManager
    off = IncidentManager(state, state.federation, None)
    assert off.trigger("operator", sync=True) is None
