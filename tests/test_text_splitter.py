from vnsum_tpu.text import RecursiveTokenSplitter
from vnsum_tpu.text.splitter import VIETNAMESE_SEPARATORS


def words(text: str) -> int:
    return len(text.split())


def test_no_split_when_fits():
    sp = RecursiveTokenSplitter(chunk_size=100, chunk_overlap=0, length_function=words)
    assert sp.split_text("một hai ba") == ["một hai ba"]


def test_splits_on_paragraphs_first():
    text = "câu một dài dài.\n\ncâu hai cũng dài.\n\ncâu ba nữa."
    sp = RecursiveTokenSplitter(chunk_size=5, chunk_overlap=0, length_function=words)
    chunks = sp.split_text(text)
    assert len(chunks) >= 2
    # nothing lost except whitespace at joins
    joined = " ".join(chunks)
    for w in text.split():
        assert w in joined


def test_respects_chunk_size():
    text = ". ".join(f"câu số {i} có vài từ" for i in range(50))
    sp = RecursiveTokenSplitter(chunk_size=20, chunk_overlap=0, length_function=words)
    for c in sp.split_text(text):
        assert words(c) <= 20


def test_overlap_carries_tail():
    text = "\n\n".join(f"đoạn {i} nội dung dài thêm chữ" for i in range(10))
    sp = RecursiveTokenSplitter(chunk_size=12, chunk_overlap=6, length_function=words)
    chunks = sp.split_text(text)
    assert len(chunks) >= 2
    # consecutive chunks share at least one word due to overlap
    for a, b in zip(chunks, chunks[1:]):
        assert set(a.split()) & set(b.split())


def test_oversized_atomic_piece_falls_through_ladder():
    # a single "word" longer than chunk_size in characters gets split at ""
    text = "x" * 50
    sp = RecursiveTokenSplitter(chunk_size=10, chunk_overlap=0, length_function=len)
    chunks = sp.split_text(text)
    assert all(len(c) <= 10 for c in chunks)
    assert "".join(chunks) == text


def test_separator_kept_with_following_piece():
    sp = RecursiveTokenSplitter(chunk_size=3, chunk_overlap=0, length_function=words)
    chunks = sp.split_text("a b c. d e f. g h i")
    # the period travels with the following chunk start (langchain
    # keep_separator=True semantics), minus the strip at joins
    assert chunks[0] == "a b c"
    assert chunks[1].startswith(". d") or chunks[1].startswith("d")


def test_empty_text():
    sp = RecursiveTokenSplitter(chunk_size=10, chunk_overlap=0)
    assert sp.split_text("") == []


def test_default_ladder_is_vietnamese():
    assert VIETNAMESE_SEPARATORS[0] == "\n\n"
    assert VIETNAMESE_SEPARATORS[-1] == ""


def test_token_length_function():
    from vnsum_tpu.text import ByteTokenizer

    tok = ByteTokenizer()
    text = "xin chào " * 100
    sp = RecursiveTokenSplitter(
        chunk_size=64, chunk_overlap=8, length_function=tok.count
    )
    for c in sp.split_text(text):
        assert tok.count(c) <= 64


def test_batch_length_function_is_equivalent():
    """length_batch_function must produce IDENTICAL chunks to the scalar
    length function — it exists purely to collapse thousands of per-piece
    tokenizer calls into one call per split level."""
    text = ("Việt Nam phát triển kinh tế. Xã hội bền vững! Văn hóa đa dạng; "
            "giáo dục hiện đại?\n\nĐoạn mới với nhiều câu. " * 40)
    calls = {"batch": 0, "scalar": 0}

    def scalar(t):
        calls["scalar"] += 1
        return len(t.split())

    def batch(ts):
        calls["batch"] += 1
        return [len(t.split()) for t in ts]

    from vnsum_tpu.text.splitter import RecursiveTokenSplitter

    base = RecursiveTokenSplitter(40, 8, length_function=scalar)
    fast = RecursiveTokenSplitter(
        40, 8, length_function=scalar, length_batch_function=batch
    )
    a = base.split_text(text)
    b = fast.split_text(text)
    assert a == b
    assert calls["batch"] > 0
