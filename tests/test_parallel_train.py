import jax
import jax.numpy as jnp
import numpy as np
import pytest

from vnsum_tpu.models import init_params, tiny_llama
from vnsum_tpu.models.llama import dense_causal_attention, forward_train
from vnsum_tpu.parallel import make_mesh
from vnsum_tpu.parallel.ring import ring_attention
from vnsum_tpu.train import TrainConfig, Trainer, lm_loss


@pytest.fixture(scope="module")
def mesh8():
    return make_mesh({"data": 2, "model": 2, "seq": 2}, platform="cpu")


def test_forward_train_matches_cached_forward():
    """Training forward (no cache) must agree with the inference forward."""
    from vnsum_tpu.models import forward, init_kv_cache
    from vnsum_tpu.models.llama import (
        prefill_attention_mask,
        prefill_positions,
    )

    cfg = tiny_llama()
    params = init_params(jax.random.key(0), cfg)
    tokens = jnp.arange(16, dtype=jnp.int32).reshape(2, 8) + 3
    train_logits = forward_train(params, cfg, tokens, remat=False)

    pad = jnp.zeros((2,), jnp.int32)
    cache = init_kv_cache(cfg, 2, 8)
    inf_logits, _ = forward(
        params, cfg, tokens, prefill_positions(pad, 8), cache, 0,
        prefill_attention_mask(pad, 8, 8),
    )
    np.testing.assert_allclose(
        np.asarray(train_logits), np.asarray(inf_logits), rtol=2e-4, atol=2e-4
    )


def test_ring_attention_matches_dense(mesh8):
    """Ring attention over the seq axis == dense causal attention."""
    cfg = tiny_llama()
    B, S, H, KV, hd = 2, 16, 4, 2, 16
    key = jax.random.key(1)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, S, H, hd), jnp.float32)
    k = jax.random.normal(kk, (B, S, KV, hd), jnp.float32)
    v = jax.random.normal(kv, (B, S, KV, hd), jnp.float32)

    dense = dense_causal_attention(q, k, v, H // KV)
    ring = ring_attention(q, k, v, H // KV, mesh=mesh8)
    np.testing.assert_allclose(
        np.asarray(dense), np.asarray(ring), rtol=1e-5, atol=1e-5
    )


def test_forward_train_with_ring_attention_matches_dense(mesh8):
    from functools import partial

    cfg = tiny_llama()
    params = init_params(jax.random.key(0), cfg)
    tokens = (jnp.arange(32, dtype=jnp.int32).reshape(2, 16) * 5) % cfg.vocab_size
    dense_logits = forward_train(params, cfg, tokens, remat=False)
    ring_logits = forward_train(
        params, cfg, tokens,
        attention_fn=partial(ring_attention, mesh=mesh8), remat=False,
    )
    np.testing.assert_allclose(
        np.asarray(dense_logits), np.asarray(ring_logits), rtol=5e-4, atol=5e-4
    )


def test_lm_loss_decreases_under_training(mesh8):
    cfg = tiny_llama()
    trainer = Trainer(
        cfg, mesh8, TrainConfig(learning_rate=5e-3, remat=False)
    )
    tokens = np.tile(np.arange(16, dtype=np.int32)[None], (4, 1)) + 7
    losses = [trainer.step(tokens) for _ in range(5)]
    assert losses[-1] < losses[0]
    assert np.isfinite(losses).all()


def test_training_with_context_parallel(mesh8):
    cfg = tiny_llama()
    trainer = Trainer(
        cfg, mesh8,
        TrainConfig(learning_rate=5e-3, context_parallel=True, remat=False),
    )
    tokens = np.tile(np.arange(16, dtype=np.int32)[None], (4, 1)) + 7
    losses = [trainer.step(tokens) for _ in range(3)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_loss_mask_excludes_positions():
    cfg = tiny_llama()
    params = init_params(jax.random.key(0), cfg)
    tokens = jnp.ones((1, 8), jnp.int32) * 5
    full = lm_loss(params, cfg, tokens, jnp.ones_like(tokens, dtype=bool), remat=False)
    none = lm_loss(params, cfg, tokens, jnp.zeros_like(tokens, dtype=bool), remat=False)
    assert float(none) == 0.0
    assert float(full) > 0.0


@pytest.mark.xfail(
    tuple(int(x) for x in jax.__version__.split(".")[:2]) < (0, 5),
    reason=(
        "seed numerics on jax<=0.4.x CPU: the fsdp-sharded step's gradient "
        "all-reduce sums per-shard partials in a different order than the "
        "replicated step's single reduction; bf16 rounding in the AdamW "
        "update amplifies the last-bit logit drift into ~1.4% loss "
        "divergence by step 3 (pre-existing at seed import, CHANGES.md "
        "PR 1). strict=False so a newer JAX whose reduction orders happen "
        "to agree turns this back into a pass, not a failure."
    ),
    strict=False,
)
def test_fsdp_training_matches_plain():
    """fsdp=2 (stacked layers sharded ZeRO-3 style) must produce the same
    losses as the unsharded trainer — sharding is layout, not math."""
    import jax

    from vnsum_tpu.parallel import make_mesh
    from vnsum_tpu.train import TrainConfig, Trainer

    cfg = tiny_llama()
    tokens = np.tile(np.arange(16, dtype=np.int32)[None], (4, 1)) + 7

    plain_mesh = make_mesh({"data": 2, "model": 2}, platform="cpu")
    plain = Trainer(cfg, plain_mesh, TrainConfig(learning_rate=5e-3, remat=False))
    l_plain = [plain.step(tokens) for _ in range(3)]

    fsdp_mesh = make_mesh({"data": 2, "model": 2, "fsdp": 2}, platform="cpu")
    fsdp = Trainer(
        cfg, fsdp_mesh,
        TrainConfig(learning_rate=5e-3, remat=False, fsdp=True),
    )
    # layer params must actually shard over the fsdp axis
    wq_sharding = fsdp.params["layers"]["wq"].sharding
    assert "fsdp" in str(wq_sharding.spec)
    l_fsdp = [fsdp.step(tokens) for _ in range(3)]
    np.testing.assert_allclose(l_plain, l_fsdp, rtol=2e-4)


def test_fsdp_requires_axis_and_divisibility():
    import pytest

    from vnsum_tpu.parallel import make_mesh
    from vnsum_tpu.train import TrainConfig, Trainer

    cfg = tiny_llama()  # 2 layers
    no_axis = make_mesh({"data": 2}, platform="cpu")
    with pytest.raises(ValueError, match="fsdp' axis"):
        Trainer(cfg, no_axis, TrainConfig(fsdp=True))
    bad = make_mesh({"fsdp": 4, "data": 2}, platform="cpu")
    with pytest.raises(ValueError, match="not divisible"):
        Trainer(cfg, bad, TrainConfig(fsdp=True))
