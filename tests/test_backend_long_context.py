"""Long-context backend: ring prefill + seq-sharded decode (VERDICT r1 #9).

Parity anchor: the long path on an 8-device CPU mesh must reproduce the plain
one-chip engine's greedy outputs given the SAME weights — including prompts
that exceed the one-chip max_seq_len ceiling (which the dense oracle only
handles because CPU hosts have no HBM limit)."""
from __future__ import annotations

import jax
import numpy as np
import pytest

from vnsum_tpu.backend.engine import TpuBackend
from vnsum_tpu.backend.long_context import LongContextBackend, long_prefill
from vnsum_tpu.models import tiny_llama
from vnsum_tpu.models.llama import init_params
from vnsum_tpu.parallel.mesh import make_mesh

PROMPTS = [
    "Tóm tắt văn bản sau: nền kinh tế tăng trưởng ổn định trong quý một. "
    * 2,
    "hai",
    "Một tài liệu dài hơn hẳn nói về chính sách giáo dục và y tế cơ sở "
    "tại các địa phương miền núi phía bắc. " * 3,
]


@pytest.fixture(scope="module")
def mesh():
    return make_mesh({"data": 2, "seq": 4}, platform="cpu")


@pytest.fixture(scope="module")
def setup(mesh):
    # ONE set of weights; the dense oracle gets a big single-chip context
    # (fine on CPU) while the long backend shards the same lengths over seq
    cfg = tiny_llama(max_seq_len=2048)
    params = init_params(jax.random.key(3), cfg)
    dense = TpuBackend(
        model_config=cfg, params=params, batch_size=4, max_new_tokens=16,
        continuous=False,
    )
    long = LongContextBackend(
        model_config=cfg, mesh=mesh, params=params, max_new_tokens=16,
        max_total_tokens=2048,
    )
    return dense, long


def test_prefill_logits_match_dense(mesh):
    from vnsum_tpu.models.llama import (
        forward,
        init_kv_cache,
        prefill_attention_mask,
        prefill_positions,
    )
    import jax.numpy as jnp

    cfg = tiny_llama(max_seq_len=1024)
    params = init_params(jax.random.key(0), cfg)
    B, S = 2, 512  # divisible by seq axis (4)
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 256, size=(B, S)).astype(np.int32)
    pad = np.array([0, 100], dtype=np.int32)
    tokens[1, :100] = 258  # left padding

    logits_long, cache = long_prefill(
        params, cfg, jnp.asarray(tokens), jnp.asarray(pad), mesh
    )

    dense_cache = init_kv_cache(cfg, B, S)
    mask = prefill_attention_mask(jnp.asarray(pad), S, S)
    logits_dense, _ = forward(
        params, cfg, jnp.asarray(tokens), prefill_positions(jnp.asarray(pad), S),
        dense_cache, 0, mask, last_only=True,
    )
    np.testing.assert_allclose(
        np.asarray(logits_long), np.asarray(logits_dense)[:, -1], atol=2e-4
    )
    # engine-native stacked layout [L, B, KV, S, hd] (what the Pallas decode
    # kernel consumes shard-locally)
    assert cache["k"].shape == (cfg.n_layers, B, cfg.n_kv_heads, S, cfg.head_dim)


def test_greedy_parity_with_dense_engine(setup):
    dense, long = setup
    expect = dense.generate(PROMPTS)
    got = long.generate(PROMPTS)
    assert got == expect


def test_exceeds_single_chip_ceiling(mesh):
    """A prompt longer than the one-chip max_seq_len runs UN-truncated on the
    seq-sharded path and matches a big-context dense oracle."""
    small_cfg = tiny_llama(max_seq_len=128)   # one-chip ceiling: 128
    big_cfg = tiny_llama(max_seq_len=2048)    # same arch, same weights
    params = init_params(jax.random.key(7), small_cfg)

    long_doc = (
        "Chính phủ ban hành nghị định mới về phát triển hạ tầng giao thông "
        "và chuyển đổi số tại đồng bằng sông Cửu Long. " * 6
    )  # ~700 bytes >> 128

    long = LongContextBackend(
        model_config=small_cfg, mesh=mesh, params=params, max_new_tokens=12,
        max_total_tokens=2048,
    )
    oracle = TpuBackend(
        model_config=big_cfg, params=params, batch_size=2, max_new_tokens=12,
        continuous=False,
    )
    got = long.generate([long_doc])
    expect = oracle.generate([long_doc])
    assert got == expect
    # and the one-chip engine really would have truncated this prompt
    assert len(long_doc.encode()) > small_cfg.max_seq_len


def test_truncated_strategy_untruncated_via_long_backend(mesh):
    """The reference's truncated strategy (16k cut) becomes a full-document
    one-shot summarizer when handed the long backend."""
    from vnsum_tpu.strategies.truncated import TruncatedStrategy

    cfg = tiny_llama(max_seq_len=128)
    params = init_params(jax.random.key(1), cfg)
    long = LongContextBackend(
        model_config=cfg, mesh=mesh, params=params, max_new_tokens=8,
        max_total_tokens=4096,
    )
    st = TruncatedStrategy(long, max_context=4096, max_new_tokens=8)
    doc = "Báo cáo kinh tế xã hội sáu tháng đầu năm cho thấy nhiều tín hiệu tích cực. " * 10
    res = st.summarize(doc)
    assert isinstance(res.summary, str)
    assert res.num_chunks == 1


def test_batch_grouping_and_config_max_new(mesh):
    """Prompts group into batch_size rows with per-group buckets (one giant
    longest-prompt batch would OOM at real scale), and config.max_new_tokens
    is honored like TpuBackend."""
    from vnsum_tpu.core.config import GenerationConfig

    cfg = tiny_llama(max_seq_len=2048)
    params = init_params(jax.random.key(2), cfg)
    be = LongContextBackend(
        model_config=cfg, mesh=mesh, params=params, batch_size=2,
        max_new_tokens=16, max_total_tokens=2048,
    )
    prompts = ["a " * n for n in (4, 300, 8, 280, 2)]
    outs = be.generate(prompts)
    assert len(outs) == 5
    # short prompts bucket separately from long ones: at least two S buckets
    assert len({k[1] for k in be._fns}) >= 2
    # per-prompt order preserved
    singles = [be.generate([p])[0] for p in prompts]
    assert outs == singles

    short = be.generate(
        ["một văn bản"], config=GenerationConfig(max_new_tokens=4)
    )[0]
    longer = be.generate(
        ["một văn bản"], config=GenerationConfig(max_new_tokens=16)
    )[0]
    assert len(short.encode()) <= len(longer.encode())


def test_long_backend_sampled_seed_replay(mesh):
    from vnsum_tpu.core.config import GenerationConfig

    cfg = tiny_llama(max_seq_len=512)
    params = init_params(jax.random.key(5), cfg)

    def fresh():
        return LongContextBackend(
            model_config=cfg, mesh=mesh, params=params, batch_size=2,
            max_new_tokens=8, max_total_tokens=512,
        )

    gen = GenerationConfig(temperature=1.0, seed=4, max_new_tokens=8)
    a = fresh()
    a1 = a.generate(["văn bản"], config=gen)
    a2 = a.generate(["văn bản"], config=gen)
    assert a1 != a2  # fresh randomness per dispatch
    b = fresh()
    assert b.generate(["văn bản"], config=gen) == a1  # same-seed replay
    assert b.generate(["văn bản"], config=gen) == a2


def test_pipeline_long_context_truncated_untruncated(tmp_path):
    """--long-context end to end: the pipeline's truncated approach runs
    full documents PAST the one-chip ceiling through the seq-sharded
    backend (models registry 'tiny' has max_seq_len=256)."""
    from vnsum_tpu.core.config import PipelineConfig
    from vnsum_tpu.data.synthesize import synthesize_corpus
    from vnsum_tpu.pipeline.runner import PipelineRunner

    synthesize_corpus(
        tmp_path / "c", n_docs=2, tokens_per_doc=150, summary_tokens=30,
        seed=4,
    )  # ~150 words ≈ 900+ bytes per doc >> 256
    cfg = PipelineConfig(
        approach="truncated",
        models=["tiny"],
        backend="tpu",
        long_context=True,
        mesh_shape={"data": 2, "seq": 4},
        allow_cpu_mesh=True,  # 8-way mesh on a host whose default is 1 chip
        max_context=2048,
        max_new_tokens=8,
        batch_size=2,
        docs_dir=str(tmp_path / "c/doc"),
        summary_dir=str(tmp_path / "c/summary"),
        generated_summaries_dir=str(tmp_path / "gen"),
        results_dir=str(tmp_path / "results"),
        logs_dir=str(tmp_path / "logs"),
    )
    results = PipelineRunner(cfg).run()
    rec = results.summarization["tiny"]
    assert rec["successful"] == 2 and rec["failed"] == 0
    # docs really exceeded the one-chip limit
    for p in (tmp_path / "c/doc").glob("*.txt"):
        assert len(p.read_text(encoding="utf-8").encode()) > 256


def test_long_context_config_validation():
    import pytest as _pytest

    from vnsum_tpu.core.config import PipelineConfig

    with _pytest.raises(ValueError, match="seq axis"):
        PipelineConfig(long_context=True, mesh_shape={"data": 2})
    with _pytest.raises(ValueError, match="backend='tpu'"):
        PipelineConfig(long_context=True, backend="fake",
                       mesh_shape={"seq": 4})


def test_long_context_int8_weights_and_cache(mesh):
    """int8 weights + int8 prefill cache run end to end, and the quantized
    sharded-cache decode attention stays numerically close to the fp path
    (per-vector int8 is ~1/127 relative error)."""
    import jax.numpy as jnp

    from vnsum_tpu.backend.long_context import (
        make_long_decode_attention,
        long_prefill,
        quantize_prefill_cache,
    )
    from vnsum_tpu.models.llama import init_kv_cache

    cfg = tiny_llama(max_seq_len=2048)
    params = init_params(jax.random.key(9), cfg)

    # numerical check: same prefill cache, fp vs int8, one decode-attention
    B, S = 2, 512
    rng = np.random.default_rng(1)
    tokens = rng.integers(0, 256, size=(B, S)).astype(np.int32)
    pad = jnp.asarray(np.array([0, 50], dtype=np.int32))
    _, cache = long_prefill(params, cfg, jnp.asarray(tokens), pad, mesh)

    q = jnp.asarray(
        rng.standard_normal((B, 1, cfg.n_heads, cfg.head_dim)), jnp.float32
    )
    decode_cache = init_kv_cache(cfg, B, 8)
    t = jnp.int32(0)
    attn_fp = make_long_decode_attention(mesh, cache, pad, cfg.q_per_kv)
    attn_q8 = make_long_decode_attention(
        mesh, quantize_prefill_cache(cache), pad, cfg.q_per_kv
    )
    out_fp = np.asarray(attn_fp(q, decode_cache, jnp.int32(0), t))
    out_q8 = np.asarray(attn_q8(q, decode_cache, jnp.int32(0), t))
    np.testing.assert_allclose(out_fp, out_q8, atol=0.05, rtol=0.05)

    # and the full int8 program runs end to end
    q8 = LongContextBackend(
        model_config=cfg, mesh=mesh, params=params, batch_size=2,
        max_new_tokens=12, max_total_tokens=2048,
        quantize=True, quantize_kv=True,
    )
    doc = "Hội nghị thường niên về chuyển đổi năng lượng tái tạo. " * 9
    outs = q8.generate([doc])
    assert len(outs) == 1 and isinstance(outs[0], str)


def test_decode_kernel_path_greedy_parity(mesh):
    """VERDICT r3 #5: the kernelized shard-local decode (stacked-cache
    Pallas kernel per shard + LSE merge) must reproduce the dense engine's
    greedy outputs exactly — fp and int8 cache variants both run."""
    cfg = tiny_llama(max_seq_len=2048)
    params = init_params(jax.random.key(3), cfg)
    dense = TpuBackend(
        model_config=cfg, params=params, batch_size=4, max_new_tokens=16,
        continuous=False,
    )
    kernel_long = LongContextBackend(
        model_config=cfg, mesh=mesh, params=params, max_new_tokens=16,
        max_total_tokens=2048, decode_kernel=True, interpret=True,
    )
    assert kernel_long.generate(PROMPTS) == dense.generate(PROMPTS)


def test_decode_kernel_partial_matches_dense_partial(mesh):
    """Same frozen prefill cache, kernel vs einsum shard-local partials —
    the merged attention outputs must agree to fp tolerance (fp cache) and
    int8 tolerance (quantized cache)."""
    import jax.numpy as jnp

    from vnsum_tpu.backend.long_context import (
        long_prefill,
        make_long_decode_attention,
        quantize_prefill_cache,
    )
    from vnsum_tpu.models.llama import init_kv_cache

    cfg = tiny_llama(max_seq_len=2048)
    params = init_params(jax.random.key(21), cfg)
    B, S = 2, 512
    rng = np.random.default_rng(2)
    tokens = rng.integers(0, 256, size=(B, S)).astype(np.int32)
    pad = jnp.asarray(np.array([0, 70], dtype=np.int32))
    _, cache = long_prefill(params, cfg, jnp.asarray(tokens), pad, mesh)

    q = jnp.asarray(
        rng.standard_normal((B, 1, cfg.n_heads, cfg.head_dim)), jnp.float32
    )
    decode_cache = init_kv_cache(cfg, B, 8)
    t = jnp.int32(0)
    for prep, tol in ((lambda c: c, 2e-5), (quantize_prefill_cache, 2e-5)):
        pc = prep(cache)
        dense_attn = make_long_decode_attention(
            mesh, pc, pad, cfg.q_per_kv, decode_kernel=False
        )
        kernel_attn = make_long_decode_attention(
            mesh, pc, pad, cfg.q_per_kv, decode_kernel=True, interpret=True
        )
        np.testing.assert_allclose(
            np.asarray(dense_attn(q, decode_cache, jnp.int32(1), t)),
            np.asarray(kernel_attn(q, decode_cache, jnp.int32(1), t)),
            rtol=tol, atol=tol,
        )


def test_long_backend_rejects_budget_exceeding_context(mesh):
    cfg = tiny_llama(max_seq_len=512)
    params = init_params(jax.random.key(0), cfg)
    with pytest.raises(ValueError, match="max_new_tokens"):
        LongContextBackend(
            model_config=cfg, mesh=mesh, params=params,
            max_new_tokens=512, max_total_tokens=512,
        )
    be = LongContextBackend(
        model_config=cfg, mesh=mesh, params=params,
        max_new_tokens=8, max_total_tokens=512,
    )
    with pytest.raises(ValueError, match="max_new_tokens"):
        be.generate(["x"], max_new_tokens=600)


def test_greedy_parity_with_model_axis_active():
    """TP x SP composition: heads sharded over `model` AND sequence over
    `seq` must still match the dense single-device engine bit-for-bit."""
    mesh = make_mesh({"data": 1, "model": 2, "seq": 4}, platform="cpu")
    cfg = tiny_llama(max_seq_len=2048)
    params = init_params(jax.random.key(13), cfg)
    dense = TpuBackend(
        model_config=cfg, params=params, batch_size=2, max_new_tokens=12,
        continuous=False,
    )
    long = LongContextBackend(
        model_config=cfg, mesh=mesh, params=params, batch_size=2,
        max_new_tokens=12, max_total_tokens=2048,
    )
    assert long.generate(PROMPTS) == dense.generate(PROMPTS)
