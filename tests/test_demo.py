"""Demo tests: the side-by-side approach runner and the stdlib web server
(capability match for the reference's streamlit_demo.py, SURVEY.md §2 C14),
driven over a live ThreadingHTTPServer with the FakeBackend."""
import json
import threading
import urllib.error
import urllib.request

import pytest

from vnsum_tpu.backend.fake import FakeBackend
from vnsum_tpu.core.config import APPROACHES
from vnsum_tpu.demo.core import compute_metrics, run_approaches
from vnsum_tpu.demo.server import DemoState, make_server

DOC = "\n\n".join(
    f"Đoạn văn {i}: " + "nội dung tiếng Việt có dấu thanh. " * 25
    for i in range(5)
)
REF = "Tóm tắt: nội dung tiếng Việt có dấu thanh."


def test_run_all_approaches():
    runs = run_approaches(DOC, FakeBackend(), reference=REF)
    assert [r.approach for r in runs] == list(APPROACHES)
    for r in runs:
        assert r.status == "success", f"{r.approach}: {r.error}"
        assert r.summary
        assert r.metrics["rouge1"] > 0
        assert r.seconds >= 0


def test_run_subset_and_progress():
    seen = []
    runs = run_approaches(
        DOC, FakeBackend(), approaches=["truncated", "mapreduce"],
        progress=lambda i, n, name: seen.append((i, n, name)),
    )
    assert [r.approach for r in runs] == ["truncated", "mapreduce"]
    assert seen == [(0, 2, "truncated"), (1, 2, "mapreduce")]
    # no reference -> no metrics
    assert runs[0].metrics == {}


def test_one_failure_does_not_kill_the_rest():
    class ExplodingBackend(FakeBackend):
        def generate(self, prompts, **kw):
            raise RuntimeError("boom")

    runs = run_approaches(DOC, ExplodingBackend(),
                          approaches=["mapreduce", "truncated"])
    assert all(r.status == "failed" for r in runs)
    assert all(r.error for r in runs)


def test_compute_metrics_identity():
    m = compute_metrics(REF, REF)
    assert m["rouge1"] == pytest.approx(1.0)
    assert set(m) == {"rouge1", "rouge2", "rougeL"}


@pytest.fixture()
def demo_server(tmp_path):
    docs = tmp_path / "doc"
    refs = tmp_path / "summary"
    docs.mkdir()
    refs.mkdir()
    (docs / "sample.txt").write_text(DOC, encoding="utf-8")
    (refs / "sample.txt").write_text(REF, encoding="utf-8")

    from vnsum_tpu.data import DocumentDataset

    state = DemoState(FakeBackend(), DocumentDataset(docs, refs))
    server = make_server(state, "127.0.0.1", 0)  # ephemeral port
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    yield f"http://127.0.0.1:{server.server_address[1]}"
    server.shutdown()
    server.server_close()


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as resp:
        return resp.status, resp.read()


def _post(url, payload):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    with urllib.request.urlopen(req, timeout=60) as resp:
        return resp.status, json.loads(resp.read())


def test_server_index(demo_server):
    status, body = _get(demo_server + "/")
    assert status == 200
    assert b"VN-LongSum" in body
    for a in APPROACHES:
        assert a.encode() in body


def test_server_docs_listing_and_fetch(demo_server):
    status, body = _get(demo_server + "/api/docs")
    assert status == 200 and json.loads(body) == {"docs": ["sample.txt"]}
    status, body = _get(demo_server + "/api/doc?name=sample.txt")
    d = json.loads(body)
    assert d["text"].startswith("Đoạn văn 0")
    assert d["reference"] == REF


def test_server_summarize(demo_server):
    status, d = _post(
        demo_server + "/api/summarize",
        {"text": DOC, "reference": REF, "approaches": ["mapreduce", "truncated"]},
    )
    assert status == 200
    assert [r["approach"] for r in d["runs"]] == ["mapreduce", "truncated"]
    for r in d["runs"]:
        assert r["status"] == "success"
        assert r["summary"]
        assert r["metrics"]["rouge1"] > 0


def test_server_rejects_bad_requests(demo_server):
    with pytest.raises(urllib.error.HTTPError) as exc:
        _post(demo_server + "/api/summarize", {"text": "   "})
    assert exc.value.code == 400
    with pytest.raises(urllib.error.HTTPError) as exc:
        _post(demo_server + "/api/summarize",
              {"text": "x", "approaches": ["nope"]})
    assert exc.value.code == 400
    with pytest.raises(urllib.error.HTTPError) as exc:
        _get(demo_server + "/api/doc?name=missing.txt")
    assert exc.value.code == 404
