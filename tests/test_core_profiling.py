"""Tests for the tracing/profiling subsystem (SURVEY.md §5: the reference has
only LangSmith @traceable + ad-hoc wall-clock fields; we provide aggregated
spans + gated jax.profiler traces)."""
import threading

from vnsum_tpu.core.profiling import Tracer, annotate, device_profile


def test_span_aggregates():
    t = Tracer()
    for _ in range(3):
        with t.span("work"):
            pass
    stats = t.stats()
    assert stats["work"]["count"] == 3
    assert stats["work"]["total_s"] >= 0.0
    assert stats["work"]["min_s"] <= stats["work"]["max_s"]


def test_span_nesting_builds_hierarchical_names():
    t = Tracer()
    with t.span("outer"):
        with t.span("inner"):
            pass
    with t.span("inner"):
        pass
    stats = t.stats()
    assert set(stats) == {"outer", "outer/inner", "inner"}


def test_span_exception_still_recorded():
    t = Tracer()
    try:
        with t.span("boom"):
            raise ValueError
    except ValueError:
        pass
    assert t.stats()["boom"]["count"] == 1
    # stack unwound correctly: next span is top-level
    with t.span("after"):
        pass
    assert "boom/after" not in t.stats()


def test_tracer_thread_safety():
    t = Tracer()

    def worker():
        for _ in range(50):
            with t.span("shared"):
                pass

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert t.stats()["shared"]["count"] == 200


def test_record_external_duration():
    t = Tracer()
    t.record("device_step", 0.5)
    t.record("device_step", 1.5)
    s = t.stats()["device_step"]
    assert s["count"] == 2 and s["total_s"] == 2.0 and s["max_s"] == 1.5


def test_reset():
    t = Tracer()
    with t.span("x"):
        pass
    t.reset()
    assert t.stats() == {}


def test_device_profile_noop_without_dir(monkeypatch):
    monkeypatch.delenv("VNSUM_PROFILE_DIR", raising=False)
    with device_profile():  # must not require jax import side effects
        pass


def test_device_profile_writes_trace(tmp_path):
    with device_profile(str(tmp_path)):
        import jax.numpy as jnp

        (jnp.ones((8, 8)) @ jnp.ones((8, 8))).block_until_ready()
    # jax.profiler.trace writes plugins/profile/<ts>/ under the log dir
    assert any(tmp_path.rglob("*.xplane.pb"))


def test_annotate_is_usable():
    with annotate("phase"):
        pass


def test_pipeline_records_tracing(tmp_path):
    from vnsum_tpu.core.config import PipelineConfig
    from vnsum_tpu.eval import EmbeddingModel
    from vnsum_tpu.models.encoder import tiny_encoder
    from vnsum_tpu.pipeline.runner import PipelineRunner

    docs = tmp_path / "doc"
    refs = tmp_path / "summary"
    docs.mkdir()
    refs.mkdir()
    for i in range(2):
        (docs / f"d{i}.txt").write_text("một hai ba bốn năm " * 50)
        (refs / f"d{i}.txt").write_text("tóm tắt " * 5)
    cfg = PipelineConfig(
        approach="truncated",
        models=["fake"],
        backend="fake",
        docs_dir=str(docs),
        summary_dir=str(refs),
        generated_summaries_dir=str(tmp_path / "gen"),
        results_dir=str(tmp_path / "results"),
        logs_dir=str(tmp_path / "logs"),
    )
    runner = PipelineRunner(
        cfg,
        embedding_model=EmbeddingModel(config=tiny_encoder(), max_len=64, batch_size=4),
    )
    results = runner.run()
    spans = results.tracing["spans"]
    assert "analyze" in spans
    assert "summarize" in spans
    assert "summarize/batch" in spans
    assert "evaluate" in spans
    d = results.to_dict()
    assert d["results"]["tracing"]["spans"]["summarize"]["count"] == 1
