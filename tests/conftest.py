"""Test environment: force JAX onto CPU with 8 virtual devices so mesh /
sharding tests run without TPU hardware (SURVEY.md §4 test strategy).

Must run before the first `import jax` anywhere in the test session.
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import pytest  # noqa: E402

# Heavy JAX-compile modules: every test in these files traces + compiles real
# model programs, which dominates wall-clock on a 1-core host (full suite
# >10 min there). The remaining files are the FAST tier — host logic plus
# tiny-encoder compiles — and finish in ~2.5 min:
#   python -m pytest -m "not slow"
# The full hermetic suite stays the CI default (plain `pytest`).
_SLOW_MODULES = {
    "test_backend_continuous",
    "test_backend_engine",
    "test_backend_long_context",
    "test_graft_entry",
    "test_model_convert",
    "test_model_gemma",
    "test_model_llama",
    "test_model_phi",
    "test_model_quant",
    "test_ops_decode",
    "test_ops_flash",
    "test_parallel_distributed",
    "test_parallel_train",
    "test_pipeline_weights_dir",
    "test_train_checkpoint",
}


def pytest_collection_modifyitems(config, items):
    for item in items:
        if item.module.__name__ in _SLOW_MODULES:
            item.add_marker(pytest.mark.slow)


@pytest.fixture(scope="session", autouse=True)
def _cpu_default_device():
    # the axon TPU plugin ignores JAX_PLATFORMS=cpu; pin computations to the
    # host CPU backend (with its 8 forced virtual devices) explicitly
    import jax

    jax.config.update("jax_default_device", jax.devices("cpu")[0])
    yield


@pytest.fixture(scope="session")
def cpu_mesh8():
    import jax
    from vnsum_tpu.parallel.mesh import make_mesh

    assert len(jax.devices("cpu")) == 8
    return make_mesh({"data": 2, "model": 2, "seq": 2}, platform="cpu")
