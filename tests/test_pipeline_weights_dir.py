"""Real-weight chain, hermetic on CPU: HF checkpoint dir (real transformers
save_pretrained + trained BPE tokenizer) -> models.convert -> TpuBackend(HF
tokenizer) -> mapreduce -> ROUGE (quality-gate machinery, reference
evaluation_results/first_dataset/mapreduce/llama3_2_3b_results.json)."""
import pytest

from vnsum_tpu.core.config import PipelineConfig
from vnsum_tpu.data.synthesize import synthesize_corpus
from vnsum_tpu.pipeline.runner import PipelineRunner

transformers = pytest.importorskip("transformers")


@pytest.fixture(scope="module")
def corpus_and_ckpt(tmp_path_factory):
    from vnsum_tpu.models.fixtures import make_tiny_hf_checkpoint

    root = tmp_path_factory.mktemp("parity")
    synthesize_corpus(
        root / "corpus", n_docs=3, tokens_per_doc=300, summary_tokens=40,
        seed=5,
    )
    docs = [
        p.read_text(encoding="utf-8")
        for p in sorted((root / "corpus/doc").glob("*.txt"))
    ]
    make_tiny_hf_checkpoint(
        root / "ckpt", docs, vocab_size=512, dim=64, n_layers=2,
        train_steps=0,
    )
    return root


def _config(root, **kw):
    base = dict(
        approach="mapreduce",
        models=["tiny-parity"],
        backend="tpu",
        weights_dir=str(root / "ckpt"),
        docs_dir=str(root / "corpus/doc"),
        summary_dir=str(root / "corpus/summary"),
        generated_summaries_dir=str(root / "gen"),
        results_dir=str(root / "results"),
        logs_dir=str(root / "logs"),
        chunk_size=120,
        chunk_overlap=12,
        token_max=100,
        max_new_tokens=12,
        batch_size=4,
    )
    base.update(kw)
    return PipelineConfig(**base)


def test_weights_dir_end_to_end_with_rouge(corpus_and_ckpt):
    root = corpus_and_ckpt
    results = PipelineRunner(_config(root)).run()

    rec = results.summarization["tiny-parity"]
    assert rec["successful"] == 3 and rec["failed"] == 0
    ev = results.evaluation["tiny-parity"]
    assert 0.0 <= ev["rouge_scores"]["rougeL_f1"] <= 1.0
    assert "bert_scores" in ev and "semantic_similarity" in ev

    # generated files exist and decode through the checkpoint's tokenizer
    gen = root / "gen_mapreduce_tiny-parity"
    files = sorted(gen.glob("*.txt"))
    assert len(files) == 3
    for f in files:
        f.read_text(encoding="utf-8")  # valid utf-8


def test_weights_dir_tokenizer_comes_from_checkpoint(corpus_and_ckpt):
    root = corpus_and_ckpt
    runner = PipelineRunner(_config(root))
    backend = runner.backend_factory("tiny-parity")
    # trained BPE vocab, not the byte fallback
    assert backend.tok.vocab_size <= 512
    ids = backend.tok.encode("tình hình kinh tế Việt Nam")
    assert ids and backend.tok.decode(ids).strip().startswith("tình hình")
    # model config came from the checkpoint's config.json
    assert backend.cfg.dim == 64
    assert backend.cfg.vocab_size >= backend.tok.vocab_size


def test_weights_dir_resume_skips_existing(corpus_and_ckpt):
    root = corpus_and_ckpt
    # hermetic: pre-write all 3 outputs into a fresh dir; the run must skip
    # every doc (resume-by-file, ref run_full_evaluation_pipeline.py:422-431)
    cfg = _config(root, generated_summaries_dir=str(root / "gen_resume"))
    runner = PipelineRunner(cfg)
    out_dir = runner._output_dir("tiny-parity")
    out_dir.mkdir(parents=True, exist_ok=True)
    for name in ("doc_000.txt", "doc_001.txt", "doc_002.txt"):
        (out_dir / name).write_text("đã có", encoding="utf-8")
    rec = runner.run_summarization_for_model("tiny-parity")
    assert rec.total_documents == 0
    # pre-existing outputs untouched
    assert (out_dir / "doc_000.txt").read_text(encoding="utf-8") == "đã có"
