"""Gemma3-family support on the shared decoder stack: GeGLU, sandwich
(1+w) RMSNorms, embed scaling, query_pre_attn_scalar, per-head QK-norm, and
alternating sliding/global attention with two RoPE bases.

Parity anchor is HF transformers' Gemma3ForCausalLM on a tiny config — the
reference sweeps gemma3:4b (run_full_evaluation_pipeline.py:960-962) but
only ever through Ollama HTTP; here the family runs natively.
"""
from __future__ import annotations

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax
import jax.numpy as jnp

from vnsum_tpu.models.convert import (
    config_from_hf,
    convert_torch_model,
    load_hf_checkpoint,
    save_hf_checkpoint,
)
from vnsum_tpu.models.llama import (
    forward,
    gemma3_4b,
    init_kv_cache,
    init_params,
    prefill_attention_mask,
    prefill_positions,
    tiny_llama,
)

HF_CFG = dict(
    vocab_size=384,
    hidden_size=64,
    intermediate_size=128,
    num_hidden_layers=4,
    num_attention_heads=4,
    num_key_value_heads=2,
    head_dim=16,
    max_position_embeddings=256,
    rope_theta=10000.0,
    rope_local_base_freq=5000.0,
    rms_norm_eps=1e-6,
    tie_word_embeddings=True,
    model_type="gemma3_text",
    query_pre_attn_scalar=32,
    # small window + explicit mixed layer types so the sliding path is
    # actually exercised (layers 0,1,3 sliding / 2 global)
    sliding_window=8,
    layer_types=[
        "sliding_attention", "sliding_attention",
        "full_attention", "sliding_attention",
    ],
)


@pytest.fixture(scope="module")
def hf_model():
    torch.manual_seed(0)
    cfg = transformers.Gemma3TextConfig(**{
        k: v for k, v in HF_CFG.items() if k != "model_type"
    })
    return transformers.Gemma3ForCausalLM(cfg).eval()


@pytest.fixture(scope="module")
def converted(hf_model):
    cfg = config_from_hf(HF_CFG, dtype=jnp.float32)
    assert cfg.sandwich_norms and cfg.norm_plus_one and cfg.embed_scale
    assert cfg.act == "gelu_tanh"
    assert cfg.query_scale == 32
    assert cfg.sliding_window == 8
    assert cfg.layer_is_global == (False, False, True, False)
    assert cfg.rope_local_theta == 5000.0
    params = convert_torch_model(hf_model, cfg)
    for k in ("q_norm", "k_norm", "post_attn_norm", "post_ffw_norm"):
        assert k in params["layers"], k
    return cfg, params


def _hf_logits(hf_model, tokens: np.ndarray) -> np.ndarray:
    with torch.no_grad():
        out = hf_model(torch.from_numpy(tokens).long())
    return out.logits.float().numpy()


def _our_logits(cfg, params, tokens: np.ndarray, pad=None) -> np.ndarray:
    B, S = tokens.shape
    pad = pad if pad is not None else np.zeros((B,), np.int32)
    cache = init_kv_cache(cfg, B, S)
    out, _ = forward(
        params, cfg, jnp.asarray(tokens),
        prefill_positions(jnp.asarray(pad), S), cache, 0,
        prefill_attention_mask(jnp.asarray(pad), S, S),
    )
    return np.asarray(out)


def test_gemma3_prefill_logit_parity(hf_model, converted):
    """Sequence long enough (24 > window 8) that sliding layers genuinely
    mask distant positions — parity fails if window/rope-base selection is
    wrong on any layer."""
    cfg, params = converted
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size, (2, 24), dtype=np.int32)
    ours = _our_logits(cfg, params, tokens)
    theirs = _hf_logits(hf_model, tokens)
    np.testing.assert_allclose(ours, theirs, atol=3e-4, rtol=3e-3)


def test_gemma3_decode_matches_hf_incremental(hf_model, converted):
    """KV-cache decode (prefill + single-token steps) must match the HF
    full-sequence forward at every step — exercises the sliding mask in
    decode slot space."""
    from vnsum_tpu.models.llama import decode_attention_mask

    cfg, params = converted
    rng = np.random.default_rng(1)
    S, T = 12, 6
    seq = rng.integers(0, cfg.vocab_size, (1, S + T), dtype=np.int32)
    theirs = _hf_logits(hf_model, seq)

    C = S + T
    pad = np.zeros((1,), np.int32)
    cache = init_kv_cache(cfg, 1, C)
    logits, cache = forward(
        params, cfg, jnp.asarray(seq[:, :S]),
        prefill_positions(jnp.asarray(pad), S), cache, 0,
        prefill_attention_mask(jnp.asarray(pad), S, C),
    )
    np.testing.assert_allclose(
        np.asarray(logits), theirs[:, :S], atol=3e-4, rtol=3e-3
    )
    for t in range(T):
        pos = np.asarray([[S + t]], np.int32)
        step_logits, cache = forward(
            params, cfg, jnp.asarray(seq[:, S + t : S + t + 1]),
            jnp.asarray(pos), cache, S + t,
            decode_attention_mask(jnp.asarray(pad), S + t, C),
        )
        np.testing.assert_allclose(
            np.asarray(step_logits)[:, 0], theirs[:, S + t],
            atol=3e-4, rtol=3e-3,
        )


def test_gemma3_hf_checkpoint_roundtrip(tmp_path, converted):
    cfg, params = converted
    out = tmp_path / "export"
    save_hf_checkpoint(params, cfg, str(out))
    cfg2, params2 = load_hf_checkpoint(str(out), dtype=jnp.float32)
    assert cfg2.sandwich_norms and cfg2.sliding_window == 8
    assert cfg2.layer_is_global == cfg.layer_is_global
    rng = np.random.default_rng(2)
    tokens = rng.integers(0, cfg.vocab_size, (1, 16), dtype=np.int32)
    bf = jax.tree.map(
        lambda x: x.astype(jnp.bfloat16).astype(jnp.float32), params
    )
    np.testing.assert_array_equal(
        _our_logits(cfg, bf, tokens), _our_logits(cfg2, params2, tokens)
    )


def test_gemma3_engine_generate_and_registry():
    from vnsum_tpu.backend.engine import TpuBackend
    from vnsum_tpu.models import MODEL_REGISTRY

    cfg4 = MODEL_REGISTRY["gemma3:4b"]()
    assert cfg4.sandwich_norms and cfg4.sliding_window == 1024
    assert sum(cfg4.layer_is_global) == 5  # 34 layers, every 6th global

    tiny_g = tiny_llama(
        qk_norm=True, act="gelu_tanh", sandwich_norms=True,
        norm_plus_one=True, embed_scale=True, query_scale=32.0,
        sliding_window=8,
        layer_is_global=(False, True),
    )
    be = TpuBackend(
        model_config=tiny_g, tokenizer="byte", batch_size=2,
        max_new_tokens=8, seed=0,
    )
    outs = be.generate(["văn bản một", "hai"])
    assert len(outs) == 2 and all(isinstance(o, str) for o in outs)


def test_gemma3_flash_kernels_match_dense_engine():
    """VERDICT r3 #2: sliding-window configs now run the Pallas kernels (per
    -layer window via scalar prefetch) — the full fast path (flash prefill +
    decode + int8 KV) must emit exactly the dense windowed path's tokens on
    a mixed sliding/global tiny Gemma."""
    from vnsum_tpu.backend.engine import TpuBackend

    tiny_g = tiny_llama(
        max_seq_len=128, qk_norm=True, act="gelu_tanh", sandwich_norms=True,
        norm_plus_one=True, embed_scale=True, query_scale=32.0,
        sliding_window=8, layer_is_global=(False, True),
    )
    kw = dict(
        model_config=tiny_g, tokenizer="byte", batch_size=2,
        max_new_tokens=12, seed=0,
    )
    dense = TpuBackend(flash=False, **kw)
    # quantize_kv must stay OFF here: "auto" resolves True under
    # flash+interpret, and int8-KV rounding breaks exact token parity
    fast = TpuBackend(flash=True, interpret=True, quantize_kv=False, **kw)
    # prompts longer than the window so sliding layers genuinely clamp
    prompts = ["văn bản một dài hơn cửa sổ trượt tám token", "hai ngắn"]
    assert dense.generate(prompts) == fast.generate(prompts)
    # int8 KV on the windowed path: quantization rounds logits (so exact
    # token parity vs the bf16 cache is not guaranteed on a random model) —
    # assert the full fast path runs and produces strings
    q = TpuBackend(flash=True, quantize_kv=True, interpret=True, **kw)
    outs = q.generate(prompts)
    assert len(outs) == 2 and all(isinstance(o, str) for o in outs)


def test_gemma3_mesh_sharding():
    from vnsum_tpu.parallel import make_mesh
    from vnsum_tpu.parallel.sharding import shard_params

    mesh = make_mesh({"data": 2, "model": 2}, platform="cpu")
    cfg = tiny_llama(
        qk_norm=True, sandwich_norms=True, norm_plus_one=True,
    )
    params = init_params(jax.random.key(0), cfg)
    sharded = shard_params(params, mesh, cfg.tie_embeddings)
    assert "post_attn_norm" in sharded["layers"]


def test_gemma3_mesh_engine_generates():
    """Regression (r3 review): _mesh_in_shardings must carry the sandwich
    norm leaves, or any Gemma3 config under a mesh dies with a pytree
    structure mismatch at dispatch."""
    from vnsum_tpu.backend.engine import TpuBackend
    from vnsum_tpu.parallel import make_mesh

    mesh = make_mesh({"data": 2, "model": 2}, platform="cpu")
    tiny_g = tiny_llama(
        qk_norm=True, act="gelu_tanh", sandwich_norms=True,
        norm_plus_one=True, embed_scale=True, query_scale=32.0,
        sliding_window=8, layer_is_global=(False, True),
    )
    be = TpuBackend(
        model_config=tiny_g, tokenizer="byte", batch_size=2,
        max_new_tokens=6, seed=0, mesh=mesh, flash=False,
    )
    outs = be.generate(["văn bản một", "hai"])
    assert len(outs) == 2


def test_multimodal_checkpoint_layout_loads(tmp_path, converted):
    """Real gemma-3-4b+ repos are multimodal: config nested under
    text_config, tensors under language_model.model.* — the loader must
    unwrap both."""
    import json
    import os

    from safetensors.numpy import load_file, save_file

    cfg, params = converted
    plain = tmp_path / "plain"
    save_hf_checkpoint(params, cfg, str(plain))

    mm = tmp_path / "multimodal"
    os.makedirs(mm)
    with open(plain / "config.json") as f:
        inner_cfg = json.load(f)
    outer = {
        "architectures": ["Gemma3ForConditionalGeneration"],
        "model_type": "gemma3",
        "text_config": inner_cfg,
    }
    (mm / "config.json").write_text(json.dumps(outer))
    index = json.loads((plain / "model.safetensors.index.json").read_text())
    new_map = {}
    for shard in set(index["weight_map"].values()):
        tensors = load_file(str(plain / shard))
        renamed = {f"language_model.{k}": v for k, v in tensors.items()}
        save_file(renamed, str(mm / shard))
        for k in renamed:
            new_map[k] = shard
    (mm / "model.safetensors.index.json").write_text(
        json.dumps({"metadata": index["metadata"], "weight_map": new_map})
    )

    cfg2, params2 = load_hf_checkpoint(str(mm), dtype=jnp.float32)
    assert cfg2.sandwich_norms and cfg2.sliding_window == cfg.sliding_window
    rng = np.random.default_rng(5)
    tokens = rng.integers(0, cfg.vocab_size, (1, 16), dtype=np.int32)
    bf = jax.tree.map(
        lambda x: x.astype(jnp.bfloat16).astype(jnp.float32), params
    )
    np.testing.assert_array_equal(
        _our_logits(cfg, bf, tokens), _our_logits(cfg2, params2, tokens)
    )


def test_registry_configs_shard_structurally():
    """Every registry family's param tree must match its sharding-spec tree
    (structure, not shapes) — catches the threading bug class where a new
    param leaf (q_norm, post_attn_norm, ...) misses a param_specs flag."""
    import dataclasses

    from vnsum_tpu.models import MODEL_REGISTRY
    from vnsum_tpu.parallel.sharding import param_specs

    for name, factory in MODEL_REGISTRY.items():
        cfg = factory()
        # shrink to a traceable size; structure is all that matters
        small = dataclasses.replace(
            cfg, dim=64, n_layers=2, n_heads=4, n_kv_heads=2, head_dim=16,
            intermediate=128, vocab_size=384, max_seq_len=128,
            dtype=jnp.float32,
            layer_is_global=cfg.layer_is_global[:2]
            if cfg.layer_is_global else (),
        )
        params = jax.eval_shape(
            lambda: init_params(jax.random.key(0), small)
        )
        specs = param_specs(
            small.tie_embeddings,
            qk_norm=small.qk_norm,
            sandwich_norms=small.sandwich_norms,
        )
        assert (
            jax.tree.structure(params) == jax.tree.structure(specs)
        ), f"{name}: params/specs tree mismatch"
