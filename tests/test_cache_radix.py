"""Radix prefix index + paged block store (vnsum_tpu.cache) unit tests.

The acceptance-critical property lives here: eviction under a tight block
budget can never reallocate a block a live match still pins, and chains only
evict tail-first (leaves), so a surviving match can never dangle.
"""
import threading

import numpy as np
import pytest

from vnsum_tpu.cache import BlockStore, PrefixCache, RadixIndex


def seq(n, base=0):
    return [base + i for i in range(n)]


# -- radix index -------------------------------------------------------------


def test_match_is_block_aligned():
    idx = RadixIndex(num_blocks=8, block_tokens=4)
    idx.insert(seq(10), upto=10)  # caches 2 blocks = 8 tokens
    m = idx.match(seq(10))
    assert m.tokens == 8
    assert len(m.blocks) == 2
    idx.release(m)


def test_match_respects_max_tokens():
    idx = RadixIndex(num_blocks=8, block_tokens=4)
    idx.insert(seq(12), upto=12)
    m = idx.match(seq(12), max_tokens=7)  # only 1 whole block fits under 7
    assert m.tokens == 4
    idx.release(m)


def test_divergent_suffixes_share_prefix_blocks():
    idx = RadixIndex(num_blocks=8, block_tokens=4)
    a = seq(4) + [100, 101, 102, 103]
    b = seq(4) + [200, 201, 202, 203]
    idx.insert(a, upto=8)
    idx.insert(b, upto=8)
    assert idx.blocks_used == 3  # shared head + two tails
    ma, mb = idx.match(a), idx.match(b)
    assert ma.blocks[0] == mb.blocks[0]
    assert ma.blocks[1] != mb.blocks[1]
    idx.release(ma)
    idx.release(mb)


def test_insert_reuses_existing_chain():
    idx = RadixIndex(num_blocks=8, block_tokens=4)
    new1 = idx.insert(seq(8), upto=8)
    new2 = idx.insert(seq(8), upto=8)
    assert len(new1) == 2 and new2 == []
    assert idx.stats.inserted_blocks == 2


def test_probe_is_readonly():
    idx = RadixIndex(num_blocks=8, block_tokens=4)
    idx.insert(seq(8), upto=8)
    before = idx.stats.lookups
    assert idx.probe(seq(8)) == 8
    assert idx.probe(seq(3)) == 0
    assert idx.stats.lookups == before  # probes don't count as lookups


def test_lru_evicts_oldest_unpinned_leaf():
    idx = RadixIndex(num_blocks=2, block_tokens=4)
    idx.insert(seq(4, 0), upto=4)
    idx.insert(seq(4, 100), upto=4)
    # touch the first chain so the second becomes LRU
    m = idx.match(seq(4, 0))
    idx.release(m)
    idx.insert(seq(4, 200), upto=4)  # forces one eviction
    assert idx.stats.evictions == 1
    assert idx.probe(seq(4, 0)) == 4      # recently used: survived
    assert idx.probe(seq(4, 100)) == 0    # LRU victim
    assert idx.probe(seq(4, 200)) == 4


def test_pinned_blocks_never_evicted():
    idx = RadixIndex(num_blocks=2, block_tokens=4)
    idx.insert(seq(8), upto=8)  # fills the pool with one 2-block chain
    m = idx.match(seq(8))       # pin both
    # insertion pressure: nothing is evictable while the match is live
    assert idx.insert(seq(4, 500), upto=4) == []
    assert idx.stats.evictions == 0
    assert idx.probe(seq(8)) == 8
    idx.release(m)
    # released: now the tail leaf can go
    assert len(idx.insert(seq(4, 500), upto=4)) == 1
    assert idx.stats.evictions == 1


def test_chains_evict_tail_first():
    idx = RadixIndex(num_blocks=3, block_tokens=2)
    idx.insert(seq(6), upto=6)  # one 3-block chain
    idx.insert(seq(2, 900), upto=2)  # evicts exactly one block
    assert idx.stats.evictions == 1
    # the interior of the chain must have survived: the head 2 blocks match
    assert idx.probe(seq(6)) == 4


def test_release_idempotent():
    idx = RadixIndex(num_blocks=4, block_tokens=2)
    idx.insert(seq(4), upto=4)
    m = idx.match(seq(4))
    idx.release(m)
    idx.release(m)  # second release is a no-op, refs must not go negative
    m2 = idx.match(seq(4))
    assert all(n.refs == 1 for n in m2.nodes)
    idx.release(m2)


def test_concurrent_probes_against_mutation():
    """HTTP-thread probes race the engine thread's match/insert/release
    churn; no exceptions, no negative refs, pool accounting stays sane."""
    idx = RadixIndex(num_blocks=16, block_tokens=4)
    stop = threading.Event()
    errors = []

    def prober():
        while not stop.is_set():
            try:
                idx.probe(seq(16, 0))
                idx.probe(seq(8, 100))
            except Exception as e:  # pragma: no cover - the assertion target
                errors.append(e)
                return

    threads = [threading.Thread(target=prober) for _ in range(4)]
    for t in threads:
        t.start()
    # the "engine thread": steady match/insert/release churn with eviction
    for i in range(300):
        tokens = seq(16, (i % 5) * 1000)
        m = idx.match(tokens, max_tokens=len(tokens) - 1)
        idx.insert(tokens, upto=12)
        idx.release(m)
    stop.set()
    for t in threads:
        t.join()
    assert not errors
    assert 0 <= idx.blocks_used <= 16


# -- block store -------------------------------------------------------------


@pytest.fixture(scope="module")
def jnp():
    return pytest.importorskip("jax.numpy")


def _fake_cache(jnp, L=2, B=3, KV=2, C=32, hd=4, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "k": jnp.asarray(rng.normal(size=(L, B, KV, C, hd)).astype(np.float32)),
        "v": jnp.asarray(rng.normal(size=(L, B, KV, C, hd)).astype(np.float32)),
    }


def test_store_write_gather_roundtrip(jnp):
    BLK = 4
    store = BlockStore(
        num_blocks=8, block_tokens=BLK, n_layers=2, n_kv_heads=2,
        head_dim=4, dtype=jnp.float32,
    )
    src = _fake_cache(jnp)
    # extract two consecutive blocks of row 1 starting at slot 8
    store.write_block(src, row=1, slot=8, block_id=3)
    store.write_block(src, row=1, slot=12, block_id=5)
    # gather them into row 0 and row 2 of a zero cache at different offsets
    dst = {k: jnp.zeros_like(v) for k, v in _fake_cache(jnp, seed=1).items()}
    ids = np.array([[3, 5], [store.scratch_id] * 2, [3, 5]], dtype=np.int32)
    starts = np.array([4, 0, 16], dtype=np.int32)
    out = store.gather(dst, ids, starts)
    for name in ("k", "v"):
        slab = np.asarray(src[name])[:, 1, :, 8:16]
        np.testing.assert_array_equal(np.asarray(out[name])[:, 0, :, 4:12], slab)
        np.testing.assert_array_equal(np.asarray(out[name])[:, 2, :, 16:24], slab)
        # scratch-padded row untouched beyond zeros
        np.testing.assert_array_equal(
            np.asarray(out[name])[:, 1], np.zeros_like(np.asarray(out[name])[:, 1])
        )


def test_store_quantized_leaves_roundtrip(jnp):
    BLK = 4
    store = BlockStore(
        num_blocks=4, block_tokens=BLK, n_layers=1, n_kv_heads=1,
        head_dim=4, dtype=jnp.float32, quantized=True,
    )
    assert set(store.pool) == {"k", "v", "ks", "vs"}
    rng = np.random.default_rng(0)
    src = {
        "k": jnp.asarray(rng.integers(-127, 127, size=(1, 2, 1, 16, 4), dtype=np.int8)),
        "v": jnp.asarray(rng.integers(-127, 127, size=(1, 2, 1, 16, 4), dtype=np.int8)),
        "ks": jnp.asarray(rng.normal(size=(1, 2, 1, 16)).astype(np.float32)),
        "vs": jnp.asarray(rng.normal(size=(1, 2, 1, 16)).astype(np.float32)),
    }
    store.write_block(src, row=0, slot=4, block_id=2)
    dst = {k: jnp.zeros_like(v) for k, v in src.items()}
    out = store.gather(dst, np.array([[2], [store.scratch_id]], np.int32),
                       np.array([8, 0], np.int32))
    for name in src:
        got = np.asarray(out[name])[:, 0, :, 8:12]
        want = np.asarray(src[name])[:, 0, :, 4:8]
        np.testing.assert_array_equal(got, want)


def test_prefix_cache_facade(jnp):
    pc = PrefixCache(
        num_blocks=8, block_tokens=4, n_layers=2, n_kv_heads=2,
        head_dim=4, dtype=jnp.float32,
    )
    cache = _fake_cache(jnp)
    ids = seq(10)
    n = pc.insert(cache, row=0, slot_base=2, ids=ids, upto=9)  # 2 whole blocks
    assert n == 2
    assert pc.probe(ids) == 8
    m = pc.match(ids, max_tokens=len(ids) - 1)
    assert m.tokens == 8
    scratch = pc.store.scratch_id
    ids_all = np.array(
        [m.blocks, [scratch] * len(m.blocks), [scratch] * len(m.blocks)],
        np.int32,
    )
    seeded = pc.gather(
        {k: jnp.zeros_like(v) for k, v in cache.items()},
        ids_all, np.array([2, 0, 0], np.int32),
    )
    for name in ("k", "v"):
        np.testing.assert_array_equal(
            np.asarray(seeded[name])[:, 0, :, 2:10],
            np.asarray(cache[name])[:, 0, :, 2:10],
        )
    pc.release(m)
    st = pc.stats_dict()
    assert st["blocks_used"] == 2 and st["blocks_total"] == 8
    assert st["hbm_bytes"] > 0
