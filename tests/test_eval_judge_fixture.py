"""Judge-fixture curriculum tests (vnsum_tpu/eval/judge_fixture.py).

The trained device judge (scripts/make_trained_judge_artifact.py) rests on
two invariants testable without training: the curriculum supervises the
EXACT token position ``TpuBackend.score_choices`` queries, and the
corruption machinery grades cleanly. A tiny training smoke (slow tier)
checks the loop runs end to end and saves a loadable HF checkpoint.
"""
import pytest

from vnsum_tpu.eval.geval import LLMJudge
from vnsum_tpu.eval.judge_fixture import (
    CONTENT_WORDS,
    LEVELS,
    NOISE_WORDS,
    build_cases,
    corrupt,
    curriculum_corpus,
    level_digit,
    make_summary,
)


def test_level_digit_mapping():
    assert [level_digit(p) for p in LEVELS] == [5, 4, 3, 2, 1]


def test_lexicons_disjoint():
    assert not set(CONTENT_WORDS) & set(NOISE_WORDS)


def test_corrupt_replaces_expected_fraction():
    import random

    rng = random.Random(0)
    s = make_summary(rng, sentences=5, words_per_sentence=10)
    n = len(s.split())
    for p in (0.0, 0.5, 1.0):
        bad = sum(
            w in NOISE_WORDS for w in corrupt(random.Random(1), s, p).split()
        )
        assert abs(bad - p * n) <= 1


def test_cases_balanced_and_use_production_template():
    cases = build_cases(3, seed=0)
    # per level: 3 correctness + 3 coherence
    assert len(cases) == len(LEVELS) * 6
    for c in cases:
        # the forced prefix must terminate every prompt — score_choices
        # appends the digit right after it
        assert c.prompt.endswith(LLMJudge._FORCED_PREFIX)
        assert "expert evaluator of text summaries" in c.prompt
        if c.kind == "correctness":
            assert "Reference summary:" in c.prompt
        else:
            assert "Reference summary:" not in c.prompt
    digits = {c.digit for c in cases}
    assert digits == {1, 2, 3, 4, 5}


def test_clean_correctness_case_is_verbatim_faithful():
    for c in build_cases(2, seed=3):
        if c.level == 0.0 and c.kind == "correctness":
            gen = c.prompt.split("Generated summary:\n")[1].split(
                "\n\nReference summary:\n"
            )[0]
            ref = c.prompt.split("\n\nReference summary:\n")[1].split("\n")[0]
            assert gen == ref


def test_curriculum_corpus_teaches_digit_merges():
    texts = curriculum_corpus(build_cases(2, seed=0))
    joined = " ".join(texts)
    for d in "12345":
        assert f'{{"score": {d}' in joined


@pytest.mark.slow
def test_training_smoke_saves_loadable_checkpoint(tmp_path):
    import torch

    from vnsum_tpu.eval.judge_fixture import train_judge_fixture

    model, tok, digit_ids = train_judge_fixture(
        tmp_path / "judge", n_per_level=2, steps=3, vocab_size=384
    )
    assert len(set(digit_ids)) == 5
    # the saved checkpoint loads through the production converter path
    from vnsum_tpu.models.convert import load_hf_checkpoint

    cfg, params = load_hf_checkpoint(str(tmp_path / "judge"))
    assert cfg.vocab_size == len(tok)
    # supervised position == score_choices' query position: the first token
    # of a digit choice scores next after [bos] + encode(prompt)
    c = build_cases(1, seed=9)[0]
    ids = [tok.bos_token_id] + tok.encode(c.prompt)
    with torch.no_grad():
        logits = model(input_ids=torch.tensor([ids])).logits[0, -1]
    assert logits.shape[-1] == cfg.vocab_size