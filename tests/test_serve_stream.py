"""SSE streaming (serve/stream.py + the /v1/* stream surface): delta
byte-identity against the non-streaming reply, staggered in-flight joins,
the one-shot single-delta fallback, summarize progress events, and the
stream metrics rows."""
from __future__ import annotations

import http.client
import json
import threading
import urllib.parse
import urllib.request

import pytest

from vnsum_tpu.backend.fake import FakeBackend
from vnsum_tpu.serve import StreamChannel
from vnsum_tpu.serve.server import ServeState, make_server

DOC = "\n\n".join(
    f"Đoạn văn {i}: " + "nội dung tiếng Việt có dấu thanh. " * 25
    for i in range(4)
)


# -- channel unit behavior ----------------------------------------------------


def test_channel_emits_monotone_suffix_deltas():
    ch = StreamChannel("r1")
    assert ch.push_text("mot")
    assert not ch.push_text("mot")          # not extending: nothing leaves
    assert ch.push_text("mot hai")
    assert not ch.push_text("khac hoan toan")  # regression (preempt restart)
    assert not ch.push_text("mot")             # still behind the high-water
    assert ch.push_text("mot hai ba")          # re-passed the mark: resumes
    deltas = []
    while not ch.empty():
        ev = ch.pop(0.01)
        if ev and ev[0] == "delta":
            deltas.append(ev[1]["text"])
    assert "".join(deltas) == "mot hai ba"


def test_channel_coalesces_on_full_preserving_identity():
    """A slow consumer's pending deltas collapse into fewer events when the
    bounded channel fills — and the concatenation identity survives,
    because adjacent deltas concatenate in order."""
    ch = StreamChannel("r2", maxsize=4)
    final = ""
    for i in range(64):
        final += f"tu{i} "
        ch.push_text(final)
    assert ch.coalesced > 0
    deltas = []
    while not ch.empty():
        ev = ch.pop(0.01)
        if ev and ev[0] == "delta":
            deltas.append(ev[1]["text"])
    assert len(deltas) < 64  # actually coalesced
    assert "".join(deltas) == final


def test_channel_coalesce_keeps_latest_progress_and_interleaves():
    ch = StreamChannel("r3", maxsize=4)
    ch.push_text("a")
    for n in range(1, 30):
        ch.push_event("progress", {"llm_requests_done": n})
    ch.push_text("ab")
    events = []
    while not ch.empty():
        events.append(ch.pop(0.01))
    kinds = [e[0] for e in events]
    assert kinds.count("progress") < 29  # progress runs collapsed
    last_progress = [e for e in events if e[0] == "progress"][-1]
    assert last_progress[1]["llm_requests_done"] == 29  # latest survives
    assert "".join(e[1]["text"] for e in events if e[0] == "delta") == "ab"


def test_channel_bound_holds_under_alternating_kinds():
    """Pathological alternation (delta/progress/delta/...) defeats
    adjacent-run merging; the global collapse must still hold the hard
    bound (at most one event per kind) AND the concatenation identity."""
    ch = StreamChannel("r8", maxsize=6)
    final = ""
    for i in range(100):
        final += f"t{i} "
        ch.push_text(final)
        ch.push_event("progress", {"llm_requests_done": i})
    # bounded despite never popping: the whole backlog is a handful of
    # events, not 200
    assert len(ch._q) < 6
    events = []
    while not ch.empty():
        events.append(ch.pop(0.01))
    assert "".join(p["text"] for n, p, _s in events if n == "delta") == final
    assert max(
        p["llm_requests_done"] for n, p, _s in events if n == "progress"
    ) == 99


def test_channel_detach_supersedes_stale_consumer():
    from vnsum_tpu.serve import StreamDetached

    ch = StreamChannel("r4")
    gen1 = ch.attach()
    ch.push_text("mot")
    assert ch.pop(0.01, gen1)[0] == "delta"
    gen2 = ch.attach()
    with pytest.raises(StreamDetached):
        ch.pop(0.01, gen1)  # the stale consumer must stand down
    ch.push_text("mot hai")
    assert ch.pop(0.01, gen2)[1]["text"] == " hai"


def test_channel_resume_snapshot_folds_buffered_deltas():
    ch = StreamChannel("r5")
    ch.push_text("mot")
    ch.push_text("mot hai")          # both deltas still buffered
    ch.push_event("progress", {"llm_requests_done": 1})
    text, seq = ch.resume_snapshot()
    assert text == "mot hai" and seq >= 2
    # buffered deltas are gone (their bytes live in the snapshot); the
    # progress event survived
    ev = ch.pop(0.01)
    assert ev[0] == "progress"
    assert ch.empty()
    ch.push_text("mot hai ba")
    assert text + ch.pop(0.01)[1]["text"] == "mot hai ba"


def test_channel_concatenation_identity_under_concurrent_churn():
    """Randomized producer/consumer race over a tiny bounded channel, with
    preemption-style regressions, mid-stream coalescing, and one resume:
    snapshot + collected deltas must reassemble the exact final text."""
    import random

    rng = random.Random(13)
    words = [f"tu{i}" for i in range(400)]
    final = " ".join(words)
    ch = StreamChannel("r6", maxsize=8)
    collected: list[str] = []
    stop = threading.Event()

    def consumer():
        while not stop.is_set() or not ch.empty():
            ev = ch.pop(0.002)
            if ev and ev[0] == "delta":
                collected.append(ev[1]["text"])
            if rng.random() < 0.05:
                import time as _t
                _t.sleep(0.003)  # slow consumer: force coalescing

    t = threading.Thread(target=consumer, daemon=True)
    t.start()
    upto = 0
    while upto < len(words):
        upto += rng.randint(1, 7)
        snapshot = " ".join(words[: min(upto, len(words))])
        ch.push_text(snapshot)
        if rng.random() < 0.2:
            # preemption restart: a non-extending snapshot emits nothing
            ch.push_text(" ".join(words[: max(upto // 2, 1)]))
    ch.push_text(final)
    stop.set()
    t.join(timeout=30)
    assert "".join(collected) == final


def test_channel_resume_snapshot_identity_with_consumer_gap():
    """Disconnect-shaped sequence: consume a prefix, drop events on the
    floor (the dead socket), resume via snapshot, drain the rest — the
    reassembled text is exact."""
    import random

    rng = random.Random(29)
    words = [f"w{i}" for i in range(200)]
    final = " ".join(words)
    ch = StreamChannel("r7", maxsize=8)
    got: list[str] = []
    # phase 1: live consumption of a random prefix of pushes
    upto = 0
    while upto < 80:
        upto += rng.randint(1, 9)
        ch.push_text(" ".join(words[:upto]))
        if rng.random() < 0.7:
            ev = ch.pop(0.001)
            if ev and ev[0] == "delta":
                got.append(ev[1]["text"])
    prefix = "".join(got)
    # phase 2: disconnected — more pushes pile up (and coalesce)
    while upto < len(words):
        upto += rng.randint(1, 9)
        ch.push_text(" ".join(words[: min(upto, len(words))]))
    ch.push_text(final)
    # phase 3: resume — the snapshot replaces everything buffered
    text, _seq = ch.resume_snapshot()
    assert text.startswith(prefix)
    rest: list[str] = []
    while not ch.empty():
        ev = ch.pop(0.001)
        if ev and ev[0] == "delta":
            rest.append(ev[1]["text"])
    assert text + "".join(rest) == final


# -- SSE over HTTP ------------------------------------------------------------


def sse_post(base, path, payload, headers=None):
    """POST and parse the whole SSE response into [(event, payload)]."""
    u = urllib.parse.urlparse(base)
    conn = http.client.HTTPConnection(u.hostname, u.port, timeout=60)
    try:
        body = json.dumps(payload)
        conn.request("POST", path, body=body, headers={
            "Content-Type": "application/json", **(headers or {}),
        })
        resp = conn.getresponse()
        assert resp.status == 200, resp.read()
        assert resp.getheader("Content-Type", "").startswith(
            "text/event-stream"
        )
        raw = resp.read().decode()
    finally:
        conn.close()
    events = []
    for frame in raw.split("\n\n"):
        if not frame.strip():
            continue
        name = data = None
        for line in frame.splitlines():
            if line.startswith("event: "):
                name = line[len("event: "):]
            elif line.startswith("data: "):
                data = json.loads(line[len("data: "):])
        events.append((name, data))
    return events


def deltas_of(events):
    return "".join(p["text"] for n, p in events if n == "delta")


def _post(url, payload):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    with urllib.request.urlopen(req, timeout=60) as resp:
        return resp.status, json.loads(resp.read())


@pytest.fixture()
def inflight_server():
    state = ServeState(
        FakeBackend(segment_words=4, segment_overhead_s=0.002,
                    batch_overhead_s=0.005),
        max_batch=4, max_wait_s=0.005, inflight=True, slots=4,
    )
    server = make_server(state, "127.0.0.1", 0)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{server.server_address[1]}", state
    server.shutdown()
    server.server_close()
    state.close()


def test_streamed_generate_is_byte_identical_to_nonstreaming(inflight_server):
    base, _ = inflight_server
    prompt = "tom tat van ban tieng viet nay " * 8
    _, plain = _post(base + "/v1/generate", {"prompt": prompt})
    events = sse_post(base, "/v1/generate",
                      {"prompt": prompt, "stream": True})
    assert events[-1][0] == "done"
    done = events[-1][1]
    text = done["completions"][0]["text"]
    # the headline invariant: concatenated deltas == the final text == the
    # non-streaming reply for the same request
    assert deltas_of(events) == text
    assert text == plain["completions"][0]["text"]
    # several segment-boundary deltas, not one blob at the end
    assert sum(1 for n, _ in events if n == "delta") > 1
    assert done["completions"][0]["record"]["status"] == "ok"
    assert done["request_id"]


def test_streamed_deltas_under_staggered_joins(inflight_server):
    """Concurrent streams joining a running batch at different segments:
    every stream's deltas must reassemble ITS own text (no cross-slot
    bleed), byte-identical to a solo run."""
    base, _ = inflight_server
    prompts = [f"tai lieu so {i} rieng biet noi dung " * (4 + 2 * i)
               for i in range(4)]
    results: list = [None] * len(prompts)

    def worker(i):
        # staggered: each joiner arrives a few segments into the others
        import time
        time.sleep(0.004 * i)
        results[i] = sse_post(base, "/v1/generate",
                              {"prompt": prompts[i], "stream": True})

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(len(prompts))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for i, events in enumerate(results):
        expect = FakeBackend().generate([prompts[i]])[0]
        assert events[-1][0] == "done"
        assert deltas_of(events) == expect, f"stream {i} corrupted"


def test_streamed_deltas_at_fused_cadence_reassemble_exactly():
    """--fused-segments 4 coarsens delta pushes to one per host dispatch
    (the coalesced boundary fetch) — the reassembled stream must still be
    byte-identical to the non-streaming reply for the same prompt."""
    state = ServeState(
        FakeBackend(segment_words=4, segment_overhead_s=0.002,
                    batch_overhead_s=0.005),
        max_batch=4, max_wait_s=0.005, inflight=True, slots=4,
        fused_segments=4,
    )
    server = make_server(state, "127.0.0.1", 0)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{server.server_address[1]}"
    try:
        prompt = "dong chay hop nhat bon phan doan mot luot " * 8
        events = sse_post(base, "/v1/generate",
                          {"prompt": prompt, "stream": True})
        assert events[-1][0] == "done"
        text = events[-1][1]["completions"][0]["text"]
        assert deltas_of(events) == text
        assert text == FakeBackend().generate([prompt])[0]
        snap = state.scheduler.metrics.snapshot()
        assert snap.fused_dispatches > 0
        assert snap.segments >= snap.fused_dispatches
    finally:
        server.shutdown()
        server.server_close()
        state.close()


def test_streamed_generate_on_batch_scheduler_single_final_delta():
    """The one-shot dispatch path has no observable mid-decode boundary:
    streaming degrades to one delta carrying the whole text, and the
    identity invariant still holds."""
    state = ServeState(FakeBackend(), max_batch=4, max_wait_s=0.005)
    server = make_server(state, "127.0.0.1", 0)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{server.server_address[1]}"
    try:
        prompt = "duong mot lan " * 6
        events = sse_post(base, "/v1/generate",
                          {"prompt": prompt, "stream": True})
        assert [n for n, _ in events] == ["delta", "done"]
        assert deltas_of(events) == events[-1][1]["completions"][0]["text"]
    finally:
        server.shutdown()
        server.server_close()
        state.close()


def test_stream_rejects_multi_prompt(inflight_server):
    base, _ = inflight_server
    with pytest.raises(urllib.error.HTTPError) as exc:
        _post(base + "/v1/generate",
              {"prompts": ["mot", "hai"], "stream": True})
    assert exc.value.code == 400


def test_stream_admission_shed_is_plain_429(inflight_server):
    # sheds decided BEFORE the stream opens answer as typed JSON, not SSE
    base, _ = inflight_server
    with pytest.raises(urllib.error.HTTPError) as exc:
        _post(base + "/v1/generate",
              {"prompt": "tre han", "deadline_ms": 0, "stream": True})
    assert exc.value.code == 429
    assert json.loads(exc.value.read())["reason"] == "deadline"


def test_streamed_summarize_progress_and_done_payload(inflight_server):
    base, _ = inflight_server
    _, plain = _post(base + "/v1/summarize",
                     {"text": DOC, "approach": "mapreduce"})
    events = sse_post(base, "/v1/summarize",
                      {"text": DOC, "approach": "mapreduce", "stream": True})
    names = [n for n, _ in events]
    assert names[-1] == "done" and "progress" in names
    done = events[-1][1]
    # the done event is the non-streaming reply, summary byte-identical
    assert done["summary"] == plain["summary"]
    assert done["approach"] == "mapreduce"
    assert done["serving"]["llm_requests"] == done["llm_calls"]
    # progress counted up to the full fan-out
    last_progress = [p for n, p in events if n == "progress"][-1]
    assert last_progress["llm_requests_done"] == done["llm_calls"]


def test_stream_journal_lifecycle_and_metrics(tmp_path, inflight_server):
    base, state = inflight_server
    sse_post(base, "/v1/generate",
             {"prompt": "do luong luong su kien " * 6, "stream": True})
    snap = state.scheduler.metrics.snapshot()
    assert snap.stream_requests >= 1
    assert snap.stream_events >= 2  # deltas + done
    assert snap.streams_open == 0   # gauge returns to zero after close
    with urllib.request.urlopen(base + "/metrics", timeout=10) as resp:
        text = resp.read().decode()
    assert "vnsum_serve_stream_requests_total" in text
    assert "vnsum_serve_stream_events_total" in text
    assert "vnsum_serve_stream_active 0" in text


def test_streaming_request_journals_streaming_state(tmp_path):
    """The STREAMING lifecycle event lands in the ledger at first delta and
    the entry still terminates COMPLETE."""
    state = ServeState(
        FakeBackend(segment_words=4, segment_overhead_s=0.002),
        max_batch=4, max_wait_s=0.005, inflight=True, slots=4,
        journal_dir=str(tmp_path / "journal"),
    )
    server = make_server(state, "127.0.0.1", 0)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{server.server_address[1]}"
    try:
        events = sse_post(
            base, "/v1/generate",
            {"prompt": "ghi so cai dong su kien " * 8, "stream": True,
             "request_id": "stream-led-1"},
        )
        assert events[-1][0] == "done"
    finally:
        server.shutdown()
        server.server_close()
        state.close()
    from vnsum_tpu.serve.journal import RequestJournal

    entries, _sealed, torn = RequestJournal.read_state(tmp_path / "journal")
    assert torn == 0
    assert entries["stream-led-1"].status == "complete"
    raw = b"".join(
        p.read_bytes() for p in sorted((tmp_path / "journal").glob("*.jsonl"))
    )
    assert b'"e":"streaming"' in raw
